"""MPI_Win windows over device buffers.

The reference's osc framework (``ompi/mca/osc/osc.h:205-338``: put/get/
accumulate/CAS/fetch-op + fence/PSCW/lock epochs, ``osc/rdma`` data
movement) recast for a single-controller device mesh:

- A window is a device-resident array with a leading rank axis — slice
  i lives in rank i's HBM (NamedSharding over the comm's sub-mesh), the
  MPI_Win_allocate memory model.
- RMA calls during an epoch queue; closing the epoch (fence, unlock,
  complete, flush) applies them in submission order as ONE jitted
  sharded program per epoch — the MPI completion rule ("RMA completes
  at synchronization") is the natural XLA execution model, and the
  epoch batch is the osc/rdma "aggregate and issue at sync" strategy.
- get/get_accumulate/fetch_and_op/compare_and_swap return Requests
  whose values materialize at epoch close.

Epoch rules enforced (``ompi/win/win.c`` access-epoch checks): RMA
outside any epoch raises; fence/lock/PSCW cannot be mixed.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..mca import pvar
from ..ops.op import Op, REPLACE, SUM
from ..request.request import Request, Status
from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.stream("osc")

_epoch_count = pvar.counter("osc_epochs", "RMA epochs closed")
_rma_ops = pvar.counter("osc_rma_ops", "RMA operations issued")
_epoch_programs = pvar.counter(
    "osc_epoch_programs", "distinct compiled epoch-close programs"
)
_epoch_dispatches = pvar.counter(
    "osc_epoch_dispatches", "epoch-close program invocations"
)

#: compiled epoch-close programs, keyed by (op count padded to a power
#: of two, window shape, dtype, ordered distinct (kind, op, indexed)
#: branches, scalar-payload mode) — padding keeps the cache O(log n)
#: per branch set across varying epoch lengths
_program_cache: Dict[Tuple, object] = {}

#: one epoch program compiles/executes at a time, PROCESS-wide: two
#: threads driving first-call jit compilation/execution concurrently
#: (distinct windows, so the per-window _op_lock does not serialize
#: them) deadlock inside this jaxlib — both park in prog() forever
#: (reproduced ~1 in 3 by test_shmem_topo's lock-contention test, the
#: flight recorder's own thread stacks pinpointed it). Epoch programs
#: are sub-ms on driver-mode windows, so serializing dispatch costs
#: nothing measurable.
_dispatch_lock = threading.Lock()

LOCK_EXCLUSIVE = 1
LOCK_SHARED = 2


class _EpochKind(enum.Enum):
    NONE = "none"
    FENCE = "fence"
    LOCK = "lock"
    PSCW = "pscw"


class _PendingOp:
    __slots__ = ("kind", "target", "data", "op", "request", "compare",
                 "index", "status_rank")

    def __init__(self, kind, target, data=None, op=None, request=None,
                 compare=None, index=None, status_rank=None) -> None:
        self.kind = kind
        self.target = target
        self.data = data
        self.op = op
        self.request = request
        self.compare = compare
        # flat element offset within the target slot (MPI target_disp
        # for single-element ops); None = whole-slot operation
        self.index = index
        # the COMM rank to report in the request's Status when target
        # has been remapped to a storage row (spanning windows)
        self.status_rank = status_rank


# predefined window attributes (mpi.h MPI_WIN_BASE..MPI_WIN_MODEL)
WIN_BASE = "win_base"
WIN_SIZE = "win_size"
WIN_DISP_UNIT = "win_disp_unit"
WIN_CREATE_FLAVOR = "win_create_flavor"
WIN_MODEL = "win_model"
# create flavors (MPI_WIN_FLAVOR_*)
FLAVOR_CREATE = 1
FLAVOR_ALLOCATE = 2
FLAVOR_DYNAMIC = 3
FLAVOR_SHARED = 4
# memory models: driver mode is one address space with epoch-close
# visibility = MPI_WIN_UNIFIED semantics
MODEL_SEPARATE = 1
MODEL_UNIFIED = 2


class Window:
    def __init__(self, comm, base: jax.Array, name: str = "") -> None:
        if getattr(comm, "spans_processes", False):
            # guard against silent mis-sharding: comm.submesh covers
            # only LOCAL members on a spanning comm, so placing
            # comm.size rows over it would scatter remote ranks' slices
            # onto local devices — the wire window stores local slices
            # and ships remote RMA to its home (osc/wire_win.py)
            raise MPIError(
                ErrorCode.ERR_WIN,
                f"{comm.name} spans controller processes; construct "
                "windows through win_create/win_allocate (wire-window "
                "path), not Window() directly",
            )
        if base.shape[0] != comm.size:
            raise MPIError(
                ErrorCode.ERR_WIN,
                f"window base leading axis {base.shape[0]} != comm size "
                f"{comm.size}",
            )
        self._init_state(comm, base, name)

    def _init_state(self, comm, base, name: str) -> None:
        """Shared field setup (subclasses with a different leading-axis
        contract — the spanning-comm wire window — reuse this so new
        fields cannot silently diverge)."""
        self.comm = comm
        self.name = name or f"win{id(self):x}"
        self._shard = NamedSharding(comm.submesh, P("rank"))
        self._data = jax.device_put(jnp.asarray(base), self._shard)
        self._epoch = _EpochKind.NONE
        self._locked: Dict[int, int] = {}  # target -> lock type
        self._pending: List[_PendingOp] = []
        # one controller, possibly many threads (a producer thread
        # posting AMOs while a waiter polls with get/flush): the
        # pending queue and its apply/commit must be atomic or
        # concurrent flushes lose ops
        import threading as _threading

        self._op_lock = _threading.RLock()
        self._group_exposed = None  # PSCW exposure group
        self._freed = False
        self._flavor = FLAVOR_CREATE  # constructors override
        self._attrs: Dict[int, object] = {}  # user keyvals (win_keyval)
        # frozen per-epoch-signature access plans and precomposed
        # remote-batch wire frames (osc/plan); evicted at free()
        self._access_plans: Dict[Tuple, Any] = {}
        self._batch_templates: Dict[Tuple, Any] = {}

    # -- queries -----------------------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._data.shape[1:])

    @property
    def dtype(self):
        return self._data.dtype

    def read(self) -> jax.Array:
        """Local loads of the whole window (valid outside access epochs
        or after a flush; driver mode sees every rank's slice)."""
        return self._data

    def set_attr(self, keyval, value) -> None:
        """MPI_Win_set_attr with a user keyval (the same Keyval
        objects ``comm.create_keyval`` mints — ``win.c`` shares one
        attribute machinery across comm/win/datatype)."""
        if self._freed:
            raise MPIError(ErrorCode.ERR_WIN, f"{self.name} freed")
        self._attrs[keyval.id] = value

    def delete_attr(self, keyval) -> None:
        from ..comm.communicator import _keyval_table

        kv = _keyval_table.get(keyval.id)
        value = self._attrs.pop(keyval.id, None)
        if kv is not None and kv.delete_fn is not None and value is not None:
            kv.delete_fn(self, kv, value, kv.extra_state)

    def get_attr(self, key):
        """MPI_Win_get_attr: predefined string attributes
        (``ompi/win/win.c`` WIN_BASE..WIN_MODEL) or a user Keyval;
        returns (found, value).  MPI's view is per-process: WIN_SIZE /
        WIN_DISP_UNIT describe ONE rank's window (block bytes,
        element size).  WIN_BASE in driver mode is the whole
        (comm.size, ...) storage — one controller plays every rank,
        so "the local base" is ``base[rank]``; sizes are metadata
        only (no device access)."""
        import math

        if not isinstance(key, str):  # user keyval
            if key.id in self._attrs:
                return True, self._attrs[key.id]
            return False, None
        if key == WIN_BASE:
            return True, self._data
        if key == WIN_SIZE:
            n = math.prod(self._data.shape[1:])
            return True, int(n * self._data.dtype.itemsize)
        if key == WIN_DISP_UNIT:
            return True, int(self._data.dtype.itemsize)
        if key == WIN_CREATE_FLAVOR:
            return True, self._flavor
        if key == WIN_MODEL:
            return True, MODEL_UNIFIED
        return False, None

    def shared_query(self, rank: int):
        """MPI_Win_shared_query (``osc/sm``): (size_bytes, disp_unit,
        block) for ``rank``'s segment of a shared window.  The block
        is a SNAPSHOT as of the last epoch close (arrays are
        immutable; every flush rebinds the window storage), so unlike
        the reference's baseptr it does not observe later stores —
        re-query after a flush, same discipline as :meth:`read`.
        ``rank=-1`` (MPI_PROC_NULL convention) answers for the lowest
        rank."""
        if not getattr(self, "_shared", False):
            raise MPIError(
                ErrorCode.ERR_RMA_SHARED,
                f"{self.name} was not created by win_allocate_shared",
            )
        if rank == -1:
            rank = 0
        if not 0 <= rank < self.comm.size:
            raise MPIError(ErrorCode.ERR_RANK,
                           f"shared_query rank {rank} out of range")
        blk = self._data[rank]
        return int(blk.size * blk.dtype.itemsize), \
            int(blk.dtype.itemsize), blk

    # -- epoch state machine ----------------------------------------------
    def _require(self, *kinds: _EpochKind) -> None:
        if self._freed:
            raise MPIError(ErrorCode.ERR_WIN, f"{self.name} freed")
        if self._epoch not in kinds:
            raise MPIError(
                ErrorCode.ERR_RMA_SYNC,
                f"operation requires epoch {[k.value for k in kinds]}, "
                f"window is in '{self._epoch.value}'",
            )

    def fence(self, _barrier: bool = True) -> None:
        """Open/continue a fence epoch; applies queued ops (MPI fence
        both closes the previous access epoch and opens the next).
        ``_barrier=False`` is for composite windows (DynamicWindow)
        that fan one fence over many regions and barrier ONCE."""
        self._require(_EpochKind.NONE, _EpochKind.FENCE)
        self._apply_pending()
        self._epoch = _EpochKind.FENCE
        if _barrier:
            self.comm.barrier()

    def fence_end(self, _barrier: bool = True) -> None:
        """Final fence (MPI_MODE_NOSUCCEED): close the epoch."""
        self._require(_EpochKind.FENCE)
        self._apply_pending()
        self._epoch = _EpochKind.NONE
        if _barrier:
            self.comm.barrier()

    def lock(self, target: int, lock_type: int = LOCK_EXCLUSIVE) -> None:
        self._require(_EpochKind.NONE, _EpochKind.LOCK)
        if target in self._locked:
            raise MPIError(ErrorCode.ERR_RMA_SYNC,
                           f"target {target} already locked")
        self._locked[target] = lock_type
        self._epoch = _EpochKind.LOCK

    def lock_all(self) -> None:
        self._require(_EpochKind.NONE)
        for t in range(self.comm.size):
            self._locked[t] = LOCK_SHARED
        self._epoch = _EpochKind.LOCK

    def unlock(self, target: int) -> None:
        self._require(_EpochKind.LOCK)
        if target not in self._locked:
            raise MPIError(ErrorCode.ERR_RMA_SYNC,
                           f"target {target} not locked")
        self._apply_pending(only_target=target)
        del self._locked[target]
        if not self._locked:
            self._epoch = _EpochKind.NONE

    def unlock_all(self) -> None:
        self._require(_EpochKind.LOCK)
        self._apply_pending()
        self._locked.clear()
        self._epoch = _EpochKind.NONE

    def flush(self, target: int) -> None:
        """Complete pending ops to one target inside a passive epoch."""
        self._require(_EpochKind.LOCK)
        self._apply_pending(only_target=target)

    def flush_all(self) -> None:
        self._require(_EpochKind.LOCK)
        self._apply_pending()

    def flush_local(self, target: int) -> None:
        """MPI_Win_flush_local: local completion only. Buffers here are
        immutable arrays (reusable the moment the op is queued), so
        local completion is implied — but MPI still requires the epoch
        check, and completing remotely too is allowed (stronger)."""
        self.flush(target)

    def flush_local_all(self) -> None:
        self.flush_all()

    def sync(self) -> None:
        """MPI_Win_sync: synchronize public/private window copies. The
        driver-mode window is MPI_WIN_UNIFIED with one storage array —
        there is no second copy to reconcile (get_attr WIN_MODEL)."""
        self._require(_EpochKind.FENCE, _EpochKind.LOCK,
                      _EpochKind.PSCW, _EpochKind.NONE)

    # PSCW (generalized active target)
    def post(self, group) -> None:
        """Exposure epoch: this window's slices may be targeted by the
        ranks of ``group`` (driver mode keeps one state machine)."""
        self._require(_EpochKind.NONE)
        self._group_exposed = group
        self._epoch = _EpochKind.PSCW

    def start(self, group) -> None:
        self._require(_EpochKind.NONE, _EpochKind.PSCW)
        self._epoch = _EpochKind.PSCW

    def complete(self) -> None:
        """Close the access side of a PSCW epoch (MPI_Win_complete)."""
        self._require(_EpochKind.PSCW)
        self._apply_pending()
        self._epoch = _EpochKind.NONE

    def wait(self) -> None:
        """Close the exposure side (MPI_Win_wait). The single driver
        state machine conflates access/exposure, so wait() after the
        origin's complete() must succeed — it applies anything still
        pending and clears the exposure group. A bare start() access
        epoch has no exposure to wait on and is rejected."""
        if self._group_exposed is None:
            raise MPIError(ErrorCode.ERR_RMA_SYNC,
                           "wait() without a matching post()")
        if self._epoch is _EpochKind.PSCW:
            self._apply_pending()
            self._epoch = _EpochKind.NONE
        self._group_exposed = None

    def test(self) -> bool:
        """MPI_Win_test: nonblocking wait(). Single controller: every
        origin's complete() has necessarily run by the time test() is
        reachable, so a posted exposure tests complete (and closes,
        like wait)."""
        if self._group_exposed is None:
            raise MPIError(ErrorCode.ERR_RMA_SYNC,
                           "test() without a matching post()")
        self.wait()
        return True

    def free(self) -> None:
        if self._pending:
            raise MPIError(ErrorCode.ERR_RMA_SYNC,
                           "free with unsynchronized RMA operations")
        # MPI_Win_free runs the attribute delete callbacks for every
        # still-attached user keyval — the same shared attribute
        # machinery Communicator.free() drains (win.c keyval contract)
        from ..comm.communicator import _keyval_table

        for kv_id, value in list(self._attrs.items()):
            kv = _keyval_table.get(kv_id)
            if kv and kv.delete_fn:
                kv.delete_fn(self, kv, value, kv.extra_state)
        self._attrs.clear()
        # a freed window must not pin fused epoch programs or frame
        # templates (osc/plan eviction contract)
        self._access_plans.clear()
        self._batch_templates.clear()
        self._freed = True

    # -- RMA operations ----------------------------------------------------
    def _queue(self, op: _PendingOp) -> Optional[Request]:
        self._require(_EpochKind.FENCE, _EpochKind.LOCK, _EpochKind.PSCW)
        if (self._epoch is _EpochKind.LOCK
                and op.target not in self._locked):
            raise MPIError(ErrorCode.ERR_RMA_SYNC,
                           f"target {op.target} not locked")
        if not 0 <= op.target < self.comm.size:
            raise MPIError(ErrorCode.ERR_RANK,
                           f"RMA target {op.target} out of range")
        if op.index is not None:
            slot_elems = 1
            for d in self.shape:
                slot_elems *= d
            if not 0 <= op.index < slot_elems:
                raise MPIError(
                    ErrorCode.ERR_ARG,
                    f"RMA element index {op.index} out of range for "
                    f"slot of {slot_elems} elements",
                )
        _rma_ops.add()
        with self._op_lock:
            self._pending.append(op)
        return op.request

    def _rma_request(self, target: int) -> Request:
        """A Request completable by ``wait()`` ALONE: its block_fn
        flushes the op's target (``_apply_pending(only_target)``), the
        per-op completion MPI 3.1 gives request-based RMA inside a
        passive epoch (``osc.h:341-366`` — MPI_Wait on an Rput/Rget
        request has flush semantics for that operation). Without this,
        wait() before the epoch close raised 'wait() would deadlock'
        even though the spec promises completion. Flushing the whole
        target is stronger than one op — allowed, same-origin ordering
        makes it indistinguishable."""
        return Request(
            block_fn=lambda: self._apply_pending(only_target=target)
        )

    def put(self, data, target: int, index: Optional[int] = None) -> None:
        """Put a whole slot, or (``index`` given) a single element at a
        flat offset within the slot (MPI target_disp addressing)."""
        self._queue(_PendingOp("put", target, jnp.asarray(data), REPLACE,
                               index=index))

    def get(self, target: int) -> Request:
        req = self._rma_request(target)
        self._queue(_PendingOp("get", target, request=req))
        return req

    def accumulate(self, data, target: int, op: Op = SUM,
                   index: Optional[int] = None) -> None:
        self._queue(_PendingOp("acc", target, jnp.asarray(data), op,
                               index=index))

    def get_accumulate(self, data, target: int, op: Op = SUM,
                       index: Optional[int] = None) -> Request:
        req = self._rma_request(target)
        self._queue(
            _PendingOp("get_acc", target, jnp.asarray(data), op, req,
                       index=index)
        )
        return req

    def fetch_and_op(self, value, target: int, op: Op = SUM,
                     index: Optional[int] = None) -> Request:
        """MPI_Fetch_and_op: single element when ``index`` is given
        (the MPI call is defined on ONE element at target_disp —
        ``osc.h:310``); whole-slot elementwise otherwise."""
        return self.get_accumulate(value, target, op, index=index)

    # -- request-based RMA (MPI-3 MPI_Rput/Rget/Raccumulate) ---------------
    # Each returns a Request completable INSIDE the epoch (wait =
    # per-op flush semantics, osc.h:341-366). get/get_accumulate are
    # already request-based; the R-forms of put/accumulate attach a
    # request that completes when the op applies (epoch close or
    # flush), carrying the pre-op slice like the reference's
    # origin-completion semantics allow.
    def rput(self, data, target: int,
             index: Optional[int] = None) -> Request:
        req = self._rma_request(target)
        self._queue(_PendingOp("put", target, jnp.asarray(data), REPLACE,
                               request=req, index=index))
        return req

    def raccumulate(self, data, target: int, op: Op = SUM,
                    index: Optional[int] = None) -> Request:
        req = self._rma_request(target)
        self._queue(_PendingOp("acc", target, jnp.asarray(data), op,
                               request=req, index=index))
        return req

    def rget(self, target: int) -> Request:
        return self.get(target)

    def rget_accumulate(self, data, target: int, op: Op = SUM,
                        index: Optional[int] = None) -> Request:
        return self.get_accumulate(data, target, op, index=index)

    def compare_and_swap(self, value, compare, target: int,
                         index: Optional[int] = None) -> Request:
        """MPI_Compare_and_swap. With ``index``, true single-element
        CAS at a flat offset (MPI semantics, ``osc.h:324``); without,
        an elementwise CAS over the whole slot (a documented
        whole-block extension)."""
        req = self._rma_request(target)
        self._queue(
            _PendingOp("cas", target, jnp.asarray(value), None, req,
                       compare=jnp.asarray(compare), index=index)
        )
        return req

    # -- application -------------------------------------------------------
    @staticmethod
    def _branch_key(p: _PendingOp) -> Tuple[str, Any, bool]:
        indexed = p.index is not None
        if p.kind in ("acc", "get_acc"):
            # the op OBJECT (frozen, hashable), not its name: branch
            # keys feed the epoch program cache sig, and a same-named
            # op with a different combiner must get its own branch
            return ("acc", p.op, indexed)
        return (p.kind, "", indexed)

    @staticmethod
    def _branch_fn(key: Tuple[str, Any, bool], op: Optional[Op]):
        """One lax.switch branch: (cur, payload, compare, idx) ->
        (new_slice, pre_op_read). ``payload``/``compare`` may be
        scalars (scalar-payload epochs) or full slices; indexed
        branches operate on the single element at flat offset ``idx``
        (single-element MPI semantics — the read-back element is
        extracted host-side from the pre-op slice)."""
        kind, _, indexed = key

        def elem(pay, idx):
            # scalar payload, or a slice broadcast from one — any
            # element of the flattened broadcast is the scalar
            return (pay if jnp.ndim(pay) == 0
                    else pay.reshape(-1)[idx])

        if kind == "noop":
            return lambda cur, pay, cmp, idx: (cur, cur)
        if kind == "put":
            if indexed:
                return lambda cur, pay, cmp, idx: (
                    cur.reshape(-1).at[idx].set(elem(pay, idx))
                    .reshape(cur.shape), cur)
            return lambda cur, pay, cmp, idx: (
                jnp.broadcast_to(pay, cur.shape), cur)
        if kind == "get":
            return lambda cur, pay, cmp, idx: (cur, cur)
        if kind == "acc":
            if indexed:
                def acc_elem(cur, pay, cmp, idx):
                    flat = cur.reshape(-1)
                    new_e = op(flat[idx], elem(pay, idx))
                    return flat.at[idx].set(new_e).reshape(cur.shape), cur
                return acc_elem
            # ops that ignore cur (REPLACE) return the payload as-is —
            # a scalar in scalar-payload epochs — so pin the branch
            # output to the slice shape or lax.switch rejects the
            # branch set (scalar new vs slice new)
            return lambda cur, pay, cmp, idx: (
                jnp.broadcast_to(op(cur, pay), cur.shape), cur)
        # cas
        if indexed:
            def cas_elem(cur, pay, cmp, idx):
                flat = cur.reshape(-1)
                old = flat[idx]
                new_e = jnp.where(old == elem(cmp, idx),
                                  elem(pay, idx), old)
                return flat.at[idx].set(new_e).reshape(cur.shape), cur
            return cas_elem
        return lambda cur, pay, cmp, idx: (
            jnp.where(cur == cmp, pay, cur), cur
        )

    def _apply_pending(self, only_target: Optional[int] = None) -> None:
        """Apply queued ops in submission order (MPI same-origin
        ordering; driver mode's single queue is globally ordered) as
        ONE compiled program per epoch.

        The program is a ``lax.scan`` over the op list: step i reads
        slice ``targets[i]``, dispatches ``codes[i]`` through a
        ``lax.switch`` over the epoch's distinct (kind, op) branches,
        writes the new slice back, and emits the pre-op value (what
        get/get_acc/cas return). Targets/kinds/payloads are runtime
        DATA, so the compile cache key is only (op count, window
        shape/dtype, branch set): re-closing an epoch with the same
        shape never retraces, and dispatch count is 1 per close
        regardless of how many RMA ops queued (the osc/rdma "aggregate
        and issue at sync" strategy, done as XLA intends it).
        """
        with self._op_lock:
            self._apply_pending_locked(only_target)

    def _take_pending(self, only_target: Optional[int] = None
                      ) -> List[_PendingOp]:
        """Atomically remove (and return) the ops this close covers."""
        if only_target is None:
            todo, self._pending = self._pending, []
        else:
            todo = [p for p in self._pending if p.target == only_target]
            self._pending = [
                p for p in self._pending if p.target != only_target
            ]
        return todo

    def _apply_pending_locked(self, only_target: Optional[int] = None
                              ) -> None:
        if not self._pending:
            return
        _epoch_count.add()
        todo = self._take_pending(only_target)
        if not todo:
            return
        t0 = time.perf_counter()
        from . import plan as _osc_plan

        # a repeated epoch replays its frozen access plan (one fused
        # program, no per-close branch dispatch); the first close of a
        # new signature captures through the interpreted program below
        if not _osc_plan.close_epoch(self, todo, t0):
            self._run_epoch_program(todo, _t0=t0)

    def _run_epoch_program(self, todo: List[_PendingOp],
                           _t0: Optional[float] = None) -> None:
        """Apply ``todo`` (targets = storage row indices) as one
        compiled program and complete its read requests. Callers hold
        ``_op_lock``. ``_t0`` (close-entry clock) feeds the shared
        orchestration timer so the interpreted and planned paths are
        measured over identical spans."""
        if not todo:
            return
        from jax import lax

        dtype = self._data.dtype
        block = self.shape

        # Scalar-payload epochs (the common AMO pattern: many scalar
        # accumulates/CAS on a large window) keep payloads as (n,)
        # scalars — broadcast happens INSIDE the kernel, so host-side
        # staging is n scalars, not n x slot bytes.
        scalar_mode = all(
            (p.data is None or jnp.ndim(p.data) == 0)
            and (p.compare is None or jnp.ndim(p.compare) == 0)
            for p in todo
        ) and block != ()

        branch_keys: List[Tuple[str, Any, bool]] = []
        branch_fns = []
        codes: List[int] = []
        for p in todo:
            k = self._branch_key(p)
            if k not in branch_keys:
                branch_keys.append(k)
                branch_fns.append(self._branch_fn(k, p.op))
            codes.append(branch_keys.index(k))

        # Pad the op count to the next power of two with no-op entries
        # so the program cache holds O(log n) programs per branch set
        # instead of one per distinct epoch length. The noop branch is
        # ALWAYS part of the branch set so padded and exact-power-of-two
        # epochs share one program.
        n = len(todo)
        n_pad = 1 << (n - 1).bit_length() if n > 1 else 1
        noop_key = ("noop", "", False)
        if noop_key not in branch_keys:
            branch_keys.append(noop_key)
            branch_fns.append(self._branch_fn(noop_key, None))
        codes.extend([branch_keys.index(noop_key)] * (n_pad - n))

        pay_shape = () if scalar_mode else block
        zeros = jnp.zeros(pay_shape, dtype)  # shared by all pad slots

        def pay(x):
            if x is None:
                return zeros
            return jnp.broadcast_to(jnp.asarray(x).astype(dtype),
                                    pay_shape)

        codes_a = jnp.asarray(codes, jnp.int32)
        targets_a = jnp.asarray(
            [p.target for p in todo] + [0] * (n_pad - n), jnp.int32
        )
        zero_pad = [None] * (n_pad - n)
        payloads = jnp.stack([pay(p.data) for p in todo]
                             + [pay(x) for x in zero_pad])
        compares = jnp.stack([pay(p.compare) for p in todo]
                             + [pay(x) for x in zero_pad])
        indices = jnp.asarray(
            [p.index if p.index is not None else 0 for p in todo]
            + [0] * (n_pad - n), jnp.int32
        )

        sig = (n_pad, block, str(dtype), tuple(branch_keys), scalar_mode)
        if _t0 is not None:
            from . import plan as _osc_plan

            _osc_plan.orch_add(time.perf_counter() - _t0)
        with _dispatch_lock:
            prog = _program_cache.get(sig)
            if prog is None:
                _epoch_programs.add()

                def close_epoch(data, codes, targets, payloads,
                                compares, indices):
                    def step(data, xs):
                        code, tgt, payv, cmpv, idx = xs
                        cur = lax.dynamic_index_in_dim(
                            data, tgt, 0, keepdims=False
                        )
                        new, read = lax.switch(
                            code, branch_fns, cur, payv, cmpv, idx
                        )
                        data = lax.dynamic_update_index_in_dim(
                            data, new, tgt, 0
                        )
                        return data, read

                    return lax.scan(
                        step, data,
                        (codes, targets, payloads, compares, indices)
                    )

                prog = jax.jit(close_epoch)
                _program_cache[sig] = prog
            _epoch_dispatches.add()
            new_data, reads = prog(
                self._data, codes_a, targets_a, payloads, compares,
                indices
            )
        # Complete read requests from ONE host copy of the outputs.
        # ``reads[i]`` on the sharded program output would dispatch an
        # eager multi-device gather OUTSIDE _dispatch_lock; a
        # concurrent thread's compiled epoch program then deadlocks
        # jaxlib's cross-program collective rendezvous — each program
        # holds a subset of the per-device threads and neither can
        # assemble its full set (flight-recorder stacks during
        # test_shmem_topo's lock-contention hang pinned one thread in
        # apply_primitive(gather) at this line with two run_ids parked
        # at the rendezvous). Device work stays exclusively under
        # _dispatch_lock; the host fetch is per-shard copies, not a
        # program, and epochs with no read requests skip it entirely.
        reads_np = None
        for i, p in enumerate(todo):
            if p.request is not None:
                if reads_np is None:
                    import numpy as _np

                    reads_np = _np.asarray(reads)
                value = reads_np[i]
                if p.index is not None:
                    # single-element op: hand back the element itself
                    value = value.reshape(-1)[p.index]
                src = (p.target if p.status_rank is None
                       else p.status_rank)
                p.request.complete(value=jnp.asarray(value),
                                   status=Status(source=src))
        self._data = new_data


def win_create(comm, base, name: str = "") -> Window:
    """MPI_Win_create: wrap existing per-rank buffers (leading rank
    axis; one slice per LOCAL member on a spanning comm)."""
    if getattr(comm, "spans_processes", False):
        from .wire_win import WireWindow

        return WireWindow(comm, jnp.asarray(base), name)
    return Window(comm, jnp.asarray(base), name)


def win_allocate(comm, shape: Tuple[int, ...], dtype=jnp.float32,
                 name: str = "") -> Window:
    """MPI_Win_allocate: fresh zeroed window, one ``shape`` block per
    rank."""
    if getattr(comm, "spans_processes", False):
        from .wire_win import WireWindow

        local_n = len(comm.local_comm_ranks)
        win = WireWindow(
            comm, jnp.zeros((local_n,) + tuple(shape), dtype), name
        )
    else:
        win = Window(
            comm, jnp.zeros((comm.size,) + tuple(shape), dtype), name
        )
    win._flavor = FLAVOR_ALLOCATE
    return win


def win_allocate_shared(comm, shape: Tuple[int, ...],
                        dtype=jnp.float32, name: str = "") -> Window:
    """MPI_Win_allocate_shared (the ``osc/sm`` component's role): a
    window whose ranks' blocks are one CONTIGUOUS allocation (the
    default alloc_shared_noncontig=false layout), so neighbors can
    address each other's memory directly. The window carries
    :meth:`Window.shared_query`; the comm should come from
    ``split_type_shared`` (enforced loosely — driver mode has one
    address space by construction, so every comm qualifies; a real
    multi-host comm would reject here, and the honest check is the
    endpoints' host identity)."""
    if getattr(comm, "spans_processes", False):
        raise MPIError(
            ErrorCode.ERR_RMA_SHARED,
            "win_allocate_shared needs a process-local comm (device "
            "buffers cannot be shared across controller processes); "
            "split with split_type_shared first",
        )
    # direct attribute access ON PURPOSE: a rename in runtime/group
    # must surface as an AttributeError here, not silently turn the
    # multi-host safety gate vacuous
    members = set(comm.group.world_ranks)
    hosts = {ep.host for ep in comm.runtime.endpoints
             if ep.rank in members}
    if len(hosts) > 1:
        raise MPIError(
            ErrorCode.ERR_RMA_SHARED,
            f"win_allocate_shared needs a single-host comm "
            f"(got hosts {sorted(h or '?' for h in hosts)}); split "
            "with split_type_shared first",
        )
    win = win_allocate(comm, shape, dtype, name)
    win._shared = True
    win._flavor = FLAVOR_SHARED
    return win


class DynamicWindow:
    """MPI_Win_create_dynamic + MPI_Win_attach/detach
    (``ompi/mca/osc/rdma`` dynamic-flavor support): a window created
    EMPTY whose memory regions attach and detach while it lives.

    Driver-mode mapping: each :meth:`attach` creates one uniform
    per-rank region (a fresh :class:`Window`) addressed by the
    returned region id — the analogue of the reference's
    absolute-address targeting, with the id playing the attached-base
    role.  Epoch synchronization spans the WHOLE dynamic window:
    fence/lock_all/unlock_all/flush_all fan out to every attached
    region (one comm barrier per fence, not per region) and a region
    attached MID-EPOCH inherits the open epoch, as MPI_Win_attach
    requires.  Per-region RMA goes through the owning region's queue
    (MPI ordering guarantees are per (origin, target) pair).
    Detaching with queued unsynchronized ops is refused, and free()
    refuses atomically — it frees nothing unless EVERY region is
    synchronized.  A lock guards the region table: the documented
    Window threading pattern (producer thread + waiter) extends to
    concurrent attach/detach against epoch fan-outs."""

    def __init__(self, comm, name: str = "") -> None:
        import threading as _threading

        self.comm = comm
        self.name = name or f"dynwin{id(self):x}"
        self._regions: Dict[int, Window] = {}
        self._next_region = 0
        self._flavor = FLAVOR_DYNAMIC
        self._freed = False
        self._open: Optional[str] = None  # None | "fence" | "lock"
        self._lock = _threading.RLock()

    # -- attach / detach ---------------------------------------------------
    def attach(self, shape: Tuple[int, ...], dtype=jnp.float32) -> int:
        """MPI_Win_attach: expose a fresh zeroed per-rank region;
        returns its region id. Legal mid-epoch — the new region joins
        the open epoch."""
        with self._lock:
            if self._freed:
                raise MPIError(ErrorCode.ERR_WIN, f"{self.name} freed")
            rid = self._next_region
            self._next_region += 1
            win = win_allocate(self.comm, shape, dtype,
                               f"{self.name}.r{rid}")
            win._flavor = FLAVOR_DYNAMIC
            if self._open == "fence":
                win.fence(_barrier=False)
            elif self._open == "lock":
                win.lock_all()
            self._regions[rid] = win
            return rid

    def detach(self, region: int) -> None:
        """MPI_Win_detach: the region must have no unsynchronized
        RMA queued (same rule as freeing mid-epoch)."""
        with self._lock:
            win = self._region(region)
            if win._pending:
                raise MPIError(
                    ErrorCode.ERR_RMA_SYNC,
                    f"{self.name}: detach of region {region} with "
                    "unsynchronized RMA operations",
                )
            win._freed = True
            del self._regions[region]

    def _region(self, region: int) -> Window:
        with self._lock:
            if self._freed:
                raise MPIError(ErrorCode.ERR_WIN, f"{self.name} freed")
            w = self._regions.get(region)
            if w is None:
                raise MPIError(
                    ErrorCode.ERR_BASE,
                    f"{self.name}: region {region} is not attached "
                    f"(attached: {sorted(self._regions)})",
                )
            return w

    # -- queries -----------------------------------------------------------
    def get_attr(self, key: str):
        if key == WIN_CREATE_FLAVOR:
            return True, self._flavor
        if key == WIN_MODEL:
            return True, MODEL_UNIFIED
        if key == WIN_BASE:
            # MPI_BOTTOM for dynamic windows: no single base
            return True, None
        if key == WIN_SIZE:
            return True, 0
        if key == WIN_DISP_UNIT:
            return True, 1
        return False, None

    def read(self, region: int) -> jax.Array:
        return self._region(region).read()

    # -- epochs fan out to every attached region ---------------------------
    def fence(self) -> None:
        with self._lock:
            for w in self._regions.values():
                w.fence(_barrier=False)
            self._open = "fence"
        self.comm.barrier()  # ONE barrier per fence, not per region

    def fence_end(self) -> None:
        with self._lock:
            for w in self._regions.values():
                w.fence_end(_barrier=False)
            self._open = None
        self.comm.barrier()

    def lock_all(self) -> None:
        with self._lock:
            for w in self._regions.values():
                w.lock_all()
            self._open = "lock"

    def unlock_all(self) -> None:
        with self._lock:
            for w in self._regions.values():
                w.unlock_all()
            self._open = None

    def flush_all(self) -> None:
        with self._lock:
            for w in self._regions.values():
                w.flush_all()

    # -- RMA: target = (rank, region) --------------------------------------
    def put(self, data, target: int, *, region: int, **kw):
        return self._region(region).put(data, target, **kw)

    def get(self, target: int, *, region: int, **kw):
        return self._region(region).get(target, **kw)

    def accumulate(self, data, target: int, *, region: int, **kw):
        return self._region(region).accumulate(data, target, **kw)

    def get_accumulate(self, data, target: int, *, region: int, **kw):
        return self._region(region).get_accumulate(data, target, **kw)

    def fetch_and_op(self, data, target: int, *, region: int, **kw):
        return self._region(region).fetch_and_op(data, target, **kw)

    def compare_and_swap(self, value, compare, target: int, *,
                         region: int, **kw):
        return self._region(region).compare_and_swap(
            value, compare, target, **kw)

    def free(self) -> None:
        """Atomic: refuses (freeing NOTHING) unless every region is
        synchronized — a partial free would strand pending ops on a
        half-dead window."""
        with self._lock:
            bad = [rid for rid, w in self._regions.items() if w._pending]
            if bad:
                raise MPIError(
                    ErrorCode.ERR_RMA_SYNC,
                    f"{self.name}: free with unsynchronized RMA in "
                    f"region(s) {bad}",
                )
            for w in self._regions.values():
                w.free()
            self._regions.clear()
            self._freed = True


def win_create_dynamic(comm, name: str = "") -> DynamicWindow:
    """MPI_Win_create_dynamic: an empty window; memory attaches
    later (``ompi/mpi/c/win_create_dynamic.c``)."""
    return DynamicWindow(comm, name)
