"""ompi_release_tpu — a TPU-native message-passing & collectives framework.

A ground-up re-design of the capabilities of Open MPI 1.8.5 (reference
surveyed in SURVEY.md) for TPUs: the data plane lowers to JAX/XLA
(`psum`/`ppermute`/`all_gather` over a persistent device mesh, Pallas
kernels where hand scheduling wins); the control plane is a lightweight
in-process runtime with an ORTE-style job state machine.

Layering (mirrors the reference's OPAL/ORTE/OMPI/OSHMEM stack, SURVEY §1):

  - ``mca``/``utils``     — OPAL analogue: config vars, components, logging
  - ``runtime``           — ORTE analogue: mesh bring-up, job state machine
  - ``datatype``/``ops``/``comm``/``coll``/``p2p``/``osc``/``io`` — OMPI
  - ``shmem``             — OSHMEM analogue: symmetric heap put/get
  - ``parallel``/``models`` — parallelism strategies (DP/TP/PP/SP/EP/CP)
    built over the substrate, with a flagship model as validation workload

Heavy (jax-importing) subpackages are imported lazily so that pure-host
config/unit tooling stays cheap, mirroring opal_init_util vs full init
(``opal/runtime/opal_init.c:245,350``).
"""

from . import mca, utils
from .utils.errors import ErrorCode, MPIError

__version__ = "0.1.0"

_LAZY = {
    "runtime", "datatype", "ops", "comm", "coll", "p2p", "osc", "shmem",
    "io", "parallel", "models", "tools", "obs", "testing", "service",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def init(*, cli_args=None):
    """Bring up the full runtime (the ``MPI_Init`` analogue).

    Returns the WORLD communicator. See ``runtime.init`` for details.
    """
    from .runtime import init as _rt_init

    return _rt_init(cli_args=cli_args)


def finalize():
    from .runtime import finalize as _rt_finalize

    return _rt_finalize()


def initialized() -> bool:
    """MPI_Initialized."""
    from .runtime.runtime import Runtime

    return Runtime.is_initialized()


def finalized() -> bool:
    """MPI_Finalized."""
    from .runtime.runtime import Runtime

    rt = Runtime._instance
    return bool(rt is not None and rt.finalized)


def wtime() -> float:
    """MPI_Wtime: monotonic wall-clock seconds."""
    import time

    return time.monotonic()


def wtick() -> float:
    """MPI_Wtick: the wtime clock's resolution."""
    import time

    return time.get_clock_info("monotonic").resolution


def get_version():
    """MPI_Get_version analogue: (framework version, reference level).

    The capability level mirrors the reference's MPI-3.0-era surface
    (the subset re-designed TPU-native; see README's inventory)."""
    return __version__, "ompi-1.8.5-capability"


def error_string(code) -> str:
    """MPI_Error_string: human text for an error class."""
    try:
        return ErrorCode(code).name
    except ValueError:
        return f"unknown error code {code}"
