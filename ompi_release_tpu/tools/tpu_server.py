"""tpu-server — the standalone ``orte-server`` analogue.

The reference's cross-job dynamics need a name server that OUTLIVES
any one job: ``orte-server`` (``orte/tools/orte-server``) hosts the
``pubsub/orte`` name table so two independently-launched mpirun jobs
can MPI_Publish_name / MPI_Lookup_name each other
(``ompi/mca/pubsub/orte/pubsub_orte.c``). A tpurun job's HNP already
serves names for its OWN workers; this tool is the job-independent
server: any process (from any job) connects with a :class:`NameClient`
and publishes/looks up over the same seq-correlated frame protocol.

Beyond names, the server answers a ``metrics`` RPC (TAG_METRICS): the
Prometheus text exposition of every pvar registered in the server
process (``obs/export.py``), so ``tpu_top --metrics host:port`` (or
any scraper speaking the frame protocol) can watch the observability
plane live.

Usage::

    python -m ompi_release_tpu.tools.tpu_server [--port P] [--bind A]
    # prints "tpu-server URI: host:port" then serves until SIGINT

    client = NameClient("hostA", 45123)
    client.publish("my-service", port_str)
    port = client.lookup("my-service", timeout_ms=20000)
    page = client.metrics()          # Prometheus text page
"""

from __future__ import annotations

import argparse
import random
import threading
import time
from typing import List, Optional, Tuple

from ..native import DssBuffer, OobEndpoint
from ..runtime.coordinator import local_addr_toward
from ..runtime.pubsub import (PubsubTable, TAG_LOOKUP, TAG_PUBLISH,
                              TAG_UNPUBLISH)
from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.stream("tpu-server")

TAG_METRICS = 13  # client->server: Prometheus pvar exposition request
TAG_JOURNAL = 14  # client->server: obs rank-journal dump (JSON)
TAG_SERIES = 15   # client->server: continuous pvar time-series (JSON)


class MetricsPubsubTable(PubsubTable):
    """Name table + three observability RPCs over the same
    seq-correlated reply channel: TAG_METRICS answers with the
    Prometheus text page of every pvar registered in this process;
    TAG_JOURNAL answers with this process's rank journal dump
    (``obs.export.rank_dump`` JSON) — the unit ``tpu-doctor collect``
    fetches and ``tpu-doctor merge`` joins across ranks; TAG_SERIES
    answers with this process's continuous sampler ring
    (``obs.export.series_dump`` JSON) — identity + clock offset +
    time-series points, the live feed ``tpu_top`` renders."""

    def __init__(self, ep) -> None:
        super().__init__(ep)
        self.serve_tags.append(TAG_METRICS)
        self.serve_tags.append(TAG_JOURNAL)
        self.serve_tags.append(TAG_SERIES)

    def handle(self, tag: int, src: int, raw: bytes) -> None:
        if tag not in (TAG_METRICS, TAG_JOURNAL, TAG_SERIES):
            return super().handle(tag, src, raw)
        b = DssBuffer(raw)
        (seq,) = b.unpack_int64()
        if tag == TAG_METRICS:
            from ..obs import export as obs_export

            self._reply(src, seq, True, obs_export.prometheus_text())
        else:
            import json as _json

            from ..obs import export as obs_export

            doc = (obs_export.rank_dump() if tag == TAG_JOURNAL
                   else obs_export.series_dump())
            self._reply(src, seq, True, _json.dumps(doc))


class NameServer:
    """Standalone name-table server: the shared runtime/pubsub.py
    protocol on its own endpoint (no job attached). ``table_factory``
    lets richer residents (``service.daemon.ServiceDaemon``, the
    tenant-multiplexing ``tpu_serviced``) reuse the endpoint/serve
    plumbing with a wider RPC table."""

    def __init__(self, port: int = 0, bind_addr: str = "127.0.0.1",
                 table_factory=None,
                 secret: Optional[bytes] = None) -> None:
        self.ep = OobEndpoint(0, port, bind_addr, secret=secret)
        self._table = (table_factory or MetricsPubsubTable)(self.ep)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._table.serve_loop, args=(self._stop,),
            daemon=True,
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return self.ep.port

    def shutdown(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)
        self.ep.close()


class NameClient:
    """A job-independent pubsub client (any process, any job).

    Client ids are random high ints so clients from different jobs
    (which all call their own rank "1") cannot collide on the
    server's per-connection identity. The RPC protocol is the shared
    runtime/pubsub.py helper (same as WorkerAgent's in-job client).
    """

    def __init__(self, host: str, port: int,
                 secret: Optional[bytes] = None) -> None:
        self.client_id = random.randrange(1 << 20, 1 << 30)
        self.ep = OobEndpoint(self.client_id, secret=secret)
        self.ep.connect(0, host, port)
        self._lock = threading.Lock()

    def _rpc(self, tag: int, *fields: str,
             timeout_ms: int = 10_000) -> Tuple[bool, str]:
        from ..runtime.pubsub import pubsub_rpc

        return pubsub_rpc(self.ep, self._lock, self, tag, *fields,
                          timeout_ms=timeout_ms)

    def publish(self, service: str, port: str,
                ttl_s: Optional[float] = None) -> None:
        """Publish a name; ``ttl_s`` bounds its lifetime server-side
        (the entry is pruned by the serve loop after expiry — a crashy
        client's names cannot outlive it by more than the TTL). The
        TTL rides as an optional trailing frame field, so old servers
        simply ignore it."""
        fields = [service, port]
        if ttl_s is not None:
            fields.append(str(int(float(ttl_s) * 1000)))
        ok, msg = self._rpc(TAG_PUBLISH, *fields)
        if not ok:
            raise MPIError(ErrorCode.ERR_NAME,
                           f"publish '{service}': {msg}")

    def lookup(self, service: str, *, timeout_ms: int = 10_000) -> str:
        ok, value = self._rpc(TAG_LOOKUP, service, str(timeout_ms),
                              timeout_ms=timeout_ms)
        if not ok:
            raise MPIError(ErrorCode.ERR_NAME,
                           f"lookup '{service}': {value}")
        return value

    def unpublish(self, service: str) -> None:
        ok, _ = self._rpc(TAG_UNPUBLISH, service)
        if not ok:
            raise MPIError(ErrorCode.ERR_NAME,
                           f"unpublish '{service}': not published")

    def metrics(self, *, timeout_ms: int = 10_000) -> str:
        """Prometheus text exposition of the server process's pvars."""
        ok, text = self._rpc(TAG_METRICS, timeout_ms=timeout_ms)
        if not ok:
            raise MPIError(ErrorCode.ERR_NAME, f"metrics: {text}")
        return text

    def journal(self, *, timeout_ms: int = 10_000) -> dict:
        """The server process's obs rank-journal dump (spans + rank
        identity + clock offset) — tpu-doctor's remote collect path."""
        import json as _json

        ok, text = self._rpc(TAG_JOURNAL, timeout_ms=timeout_ms)
        if not ok:
            raise MPIError(ErrorCode.ERR_NAME, f"journal: {text}")
        return _json.loads(text)

    def series(self, *, timeout_ms: int = 10_000) -> dict:
        """The server process's continuous pvar time-series ring
        (``{"meta": ..., "points": [...]}``) — the live feed behind
        ``tpu_top`` and the doctor's series merge."""
        import json as _json

        ok, text = self._rpc(TAG_SERIES, timeout_ms=timeout_ms)
        if not ok:
            raise MPIError(ErrorCode.ERR_NAME, f"series: {text}")
        return _json.loads(text)

    def close(self) -> None:
        self.ep.close()


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu-server",
        description="Standalone cross-job name server (orte-server "
                    "analogue)")
    ap.add_argument("--port", type=int, default=0,
                    help="listen port (0 = ephemeral)")
    ap.add_argument("--bind", default="0.0.0.0",
                    help="listen address (default: all interfaces)")
    args = ap.parse_args(argv)
    srv = NameServer(args.port, args.bind)
    # advertise an address clients can actually dial: the outward
    # interface only when listening on all interfaces, else the bound
    # address itself
    host = (local_addr_toward("192.0.2.1") if args.bind == "0.0.0.0"
            else args.bind)
    print(f"tpu-server URI: {host}:{srv.port}", flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        srv.shutdown()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
