"""tpu-tune — measure collective algorithms and emit a dynamic rule
file.

The reference ships tuned's decision constants baked in and leaves the
operator to hand-write a dynamic rules file
(``ompi/mca/coll/tuned/coll_tuned_dynamic_file.c`` reads it; nothing
generates it). This tool closes that loop: it times EVERY legal
algorithm of each tunable collective at each sweep size on the actual
device mesh, picks the winner, and writes a
``coll/dynamic_rules.py``-format file whose comments carry the
measurements that justify each rule — load it with::

    --mca coll_tuned_use_dynamic_rules 1 \\
    --mca coll_tuned_dynamic_rules_filename FILE

Sizes in the emitted rules are each collective's own decision unit
(per-rank bytes, total bytes for allgather, per-destination block for
alltoall/scatter — the same units ``dynamic_rules.lookup`` is queried
with; see that module's table).

Usage::

    python -m ompi_release_tpu.tools.tpu_tune -o rules.conf \\
        [--sizes 1024,65536,1048576] [--repeats 5] [--ops allreduce,...]
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..mca import var as mca_var
from ..utils import output

_log = output.stream("tune")

#: op -> (runner(comm, x), decision-unit bytes for per-rank bytes b
#: and comm size n)
_OPS: Dict[str, Tuple] = {
    "allreduce": (lambda c, x: c.allreduce(x), lambda b, n: b),
    "bcast": (lambda c, x: c.bcast(x, root=0), lambda b, n: b),
    "reduce": (lambda c, x: c.reduce(x, root=0), lambda b, n: b),
    "allgather": (lambda c, x: c.allgather(x), lambda b, n: b * n),
    "alltoall": (lambda c, x: c.alltoall(x), lambda b, n: b // n),
    "gather": (lambda c, x: c.gather(x, root=0), lambda b, n: b),
    "scatter": (lambda c, x: c.scatter(x, root=0), lambda b, n: b // n),
}


def _algorithms(op: str) -> List[str]:
    from ..coll import dynamic_rules

    return [a for a in dynamic_rules.RULE_COLLECTIVES[op]
            if a != "auto"]


def _time_once(fn, comm, x) -> float:
    import jax

    t0 = time.perf_counter()
    jax.block_until_ready(fn(comm, x))
    return time.perf_counter() - t0


def measure(comm, ops: Sequence[str], sizes: Sequence[int],
            repeats: int = 5) -> Dict[str, List[Dict]]:
    """{op: [{size, unit_bytes, times: {alg: s}, winner}]} — per-rank
    buffer sizes in bytes; min-of-repeats timing (dispatch latency
    spikes are one-sided)."""
    if getattr(comm, "spans_processes", False):
        from ..utils.errors import ErrorCode, MPIError

        raise MPIError(
            ErrorCode.ERR_NOT_AVAILABLE,
            "tpu-tune measures the in-process compiled algorithms "
            "(driver-mode buffers); run it single-process on the "
            "target mesh shape — the rule file it emits applies to "
            "any job",
        )
    n = comm.size
    results: Dict[str, List[Dict]] = {}
    for op in ops:
        runner, unit_fn = _OPS[op]
        var = f"coll_tuned_{op}_algorithm"
        rows = []
        for size in sizes:
            elems = max(n, size // 4)
            elems = -(-elems // n) * n  # alltoall/scatter need % n == 0
            x = np.ones((n, elems), np.float32)
            times: Dict[str, float] = {}
            for alg in _algorithms(op):
                mca_var.set_value(var, alg)
                try:
                    _time_once(runner, comm, x)  # compile + warm
                    times[alg] = min(
                        _time_once(runner, comm, x)
                        for _ in range(repeats)
                    )
                except Exception as e:
                    # an algorithm an op/shape cannot run (e.g. ring
                    # without identity) is skipped, not fatal
                    _log.verbose(2, f"{op}/{alg}@{size}: {e}")
                finally:
                    mca_var.set_value(var, "auto")
            if not times:
                continue
            winner = min(times, key=times.get)
            rows.append({
                "size": size, "unit_bytes": unit_fn(elems * 4, n),
                "times": times, "winner": winner,
            })
        results[op] = rows
    return results


def _fixed_choice(comm, op: str, size: int) -> Optional[str]:
    """What the baked-in decision constants would pick (for the
    emitted differs-from-fixed annotations)."""
    from .. import ops as ops_mod
    from ..coll import components as coll_components

    n = comm.size
    elems = max(n, size // 4)
    elems = -(-elems // n) * n
    x = np.ones((n, elems), np.float32)
    mod = coll_components._TunedModule(comm)
    # the pickers consult dynamic rules BEFORE the fixed constants —
    # when re-tuning an already-tuned deployment the annotation must
    # still compare against the constants, not the old rule file
    prev = mca_var.get("coll_tuned_use_dynamic_rules", False)
    mca_var.set_value("coll_tuned_use_dynamic_rules", False)
    try:
        if op == "allreduce":
            return mod._pick_allreduce(x, ops_mod.SUM)
        if op == "bcast":
            return mod._pick_bcast(x)[0]
        if op == "reduce":
            return mod._pick_reduce(x, ops_mod.SUM)
        if op == "allgather":
            return mod._pick_allgather(x)
        if op == "alltoall":
            return mod._pick_alltoall(x)
    except Exception:
        pass
    finally:
        mca_var.set_value("coll_tuned_use_dynamic_rules", prev)
    return None


def emit(comm, results: Dict[str, List[Dict]]) -> str:
    """Render measurements as a dynamic rule file: ascending
    min_msg_bytes lines per op (LAST match wins, so each line is the
    threshold where the winner changes), every rule justified by its
    measurements in a comment."""
    import jax

    dev = jax.devices()[0]
    lines = [
        "# generated by tpu-tune — measured algorithm selection",
        f"# mesh: {len(jax.devices())} x {dev.device_kind} "
        f"({jax.default_backend()}), comm size {comm.size}",
        "# load with: --mca coll_tuned_use_dynamic_rules 1 "
        "--mca coll_tuned_dynamic_rules_filename <this file>",
        "#",
        "# collective  min_comm_size  min_msg_bytes  algorithm",
    ]
    for op, rows in results.items():
        if not rows:
            continue
        lines.append("")
        prev = None
        for i, row in enumerate(rows):
            t = ", ".join(f"{a}={s * 1e6:.0f}us"
                          for a, s in sorted(row["times"].items(),
                                             key=lambda kv: kv[1]))
            fixed = _fixed_choice(comm, op, row["size"])
            note = (f"  [differs from fixed constants: {fixed}]"
                    if fixed is not None
                    and fixed != row["winner"] else "")
            lines.append(f"# {op} @ {row['size']}B/rank: {t}{note}")
            if row["winner"] != prev:
                thresh = 0 if i == 0 else row["unit_bytes"]
                lines.append(
                    f"{op}  0  {thresh}  {row['winner']}"
                )
                prev = row["winner"]
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu-tune",
        description="Measure collective algorithms on this mesh and "
                    "emit a dynamic rules file",
    )
    ap.add_argument("-o", "--output", required=True)
    ap.add_argument("--sizes", default="1024,65536,1048576,16777216",
                    help="comma-separated per-rank buffer sizes (bytes)")
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--ops", default="allreduce,bcast,reduce,"
                                     "allgather,alltoall")
    args = ap.parse_args(argv)

    import ompi_release_tpu as mpi

    comm = mpi.init()
    # ascending is load-bearing: emit() writes threshold lines in row
    # order and dynamic_rules takes the LAST match
    sizes = sorted(int(s) for s in args.sizes.split(",") if s)
    ops = [o.strip() for o in args.ops.split(",") if o.strip()]
    results = measure(comm, ops, sizes, repeats=args.repeats)
    text = emit(comm, results)
    with open(args.output, "w") as f:
        f.write(text)
    # validate what we just wrote parses (a typo'd generator must not
    # hand the operator a file that fails at job start)
    from ..coll import dynamic_rules

    dynamic_rules.load_rules(args.output)
    n_rules = sum(1 for ln in text.splitlines()
                  if ln and not ln.startswith("#"))
    print(f"tpu-tune: wrote {n_rules} rule(s) to {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
