"""tpu-tune — measure collective algorithms and emit a dynamic rule
file.

The reference ships tuned's decision constants baked in and leaves the
operator to hand-write a dynamic rules file
(``ompi/mca/coll/tuned/coll_tuned_dynamic_file.c`` reads it; nothing
generates it). This tool closes that loop: it times EVERY legal
algorithm of each tunable collective at each sweep size on the actual
device mesh, picks the winner, and writes a
``coll/dynamic_rules.py``-format file whose comments carry the
measurements that justify each rule — load it with::

    --mca coll_tuned_use_dynamic_rules 1 \\
    --mca coll_tuned_dynamic_rules_filename FILE

Sizes in the emitted rules are each collective's own decision unit
(per-rank bytes, total bytes for allgather, per-destination block for
alltoall/scatter — the same units ``dynamic_rules.lookup`` is queried
with; see that module's table).

Timing protocol: the first call of every (algorithm, size) compiles
the program AND primes the driver's plan cache; the measured repeats
that follow therefore never include compile time. The compile cost is
still reported — as a separate ``compile:`` field in the emitted
rule-file comments — because an operator choosing between algorithms
with similar steady-state times may care which one stalls the first
iteration longer.

``--segsizes`` additionally sweeps the pipeline segment size
(``coll/pipeline.py``) for rows whose winner is pipeline-capable (ring
allreduce, binomial bcast/reduce) and emits the winning value as the
rule file's fifth ``segsize`` column (0 pins pipelining off when
monolithic won), with the per-segsize measurements in a comment.

Usage::

    python -m ompi_release_tpu.tools.tpu_tune -o rules.conf \\
        [--sizes 1024,65536,1048576] [--repeats 5] [--ops allreduce,...] \\
        [--segsizes 65536,262144,1048576]
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..mca import var as mca_var
from ..tuning import db as tuning_db
from ..utils import output

_log = output.stream("tune")


def measured_fingerprint(hier_procs: int = 0,
                         hosts_per: int = 0) -> tuning_db.Fingerprint:
    """The topology fingerprint a tpu-tune run actually measured: the
    hier sweep's process/host layout when one ran (that is what the
    hier_* rules are valid for), else the single-process in-process
    mesh (:data:`..tuning.db.LOCAL`)."""
    if hier_procs >= 2:
        hp = int(hosts_per) if hosts_per and hosts_per > 0 \
            else int(hier_procs)
        hosts = -(-int(hier_procs) // hp)
        return tuning_db.Fingerprint(
            hosts=hosts, procs_per_host=hp if hier_procs % hp == 0
            else 0,
            link_classes=("shm", "dcn") if hosts > 1 else ("shm",),
            P=int(hier_procs))
    return tuning_db.LOCAL

#: op -> (runner(comm, x), decision-unit bytes for per-rank bytes b
#: and comm size n)
_OPS: Dict[str, Tuple] = {
    "allreduce": (lambda c, x: c.allreduce(x), lambda b, n: b),
    "bcast": (lambda c, x: c.bcast(x, root=0), lambda b, n: b),
    "reduce": (lambda c, x: c.reduce(x, root=0), lambda b, n: b),
    "allgather": (lambda c, x: c.allgather(x), lambda b, n: b * n),
    "alltoall": (lambda c, x: c.alltoall(x), lambda b, n: b // n),
    "gather": (lambda c, x: c.gather(x, root=0), lambda b, n: b),
    "scatter": (lambda c, x: c.scatter(x, root=0), lambda b, n: b // n),
}


def _algorithms(op: str) -> List[str]:
    from ..coll import dynamic_rules

    return [a for a in dynamic_rules.RULE_COLLECTIVES[op]
            if a != "auto"]


def _time_once(fn, comm, x) -> float:
    import jax

    t0 = time.perf_counter()
    jax.block_until_ready(fn(comm, x))
    return time.perf_counter() - t0


def _tuned_dup(comm):
    """A dup whose c_coll table is served by the tuned component:
    ``coll_tuned_<op>_algorithm`` forcing and rule files only act
    through the tuned pickers, while a default comm's chain is led by
    xla (priority 100) — measuring there would time xla's one program
    under every forced name and crown a noise winner."""
    mca_var.set_value("coll", "tuned")
    try:
        return comm.dup(name="tune_tuned")
    finally:
        mca_var.VARS.unset("coll")


def sweep_segsizes(comm, op: str, alg: str, x,
                   segsizes: Sequence[int], repeats: int = 5
                   ) -> Dict[int, float]:
    """Time ``alg`` under each pipeline segment size (plus 0 = the
    monolithic baseline); returns {segsize: best_seconds}. The cvar
    under sweep is ``coll_pipeline_segsize`` — exactly what the
    emitted rule's ``segsize`` column will set per matching call.

    Dynamic rules are pinned OFF for the sweep: a live rules file's
    segsize column outranks the swept cvar (pick_segsize: rules >
    cvar), which would make every sweep point measure the same
    configuration when re-tuning an already-tuned deployment.
    Segment sizes >= the per-rank message are skipped — they compile
    the identical monolithic program as 0 and would only let timer
    noise crown a never-exercised value."""
    runner, _ = _OPS[op]
    var = f"coll_tuned_{op}_algorithm"
    msg_bytes = int(x[0].size) * int(x.dtype.itemsize)
    out: Dict[int, float] = {}
    prev_rules = mca_var.get("coll_tuned_use_dynamic_rules", False)
    prev_seg = mca_var.get("coll_pipeline_segsize", 1 << 20)
    prev_alg = mca_var.get(var, "auto")
    mca_var.set_value("coll_tuned_use_dynamic_rules", False)
    mca_var.set_value(var, alg)
    try:
        for seg in [0] + [s for s in segsizes if 0 < s < msg_bytes]:
            mca_var.set_value("coll_pipeline_segsize", seg)
            try:
                _time_once(runner, comm, x)  # compile + prime plan cache
                out[seg] = min(
                    _time_once(runner, comm, x) for _ in range(repeats)
                )
            except Exception as e:
                _log.verbose(2, f"{op}/{alg} segsize {seg}: {e}")
    finally:
        # restore (not clobber): the operator may have forced their
        # own algorithm/segsize before running tpu-tune
        mca_var.set_value("coll_pipeline_segsize", prev_seg)
        mca_var.set_value(var, prev_alg)
        mca_var.set_value("coll_tuned_use_dynamic_rules", prev_rules)
    return out


def sweep_wire_segsizes(segsizes: Sequence[int],
                        size_bytes: int = 16 << 20,
                        repeats: int = 3) -> Dict[int, float]:
    """Time ONE cross-process-shaped staged transfer through a real
    loopback OOB endpoint pair at each ``wire_pipeline_segsize`` (0 =
    the legacy monolithic ``tobytes()`` framing); returns
    {segsize: best_seconds}. This sweeps the cvar the wire router's
    DCN staging path reads (``DcnBtl.pipeline_segsize``), so the
    emitted recommendation measures the exact send+reassemble code a
    ``tpurun`` job will run — sockets, framing, CRC and all."""
    from ..btl.components import DcnBtl
    from ..native import OobEndpoint

    a, b = OobEndpoint(0), OobEndpoint(1)
    out: Dict[int, float] = {}
    prev = mca_var.get("wire_pipeline_segsize", 1 << 20)
    try:
        b.connect(0, "127.0.0.1", a.port)
        m = DcnBtl()
        x = np.ones(max(1, size_bytes // 4), np.float32)
        for seg in [0] + sorted({int(s) for s in segsizes if s > 0}):
            mca_var.set_value("wire_pipeline_segsize", seg)
            try:
                best = None
                for _ in range(repeats):
                    t0 = time.perf_counter()
                    m.send_staged(b, 0, 151, x)
                    got = np.asarray(m.recv_staged(a, 151))
                    dt = time.perf_counter() - t0
                    best = dt if best is None else min(best, dt)
                if got.shape != x.shape or got[0] != x[0]:
                    continue  # never crown a corrupting config
                out[seg] = best
            except Exception as e:
                _log.verbose(2, f"wire segsize {seg}: {e}")
    finally:
        mca_var.set_value("wire_pipeline_segsize", prev)
        a.close()
        b.close()
    return out


def emit_wire_rules(seg_times: Dict[int, float],
                    size_bytes: int = 16 << 20) -> str:
    """Rule-comment block for the wire sweep (the same measured-
    justification treatment as the coll segsize column): every point's
    time, plus the winning ``--mca wire_pipeline_segsize`` the operator
    should launch with. Wire cvars are job-wide, not per-collective, so
    this block is advisory comments rather than rule lines — the
    loader ignores it."""
    if not seg_times:
        return ""
    pts = ", ".join(
        f"{('off' if k == 0 else k)}={v * 1e3:.1f}ms"
        for k, v in sorted(seg_times.items(), key=lambda kv: kv[1]))
    best = min(seg_times, key=seg_times.get)
    lines = [
        "",
        f"# wire pipeline sweep ({size_bytes >> 20} MiB staged "
        f"loopback): {pts}",
        f"# recommended: --mca wire_pipeline_segsize {best}"
        + ("  (legacy monolithic framing won)" if best == 0 else ""),
    ]
    return "\n".join(lines)


#: worker app for the hier sweep: a REAL loopback tpurun job (one
#: device per process, so comm size == process count) that times every
#: legal INTER schedule of each spanning collective under the
#: ``hier_inter_algorithm`` forcing cvar. Process 0 writes the rows to
#: OMPITPU_HIER_TUNE_OUT.
_HIER_TUNE_APP = r'''
import json, os, sys, time
sys.path.insert(0, %(repo)r)
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1"
                           ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# --hier-hosts-per: group processes into fake hosts of that size so
# the sweep times the topology-aware schedules (multiring/torus2d)
# over a real shm/DCN split instead of one flat host. NODE_ID is
# 1-BASED (tpurun): subtract 1 or the groups come out ragged and
# torus_grid() would silently degrade every torus leg to the flat
# ring while the sweep labels the timings torus2d.
_hp = int(os.environ.get("OMPITPU_HIER_TUNE_HOSTS_PER", "0"))
if _hp > 0:
    os.environ["OMPITPU_HOST_ID"] = (
        "tunehost-%%d"
        %% ((int(os.environ["OMPITPU_NODE_ID"]) - 1) // _hp))
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import ompi_release_tpu as mpi
from ompi_release_tpu.coll import hier_schedules
from ompi_release_tpu.mca import var as mca_var
from ompi_release_tpu.runtime.runtime import Runtime

OPS = json.loads(os.environ["OMPITPU_HIER_TUNE_OPS"])
SIZES = json.loads(os.environ["OMPITPU_HIER_TUNE_SIZES"])
REPEATS = int(os.environ.get("OMPITPU_HIER_TUNE_REPEATS", "3"))
world = mpi.init()
rt = Runtime.current()
me = rt.bootstrap["process_index"]
n = world.size

def runner(op, x):
    if op == "allreduce":
        return world.allreduce(x)
    if op == "bcast":
        return world.bcast(x, root=0)
    if op == "reduce":
        return world.reduce(x, root=0)
    if op == "allgather":
        return world.allgather(x)
    if op == "alltoall":
        return world.alltoall(x)
    if op == "gather":
        return world.gather(x, root=0)
    if op == "scatter":
        return world.scatter(x, root=0)
    raise ValueError(op)

def unit_bytes(op, elems):
    # the hier decision units pick() documents
    if op == "allgather":
        return elems * 4 * n
    if op == "alltoall":
        return (elems // n) * 4
    if op == "scatter":
        return 0  # size-blind decision (root-only buffer)
    return elems * 4

results = {}
for op in OPS:
    rows = []
    for size in SIZES:
        elems = max(n, size // 4)
        elems = -(-elems // n) * n
        x = np.ones((1, elems), np.float32)
        times = {}
        for alg in hier_schedules.ALGORITHMS[op]:
            if alg == "auto":
                continue
            mca_var.set_value("hier_inter_algorithm", alg)
            try:
                world.barrier()
                runner(op, x)  # warm the shadow-comm programs
                best = None
                for _ in range(REPEATS):
                    world.barrier()
                    t0 = time.perf_counter()
                    jax.block_until_ready(runner(op, x))
                    dt = time.perf_counter() - t0
                    best = dt if best is None else min(best, dt)
                times[alg] = best
            except Exception as e:
                if me == 0:
                    print("hier-tune skip %%s/%%s@%%d: %%s"
                          %% (op, alg, size, e), file=sys.stderr)
            finally:
                mca_var.VARS.unset("hier_inter_algorithm")
        if times:
            rows.append({"size": size,
                         "unit_bytes": unit_bytes(op, elems),
                         "times": times,
                         "winner": min(times, key=times.get)})
    results[op] = rows
world.barrier()
if me == 0:
    # witness that the topo family actually ran (a ragged fake-host
    # grouping would silently degrade torus2d to the flat ring and
    # this would read 0 — the hosts-per sweep test pins it > 0)
    from ompi_release_tpu.mca import pvar as _pvar
    _tr = _pvar.PVARS.lookup("hier_topo_schedule_runs")
    with open(os.environ["OMPITPU_LOOPBACK_OUT"], "w") as f:
        json.dump({"nprocs": n, "results": results,
                   "topo_runs": float(_tr.read()) if _tr else 0.0}, f)
mpi.finalize()
'''


#: worker app for the tree-bucket sweep: a REAL loopback tpurun job
#: driving parallel/tree.TreeSync whole-tree allreduce passes over a
#: trainer-shaped mixed-size gradient tree at each candidate bucket
#: capacity (0 = the per-leaf path). Process 0 writes the rows to
#: OMPITPU_LOOPBACK_OUT.
_TREE_TUNE_APP = r'''
import json, os, sys, time
sys.path.insert(0, %(repo)r)
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1"
                           ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
# distinct shm identity per worker: the pass rides the DCN staged
# path, so the sweep times real wire traffic, not a memcpy
os.environ["OMPITPU_HOST_ID"] = (
    "treetune-" + os.environ["OMPITPU_NODE_ID"])
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import ompi_release_tpu as mpi
from ompi_release_tpu.parallel import tree as tree_mod
from ompi_release_tpu.runtime.runtime import Runtime

BUCKETS = json.loads(os.environ["OMPITPU_TREE_TUNE_BUCKETS"])
REPEATS = int(os.environ.get("OMPITPU_TREE_TUNE_REPEATS", "3"))
world = mpi.init()
rt = Runtime.current()
me = rt.bootstrap["process_index"]
ln = len(world.local_comm_ranks)

# a trainer-shaped tree: many small leaves (biases/norms), a medium
# band (projections), a couple of large ones (embeddings)
rng = np.random.RandomState(7)
grads = {}
for k in range(16):
    grads["small%%02d" %% k] = rng.randn(ln, 1024).astype(np.float32)
for k in range(6):
    grads["mid%%d" %% k] = rng.randn(ln, 16384).astype(np.float32)
for k in range(2):
    grads["big%%d" %% k] = rng.randn(ln, 131072).astype(np.float32)
metas = [(g.shape, g.dtype) for g in
         (grads[k] for k in sorted(grads))]
total = sum(g.nbytes for g in grads.values())

rows = []
for b in BUCKETS:
    sync = tree_mod.TreeSync(world, mean=False, bucket_bytes=b)
    world.barrier()
    sync.issue(grads).wait()  # warm programs + plan cache + channels
    best = None
    for _ in range(REPEATS):
        world.barrier()
        t0 = time.perf_counter()
        sync.issue(grads).wait()
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    rows.append({"bucket": b, "seconds": best,
                 "transfers": tree_mod.plan_from_meta(
                     metas, b).n_transfers()})
world.barrier()
if me == 0:
    with open(os.environ["OMPITPU_LOOPBACK_OUT"], "w") as f:
        json.dump({"nprocs": world.size, "tree_bytes": int(total),
                   "leaves": len(grads), "rows": rows}, f)
mpi.finalize()
'''


def sweep_tree_buckets(nprocs: int, buckets: Sequence[int],
                       repeats: int = 3,
                       timeout_s: int = 600) -> Optional[Dict]:
    """Time the planned whole-tree allreduce pass
    (``parallel/tree.TreeSync``) at each bucket capacity through a
    real ``nprocs``-process loopback ``tpurun`` job — the bucket size
    IS the tree planner's fusion threshold, so this sweep measures the
    fewer-collectives vs bigger-staging tradeoff on the exact wire
    path a job runs. ``0`` is always included (the per-leaf path the
    rules can pin with ``per_leaf``)."""
    import json as _json
    import os as _os

    from ..tools.tpurun import run_loopback_app

    cand = sorted({int(b) for b in buckets if int(b) > 0})
    out = run_loopback_app(
        nprocs,
        _TREE_TUNE_APP % {
            "repo": _os.path.dirname(_os.path.dirname(
                _os.path.dirname(_os.path.abspath(__file__))))},
        {"OMPITPU_TREE_TUNE_BUCKETS": _json.dumps([0] + cand),
         "OMPITPU_TREE_TUNE_REPEATS": str(repeats)},
        "tree_tune.json", timeout_s=timeout_s)
    if out is None:
        _log.verbose(1, "tree-bucket sweep job failed")
    return out


def emit_tree_rules(sweep: Dict) -> str:
    """Render a tree-bucket sweep as a ``tree_buckets`` rule line the
    planner auto-selects (``parallel/tree.resolve_bucket_bytes``):
    algorithm ``fused`` with the winning capacity in the 5th column,
    or ``per_leaf`` when bucketing lost. Measurements (time and
    transfer count per candidate) ride in the justification comment,
    the same treatment as every other emitted rule."""
    if not sweep or not sweep.get("rows"):
        return ""
    rows = sweep["rows"]
    pts = ", ".join(
        f"{('per_leaf' if r['bucket'] == 0 else r['bucket'])}="
        f"{r['seconds'] * 1e3:.1f}ms/{r['transfers']}xfers"
        for r in sorted(rows, key=lambda r: r["seconds"]))
    best = min(rows, key=lambda r: r["seconds"])
    lines = [
        "",
        f"# tree_buckets: planned whole-tree pass, measured on a "
        f"{sweep['nprocs']}-process loopback job "
        f"({sweep['leaves']}-leaf {sweep['tree_bytes'] >> 10} KiB "
        f"tree, tpu-tune --tree-buckets); min_msg_bytes is TOTAL "
        f"tree bytes",
        f"#   {pts}",
    ]
    if best["bucket"] == 0:
        lines.append("tree_buckets  0  0  per_leaf")
    else:
        lines.append(f"tree_buckets  0  0  fused  {best['bucket']}")
    return "\n".join(lines) + "\n"


def sweep_hier(nprocs: int, ops: Sequence[str], sizes: Sequence[int],
               repeats: int = 3, timeout_s: int = 600,
               hosts_per: int = 0) -> Optional[Dict]:
    """Measure the spanning collectives' INTER schedules through a
    real ``nprocs``-process loopback ``tpurun`` job (the schedules
    only exist across process boundaries — a single-process sweep
    cannot time them). The menu comes from
    ``hier_schedules.ALGORITHMS``, so the topology-aware variants
    (multiring/torus2d) are swept too; ``hosts_per`` > 0 groups the
    processes into fake hosts of that size (distinct shm identities
    per group) so those variants see a real shm/DCN split. Returns
    ``{"nprocs", "hosts_per", "results"}`` in :func:`measure`'s row
    shape, or None if the job failed."""
    import json as _json
    import os as _os

    from ..tools.tpurun import run_loopback_app

    out = run_loopback_app(
        nprocs,
        _HIER_TUNE_APP % {
            "repo": _os.path.dirname(_os.path.dirname(
                _os.path.dirname(_os.path.abspath(__file__))))},
        {"OMPITPU_HIER_TUNE_OPS": _json.dumps(list(ops)),
         "OMPITPU_HIER_TUNE_SIZES": _json.dumps(
             sorted(int(s) for s in sizes)),
         "OMPITPU_HIER_TUNE_REPEATS": str(repeats),
         "OMPITPU_HIER_TUNE_HOSTS_PER": str(int(hosts_per))},
        "hier_tune.json", timeout_s=timeout_s)
    if out is None:
        _log.verbose(1, "hier sweep job failed")
    elif isinstance(out, dict):
        out.setdefault("hosts_per", int(hosts_per))
    return out


def emit_hier_rules(sweep: Dict) -> str:
    """Render a hier sweep as ``hier_<op>`` rule lines (same
    ascending-threshold last-match-wins shape as :func:`emit`, and the
    same min_comm_size=0 convention: the emitted rules apply at every
    process count, since one sweep measures one). The measured process
    count is recorded in the header comment — re-run at another
    ``--hier-procs`` and hand-scope the lines if your jobs vary."""
    if not sweep:
        return ""
    nprocs = int(sweep["nprocs"])
    lines = [
        "",
        f"# hier_* inter-process schedules, measured on a {nprocs}-"
        "process loopback job (tpu-tune --hier-procs); min_comm_size "
        "is the PROCESS count",
    ]
    for op, rows in sweep["results"].items():
        if not rows:
            continue
        prev = None
        for i, row in enumerate(rows):
            t = ", ".join(f"{a}={s * 1e6:.0f}us"
                          for a, s in sorted(row["times"].items(),
                                             key=lambda kv: kv[1]))
            lines.append(f"# hier_{op} @ {row['size']}B: {t}")
            if row["winner"] != prev:
                thresh = 0 if i == 0 else row["unit_bytes"]
                lines.append(
                    f"hier_{op}  0  {thresh}  {row['winner']}")
                prev = row["winner"]
    return "\n".join(lines) + "\n"


def measure(comm, ops: Sequence[str], sizes: Sequence[int],
            repeats: int = 5, *, segsizes: Optional[Sequence[int]] = None,
            algs: Optional[Sequence[str]] = None) -> Dict[str, List[Dict]]:
    """{op: [{size, unit_bytes, times: {alg: s}, compile: {alg: s},
    winner[, segsize, segsize_times]}]} — per-rank buffer sizes in
    bytes; min-of-repeats timing (dispatch latency spikes are
    one-sided). The first call per algorithm compiles AND primes the
    driver plan cache, so the measured repeats exclude compile time;
    the compile cost is reported separately in ``compile``. With
    ``segsizes``, pipeline-capable winners get a segment-size sweep
    (``segsize`` = best, 0 = monolithic won). ``algs`` restricts the
    algorithm menu (default: every legal algorithm of the op)."""
    if getattr(comm, "spans_processes", False):
        from ..utils.errors import ErrorCode, MPIError

        raise MPIError(
            ErrorCode.ERR_NOT_AVAILABLE,
            "tpu-tune measures the in-process compiled algorithms "
            "(driver-mode buffers); run it single-process on the "
            "target mesh shape — the rule file it emits applies to "
            "any job",
        )
    from ..coll import pipeline

    n = comm.size
    tuned = _tuned_dup(comm)
    # measure from scratch: an active rules file (a previous tuning
    # run) must not steer this one — the algorithm is pinned by the
    # forced cvar, and its segsize column would silently pipeline the
    # alg-phase timings (pick_segsize: rules > cvar). The ambient
    # coll_pipeline_segsize is pinned to 0 too: the alg phase times
    # MONOLITHIC algorithms (the segsize sweep's own 0-baseline), and
    # pipelining is explored only by the explicit sweep
    prev_rules = mca_var.get("coll_tuned_use_dynamic_rules", False)
    prev_seg = mca_var.get("coll_pipeline_segsize", 1 << 20)
    mca_var.set_value("coll_tuned_use_dynamic_rules", False)
    mca_var.set_value("coll_pipeline_segsize", 0)
    try:
        results: Dict[str, List[Dict]] = {}
        for op in ops:
            runner, unit_fn = _OPS[op]
            var = f"coll_tuned_{op}_algorithm"
            # restore the OPERATOR's forced value after each timing,
            # not the literal 'auto' — tpu-tune must not clobber a
            # deployment's pinned algorithm (ADVICE r5)
            prev_alg = mca_var.get(var, "auto")
            rows = []
            for size in sizes:
                elems = max(n, size // 4)
                elems = -(-elems // n) * n  # alltoall/scatter: % n == 0
                x = np.ones((n, elems), np.float32)
                times: Dict[str, float] = {}
                compiles: Dict[str, float] = {}
                for alg in (algs or _algorithms(op)):
                    mca_var.set_value(var, alg)
                    try:
                        # compile + warm: this first call also primes
                        # the driver plan cache, so the repeats below
                        # never pay compile time
                        t_first = _time_once(runner, tuned, x)
                        times[alg] = min(
                            _time_once(runner, tuned, x)
                            for _ in range(repeats)
                        )
                        compiles[alg] = max(0.0, t_first - times[alg])
                    except Exception as e:
                        # an algorithm an op/shape cannot run (e.g.
                        # ring without identity) is skipped, not fatal
                        _log.verbose(2, f"{op}/{alg}@{size}: {e}")
                    finally:
                        mca_var.set_value(var, prev_alg)
                if not times:
                    continue
                winner = min(times, key=times.get)
                row = {
                    "size": size, "unit_bytes": unit_fn(elems * 4, n),
                    "times": times, "compile": compiles, "winner": winner,
                }
                pipe_alg = pipeline.PIPELINE_CAPABLE.get(op)
                pos_segs = [s for s in (segsizes or ()) if s > 0]
                if (pos_segs and winner == pipe_alg
                        and size > min(pos_segs)):
                    seg_times = sweep_segsizes(
                        tuned, op, winner, x, segsizes, repeats
                    )
                    if seg_times:
                        row["segsize_times"] = seg_times
                        row["segsize"] = min(seg_times, key=seg_times.get)
                rows.append(row)
            results[op] = rows
        return results
    finally:
        mca_var.set_value("coll_tuned_use_dynamic_rules", prev_rules)
        mca_var.set_value("coll_pipeline_segsize", prev_seg)
        tuned.free()


def _fixed_choice(comm, op: str, size: int) -> Optional[str]:
    """What the baked-in decision constants would pick (for the
    emitted differs-from-fixed annotations)."""
    from .. import ops as ops_mod
    from ..coll import components as coll_components

    n = comm.size
    elems = max(n, size // 4)
    elems = -(-elems // n) * n
    x = np.ones((n, elems), np.float32)
    mod = coll_components._TunedModule(comm)
    # the pickers consult dynamic rules BEFORE the fixed constants —
    # when re-tuning an already-tuned deployment the annotation must
    # still compare against the constants, not the old rule file
    prev = mca_var.get("coll_tuned_use_dynamic_rules", False)
    mca_var.set_value("coll_tuned_use_dynamic_rules", False)
    try:
        if op == "allreduce":
            return mod._pick_allreduce(x, ops_mod.SUM)
        if op == "bcast":
            return mod._pick_bcast(x)[0]
        if op == "reduce":
            return mod._pick_reduce(x, ops_mod.SUM)
        if op == "allgather":
            return mod._pick_allgather(x)
        if op == "alltoall":
            return mod._pick_alltoall(x)
    except Exception:
        pass
    finally:
        mca_var.set_value("coll_tuned_use_dynamic_rules", prev)
    return None


def emit(comm, results: Dict[str, List[Dict]]) -> str:
    """Render measurements as a dynamic rule file: ascending
    min_msg_bytes lines per op (LAST match wins, so each line is the
    threshold where the winner changes), every rule justified by its
    measurements in a comment."""
    import jax

    dev = jax.devices()[0]
    lines = [
        "# generated by tpu-tune — measured algorithm selection",
        f"# mesh: {len(jax.devices())} x {dev.device_kind} "
        f"({jax.default_backend()}), comm size {comm.size}",
        "# load with: --mca coll_tuned_use_dynamic_rules 1 "
        "--mca coll_tuned_dynamic_rules_filename <this file>",
        "#",
        "# collective  min_comm_size  min_msg_bytes  algorithm  [segsize]",
    ]
    for op, rows in results.items():
        if not rows:
            continue
        lines.append("")
        prev = None
        for i, row in enumerate(rows):
            t = ", ".join(f"{a}={s * 1e6:.0f}us"
                          for a, s in sorted(row["times"].items(),
                                             key=lambda kv: kv[1]))
            fixed = _fixed_choice(comm, op, row["size"])
            note = (f"  [differs from fixed constants: {fixed}]"
                    if fixed is not None
                    and fixed != row["winner"] else "")
            lines.append(f"# {op} @ {row['size']}B/rank: {t}{note}")
            if row.get("compile"):
                c = ", ".join(
                    f"{a}={s * 1e3:.0f}ms"
                    for a, s in sorted(row["compile"].items(),
                                       key=lambda kv: kv[1]))
                lines.append(f"#   compile: {c}")
            if row.get("segsize_times"):
                st = ", ".join(
                    f"{('off' if k == 0 else k)}={v * 1e6:.0f}us"
                    for k, v in sorted(row["segsize_times"].items(),
                                       key=lambda kv: kv[1]))
                lines.append(
                    f"#   segsize sweep ({row['winner']}): {st}"
                )
            pick = (row["winner"], row.get("segsize"))
            if pick != prev:
                thresh = 0 if i == 0 else row["unit_bytes"]
                seg_col = ("" if row.get("segsize") is None
                           else f"  {row['segsize']}")
                lines.append(
                    f"{op}  0  {thresh}  {row['winner']}{seg_col}"
                )
                prev = pick
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu-tune",
        description="Measure collective algorithms on this mesh and "
                    "emit a dynamic rules file",
    )
    ap.add_argument("-o", "--output", required=True)
    ap.add_argument("--sizes", default="1024,65536,1048576,16777216",
                    help="comma-separated per-rank buffer sizes (bytes)")
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--ops", default="allreduce,bcast,reduce,"
                                     "allgather,alltoall")
    ap.add_argument("--segsizes", default="65536,262144,1048576",
                    help="comma-separated pipeline segment sizes to "
                         "sweep for pipeline-capable winners (emits "
                         "the segsize rule column); empty disables")
    ap.add_argument("--wire-segsizes", default="",
                    help="comma-separated wire_pipeline_segsize values "
                         "to sweep through a loopback OOB staged "
                         "transfer (emits a recommendation comment); "
                         "empty disables")
    ap.add_argument("--hier-procs", type=int, default=0,
                    help="process count for the spanning-collective "
                         "INTER schedule sweep (a real loopback tpurun "
                         "job; emits hier_* rule lines); 0 disables")
    ap.add_argument("--hier-ops", default="allreduce,bcast,reduce,"
                                          "allgather,alltoall",
                    help="spanning collectives the hier sweep times")
    ap.add_argument("--hier-sizes", default="1024,65536,1048576",
                    help="per-rank buffer sizes (bytes) for the hier "
                         "sweep")
    ap.add_argument("--hier-hosts-per", type=int, default=0,
                    help="group the hier sweep's processes into fake "
                         "hosts of this size (distinct shm identities) "
                         "so the topology-aware schedules (multiring/"
                         "torus2d) measure over a real shm/DCN split; "
                         "0 keeps the machine's own host identity")
    ap.add_argument("--db", default="",
                    help="register the emitted rules file into this "
                         "tuning-database directory (a new versioned, "
                         "fingerprint-stamped entry jobs auto-select "
                         "via --mca coll_tuning_db_dir); empty "
                         "disables")
    ap.add_argument("--tree-buckets", default="",
                    help="comma-separated bucket capacities (bytes) to "
                         "sweep for the planned whole-tree pass "
                         "(parallel/tree) through a loopback tpurun "
                         "job; emits a tree_buckets rule line the "
                         "planner auto-selects; empty disables")
    ap.add_argument("--tree-procs", type=int, default=3,
                    help="process count for the tree-bucket sweep job")
    args = ap.parse_args(argv)

    import ompi_release_tpu as mpi

    comm = mpi.init()
    # ascending is load-bearing: emit() writes threshold lines in row
    # order and dynamic_rules takes the LAST match
    sizes = sorted(int(s) for s in args.sizes.split(",") if s)
    ops = [o.strip() for o in args.ops.split(",") if o.strip()]
    segsizes = sorted(int(s) for s in args.segsizes.split(",") if s)
    results = measure(comm, ops, sizes, repeats=args.repeats,
                      segsizes=segsizes or None)
    text = emit(comm, results)
    wire_segs = sorted(int(s) for s in args.wire_segsizes.split(",")
                       if s.strip())
    if wire_segs:
        text += emit_wire_rules(sweep_wire_segsizes(wire_segs)) + "\n"
    if args.hier_procs >= 2:
        hier_ops = [o.strip() for o in args.hier_ops.split(",")
                    if o.strip()]
        hier_sizes = sorted(int(s) for s in args.hier_sizes.split(",")
                            if s.strip())
        sweep = sweep_hier(args.hier_procs, hier_ops, hier_sizes,
                           repeats=args.repeats,
                           hosts_per=args.hier_hosts_per)
        if sweep:
            text += emit_hier_rules(sweep)
    tree_buckets = [int(s) for s in args.tree_buckets.split(",")
                    if s.strip()]
    if tree_buckets:
        tsweep = sweep_tree_buckets(args.tree_procs, tree_buckets,
                                    repeats=args.repeats)
        if tsweep:
            text += emit_tree_rules(tsweep)
    # every emitted file is stamped with the MEASURED topology
    # fingerprint — the tuning-db selection key, and honest provenance
    # even for hand-pointed files
    fp = measured_fingerprint(args.hier_procs, args.hier_hosts_per)
    text = tuning_db.stamp(text, fp)
    with open(args.output, "w") as f:
        f.write(text)
    # validate what we just wrote parses (a typo'd generator must not
    # hand the operator a file that fails at job start)
    from ..coll import dynamic_rules

    dynamic_rules.load_rules(args.output)
    n_rules = sum(1 for ln in text.splitlines()
                  if ln and not ln.startswith("#"))
    print(f"tpu-tune: wrote {n_rules} rule(s) to {args.output} "
          f"[fingerprint {fp.canon()}]")
    if args.db:
        path = tuning_db.TuningDb(args.db).register(text, fp)
        print(f"tpu-tune: registered into tuning db: {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
