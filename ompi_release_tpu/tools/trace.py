"""Tracing interposition — the PMPI / libompitrace analogue.

The reference lets tracers interpose on every MPI call without
relinking via weak PMPI symbols (``ompi/mpi/c/init.c:32``) and ships
``libompitrace`` as a minimal example. The same property here: wrap a
communicator in :func:`wrap` and every collective/p2p call is recorded
(name, wall time, payload bytes) to an event list, optional JSONL
sink, and per-operation timing pvars — without touching the wrapped
object or the call sites. ``profiler_trace`` bridges to the JAX
profiler (XPlane) for device-side timelines.
"""

from __future__ import annotations

import contextlib
import json
import time
from typing import Any, Callable, Dict, List, Optional

from .. import obs as _obs
from ..mca import pvar

#: communicator methods interposed (the PMPI surface built so far)
TRACED = (
    "allreduce", "reduce", "bcast", "allgather", "gather", "scatter",
    "reduce_scatter_block", "alltoall", "scan", "exscan", "barrier",
    "iallreduce", "ireduce", "ibcast", "iallgather", "igather",
    "iscatter", "ireduce_scatter_block", "ireduce_scatter",
    "ialltoall", "iscan", "iexscan", "ibarrier",
    "allreduce_init", "bcast_init", "allgather_init",
    "reduce_scatter_init", "alltoall_init", "barrier_init",
    "send", "recv", "isend", "irecv", "sendrecv", "iprobe",
)


class TraceEvent:
    __slots__ = ("op", "t_start", "dt", "nbytes")

    def __init__(self, op: str, t_start: float, dt: float,
                 nbytes: int) -> None:
        self.op = op
        self.t_start = t_start
        self.dt = dt
        self.nbytes = nbytes

    def asdict(self) -> Dict[str, Any]:
        return {"op": self.op, "t": self.t_start, "dt": self.dt,
                "bytes": self.nbytes}


def _payload_bytes(args, kwargs: Optional[Dict[str, Any]] = None) -> int:
    """Total bytes across positional AND keyword array arguments —
    calls made with keyword buffers (``comm.allreduce(x=buf)``) must
    count the same as positional ones."""
    n = 0
    vals = list(args) + (list(kwargs.values()) if kwargs else [])
    for a in vals:
        sz = getattr(a, "size", None)
        it = getattr(getattr(a, "dtype", None), "itemsize", None)
        if sz is not None and it is not None:
            n += int(sz) * int(it)
    return n


class TracingComm:
    """Transparent proxy: traced methods are timed + recorded, all
    other attribute access passes through."""

    def __init__(self, comm, sink_path: Optional[str] = None) -> None:
        object.__setattr__(self, "_comm", comm)
        object.__setattr__(self, "events", [])
        object.__setattr__(self, "_sink", open(sink_path, "a")
                           if sink_path else None)
        object.__setattr__(self, "_timers", {})

    def _timer(self, op: str):
        t = self._timers.get(op)
        if t is None:
            t = pvar.timer(f"trace_{op}_seconds",
                           f"cumulative time in traced {op}")
            self._timers[op] = t
        return t

    def __getattr__(self, name: str) -> Any:
        attr = getattr(self._comm, name)
        if name not in TRACED or not callable(attr):
            return attr

        def traced(*args, **kw):
            t0 = time.perf_counter()
            try:
                return attr(*args, **kw)
            finally:
                dt = time.perf_counter() - t0
                ev = TraceEvent(name, t0, dt, _payload_bytes(args, kw))
                self.events.append(ev)
                self._timer(name).add(dt)
                if _obs.enabled:
                    # the PMPI proxy feeds the same journal as the
                    # in-framework emit points: one stream
                    _obs.record(name, "pmpi", t0, dt, nbytes=ev.nbytes)
                if self._sink is not None:
                    self._sink.write(json.dumps(ev.asdict()) + "\n")
                    # flush per event: a crashed run must not lose
                    # buffered trace lines
                    self._sink.flush()

        return traced

    def __setattr__(self, name: str, value: Any) -> None:
        setattr(self._comm, name, value)

    def summary(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        for ev in self.events:
            s = out.setdefault(
                ev.op, {"calls": 0, "seconds": 0.0, "bytes": 0}
            )
            s["calls"] += 1
            s["seconds"] += ev.dt
            s["bytes"] += ev.nbytes
        return out

    def close(self) -> None:
        if self._sink is not None:
            self._sink.close()


def wrap(comm, sink_path: Optional[str] = None) -> TracingComm:
    """Interpose on a communicator (PMPI shim analogue)."""
    return TracingComm(comm, sink_path)


@contextlib.contextmanager
def profiler_trace(logdir: str):
    """Device-side profiling via the JAX profiler (XPlane/TensorBoard),
    the VampirTrace analogue for the compiled data plane."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
