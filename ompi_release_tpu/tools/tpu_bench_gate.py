"""tpu-bench-gate — make the perf trajectory trustworthy.

The round records (``BENCH_r*.json``) are the repo's only longitudinal
perf evidence, and until now nothing *read* them: a regression had to
be noticed by a human diffing JSON, and rounds 4-5 silently lost all
TPU metrics to backend-init failures. This tool closes the loop:

1. parse every historical round's metric lines (the ``tail`` JSONL of
   a driver round record, or a plain JSONL file from ``bench.py``);
2. group lines by ``(metric, tier)`` — the tier label keeps
   loopback-CPU fallback rounds from contaminating TPU noise fits;
3. fit a robust noise bound per line (median ± sigma × MAD-scale,
   floored at a relative band, because the measured HBM ceiling
   wobbles ±20% session to session — see bench.py's ceiling notes);
4. exit non-zero when the candidate round regresses past the bound in
   the metric's *bad* direction (lower for bandwidths/speedups,
   higher for latencies/wait times).

Lines that are not comparable are skipped, never gated: null values,
``unstable`` / ``partial_rounds`` / ``error`` markers, units with no
known good direction, and metrics with fewer than ``--min-rounds``
clean historical points.

Usage::

    # newest BENCH_r*.json is the candidate, the rest are history
    python -m ompi_release_tpu.tools.tpu_bench_gate BENCH_r*.json

    # explicit candidate (e.g. a fresh bench run's JSONL output)
    python -m ompi_release_tpu.tools.tpu_bench_gate BENCH_r*.json \
        --candidate fresh.jsonl

``bench.py`` also runs :func:`evaluate` in-process at the end of every
round against the on-disk history and emits a ``bench_gate`` metric
line, so the round record itself says whether the round regressed.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: units where bigger is better (bandwidths, throughputs, speedups,
#: hidden-comm fractions from the overlap suite)
HIGHER_BETTER = {"GB/s", "TFLOP/s", "frac_hidden"}
#: units where smaller is better (latencies, waits, message counts)
LOWER_BETTER = {"s", "seconds", "us", "us/hop", "hol_wait_s",
                "sends_at_root", "device_collectives", "steps",
                "copies/MiB"}
#: metric-name fallback when the unit alone is ambiguous: the overlap
#: suite's lines (hidden-comm fraction, overlap speedups), the
#: tree_overlap suite's lines (planned-pass speedup, whole-tree
#: hidden-comm fraction, nonblocking-pipeline speedup), and the
#: steady_state suite's compiled_* lines (interpreted-vs-compiled
#: orchestration speedups) are all higher-better — less comm or
#: Python time exposed on the critical path. The fleet_scaling
#: suite's topo_* lines (topology-aware schedule speedups over the
#: flat ring: inter-host byte ratio, virtual-makespan ratio) are
#: higher-better too — a shrunk ratio means the torus/multiring
#: advantage regressed.
METRIC_HIGHER_BETTER_PREFIXES = ("overlap_", "tree_", "compiled_",
                                 "topo_")
#: ...and the ft_recovery suite's lines (recovery wall time, steps
#: recomputed after rollback) and the contract-sentinel suite's lines
#: (per-collective overhead, enabled AND disabled legs) are all
#: lower-better — the sentinel's "near-zero overhead when off" claim
#: is gate-enforced across rounds, like any latency regression.
#: The fleet_scaling suite's sim_* lines (simulated-fleet schedule
#: round counts, bytes per rank, virtual-clock makespan) are
#: lower-better too: they are DETERMINISTIC functions of the schedule
#: code over the fabric model (tier_label "sim" keeps them out of the
#: wall-clock tiers' fits), so a tripped bound is a real scaling
#: regression — a schedule doing more rounds or shipping more bytes
#: at the same P — not measurement noise. The steady_state suite's
#: steady_* lines (per-op wall and Python-orchestration seconds for
#: interpreted and compiled legs) are lower-better latencies. The
#: multi_tenant suite's tenant_* lines (latency-tenant p99 solo /
#: contended / FIFO, and the tenant_latency_isolation degradation
#: ratio — THE service-plane acceptance factor) are lower-better on
#: the same sim tier: a grown isolation ratio means the weighted-fair
#: wire lets a bulk tenant degrade a latency tenant further.
#: The flight-recorder lines are lower-better on the same logic:
#: ``steady_obs_*`` (obs-ON compiled orchestration seconds and the
#: obs-ON/obs-OFF overhead ratio — THE "tracing never de-optimizes
#: the hot path" acceptance factor, already covered by ``steady_``)
#: and ``ledger_*`` (bytes appended to the per-rank binary ring per
#: observed compiled fire — a grown record means the fixed-size
#: fire-path write got heavier).
#: The native_wire suite's lines split by unit: ``wire_native_p2p_*``
#: bandwidths carry "GB/s" (higher-better via the unit table, which
#: is checked first), while ``wire_native_copies*`` witnesses count
#: byte-path materializations per MiB shipped — lower-better, with
#: 0.0 the zero-copy acceptance target; a grown count means an array
#: started taking the staged/fallback copy path again.
#: The native telemetry lines follow suit: ``wire_native_stall_*``
#: (full/empty-ring stall counts and cumulative blocked seconds from
#: the C-side counter blocks) and ``wire_native_ring_hwm_frac`` (the
#: worst ring occupancy high-water fraction) are lower-better — a
#: growth means the consumer fell behind or rings shrank into
#: backpressure. ``native_obs_overhead_*`` is the counters-always-on
#: acceptance ratio (telemetry-on p2p wall over telemetry-free
#: baseline, budget 1.05): lower-better, a grown ratio means the
#: always-on counter block started costing wall time.
#: The native_rounds suite (frozen plans lowered into the C plan
#: executor) rides the SAME two prefixes by construction:
#: ``steady_native_orch_*`` seconds (whole-fire orchestration with
#: the descriptor loop running C-side) are lower-better via
#: ``steady_``, and ``compiled_native_*`` speedups (native executor
#: over the interpreted PlannedXchg replay — THE >= 2x tentpole
#: acceptance factor at <= 256 KiB) are higher-better via
#: ``compiled_``; a shrunk ratio means Python crept back into the
#: per-round byte path.
METRIC_LOWER_BETTER_PREFIXES = ("ft_", "ledger_", "sentinel_", "sim_",
                                "steady_", "tenant_",
                                "wire_native_copies",
                                "wire_native_stall",
                                "wire_native_ring_hwm_frac",
                                "native_obs_overhead")

DEFAULT_SIGMA = 4.0
#: relative noise floor: the bench's own ceiling docs put single-run
#: wobble at ±20%, so no fit may claim a tighter band than this
DEFAULT_REL_FLOOR = 0.25
#: ...except the "sim" tier: fleet-simulator lines are deterministic
#: replays (bit-identical history, MAD = 0), so the wall-clock wobble
#: floor would silently pass schedule regressions up to 25% (8 -> 10
#: recursive-doubling rounds). A 2% floor tolerates float drift
#: across numpy versions while tripping on any real round/byte change
SIM_TIER = "sim"
SIM_REL_FLOOR = 0.02
DEFAULT_MIN_ROUNDS = 3


def _direction(unit: Optional[str],
               metric: Optional[str] = None) -> Optional[int]:
    """+1 = higher is better, -1 = lower is better, None = no gate."""
    if unit is not None:
        if unit in HIGHER_BETTER or unit.startswith("x_"):
            return 1
        if unit in LOWER_BETTER:
            return -1
    if metric and any(metric.startswith(p)
                      for p in METRIC_HIGHER_BETTER_PREFIXES):
        return 1
    if metric and any(metric.startswith(p)
                      for p in METRIC_LOWER_BETTER_PREFIXES):
        return -1
    return None


def line_tier(line: Dict[str, Any]) -> str:
    """The comparability tier of one metric line. ``tier_label`` is
    authoritative (bench.py stamps it on every line); older rounds
    only carried ``backend: cpu`` on fallback lines, so that maps to
    the loopback tier and everything else counts as tpu."""
    t = line.get("tier_label")
    if t:
        return str(t)
    return "loopback-cpu" if line.get("backend") == "cpu" else "tpu"


def gateable(line: Dict[str, Any]) -> bool:
    """Only clean, complete, direction-known lines feed the fit/gate."""
    if not isinstance(line, dict) or not line.get("metric"):
        return False
    if line.get("metric") in ("bench_error", "bench_gate"):
        return False
    v = line.get("value")
    if not isinstance(v, (int, float)) or isinstance(v, bool):
        return False
    if line.get("unstable") or line.get("error") \
            or line.get("partial_rounds"):
        return False
    return _direction(line.get("unit"), line.get("metric")) is not None


def parse_round_file(path: str) -> List[Dict[str, Any]]:
    """Metric lines from one round record: a driver round JSON (the
    ``tail`` field holds the bench's JSONL stdout) or a plain JSONL
    file. Non-JSON lines (jax warnings) and event lines are skipped."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
        if isinstance(doc, dict) and isinstance(doc.get("tail"), str):
            text = doc["tail"]
        elif isinstance(doc, list):
            return [ln for ln in doc
                    if isinstance(ln, dict) and ln.get("metric")]
        elif isinstance(doc, dict) and doc.get("metric"):
            return [doc]
    except ValueError:
        pass  # plain JSONL
    lines = []
    for raw in text.splitlines():
        raw = raw.strip()
        if not raw.startswith("{"):
            continue
        try:
            obj = json.loads(raw)
        except ValueError:
            continue
        if isinstance(obj, dict) and obj.get("metric"):
            lines.append(obj)
    return lines


def _median(vals: Sequence[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def fit_bound(history: Sequence[float], *,
              sigma: float = DEFAULT_SIGMA,
              rel_floor: float = DEFAULT_REL_FLOOR
              ) -> Tuple[float, float]:
    """(median, allowed absolute deviation) from a metric's clean
    history: ``sigma`` MAD-scales (MAD × 1.4826 ≈ a robust stddev),
    with the TOTAL band floored at ``rel_floor × |median|`` — a
    coincidentally-quiet history cannot produce a hair-trigger gate,
    and a genuinely noisy line gets the wider statistical band. With
    the defaults the band is at least ±25% (the bench's own
    session-to-session wobble) so a 2× latency regression or a halved
    bandwidth always trips while ±20% ceiling wobble never does."""
    med = _median(history)
    mad = _median([abs(v - med) for v in history])
    return med, max(sigma * mad * 1.4826, rel_floor * abs(med))


def evaluate(history_rounds: List[List[Dict[str, Any]]],
             candidate_lines: List[Dict[str, Any]], *,
             sigma: float = DEFAULT_SIGMA,
             rel_floor: float = DEFAULT_REL_FLOOR,
             min_rounds: int = DEFAULT_MIN_ROUNDS) -> Dict[str, Any]:
    """Gate one candidate round against the history. Returns
    ``{"checked", "skipped", "regressions": [...], "lines": [...]}``;
    a regression entry names the metric, the fitted bound, and how far
    past it the candidate landed."""
    hist: Dict[Tuple[str, str], List[float]] = {}
    for rnd in history_rounds:
        for ln in rnd:
            if gateable(ln):
                hist.setdefault((ln["metric"], line_tier(ln)),
                                []).append(float(ln["value"]))
    checked = 0
    skipped = 0
    regressions: List[Dict[str, Any]] = []
    detail: List[Dict[str, Any]] = []
    for ln in candidate_lines:
        if not gateable(ln):
            skipped += 1
            continue
        key = (ln["metric"], line_tier(ln))
        series = hist.get(key, [])
        if len(series) < min_rounds:
            skipped += 1
            detail.append({"metric": key[0], "tier": key[1],
                           "status": "no-history",
                           "rounds": len(series)})
            continue
        med, dev = fit_bound(
            series, sigma=sigma,
            rel_floor=min(rel_floor, SIM_REL_FLOOR)
            if key[1] == SIM_TIER else rel_floor)
        v = float(ln["value"])
        direction = _direction(ln.get("unit"), ln.get("metric"))
        checked += 1
        if direction > 0:
            bound, bad = med - dev, v < med - dev
        else:
            bound, bad = med + dev, v > med + dev
        entry = {"metric": key[0], "tier": key[1], "value": v,
                 "median": round(med, 6), "bound": round(bound, 6),
                 "unit": ln.get("unit"), "rounds": len(series),
                 "status": "REGRESSION" if bad else "ok"}
        detail.append(entry)
        if bad:
            regressions.append(entry)
    return {"checked": checked, "skipped": skipped,
            "regressions": regressions, "lines": detail}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu-bench-gate",
        description="Fail (exit != 0) when the newest bench round "
                    "regresses past fitted noise bounds of the "
                    "BENCH_r*.json history")
    ap.add_argument("files", nargs="*",
                    help="round records, oldest..newest (default: "
                         "./BENCH_r*.json sorted by name)")
    ap.add_argument("--candidate", default=None,
                    help="gate this file instead of the newest "
                         "history round (e.g. a fresh bench JSONL)")
    ap.add_argument("--sigma", type=float, default=DEFAULT_SIGMA,
                    help=f"bound width in MAD-scales (default "
                         f"{DEFAULT_SIGMA})")
    ap.add_argument("--rel-floor", type=float,
                    default=DEFAULT_REL_FLOOR,
                    help="minimum relative noise band (default "
                         f"{DEFAULT_REL_FLOOR} — the bench's own "
                         "ceiling wobble)")
    ap.add_argument("--min-rounds", type=int,
                    default=DEFAULT_MIN_ROUNDS,
                    help="history points required before a metric is "
                         f"gated (default {DEFAULT_MIN_ROUNDS})")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable verdict on stdout")
    args = ap.parse_args(argv)

    files = args.files or sorted(glob.glob("BENCH_r*.json"))
    if not files:
        print("tpu-bench-gate: no round records given and no "
              "./BENCH_r*.json found", file=sys.stderr)
        return 2
    files = sorted(files)
    if args.candidate is not None:
        history, cand_path = files, args.candidate
    else:
        if len(files) < 2:
            print("tpu-bench-gate: need at least 2 rounds (history + "
                  "candidate)", file=sys.stderr)
            return 2
        history, cand_path = files[:-1], files[-1]
    rounds = [parse_round_file(p) for p in history]
    cand = parse_round_file(cand_path)
    verdict = evaluate(rounds, cand, sigma=args.sigma,
                       rel_floor=args.rel_floor,
                       min_rounds=args.min_rounds)
    if args.json:
        print(json.dumps(verdict, indent=2))
    else:
        print(f"tpu-bench-gate: {len(history)} history round(s), "
              f"candidate {os.path.basename(cand_path)}: "
              f"{verdict['checked']} line(s) gated, "
              f"{verdict['skipped']} skipped")
        for e in verdict["lines"]:
            if e.get("status") == "no-history":
                continue
            mark = "FAIL" if e["status"] == "REGRESSION" else "  ok"
            print(f"  {mark} {e['metric']} [{e['tier']}]: "
                  f"{e['value']:g} {e['unit']} vs median "
                  f"{e['median']:g} (bound {e['bound']:g}, "
                  f"{e['rounds']} rounds)")
        if verdict["regressions"]:
            print(f"tpu-bench-gate: {len(verdict['regressions'])} "
                  "REGRESSION(S) past fitted noise bounds")
    return 1 if verdict["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
