"""tpu-doctor — postmortem & distributed-tracing workbench.

The operator-facing end of the observability plane's cross-process
layer (``obs/doctor.py``): collect per-rank journal dumps, merge them
into ONE clock-aligned Perfetto trace with send→recv flow arrows, and
print the critical-path / rank-skew report naming the slowest rank
per collective round.

Usage::

    # ranks ran with --mca obs_enable 1 --mca obs_dump_dir DIR
    python -m ompi_release_tpu.tools.tpu_doctor merge DIR -o trace.json
    python -m ompi_release_tpu.tools.tpu_doctor report DIR
    python -m ompi_release_tpu.tools.tpu_doctor postmortem DIR

    # ranks ran with --mca obs_sentinel 1: align per-comm collective
    # call signatures across ranks and name the first desync
    python -m ompi_release_tpu.tools.tpu_doctor contracts DIR

    # fetch a live process's journal over the tpu-server journal RPC
    python -m ompi_release_tpu.tools.tpu_doctor collect host:port -o DIR

``merge`` also accepts a directory holding only ``postmortem-*.json``
files (a hung job's flight-recorder output): the journal tails inside
are merged the same way. Load the trace at ui.perfetto.dev or
chrome://tracing; flow arrows join each wire send span to its matching
recv on the peer rank.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import List, Optional

from ..obs import doctor as _doctor


def _cmd_merge(args) -> int:
    dumps = _doctor.load_dir(args.dir)
    trace = _doctor.merge(dumps)
    out = args.out or os.path.join(args.dir, "merged-trace.json")
    with open(out, "w") as f:
        json.dump(trace, f)
    od = trace["otherData"]
    print(f"tpu-doctor: merged {od['processes']} rank journal(s), "
          f"{od['spans']} spans, {od['flows']} flow arrow(s) "
          f"({od['cross_process_flows']} cross-process) -> {out}")
    print("open in ui.perfetto.dev or chrome://tracing")
    return 0


def _cmd_report(args) -> int:
    dumps = _doctor.load_dir(args.dir)
    # series dumps ride the same obs_dump_dir: when present, the
    # report annotates its critical path with sampled rates
    try:
        series = _doctor.load_series_dir(args.dir)
    except (OSError, ValueError):
        series = []
    text, _ = _doctor.skew_report(dumps, series=series or None)
    print(text)
    return 0


def _cmd_series(args) -> int:
    """Merge per-rank series dumps into ONE clock-corrected fleet
    series — JSONL (one corrected point per line) or OpenMetrics."""
    from ..obs import export as _export

    docs = _doctor.load_series_dir(args.dir)
    if not docs:
        print(f"no series-p*.jsonl under {args.dir} (set --mca "
              "obs_sample_interval > 0 and obs_dump_dir)",
              file=sys.stderr)
        return 1
    merged = _doctor.merge_series(docs)
    if args.openmetrics:
        # ONE exposition over the merged, clock-corrected points:
        # concatenating per-process pages would repeat/interleave
        # family TYPE lines, which the OpenMetrics spec forbids
        text = _export.openmetrics_series(
            [dict(p, t=p["ts"]) for p in merged])
        if args.out:
            with open(args.out, "w") as f:
                f.write(text)
        else:
            print(text, end="")
    else:
        out = args.out or os.path.join(args.dir, "merged-series.jsonl")
        with open(out, "w") as f:
            for p in merged:
                f.write(json.dumps(p) + "\n")
        print(f"tpu-doctor: merged {len(docs)} rank series "
              f"({len(merged)} clock-corrected points) -> {out}")
    return 0


def _cmd_contracts(args) -> int:
    """Collective-contract alignment: per-comm posting sequences of
    sentinel call signatures, merged across ranks; exit 3 when a
    divergence was found (0 = all call streams agree)."""
    dumps = _doctor.load_dir(args.dir)
    text, data = _doctor.contract_report(dumps, directory=args.dir)
    print(text)
    return 3 if data["divergences"] else 0


def _cmd_postmortem(args) -> int:
    """Summarize every postmortem in a directory: the hang story."""
    paths = sorted(glob.glob(os.path.join(args.dir, "postmortem-*.json")))
    if not paths:
        print(f"no postmortem-*.json under {args.dir}", file=sys.stderr)
        return 1
    for p in paths:
        with open(p) as f:
            pm = json.load(f)
        rank = pm.get("rank", {})
        print(f"{os.path.basename(p)}: reason={pm.get('reason')} "
              f"proc={rank.get('pidx', '?')} pid={rank.get('pid')}")
        for st in pm.get("stalled", []) or []:
            info = st.get("info") or {}
            awaiting = (info.get("awaiting_ranks")
                        or info.get("awaiting_procs") or "?")
            print(f"  STALLED {st.get('op')} (comm {st.get('comm')}): "
                  f"waited {st.get('waited_s')}s, awaiting {awaiting}")
        rounds = pm.get("hier_rounds")
        if isinstance(rounds, dict):
            for cid, st in rounds.items():
                print(f"  round: comm {cid} op={st.get('op')} "
                      f"#{st.get('round')} awaiting ranks "
                      f"{st.get('awaiting_ranks')}")
        mq = pm.get("msg_queues")
        if isinstance(mq, list):
            for c in mq:
                unex, posted = c.get("unexpected", []), c.get("posted", [])
                if unex or posted:
                    print(f"  queues: {c.get('comm')} "
                          f"{len(unex)} unexpected, {len(posted)} posted")
    return 0


def _cmd_collect(args) -> int:
    from .tpu_server import NameClient

    host, _, port = args.server.rpartition(":")
    if not host:
        print("collect needs host:port", file=sys.stderr)
        return 2
    out_dir = args.out or "."
    os.makedirs(out_dir, exist_ok=True)
    client = NameClient(host, int(port))
    try:
        dump = client.journal()
        pidx = dump.get("meta", {}).get("pidx", 0)
        path = os.path.join(out_dir, f"journal-p{pidx}.json")
        with open(path, "w") as f:
            json.dump(dump, f)
        print(f"tpu-doctor: {len(dump.get('spans', []))} spans from "
              f"{args.server} -> {path}")
        if args.metrics:
            mpath = os.path.join(out_dir, f"metrics-p{pidx}.prom")
            with open(mpath, "w") as f:
                f.write(client.metrics())
            print(f"tpu-doctor: pvar exposition -> {mpath}")
    finally:
        client.close()
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu-doctor",
        description="Merge per-rank obs journals into one Perfetto "
                    "trace, explain hangs from postmortems, and rank "
                    "the slow ranks")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("merge", help="merge rank dumps into one "
                                     "Perfetto trace with flow arrows")
    p.add_argument("dir", help="directory of journal-p*.json (or "
                               "postmortem-*.json) dumps")
    p.add_argument("-o", "--out", default=None,
                   help="output trace path (default: "
                        "<dir>/merged-trace.json)")
    p.set_defaults(fn=_cmd_merge)

    p = sub.add_parser("report", help="critical-path + rank-skew "
                                      "report per collective round "
                                      "(annotated with sampled rates "
                                      "when series-p*.jsonl exist)")
    p.add_argument("dir")
    p.set_defaults(fn=_cmd_report)

    p = sub.add_parser("series", help="merge per-rank continuous "
                                      "series dumps into one "
                                      "clock-corrected fleet series")
    p.add_argument("dir", help="directory of series-p*.jsonl dumps "
                               "(obs_sample_interval + obs_dump_dir)")
    p.add_argument("-o", "--out", default=None,
                   help="output path (default: "
                        "<dir>/merged-series.jsonl)")
    p.add_argument("--openmetrics", action="store_true",
                   help="emit OpenMetrics-with-timestamps text "
                        "instead of JSONL")
    p.set_defaults(fn=_cmd_series)

    p = sub.add_parser(
        "contracts",
        help="align per-comm collective call signatures "
             "(obs_sentinel >= 1) across rank journals or watchdog "
             "postmortems and name the first desync: missing "
             "participant, op/dtype/count mismatch, posting-order "
             "swap, or epoch skew — with both call sites (exit 3 on "
             "divergence)")
    p.add_argument("dir", help="directory of journal-p*.json and/or "
                               "postmortem-*.json dumps")
    p.set_defaults(fn=_cmd_contracts)

    p = sub.add_parser("postmortem", help="summarize flight-recorder "
                                          "dumps: stuck ops + waiting "
                                          "ranks")
    p.add_argument("dir")
    p.set_defaults(fn=_cmd_postmortem)

    p = sub.add_parser("collect", help="fetch a live process's journal "
                                       "over the tpu-server RPC")
    p.add_argument("server", help="host:port of a tpu-server (or any "
                                  "process running MetricsPubsubTable)")
    p.add_argument("-o", "--out", default=None,
                   help="output directory (default: .)")
    p.add_argument("--metrics", action="store_true",
                   help="also save the Prometheus pvar exposition")
    p.set_defaults(fn=_cmd_collect)

    args = ap.parse_args(argv)
    try:
        return args.fn(args)
    except (FileNotFoundError, ValueError) as e:
        print(f"tpu-doctor: {e}", file=sys.stderr)
        return 1
    except BrokenPipeError:
        # `tpu-doctor ... | head` closes our stdout mid-print: the
        # Unix-polite exit, not a traceback
        try:
            sys.stdout.close()
        except Exception:
            pass
        return 0


if __name__ == "__main__":
    sys.exit(main())
