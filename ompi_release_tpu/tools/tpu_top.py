"""tpu-top — live fleet dashboard (``orte-top`` analogue, grown up).

Four modes:

- ``--tenants HOST:PORT``: the multi-tenant service plane's view —
  poll a ``tpu_serviced`` daemon's TAG_TENANTS RPC and render who is
  burning the fabric: per-tenant coll/s, MB/s, lane share, HOL wait
  (self-reported via lease renewals), lease/beat ages, capacity in
  use, and recent evictions with their reasons.

- default: tpu_ps's per-rank process snapshot on a refresh loop
  (``python -m ompi_release_tpu.tools.tpu_top [-d SECS]``).
- ``--metrics HOST:PORT``: poll a ``tpu_server``'s metrics RPC and
  render the live Prometheus pvar page. Survives server restarts: a
  failed poll prints a stale-data marker and reconnects with backoff
  instead of exiting.
- ``--fleet [HOST:PORT]`` / ``--fleet-from DIR``: the continuous
  metrics plane's dashboard. Renders one row per controller process
  from the sampler's time-series points — collective rate, bytes/s,
  latency percentiles (from the ``coll_*_latency`` histogram pvar
  deltas), mean arrival skew, the compiled-fire ratio (``comp%``,
  from the ``coll_compiled_cache_hits`` aggregate deltas — how much
  of the window's traffic replayed frozen plans), and inline STALL /
  DESYNC / DARK / STALE flags (DESYNC counts the contract sentinel's
  detected cross-rank collective mismatches, ``sentinel_mismatches``
  deltas; DARK marks a rank whose compiled fires emitted neither
  spans nor flight-recorder ledger records — observed traffic that
  tracing cannot see) — either
  live from a job HNP's TAG_SERIES store (discovered via the session
  dir when no target is given) or offline from ``series-p*.jsonl``
  dumps. The refresh loop reconnects with backoff and marks rows
  stale rather than dying with the server.
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import time
from typing import Any, Dict, List, Optional

#: a proc whose newest push/sample is older than this many refresh
#: delays is flagged STALE (its rank may be hung — or the sampler off)
STALE_FACTOR = 3.0


# ---------------------------------------------------------------------------
# fleet summarization (pure — the testable core)
# ---------------------------------------------------------------------------


def summarize_points(points: List[Dict[str, Any]],
                     window_s: float = 15.0,
                     now: Optional[float] = None) -> Dict[str, Any]:
    """Fold one process's sampler points (newest ``window_s`` seconds
    of them) into the dashboard row: collective ops/s and MB/s from
    the per-cid ``coll_ops``/``coll_bytes`` deltas, p50/p99 latency
    from the ``coll_*_latency`` histogram delta buckets, mean skew
    from ``coll_*_skew_seconds``, a stall flag from
    ``obs_stalls_detected`` deltas, and a desync flag from the
    contract sentinel's ``sentinel_mismatches`` deltas.

    The compiled steady state is first-class: the compiled-fire ratio
    folds from the ``coll_compiled_cache_hits`` aggregate deltas
    (sum = frozen-plan replays, count = total fires through the plan
    layer), and a rank whose compiled traffic left NO trace — plan
    replays in the window but neither per-cid ``coll_ops`` span folds
    nor flight-recorder ``ledger_records`` — comes back ``dark``:
    obs is on (the sampler only runs under obs) yet the hot path is
    invisible, exactly the de-optimization regression this plane
    exists to catch.

    The native wire gets the same treatment one layer down: staged
    throughput splits into its native share (``wire_native_bytes``
    deltas over the ``btl_dcn_staged_bytes`` total), and a rank moving
    native frames while NONE of the C-side telemetry series
    (``wire_native_ring_stalls`` / ``wire_native_stall_seconds`` /
    ``wire_native_ring_hwm_frac``, folded from the ring-header counter
    blocks) ever produced a point comes back ``dark_native`` — the
    signature of a stale ``libompitpu_native.so`` predating the
    telemetry block. ``now`` defaults to the newest point's time
    (dump replay); pass the live clock for live feeds."""
    from ..obs.sampler import percentile

    if not points:
        return {"ops_s": None, "mb_s": None, "p50_ms": None,
                "p99_ms": None, "skew_ms": None, "stalls": 0,
                "desyncs": 0, "cids": [], "age_s": None,
                "window_s": 0.0, "compiled_frac": None,
                "ledger_records": 0, "dark": False,
                "native_mb_s": None, "staged_mb_s": None,
                "native_frac": None, "dark_native": False}
    ts = [float(p["t"]) for p in points]
    t_new = max(ts)
    if now is None:
        now = t_new
    lo = t_new - window_s
    ops = bytes_ = 0.0
    lat_buckets: Dict[float, float] = {}
    skew_sum = skew_count = 0.0
    stalls = desyncs = 0.0
    plan_hits = plan_fires = ledger_recs = 0.0
    native_bytes = native_frames = wire_bytes = 0.0
    native_tele = 0
    cids = set()
    t_used = []
    for p in points:
        t = float(p["t"])
        if t < lo:
            continue
        name = str(p.get("name", ""))
        v = p.get("v")
        t_used.append(t)
        cid = int(p.get("cid", -1))
        if name == "coll_ops":
            ops += float(v or 0)
            cids.add(cid)
        elif name == "coll_bytes":
            bytes_ += float(v or 0)
        elif name.endswith("_latency") and isinstance(v, dict):
            for ub, c in (v.get("buckets") or {}).items():
                lat_buckets[float(ub)] = (lat_buckets.get(float(ub), 0.0)
                                          + float(c))
        elif name.endswith("_skew_seconds") and isinstance(v, dict):
            skew_sum += float(v.get("sum", 0.0))
            skew_count += float(v.get("count", 0.0))
        elif name == "obs_stalls_detected":
            stalls += float(v or 0)
        elif name == "sentinel_mismatches":
            desyncs += float(v or 0)
        elif name == "coll_compiled_cache_hits" and isinstance(v, dict):
            plan_hits += float(v.get("sum", 0.0) or 0.0)
            plan_fires += float(v.get("count", 0.0) or 0.0)
        elif name == "ledger_records":
            ledger_recs += float(v or 0)
        elif name == "wire_native_bytes":
            native_bytes += float(v or 0)
        elif name == "wire_native_frames":
            native_frames += float(v or 0)
        elif name == "btl_dcn_staged_bytes":
            wire_bytes += float(v or 0)
        elif name in ("wire_native_ring_stalls",
                      "wire_native_stall_seconds",
                      "wire_native_ring_hwm_frac"):
            native_tele += 1
    # a window holding a single sampler tick has NO measurable span —
    # rates are unknown then, not "whatever 1 ms would imply" (a lone
    # 10-op tick must render '-', never 10000 coll/s)
    distinct = sorted(set(t_used))
    window = (distinct[-1] - distinct[0]
              if len(distinct) >= 2 else None)
    p50 = percentile(lat_buckets, 0.5)
    p99 = percentile(lat_buckets, 0.99)
    return {
        "ops_s": ops / window if window else None,
        "mb_s": bytes_ / window / 1e6 if window else None,
        "p50_ms": p50 * 1e3 if p50 is not None else None,
        "p99_ms": p99 * 1e3 if p99 is not None else None,
        "skew_ms": (skew_sum / skew_count * 1e3) if skew_count else None,
        "stalls": int(stalls),
        "desyncs": int(desyncs),
        "cids": sorted(c for c in cids if c >= 0),
        "age_s": max(now - t_new, 0.0),
        "window_s": window or 0.0,
        "compiled_frac": (plan_hits / plan_fires
                          if plan_fires else None),
        "ledger_records": int(ledger_recs),
        "dark": bool(plan_hits > 0 and ops == 0
                     and ledger_recs == 0),
        "native_mb_s": native_bytes / window / 1e6 if window else None,
        "staged_mb_s": (max(0.0, wire_bytes - native_bytes)
                        / window / 1e6 if window else None),
        "native_frac": (min(1.0, native_bytes / wire_bytes)
                        if wire_bytes else None),
        "dark_native": bool(native_frames > 0 and native_tele == 0),
    }


def _fmt(v, spec: str, dash: str = "-") -> str:
    return dash if v is None else format(v, spec)


def render_fleet(docs: List[Dict[str, Any]], window_s: float = 15.0,
                 stale_after_s: Optional[float] = None) -> str:
    """The per-rank dashboard table from per-process series docs
    (``{"meta": {...}, "points": [...]}`` — offline dumps and the
    live fleet query share this shape via
    ``obs.doctor.fleet_to_series_docs``)."""
    head = (f"  {'proc':>4} {'ranks':>9} {'coll/s':>8} {'MB/s':>9} "
            f"{'nwMB/s':>8} {'nat%':>5} "
            f"{'p50 ms':>8} {'p99 ms':>8} {'skew ms':>8} "
            f"{'comp%':>6} {'cids':>6} flags")
    lines = [head]
    for d in docs:
        m = d.get("meta") or {}
        pidx = int(m.get("pidx", 0))
        off0 = int(m.get("rank_offset", 0) or 0)
        n = int(m.get("local_size", 0) or 0)
        ranks = f"{off0}..{off0 + n - 1}" if n else "?"
        s = summarize_points(list(d.get("points") or ()),
                             window_s=window_s)
        flags = []
        if s["stalls"]:
            flags.append(f"STALL×{s['stalls']}")
        if s["desyncs"]:
            flags.append(f"DESYNC×{s['desyncs']}")
        if s["dark"]:
            # compiled fires in the window but zero spans AND zero
            # flight-recorder records: the hot path went invisible
            flags.append("DARK")
        if s["dark_native"]:
            # native frames moved but the C-side counter-block series
            # never produced a point: the zero-copy byte path went
            # invisible (stale .so predating the telemetry block)
            flags.append("DARK-NATIVE")
        age = m.get("push_age_s")
        if age is None:
            age = s["age_s"]
        if (stale_after_s is not None and age is not None
                and age > stale_after_s):
            flags.append(f"STALE {age:.0f}s")
        lines.append(
            f"  {pidx:>4} {ranks:>9} "
            f"{_fmt(s['ops_s'], '8.1f'):>8} "
            f"{_fmt(s['mb_s'], '9.2f'):>9} "
            f"{_fmt(s['native_mb_s'], '8.2f'):>8} "
            f"{_fmt(s['native_frac'] * 100 if s['native_frac'] is not None else None, '5.1f'):>5} "
            f"{_fmt(s['p50_ms'], '8.3f'):>8} "
            f"{_fmt(s['p99_ms'], '8.3f'):>8} "
            f"{_fmt(s['skew_ms'], '8.3f'):>8} "
            f"{_fmt(s['compiled_frac'] * 100 if s['compiled_frac'] is not None else None, '5.1f'):>6} "
            f"{len(s['cids']):>6} {' '.join(flags)}".rstrip())
    if len(lines) == 1:
        lines.append("  (no series points yet — is obs_sample_interval "
                     "set on the job?)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# tenant view (TAG_TENANTS against a tpu-serviced daemon)
# ---------------------------------------------------------------------------


def render_tenants(doc: Dict[str, Any]) -> str:
    """The per-tenant fabric table from a daemon's TAG_TENANTS doc
    (``service.daemon.ServiceClient.tenants()``): who is burning the
    fabric — per-tenant collective rate, MB/s, lane share, HOL wait
    (all self-reported via lease-renewal stats), plus lease age and
    state. Evicted tenants render below the live ones with the
    eviction reason — the FT-isolation episode stays visible."""
    cap = doc.get("capacity") or {}
    head = (f"  {'tid':>3} {'tenant':>14} {'qos':>11} {'ranks':>5} "
            f"{'lanes':>5} {'coll/s':>8} {'MB/s':>9} {'lane%':>6} "
            f"{'hol ms':>7} {'beat s':>6} state")
    lines = [
        f"  capacity: {cap.get('used_ranks', 0)}/{cap.get('ranks', '?')}"
        f" ranks, {cap.get('used_lanes', 0)}/{cap.get('lanes', '?')}"
        " lanes in use",
        head,
    ]

    def row(t: Dict[str, Any]) -> str:
        s = t.get("stats") or {}
        share = s.get("lane_share")
        hol = s.get("hol_wait_s")
        state = t.get("state", "?")
        if state == "evicted" and t.get("evict_reason"):
            state = f"evicted ({t['evict_reason']})"
        return (f"  {t.get('tid', '?'):>3} "
                f"{str(t.get('name', '?'))[:14]:>14} "
                f"{str(t.get('qos', '-'))[:11]:>11} "
                f"{t.get('ranks', 0):>5} {t.get('lanes', 0):>5} "
                f"{_fmt(s.get('coll_s'), '8.1f'):>8} "
                f"{_fmt(s.get('mb_s'), '9.2f'):>9} "
                f"{_fmt(share * 100 if share is not None else None, '5.1f'):>6} "
                f"{_fmt(hol * 1e3 if hol is not None else None, '7.2f'):>7} "
                f"{_fmt(t.get('beat_age_s'), '6.1f'):>6} {state}")

    tenants = list(doc.get("tenants") or ())
    for t in tenants:
        lines.append(row(t))
    if not tenants:
        lines.append("  (no live tenants)")
    evicted = list(doc.get("evicted") or ())
    if evicted:
        lines.append("  -- recent evictions --")
        for t in evicted:
            lines.append(row(t))
    return "\n".join(lines)


def _tenants_loop(target: str, delay: float, iterations: int) -> int:
    """Poll a tpu-serviced daemon's TAG_TENANTS view on a loop, with
    the shared reconnect-with-backoff contract (see
    :func:`_client_poll_loop`)."""
    from ..service.daemon import ServiceClient

    return _client_poll_loop(
        "tenants", "tenants", target, delay, iterations,
        ServiceClient, lambda c: render_tenants(c.tenants()))


# ---------------------------------------------------------------------------
# live fleet query (TAG_SERIES against a job HNP)
# ---------------------------------------------------------------------------


class FleetClient:
    """One-shot fleet-series query against a job's HNP (high random
    client id, like PsClient — must not collide with worker ids)."""

    def __init__(self, host: str, port: int,
                 secret: Optional[str] = None) -> None:
        from ..native import OobEndpoint

        self.ep = OobEndpoint(
            random.randrange(1 << 20, 1 << 30),
            secret=secret.encode() if secret else None,
        )
        self.ep.connect(0, host, int(port))

    def query(self, timeout_ms: int = 5_000) -> Dict:
        from ..runtime.coordinator import TAG_SERIES

        self.ep.send(0, TAG_SERIES, b"{}")
        _, _, raw = self.ep.recv(tag=TAG_SERIES, timeout_ms=timeout_ms)
        return json.loads(raw)

    def close(self) -> None:
        self.ep.close()


def _fleet_targets(target: Optional[str]) -> List[Dict[str, Any]]:
    if target:
        host, port_s = target.rsplit(":", 1)
        return [{"host": host, "port": int(port_s), "pid": "?"}]
    from .tpu_ps import discover_jobs

    return discover_jobs()


def _fleet_frame(target: Optional[str], window_s: float,
                 delay: float) -> str:
    """One refresh of the live fleet view: query every target job's
    HNP; a job that does not answer renders as unreachable instead of
    killing the loop (the reconnect contract)."""
    from ..obs.doctor import fleet_to_series_docs
    from ..utils.errors import MPIError

    chunks = []
    for info in _fleet_targets(target):
        label = (f"job (tpurun pid {info.get('pid', '?')}) "
                 f"@ {info.get('host')}:{info.get('port')}")
        client = None
        try:
            client = FleetClient(info["host"], info["port"],
                                 secret=info.get("secret"))
            fleet = client.query()
        except (MPIError, OSError, ValueError) as e:
            chunks.append(f"{label}\n  (HNP unreachable: {e}; "
                          "retrying next refresh)")
            continue
        finally:
            if client is not None:
                client.close()
        docs = fleet_to_series_docs(fleet)
        chunks.append(label + "\n" + render_fleet(
            docs, window_s=window_s,
            stale_after_s=STALE_FACTOR * delay))
    return ("\n\n".join(chunks) if chunks
            else "no live tpurun jobs found")


def _fleet_loop(target: Optional[str], delay: float, iterations: int,
                window_s: float) -> int:
    i = 0
    try:
        while True:
            frame = _fleet_frame(target, window_s, delay)
            sys.stdout.write("\x1b[2J\x1b[H" if sys.stdout.isatty()
                             else "")
            print("tpu-top fleet  " + time.strftime("%H:%M:%S"))
            print(frame)
            sys.stdout.flush()
            i += 1
            if iterations and i >= iterations:
                return 0
            time.sleep(delay)
    except KeyboardInterrupt:
        return 0


def fleet_from_dir(directory: str, window_s: float = 1e18) -> str:
    """One offline frame from ``series-p*.jsonl`` dumps (the whole
    sampled history by default) — the post-run view of the same table
    the live loop renders."""
    from ..obs.doctor import load_series_dir

    docs = load_series_dir(directory)
    if not docs:
        return (f"no series-p*.jsonl under {directory} (run with "
                "--mca obs_sample_interval 1 --mca obs_dump_dir DIR)")
    return render_fleet(docs, window_s=window_s)


# ---------------------------------------------------------------------------
# pvar page mode (tpu_server metrics RPC) — with reconnect
# ---------------------------------------------------------------------------


def _client_poll_loop(flag: str, label: str, target: str,
                      delay: float, iterations: int, make_client,
                      fetch) -> int:
    """THE shared poll/render driver for the client-backed modes
    (``--metrics``, ``--tenants``): parse HOST:PORT, connect on
    demand, render ``fetch(client)`` each refresh; a dead/restarted
    server does NOT end the loop — the last frame re-renders with a
    stale marker and the client reconnects with bounded exponential
    backoff. With ``iterations`` set, exits 0 iff any frame was ever
    fetched. One contract, one implementation — a backoff/exit-code
    fix lands in every mode at once."""
    from ..utils.errors import MPIError

    try:
        host, port_s = target.rsplit(":", 1)
        port = int(port_s)
    except ValueError:
        print(f"tpu-top: --{flag} wants HOST:PORT, got {target!r}",
              file=sys.stderr)
        return 2
    client = None
    last_frame: Optional[str] = None
    last_ok: Optional[float] = None
    backoff = delay
    i = 0
    try:
        while True:
            frame = None
            err = None
            try:
                if client is None:
                    client = make_client(host, port)
                frame = fetch(client)
            except (MPIError, OSError, ValueError) as e:
                err = e
                if client is not None:
                    try:
                        client.close()
                    except Exception:
                        pass
                    client = None  # reconnect fresh next round
            sys.stdout.write("\x1b[2J\x1b[H" if sys.stdout.isatty()
                             else "")
            # target stays out of the strftime format: a '%' in it
            # (IPv6 zone-id hosts) would expand or raise
            print(f"tpu-top {label} @ " + target + "  "
                  + time.strftime("%H:%M:%S"))
            if frame is not None:
                last_frame, last_ok = frame, time.monotonic()
                backoff = delay
                print(frame, end="" if frame.endswith("\n") else "\n")
            else:
                age = (time.monotonic() - last_ok
                       if last_ok is not None else None)
                print(f"  [STALE — server unreachable: {err}; "
                      + (f"showing data from {age:.0f}s ago; "
                         if age is not None else "no data yet; ")
                      + f"reconnecting in {backoff:.0f}s]")
                if last_frame is not None:
                    print(last_frame,
                          end="" if last_frame.endswith("\n")
                          else "\n")
            sys.stdout.flush()
            i += 1
            if iterations and i >= iterations:
                return 0 if frame is not None \
                    or last_frame is not None else 1
            time.sleep(backoff if frame is None else delay)
            if frame is None:
                backoff = min(backoff * 2, 30.0)
    except KeyboardInterrupt:
        return 0
    finally:
        if client is not None:
            client.close()


def _metrics_loop(target: str, delay: float, iterations: int) -> int:
    """Poll a tpu_server's Prometheus page on the shared
    reconnect-with-backoff driver."""
    from .tpu_server import NameClient

    return _client_poll_loop("metrics", "pvars", target, delay,
                             iterations, NameClient,
                             lambda c: c.metrics())


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="tpu-top", add_help=False)
    ap.add_argument("--metrics", default=None,
                    help="render a tpu-server's live pvar page "
                         "(host:port) instead of job snapshots")
    ap.add_argument("--fleet", nargs="?", const="", default=None,
                    help="live per-rank collective-rate dashboard from "
                         "a job HNP's series store (host:port; no "
                         "argument = discover local jobs)")
    ap.add_argument("--fleet-from", default=None, metavar="DIR",
                    help="render one fleet frame from series-p*.jsonl "
                         "dumps in DIR (post-run view)")
    ap.add_argument("--tenants", default=None, metavar="HOST:PORT",
                    help="render a tpu-serviced daemon's per-tenant "
                         "fabric view (who is burning the fabric: "
                         "coll/s, MB/s, lane share, HOL wait, leases)")
    args, rest = ap.parse_known_args(argv)
    if args.fleet_from is not None:
        print(fleet_from_dir(args.fleet_from))
        return 0
    if args.metrics is None and args.fleet is None \
            and args.tenants is None:
        from .tpu_ps import main_top

        return main_top(rest)
    mp = argparse.ArgumentParser(
        prog="tpu-top --metrics/--fleet/--tenants")
    mp.add_argument("-d", "--delay", type=float, default=2.0,
                    help="refresh interval in seconds")
    mp.add_argument("--iterations", type=int, default=0,
                    help="stop after N refreshes (0 = until SIGINT)")
    mp.add_argument("--window", type=float, default=15.0,
                    help="rate window in seconds (fleet mode)")
    ma = mp.parse_args(rest)
    if args.tenants is not None:
        return _tenants_loop(args.tenants, ma.delay, ma.iterations)
    if args.fleet is not None:
        return _fleet_loop(args.fleet or None, ma.delay,
                           ma.iterations, ma.window)
    return _metrics_loop(args.metrics, ma.delay, ma.iterations)


if __name__ == "__main__":
    sys.exit(main())
