"""tpu-top — refresh-loop entry point (``orte-top`` analogue).

``python -m ompi_release_tpu.tools.tpu_top [-d SECS]``; the
implementation is tpu_ps's snapshot machinery on a loop.
"""

import sys

from .tpu_ps import main_top

if __name__ == "__main__":
    sys.exit(main_top())
