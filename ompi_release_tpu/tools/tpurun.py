"""tpurun — the job launcher (``orterun``/``mpirun`` analogue).

Usage::

    python -m ompi_release_tpu.tools.tpurun -n 4 [--mca VAR VAL]... \
        [--timeout S] prog [args...]

What the reference's ``orterun`` does (``orte/tools/orterun/orterun.c``:
build job, register state callbacks, ``orte_plm.spawn`` :1077; daemons
``orted_main.c:234`` report back; apps launch, register, run, exit;
stdio forwards through the iof) — re-shaped for one-host-many-process
and multi-host TPU jobs:

  1. start the HNP coordinator endpoint (node 0)
  2. fork N worker processes with ``OMPITPU_*`` env (the ess/env
     detection contract) + ``OMPITPU_MCA_*`` for ``--mca`` pairs
  3. serve modex + init barrier on a thread (the PLM/grpcomm role)
  4. forward each worker's stdout/stderr line-tagged ``[rank k]``
     (the iof role, ``orte/mca/iof``)
  5. monitor heartbeats (``sensor_heartbeat.c:61,78``) and process
     exits; on abnormal exit or heartbeat loss, activate the error
     state and kill the job (errmgr default_hnp policy: clean teardown)
  6. aggregate exit codes: 0 iff every worker exited 0 after FIN

The job/proc state machines are the real ``runtime/state.py`` ones, so
tests (and ``ft_tester``-style kills) can assert the exact state path
the reference defines (``plm_types.h:113-151``).
"""

from __future__ import annotations

import argparse
import os
import re
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

from ..runtime import coordinator as coord
from ..runtime.state import JobState, ProcState, StateMachine
from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.stream("tpurun")

_LOCAL_NAMES = ("localhost", "127.0.0.1")

#: session contact directory (the orterun session-dir analogue:
#: orte-ps discovers live jobs by reading the universe contact files
#: under the session dir — tpu-ps does the same here)
SESSION_DIR = os.path.join(
    os.environ.get("TMPDIR", "/tmp"),
    f"ompitpu-sessions-{os.getuid()}",
)


# ---------------------------------------------------------------------------
# rmaps-lite: hostfile + rank->host mapping (orte/mca/rmaps analogue)
# ---------------------------------------------------------------------------

class HostSpec:
    """One allocation line: hostname + slot count (ras analogue)."""

    def __init__(self, name: str, slots: int = 1) -> None:
        if slots < 1:
            raise MPIError(ErrorCode.ERR_ARG,
                           f"host {name}: slots must be >= 1")
        self.name = name
        self.slots = slots

    @property
    def is_local(self) -> bool:
        return self.name in _LOCAL_NAMES

    def __repr__(self) -> str:
        return f"HostSpec({self.name}, slots={self.slots})"


def parse_hostfile(path: str) -> List[HostSpec]:
    """Hostfile lines: ``hostname [slots=N]`` (# comments allowed) —
    the mpirun hostfile format's core."""
    hosts: List[HostSpec] = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            slots = 1
            for tok in parts[1:]:
                if tok.startswith("slots="):
                    try:
                        slots = int(tok.split("=", 1)[1])
                    except ValueError:
                        raise MPIError(
                            ErrorCode.ERR_ARG,
                            f"hostfile {path}: bad slot count in "
                            f"'{line}'",
                        )
                else:
                    # 'slot=8' silently parsing as slots=1 would map
                    # ranks onto machines the user meant to keep free
                    raise MPIError(
                        ErrorCode.ERR_ARG,
                        f"hostfile {path}: unrecognized token "
                        f"'{tok}' in '{line}' (only 'slots=N' is "
                        "supported)",
                    )
            hosts.append(HostSpec(parts[0], slots))
    if not hosts:
        raise MPIError(ErrorCode.ERR_ARG, f"hostfile {path} has no hosts")
    return hosts


def parse_host_list(spec: str) -> List[HostSpec]:
    """``--host a:2,b,c:4`` (name[:slots] comma list)."""
    hosts = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        if ":" in item:
            name, slots = item.rsplit(":", 1)
            try:
                hosts.append(HostSpec(name, int(slots)))
            except ValueError:
                raise MPIError(ErrorCode.ERR_ARG,
                               f"bad slot count in '{item}'")
        else:
            hosts.append(HostSpec(item))
    if not hosts:
        raise MPIError(ErrorCode.ERR_ARG, f"empty host list '{spec}'")
    return hosts


def map_ranks(hosts: List[HostSpec], n: int,
              policy: str = "slot") -> List[HostSpec]:
    """Rank->host mapping (the rmaps framework's mapper menu).

    ``slot``: fill each host's slots before moving on (rmaps_rr
    by-slot). ``node``: round-robin one rank per host per pass
    (by-node). ``ppr:N:node``: exactly N processes per node in
    allocation order (``orte/mca/rmaps/ppr``). ``seq``: rank i runs on
    the i-th allocation LINE, slots ignored — list a host on several
    lines to stack ranks on it (``orte/mca/rmaps/seq``).
    Oversubscription (n > total slots, or ppr N > a host's slots) is
    an error, like the reference without ``--oversubscribe``.
    rank_file mapping is a separate entry point (:func:`parse_rankfile`)
    since it carries its own placement list. mindist (NUMA/NIC
    distance) has no TPU meaning — a worker owns its chips by
    construction — and is deliberately absent.
    """
    out: List[HostSpec] = []
    if policy == "seq":
        # one rank per allocation line, in file order
        if n > len(hosts):
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"seq mapper: {n} ranks but only {len(hosts)} "
                "allocation lines (list a host once per rank)",
            )
        return list(hosts[:n])
    if policy.startswith("ppr:"):
        parts = policy.split(":")
        if len(parts) != 3 or parts[2] != "node":
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"bad ppr spec '{policy}' (expected ppr:N:node)",
            )
        try:
            per = int(parts[1])
        except ValueError:
            per = 0
        if per < 1:
            raise MPIError(ErrorCode.ERR_ARG,
                           f"bad ppr count in '{policy}'")
        for h in hosts:
            if per > h.slots:
                raise MPIError(
                    ErrorCode.ERR_ARG,
                    f"ppr {per}/node exceeds {h.slots} slot(s) on "
                    f"{h.name} (no oversubscription)",
                )
            for _ in range(per):
                if len(out) < n:
                    out.append(h)
        if len(out) < n:
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"ppr {per}/node places only "
                f"{per * len(hosts)} ranks on {len(hosts)} hosts "
                f"but {n} were requested",
            )
        return out
    total = sum(h.slots for h in hosts)
    if n > total:
        raise MPIError(
            ErrorCode.ERR_ARG,
            f"{n} ranks > {total} slots on {len(hosts)} hosts "
            "(no oversubscription)",
        )
    if policy == "slot":
        for h in hosts:
            for _ in range(h.slots):
                if len(out) < n:
                    out.append(h)
    elif policy == "node":
        used = {id(h): 0 for h in hosts}
        while len(out) < n:
            progressed = False
            for h in hosts:
                if len(out) >= n:
                    break
                if used[id(h)] < h.slots:
                    out.append(h)
                    used[id(h)] += 1
                    progressed = True
            if not progressed:  # all slots consumed (can't happen: n<=total)
                break
    else:
        raise MPIError(ErrorCode.ERR_ARG,
                       f"unknown map-by policy '{policy}'")
    return out


def parse_rankfile(path: str, n: int,
                   hosts: Optional[List[HostSpec]] = None
                   ) -> List[HostSpec]:
    """Explicit per-rank placement (``orte/mca/rmaps/rank_file``).

    Syntax, one line per rank (comments ``#``)::

        rank 3=hostB slot=1

    ``slot=`` is accepted and validated for range but carries no
    binding semantics (a TPU worker owns whole chips, not cores).
    Every rank 0..n-1 must appear exactly once. When an allocation is
    given (--hostfile/--host) every named host must be in it and its
    per-host rank count must fit its slots; without one, named hosts
    form their own allocation (one slot per placed rank)."""
    alloc = {h.name: h for h in (hosts or [])}
    placed: Dict[int, str] = {}
    counts: Dict[str, int] = {}
    try:
        lines = open(path).read().splitlines()
    except OSError as e:
        raise MPIError(ErrorCode.ERR_FILE,
                       f"cannot read rankfile {path}: {e}")
    for lineno, line in enumerate(lines, 1):
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        m = re.match(r"rank\s+(\d+)\s*=\s*(\S+?)"
                     r"(?:\s+slot\s*=\s*(\d+))?\s*$", line)
        if not m:
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"rankfile {path}:{lineno}: unparseable line "
                f"'{line}' (expected 'rank N=host [slot=S]')",
            )
        r, host, slot = int(m.group(1)), m.group(2), m.group(3)
        if r in placed:
            raise MPIError(ErrorCode.ERR_ARG,
                           f"rankfile {path}:{lineno}: rank {r} "
                           "placed twice")
        if r >= n:
            raise MPIError(ErrorCode.ERR_ARG,
                           f"rankfile {path}:{lineno}: rank {r} out "
                           f"of range for -n {n}")
        if alloc and host not in alloc:
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"rankfile {path}:{lineno}: host '{host}' not in "
                f"the allocation ({', '.join(sorted(alloc))})",
            )
        if slot is not None and alloc and int(slot) >= alloc[host].slots:
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"rankfile {path}:{lineno}: slot {slot} out of range "
                f"on {host} ({alloc[host].slots} slots)",
            )
        placed[r] = host
        counts[host] = counts.get(host, 0) + 1
    missing = [r for r in range(n) if r not in placed]
    if missing:
        raise MPIError(
            ErrorCode.ERR_ARG,
            f"rankfile {path} leaves rank(s) "
            f"{', '.join(map(str, missing))} unmapped for -n {n}",
        )
    for host, c in counts.items():
        if alloc and c > alloc[host].slots:
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"rankfile {path}: {c} ranks on {host} exceed its "
                f"{alloc[host].slots} slot(s) (no oversubscription)",
            )
    by_name = alloc or {h: HostSpec(h, counts[h]) for h in counts}
    return [by_name[placed[r]] for r in range(n)]


class Job:
    """One launched job: processes + coordinator + state machines."""

    def __init__(self, num_procs: int, argv: List[str],
                 mca: List[tuple], *, heartbeat_s: float = 0.5,
                 miss_limit: int = 4, tag_output: bool = True,
                 hosts: Optional[List[HostSpec]] = None,
                 map_by: str = "slot",
                 rankfile: Optional[str] = None,
                 launch_agent: str = "ssh",
                 on_failure: str = "abort",
                 max_restarts: int = 2,
                 ft_inject: Optional[tuple] = None) -> None:
        self.n = num_procs
        self.argv = argv
        self.mca = mca
        self.heartbeat_s = heartbeat_s
        self.miss_limit = miss_limit
        self.tag_output = tag_output
        # rmaps: rank r runs on rank_hosts[r] (default: all-local,
        # the single-host fork path); an explicit rankfile overrides
        # the policy mapper (rank_file has top rmaps priority in the
        # reference too)
        self.hosts = hosts or [HostSpec("localhost", num_procs)]
        if rankfile is not None:
            self.rank_hosts = parse_rankfile(rankfile, num_procs, hosts)
            if hosts is None:
                # the rankfile's named hosts ARE the allocation: the
                # remapper/migrator key host load by identity over
                # self.hosts, so the phantom localhost spec must not
                # survive (parse_rankfile reuses one HostSpec per
                # name, so dedup by id works)
                seen: Dict[int, HostSpec] = {}
                for h in self.rank_hosts:
                    seen.setdefault(id(h), h)
                self.hosts = list(seen.values())
        else:
            self.rank_hosts = map_ranks(self.hosts, num_procs, map_by)
        self.remote = any(not h.is_local for h in self.rank_hosts)
        self.launch_agent = launch_agent
        # errmgr policy: 'abort' = default_hnp teardown; 'restart' =
        # rmaps/resilient respawn of the failed rank on a surviving
        # slot (the app resumes from its last committed checkpoint);
        # 'continue' = the ULFM degraded world — the failed rank is
        # promoted through the job epoch (TAG_PROC_FAILED) and the
        # survivors keep running (they shrink and carry on); the job
        # exits 0 iff every SURVIVOR finished clean
        if on_failure not in ("abort", "restart", "continue"):
            raise MPIError(ErrorCode.ERR_ARG,
                           f"unknown failure policy '{on_failure}'")
        self.on_failure = on_failure
        self.max_restarts = max_restarts
        # chaos injection (--ft-inject rank:step): arm the sensor's
        # hard kill in EXACTLY the chosen child via its env cvars
        if ft_inject is not None:
            r, s = int(ft_inject[0]), int(ft_inject[1])
            if not 0 <= r < num_procs:
                raise MPIError(ErrorCode.ERR_ARG,
                               f"--ft-inject rank {r} out of range "
                               f"for -n {num_procs}")
            if s < 0:
                raise MPIError(ErrorCode.ERR_ARG,
                               f"--ft-inject step {s} must be >= 0")
            ft_inject = (r, s)
        self.ft_inject = ft_inject
        #: node ids promoted to failed under the 'continue' policy:
        #: their exit codes never fail the job, and the FIN collector
        #: stops expecting them
        self._ft_failed_ranks: set = set()
        self._restarts: Dict[int, int] = {}
        self._respawned: List[int] = []  # drained by the waitpid loop
        self._restarting: set = set()    # ranks mid-respawn (dedupe)
        self._respawn_lock = threading.Lock()
        self.job_state = StateMachine("tpurun-job")
        self.proc_state: Dict[int, int] = {}
        self.hnp: Optional[coord.HnpCoordinator] = None
        self.hnp_host = "127.0.0.1"
        self.procs: Dict[int, subprocess.Popen] = {}
        self._iof_threads: List[threading.Thread] = []
        self._failed = threading.Event()
        self._fin: set = set()
        self._fin_lock = threading.Lock()
        # hosts evacuated by tpu-migrate: the remapper never places a
        # rank (migrated OR failure-respawned) back on one of these
        self._excluded_hosts: set = set()
        # serializes rank_hosts read-modify-write: concurrent moves
        # (multi-rank migration, or migration racing a failure
        # restart) must each see the other's placement or two ranks
        # can double-book one free slot
        self._map_lock = threading.Lock()
        # per-job control-plane secret (opal/mca/sec analogue): the
        # HNP endpoint picks it up from the environment, every worker
        # inherits it (fork env / the rsh env assignments), and the
        # OOB refuses unauthenticated inbound connections — a foreign
        # local process can no longer inject TAG_DIE/TAG_MIGRATE
        import secrets as _secrets

        from ..native.bindings import SECRET_ENV

        self.secret = os.environ.get(SECRET_ENV) or _secrets.token_hex(16)
        os.environ[SECRET_ENV] = self.secret

    # -- launch ------------------------------------------------------------
    def _env_for(self, node_id: int) -> Dict[str, str]:
        env = dict(os.environ)
        env.update(self._ompitpu_env(node_id))
        return env

    def _ompitpu_env(self, node_id: int) -> Dict[str, str]:
        """The contract env vars alone — what an rsh launch must carry
        across the wire (ssh does not forward the environment; the
        reference builds them into the orted command line,
        plm_rsh_module.c:872)."""
        env = {
            "OMPITPU_JOB_SECRET": self.secret,
            "OMPITPU_HNP": f"{self.hnp_host}:{self.hnp.port}",
            "OMPITPU_NODE_ID": str(node_id),
            "OMPITPU_NUM_NODES": str(self.n),
            "OMPITPU_HOST": self.rank_hosts[node_id - 1].name,
            "OMPITPU_MCA_ess_tpurun_heartbeat_interval": str(
                self.heartbeat_s
            ),
        }
        if self.on_failure == "restart":
            # workers under the resilient policy tolerate unreachable
            # peers at wire-up (a peer may be mid-restart or finished)
            env["OMPITPU_RECOVERY"] = "1"
        if self._restarts.get(node_id, 0):
            # authoritative incarnation marker: a RESPAWNED process
            # knows it is a replacement without racing the failure
            # picture (the rejoin epoch bump can land before or after
            # any point the app samples it — the env cannot)
            env["OMPITPU_INCARNATION"] = str(self._restarts[node_id])
        if self.ft_inject is not None and node_id - 1 == self.ft_inject[0] \
                and not self._restarts.get(node_id, 0):
            # chaos: arm the sensor's SIGKILL at the chosen step in
            # THIS child only (FtTester.from_cvars reads it) — and
            # only in the FIRST incarnation: --ft-inject injects ONE
            # failure, so a respawned replacement must not re-kill
            # itself at the same step
            env["OMPITPU_MCA_sensor_ft_kill_step"] = str(
                self.ft_inject[1])
        for k, v in self.mca:
            env[f"OMPITPU_MCA_{k}"] = str(v)
        return env

    def _iof(self, node_id: int, stream, out) -> None:
        """Forward one worker stream, line-tagged (iof analogue)."""
        prefix = f"[rank {node_id - 1}] " if self.tag_output else ""
        for line in stream:
            out.write(prefix + line)
            out.flush()

    def _spawn(self, node_id: int) -> None:
        host = self.rank_hosts[node_id - 1]
        secret_on_stdin = False
        if host.is_local:
            cmd = self.argv
            env = self._env_for(node_id)
        else:
            # rsh launch (plm_rsh_module.c:929): agent + host + env
            # assignments + program. ssh joins the args and hands ONE
            # string to the remote shell, so every word is quoted
            # (the reference's plm_rsh quotes its orted cmdline too).
            # The JOB SECRET must NOT ride the command line (visible to
            # every local user via /proc/*/cmdline on both machines —
            # defeating the auth it feeds); it travels on the worker's
            # stdin instead, announced by OMPITPU_SECRET_STDIN
            import shlex

            wire_env = dict(self._ompitpu_env(node_id))
            wire_env.pop("OMPITPU_JOB_SECRET", None)
            wire_env["OMPITPU_SECRET_STDIN"] = "1"
            cmd = (
                self.launch_agent.split()
                + [host.name, "env"]
                + [shlex.quote(f"{k}={v}") for k, v in
                   sorted(wire_env.items())]
                + [shlex.quote(a) for a in self.argv]
            )
            env = dict(os.environ)
            secret_on_stdin = True
        p = subprocess.Popen(
            cmd, env=env,
            stdin=subprocess.PIPE if secret_on_stdin else None,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, bufsize=1,
        )
        if secret_on_stdin:
            try:
                p.stdin.write(self.secret + "\n")
                p.stdin.flush()
            except OSError:
                pass  # a dead child surfaces through the waitpid loop
        self.procs[node_id] = p
        self.proc_state[node_id] = ProcState.RUNNING
        for stream, out in ((p.stdout, sys.stdout), (p.stderr, sys.stderr)):
            t = threading.Thread(
                target=self._iof, args=(node_id, stream, out), daemon=True
            )
            t.start()
            self._iof_threads.append(t)

    # -- failure policy (errmgr default_hnp teardown / resilient) ----------
    def _on_worker_failure(self, node_id: int, state: int) -> None:
        self.proc_state[node_id] = state
        if self._failed.is_set():
            return
        if self.on_failure == "continue" and self.job_state.visited(
                JobState.RUNNING):
            # ULFM degraded world (only once the job is RUNNING — a
            # child that dies during bring-up must abort the launch
            # loudly, like the restart policy's guard, or survivors
            # would park in wire-up masking the real startup error):
            # promote through the job epoch (the
            # waitpid loop usually observes the corpse long before the
            # heartbeat window closes — promote_failed is idempotent
            # with the monitor's own promotion) and keep running; the
            # survivors revoke/shrink and carry on
            with self._fin_lock:
                first = node_id not in self._ft_failed_ranks
                if first:
                    self._ft_failed_ranks.add(node_id)
            if first:
                try:
                    self.hnp.promote_failed(node_id)
                except MPIError:
                    pass  # links torn down at job end
                # a WEDGED worker (heartbeat-promoted, process still
                # alive) must be reaped or the waitpid loop would spin
                # to the job timeout: control-plane kill first (the
                # odls path that reaches ssh-launched workers), then
                # SIGKILL the local handle — the rc<0 signal death is
                # exactly what the exit-code policy excuses
                p = self.procs.get(node_id)
                if p is not None and p.poll() is None:
                    try:
                        self.hnp.kill_worker(node_id)
                    except MPIError:
                        pass
                    try:
                        p.wait(timeout=1)
                    except subprocess.TimeoutExpired:
                        pass
                    if p.poll() is None:
                        p.kill()
                _log.verbose(
                    0, f"worker {node_id} failed "
                       f"({ProcState(state).name}); continuing "
                       "degraded (--ft-continue)")
            return
        if self.on_failure == "restart" and self.job_state.visited(
                JobState.RUNNING):
            # one restart per failure: the heartbeat monitor and the
            # waitpid loop can BOTH observe the same dead incarnation —
            # the budget is read-modify-written and deduped under the
            # lock, and the (slow: terminate+wait+spawn) respawn runs
            # off-thread so the monitor keeps draining beats
            with self._respawn_lock:
                if node_id in self._restarting:
                    return  # the other observer is already handling it
                used = self._restarts.get(node_id, 0)
                granted = used < self.max_restarts
                if granted:
                    self._restarts[node_id] = used + 1
                    self._restarting.add(node_id)
            if granted:
                # promote through the job epoch FIRST: survivors'
                # bounded waits must raise ERR_PROC_FAILED and enter
                # recovery while the (slow) respawn runs; the respawn
                # path's note_restarted then moves the rank from
                # failed to restarted at the next epoch
                try:
                    self.hnp.promote_failed(node_id)
                except MPIError:
                    pass
                threading.Thread(
                    target=self._restart_rank, args=(node_id, state),
                    daemon=True,
                ).start()
                return
            _log.verbose(1, f"worker {node_id}: restart budget "
                            f"({self.max_restarts}) exhausted")
        self._failed.set()
        self.job_state.activate(JobState.ABORTED, {"node": node_id,
                                                   "state": int(state)})
        _log.verbose(1, f"worker {node_id} failed "
                        f"({ProcState(state).name}); tearing down")
        self.terminate()

    def _remap_rank(self, node_id: int) -> None:
        """rmaps/resilient remap: move the failed rank to the
        least-loaded surviving slot, preferring a DIFFERENT host when
        one exists (``rmaps_resilient.c``'s move-off-the-fault-node
        policy; on a single-host allocation the same host is the only
        slot pool)."""
        with self._map_lock:
            failed_host = self.rank_hosts[node_id - 1]
            load: Dict[int, int] = {id(h): 0 for h in self.hosts}
            for i, h in enumerate(self.rank_hosts):
                if i != node_id - 1:
                    load[id(h)] += 1
            candidates = sorted(
                (h for h in self.hosts
                 if h.slots - load[id(h)] > 0
                 and h.name not in self._excluded_hosts),
                key=lambda h: (h.name == failed_host.name, load[id(h)]),
            )
            if candidates:
                self.rank_hosts[node_id - 1] = candidates[0]
            elif failed_host.name in self._excluded_hosts:
                # nowhere to put an evacuated rank: surface rather
                # than silently respawning on the host being drained
                raise MPIError(
                    ErrorCode.ERR_UNREACH,
                    f"no surviving slot for rank {node_id - 1} off "
                    f"evacuated host {failed_host.name}",
                )

    def _restart_rank(self, node_id: int, state: int) -> None:
        """Respawn the failed rank (same node id = same rank identity;
        the rejoin service re-runs its wire-up) and hand it back to
        the waitpid loop. The app's own checkpoint/restore logic
        (ft.run_with_restart / Checkpointer) resumes its work."""
        _log.verbose(
            0, f"worker {node_id} failed ({ProcState(state).name}); "
               f"restarting (attempt "
               f"{self._restarts[node_id]}/{self.max_restarts})")
        self._move_rank(node_id, f"respawn of worker {node_id}")

    def _move_rank(self, node_id: int, what: str) -> None:
        """Terminate the rank's current incarnation, remap it to a
        surviving slot, respawn it. Caller must already hold the
        rank in ``_restarting`` (that flag is what stops the waitpid
        loop and heartbeat monitor from treating the deliberate
        terminate as a new failure)."""
        try:
            old = self.procs.get(node_id)
            if old is not None and old.poll() is None:
                # kill through the control plane FIRST: under an ssh
                # launch, procs[nid] is the LOCAL ssh client —
                # terminating it orphans the remote worker, which
                # then runs to completion on the host being drained.
                # TAG_DIE reaches the worker itself (odls kill); the
                # signal path below stays as the fallback for workers
                # that died before wiring up their die watcher.
                try:
                    self.hnp.kill_worker(node_id)
                    old.wait(timeout=3)
                except (MPIError, subprocess.TimeoutExpired):
                    pass
            if old is not None and old.poll() is None:
                old.terminate()
                try:
                    old.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    old.kill()
            self._remap_rank(node_id)
            if self.rank_hosts[node_id - 1].name in self._excluded_hosts:
                # this move's placement raced a concurrent evacuation
                # (its remap ran before the exclusion landed): place
                # again now that the exclusion is visible
                self._remap_rank(node_id)
            self.hnp.note_restarted(node_id)
            self._spawn(node_id)
        except Exception as exc:
            # a failed respawn (Popen error, dead launch agent) must
            # abort the job promptly, not spin the waitpid loop until
            # the wall-clock timeout with the rank parked mid-respawn
            with self._respawn_lock:
                self._restarting.discard(node_id)
            _log.verbose(0, f"{what} failed: {exc}; aborting job")
            self.abort(f"{what} failed")
            return
        with self._respawn_lock:
            self._respawned.append(node_id)
            self._restarting.discard(node_id)

    # -- proactive migration (orte-migrate analogue) -----------------------
    def migrate_off(self, req: Dict) -> Dict:
        """Evacuate every rank currently mapped to ``req['off']``:
        mark the host excluded, then move each rank through the same
        terminate->remap->respawn path the resilient errmgr uses (the
        ``orte-migrate`` + ``rmaps/resilient`` composition; reference
        ``orte/tools/orte-migrate/orte-migrate.c``). Each moved app
        resumes from its last COMMITTED checkpoint — the same
        restart-from-checkpoint contract as failure recovery; there is
        no pre-migration snapshot barrier, so work since the last
        commit is recomputed (documented, not hidden).

        Does not touch the per-rank failure-restart budget: an
        operator-requested move is not a failure."""
        off = req.get("off")
        if not off:
            return {"ok": False, "error": "missing 'off' host"}
        if self.on_failure != "restart":
            # without the recovery machinery (rejoin service,
            # OMPITPU_RECOVERY env) a respawned incarnation can never
            # rejoin — accepting would kill a rank and hang the job
            return {"ok": False,
                    "error": "job launched without --enable-recovery; "
                             "migration needs the rejoin service"}
        if self.job_state.current != int(JobState.RUNNING) or \
                self._failed.is_set():
            # CURRENT state, not visited(): a request landing after
            # completion must not spawn an unreaped stray worker
            return {"ok": False, "error": "job is not running"}
        with self._map_lock:  # consistent placement snapshot
            targets = [i + 1 for i, h in enumerate(self.rank_hosts)
                       if h.name == off]
            if not targets:
                return {"ok": False,
                        "error": f"no ranks mapped to host '{off}'"}
            # capacity check BEFORE evacuating: surviving slots must
            # absorb every moved rank or the request is refused whole
            self._excluded_hosts.add(off)
            free = sum(h.slots for h in self.hosts
                       if h.name not in self._excluded_hosts)
            staying = sum(1 for h in self.rank_hosts
                          if h.name not in self._excluded_hosts)
            if free - staying < len(targets):
                self._excluded_hosts.discard(off)
                return {"ok": False,
                        "error": f"cannot evacuate {off}: "
                                 f"{len(targets)} rank(s) need slots "
                                 f"but only {free - staying} remain "
                                 "free"}
        moved = []
        skipped = []
        for nid in targets:
            with self._respawn_lock:
                if nid in self._restarting:
                    # already mid-move (failure respawn in flight) —
                    # its placement may predate the exclusion, so the
                    # mover rechecks before spawning; still REPORT it
                    # so the operator knows this rank was not handled
                    # by this request
                    skipped.append(nid - 1)
                    continue
                self._restarting.add(nid)
            threading.Thread(
                target=self._move_rank,
                args=(nid, f"migration of worker {nid} off {off}"),
                daemon=True,
            ).start()
            moved.append(nid - 1)
        _log.verbose(0, f"migrating rank(s) "
                        f"{', '.join(map(str, moved))} off {off}")
        reply = {"ok": True, "off": off, "ranks": moved}
        if skipped:
            reply["skipped"] = skipped
            reply["note"] = ("skipped rank(s) were mid-respawn; "
                             "verify placement with tpu-ps")
        return reply

    def abort(self, reason: str = "aborted") -> None:
        """Public abort: the errmgr teardown path with state-machine
        bookkeeping (external callers must not poke _failed)."""
        if not self._failed.is_set():
            self._failed.set()
            self.job_state.activate(JobState.ABORTED, reason)
        self.terminate()

    def terminate(self) -> None:
        # control-plane kill first (odls kill): under ssh launches the
        # Popen handles are local ssh clients and signaling them would
        # orphan the remote workers (they'd run on after the job died)
        if self.hnp is not None:
            for nid, p in self.procs.items():
                if p.poll() is None:
                    try:
                        self.hnp.kill_worker(nid)
                    except MPIError:
                        pass  # never wired up / link gone: signal path
            deadline = time.monotonic() + 2
            for p in self.procs.values():
                left = deadline - time.monotonic()
                if left <= 0 or p.poll() is not None:
                    continue
                try:
                    p.wait(timeout=left)
                except subprocess.TimeoutExpired:
                    pass
        for nid, p in self.procs.items():
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + 5
        for p in self.procs.values():
            left = max(0.1, deadline - time.monotonic())
            try:
                p.wait(timeout=left)
            except subprocess.TimeoutExpired:
                p.kill()

    # -- ps/top support ----------------------------------------------------
    def _ps_extra(self) -> Dict:
        """Launcher-side snapshot fields merged into the HNP's TAG_PS
        reply: proc states + the job identity."""
        from ..runtime.state import ProcState as _PS

        return {
            "pid": os.getpid(),
            "argv": self.argv,
            "proc_states": {
                str(nid): _PS(int(s)).name
                for nid, s in self.proc_state.items()
            },
        }

    def _write_contact_file(self) -> None:
        import json

        try:
            os.makedirs(SESSION_DIR, mode=0o700, exist_ok=True)
            self._contact_path = os.path.join(
                SESSION_DIR, f"{os.getpid()}.json"
            )
            # the contact file carries the job secret so same-user
            # tools (tpu-ps/tpu-top/tpu-migrate) can authenticate —
            # 0600, like the reference's session-dir contact files
            fd = os.open(self._contact_path,
                         os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
            with os.fdopen(fd, "w") as f:
                json.dump({
                    "pid": os.getpid(),
                    "host": self.hnp_host,
                    "port": self.hnp.port,
                    "n": self.n,
                    "argv": self.argv,
                    "started": time.time(),
                    "secret": self.secret,
                }, f)
        except OSError as e:
            _log.verbose(1, f"could not write contact file: {e}")
            self._contact_path = None

    def _remove_contact_file(self) -> None:
        path = getattr(self, "_contact_path", None)
        if path:
            try:
                os.unlink(path)
            except OSError:
                pass

    # -- run ---------------------------------------------------------------
    def run(self, timeout_s: float = 300.0) -> int:
        self.job_state.activate(JobState.INIT)
        if self.remote:
            # remote workers must dial back: listen on every
            # interface and advertise the outbound address toward the
            # first remote host (the reference's HNP URI)
            first_remote = next(
                h for h in self.rank_hosts if not h.is_local
            )
            self.hnp_host = coord.local_addr_toward(first_remote.name)
            if self.hnp_host.startswith("127."):
                # loopback is only correct when the "remote" host IS
                # this machine (fake-agent tests); a genuinely remote
                # worker handed 127.0.0.1 would dial itself and the
                # job would hang to the timeout with no clue — warn
                # loudly now, while the cause is still visible
                _log.verbose(
                    0, f"WARNING: no route toward {first_remote.name}; "
                       f"advertising loopback HNP address — remote "
                       f"workers will not reach it unless "
                       f"{first_remote.name} resolves to this machine")
            self.hnp = coord.HnpCoordinator(self.n + 1,
                                            bind_addr="0.0.0.0")
        else:
            self.hnp = coord.HnpCoordinator(self.n + 1)
        self.job_state.activate(JobState.LAUNCH_DAEMONS)
        for nid in range(1, self.n + 1):
            self._spawn(nid)
        self.job_state.activate(JobState.LAUNCH_APPS)

        # PLM/grpcomm service thread: modex + init barrier, then
        # heartbeat monitoring + FIN collection
        def serve() -> None:
            try:
                cards = self.hnp.run_modex(
                    None, timeout_ms=int(timeout_s * 1000))
                self.job_state.activate(JobState.DAEMONS_REPORTED)
                self.hnp.barrier(timeout_ms=int(timeout_s * 1000))
                self.job_state.activate(JobState.RUNNING)
            except Exception as e:
                if not self._failed.is_set():
                    _log.verbose(1, f"wire-up failed: {e}")
                    self.job_state.activate(JobState.FAILED_TO_START, e)
                    self._failed.set()
                    self.terminate()
                return
            self.hnp.start_heartbeat_monitor(
                lambda nid: self._on_worker_failure(
                    nid, ProcState.HEARTBEAT_FAILED
                ),
                interval_s=self.heartbeat_s, miss_limit=self.miss_limit,
            )
            # pubsub name service (MPI_Publish_name/Lookup_name over
            # the lifeline — the orte-server role lives in the HNP)
            self.hnp.start_name_server()
            # ps/top snapshot service + session contact file so tpu-ps
            # can discover and query this live job (orte-ps role)
            self.hnp.start_ps_responder(self._ps_extra)
            self.hnp.start_migrate_responder(self.migrate_off)
            # clock ping-pong responder: workers estimate their
            # perf_counter offset to OUR clock, so tpu-doctor can merge
            # per-rank journals onto one timeline
            self.hnp.start_clock_responder()
            # fleet series store: workers push continuous pvar deltas
            # (obs_sample_interval), tpu_top --fleet queries them live
            self.hnp.start_series_responder()
            # ULFM plane: failure-state queries + fault-tolerant
            # agreements (shrink's survivor-group consensus) — always
            # on; costs one idle thread when the app never asks
            self.hnp.start_ft_responder()
            self._write_contact_file()
            if self.on_failure == "restart":
                # a respawned worker re-runs its full ESS wire-up
                # against the live job (JOIN + init barrier)
                self.hnp.start_rejoin_service(cards)
            def _done_count() -> int:
                with self._fin_lock:  # _ft_failed_ranks mutates on
                    #                   the monitor/waitpid threads
                    return len(self._fin | self._ft_failed_ranks)

            while not self._failed.is_set() and _done_count() < self.n:
                nid = self.hnp.recv_fin(timeout_ms=200)
                if nid is not None:
                    with self._fin_lock:
                        self._fin.add(nid)
                    self.proc_state[nid] = ProcState.IOF_COMPLETE

        server = threading.Thread(target=serve, daemon=True)
        server.start()

        # waitpid loop (odls wait_local_proc analogue)
        deadline = time.monotonic() + timeout_s
        exit_codes: Dict[int, int] = {}
        pending = set(self.procs)
        # rc==0 workers whose FIN frame hasn't been drained yet: the
        # serve thread processes TAG_FIN on a bounded recv granularity,
        # so a clean exit can be observed by waitpid before its FIN is
        # seen. Give each such worker one heartbeat interval of grace
        # before declaring LIFELINE_LOST.
        grace: Dict[int, float] = {}
        def respawn_pending() -> bool:
            with self._respawn_lock:
                return bool(self._respawned or self._restarting)

        while ((pending or grace or respawn_pending())
               and time.monotonic() < deadline):
            # respawned ranks re-enter the waitpid loop (their failed
            # incarnation's exit code no longer counts)
            with self._respawn_lock:
                respawned, self._respawned = self._respawned, []
            for nid in respawned:
                pending.add(nid)
                exit_codes.pop(nid, None)
                grace.pop(nid, None)
            with self._respawn_lock:
                restarting = set(self._restarting)
            for nid in list(pending):
                if nid in restarting:
                    continue  # mid-respawn: the new proc is coming
                rc = self.procs[nid].poll()
                if rc is None:
                    continue
                pending.discard(nid)
                exit_codes[nid] = rc
                with self._fin_lock:
                    clean = nid in self._fin
                if clean:
                    # no more beats expected. ONLY once FIN confirmed:
                    # any death — nonzero, signal, or exit-0 with no
                    # FIN (lifeline lost) — must reach
                    # _on_worker_failure BEFORE any finished mark, or
                    # promote_failed would mistake the corpse for a
                    # cleanly-finished worker and never bump the epoch
                    self.hnp.note_finished(nid)
                if rc == 0 and clean:
                    self.proc_state[nid] = ProcState.TERMINATED
                elif rc != 0:
                    if not self._failed.is_set():
                        # died with nonzero code (errmgr_default_orted.c
                        # :252 analogue)
                        self._on_worker_failure(nid, ProcState.ABORTED)
                else:
                    grace[nid] = (time.monotonic()
                                  + max(self.heartbeat_s, 0.25))
            for nid in list(grace):
                with self._fin_lock:
                    clean = nid in self._fin
                if clean:
                    self.hnp.note_finished(nid)  # FIN confirmed late
                    self.proc_state[nid] = ProcState.TERMINATED
                    del grace[nid]
                elif time.monotonic() > grace[nid]:
                    del grace[nid]
                    if not self._failed.is_set():
                        # exited 0 but never sent FIN: lifeline lost
                        self._on_worker_failure(
                            nid, ProcState.LIFELINE_LOST)
            time.sleep(0.02)

        for nid in grace:  # deadline hit while still in grace
            if not self._failed.is_set():
                self._on_worker_failure(nid, ProcState.LIFELINE_LOST)

        if pending:  # timeout
            self.job_state.activate(JobState.ABORTED, "timeout")
            self._failed.set()
            self.terminate()
            for nid in pending:
                exit_codes[nid] = self.procs[nid].poll() or 124

        server.join(timeout=5)
        self._remove_contact_file()
        self.hnp.shutdown()
        for t in self._iof_threads:
            t.join(timeout=2)

        if self._failed.is_set():
            rc = next((c for c in exit_codes.values() if c), 1)
            return rc
        # a nonzero code can linger without _failed when a restart was
        # granted but its respawn never cleanly completed — that is a
        # failure, not success. Ranks promoted under the 'continue'
        # policy are the exception — their death is the EXPECTED event
        # the survivors recovered from — but ONLY signal deaths (rc<0:
        # SIGKILL'd by the fault, or job-end terminate of a wedged
        # proc): a promoted rank that exited with a nonzero CODE is an
        # app crash (e.g. a survivor whose recovery failed) and must
        # fail the job.
        leftover = next(
            (c for nid, c in exit_codes.items()
             if c and not (nid in self._ft_failed_ranks and c < 0)), 0)
        if leftover:
            self.job_state.activate(JobState.ABORTED, "restart failed")
            return leftover
        self.job_state.activate(JobState.TERMINATED)
        return 0


def run_loopback_app(nprocs: int, app_src: str, env: dict,
                     out_path: str, *, timeout_s: int = 300,
                     mca: Optional[List[tuple]] = None,
                     job_kw: Optional[Dict] = None):
    """Spawn ``app_src`` as an ``nprocs``-process loopback Job with
    ``env`` exported for the workers, and return the JSON document the
    app wrote to ``out_path`` (or None on failure). The shared harness
    behind the bench micro-suites and the tpu-tune sweeps — the
    tempdir/env-snapshot/Job/read-results dance lives exactly once.

    Note: mutates ``os.environ`` for the spawn window (workers inherit
    the parent environment) and restores it in a finally — callers
    must not run concurrent spawns from other threads."""
    import json as _json
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        app = os.path.join(td, "loopback_app.py")
        with open(app, "w") as f:
            f.write(app_src)
        resolved_out = os.path.join(td, out_path)
        env_keep = dict(os.environ)
        os.environ.update({k: str(v) for k, v in env.items()})
        os.environ["OMPITPU_LOOPBACK_OUT"] = resolved_out
        try:
            kw = dict(heartbeat_s=0.5, miss_limit=8)
            kw.update(job_kw or {})
            job = Job(nprocs, [sys.executable, app], list(mca or ()),
                      **kw)
            rc = job.run(timeout_s=timeout_s)
        finally:
            os.environ.clear()
            os.environ.update(env_keep)
        if rc != 0 or not os.path.exists(resolved_out):
            return None
        with open(resolved_out) as f:
            return _json.load(f)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpurun", description="Launch an N-process tpu job "
        "(orterun analogue)")
    ap.add_argument("-n", "--np", type=int, required=True,
                    help="number of worker processes")
    ap.add_argument("--mca", nargs=2, action="append", default=[],
                    metavar=("VAR", "VAL"),
                    help="set an MCA variable for every worker")
    ap.add_argument("--timeout", type=float, default=300.0,
                    help="job wall-clock limit in seconds")
    ap.add_argument("--heartbeat", type=float, default=0.5,
                    help="worker heartbeat interval in seconds")
    ap.add_argument("--no-tag-output", action="store_true",
                    help="do not prefix forwarded stdio with [rank k]")
    ap.add_argument("--hostfile", default=None,
                    help="allocation file: 'hostname [slots=N]' lines")
    ap.add_argument("--host", default=None,
                    help="comma host list 'a:2,b,c:4' (name[:slots])")
    ap.add_argument("--map-by", default="slot",
                    help="rank->host policy: slot | node | seq | "
                         "ppr:N:node (rmaps round_robin/seq/ppr "
                         "analogues)")
    ap.add_argument("--rankfile", default=None,
                    help="explicit per-rank placement file "
                         "('rank N=host [slot=S]' lines; overrides "
                         "--map-by, rmaps rank_file analogue)")
    ap.add_argument("--launch-agent", default="ssh",
                    help="remote launch command (plm_rsh agent)")
    ap.add_argument("--enable-recovery", action="store_true",
                    help="restart a failed rank on a surviving slot "
                         "instead of aborting the job "
                         "(rmaps/resilient + errmgr recovery)")
    ap.add_argument("--max-restarts", type=int, default=2,
                    help="per-rank restart budget with "
                         "--enable-recovery")
    ap.add_argument("--ft-continue", action="store_true",
                    help="ULFM degraded-world policy: on a rank "
                         "failure, bump the job epoch and xcast "
                         "TAG_PROC_FAILED but keep the job running — "
                         "survivors revoke()/shrink() and continue; "
                         "exit 0 iff every survivor finishes clean "
                         "(mutually exclusive with --enable-recovery)")
    ap.add_argument("--ft-inject", default=None, metavar="RANK:STEP",
                    help="chaos mode: arm the ft sensor's SIGKILL in "
                         "worker RANK at training step STEP (exports "
                         "OMPITPU_MCA_sensor_ft_kill_step into that "
                         "child only; the app's ElasticStep/FtTester "
                         ".step() clock fires it) — used by the "
                         "recovery job tests and chaos runs")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="program and arguments to launch")
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no command given")
    if args.np < 1:
        ap.error("-n must be >= 1")
    if args.hostfile and args.host:
        ap.error("--hostfile and --host are mutually exclusive")
    if args.enable_recovery and args.ft_continue:
        ap.error("--enable-recovery and --ft-continue are mutually "
                 "exclusive (respawn vs degraded-world policy)")
    ft_inject = None
    if args.ft_inject:
        try:
            r, s = args.ft_inject.split(":", 1)
            ft_inject = (int(r), int(s))
        except ValueError:
            ap.error(f"--ft-inject expects RANK:STEP, got "
                     f"'{args.ft_inject}'")
    hosts = None
    if args.hostfile:
        hosts = parse_hostfile(args.hostfile)
    elif args.host:
        hosts = parse_host_list(args.host)

    on_failure = "abort"
    if args.enable_recovery:
        on_failure = "restart"
    elif args.ft_continue:
        on_failure = "continue"
    job = Job(args.np, args.command, [tuple(m) for m in args.mca],
              heartbeat_s=args.heartbeat,
              tag_output=not args.no_tag_output,
              hosts=hosts, map_by=args.map_by, rankfile=args.rankfile,
              launch_agent=args.launch_agent,
              on_failure=on_failure,
              max_restarts=args.max_restarts,
              ft_inject=ft_inject)

    def on_signal(signum, frame):
        job._failed.set()
        job.terminate()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    return job.run(timeout_s=args.timeout)


if __name__ == "__main__":
    sys.exit(main())
