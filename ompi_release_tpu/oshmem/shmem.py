"""OpenSHMEM — symmetric heap + put/get/AMO + collectives.

The reference's OSHMEM stack (SURVEY §1.4): ``memheap`` (symmetric
heap over ``sshmem`` segments), ``spml`` (put/get over the OMPI BTLs —
``spml/yoda``), ``atomic`` (AMOs), ``scoll`` (collectives, including
the delegate-to-MPI ``scoll/mpi`` component). TPU-native recast:

- The symmetric heap is per-PE HBM: a symmetric allocation is one
  device array with a leading PE axis (slice i in PE i's HBM) — the
  same "address" (python handle) is valid for every PE, which is the
  whole symmetric-heap contract (``oshmem/mca/memheap``).
- put/get queue onto the underlying RMA window machinery (the spml →
  BTL path, here spml → osc) and complete at ``quiet``/``barrier_all``
  — OpenSHMEM's own completion rule. Fetch AMOs and get are blocking
  (they flush), put/add are posted.
- the **planned bulk path** (``shmem_bulk``, default on): posted
  puts/AMOs between ``quiet()``/``fence()`` boundaries are batched
  per symmetric allocation as light host-side tuples — no per-call
  ``jnp.asarray``, no per-call window queueing — and drained as ONE
  window epoch, which the osc access-plan machinery (``osc/plan``)
  closes as one fused device program per (allocation, signature).
  Posted ops therefore follow ``shmem_put_nbi`` source-buffer rules:
  the source is reusable after ``quiet()``. Blocking calls (get,
  fetch AMOs, ``wait_until``, ``local``) drain first, so per-call
  ordering is unchanged.
- scoll delegates to the coll framework over the same communicator
  (exactly what ``scoll/mpi`` does to OMPI).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs as _obs
from .. import ops as ops_mod
from ..mca import pvar
from ..mca import var as mca_var
from ..osc.window import Window
from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.stream("shmem")


def register_vars() -> None:
    mca_var.register(
        "shmem_bulk", "bool", True,
        "Batch posted SHMEM puts/AMOs per symmetric allocation "
        "between quiet()/fence() boundaries and drain them as one "
        "planned window epoch (one fused device program per "
        "(allocation, signature) via osc/plan); false restores "
        "per-call window queueing",
    )


register_vars()

_heap_bytes = pvar.highwatermark(
    "shmem_heap_bytes", "symmetric heap bytes allocated"
)
_bulk_ops = pvar.counter(
    "shmem_bulk_ops",
    "posted SHMEM ops deferred into the per-allocation bulk queue",
)
_bulk_flushes = pvar.counter(
    "shmem_bulk_flushes",
    "bulk-queue drains (one planned window epoch per allocation)",
)

#: generation-cached shmem_bulk snapshot — posted-op hot path reads
#: one attribute + int compare, never the registry
_conf: Tuple[int, bool] = (-1, True)


def _bulk_on() -> bool:
    global _conf
    gen = mca_var.VARS.generation
    if _conf[0] != gen:
        _conf = (gen, bool(mca_var.get("shmem_bulk", True)))
    return _conf[1]


class SymmetricArray:
    """One symmetric allocation: ``shape`` per PE, PE i's block in PE
    i's HBM. The handle itself is the symmetric address."""

    def __init__(self, ctx: "ShmemCtx", win: Window) -> None:
        self._ctx = ctx
        self._win = win
        win.lock_all()  # SHMEM has no epochs: one standing passive epoch

    @property
    def shape(self) -> Tuple[int, ...]:
        return self._win.shape

    @property
    def dtype(self):
        return self._win.dtype

    def local(self, pe: int) -> jax.Array:
        """PE ``pe``'s local view (shmem_ptr analogue; driver mode sees
        every PE). On a unified multi-controller world only
        same-process PEs are addressable — the reference's shmem_ptr
        returns NULL for PEs without a load/store path
        (``oshmem/shmem/c/shmem_ptr.c``); use :meth:`ShmemCtx.get`
        for remote PEs."""
        self._ctx._drain(self)
        self._win.flush_all()
        comm = self._win.comm
        if getattr(comm, "spans_processes", False):
            lr = list(comm.local_comm_ranks)
            if pe not in lr:
                raise MPIError(
                    ErrorCode.ERR_RMA_SHARED,
                    f"shmem_ptr: PE {pe} lives in another controller "
                    "process (no load/store path); use get()",
                )
            return self._win.read()[lr.index(pe)]
        return self._win.read()[pe]

    def free(self) -> None:
        self._ctx._drain(self)  # posted ops must land, not vanish
        self._win.unlock_all()
        self._win.free()
        self._ctx._allocs.discard(self)


class ShmemCtx:
    """The OpenSHMEM world (``shmem_init`` state)."""

    def __init__(self, comm) -> None:
        self.comm = comm
        self._allocs: set = set()
        # planned bulk path: per-allocation queues of light
        # (kind, pe, data, op, index) tuples — jnp.asarray and window
        # queueing are deferred to the drain, where the whole batch
        # closes as ONE planned window epoch
        self._bulk: Dict["SymmetricArray", List[Tuple]] = {}

    # -- setup / query (shmem.h accessors) ---------------------------------
    @property
    def n_pes(self) -> int:
        return self.comm.size

    def malloc(self, shape: Tuple[int, ...], dtype=jnp.float32
               ) -> SymmetricArray:
        """shmem_malloc: symmetric allocation (memheap analogue)."""
        from ..osc.window import win_allocate

        win = win_allocate(self.comm, tuple(shape), dtype)
        arr = SymmetricArray(self, win)
        self._allocs.add(arr)
        _heap_bytes.add(
            int(np.prod(shape)) * jnp.dtype(dtype).itemsize * self.n_pes
        )
        return arr

    # -- the planned bulk path (shmem_bulk) --------------------------------
    def _post(self, sym: SymmetricArray, kind: str, pe: int, data,
              op, index) -> None:
        """Defer one posted op into ``sym``'s bulk queue (nbi
        semantics: the source lands at the next drain). The tuple
        carries the frozen Op OBJECT — the drain replays it through
        the window queue, so osc/plan keys the fused program by the
        object, never by an op name."""
        self._bulk.setdefault(sym, []).append((kind, pe, data, op, index))
        _bulk_ops.add()

    def _drain(self, sym: SymmetricArray) -> None:
        """Replay ``sym``'s bulk queue as one window epoch and flush:
        the whole batch closes as one fused device program per
        (allocation, signature) via the osc access-plan cache."""
        q = self._bulk.pop(sym, None)
        if not q:
            return
        rec = _obs.enabled
        t0 = time.perf_counter() if rec else 0.0
        win = sym._win
        for kind, pe, data, op, index in q:
            if kind == "put":
                win.put(jnp.asarray(data), pe, index=index)
            else:  # acc
                win.accumulate(jnp.asarray(data), pe, op=op, index=index)
        win.flush_all()
        _bulk_flushes.add()
        if rec and _obs.enabled:
            _obs.record(
                "shmem_bulk_flush", "osc", t0,
                time.perf_counter() - t0, nbytes=sum(
                    int(getattr(d, "nbytes", 0) or 0)
                    for _, _, d, _, _ in q),
                comm_id=win.comm.cid)

    # -- data movement (spml put/get) --------------------------------------
    def put(self, sym: SymmetricArray, data, pe: int) -> None:
        """shmem_put: posted; completes at quiet/barrier_all."""
        if _bulk_on():
            self._post(sym, "put", pe, data, None, None)
            return
        sym._win.put(jnp.asarray(data), pe)

    def get(self, sym: SymmetricArray, pe: int) -> jax.Array:
        """shmem_get: blocking (flushes pending ops first)."""
        self._drain(sym)
        sym._win.flush_all()
        req = sym._win.get(pe)
        sym._win.flush_all()
        return req.value

    def put_elem(self, sym: SymmetricArray, value, index, pe: int) -> None:
        """Scalar put at a flat index (shmem_p): a true single-element
        posted put — O(1) staged bytes, no read-modify-write of the
        whole slot."""
        if _bulk_on():
            self._post(sym, "put", pe, value, None, int(index))
            return
        sym._win.put(jnp.asarray(value), pe, index=int(index))

    # -- atomics (oshmem/mca/atomic) ---------------------------------------
    def atomic_add(self, sym: SymmetricArray, value, pe: int) -> None:
        if _bulk_on():
            self._post(sym, "acc", pe, value, ops_mod.SUM, None)
            return
        sym._win.accumulate(jnp.asarray(value), pe, op=ops_mod.SUM)

    def atomic_fetch_add(self, sym: SymmetricArray, value, pe: int
                         ) -> jax.Array:
        self._drain(sym)  # fetch observes earlier posted ops
        req = sym._win.fetch_and_op(jnp.asarray(value), pe, op=ops_mod.SUM)
        sym._win.flush(pe)
        return req.value

    def atomic_swap(self, sym: SymmetricArray, value, pe: int) -> jax.Array:
        self._drain(sym)
        req = sym._win.fetch_and_op(jnp.asarray(value), pe,
                                    op=ops_mod.REPLACE)
        sym._win.flush(pe)
        return req.value

    def atomic_compare_swap(self, sym: SymmetricArray, cond, value, pe: int
                            ) -> jax.Array:
        self._drain(sym)
        req = sym._win.compare_and_swap(jnp.asarray(value),
                                        jnp.asarray(cond), pe)
        sym._win.flush(pe)
        return req.value

    def atomic_inc(self, sym: SymmetricArray, pe: int) -> None:
        """shmem_inc: add 1 (the counter idiom)."""
        self.atomic_add(sym, jnp.ones(sym.shape, sym.dtype), pe)

    def atomic_fetch_inc(self, sym: SymmetricArray, pe: int) -> jax.Array:
        return self.atomic_fetch_add(
            sym, jnp.ones(sym.shape, sym.dtype), pe
        )

    def atomic_set(self, sym: SymmetricArray, value, pe: int) -> None:
        """shmem_atomic_set: unconditional replace (no fetch)."""
        if _bulk_on():
            self._post(sym, "acc", pe, value, ops_mod.REPLACE, None)
            return
        sym._win.accumulate(jnp.asarray(value), pe, op=ops_mod.REPLACE)

    def atomic_fetch(self, sym: SymmetricArray, pe: int) -> jax.Array:
        """shmem_atomic_fetch: an atomic read = fetch_add(0)."""
        return self.atomic_fetch_add(
            sym, jnp.zeros(sym.shape, sym.dtype), pe
        )

    # -- point-to-point synchronization (shmem_wait_until) -----------------
    def wait_until(self, sym: SymmetricArray, cmp: str, value, *,
                   pe: int, timeout_s: float = 30.0,
                   poll_s: float = 0.001) -> jax.Array:
        """Block until pe's symmetric variable satisfies the
        comparison — the SHMEM p2p synchronization primitive
        (``shmem_wait_until``; cmp in eq/ne/gt/ge/lt/le). ``pe`` is
        explicit because one controller plays every PE in driver mode
        (in a per-process deployment it would default to the caller's
        own PE). Progress comes from other ranks' posted puts/AMOs
        being flushed (the poll flushes so posted ops land)."""
        import time as _time

        import numpy as _np

        cmps = {
            "eq": _np.equal, "ne": _np.not_equal,
            "gt": _np.greater, "ge": _np.greater_equal,
            "lt": _np.less, "le": _np.less_equal,
        }
        if cmp not in cmps:
            raise MPIError(ErrorCode.ERR_ARG,
                           f"wait_until cmp must be one of {list(cmps)}")
        target_pe = pe
        deadline = _time.monotonic() + timeout_s
        while True:
            cur = _np.asarray(self.get(sym, target_pe))
            if bool(_np.all(cmps[cmp](cur, value))):
                return jnp.asarray(cur)
            if _time.monotonic() > deadline:
                raise MPIError(
                    ErrorCode.ERR_PENDING,
                    f"wait_until({cmp}, {value}) timed out; last "
                    f"value {cur!r}",
                )
            _time.sleep(poll_s)

    def test(self, sym: SymmetricArray, cmp: str, value, *,
             pe: int) -> bool:
        """Nonblocking wait_until (shmem_test)."""
        try:
            self.wait_until(sym, cmp, value, pe=pe, timeout_s=0.0)
            return True
        except MPIError as e:
            if e.code is ErrorCode.ERR_PENDING:  # just not yet
                return False
            raise  # real failures (freed window, bad pe) must surface

    # -- ordering (shmem_quiet / shmem_fence) ------------------------------
    def quiet(self) -> None:
        """Complete all outstanding puts/AMOs (shmem_quiet): drain
        every allocation's bulk queue (one planned epoch each) and
        flush anything queued outside the bulk path."""
        for a in list(self._allocs):
            self._drain(a)
            a._win.flush_all()

    def fence(self) -> None:
        """Ordering only; driver mode applies in submission order, so
        fence == quiet here (stronger is allowed)."""
        self.quiet()

    def barrier_all(self) -> None:
        self.quiet()
        self.comm.barrier()

    # -- collectives (scoll -> coll framework, the scoll/mpi path) ---------
    def broadcast(self, x, root: int = 0):
        return self.comm.bcast(x, root=root)

    def fcollect(self, x):
        """shmem_fcollect: concatenation of every PE's block."""
        return self.comm.allgather(x)

    def alltoall(self, x):
        return self.comm.alltoall(x)

    def collect(self, bufs):
        """shmem_collect: ragged per-PE blocks concatenated in PE
        order (fcollect's equal-size constraint lifted) — rides the
        v-variant allgatherv kernel."""
        return self.comm.allgatherv(bufs)

    def sum_to_all(self, x):
        return self.comm.allreduce(x, ops_mod.SUM)

    def prod_to_all(self, x):
        return self.comm.allreduce(x, ops_mod.PROD)

    def max_to_all(self, x):
        return self.comm.allreduce(x, ops_mod.MAX)

    def min_to_all(self, x):
        return self.comm.allreduce(x, ops_mod.MIN)

    def and_to_all(self, x):
        return self.comm.allreduce(x, ops_mod.BAND)

    def or_to_all(self, x):
        return self.comm.allreduce(x, ops_mod.BOR)

    def xor_to_all(self, x):
        return self.comm.allreduce(x, ops_mod.BXOR)

    # -- distributed locks (shmem_set_lock/clear_lock/test_lock) -----------
    def lock_create(self) -> SymmetricArray:
        """A SHMEM lock: a symmetric word, 0 = free, pe+1 = held by pe
        (``shmem.h.in:167`` lock surface; the reference's
        ``oshmem/mca/atomic`` backs its locks with the same AMOs).
        The lock word lives on its home PE (0), as in the reference's
        home-PE queue discipline — contenders CAS the home copy."""
        lk = self.malloc((1,), jnp.int32)
        return lk

    def set_lock(self, lock: SymmetricArray, *, pe: int,
                 timeout_s: float = 30.0) -> None:
        """Acquire: spin CAS(0 -> pe+1) on the home PE with backoff.
        Deadlock-by-self (re-acquiring a held lock) raises instead of
        hanging — driver mode can detect it, so it does."""
        import time as _time

        me = int(pe) + 1
        deadline = _time.monotonic() + timeout_s
        delay = 0.0005
        while True:
            old = int(np.asarray(
                self.atomic_compare_swap(lock, 0, me, pe=0)
            ).reshape(-1)[0])
            if old == 0:
                return
            if old == me:
                raise MPIError(
                    ErrorCode.ERR_OTHER,
                    f"PE {pe} already holds this lock (shmem locks are "
                    "not recursive)",
                )
            if _time.monotonic() > deadline:
                raise MPIError(
                    ErrorCode.ERR_PENDING,
                    f"set_lock: PE {old - 1} held the lock for "
                    f">{timeout_s}s",
                )
            _time.sleep(delay)
            delay = min(delay * 2, 0.01)

    def test_lock(self, lock: SymmetricArray, *, pe: int) -> bool:
        """One CAS attempt; True = acquired (shmem_test_lock's 0)."""
        old = int(np.asarray(
            self.atomic_compare_swap(lock, 0, int(pe) + 1, pe=0)
        ).reshape(-1)[0])
        return old == 0

    def clear_lock(self, lock: SymmetricArray, *, pe: int) -> None:
        """Release; only the holder may clear (erroneous otherwise in
        OpenSHMEM — detected here rather than corrupting the word)."""
        me = int(pe) + 1
        old = int(np.asarray(
            self.atomic_compare_swap(lock, me, 0, pe=0)
        ).reshape(-1)[0])
        if old != me:
            raise MPIError(
                ErrorCode.ERR_OTHER,
                f"clear_lock by PE {pe} but the lock is "
                + ("free" if old == 0 else f"held by PE {old - 1}"),
            )

    def finalize(self) -> None:
        for a in list(self._allocs):
            a.free()


_ctx: Optional[ShmemCtx] = None


def shmem_init(comm=None) -> ShmemCtx:
    """shmem_init: reuses the runtime (OSHMEM sits beside OMPI on the
    same ORTE, SURVEY §1.4)."""
    global _ctx
    if _ctx is not None:
        return _ctx
    if comm is None:
        from ..runtime import runtime as rt_mod

        comm = rt_mod.init()
    _ctx = ShmemCtx(comm)
    return _ctx


def shmem_finalize() -> None:
    global _ctx
    if _ctx is not None:
        _ctx.finalize()
        _ctx = None
