"""Pallas streaming reduction kernels — the accelerated op component.

The reference's reduction hot loop is a C elementwise loop per
(op x dtype) (``ompi/mca/op/base/op_base_functions.c``); its ``op`` MCA
framework exists so accelerated components can override those kernels
(``ompi/mca/op``). This is that component for TPU: hand-tiled Pallas
kernels for the HBM-bound streaming shapes where explicit VMEM blocking
reaches the memory ceiling.

Why Pallas here at all (SURVEY §7 step 5, "where XLA's built-ins
lose"): measured on a v5e chip, the XLA fori_loop axpy reaches the same
~780 GB/s as the Pallas kernel — but XLA is free to algebraically fold
repeated affine updates across loop iterations (acc*c+a twice =
acc*c^2 + (ac+a)), which silently turns a bandwidth benchmark into a
flops one. A ``pallas_call`` is opaque to XLA, so a timing loop over it
measures real HBM traffic every iteration. The bench (bench.py) uses
these kernels for exactly that reason; the op framework exposes them
for large contiguous f32/bf16 reductions.

Block-shape choice (measured, experiments/perf_probe3.py): the axpy
(read acc, read a, write acc -> 3 streams) peaks at (256, 2048) f32
blocks = 2 MiB per buffer, 3 buffers x double-buffering = 12 MiB of
VMEM; the 2-stream copy/scale kernel peaks at (2048, 512). Both land
within ~5% of the 819 GB/s v5e HBM ceiling.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

#: measured-optimal f32 block shapes (rows, cols)
AXPY_BLOCK: Tuple[int, int] = (256, 2048)
SCALE_BLOCK: Tuple[int, int] = (2048, 512)


def _interpret() -> bool:
    # CPU (tests, simulator mesh) runs the same kernels interpreted
    return jax.default_backend() != "tpu"


def _blocked_call(kernel, nin: int, rows: int, cols: int, blk_rows: int,
                  dtype):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if rows % blk_rows:
        # a truncated grid would silently skip the tail — fatal in a
        # bandwidth benchmark (unprocessed rows inflate the number)
        raise ValueError(
            f"rows ({rows}) must be a multiple of the block height "
            f"({blk_rows})"
        )
    spec = pl.BlockSpec((blk_rows, cols), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((rows, cols), dtype),
        grid=(rows // blk_rows,),
        in_specs=[spec] * nin,
        out_specs=spec,
        input_output_aliases={nin - 1: 0},
        interpret=_interpret(),
    )


def axpy(a: jax.Array, acc: jax.Array, c: float = 1.0) -> jax.Array:
    """acc*c + a as a tiled streaming kernel (the SUM/AXPY hot loop).

    Arrays must be equal-shape f32/bf16; arbitrary shapes are flattened
    and padded up to a whole number of blocks internally.
    """
    def kernel(a_ref, acc_ref, out_ref):
        out_ref[:] = acc_ref[:] * c + a_ref[:]

    return _apply_blocked(kernel, 2, AXPY_BLOCK, a, acc)


def scale(x: jax.Array, c: float) -> jax.Array:
    """x*c streaming (2-stream read+write: the copy-ceiling kernel)."""
    def kernel(x_ref, out_ref):
        out_ref[:] = x_ref[:] * c

    return _apply_blocked(kernel, 1, SCALE_BLOCK, x)


def _apply_blocked(kernel, nin: int, block: Tuple[int, int], *arrays):
    blk_rows, cols = block
    x0 = arrays[0]
    shape, dtype = x0.shape, x0.dtype
    n = x0.size
    rows = -(-n // cols)
    rows = -(-rows // blk_rows) * blk_rows  # whole blocks
    padded_n = rows * cols

    def prep(a):
        flat = a.reshape(-1)
        if padded_n != n:
            flat = jnp.concatenate(
                [flat, jnp.zeros((padded_n - n,), dtype)]
            )
        return flat.reshape(rows, cols)

    call = _blocked_call(kernel, nin, rows, cols, blk_rows, dtype)
    out = call(*[prep(a) for a in arrays])
    return out.reshape(-1)[:n].reshape(shape)


def make_axpy_loop(rows: int, cols: int, c: float = 0.999):
    """K-iteration benchmark loop over the axpy kernel (bench.py's
    measurement body: per-iteration traffic = 3 x rows x cols x 4 B)."""
    blk_rows = AXPY_BLOCK[0]

    def kernel(a_ref, acc_ref, out_ref):
        out_ref[:] = acc_ref[:] * c + a_ref[:]

    call = _blocked_call(kernel, 2, rows, cols, blk_rows, jnp.float32)

    @partial(jax.jit, static_argnums=1)
    def loop(a, k):
        def body(i, acc):
            return call(a, acc)

        acc = jax.lax.fori_loop(
            0, k, body, jnp.zeros((rows, cols), jnp.float32)
        )
        return acc[0, 0] + acc[-1, -1]  # 8-byte completion checksum

    return loop


def make_scale_loop(rows: int, cols: int, c: float = 1.0001):
    """K-iteration loop over the 2-stream scale kernel (the measured
    HBM copy ceiling: read + write per iteration)."""
    blk_rows = SCALE_BLOCK[0]

    def kernel(x_ref, out_ref):
        out_ref[:] = x_ref[:] * c

    call = _blocked_call(kernel, 1, rows, cols, blk_rows, jnp.float32)

    @partial(jax.jit, static_argnums=1)
    def loop(a, k):
        def body(i, acc):
            return call(acc)

        acc = jax.lax.fori_loop(0, k, body, a)
        return acc[0, 0] + acc[-1, -1]

    return loop
