"""Pallas streaming reduction kernels — the accelerated op component.

The reference's reduction hot loop is a C elementwise loop per
(op x dtype) (``ompi/mca/op/base/op_base_functions.c``); its ``op`` MCA
framework exists so accelerated components can override those kernels
(``ompi/mca/op``). This is that component for TPU: hand-tiled Pallas
kernels for the HBM-bound streaming shapes where explicit VMEM blocking
reaches the memory ceiling.

Why Pallas here at all (SURVEY §7 step 5, "where XLA's built-ins
lose"): measured on a v5e chip, the XLA fori_loop axpy reaches the same
~780 GB/s as the Pallas kernel — but XLA is free to algebraically fold
repeated affine updates across loop iterations (acc*c+a twice =
acc*c^2 + (ac+a)), which silently turns a bandwidth benchmark into a
flops one. A ``pallas_call`` is opaque to XLA, so a timing loop over it
measures real HBM traffic every iteration. The bench (bench.py) uses
these kernels for exactly that reason; the op framework exposes them
for large contiguous f32/bf16 reductions.

Block-shape choice (measured on the v5e chip, 2026-07; see also
experiments/perf_probe3.py): the axpy (read acc, read a, write acc ->
3 streams) peaks at (256, 2048) f32 blocks (~780 GB/s effective); the
2-stream copy/scale kernel peaks at SHORT, WIDE blocks — (128, 2048)
and (32, 8192) both measured 820-840 GB/s against the 819 GB/s v5e
spec, while the old tall (2048, 512) block plateaued at ~650. Caveat
that shaped bench.py's design: single-run bandwidth wobbles by +-20%
between runs on the tunneled chip (contention/thermal), so any
metric/ceiling ratio must interleave both measurements round-by-round
and report variance — a ceiling measured minutes apart is fiction.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from ..mca import component as mca_component

from ..utils import jaxcompat as _jaxcompat

_jaxcompat.install()  # jax.typeof/ShapeDtypeStruct-vma on 0.4.x jaxlibs

#: measured-optimal f32 block shapes (rows, cols)
AXPY_BLOCK: Tuple[int, int] = (256, 2048)
SCALE_BLOCK: Tuple[int, int] = (128, 2048)
#: second copy-ceiling candidate (also ~820-840 GB/s measured); the
#: bench measures both and takes the per-round max as the ceiling
SCALE_BLOCK_ALT: Tuple[int, int] = (32, 8192)
#: third candidate: a 2026-07 re-sweep measured the shortest/widest
#: block winning the copy kernel under that session's conditions
#: (679 vs 657/653 GB/s for the other two) — candidates exist so the
#: ceiling is the best the chip demonstrably does TODAY, whichever
#: shape that takes
SCALE_BLOCK_ALT2: Tuple[int, int] = (16, 16384)


def _interpret() -> bool:
    # CPU (tests, simulator mesh) runs the same kernels interpreted
    return jax.default_backend() != "tpu"


def _blocked_call(kernel, nin: int, rows: int, cols: int, blk_rows: int,
                  dtype, vma=frozenset()):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if rows % blk_rows:
        # a truncated grid would silently skip the tail — fatal in a
        # bandwidth benchmark (unprocessed rows inflate the number)
        raise ValueError(
            f"rows ({rows}) must be a multiple of the block height "
            f"({blk_rows})"
        )
    spec = pl.BlockSpec((blk_rows, cols), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel,
        # vma: inside shard_map the output varies across the mesh axes
        # its inputs vary over — propagated from the caller's tracers
        # (replication typing would otherwise reject the call)
        out_shape=jax.ShapeDtypeStruct((rows, cols), dtype, vma=vma),
        grid=(rows // blk_rows,),
        in_specs=[spec] * nin,
        out_specs=spec,
        input_output_aliases={nin - 1: 0},
        interpret=_interpret(),
    )


def axpy(a: jax.Array, acc: jax.Array, c: float = 1.0) -> jax.Array:
    """acc*c + a as a tiled streaming kernel (the SUM/AXPY hot loop).

    Arrays must be equal-shape f32/bf16; arbitrary shapes are flattened
    and padded up to a whole number of blocks internally.
    """
    def kernel(a_ref, acc_ref, out_ref):
        out_ref[:] = acc_ref[:] * c + a_ref[:]

    return _apply_blocked(kernel, 2, AXPY_BLOCK, a, acc)


def scale(x: jax.Array, c: float) -> jax.Array:
    """x*c streaming (2-stream read+write: the copy-ceiling kernel)."""
    def kernel(x_ref, out_ref):
        out_ref[:] = x_ref[:] * c

    return _apply_blocked(kernel, 1, SCALE_BLOCK, x)


def _apply_blocked(kernel, nin: int, block: Tuple[int, int], *arrays):
    blk_rows, cols = block
    x0 = arrays[0]
    shape, dtype = x0.shape, x0.dtype
    n = x0.size
    rows = -(-n // cols)
    # never pad a short input up to the full tuned block height — cap
    # the block at the data, but not below Mosaic's minimum sublane
    # tile (8 for 4-byte types, 16 for bf16's packed (16, 128) tile)
    min_rows = 16 if jnp.dtype(dtype) == jnp.bfloat16 else 8
    blk_rows = max(min_rows, min(blk_rows, rows))
    rows = -(-rows // blk_rows) * blk_rows  # whole blocks
    padded_n = rows * cols

    def prep(a):
        flat = a.reshape(-1)
        if padded_n != n:
            from ..parallel.mesh_axes import vary_like

            # pad zeros must carry the data's varying-axis type or the
            # concat (and the kernel) fail shard_map's vma check
            flat = jnp.concatenate(
                [flat, vary_like(jnp.zeros((padded_n - n,), dtype),
                                 flat)]
            )
        return flat.reshape(rows, cols)

    prepped = [prep(a) for a in arrays]
    vma = frozenset()
    for p in prepped:  # union: any varying input makes the out vary
        vma = vma | getattr(jax.typeof(p), "vma", frozenset())
    call = _blocked_call(kernel, nin, rows, cols, blk_rows, dtype,
                         vma=vma)
    out = call(*prepped)
    return out.reshape(-1)[:n].reshape(shape)


# ---------------------------------------------------------------------------
# op-framework component: the accelerated override the framework exists
# for (``ompi/mca/op`` — accelerated components outrank the base C
# loops and claim the shapes they beat them on)
# ---------------------------------------------------------------------------

def _pallas_sum_fn(a, b):
    """a + b as the tiled 3-stream streaming kernel: explicit VMEM
    blocking at the measured-optimal axpy block shape. Equal shapes
    only — exactly what collective local-reduction steps pass. No
    scalar constant in the kernel body (a literal's empty varying-axis
    type would clash with ref reads under shard_map's vma tracking)."""
    def kernel(a_ref, b_ref, out_ref):
        out_ref[:] = b_ref[:] + a_ref[:]

    return _apply_blocked(kernel, 2, AXPY_BLOCK, a, b)


_pallas_sum_op = None


def make_pallas_sum():
    # ONE Op instance for the component's lifetime: program caches key
    # compiled collectives by the op OBJECT, so a fresh Op per lookup
    # would recompile on every resolved call
    global _pallas_sum_op
    if _pallas_sum_op is None:
        from .op import Op

        _pallas_sum_op = Op("sum[pallas]", _pallas_sum_fn,
                            commutative=True, identity=lambda d: 0,
                            lax_collective=None)
    return _pallas_sum_op


class PallasOpComponent(mca_component.Component):
    """Claims large contiguous f32/bf16 SUM reductions; everything else
    falls through to the xla component. The threshold is the measured
    crossover where explicit blocking stops being noise against the
    compiler's fusion (small arrays are latency-bound; the kernel's
    padding to whole blocks would dominate)."""

    NAME = "pallas"
    PRIORITY = 20  # outranks xla (10): queried first, claims narrowly

    def register_vars(self) -> None:
        from ..mca import var as mca_var

        mca_var.register(
            "op_pallas_threshold", "size", 4 * 1024 * 1024,
            "Minimum reduction size in bytes for the pallas streaming "
            "SUM kernel to claim the op (below it, XLA fusion wins)",
        )

    def lookup(self, name: str, dtype=None, nbytes: int = 0):
        from ..mca import var as mca_var

        if name != "sum" or dtype is None:
            return None
        if str(jnp.dtype(dtype)) not in ("float32", "bfloat16"):
            return None
        if nbytes < int(mca_var.get("op_pallas_threshold",
                                    4 * 1024 * 1024)):
            return None
        return make_pallas_sum()


def make_axpy_loop(rows: int, cols: int, c: float = 0.999,
                   blk_rows: int = None, dtype=jnp.float32):
    """K-iteration benchmark loop over the axpy kernel (bench.py's
    measurement body: per-iteration traffic = 3 x rows x cols x
    itemsize). ``blk_rows`` overrides the tuned block height for
    small-message sweep points whose whole array is below one block."""
    if blk_rows is None:
        blk_rows = min(AXPY_BLOCK[0], rows)

    def kernel(a_ref, acc_ref, out_ref):
        out_ref[:] = acc_ref[:] * c + a_ref[:]

    call = _blocked_call(kernel, 2, rows, cols, blk_rows, dtype)

    @partial(jax.jit, static_argnums=1)
    def loop(a, k):
        def body(i, acc):
            return call(a, acc)

        acc = jax.lax.fori_loop(
            0, k, body, jnp.zeros((rows, cols), dtype)
        )
        return acc[0, 0] + acc[-1, -1]  # 8-byte completion checksum

    return loop


def make_scale_loop(rows: int, cols: int, c: float = 1.0001,
                    blk_rows: int = None, dtype=jnp.float32):
    """K-iteration loop over the 2-stream scale kernel (the measured
    HBM copy ceiling: read + write per iteration)."""
    if blk_rows is None:
        blk_rows = min(SCALE_BLOCK[0], rows)

    def kernel(x_ref, out_ref):
        out_ref[:] = x_ref[:] * c

    call = _blocked_call(kernel, 1, rows, cols, blk_rows, dtype)

    @partial(jax.jit, static_argnums=1)
    def loop(a, k):
        def body(i, acc):
            return call(acc)

        acc = jax.lax.fori_loop(0, k, body, a)
        return acc[0, 0] + acc[-1, -1]

    return loop


def make_transpose_loop(n: int, block: int = 256, dtype=jnp.int32):
    """K-iteration loop over a blocked (n, n) transpose — the
    single-chip analogue of the 2-D-torus MPI_Alltoall shuffle
    (BASELINE config 5): every (i, j) block moves to (j, i), all-pairs
    data movement through HBM.

    The loop body applies the transpose TWICE, 4 streams (2 reads + 2
    writes of the full array) per iteration, and callers must count
    ``4 * n * n * itemsize`` bytes.  Why: a ``fori_loop`` carry lives
    in a FIXED buffer across iterations (XLA while-loop buffer
    assignment), so a single non-aliased kernel per iteration forces
    XLA to copy its fresh output back into the carry buffer — 2N
    uncounted extra bytes that halved the reported bandwidth for three
    rounds (the r03 "alltoall at 0.49 of ceiling" gap was exactly
    this, probes 5-7: square blocks, run length, 1-D vs 2-D grids all
    measured identical; only aliasing moved the number).  With two
    calls per body, call #1's input buffer is dead when call #2 runs,
    XLA reuses it for #2's output, the carry address is stable and no
    copy is inserted — measured at copy-ceiling parity.  A same-buffer
    blocked transpose cannot use ``input_output_aliases`` directly
    (block (j, i) would be clobbered before grid step (j, i) reads
    it), which is why the scale/axpy kernels alias and this one
    double-applies instead.  XLA cannot fold T(T(x)) = x across the
    two calls: a pallas_call is opaque."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if n % block:
        raise ValueError(f"n ({n}) must be a multiple of block ({block})")

    def kernel(x_ref, out_ref):
        out_ref[:] = x_ref[:].T

    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, n), dtype),
        grid=(n // block, n // block),
        in_specs=[pl.BlockSpec((block, block), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((block, block), lambda i, j: (j, i),
                               memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )

    @partial(jax.jit, static_argnums=1)
    def loop(a, k):
        def body(i, acc):
            return call(call(acc))

        acc = jax.lax.fori_loop(0, k, body, a)
        return acc[0, 0] + acc[-1, -1]

    return loop, call


def make_chain_loop(hops: int = 4, dtype=jnp.float32):
    """K-iteration loop over ``hops`` serially-dependent tiny (8, 128)
    kernels — the single-chip analogue of examples/ring_c.c's 4-rank
    token ring (each hop = one kernel dispatch, data-dependent on the
    previous). Slope / hops = per-hop launch+HBM-roundtrip latency."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    spec = pl.BlockSpec((8, 128), lambda: (0, 0),
                        memory_space=pltpu.VMEM)

    def kernel(x_ref, out_ref):
        out_ref[:] = x_ref[:] + 1

    call = pl.pallas_call(
        kernel, out_shape=jax.ShapeDtypeStruct((8, 128), dtype),
        in_specs=[spec], out_specs=spec, interpret=_interpret(),
    )

    @partial(jax.jit, static_argnums=1)
    def loop(a, k):
        def body(i, acc):
            for _ in range(hops):
                acc = call(acc)
            return acc

        acc = jax.lax.fori_loop(0, k, body, a)
        return acc[0, 0] + acc[-1, -1]

    return loop
