"""Pallas flash-attention kernel — the hand-scheduled hot op.

The one place XLA's automatic fusion loses to hand scheduling in this
framework's model stack is attention: materializing (S, S) scores is
HBM-bound, while a blocked kernel keeps the working set in VMEM and
streams K/V blocks through the MXU with an online softmax. This is the
``op`` framework's accelerated-component story (SURVEY §2.3: "op MCA
framework exists for accelerated overrides") applied where it matters.

Layout: q/k/v are (H, S, D). Grid = (H, S/block_q); each program owns
one query block, loops over key blocks with running (max, sumexp)
statistics in f32. Backward is a custom VJP that recomputes with the
pure-jnp reference (flash recompute strategy: no (S, S) residuals).

``interpret=True`` runs the same kernel on CPU for CI (the simulator
backend strategy of SURVEY §4).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, seq_k: int,
                 causal: bool, block_q: int):
    """One (head, q-block) program: stream K/V blocks, online softmax."""
    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # (block_q, D)
    d = q.shape[-1]
    scale = jax.lax.rsqrt(jnp.float32(d))
    q = q * scale

    nk = pl.cdiv(seq_k, block_k)
    q_pos = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(jk, carry):
        acc, row_m, row_l = carry
        k_blk = k_ref[0, pl.ds(jk * block_k, block_k), :].astype(
            jnp.float32
        )
        v_blk = v_ref[0, pl.ds(jk * block_k, block_k), :].astype(
            jnp.float32
        )
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        k_pos = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_pos < seq_k  # tail padding
        if causal:
            mask = mask & (q_pos >= k_pos)
        s = jnp.where(mask, s, NEG_INF)
        m = jnp.maximum(row_m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m[:, None])
        alpha = jnp.exp(row_m - m)
        acc = acc * alpha[:, None] + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32
        )
        row_l = row_l * alpha + jnp.sum(p, axis=-1)
        return acc, m, row_l

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, _, row_l = jax.lax.fori_loop(0, nk, body, (acc0, m0, l0))
    out = acc / jnp.maximum(row_l, 1e-30)[:, None]
    o_ref[0] = out.astype(o_ref.dtype)


def _flash_forward(q, k, v, *, causal: bool, block_q: int, block_k: int,
                   interpret: bool):
    h, s, d = q.shape
    bq = min(block_q, s)
    bk = min(block_k, s)
    nq = pl.cdiv(s, bq)
    nk = pl.cdiv(s, bk)
    # pad both sequence axes to whole blocks: a dynamic slice whose
    # start exceeds the buffer gets CLAMPED, which would silently read
    # the wrong K/V rows on the final partial block
    pad_q = nq * bq - s
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0)))
    pad_k = nk * bk - s
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0)))
    sk = s + pad_k

    kernel = functools.partial(
        _attn_kernel, block_k=bk, seq_k=s, causal=causal, block_q=bq,
    )
    out = pl.pallas_call(
        kernel,
        grid=(h, nq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda ih, iq: (ih, iq, 0)),
            pl.BlockSpec((1, sk, d), lambda ih, iq: (ih, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda ih, iq: (ih, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda ih, iq: (ih, iq, 0)),
        # under shard_map's replication tracking the kernel output
        # varies over the same manual axes as its inputs
        out_shape=jax.ShapeDtypeStruct(
            (h, nq * bq, d), q.dtype,
            vma=getattr(jax.typeof(q), "vma", frozenset()),
        ),
        interpret=interpret,
    )(q, k, v)
    return out[:, :s, :]


def _reference(q, k, v, causal: bool):
    d = q.shape[-1]
    s = jnp.einsum("hqd,hkd->hqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * jax.lax.rsqrt(jnp.float32(d))
    if causal:
        n = q.shape[1]
        i = jnp.arange(n)
        s = jnp.where(i[:, None] >= i[None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("hqk,hkd->hqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False, block_q: int = 128,
                    block_k: int = 128,
                    interpret: Optional[bool] = None):
    """Blocked attention. q/k/v: (H, S, D); returns (H, S, D).

    ``interpret=None`` auto-selects: compiled on TPU, interpreter
    elsewhere (CI parity runs).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, causal=causal, block_q=block_q,
                          block_k=block_k, interpret=interpret)


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    out = flash_attention(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _bwd(causal, block_q, block_k, interpret, res, g):
    # flash recompute strategy: the backward re-derives the softmax from
    # q/k/v (no (S,S) residuals stored); jnp reference keeps it exact
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _reference(q, k, v, causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
