"""Simulated-fleet scale harness — the real stack at P=256-4096 over
a virtual wire.

Everything in this repo was proven at 3-8 processes; the O(log P)
round claims of ``coll/hier_schedules.py``, the PR 9 ULFM recovery
storms, and the PR 10 sentinel forensics were all built for fleet
scale and tested at toy scale. This module closes that gap without
hardware: an in-process virtual fleet that drives the *unmodified*
production code —

- the pure round schedules of :mod:`..coll.hier_schedules`, through
  the exact ``_XchgAdapter`` exchange contract (all of a round's
  sends posted before any receive parks);
- the ULFM failure picture of :mod:`..ft.ulfm` — one real
  :class:`~..ft.ulfm.FtState` per simulated rank, fed coordinator
  notice documents through ``apply_notice``, poisoned through
  ``apply_revoke``, and consulted by every bounded virtual-wire wait
  through ``check_wait`` (the production hot-path discipline);
- the contract-sentinel chain hashing of :mod:`..obs.sentinel` — a
  per-rank rolling chain folded by the production
  :class:`~..obs.sentinel.CallSig`, journaled in the exact span shape
  ``tpu-doctor contracts`` aligns —

at hundreds to thousands of ranks, one thread per rank, no processes,
no devices, no jax.

**The virtual wire.** :class:`Fabric` models per-link latency,
bandwidth, and loss over a host topology (co-hosted ranks ride the
intra/shm link class, cross-host ranks the inter/DCN class; per-link
overrides, slow-NIC straggler multipliers, and rank-set partitions
compose on top). Time is a deterministic VIRTUAL clock: each rank
owns ``now``; a message sent at ``t`` arrives at ``t + latency +
nbytes/bandwidth`` (+ deterministic seeded retransmit penalties for
lossy links, + hold-until-heal for partition windows), and a receive
advances the receiver to ``max(now, arrival)``. Because every arrival
is a pure function of the sender's clock and the fabric parameters —
never of OS thread scheduling — per-rank clocks, the metrology, and
the event log are bit-identical across runs: seeded chaos replays are
reproducible evidence, not flaky approximations.

**Failure semantics.** Deaths are staged (``kill(p, at_round=k)``:
the rank dies at the start of its k-th exchange). A dying rank
registers an exit record carrying its precomputed coordinator notice
(epoch-stamped cumulative failed sets, the TAG_PROC_FAILED document
shape); an erroring rank revokes its communicator locally (the ULFM
errhandler pattern) and registers the revoke. A waiter whose awaited
queue stays empty consults the sender's exit record, folds the notice
/ revoke into its OWN FtState via the real ``apply_notice`` /
``apply_revoke``, and lets the real ``check_wait`` raise the typed
error — ``ERR_PROC_FAILED`` at the direct detector,
``ERR_REVOKED`` downstream — so a single staged death cascades into
exactly the revoke storm PR 9 ships, at any P.

**Metrology.** Per rank: exchange rounds, messages, bytes,
inter-host (DCN-crossing) bytes, loss retransmits, and the virtual
clock. A :meth:`FleetSim.run` returns a :class:`RunReport` of
per-run deltas, so tests assert the actual scaling curves (bcast
root sends = ceil(log2 P), recursive-doubling rounds = ceil(log2 P),
Rabenseifner inter-process send bytes/rank = 2n(P-1)/P — every
simulated rank is one process, so ``bytes_sent`` is exactly the
``hier_inter_bytes`` quantity of the real spanning collectives,
while ``inter_bytes_sent`` separately counts the host-crossing
subset) and ``bench.py``'s ``fleet_scaling`` suite emits them as
gate-guarded ``sim_*`` metric lines.

**Forensics.** Per-rank span journals (sentinel signatures, ft
events, coll rounds) dump as ``journal-p*.json`` files in the exact
shape ``obs/doctor.py`` merges — ``tpu-doctor contracts`` and the
``report`` incident timeline work on a 256-rank simulated desync the
same way they work on a 3-process real one.
"""

from __future__ import annotations

import json
import math
import os
import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..ft.ulfm import FtState
from ..obs import sentinel as _sentinel
from ..obs.journal import flow_id
from ..utils.errors import ErrorCode, MPIError

#: thread stack size for rank threads: schedules are shallow pure
#: Python + numpy, and 4096 default (8 MiB) stacks would be wasteful
THREAD_STACK = 1 << 20


class SimHang(RuntimeError):
    """A virtual-wire wait that can never complete and has no FT story
    — the simulator's watchdog: a real desync/harness bug, reported
    loudly instead of parking forever."""


class _RankKilled(BaseException):
    """Internal control flow for a staged death (BaseException so no
    schedule-level ``except Exception`` can swallow a death)."""


# ---------------------------------------------------------------------------
# fabric: links, hosts, partitions
# ---------------------------------------------------------------------------


class LinkSpec:
    """One directed link class: latency (s), bandwidth (GB/s), loss
    probability per message (modelled as deterministic retransmit
    penalties — the real wire is reliable, loss costs time)."""

    __slots__ = ("latency_s", "bytes_per_s", "loss")

    def __init__(self, latency_s: float, gb_per_s: float,
                 loss: float = 0.0) -> None:
        self.latency_s = float(latency_s)
        self.bytes_per_s = float(gb_per_s) * 1e9
        self.loss = float(loss)


#: co-hosted ranks: the shm-class link
DEFAULT_INTRA = ("intra", 1e-6, 100.0, 0.0)
#: cross-host ranks: the DCN-class link
DEFAULT_INTER = ("inter", 25e-6, 12.5, 0.0)


class Fabric:
    """The virtual wire: host topology + per-link delivery model.

    ``hosts_per`` groups ranks into hosts of that size (rank p lives
    on host ``h{p // hosts_per}``); ``host_of`` overrides with an
    explicit rank->host map. Per-link overrides (:meth:`set_link`),
    slow-NIC multipliers (:meth:`slow_nic`), and rank-set partition
    windows (:meth:`partition`) compose over the two link classes.
    Delivery times are pure functions of (src, dst, nbytes, send
    time, per-pair message index) — deterministic by construction.
    """

    def __init__(self, P: int, hosts_per: Optional[int] = None,
                 host_of: Optional[Dict[int, str]] = None,
                 intra: Optional[LinkSpec] = None,
                 inter: Optional[LinkSpec] = None,
                 seed: int = 0, rto_s: float = 1e-3) -> None:
        self.P = int(P)
        if host_of is None:
            per = int(hosts_per) if hosts_per else self.P
            host_of = {p: f"h{p // per}" for p in range(self.P)}
        self.host_of = dict(host_of)
        self.intra = intra or LinkSpec(*DEFAULT_INTRA[1:])
        self.inter = inter or LinkSpec(*DEFAULT_INTER[1:])
        self.seed = int(seed)
        self.rto_s = float(rto_s)
        self._overrides: Dict[Tuple[int, int], LinkSpec] = {}
        self._nic: Dict[int, float] = {}
        self._bw_share: Dict[int, float] = {}
        #: (ranks_a, ranks_b, t0, t1-or-None) partition windows
        self._partitions: List[Tuple[frozenset, frozenset,
                                     float, Optional[float]]] = []

    # -- topology ----------------------------------------------------------
    def host(self, p: int) -> str:
        return self.host_of.get(p, f"h{p}")

    def crosses_host(self, s: int, d: int) -> bool:
        return self.host(s) != self.host(d)

    def hosts(self) -> Dict[str, List[int]]:
        out: Dict[str, List[int]] = {}
        for p in sorted(self.host_of):
            out.setdefault(self.host_of[p], []).append(p)
        return out

    # -- shaping -----------------------------------------------------------
    def set_link(self, s: int, d: int, spec: LinkSpec) -> None:
        self._overrides[(s, d)] = spec

    def slow_nic(self, p: int, factor: float) -> None:
        """Straggler injection: every link touching ``p`` gets
        ``factor``x the latency and 1/``factor`` the bandwidth."""
        self._nic[p] = float(factor)

    def bandwidth_share(self, p: int, share: float) -> None:
        """QoS contention model: rank ``p``'s sends see ``share`` of
        the link bandwidth (latency untouched). This is how the
        multi-tenant scenarios model a saturated shared wire under
        the weighted-fair arbiter: each class's ranks get exactly
        their fair-share fraction (``service.qos.fair_share``) of
        every link they send on — deterministic, so virtual clocks
        stay replayable."""
        self._bw_share[p] = max(1e-6, float(share))

    def partition(self, ranks_a, ranks_b, t0: float,
                  t1: Optional[float] = None) -> None:
        """Sever the (a <-> b) links for sends departing in
        [t0, t1): a finite ``t1`` holds crossing messages in the
        switch until the heal (arrival >= t1), ``t1=None`` black-holes
        them — the receiver's bounded wait then fails typed."""
        self._partitions.append((frozenset(int(p) for p in ranks_a),
                                 frozenset(int(p) for p in ranks_b),
                                 float(t0),
                                 None if t1 is None else float(t1)))

    # -- delivery ----------------------------------------------------------
    def link(self, s: int, d: int) -> Tuple[float, float, float]:
        spec = self._overrides.get((s, d))
        if spec is None:
            spec = self.intra if not self.crosses_host(s, d) else \
                self.inter
        f = self._nic.get(s, 1.0) * self._nic.get(d, 1.0)
        share = self._bw_share.get(s, 1.0)
        return (spec.latency_s * f, spec.bytes_per_s / f * share,
                spec.loss)

    def delivery(self, s: int, d: int, nbytes: int, t_send: float,
                 k: int) -> Tuple[Optional[float], int]:
        """(arrival virtual time | None if black-holed, retransmit
        count). Loss draws come from the process-independent FNV fold
        (``obs.journal.flow_id``) over (seed, s, d, k, try) — the same
        message loses the same number of times on every run."""
        lat, bps, loss = self.link(s, d)
        dt = lat + nbytes / bps
        retx = 0
        if loss > 0.0:
            loss = min(loss, 0.95)
            while retx < 64 and (
                    flow_id("fleetsim-loss", self.seed, s, d, k, retx)
                    / 2.0 ** 64) < loss:
                retx += 1
            dt += retx * self.rto_s
        arrival = t_send + dt
        for (a, b, t0, t1) in self._partitions:
            if t0 <= t_send and (t1 is None or t_send < t1) and \
                    ((s in a and d in b) or (s in b and d in a)):
                if t1 is None:
                    return None, retx
                arrival = max(arrival, t1 + lat)
        return arrival, retx


# ---------------------------------------------------------------------------
# per-rank state
# ---------------------------------------------------------------------------


class _RankState:
    __slots__ = ("p", "now", "rounds", "msgs_sent", "msgs_recvd",
                 "bytes_sent", "bytes_recvd", "inter_bytes_sent",
                 "loss_retx", "alive", "ft", "sent", "spans",
                 "msg_k", "ev_seq")

    def __init__(self, p: int) -> None:
        self.p = p
        self.now = 0.0
        self.rounds = 0
        self.msgs_sent = 0
        self.msgs_recvd = 0
        self.bytes_sent = 0
        self.bytes_recvd = 0
        self.inter_bytes_sent = 0
        self.loss_retx = 0
        self.alive = True
        self.ft = FtState()          # the REAL ULFM failure picture
        self.sent: Dict[int, Tuple[int, int]] = {}  # cid -> (seq, chain)
        self.spans: List[Dict] = []  # journal-shaped span dicts
        self.msg_k: Dict[int, int] = {}
        self.ev_seq = 0

    def snap(self) -> Tuple[float, int, int, int, int, int, int]:
        return (self.now, self.rounds, self.msgs_sent, self.msgs_recvd,
                self.bytes_sent, self.inter_bytes_sent, self.loss_retx)


class RunReport:
    """Per-run metrology deltas — what the scaling assertions and the
    ``fleet_scaling`` bench lines read."""

    def __init__(self, participants: List[int], outcomes: Dict,
                 start: Dict, end: Dict) -> None:
        self.participants = participants
        self.outcomes = outcomes
        self.rounds = {p: end[p][1] - start[p][1] for p in participants}
        self.msgs_sent = {p: end[p][2] - start[p][2]
                          for p in participants}
        self.msgs_recvd = {p: end[p][3] - start[p][3]
                           for p in participants}
        self.bytes_sent = {p: end[p][4] - start[p][4]
                           for p in participants}
        self.inter_bytes_sent = {p: end[p][5] - start[p][5]
                                 for p in participants}
        self.loss_retx = {p: end[p][6] - start[p][6]
                          for p in participants}
        self.makespan = (max(end[p][0] for p in participants)
                         - min(start[p][0] for p in participants))

    def ok(self) -> List[int]:
        return sorted(p for p, (k, _) in self.outcomes.items()
                      if k == "ok")

    def errored(self) -> List[int]:
        return sorted(p for p, (k, _) in self.outcomes.items()
                      if k == "error")

    def killed(self) -> List[int]:
        return sorted(p for p, (k, _) in self.outcomes.items()
                      if k == "killed")

    def value(self, p: int):
        kind, val = self.outcomes[p]
        if kind != "ok":
            raise AssertionError(f"rank {p} outcome {kind}: {val}")
        return val

    def max_rounds(self) -> int:
        return max(self.rounds.values())

    def min_rounds(self) -> int:
        return min(self.rounds.values())

    def max_bytes_sent(self) -> int:
        return max(self.bytes_sent.values())

    def total_msgs(self) -> int:
        return sum(self.msgs_sent.values())


# ---------------------------------------------------------------------------
# the exchange adapter (the _XchgAdapter contract over the fabric)
# ---------------------------------------------------------------------------


class FleetXchg:
    """One rank's exchange endpoint on one communicator: the adapter
    :mod:`..coll.hier_schedules` drives. Checks the rank's real
    FtState before posting and inside every bounded receive wait —
    the production wire-wait discipline."""

    __slots__ = ("fleet", "me", "cid", "epoch0")

    def __init__(self, fleet: "FleetSim", me: int, cid: int = 1,
                 epoch0: int = 0) -> None:
        self.fleet = fleet
        self.me = me
        self.cid = cid
        self.epoch0 = epoch0

    def exchange(self, sends: Dict[int, list],
                 recvs: Dict[int, int]) -> Dict[int, list]:
        fleet = self.fleet
        r = fleet.ranks[self.me]
        fleet._check_death(r)
        peers = sorted(p for p, c in recvs.items() if int(c) > 0)
        # entry check: a rank that already learned of a death/revoke
        # must not post into a poisoned round (ULFM bounded-wait rule)
        r.ft.check_wait(self.cid, peers, what="schedule round",
                        epoch0=self.epoch0)
        for dst, arrs in sends.items():
            for a in arrs:
                fleet._send(r, int(dst), np.asarray(a), self.cid)
        got: Dict[int, list] = {p: [] for p in recvs}
        for src in peers:
            for _ in range(int(recvs[src])):
                got[src].append(
                    fleet._recv(r, src, self.cid, self.epoch0))
        r.rounds += 1
        return got


# ---------------------------------------------------------------------------
# the fleet
# ---------------------------------------------------------------------------


class FleetSim:
    """P simulated ranks over a :class:`Fabric`, one thread per rank
    only while a :meth:`run` is in flight. All virtual-time outputs
    (clocks, metrology, event log, journals) are deterministic
    functions of (schedule, fabric, staged chaos) — never of thread
    timing."""

    def __init__(self, P: int, *, hosts_per: Optional[int] = None,
                 fabric: Optional[Fabric] = None, seed: int = 0,
                 detect_s: float = 2e-3, slice_s: float = 15.0,
                 real_timeout_s: float = 60.0) -> None:
        self.P = int(P)
        self.procs = list(range(self.P))
        self.fabric = fabric or Fabric(self.P, hosts_per=hosts_per,
                                       seed=seed)
        self.detect_s = float(detect_s)
        self.slice_s = float(slice_s)
        self.real_timeout_s = float(real_timeout_s)
        self.ranks = {p: _RankState(p) for p in self.procs}
        self._queues: Dict[Tuple[int, int, int], queue.Queue] = {}
        self._qlock = threading.Lock()
        self._exit: Dict[int, Dict] = {}
        self._death_doc: Dict[int, Tuple[int, Dict]] = {}
        self._die_round: Dict[int, int] = {}
        self._events: List[Tuple[float, int, int, str, Dict]] = []
        self._evlock = threading.Lock()

    # -- chaos staging -----------------------------------------------------
    def kill(self, p: int, at_round: int) -> None:
        """Stage rank ``p``'s death at the start of its ``at_round``-th
        exchange (1-based). Epochs are assigned in staging order; the
        death carries the coordinator's cumulative TAG_PROC_FAILED
        document, exactly what the real HNP pushes."""
        if p in self._death_doc:
            raise ValueError(f"rank {p} already staged to die")
        epoch = len(self._death_doc) + 1
        failed_at = {q: e for q, (e, _) in self._death_doc.items()}
        failed_at[int(p)] = epoch
        doc = {"epoch": epoch, "failed": sorted(failed_at),
               "restarted": [], "rejoined": [],
               "failed_at": {str(q): e for q, e in failed_at.items()}}
        self._death_doc[int(p)] = (epoch, doc)
        self._die_round[int(p)] = int(at_round)

    def final_notice(self) -> Optional[Dict]:
        """The coordinator's authoritative post-chaos failure document
        (the newest staged death's cumulative snapshot) — what the
        recovery agreement pushes to every survivor."""
        if not self._death_doc:
            return None
        return max(self._death_doc.values(), key=lambda t: t[0])[1]

    # -- plumbing ----------------------------------------------------------
    def xchg(self, p: int, cid: int = 1, epoch0: int = 0) -> FleetXchg:
        return FleetXchg(self, p, cid, epoch0)

    def _queue(self, s: int, d: int, cid: int) -> queue.Queue:
        key = (cid, s, d)
        q = self._queues.get(key)
        if q is None:
            with self._qlock:
                q = self._queues.setdefault(key, queue.Queue())
        return q

    def _event(self, r: _RankState, kind: str, **kv) -> None:
        r.ev_seq += 1
        with self._evlock:
            self._events.append((r.now, r.p, r.ev_seq, kind, kv))

    def event_log(self) -> List[Dict]:
        """All events so far, sorted on (virtual time, rank, per-rank
        seq) — a deterministic total order, identical across replays
        of one seeded scenario."""
        with self._evlock:
            evs = sorted(self._events)
        return [dict(t=t, pidx=p, seq=s, kind=k, **kv)
                for (t, p, s, k, kv) in evs]

    def event_log_json(self) -> str:
        return json.dumps(self.event_log(), sort_keys=True)

    def _check_death(self, r: _RankState) -> None:
        die = self._die_round.get(r.p)
        if die is not None and r.rounds >= die - 1:
            raise _RankKilled()

    def _send(self, r: _RankState, dst: int, arr: np.ndarray,
              cid: int) -> None:
        k = r.msg_k.get(dst, 0)
        r.msg_k[dst] = k + 1
        nbytes = int(arr.nbytes)
        arrival, retx = self.fabric.delivery(r.p, dst, nbytes, r.now, k)
        r.msgs_sent += 1
        r.bytes_sent += nbytes
        r.loss_retx += retx
        if self.fabric.crosses_host(r.p, dst):
            r.inter_bytes_sent += nbytes
        if arrival is None:
            # black-holed by an unhealed partition: the receiver's
            # bounded wait fails typed after the detection interval
            self._queue(r.p, dst, cid).put(("void", r.now, None))
        else:
            self._queue(r.p, dst, cid).put(("msg", arrival, arr))

    def _recv(self, r: _RankState, src: int, cid: int,
              epoch0: int) -> np.ndarray:
        q = self._queue(src, r.p, cid)
        deadline = time.monotonic() + self.real_timeout_s
        while True:
            try:
                # park slices exist only as a SimHang safety net: an
                # exiting rank wakes its waiters with explicit exit
                # markers, so a healthy fleet never times out here —
                # which is what keeps thousands of parked threads from
                # thrashing one GIL with spurious timed wakeups
                kind, vt, payload = q.get(timeout=self.slice_s)
            except queue.Empty:
                info = self._exit.get(src)
                if info is not None:
                    # belt-and-braces: the sender exited (its marker
                    # may sit on a queue we had not created yet when
                    # it was broadcast) — fold its exit story and let
                    # the real check_wait raise the typed ULFM error
                    self._fold_exit(r, src, info, cid, epoch0)
                if time.monotonic() > deadline:
                    raise SimHang(
                        f"rank {r.p}: recv from {src} on cid {cid} "
                        f"parked past {self.real_timeout_s}s real "
                        f"time (virtual now {r.now:.6f})")
                continue
            if kind == "msg":
                r.msgs_recvd += 1
                r.bytes_recvd += int(payload.nbytes)
                r.now = max(r.now, vt)
                return payload
            if kind == "exit":
                # every message the sender ever posted on this pair
                # precedes its marker (program order), so detection
                # is deterministic: drain, then learn why it exited
                self._fold_exit(r, src, payload, cid, epoch0)
                continue  # pragma: no cover - _fold_exit raises
            # "void": sent into a severed link, can never arrive
            r.now = max(r.now, vt + self.detect_s)
            self._event(r, "unreachable", peer=src)
            raise MPIError(
                ErrorCode.ERR_UNREACH,
                f"recv from process {src}: virtual wire partitioned "
                f"with no heal (send at t={vt:.6f})")

    def _apply_notice(self, r: _RankState, doc: Dict,
                      vt: float) -> None:
        """Fold one coordinator failure document into rank ``r``'s
        real FtState, journaling each NEWLY learned failure the way
        the production emitter does (layer ft, peer=failed pidx,
        comm=epoch)."""
        pre = set(r.ft.failed_at)
        r.ft.apply_notice(doc)          # the real parser/monotonicity
        for q in sorted(set(r.ft.failed_at) - pre):
            r.spans.append({"seq": len(r.spans), "op": "ft_failure",
                            "layer": "ft", "t": vt, "dt": 0.0,
                            "bytes": 0, "peer": int(q),
                            "comm": int(r.ft.epoch)})
            self._event(r, "learned_failure", failed=int(q),
                        epoch=int(r.ft.epoch))

    def _apply_revoke(self, r: _RankState, cid: int,
                      epoch: int, vt: float) -> None:
        if r.ft.apply_revoke(cid, epoch):   # the real poison fold
            r.spans.append({"seq": len(r.spans), "op": "ft_revoke",
                            "layer": "ft", "t": vt, "dt": 0.0,
                            "bytes": 0, "peer": int(epoch),
                            "comm": int(cid)})
            self._event(r, "revoke", cid=int(cid), epoch=int(epoch))

    def _fold_exit(self, r: _RankState, src: int, info: Dict,
                   cid: int, epoch0: int) -> None:
        """The awaited sender exited: learn why through the real ULFM
        state machine and raise its typed error. Raises SimHang when
        the exit has no FT story this comm can see (a genuine desync:
        the sender finished a different call stream)."""
        vt = max(r.now, float(info["vt"]) + self.detect_s)
        r.now = vt
        notice = info.get("notice")
        if notice:
            self._apply_notice(r, notice, vt)
        for c in info.get("revoked", ()):
            self._apply_revoke(r, int(c), int(info.get("epoch", -1)),
                               vt)
        r.ft.check_wait(cid, (src,),
                        what=f"recv from process {src}",
                        epoch0=epoch0)
        raise SimHang(
            f"rank {r.p}: peer {src} exited ({info['kind']}) without "
            f"sending the awaited message on cid {cid} and with no "
            f"visible FT story — call streams desynced")

    def _register_exit(self, p: int, info: Dict, cid: int) -> None:
        # program order guarantees every message this rank ever posted
        # precedes the exit record: waiters drain the pair queue
        # before seeing the marker, so detection is deterministic
        info["cid"] = cid
        self._exit[p] = info
        # wake every potential waiter on this comm with an explicit
        # marker (parked receives block indefinitely by design)
        for q in self.procs:
            if q != p:
                self._queue(p, q, cid).put(("exit", info["vt"], info))

    # -- sentinel ----------------------------------------------------------
    def note_collective(self, p: int, cid: int, family: str,
                        op_name: str = "-", dtype: str = "-",
                        count: int = 0, root: int = -1,
                        site: Optional[str] = None):
        """Fold one collective call signature into rank ``p``'s
        per-comm rolling chain using the production
        :class:`~..obs.sentinel.CallSig` hashing, and journal it in
        the exact sentinel span shape ``tpu-doctor contracts``
        aligns. ``site`` must stay pipe-free (the encode_op wire
        format)."""
        r = self.ranks[p]
        canon = _sentinel.make_canon(family, op_name, dtype,
                                     int(count), int(root))
        epoch = int(r.ft.epoch)
        site = site or f"fleet_sim:{family}"
        seq, chain = r.sent.get(cid, (0, 0))
        cs = _sentinel.CallSig(cid, seq, family, canon, epoch, site,
                               chain)
        r.sent[cid] = (seq + 1, cs.chain)
        r.spans.append({"seq": len(r.spans),
                        "op": _sentinel.encode_op(canon, epoch, site),
                        "layer": "sentinel", "t": r.now, "dt": 0.0,
                        "bytes": max(int(count), 0), "peer": seq,
                        "comm": int(cid), "flow": cs.chain,
                        "fs": "g"})
        return cs

    def chain_of(self, p: int, cid: int) -> int:
        return self.ranks[p].sent.get(cid, (0, 0))[1]

    def record_recovery(self, p: int, new_cid: int, step: int,
                        duration_s: float) -> None:
        """Journal a recovery completion the way the PR 9 emitter does
        (layer ft, comm=new cid, peer=step, dt=duration)."""
        r = self.ranks[p]
        r.spans.append({"seq": len(r.spans), "op": "ft_recovery",
                        "layer": "ft", "t": r.now,
                        "dt": float(duration_s), "bytes": 0,
                        "peer": int(step), "comm": int(new_cid)})
        self._event(r, "recovered", new_cid=int(new_cid),
                    step=int(step))

    # -- journals ----------------------------------------------------------
    def write_journals(self, directory: str,
                       ranks: Optional[Sequence[int]] = None) -> int:
        """One ``journal-p*.json`` per rank in the rank_dump shape
        ``obs/doctor.py::load_dir`` reads — the forensics tools work
        on simulated fleets unmodified. Returns the file count."""
        os.makedirs(directory, exist_ok=True)
        n = 0
        for p in (self.procs if ranks is None else ranks):
            r = self.ranks[p]
            doc = {"meta": {"pidx": p, "rank_offset": p,
                            "local_size": 1, "clock_offset_s": 0.0,
                            "fleet_sim": True},
                   "spans": r.spans}
            with open(os.path.join(directory,
                                   f"journal-p{p:05d}.json"),
                      "w") as f:
                json.dump(doc, f)
            n += 1
        return n

    # -- running -----------------------------------------------------------
    def run(self, fn: Callable, *, ranks: Optional[Sequence[int]] = None,
            cid=1, epoch0: int = 0, label: Optional[str] = None,
            sig=None, timeout_s: Optional[float] = None) -> RunReport:
        """Run ``fn(xchg, p)`` on every participating rank (one thread
        each) and return the per-run :class:`RunReport`.

        ``sig`` notes a collective signature per rank before the run:
        a (family, op, dtype, count, root) tuple, or a callable
        ``sig(p) -> tuple | None`` for per-rank divergence injection.
        ``label`` journals one coll-layer span per completing rank
        (skew-report food). Queues are scoped by ``cid``: recovery
        reruns on a fresh cid never see a chaotic run's orphans.

        ``cid`` may be a callable ``cid(p) -> int`` — the multi-tenant
        shape: disjoint tenant rank sets run their own schedules on
        their own (band-scoped) cids inside ONE run, and a death's
        exit markers ripple only through the dead rank's cid queues —
        one tenant's failure storm never touches another's wire.
        """
        cid_of = cid if callable(cid) else (lambda _p, _c=cid: _c)
        parts = list(self.procs if ranks is None else ranks)
        for p in parts:
            if not self.ranks[p].alive:
                raise ValueError(f"rank {p} is dead; exclude it")
            info = self._exit.pop(p, None)  # (re)joining this run
            if info is not None and info.get("cid") == cid_of(p):
                # its exit markers (and possibly undrained payloads)
                # still sit on this cid's queues; replaying over them
                # would fail spuriously. Production ULFM has the same
                # rule: a comm that saw a failure is revoked and
                # REBUILT — rejoin on a fresh cid (ft_cid).
                raise ValueError(
                    f"rank {p} exited the previous run on cid "
                    f"{cid_of(p)} ({info['kind']}); rerun survivors "
                    "on a fresh cid (the ULFM revoke -> rebuild shape)")
        start = {p: self.ranks[p].snap() for p in parts}
        out: Dict[int, Tuple[str, object]] = {}

        def worker(p):
            r = self.ranks[p]
            pcid = cid_of(p)
            x = FleetXchg(self, p, pcid, epoch0)
            try:
                if sig is not None:
                    s = sig(p) if callable(sig) else sig
                    if s is not None:
                        self.note_collective(p, pcid, *s)
                t0 = r.now
                val = fn(x, p)
                if label:
                    r.spans.append({"seq": len(r.spans), "op": label,
                                    "layer": "coll", "t": t0,
                                    "dt": r.now - t0, "bytes": 0,
                                    "peer": -1, "comm": int(pcid)})
                self._event(r, "done", op=label or "run")
                out[p] = ("ok", val)
            except _RankKilled:
                epoch, doc = self._death_doc[p]
                r.alive = False
                self._event(r, "died", epoch=epoch)
                self._register_exit(p, {"kind": "dead", "vt": r.now,
                                        "notice": doc, "revoked": (),
                                        "epoch": epoch}, pcid)
                out[p] = ("killed", r.now)
            except MPIError as e:
                # the ULFM errhandler pattern: the detector revokes
                # the comm, and the revoke cascades via exit records
                self._apply_revoke(r, pcid, int(r.ft.epoch), r.now)
                self._event(r, "error", code=e.code.name)
                self._register_exit(
                    p, {"kind": "error", "vt": r.now,
                        "notice": {
                            "epoch": int(r.ft.epoch),
                            "failed": sorted(r.ft.failed),
                            "restarted": [], "rejoined": [],
                            "failed_at": {str(q): e2 for q, e2
                                          in r.ft.failed_at.items()},
                        },
                        "revoked": (pcid,), "epoch": int(r.ft.epoch)},
                    pcid)
                out[p] = ("error", e)
            except SimHang as e:
                self._event(r, "hang", detail=str(e)[:120])
                self._register_exit(p, {"kind": "hang", "vt": r.now,
                                        "notice": None, "revoked": (),
                                        "epoch": int(r.ft.epoch)},
                                    pcid)
                out[p] = ("hang", e)
            except Exception as e:  # pragma: no cover - harness bug
                self._event(r, "crash", detail=str(e)[:120])
                self._register_exit(p, {"kind": "crash", "vt": r.now,
                                        "notice": None, "revoked": (),
                                        "epoch": int(r.ft.epoch)},
                                    pcid)
                out[p] = ("crash", e)

        old_stack = threading.stack_size()
        try:
            threading.stack_size(THREAD_STACK)
        except (ValueError, RuntimeError):  # pragma: no cover
            pass
        try:
            # the stack-size global is consumed at start() time, not
            # Thread() construction — it must stay set through here
            threads = [threading.Thread(target=worker, args=(p,),
                                        daemon=True) for p in parts]
            for t in threads:
                t.start()
        finally:
            try:
                threading.stack_size(old_stack)
            except (ValueError, RuntimeError):  # pragma: no cover
                pass
        deadline = time.monotonic() + (timeout_s if timeout_s
                                       is not None
                                       else self.real_timeout_s + 30)
        for t in threads:
            t.join(max(0.1, deadline - time.monotonic()))
        missing = [p for p in parts if p not in out]
        if missing:
            raise SimHang(f"{len(missing)} rank thread(s) never "
                          f"finished: {missing[:8]}...")
        end = {p: self.ranks[p].snap() for p in parts}
        return RunReport(parts, out, start, end)


# ---------------------------------------------------------------------------
# scaling-law helpers (shared by tests and the bench suite)
# ---------------------------------------------------------------------------


def log2_rounds(P: int) -> int:
    """ceil(log2 P) — THE round/fan-out count every O(log P) claim
    asserts against."""
    return int(math.ceil(math.log2(P))) if P > 1 else 0


def rabenseifner_bytes_per_rank(n_elems: int, itemsize: int,
                                P: int) -> int:
    """Exact per-rank send bytes of the Rabenseifner allreduce at a
    power-of-two P (chunks pad to per=ceil(n/P) elements): (P-1)
    chunks out in the halving reduce-scatter plus (P-1) chunks back
    in the doubling allgather — 2n(P-1)/P bytes, the O(n) bound the
    (P-1)n linear path is measured against."""
    per = -(-int(n_elems) // P)
    return 2 * (P - 1) * per * int(itemsize)
