"""Seeded chaos scenarios over the simulated fleet.

A scenario is a deterministic script: seed -> staged chaos (cascading
rank deaths, a network partition window, slow-NIC stragglers) -> a
collective episode on the real ``hier_schedules`` code -> the ULFM
recovery shape (authoritative notice push, epoch agreement, the real
``ft_cid`` rebuild derivation, ``clear_revoked``) -> a verified rerun
among the survivors on the rebuilt cid. Because every virtual-time
output of :mod:`.fleet_sim` is a pure function of the seed and the
schedule, one scenario replayed twice produces bit-identical event
logs — chaos as reproducible evidence.

The P=64 smoke configuration stays in tier-1 (seconds); P >= 1024 and
long chaos runs are ``@slow`` test territory.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..coll import hier_schedules as hs
from ..ft import ulfm as _ulfm
from .fleet_sim import FleetSim, log2_rounds


class ChaosResult:
    """Everything a forensics/determinism test needs from one
    scenario run."""

    __slots__ = ("P", "seed", "victims", "straggler", "partition_t1",
                 "survivors", "agreed_epoch", "new_cid", "phase1",
                 "phase2", "event_log_json", "fleet")

    def __init__(self, **kv) -> None:
        for k in self.__slots__:
            setattr(self, k, kv.get(k))


def _fold_sum(parts: List[np.ndarray]) -> np.ndarray:
    acc = parts[0]
    for nxt in parts[1:]:
        acc = acc + nxt
    return acc


def _exact_allreduce(data: Dict[int, np.ndarray], procs: List[int]):
    """fn(x, p): recursive-doubling allreduce (Bruck allgather of the
    per-rank blocks + an index-order local fold) — the exact-order
    schedule, bitwise-reproducible at any P."""
    counts = [int(data[p].size) for p in procs]

    def fn(x, p):
        return _fold_sum(hs.allgather_bruck(x, procs, p, data[p],
                                            counts))

    return fn


def cascading_failure(P: int = 64, *, seed: int = 0,
                      hosts_per: int = 8, deaths: int = 2,
                      partition: bool = True, straggler: bool = True,
                      elems: int = 64,
                      detect_s: float = 2e-3) -> ChaosResult:
    """The multi-failure chaos episode, end to end:

    1. stage ``deaths`` seeded rank deaths mid-schedule, a seeded
       slow-NIC straggler, and (optionally) a healing partition
       between the lower and upper host halves;
    2. run a P-rank allreduce on the real recursive-doubling schedule
       — the deaths cascade through the real FtState machinery into
       typed ``ERR_PROC_FAILED`` / ``ERR_REVOKED`` errors;
    3. recover: push the coordinator's authoritative notice to every
       survivor (epoch agreement), derive the rebuilt cid with the
       real ``ft_cid`` on EVERY survivor's own state (asserting they
       all agree), ``clear_revoked`` the fresh cid;
    4. rerun the allreduce among survivors on the rebuilt cid and
       verify the numeric result against the linear fold.
    """
    rng = np.random.RandomState(seed)
    fleet = FleetSim(P, hosts_per=hosts_per, seed=seed,
                     detect_s=detect_s)
    R = max(1, log2_rounds(P))
    cand = rng.permutation(np.arange(1, P))
    victims = sorted(int(v) for v in cand[:deaths])
    for v in victims:
        fleet.kill(v, at_round=1 + int(rng.randint(0, R)))
    straggler_rank: Optional[int] = None
    if straggler and len(cand) > deaths:
        straggler_rank = int(cand[deaths])
        fleet.fabric.slow_nic(straggler_rank, 4.0)
    partition_t1 = None
    if partition:
        half = P // 2
        partition_t1 = float(rng.uniform(5e-4, 2e-3))
        fleet.fabric.partition(range(half), range(half, P),
                               t0=0.0, t1=partition_t1)

    data = {p: (np.arange(elems, dtype=np.int64) + 1) * (p + 1)
            for p in range(P)}
    cid = 1
    phase1 = fleet.run(
        _exact_allreduce(data, fleet.procs), cid=cid,
        label="allreduce",
        sig=("allreduce", "sum", "int64", elems, -1))

    # -- recovery: agreement + rebuild (the ULFM shrink shape) ------------
    survivors = [p for p in fleet.procs if fleet.ranks[p].alive]
    final = fleet.final_notice()
    for p in survivors:
        r = fleet.ranks[p]
        fleet._apply_notice(r, final, r.now)
    epochs = {int(fleet.ranks[p].ft.epoch) for p in survivors}
    assert len(epochs) == 1, f"agreement failed: {sorted(epochs)}"
    agreed = epochs.pop()
    # every survivor derives the rebuilt cid from ITS OWN agreed
    # epoch through the production derivation — they must all agree
    cids = {_ulfm.ft_cid(int(fleet.ranks[p].ft.epoch), cid)
            for p in survivors}
    assert len(cids) == 1, f"ft_cid disagreement: {sorted(cids)}"
    new_cid = cids.pop()
    for p in survivors:
        fleet.ranks[p].ft.clear_revoked(new_cid)
    t_done = max(fleet.ranks[p].now for p in survivors)
    fleet.record_recovery(survivors[0], new_cid, step=agreed,
                          duration_s=t_done)

    # -- verified rerun among survivors on the rebuilt cid ----------------
    phase2 = fleet.run(
        _exact_allreduce(data, survivors), ranks=survivors,
        cid=new_cid, epoch0=agreed, label="allreduce",
        sig=("allreduce", "sum", "int64", elems, -1))
    want = _fold_sum([data[p] for p in survivors])
    for p in survivors:
        np.testing.assert_array_equal(np.asarray(phase2.value(p)),
                                      want)

    return ChaosResult(P=P, seed=seed, victims=victims,
                       straggler=straggler_rank,
                       partition_t1=partition_t1,
                       survivors=survivors, agreed_epoch=agreed,
                       new_cid=new_cid, phase1=phase1, phase2=phase2,
                       event_log_json=fleet.event_log_json(),
                       fleet=fleet)


class MultiTenantResult:
    """Everything the fairness/isolation tests and the bench
    ``multi_tenant`` suite need from one scenario run."""

    __slots__ = ("P", "seed", "classes", "share_lat", "fifo_share",
                 "lat_ranks", "bulk_ranks", "lat_cid", "bulk_cid",
                 "solo_durations", "qos_durations", "fifo_durations",
                 "bulk_durations", "solo_makespan", "qos_makespan",
                 "fifo_makespan", "killed_rank", "outcomes_lat",
                 "outcomes_bulk", "qos_fleet")

    def __init__(self, **kv) -> None:
        for k in self.__slots__:
            setattr(self, k, kv.get(k))

    @staticmethod
    def p99(durations: Dict[int, float]) -> float:
        return float(np.percentile(
            np.asarray(sorted(durations.values())), 99.0))


def multi_tenant(P: int = 256, *, seed: int = 0, hosts_per: int = 8,
                 classes: str = "latency:8,bulk:2",
                 lat_elems: int = 131072, bulk_elems: int = 131072,
                 kill_bulk: bool = False,
                 detect_s: float = 2e-3) -> MultiTenantResult:
    """N tenants x small fleets over ONE shared fabric — the service
    plane's fairness + FT-isolation scenario.

    Two tenants share every host NIC: the **latency** tenant owns one
    rank per host (P/hosts_per ranks, its own band cid via the real
    :func:`~..ft.ulfm.tenant_cid`), the **bulk** tenant the rest.
    Three deterministic legs on the real ``hier_schedules`` code:

    1. **solo** — the latency tenant's allgather alone on a fresh
       fabric (full wire);
    2. **qos** — both tenants concurrently, each rank's send
       bandwidth scaled to its class's weighted-fair share
       (``service.qos.fair_share`` over the REAL parsed class
       weights — the steady-state guarantee of the WireArbiter,
       modeled deterministically so virtual clocks stay replayable);
    3. **fifo** — the same contention WITHOUT QoS: every sender gets
       1/ranks-per-host of its NIC (the head-of-line share a
       saturating bulk tenant leaves a latency tenant on a fair-less
       wire).

    The fairness claim is two assertions the tests pin: the QoS leg's
    latency makespan stays within ``1/share`` (+margin) of solo, and
    beats the FIFO leg. ``kill_bulk=True`` stages a bulk rank's death
    mid-schedule in the qos leg: the bulk tenant's ranks raise typed
    ``ERR_PROC_FAILED``/``ERR_REVOKED`` on exactly the bulk tenant's
    band cid while every latency rank finishes clean — one tenant's
    failure storm never crosses the band boundary.
    """
    from ..service import qos as _qos

    parsed = _qos.parse_classes(classes)
    share_lat = _qos.fair_share("latency", parsed)
    share_bulk = _qos.fair_share("bulk", parsed)
    fifo_share = 1.0 / hosts_per
    lat_ranks = [p for p in range(P) if p % hosts_per == 0]
    bulk_ranks = [p for p in range(P) if p % hosts_per != 0]
    lat_cid = _ulfm.tenant_cid(0, 0)
    bulk_cid = _ulfm.tenant_cid(1, 0)
    lat_data = {p: np.full(lat_elems, p + 1, np.int64)
                for p in lat_ranks}
    bulk_data = {p: np.arange(bulk_elems, dtype=np.float32)
                 * ((p % 7) + 1) for p in bulk_ranks}
    lat_counts = [lat_elems] * len(lat_ranks)

    def lat_fn(x, p):
        return _fold_sum(hs.allgather_bruck(x, lat_ranks, p,
                                            lat_data[p], lat_counts))

    def bulk_fn(x, p):
        return hs.allreduce_rabenseifner(x, bulk_ranks, p,
                                         bulk_data[p], np.add, 0.0)

    def durations(fleet: FleetSim, ranks) -> Dict[int, float]:
        return {p: fleet.ranks[p].now for p in ranks}

    # -- leg 1: latency tenant solo ---------------------------------------
    solo = FleetSim(P, hosts_per=hosts_per, seed=seed,
                    detect_s=detect_s)
    solo.run(lat_fn, ranks=lat_ranks, cid=lat_cid, label="allgather")
    solo_dur = durations(solo, lat_ranks)

    def contended(shares: Dict[str, float],
                  kill: bool) -> tuple:
        fleet = FleetSim(P, hosts_per=hosts_per, seed=seed,
                         detect_s=detect_s)
        for p in lat_ranks:
            fleet.fabric.bandwidth_share(p, shares["latency"])
        for p in bulk_ranks:
            fleet.fabric.bandwidth_share(p, shares["bulk"])
        if kill:
            fleet.kill(bulk_ranks[1], at_round=2)
        rep = fleet.run(
            lambda x, p: (lat_fn(x, p) if p in lat_data
                          else bulk_fn(x, p)),
            cid=lambda p: lat_cid if p % hosts_per == 0 else bulk_cid,
            label="multi_tenant",
            sig=lambda p: (("allgather", "-", "int64", lat_elems, -1)
                           if p % hosts_per == 0 else
                           ("allreduce", "add", "float32", bulk_elems,
                            -1)))
        return fleet, rep

    # -- leg 2: contended under weighted-fair QoS -------------------------
    qos_fleet, qos_rep = contended(
        {"latency": share_lat, "bulk": share_bulk}, kill_bulk)
    # -- leg 3: contended FIFO (no QoS): per-sender NIC share -------------
    _fifo_fleet, fifo_rep = contended(
        {"latency": fifo_share, "bulk": 1.0 - fifo_share}, False)

    return MultiTenantResult(
        P=P, seed=seed, classes=parsed, share_lat=share_lat,
        fifo_share=fifo_share, lat_ranks=lat_ranks,
        bulk_ranks=bulk_ranks, lat_cid=lat_cid, bulk_cid=bulk_cid,
        solo_durations=solo_dur,
        qos_durations=durations(qos_fleet, lat_ranks),
        fifo_durations=durations(_fifo_fleet, lat_ranks),
        # the bulk tenant's clocks in the SAME contended-QoS leg the
        # lat tenant's qos_durations come from — one leg, both classes
        bulk_durations=durations(qos_fleet, bulk_ranks),
        solo_makespan=max(solo_dur.values()),
        qos_makespan=max(qos_fleet.ranks[p].now for p in lat_ranks),
        fifo_makespan=max(_fifo_fleet.ranks[p].now
                          for p in lat_ranks),
        killed_rank=bulk_ranks[1] if kill_bulk else None,
        outcomes_lat={p: qos_rep.outcomes[p] for p in lat_ranks},
        outcomes_bulk={p: qos_rep.outcomes[p] for p in bulk_ranks},
        qos_fleet=qos_fleet)


def sentinel_desync(P: int = 256, *, divergent_rank: int = 137,
                    divergent_seq: int = 2, seed: int = 0,
                    hosts_per: int = 8) -> FleetSim:
    """A P-rank healthy fleet whose rank ``divergent_rank`` posts a
    mismatched collective signature at posting seq ``divergent_seq``
    while every schedule still completes: the caller-intent desync
    class the contract sentinel exists for. Runs ``divergent_seq + 1``
    bcast rounds on the real binomial schedule, noting signatures
    through the production CallSig chain per rank; returns the fleet
    (callers dump journals and run ``tpu-doctor contracts``)."""
    fleet = FleetSim(P, hosts_per=hosts_per, seed=seed)
    procs = fleet.procs
    val = np.arange(16, dtype=np.int32)
    good = ("allreduce", "sum", "float32", 1024, -1, "trainer.py:203")
    bad = ("bcast", "-", "float32", 1024, 0, "restore.py:88")
    for call in range(divergent_seq + 1):

        def sig(p, _call=call):
            if _call == divergent_seq and p == divergent_rank:
                return bad
            return good

        fleet.run(
            lambda x, p: hs.bcast_binomial(x, procs, p, 0,
                                           val if p == 0 else None),
            cid=1, label="bcast", sig=sig)
    return fleet
