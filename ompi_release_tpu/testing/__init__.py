"""In-process simulation harnesses — run the real stack without
processes or devices.

Two simulators share the ``_XchgAdapter`` exchange contract of
``coll/hier_schedules.py`` (one call posts all of a schedule round's
sends, then reaps its receives), so the same unmodified schedule code
runs under either:

- :mod:`.lockstep` — the minimal thread-per-process FIFO world the
  bitwise-parity matrix of ``tests/test_hier_schedules.py`` drives:
  no clock, no fabric model, just the transport contract. Milliseconds
  per (P, op, dtype, algorithm) cell.
- :mod:`.fleet_sim` — the simulated-fleet scale harness: hundreds to
  thousands of ranks over a virtual wire with per-link latency /
  bandwidth / loss, host topologies, a deterministic virtual clock,
  per-rank metrology (rounds, messages, inter-host bytes), and the
  real ``ft/ulfm.py`` failure picture + ``obs/sentinel.py`` chain
  hashing driven per simulated rank.
- :mod:`.scenarios` — seeded chaos scripts over the fleet sim
  (cascading rank deaths, network partitions, slow-NIC stragglers)
  that replay deterministically and roll the survivors through the
  ULFM revoke -> rebuild recovery shape.

Import-light by design (numpy only, no jax): the harness must bring
up a 4096-rank virtual fleet in well under a second.
"""

from .lockstep import SimWorld, SimXchg, simulate  # noqa: F401
