"""Lockstep in-memory exchange world — the minimal simulator.

One thread per simulated process, one FIFO queue per (src, dst) pair,
and the exact transport contract the real ``coll/hier._XchgAdapter``
provides: all of a round's sends are posted before any receive parks.
The pure schedules of ``coll/hier_schedules.py`` run under it
unmodified, which is what lets the bitwise-parity matrix cover the
whole (P, op, dtype, algorithm) cross product in milliseconds,
device- and process-free.

Extracted from ``tests/test_hier_schedules.py`` so the simulator is a
first-class citizen: the parity tests import it from here, and
:mod:`.fleet_sim` scales the same adapter contract to thousands of
ranks with a fabric model on top.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, Sequence

import numpy as np


class SimWorld:
    """Per-(src, dst) FIFO queues for one simulated process set."""

    def __init__(self, procs: Sequence[int]) -> None:
        self.q = {(s, d): queue.Queue() for s in procs for d in procs}


class SimXchg:
    """In-memory exchange adapter: per-(src, dst) FIFO, all sends
    posted before any receive parks — the wire adapter's contract."""

    def __init__(self, world: SimWorld, me: int) -> None:
        self.world, self.me = world, me

    def exchange(self, sends: Dict[int, list],
                 recvs: Dict[int, int]) -> Dict[int, list]:
        for dst, arrs in sends.items():
            for a in arrs:
                self.world.q[(self.me, dst)].put(np.asarray(a))
        return {
            src: [self.world.q[(src, self.me)].get(timeout=30)
                  for _ in range(c)]
            for src, c in recvs.items()
        }


def simulate(procs: Sequence[int], fn: Callable, timeout: float = 60):
    """Run ``fn(xchg, pidx)`` on one thread per process; returns
    {pidx: result}; any thread's exception is re-raised as an
    AssertionError naming the failing process."""
    world = SimWorld(procs)
    out, errs = {}, {}

    def worker(p):
        try:
            out[p] = fn(SimXchg(world, p), p)
        except Exception as e:  # pragma: no cover - failure path
            errs[p] = e

    ts = [threading.Thread(target=worker, args=(p,), daemon=True)
          for p in procs]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout)
    assert not errs, errs
    assert len(out) == len(procs), f"threads hung: {sorted(out)}"
    return out
