"""Versioned per-topology tuning database — measured decision tables a
fleet selects by fingerprint instead of by hand.

The reference's coll/tuned reads ONE operator-pointed rules file
(``coll_tuned_dynamic_rules_filename``); at fleet scale that breaks
down the moment two jobs run on different slices: an 8-host job and a
128-host job want different ``hier_*`` schedules, and every new
topology re-pays the whole ``tpu-tune`` sweep. This module makes the
sweep durable and the selection automatic:

fingerprint
    :class:`Fingerprint` canonicalizes the four keys schedule selection
    actually depends on — host count, processes per host (0 = ragged),
    the link classes between them (``local`` single-process, ``shm``
    one host, ``shm+dcn`` spanning), and the process count P. It
    round-trips through the ``# fingerprint:`` header stanza
    :mod:`..coll.dynamic_rules` parses, so every rules file names the
    topology it was measured on.

database layout
    A directory of ordinary rule files, ``<slug>-vN.conf`` — each a
    valid ``dynamic_rules`` file whose header stanza carries its
    fingerprint and version. :meth:`TuningDb.register` validates
    through the real loader BEFORE publishing (a typo'd generator must
    not poison the fleet's table) and never overwrites: re-tuning the
    same topology writes v2, v3, ... so the trail of what was measured
    when survives.

selection
    :func:`select_rules_path` answers "which entry serves THIS job":
    exact fingerprint match at the highest version, else the nearest
    entry over the same link classes (same procs-per-host preferred,
    then closest P, then closest host count). ``dynamic_rules``
    consults it automatically when ``coll_tuning_db_dir`` is set and
    no explicit rules filename is — the operator points a fleet at ONE
    directory instead of hand-wiring a file per job shape. Precedence
    is unchanged: forcing > rules (explicit file > DB entry) > fixed
    decision constants.

The active fingerprint is published at comm construction
(``coll/hier._HierModule`` derives it from the modex host identity);
single-process jobs fall back to :data:`LOCAL`.
"""

from __future__ import annotations

import dataclasses
import os
import re
import threading
import time as _time
from typing import Dict, List, Mapping, Optional, Tuple

from .. import obs as _obs
from ..mca import pvar
from ..mca import var as mca_var
from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.stream("tuning")

#: uncached database resolutions (cache misses of the per-(dir,
#: fingerprint) selection cache — a register/re-tune moves the dir
#: mtime and shows up here as one re-resolve)
_db_resolves = pvar.counter(
    "tuning_db_resolves",
    "tuning-database best-match resolutions (selection-cache misses)",
)


def register_vars() -> None:
    mca_var.register(
        "coll_tuning_db_dir", "str", "",
        "Directory of the versioned per-topology tuning database "
        "(tpu-tune --db writes it). When set and no explicit "
        "coll_tuned_dynamic_rules_filename is, dynamic rules "
        "auto-select the best-matching entry for the job's topology "
        "fingerprint at comm construction; empty disables",
    )


register_vars()  # idempotent; the cvar must exist before any lookup


# ---------------------------------------------------------------------------
# the topology fingerprint
# ---------------------------------------------------------------------------

_CANON_RE = re.compile(
    r"^hosts=(\d+);ppn=(\d+);links=([a-z0-9+]+);P=(\d+)$")


@dataclasses.dataclass(frozen=True)
class Fingerprint:
    """The four keys schedule selection depends on. ``procs_per_host``
    is 0 when hosts hold unequal process counts (a ragged layout never
    exact-matches a uniform one)."""

    hosts: int
    procs_per_host: int
    link_classes: Tuple[str, ...]
    P: int

    def canon(self) -> str:
        """The canonical one-line form the header stanza carries."""
        return (f"hosts={self.hosts};ppn={self.procs_per_host};"
                f"links={'+'.join(self.link_classes)};P={self.P}")

    def slug(self) -> str:
        """Filesystem-safe entry-name stem."""
        return (f"h{self.hosts}ppn{self.procs_per_host}p{self.P}-"
                + "-".join(self.link_classes))

    @classmethod
    def parse(cls, text: str) -> "Fingerprint":
        m = _CANON_RE.match(str(text).strip())
        if not m:
            raise ValueError(
                f"malformed topology fingerprint {text!r} (expected "
                "'hosts=H;ppn=N;links=a+b;P=P')")
        return cls(int(m.group(1)), int(m.group(2)),
                   tuple(m.group(3).split("+")), int(m.group(4)))


#: the single-process fallback fingerprint (in-process collectives
#: never cross a link; the DB still matches "local" entries exactly)
LOCAL = Fingerprint(hosts=1, procs_per_host=1,
                    link_classes=("local",), P=1)


def fingerprint_for(host_of: Mapping[int, str], P: int) -> Fingerprint:
    """Fingerprint of one spanning layout: the rank->host map the
    modex cards already carry (``coll/hier`` host grouping) plus the
    process count. Link classes follow the transport choice: one host
    rides shm, several ride shm+dcn."""
    sizes: Dict[str, int] = {}
    for p in host_of:
        sizes[host_of[p]] = sizes.get(host_of[p], 0) + 1
    hosts = max(1, len(sizes))
    uniform = len(set(sizes.values())) == 1 if sizes else True
    ppn = next(iter(sizes.values())) if (sizes and uniform) else 0
    links = ("shm", "dcn") if hosts > 1 else ("shm",)
    return Fingerprint(hosts=hosts, procs_per_host=ppn,
                       link_classes=links, P=int(P))


_active_lock = threading.Lock()
_active: Optional[Fingerprint] = None


def set_active(fp: Fingerprint, force: bool = True) -> None:
    """Publish the job's topology fingerprint. With ``force=False``
    (what comm construction passes) the WIDEST comm wins: a 2-host
    subcommunicator built after the 16-host world must not steer the
    world's DB selection to 2-host rules — rule selection is a
    process-global cvar plane, so its key is the job's layout, i.e.
    the largest process set seen. ``force=True`` (operator/test/
    re-tune surface) replaces unconditionally."""
    global _active
    with _active_lock:
        if force or _active is None or fp.P >= _active.P:
            _active = fp


def active() -> Fingerprint:
    with _active_lock:
        return _active if _active is not None else LOCAL


def _reset_for_tests() -> None:
    global _active
    with _active_lock:
        _active = None
    with _select_lock:
        _select_cache.clear()


# ---------------------------------------------------------------------------
# header stanza helpers (shared with dynamic_rules / tpu-tune)
# ---------------------------------------------------------------------------

FP_LINE_RE = re.compile(r"^#\s*fingerprint:\s*(.+?)\s*$")
VERSION_LINE_RE = re.compile(r"^#\s*version:\s*(\d+)\s*$")


def stamp(text: str, fp: Fingerprint, version: Optional[int] = None,
          source: Optional[str] = None) -> str:
    """Prepend (or replace) the fingerprint header stanza on one rules
    file's text — what 'stamped with the measured topology
    fingerprint' means concretely."""
    lines = [ln for ln in text.splitlines()
             if not (FP_LINE_RE.match(ln) or VERSION_LINE_RE.match(ln))]
    head = [f"# fingerprint: {fp.canon()}"]
    if version is not None:
        head.append(f"# version: {int(version)}")
    if source:
        head.append(f"# db-source: {source}")
    return "\n".join(head + lines) + "\n"


def read_header(path: str) -> Tuple[Optional[Fingerprint],
                                    Optional[int]]:
    """(fingerprint, version) from one rules file's comment header, or
    (None, None) for a legacy file without the stanza. Malformed
    stanzas raise — a fingerprint that silently failed to parse would
    make the entry unselectable with no symptom."""
    fp: Optional[Fingerprint] = None
    version: Optional[int] = None
    try:
        with open(path) as f:
            for line in f:
                m = FP_LINE_RE.match(line)
                if m:
                    try:
                        fp = Fingerprint.parse(m.group(1))
                    except ValueError as e:
                        raise MPIError(ErrorCode.ERR_ARG,
                                       f"{path}: {e}")
                m = VERSION_LINE_RE.match(line)
                if m:
                    version = int(m.group(1))
    except OSError as e:
        raise MPIError(ErrorCode.ERR_FILE,
                       f"cannot read tuning entry {path}: {e}")
    return fp, version


# ---------------------------------------------------------------------------
# the database
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Entry:
    fingerprint: Fingerprint
    version: int
    path: str


class TuningDb:
    """One directory of fingerprint-stamped, versioned rule files."""

    def __init__(self, root: str) -> None:
        self.root = str(root)

    def entries(self) -> List[Entry]:
        """Every selectable entry (files without a fingerprint stanza
        are skipped: nothing to match them by)."""
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return []
        out: List[Entry] = []
        for name in names:
            if not name.endswith(".conf"):
                continue
            path = os.path.join(self.root, name)
            fp, version = read_header(path)
            if fp is None:
                continue
            out.append(Entry(fp, version or 1, path))
        return out

    def register(self, text: str, fp: Fingerprint,
                 source: str = "tpu-tune") -> str:
        """Publish one rules file under ``fp`` at the next version.
        The text is stamped, then validated through the REAL rule
        loader before the rename publishes it — the database can never
        serve a file that fails at job start."""
        from ..coll import dynamic_rules
        # the hier_* rule namespaces live in hier_schedules (jax-free);
        # without them a device-free caller could not validate the
        # very rules the probes emit
        from ..coll import hier_schedules  # noqa: F401

        os.makedirs(self.root, exist_ok=True)
        version = 1 + max(
            (e.version for e in self.entries() if e.fingerprint == fp),
            default=0)
        stamped = stamp(text, fp, version=version, source=source)
        path = os.path.join(self.root, f"{fp.slug()}-v{version}.conf")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(stamped)
        try:
            dynamic_rules.load_rules(tmp)  # loud on any typo
        except MPIError:
            os.unlink(tmp)
            raise
        os.replace(tmp, path)
        if _obs.enabled:
            _obs.record("tuning_db_register", "tuning",
                        _time.perf_counter(), 0.0,
                        nbytes=len(stamped))
        _log.verbose(1, f"tuning db: registered {fp.canon()} "
                        f"v{version} -> {path}")
        return path

    def best_match(self, fp: Fingerprint) -> Optional[str]:
        """The entry serving ``fp``: exact match at the highest
        version, else the nearest same-link-class entry (matching
        procs-per-host preferred, then closest P, then closest host
        count, then newest). None when no entry shares the link
        classes — a local table must never steer a spanning job."""
        cands = [e for e in self.entries()
                 if e.fingerprint.link_classes == fp.link_classes]
        if not cands:
            return None
        exact = [e for e in cands if e.fingerprint == fp]
        if exact:
            return max(exact, key=lambda e: e.version).path
        cands.sort(key=lambda e: (
            e.fingerprint.procs_per_host != fp.procs_per_host,
            abs(e.fingerprint.P - fp.P),
            abs(e.fingerprint.hosts - fp.hosts),
            -e.version, e.path))
        return cands[0].path


# ---------------------------------------------------------------------------
# selection cache (the dynamic_rules auto-select hot-ish path)
# ---------------------------------------------------------------------------

_select_lock = threading.Lock()
#: (root, fingerprint canon) -> (dir mtime_ns, resolved path|None)
_select_cache: Dict[Tuple[str, str],
                    Tuple[int, Optional[str]]] = {}


def select_rules_path(root: Optional[str] = None,
                      fp: Optional[Fingerprint] = None) -> Optional[str]:
    """The DB entry the current job should load, or None (no DB dir /
    no matching entry). Cached per (dir, fingerprint) and invalidated
    by the directory's mtime — ``register`` always creates a NEW file,
    so a re-tune moves the mtime and the next lookup re-resolves."""
    root = root if root is not None \
        else str(mca_var.get("coll_tuning_db_dir", "") or "")
    if not root:
        return None
    fp = fp or active()
    try:
        dir_mtime = os.stat(root).st_mtime_ns
    except OSError:
        return None  # no DB yet: fall through to fixed constants
    key = (root, fp.canon())
    with _select_lock:
        cached = _select_cache.get(key)
        if cached is not None and cached[0] == dir_mtime:
            return cached[1]
    path = TuningDb(root).best_match(fp)
    _db_resolves.add()
    with _select_lock:
        _select_cache[key] = (dir_mtime, path)
    if path:
        _log.verbose(2, f"tuning db: {fp.canon()} -> {path}")
    return path
