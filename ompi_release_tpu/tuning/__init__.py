"""Persistent per-topology tuning — the coll/tuned + coll/ml decision
tables made fleet-durable.

:mod:`.db` stores versioned dynamic-rule files keyed by a topology
fingerprint (hosts, procs-per-host, link classes, P) so a fleet never
re-pays a tuning sweep; :mod:`.retune` watches the PR 6 series plane
for sustained slow links and applies re-measured rules through a
cvar write (which bumps the MCA write generation, so PR 13 frozen
``SchedulePlan``s re-plan at the next fire, never mid-schedule).
"""

from . import db  # noqa: F401  (registers the coll_tuning_db_dir cvar)
