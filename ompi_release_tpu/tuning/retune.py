"""Online re-tuning — the PR 6 series plane watched for sustained
slow links, answered with a bounded micro-probe and a cvar-applied
rule update.

The fleet metrics plane (:mod:`..obs.sampler`) already produces the
live signal an online re-tuner needs: per-communicator ``coll_bytes``
/ ``coll_seconds`` series points (MB/s once divided) and the skew
pvars. This module closes the loop, gated end to end (``tune_online``
defaults OFF; when off, nothing runs — no hook, no state):

detect
    :class:`OnlineRetuner.observe_points` folds each tick's per-cid
    points into an MB/s sample and keeps a bounded window per comm.
    A sample below ``median(window) / tune_online_slow_factor``
    counts as slow; ``tune_online_sustain`` CONSECUTIVE slow ticks —
    a sustained slow link, not one hiccup — trigger a re-tune
    (cooldown-limited, so a flapping link cannot probe-storm).

probe
    A BOUNDED micro-probe re-measures the schedule menu: the pluggable
    ``probe(cid)`` callable returns replacement rule text (or None to
    decline). :func:`fleet_probe` is the built-in model-based probe —
    one run per candidate algorithm of the real schedule code over a
    :class:`~..testing.fleet_sim.Fabric` mirror of the observed
    topology (straggler included), deterministic and device-free.

apply
    The winning rules register into the tuning database
    (:mod:`.db` — a NEW version, the measured trail survives) and the
    selection lands via a CVAR WRITE (``coll_tuned_dynamic_rules_
    filename`` -> the new entry). That write bumps the MCA registry's
    write generation, which is exactly the PR 13 contract: every
    frozen ``SchedulePlan`` re-plans at its NEXT fire, never
    mid-schedule — an online re-tune can never corrupt a round in
    flight.

Arming rides ``Runtime.init`` next to the sampler: when
``tune_online`` is set (and obs + the sampler are live), the retuner
registers a post-tick hook on :data:`..obs.sampler.TICK_HOOKS` and
drains new series points each tick.
"""

from __future__ import annotations

import statistics
import time as _time
from collections import deque
from typing import Callable, Dict, List, Optional

from .. import obs as _obs
from ..mca import pvar
from ..mca import var as mca_var
from ..utils import output

_log = output.stream("tuning")

_slow_flags = pvar.counter(
    "tune_slow_link_flags",
    "sampler ticks whose per-comm MB/s fell below the sustained-slow "
    "threshold (baseline / tune_online_slow_factor)",
)
_probe_timer = pvar.timer(
    "tune_probe_seconds",
    "accumulated seconds spent in online re-tune micro-probes "
    "(bounded: one run per candidate algorithm)",
)
_retunes = pvar.counter(
    "tune_retunes_applied",
    "online re-tunes applied (rule registered into the tuning db and "
    "selected via the generation-bumping cvar write)",
)


def register_vars() -> None:
    mca_var.register(
        "tune_online", "bool", False,
        "Arm the online re-tuner on the continuous sampler's tick "
        "hook: sustained per-comm MB/s degradation triggers a bounded "
        "micro-probe and a cvar-applied rule update (requires obs + "
        "obs_sample_interval > 0; plans re-freeze at the next fire)",
    )
    mca_var.register(
        "tune_online_window", "int", 8,
        "Rolling window (sampler ticks) of per-comm MB/s samples the "
        "slow-link baseline is the median of",
    )
    mca_var.register(
        "tune_online_sustain", "int", 3,
        "Consecutive below-threshold ticks before a re-tune triggers "
        "(one hiccup is not a slow link)",
    )
    mca_var.register(
        "tune_online_slow_factor", "float", 2.0,
        "A tick is 'slow' when its MB/s < window median / this factor",
    )
    mca_var.register(
        "tune_online_cooldown_s", "float", 120.0,
        "Minimum seconds between applied re-tunes per communicator "
        "(a flapping link must not probe-storm)",
    )


register_vars()  # idempotent; cvars must exist before any arm


class OnlineRetuner:
    """Sustained-slow-link detector + probe/apply driver. ``probe`` is
    ``probe(cid) -> Optional[str]`` returning replacement rule text;
    ``db_dir`` defaults to the ``coll_tuning_db_dir`` cvar at apply
    time. ``clock`` is injectable for deterministic tests."""

    def __init__(self, probe: Optional[Callable[[int], Optional[str]]]
                 = None, db_dir: Optional[str] = None,
                 clock: Callable[[], float] = _time.monotonic) -> None:
        self.probe = probe
        self.db_dir = db_dir
        self.clock = clock
        self._rates: Dict[int, deque] = {}
        self._slow: Dict[int, int] = {}
        self._last_apply: Dict[int, float] = {}
        self._cursor = 0
        #: applied re-tunes, newest last: {"cid", "path", "t"} — the
        #: forensic trail tests and tpu-doctor read
        self.applied: List[Dict] = []

    # -- detection ---------------------------------------------------------
    def observe_rate(self, cid: int, mb_s: float) -> bool:
        """Fold one per-comm MB/s sample; True when this sample
        completes a sustained-slow streak (trigger)."""
        window = max(2, int(mca_var.get("tune_online_window", 8)))
        factor = float(mca_var.get("tune_online_slow_factor", 2.0))
        sustain = max(1, int(mca_var.get("tune_online_sustain", 3)))
        dq = self._rates.setdefault(cid, deque(maxlen=window))
        trigger = False
        if len(dq) >= max(2, window // 2):
            base = statistics.median(dq)
            if base > 0 and mb_s < base / max(1.0, factor):
                _slow_flags.add()
                self._slow[cid] = self._slow.get(cid, 0) + 1
                if self._slow[cid] >= sustain:
                    cooldown = float(
                        mca_var.get("tune_online_cooldown_s", 120.0))
                    last = self._last_apply.get(cid)
                    if last is None or \
                            self.clock() - last >= cooldown:
                        trigger = True
                        self._slow[cid] = 0
            else:
                self._slow[cid] = 0
        dq.append(float(mb_s))
        return trigger

    def observe_points(self, points: List[Dict]) -> List[int]:
        """Fold a batch of sampler series points (the ring's dict
        shape); returns the cids whose streak completed. One (tick,
        cid) pair folds to one MB/s sample — coll_bytes over
        coll_seconds, the sampler's per-comm rate series."""
        acc: Dict[tuple, Dict[str, float]] = {}
        order: List[tuple] = []
        for pt in points:
            name = pt.get("name")
            if name not in ("coll_bytes", "coll_seconds"):
                continue
            key = (pt.get("t"), pt.get("cid"))
            if key not in acc:
                acc[key] = {}
                order.append(key)
            acc[key][name] = float(pt.get("v") or 0.0)
        triggered: List[int] = []
        for key in order:
            secs = acc[key].get("coll_seconds", 0.0)
            if secs <= 0:
                continue
            mb_s = acc[key].get("coll_bytes", 0.0) / secs / 1e6
            cid = int(key[1])
            if self.observe_rate(cid, mb_s) and cid not in triggered:
                triggered.append(cid)
        return triggered

    # -- probe + apply -----------------------------------------------------
    def retune(self, cid: int) -> Optional[str]:
        """Run the bounded micro-probe for one flagged comm and apply
        its verdict; returns the registered rules path (None when no
        probe is configured or it declined)."""
        if self.probe is None:
            _log.verbose(1, f"online retune: cid {cid} flagged "
                            "sustained-slow; no probe configured")
            return None
        rec = _obs.enabled
        t0 = _time.perf_counter() if rec else 0.0
        with _probe_timer.timing():
            text = self.probe(cid)
        if rec and _obs.enabled:
            _obs.record("retune_probe", "tune", t0,
                        _time.perf_counter() - t0, comm_id=cid)
        if not text:
            return None
        return self.apply(text, cid=cid)

    def apply(self, rule_text: str, cid: int = -1) -> str:
        """Register the re-measured rules as a NEW tuning-db version
        and select them via the cvar write that bumps the MCA write
        generation — frozen plans re-freeze at the next fire."""
        from . import db as _db

        root = self.db_dir or \
            str(mca_var.get("coll_tuning_db_dir", "") or "")
        if not root:
            raise ValueError(
                "online retune needs a tuning database: set "
                "coll_tuning_db_dir (or pass db_dir)")
        rec = _obs.enabled
        t0 = _time.perf_counter() if rec else 0.0
        path = _db.TuningDb(root).register(
            rule_text, _db.active(), source="online-retune")
        # THE generation-bumping write: selection moves to the new
        # entry AND every frozen SchedulePlan re-plans at its next
        # fire (coll/plan stamps plans with VARS.generation)
        mca_var.set_value("coll_tuned_use_dynamic_rules", True)
        mca_var.set_value("coll_tuned_dynamic_rules_filename", path)
        self._last_apply[cid] = self.clock()
        self.applied.append({"cid": cid, "path": path,
                             "t": self.clock()})
        _retunes.add()
        if rec and _obs.enabled:
            _obs.record("retune_apply", "tune", t0,
                        _time.perf_counter() - t0, comm_id=cid)
        _log.warn(f"online retune applied for comm {cid}: {path}")
        return path

    # -- sampler hook ------------------------------------------------------
    def tick(self) -> None:
        """Post-tick hook: drain the series ring incrementally and
        act on completed streaks. Never raises (the sampler's plane
        must survive a broken consumer — it also guards, belt and
        braces)."""
        try:
            from ..obs import sampler as _sampler

            pts, self._cursor = _sampler.RING.drain_since(self._cursor)
            for cid in self.observe_points(pts):
                self.retune(cid)
        except Exception as e:  # pragma: no cover - defensive
            _log.verbose(1, f"online retune tick failed: {e}")


# ---------------------------------------------------------------------------
# the built-in model-based micro-probe
# ---------------------------------------------------------------------------

def fleet_probe(P: int, hosts_per: int, n_elems: int = 4096,
                algs=("ring", "multiring", "torus2d"), seed: int = 0,
                fabric_factory: Optional[Callable] = None,
                min_comm_size: int = 0, min_bytes: int = 0) -> str:
    """Bounded, deterministic micro-probe: ONE run per candidate
    allreduce schedule of the real round code over a virtual-fabric
    mirror of the observed topology (``fabric_factory`` injects the
    straggler picture; default = a clean ``hosts_per`` fabric).
    Returns a ``hier_allreduce`` rule line naming the winner by
    virtual makespan. Device-free — runnable from a live job without
    touching the wire."""
    import numpy as np

    from ..coll import hier_schedules as _hs
    from ..coll import topo_schedules as _topo
    from ..testing import fleet_sim as _fs

    def default_factory():
        return _fs.Fabric(P, hosts_per=hosts_per, seed=seed)

    factory = fabric_factory or default_factory
    procs = list(range(P))
    data = {p: np.arange(int(n_elems), dtype=np.float32) * (p % 3 + 1)
            for p in procs}
    makespans: Dict[str, float] = {}
    for alg in algs:
        fleet = _fs.FleetSim(P, fabric=factory(), seed=seed)
        host_of = fleet.fabric.host_of

        def fn(x, p, alg=alg, host_of=host_of):
            if alg == "multiring":
                return _topo.allreduce_multiring(
                    x, procs, p, data[p], np.add, 0.0,
                    int(mca_var.get("hier_multiring_k", 4)))
            if alg == "torus2d":
                return _topo.allreduce_torus2d(
                    x, procs, p, data[p], np.add, 0.0, host_of)
            return _hs.allreduce_ring(x, procs, p, data[p], np.add,
                                      0.0)

        rep = fleet.run(fn, label=f"probe_{alg}")
        if len(rep.ok()) == P:
            makespans[alg] = rep.makespan
    if not makespans:
        raise RuntimeError("fleet probe: every candidate failed")
    winner = min(sorted(makespans), key=makespans.get)
    just = ", ".join(f"{a}={makespans[a] * 1e3:.3f}ms"
                     for a in sorted(makespans, key=makespans.get))
    return (f"# online re-tune micro-probe (P={P}, hosts_per="
            f"{hosts_per}, {int(n_elems)} f32): {just}\n"
            f"hier_allreduce  {int(min_comm_size)}  {int(min_bytes)}"
            f"  {winner}\n")


# ---------------------------------------------------------------------------
# lifecycle (Runtime.init / finalize, next to the sampler)
# ---------------------------------------------------------------------------

RETUNER: Optional[OnlineRetuner] = None


def default_probe(cid: int) -> Optional[str]:
    """The probe a production arm gets when none is injected: a
    :func:`fleet_probe` over a virtual mirror of the job's ACTIVE
    topology fingerprint (:func:`..tuning.db.active` — published at
    comm construction). Declines (None) for single-process or ragged
    layouts the fleet model cannot mirror, so a trigger there is a
    logged no-op rather than a bogus rule."""
    from . import db as _db

    fp = _db.active()
    if fp.P < 2:
        return None
    hosts_per = fp.procs_per_host
    if hosts_per <= 0:  # ragged layout: no uniform mirror to probe
        return None
    return fleet_probe(fp.P, hosts_per)


def maybe_start(runtime=None,
                probe: Optional[Callable] = None) -> bool:
    """Arm the retuner iff ``tune_online`` is set and obs is enabled
    (the sampler's tick hook is the drive shaft — without
    ``obs_sample_interval`` > 0 nothing ever ticks). Zero cost when
    off: no object, no hook. Without an injected ``probe`` the
    built-in :func:`default_probe` runs, so the detect->probe->apply
    loop is live in production, not just in tests."""
    global RETUNER
    if not _obs.enabled or not bool(mca_var.get("tune_online", False)):
        return False
    from ..obs import sampler as _sampler

    if RETUNER is None:
        RETUNER = OnlineRetuner(probe=probe or default_probe)
    if RETUNER.tick not in _sampler.TICK_HOOKS:
        _sampler.TICK_HOOKS.append(RETUNER.tick)
    return True


def stop() -> None:
    global RETUNER
    if RETUNER is not None:
        from ..obs import sampler as _sampler

        try:
            _sampler.TICK_HOOKS.remove(RETUNER.tick)
        except ValueError:
            pass
    RETUNER = None


def _reset_for_tests() -> None:
    stop()
