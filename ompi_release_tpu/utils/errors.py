"""MPI-style error codes and error handlers.

Analogue of ``ompi/errhandler/`` + the MPI error classes: operations
raise :class:`MPIError` carrying a standard error class; communicators
carry an :class:`Errhandler` deciding whether errors abort the job
(``MPI_ERRORS_ARE_FATAL``, the MPI default) or propagate to the caller
(``MPI_ERRORS_RETURN``).
"""

from __future__ import annotations

import enum
from typing import Callable, Optional


class ErrorCode(enum.IntEnum):
    """Subset of the MPI error classes (``mpi.h`` MPI_ERR_*)."""

    SUCCESS = 0
    ERR_BUFFER = 1
    ERR_COUNT = 2
    ERR_TYPE = 3
    ERR_TAG = 4
    ERR_COMM = 5
    ERR_RANK = 6
    ERR_REQUEST = 7
    ERR_ROOT = 8
    ERR_GROUP = 9
    ERR_OP = 10
    ERR_TOPOLOGY = 11
    ERR_DIMS = 12
    ERR_ARG = 13
    ERR_UNKNOWN = 14
    ERR_TRUNCATE = 15
    ERR_OTHER = 16
    ERR_INTERN = 17
    ERR_IN_STATUS = 18
    ERR_PENDING = 19
    ERR_WIN = 45
    ERR_RMA_SYNC = 50
    ERR_RMA_SHARED = 71  # MPI_ERR_RMA_SHARED: shared-window constraint
    ERR_BASE = 46
    ERR_DISP = 52
    ERR_IO = 32
    ERR_FILE = 27
    ERR_NO_MEM = 34
    ERR_NAME = 33  # MPI_ERR_NAME: service name not published
    ERR_PORT = 38  # MPI_ERR_PORT: invalid port (connect/accept)
    ERR_SPAWN = 42  # MPI_ERR_SPAWN
    ERR_NOT_AVAILABLE = 100
    ERR_UNREACH = 101  # OMPI_ERR_UNREACH: no transport reaches the peer
    # ULFM fault-tolerance classes (MPIX_ERR_* of the MPI 4.x FT
    # chapter): a wait on a peer the job epoch marks dead completes in
    # error instead of hanging, and operations on a revoked
    # communicator are interrupted with ERR_REVOKED
    ERR_PROC_FAILED = 75   # MPIX_ERR_PROC_FAILED
    ERR_REVOKED = 76       # MPIX_ERR_REVOKED
    # collective contract violation (obs/sentinel.py inline mode): a
    # peer rank's call signature — family/op/dtype/count/root at the
    # same per-comm posting seq — diverged from this rank's. MPI has
    # no class for this (it is erroneous-program territory MUST-style
    # tools diagnose); raising it typed within the round beats the
    # alternative, an unexplained hang
    ERR_COLL_MISMATCH = 77


class MPIError(RuntimeError):
    def __init__(self, code: ErrorCode, message: str = "") -> None:
        super().__init__(f"{code.name}: {message}" if message else code.name)
        self.code = code
        self.message = message


class Errhandler:
    """Error handler attached to communicators/windows/files."""

    def __init__(self, fn: Optional[Callable[[object, MPIError], None]] = None,
                 name: str = "user") -> None:
        self._fn = fn
        self.name = name

    def invoke(self, obj: object, err: MPIError) -> None:
        if self._fn is None:
            raise err
        self._fn(obj, err)


def _fatal(obj: object, err: MPIError) -> None:
    # the reference aborts the whole job; we raise SystemExit to mirror
    # MPI_Abort semantics without killing the test runner's interpreter
    raise SystemExit(f"MPI error (ERRORS_ARE_FATAL) on {obj}: {err}")


def _return(obj: object, err: MPIError) -> None:
    raise err


ERRORS_ARE_FATAL = Errhandler(_fatal, name="ERRORS_ARE_FATAL")
ERRORS_RETURN = Errhandler(_return, name="ERRORS_RETURN")
