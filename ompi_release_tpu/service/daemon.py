"""tpu-serviced — the resident multi-tenant collective daemon.

``tpu_server`` is the job-independent *name* server (the orte-server
role); this daemon is the next stage of that idea (ROADMAP item 2):
a resident process that many independently launched jobs ATTACH to as
**tenants** of one fabric. It serves, over the same seq-correlated
OOB frame protocol:

- everything ``tpu_server`` serves (publish/lookup/unpublish names +
  the metrics/journal/series observability RPCs);
- ``TAG_TENANT`` — the tenant control plane: ``admit`` (admission
  control against rank/lane capacity; returns the tenant id, its
  private cid band, its lease token), ``renew`` (heartbeat + stats
  report), ``release`` (graceful exit), ``fail`` (a tenant reporting
  its own rank death — eviction with the episode named);
- ``TAG_TENANTS`` — the per-tenant fabric view ``tpu_top --tenants``
  renders: who is burning the fabric (coll/s, MB/s, lane share, HOL
  wait per tenant), lease ages, recent evictions.

Tenant-scoped pubsub: every name published through the daemon is
stamped with its publisher's client id (see ``runtime/pubsub.py``);
eviction — explicit, or by lease expiry in the serve loop's
``prune()`` — drops the tenant's names, revokes its cid band through
the real ULFM machinery, and clears its sentinel chains. Other
tenants and the daemon itself never notice: the kill-mid-allreduce
job test pins exactly that.

Usage::

    python -m ompi_release_tpu.service.daemon [--port P] [--bind A]
        [--capacity-ranks N] [--capacity-lanes N] [--lease SECS]

    client = ServiceClient(host, port)
    grant = client.admit("trainer-a", ranks=8, qos="latency")
    ...
    client.renew(grant["tid"], grant["token"],
                 stats={"coll_s": 120.0, "mb_s": 85.0})
    client.release(grant["tid"], grant["token"])
"""

from __future__ import annotations

import argparse
import json
import time
from typing import Any, Dict, List, Optional

from ..native import DssBuffer
from ..runtime.coordinator import local_addr_toward
from ..utils import output
from ..utils.errors import ErrorCode, MPIError
from ..tools.tpu_server import MetricsPubsubTable, NameClient, NameServer
from .tenant import TenantRegistry

_log = output.stream("tpu-serviced")

#: tenant control RPC (admit/renew/release/fail as one JSON doc)
TAG_TENANT = 16
#: per-tenant fabric view (the tpu_top --tenants feed)
TAG_TENANTS = 17

#: env var carrying the SERVICE-plane auth secret. Deliberately
#: distinct from ``OMPITPU_JOB_SECRET``: the daemon is shared by many
#: jobs from different trust domains, so a tenant must never present
#: (or be asked for) another job's private control-plane secret —
#: inside a tpurun worker the ambient job secret would leak into a
#: default-constructed endpoint and the daemon would refuse it.
SERVICE_SECRET_ENV = "OMPITPU_SERVICE_SECRET"


def service_secret() -> bytes:
    """The shared service-plane secret (empty = unauthenticated)."""
    import os

    return os.environ.get(SERVICE_SECRET_ENV, "").encode()


class ServiceTable(MetricsPubsubTable):
    """The daemon's RPC table: names + observability + the tenant
    control plane, one serve loop. ``prune()`` — already run every
    serve iteration by the shared pubsub plumbing — additionally
    sweeps expired leases, so silent tenant death is detected by the
    very loop that serves live ones."""

    def __init__(self, ep, registry: TenantRegistry) -> None:
        super().__init__(ep)
        self.registry = registry
        self.serve_tags.append(TAG_TENANT)
        self.serve_tags.append(TAG_TENANTS)
        # eviction drops the tenant's published names by owner
        # identity — a dead tenant's stale names must never resolve
        # for the next tenant
        registry.add_evict_listener(
            lambda t, reason: self.evict_owner(t.owner))

    def prune(self) -> None:
        super().prune()
        self.registry.sweep()

    def handle(self, tag: int, src: int, raw: bytes) -> None:
        if tag not in (TAG_TENANT, TAG_TENANTS):
            return super().handle(tag, src, raw)
        b = DssBuffer(raw)
        (seq,) = b.unpack_int64()
        if tag == TAG_TENANTS:
            self._reply(src, seq, True,
                        json.dumps(self.registry.doc()))
            return
        try:
            doc = json.loads(b.unpack_string())
            op = str(doc.get("op", ""))
            out = self._tenant_op(op, doc, src)
        except MPIError as e:
            self._reply(src, seq, False, f"{e.code.name}: {e}")
            return
        except Exception as e:
            self._reply(src, seq, False, f"malformed tenant rpc: {e}")
            return
        self._reply(src, seq, True, json.dumps(out))

    def _tenant_op(self, op: str, doc: Dict[str, Any],
                   src: int) -> Dict[str, Any]:
        reg = self.registry
        if op == "admit":
            t = reg.admit(doc.get("name", ""),
                          int(doc.get("ranks", 0)),
                          qos=str(doc.get("qos", "best_effort")),
                          lanes=int(doc.get("lanes", 1)),
                          owner=src,
                          lease_s=doc.get("lease_s"))
            lo, hi = t.band
            return {"tid": t.tid, "token": t.token, "band": [lo, hi],
                    "qos": t.qos, "lease_s": t.lease_s}
        if op == "renew":
            t = reg.renew(int(doc.get("tid", -1)),
                          str(doc.get("token", "")),
                          stats=doc.get("stats"))
            return {"tid": t.tid, "expires_in_s":
                    round(t.expires_at - time.monotonic(), 3)}
        if op == "release":
            t = reg.release(int(doc.get("tid", -1)),
                            str(doc.get("token", "")))
            return {"tid": t.tid, "state": t.state}
        if op == "fail":
            t = reg.fail(int(doc.get("tid", -1)),
                         str(doc.get("token", "")),
                         reason=str(doc.get("reason",
                                            "rank failure reported")))
            return {"tid": t.tid, "state": t.state,
                    "evict_reason": t.evict_reason}
        raise MPIError(ErrorCode.ERR_ARG,
                       f"unknown tenant op {op!r}")


class ServiceDaemon(NameServer):
    """The resident daemon: a :class:`~..tools.tpu_server.NameServer`
    whose table is the tenant-multiplexing :class:`ServiceTable`."""

    def __init__(self, port: int = 0, bind_addr: str = "127.0.0.1", *,
                 capacity_ranks: int = 256, capacity_lanes: int = 64,
                 lease_s: float = 30.0,
                 secret: Optional[bytes] = None) -> None:
        self.registry = TenantRegistry(
            capacity_ranks=capacity_ranks,
            capacity_lanes=capacity_lanes, lease_s=lease_s)
        super().__init__(
            port, bind_addr,
            table_factory=lambda ep: ServiceTable(ep, self.registry),
            secret=service_secret() if secret is None else secret)


class ServiceClient(NameClient):
    """A tenant job's handle on the daemon: the NameClient pubsub RPCs
    plus the tenant control plane. One client per job controller; the
    client id doubles as the tenant's owner identity (name eviction).

    Authenticates with the SERVICE secret (``OMPITPU_SERVICE_SECRET``),
    never the ambient per-job ``OMPITPU_JOB_SECRET`` a tpurun worker
    inherits — the daemon sits outside any one job's trust domain."""

    def __init__(self, host: str, port: int,
                 secret: Optional[bytes] = None) -> None:
        super().__init__(
            host, port,
            secret=service_secret() if secret is None else secret)

    def _tenant_rpc(self, doc: Dict[str, Any], *,
                    timeout_ms: int = 10_000) -> Dict[str, Any]:
        ok, text = self._rpc(TAG_TENANT, json.dumps(doc),
                             timeout_ms=timeout_ms)
        if not ok:
            code = ErrorCode.ERR_OTHER
            for c in (ErrorCode.ERR_NO_MEM, ErrorCode.ERR_NAME,
                      ErrorCode.ERR_ARG):
                if text.startswith(c.name):
                    code = c
                    break
            raise MPIError(code, f"tenant rpc "
                                 f"{doc.get('op')}: {text}")
        return json.loads(text)

    def admit(self, name: str, ranks: int, *,
              qos: str = "best_effort", lanes: int = 1,
              lease_s: Optional[float] = None) -> Dict[str, Any]:
        doc: Dict[str, Any] = {"op": "admit", "name": name,
                               "ranks": int(ranks), "qos": qos,
                               "lanes": int(lanes)}
        if lease_s is not None:
            doc["lease_s"] = float(lease_s)
        return self._tenant_rpc(doc)

    def renew(self, tid: int, token: str,
              stats: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        return self._tenant_rpc({"op": "renew", "tid": int(tid),
                                 "token": token, "stats": stats or {}})

    def release(self, tid: int, token: str) -> Dict[str, Any]:
        return self._tenant_rpc({"op": "release", "tid": int(tid),
                                 "token": token})

    def fail(self, tid: int, token: str,
             reason: str = "rank failure reported") -> Dict[str, Any]:
        return self._tenant_rpc({"op": "fail", "tid": int(tid),
                                 "token": token, "reason": reason})

    def tenants(self, *, timeout_ms: int = 10_000) -> Dict[str, Any]:
        """The TAG_TENANTS fabric view (tpu_top --tenants feed)."""
        ok, text = self._rpc(TAG_TENANTS, timeout_ms=timeout_ms)
        if not ok:
            raise MPIError(ErrorCode.ERR_NAME, f"tenants: {text}")
        return json.loads(text)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu-serviced",
        description="Resident multi-tenant collective daemon "
                    "(names + admission control + per-tenant view)")
    ap.add_argument("--port", type=int, default=0,
                    help="listen port (0 = ephemeral)")
    ap.add_argument("--bind", default="0.0.0.0",
                    help="listen address (default: all interfaces)")
    ap.add_argument("--capacity-ranks", type=int, default=256,
                    help="total ranks admissible across tenants")
    ap.add_argument("--capacity-lanes", type=int, default=64,
                    help="total wire lanes admissible across tenants")
    ap.add_argument("--lease", type=float, default=30.0,
                    help="tenant lease seconds (heartbeat deadline)")
    args = ap.parse_args(argv)
    srv = ServiceDaemon(args.port, args.bind,
                        capacity_ranks=args.capacity_ranks,
                        capacity_lanes=args.capacity_lanes,
                        lease_s=args.lease)
    host = (local_addr_toward("192.0.2.1") if args.bind == "0.0.0.0"
            else args.bind)
    print(f"tpu-serviced URI: {host}:{srv.port}", flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        srv.shutdown()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
