"""service — the multi-tenant service plane (ROADMAP item 2).

A resident daemon (:mod:`.daemon`, ``tpu-serviced``) admits many
independently launched jobs as tenants of one fabric:
:mod:`.tenant` is the admission-control/lease registry over the
tenant cid-band discipline of :mod:`..ft.ulfm`; :mod:`.qos` is the
per-class lane partitioning + weighted-fair fragment scheduling the
:class:`~..runtime.wire.WireRouter` engages under the
``wire_qos_classes`` cvar. Import-light: nothing here touches jax.
"""

from . import qos, tenant  # noqa: F401

__all__ = ["qos", "tenant", "daemon"]


def __getattr__(name):
    if name == "daemon":
        import importlib

        mod = importlib.import_module(".daemon", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
