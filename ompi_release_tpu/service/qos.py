"""QoS classes for the multi-tenant wire — lane partitioning +
weighted-fair frame scheduling.

PR 3 gave the wire per-(destination, tag-class) lanes so independent
tags stop serializing behind one stream; under the service plane the
contention unit is the *tenant*, not the tag: a bulk tenant streaming
256 MiB allgather fragments must not head-of-line-block a latency
tenant's 4 KiB allreduce. Two mechanisms, both keyed by the
``wire_qos_classes`` cvar (``"latency:8,bulk:2,best_effort:1"`` —
ordered ``name:weight`` entries):

- **lane classes** (:func:`lane_ranges`): the ``wire_p2p_lanes`` lane
  space is partitioned into per-class contiguous sub-ranges sized by
  weight (largest-remainder, one lane minimum), so one class's p2p
  transfers never share a channel lock with another class's;
- **weighted-fair fragment scheduling** (:class:`WireArbiter`): a
  virtual-clock deficit gate over the fragment bursts of
  ``coll_send_all`` / ``coll_send_planned`` — each class accumulates
  normalized spend (frames / weight), and a class ahead of every
  other *active* class by more than one quantum parks until the
  others catch up or leave. With a single active class the gate is
  one lock acquire + compare: the solo-tenant fast path stays flat.

A sender's class resolves per communicator: the comm's stamped
``_qos_class`` (tenant comms, see :meth:`~..comm.communicator
.Communicator.set_qos_class`) wins over the process-wide
``wire_qos_class`` cvar. Unknown/empty classes ride the legacy full
lane range at weight 1. With ``wire_qos_classes`` unset nothing here
is ever imported by the wire — the zero-config path is byte-for-byte
the PR 3 behavior.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from .. import obs as _obs
from ..mca import pvar as _pvar
from ..mca import var as _var
from ..utils.errors import ErrorCode, MPIError

#: frames a class may run ahead of the slowest active class before
#: its gate parks (the DRR quantum — small enough that a latency
#: burst preempts within one pipeline window, large enough that the
#: gate never thrashes on single-fragment rounds)
DEFAULT_QUANTUM = 16.0

_gate_waits = _pvar.counter(
    "wire_qos_gate_waits",
    "fragment bursts the weighted-fair QoS arbiter parked because "
    "their class was ahead of other active classes' fair share",
)
_gate_wait_s = _pvar.timer(
    "wire_qos_gate_wait_seconds",
    "seconds senders spent parked in the QoS arbiter's weighted-fair "
    "gate (the bulk tenant paying for the latency tenant's share)",
)


def register_vars() -> None:
    _var.register(
        "wire_qos_classes", "str", "",
        "Ordered QoS class spec 'name:weight,...' (e.g. "
        "'latency:8,bulk:2,best_effort:1'): partitions the "
        "wire_p2p_lanes lane space per class and arms weighted-fair "
        "scheduling of collective fragment bursts. Empty = off (the "
        "single-tenant legacy wire, zero added cost)",
    )
    _var.register(
        "wire_qos_class", "str", "",
        "This process's default QoS class (a tenant job sets it at "
        "admission); a communicator's stamped class overrides it. "
        "Unknown/empty classes ride the legacy full lane range",
    )


register_vars()  # idempotent; cvars must exist before the first router


def parse_classes(spec: str) -> Dict[str, float]:
    """``"latency:8,bulk:2"`` -> ordered ``{name: weight}``. A bare
    name gets weight 1; malformed weights raise loudly (a typo'd QoS
    config silently collapsing to FIFO would defeat the whole plane)."""
    out: Dict[str, float] = {}
    for part in str(spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, _, w = part.partition(":")
        name = name.strip()
        if not name:
            raise MPIError(ErrorCode.ERR_ARG,
                           f"wire_qos_classes entry {part!r} has no "
                           "class name")
        try:
            weight = float(w) if w.strip() else 1.0
        except ValueError:
            raise MPIError(ErrorCode.ERR_ARG,
                           f"wire_qos_classes weight {w!r} for class "
                           f"'{name}' is not a number")
        if weight <= 0:
            raise MPIError(ErrorCode.ERR_ARG,
                           f"wire_qos_classes weight {weight} for "
                           f"class '{name}' must be > 0")
        out[name] = weight
    return out


def fair_share(cls: str, classes: Dict[str, float]) -> float:
    """``cls``'s guaranteed fraction of the wire under contention
    from every other class — the bound the isolation tests and the
    fleet-sim contention model key on."""
    total = sum(classes.values())
    if total <= 0 or cls not in classes:
        return 1.0
    return classes[cls] / total


def lane_ranges(classes: Dict[str, float],
                nlanes: int) -> Dict[str, Tuple[int, int]]:
    """Partition ``nlanes`` p2p lanes into per-class contiguous
    ``(start, count)`` sub-ranges, weight-proportional by largest
    remainder with a one-lane minimum. More classes than lanes:
    class i shares lane ``i % nlanes`` (count 1) — degraded but never
    starved."""
    names = list(classes)
    n = max(1, int(nlanes))
    if not names:
        return {}
    if len(names) > n:
        return {name: (i % n, 1) for i, name in enumerate(names)}
    total = sum(classes.values())
    exact = {name: classes[name] / total * n for name in names}
    counts = {name: max(1, int(exact[name])) for name in names}
    # largest-remainder distribution of the leftover lanes
    left = n - sum(counts.values())
    by_rem = sorted(names, key=lambda m: (exact[m] - int(exact[m]),
                                          classes[m]), reverse=True)
    i = 0
    while left > 0:
        counts[by_rem[i % len(by_rem)]] += 1
        left -= 1
        i += 1
    while left < 0:  # one-lane minimums overshot: shave the largest
        big = max(names, key=lambda m: counts[m])
        if counts[big] <= 1:  # pragma: no cover - len(names) <= n
            break
        counts[big] -= 1
        left += 1
    out: Dict[str, Tuple[int, int]] = {}
    start = 0
    for name in names:
        out[name] = (start, counts[name])
        start += counts[name]
    return out


class WireArbiter:
    """Weighted-fair virtual-clock gate over concurrent wire senders.

    Every class carries a normalized spend ``vt = frames / weight``.
    :meth:`gate` (called once per fragment burst) parks while this
    class's vt exceeds the minimum vt among the OTHER active classes
    by more than ``quantum / weight`` — so at steady contention the
    per-class frame throughput converges to the weight ratio, while a
    class alone on the wire never waits. A class entering from idle
    catches its clock up to the active minimum (no credit banked for
    idle time — the classic virtual-clock rule). Waits are bounded
    slices so a stalled peer class can only slow, never wedge, the
    gate."""

    def __init__(self, classes: Dict[str, float],
                 quantum: float = DEFAULT_QUANTUM) -> None:
        self._w = {str(k): max(float(v), 1e-9)
                   for k, v in classes.items()}
        self._quantum = float(quantum)
        self._cond = threading.Condition()
        self._active: Dict[str, int] = {}
        self._vt: Dict[str, float] = {}

    def weight(self, cls: Optional[str]) -> float:
        return self._w.get(cls or "", 1.0)

    def _min_other_vt(self, cls: str) -> Optional[float]:
        others = [self._vt.get(c, 0.0) for c, n in self._active.items()
                  if n > 0 and c != cls]
        return min(others) if others else None

    def enter(self, cls: Optional[str]) -> None:
        cls = cls or ""
        with self._cond:
            if self._active.get(cls, 0) == 0:
                floor = self._min_other_vt(cls)
                if floor is not None:
                    self._vt[cls] = max(self._vt.get(cls, 0.0), floor)
            self._active[cls] = self._active.get(cls, 0) + 1

    def leave(self, cls: Optional[str]) -> None:
        cls = cls or ""
        with self._cond:
            n = self._active.get(cls, 1) - 1
            if n <= 0:
                self._active.pop(cls, None)
            else:
                self._active[cls] = n
            self._cond.notify_all()

    def gate(self, cls: Optional[str], cost: float = 1.0) -> None:
        cls = cls or ""
        slack = self._quantum / self.weight(cls)
        with self._cond:
            waited = False
            t0 = 0.0
            while True:
                floor = self._min_other_vt(cls)
                if floor is None or \
                        self._vt.get(cls, 0.0) <= floor + slack:
                    break
                if not waited:
                    waited = True
                    t0 = time.perf_counter()
                    _gate_waits.add()
                self._cond.wait(timeout=0.05)
            self._vt[cls] = (self._vt.get(cls, 0.0)
                             + float(cost) / self.weight(cls))
            if waited:
                dt = time.perf_counter() - t0
                _gate_wait_s.add(dt)
                if _obs.enabled:
                    # the HOL wait this class paid for the others'
                    # fair share — visible in traces per burst
                    _obs.record(f"qos_gate_wait:{cls or '-'}", "wire",
                                t0, dt, nbytes=int(cost))
            self._cond.notify_all()

    def spend(self, cls: Optional[str]) -> float:
        """Normalized spend (test/monitoring hook)."""
        with self._cond:
            return self._vt.get(cls or "", 0.0)


#: one arbiter per class spec: every WireTuning generation sharing a
#: spec shares one arbiter, so fairness state survives cvar-generation
#: churn on unrelated cvars
_arbiters: Dict[str, WireArbiter] = {}
_arbiters_lock = threading.Lock()


def arbiter_for(spec: str) -> WireArbiter:
    with _arbiters_lock:
        arb = _arbiters.get(spec)
        if arb is None:
            arb = _arbiters[spec] = WireArbiter(parse_classes(spec))
        return arb


def _reset_for_tests() -> None:
    with _arbiters_lock:
        _arbiters.clear()
