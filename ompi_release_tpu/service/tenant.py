"""Tenant registry — admission control, leases, and scoped eviction.

The resident daemon (:mod:`.daemon`) admits many independently
launched jobs onto one fabric; this module is the bookkeeping that
makes them *tenants* instead of noisy neighbors:

- **admission control**: capacity in ranks and lanes, a bounded
  tenant-id space (the cid-band discipline of
  :mod:`..ft.ulfm` — 64 slots of 4096 cids each), duplicate-name
  refusal. Denials are typed errors, counted in
  ``service_admissions_denied``.
- **leases + heartbeats**: every tenant holds a lease (a secret
  token, an expiry) renewed by heartbeat; :meth:`TenantRegistry
  .sweep` evicts expired tenants — the daemon's serve loop runs it
  every iteration, so a tenant whose job died silently is gone within
  one lease, its published names pruned and its cid band revoked.
- **scoped eviction**: eviction revokes exactly the tenant's cid band
  through the real ULFM machinery (:meth:`~..ft.ulfm.FtState
  .revoke_band`), clears its sentinel chains, and notifies listeners
  (the daemon evicts the tenant's pubsub names by owner). Other
  tenants and the daemon never notice. A freed tenant slot is
  re-admittable: admission clears the stale band/chain state exactly
  like the explicit-cid rebuild path.

Import-light by design (no jax): the registry runs inside the daemon
process, inside tests, and inside the fleet simulator.
"""

from __future__ import annotations

import secrets
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from .. import obs as _obs
from ..ft import ulfm as _ulfm
from ..mca import pvar as _pvar
from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.stream("tenant")

DEFAULT_LEASE_S = 30.0
#: evicted-tenant records kept for the TAG_TENANTS forensics view
EVICTED_KEEP = 32

_admitted = _pvar.counter(
    "service_tenants_admitted",
    "tenants admitted to this service daemon's fabric",
)
_evicted = _pvar.counter(
    "service_tenants_evicted",
    "tenants evicted (released, failed, or lease-expired)",
)
_denied = _pvar.counter(
    "service_admissions_denied",
    "tenant admissions refused by capacity/identity admission control",
)


class Tenant:
    """One admitted tenant: identity, lease, capacity grant, QoS
    class, and the stats document its heartbeats report."""

    __slots__ = ("tid", "name", "owner", "qos", "ranks", "lanes",
                 "lease_s", "token", "admitted_at", "last_beat",
                 "expires_at", "state", "evict_reason", "stats")

    def __init__(self, tid: int, name: str, owner: Any, qos: str,
                 ranks: int, lanes: int, lease_s: float) -> None:
        now = time.monotonic()
        self.tid = tid
        self.name = name
        self.owner = owner
        self.qos = qos
        self.ranks = int(ranks)
        self.lanes = int(lanes)
        self.lease_s = float(lease_s)
        self.token = secrets.token_hex(8)
        self.admitted_at = now
        self.last_beat = now
        self.expires_at = now + self.lease_s
        self.state = "live"
        self.evict_reason: Optional[str] = None
        self.stats: Dict[str, Any] = {}

    @property
    def band(self) -> tuple:
        return _ulfm.tenant_band(self.tid)

    def doc(self, now: Optional[float] = None) -> Dict[str, Any]:
        """JSON-able record (no token: the lease secret never rides
        the TAG_TENANTS listing)."""
        now = time.monotonic() if now is None else now
        lo, hi = self.band
        return {
            "tid": self.tid, "name": self.name, "qos": self.qos,
            "ranks": self.ranks, "lanes": self.lanes,
            "state": self.state, "evict_reason": self.evict_reason,
            "band": [lo, hi], "lease_s": self.lease_s,
            "age_s": round(now - self.admitted_at, 3),
            "beat_age_s": round(now - self.last_beat, 3),
            "expires_in_s": round(self.expires_at - now, 3),
            "stats": dict(self.stats),
        }


class TenantRegistry:
    """Admission control + leases over the tenant cid-band space."""

    def __init__(self, *, capacity_ranks: int = 256,
                 capacity_lanes: int = 64,
                 lease_s: float = DEFAULT_LEASE_S,
                 max_tenants: int = _ulfm.MAX_TENANTS) -> None:
        self.capacity_ranks = int(capacity_ranks)
        self.capacity_lanes = int(capacity_lanes)
        self.lease_s = float(lease_s)
        self._lock = threading.Lock()
        self._tenants: Dict[int, Tenant] = {}
        self._free_tids: List[int] = list(
            range(min(int(max_tenants), _ulfm.MAX_TENANTS)))
        self._evicted: deque = deque(maxlen=EVICTED_KEEP)
        self._listeners: List[Callable[[Tenant, str], None]] = []

    # -- wiring ------------------------------------------------------------
    def add_evict_listener(
            self, cb: Callable[[Tenant, str], None]) -> None:
        """``cb(tenant, reason)`` runs on every eviction (the daemon
        registers pubsub name pruning here). A raising listener never
        blocks the eviction."""
        self._listeners.append(cb)

    # -- queries -----------------------------------------------------------
    def live(self) -> List[Tenant]:
        with self._lock:
            return sorted(self._tenants.values(), key=lambda t: t.tid)

    def get(self, tid: int) -> Optional[Tenant]:
        with self._lock:
            return self._tenants.get(int(tid))

    def used_ranks(self) -> int:
        with self._lock:
            return sum(t.ranks for t in self._tenants.values())

    def used_lanes(self) -> int:
        with self._lock:
            return sum(t.lanes for t in self._tenants.values())

    def doc(self) -> Dict[str, Any]:
        """The TAG_TENANTS listing: live tenants, recent evictions,
        capacity."""
        now = time.monotonic()
        with self._lock:
            live = [t.doc(now) for t in
                    sorted(self._tenants.values(), key=lambda t: t.tid)]
            gone = [t.doc(now) for t in self._evicted]
            used_r = sum(t.ranks for t in self._tenants.values())
            used_l = sum(t.lanes for t in self._tenants.values())
        return {
            "tenants": live, "evicted": gone,
            "capacity": {"ranks": self.capacity_ranks,
                         "lanes": self.capacity_lanes,
                         "used_ranks": used_r, "used_lanes": used_l},
        }

    # -- admission ---------------------------------------------------------
    def admit(self, name: str, ranks: int, *, qos: str = "best_effort",
              lanes: int = 1, owner: Any = None,
              lease_s: Optional[float] = None) -> Tenant:
        """Admit one tenant or raise typed: ERR_ARG on a malformed
        request, ERR_NAME on a duplicate live name, ERR_NO_MEM when
        rank/lane capacity or the tenant-id space is exhausted."""
        name = str(name or "").strip()
        ranks = int(ranks)
        lanes = int(lanes)
        if not name or ranks <= 0 or lanes <= 0:
            _denied.add()
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"admission needs a name and positive ranks/lanes "
                f"(got name={name!r}, ranks={ranks}, lanes={lanes})",
            )
        with self._lock:
            if any(t.name == name for t in self._tenants.values()):
                _denied.add()
                raise MPIError(
                    ErrorCode.ERR_NAME,
                    f"tenant name '{name}' already admitted — release "
                    "it or pick another identity",
                )
            used_r = sum(t.ranks for t in self._tenants.values())
            used_l = sum(t.lanes for t in self._tenants.values())
            if used_r + ranks > self.capacity_ranks \
                    or used_l + lanes > self.capacity_lanes:
                _denied.add()
                raise MPIError(
                    ErrorCode.ERR_NO_MEM,
                    f"admission of '{name}' ({ranks} ranks, {lanes} "
                    f"lanes) exceeds capacity "
                    f"({used_r}/{self.capacity_ranks} ranks, "
                    f"{used_l}/{self.capacity_lanes} lanes in use)",
                )
            if not self._free_tids:
                _denied.add()
                raise MPIError(
                    ErrorCode.ERR_NO_MEM,
                    f"admission of '{name}': tenant-id space exhausted "
                    f"({_ulfm.MAX_TENANTS} slots)",
                )
            tid = self._free_tids.pop(0)
            t = Tenant(tid, name, owner, str(qos), ranks, lanes,
                       float(lease_s if lease_s is not None
                             else self.lease_s))
            self._tenants[tid] = t
        # a reused slot starts with a clean namespace: clear the
        # evicted predecessor's band poison + sentinel chains (the
        # explicit-cid rebuild discipline, band-wide)
        lo, hi = t.band
        _ulfm.state().clear_band(lo, hi)
        from ..obs import sentinel as _sentinel

        _sentinel.clear_band(lo, hi)
        _admitted.add()
        if _obs.enabled:
            # incident-timeline food: who joined the fabric, when,
            # with which band (comm slot) and capacity (bytes slot)
            _obs.record(f"tenant_admit:{name}", "service",
                        time.perf_counter(), 0.0, peer=tid,
                        comm_id=lo, nbytes=ranks)
        _log.verbose(1, f"admitted tenant {tid} '{name}' qos={qos} "
                        f"ranks={ranks} lanes={lanes} band=[{lo},{hi})")
        return t

    # -- leases ------------------------------------------------------------
    def _auth(self, tid: int, token: str) -> Tenant:
        t = self._tenants.get(int(tid))
        if t is None:
            raise MPIError(ErrorCode.ERR_NAME,
                           f"unknown/evicted tenant id {tid}")
        if str(token) != t.token:
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"bad lease token for tenant {tid} — another tenant "
                "cannot renew or release this lease",
            )
        return t

    def renew(self, tid: int, token: str,
              stats: Optional[Dict[str, Any]] = None) -> Tenant:
        """Heartbeat: extend the lease, fold the tenant's reported
        stats (coll/s, MB/s, lane share, HOL wait — whatever the job
        measures about itself) into the TAG_TENANTS view."""
        with self._lock:
            t = self._auth(tid, token)
            now = time.monotonic()
            t.last_beat = now
            t.expires_at = now + t.lease_s
            if stats:
                t.stats.update(
                    {str(k): v for k, v in stats.items()})
            return t

    def release(self, tid: int, token: str) -> Tenant:
        """Graceful exit: authenticated self-eviction."""
        with self._lock:
            t = self._auth(tid, token)
        return self._do_evict(t, "released")

    def fail(self, tid: int, token: str,
             reason: str = "rank failure reported") -> Tenant:
        """A tenant reporting its own rank death (the ULFM episode):
        eviction with the failure named — the band revoke is the
        'only that tenant's comms' guarantee."""
        with self._lock:
            t = self._auth(tid, token)
        return self._do_evict(t, reason)

    def evict(self, tid: int, reason: str) -> Optional[Tenant]:
        """Registry-side eviction (no token: the daemon operator and
        the sweep own this path)."""
        with self._lock:
            t = self._tenants.get(int(tid))
        if t is None:
            return None
        return self._do_evict(t, reason)

    def _do_evict(self, t: Tenant, reason: str) -> Tenant:
        with self._lock:
            if self._tenants.get(t.tid) is not t:
                return t  # already evicted (idempotent)
            del self._tenants[t.tid]
            t.state = "evicted"
            t.evict_reason = reason
            self._evicted.append(t)
            self._free_tids.append(t.tid)
            self._free_tids.sort()
        # the scoped revoke: exactly this tenant's cid band — live
        # comms poisoned through the real ULFM path, the band record
        # covering any future cid a straggler mints
        lo, hi = t.band
        _ulfm.state().revoke_band(lo, hi)
        from ..obs import sentinel as _sentinel

        _sentinel.clear_band(lo, hi)
        if _obs.enabled:
            _obs.record(f"tenant_evict:{t.name}:{reason}", "service",
                        time.perf_counter(), 0.0, peer=t.tid,
                        comm_id=lo, nbytes=t.ranks)
        for cb in list(self._listeners):
            try:
                cb(t, reason)
            except Exception as e:
                _log.verbose(1, f"evict listener failed: {e}")
        _evicted.add()
        _log.verbose(1, f"evicted tenant {t.tid} '{t.name}': {reason}")
        return t

    def sweep(self, now: Optional[float] = None) -> List[Tenant]:
        """Evict every live tenant whose lease expired (the daemon's
        serve loop runs this each iteration — lease expiry IS the
        lifeline-loss detector for silently dead jobs)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            expired = [t for t in self._tenants.values()
                       if t.expires_at <= now]
        return [self._do_evict(
            t, f"lease expired (no heartbeat for "
               f"{now - t.last_beat:.1f}s)") for t in expired]

    def note_owner_lost(self, owner: Any) -> List[Tenant]:
        """Lifeline loss: evict every live tenant admitted by
        ``owner`` (the daemon calls this when a client connection is
        known dead ahead of its lease expiry)."""
        with self._lock:
            lost = [t for t in self._tenants.values()
                    if t.owner == owner]
        return [self._do_evict(t, "owner lifeline lost")
                for t in lost]
