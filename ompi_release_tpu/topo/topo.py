"""Cartesian / graph / dist-graph topologies + neighborhood collectives.

The reference's ``topo/basic`` component (``ompi/mca/topo``, SURVEY
§2.3) provides rank<->coordinate math and neighbor queries attached to
a communicator; neighborhood collectives live in coll. On TPU the cart
topology is doubly load-bearing: laying a cart communicator onto the
mesh in device order keeps grid neighbors physically adjacent on the
ICI torus, and the static neighbor lists compile into single ppermute
programs (one per direction) for the neighborhood collectives.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax.numpy as jnp

from ..runtime.mesh import factorize_torus
from ..utils.errors import ErrorCode, MPIError


def dims_create(nnodes: int, ndims: int,
                dims: Optional[Sequence[int]] = None) -> Tuple[int, ...]:
    """MPI_Dims_create: fill zero entries of ``dims`` with a balanced
    factorization."""
    if dims is None or not any(dims):
        return factorize_torus(nnodes, ndims)
    dims = list(dims)
    fixed = int(np.prod([d for d in dims if d > 0])) if any(
        d > 0 for d in dims
    ) else 1
    if nnodes % fixed:
        raise MPIError(
            ErrorCode.ERR_DIMS,
            f"cannot fill dims {dims} for {nnodes} nodes",
        )
    free = [i for i, d in enumerate(dims) if d <= 0]
    if not free:
        if fixed != nnodes:
            raise MPIError(
                ErrorCode.ERR_DIMS,
                f"fully-specified dims {dims} have product {fixed} != "
                f"{nnodes} nodes",
            )
        return tuple(dims)
    fills = factorize_torus(nnodes // fixed, len(free))
    for i, f in zip(free, fills):
        dims[i] = f
    return tuple(dims)


class CartTopo:
    """Cartesian topology attached to a communicator."""

    def __init__(self, comm, dims: Sequence[int],
                 periods: Sequence[bool]) -> None:
        self.comm = comm
        self.dims = tuple(int(d) for d in dims)
        self.periods = tuple(bool(p) for p in periods)
        if int(np.prod(self.dims)) != comm.size:
            raise MPIError(
                ErrorCode.ERR_DIMS,
                f"cart dims {self.dims} != comm size {comm.size}",
            )

    @property
    def ndims(self) -> int:
        return len(self.dims)

    def coords(self, rank: int) -> Tuple[int, ...]:
        """MPI_Cart_coords (row-major, like the reference)."""
        c = []
        for d in reversed(self.dims):
            c.append(rank % d)
            rank //= d
        return tuple(reversed(c))

    def rank(self, coords: Sequence[int]) -> int:
        """MPI_Cart_rank; periodic dims wrap, others must be in range."""
        r = 0
        for d, p, c in zip(self.dims, self.periods, coords):
            if p:
                c %= d
            elif not 0 <= c < d:
                return -1  # MPI_PROC_NULL
            r = r * d + c
        return r

    def shift(self, dim: int, disp: int, rank: int) -> Tuple[int, int]:
        """MPI_Cart_shift -> (source, dest); -1 = MPI_PROC_NULL."""
        c = list(self.coords(rank))
        cd = list(c)
        cd[dim] += disp
        cs = list(c)
        cs[dim] -= disp
        return self.rank(cs), self.rank(cd)

    def _neighbor_at(self, rank: int, dim: int, delta: int) -> int:
        c = list(self.coords(rank))
        c[dim] += delta
        return self.rank(c)

    def neighbors(self, rank: int) -> List[int]:
        """Neighborhood order per MPI: for each dim, -1 then +1."""
        return [
            self._neighbor_at(rank, dim, delta)
            for dim in range(self.ndims)
            for delta in (-1, 1)
        ]

    def sub(self, remain_dims: Sequence[bool]):
        """MPI_Cart_sub: partition into sub-grids over the kept dims.
        Driver mode: returns the per-rank list of (subcomm, subtopo)."""
        keep = [i for i, k in enumerate(remain_dims) if k]
        drop = [i for i, k in enumerate(remain_dims) if not k]
        colors = []
        for r in range(self.comm.size):
            c = self.coords(r)
            color = 0
            for i in drop:
                color = color * self.dims[i] + c[i]
            colors.append(color)
        subs = self.comm.split(colors)
        sub_dims = tuple(self.dims[i] for i in keep)
        sub_periods = tuple(self.periods[i] for i in keep)
        out = []
        seen: Dict[int, CartTopo] = {}
        for r, sc in enumerate(subs):
            if sc is None:
                out.append(None)
                continue
            if sc.cid not in seen:
                topo = CartTopo(sc, sub_dims, sub_periods)
                sc.topo = topo
                seen[sc.cid] = topo
            out.append((sc, seen[sc.cid]))
        return out

    # -- neighborhood collectives (static ppermute programs) --------------
    def neighbor_perms(self) -> List[List[Tuple[int, int]]]:
        """One static (src, dst) edge list per neighbor slot, in the
        MPI neighbor order — each compiles to one ppermute."""
        perms: List[List[Tuple[int, int]]] = []
        for dim in range(self.ndims):
            for delta in (-1, 1):
                edges = []
                for r in range(self.comm.size):
                    nbr = self._neighbor_at(r, dim, delta)
                    if nbr >= 0:
                        edges.append((nbr, r))
                perms.append(edges)
        return perms

    def neighbor_allgather(self, x):
        """MPI_Neighbor_allgather, driver mode: x has a leading rank
        axis; returns (size, n_neighbors, ...) — slot order matches
        ``neighbors()``; missing neighbors (non-periodic edge) yield
        zeros."""
        from jax import lax

        from ..coll.driver import run_sharded

        perms = self.neighbor_perms()

        def body(xb):
            outs = [
                lax.ppermute(xb, "rank", p) for p in perms
            ]
            return jnp.stack(outs, axis=0)

        return run_sharded(
            self.comm, ("topo", "neighbor_allgather", len(perms)), body, x
        )

    def neighbor_alltoall(self, x):
        """MPI_Neighbor_alltoall: x is (size, n_neighbors, ...) — block
        j goes to neighbor slot j; received blocks keep slot order."""
        from jax import lax

        from ..coll.driver import run_sharded

        perms = self.neighbor_perms()
        nn = len(perms)
        if x.shape[1] != nn:
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"neighbor_alltoall needs {nn} blocks per rank",
            )
        # slot j (dim, disp) sends to the OPPOSITE slot at the neighbor:
        # what I send "left" arrives at my left neighbor's "right" slot
        def body(xb):
            outs = []
            for j, p in enumerate(perms):
                opp = j ^ 1  # (-1 <-> +1) within the same dim
                send = xb[opp]
                outs.append(lax.ppermute(send, "rank", p))
            return jnp.stack(outs, axis=0)

        return run_sharded(
            self.comm, ("topo", "neighbor_alltoall", nn), body, x
        )


class GraphTopo:
    """MPI_Graph_create analogue (index/edges arrays)."""

    def __init__(self, comm, index: Sequence[int],
                 edges: Sequence[int]) -> None:
        self.comm = comm
        self.index = tuple(index)
        self.edges = tuple(edges)
        if len(index) != comm.size:
            raise MPIError(
                ErrorCode.ERR_TOPOLOGY,
                f"graph index length {len(index)} != comm size",
            )

    def neighbors(self, rank: int) -> List[int]:
        lo = self.index[rank - 1] if rank else 0
        return list(self.edges[lo:self.index[rank]])


class DistGraphTopo:
    """MPI_Dist_graph_create_adjacent analogue."""

    def __init__(self, comm, sources: Sequence[int],
                 destinations: Sequence[int]) -> None:
        self.comm = comm
        self.sources = tuple(sources)
        self.destinations = tuple(destinations)


def cart_create(comm, dims: Sequence[int],
                periods: Optional[Sequence[bool]] = None,
                reorder: bool = True):
    """MPI_Cart_create: dup the comm, attach a cart topology.

    ``reorder=True`` keeps device order (ranks stay mesh-contiguous so
    grid neighbors sit on adjacent ICI links — on TPU reordering INTO
    device order is always the right answer).
    """
    dims = dims_create(comm.size, len(dims), dims)
    if periods is None:
        periods = [False] * len(dims)
    c = comm.dup(name=f"cart{tuple(dims)}")
    topo = CartTopo(c, dims, periods)
    c.topo = topo
    return c, topo


def graph_create(comm, index: Sequence[int], edges: Sequence[int]):
    c = comm.dup(name="graph")
    topo = GraphTopo(c, index, edges)
    c.topo = topo
    return c, topo


def dist_graph_create_adjacent(comm, sources: Sequence[int],
                               destinations: Sequence[int]):
    c = comm.dup(name="dist_graph")
    topo = DistGraphTopo(c, sources, destinations)
    c.topo = topo
    return c, topo
