"""Exporters — journal and pvars in standard tool formats.

Three consumers, three formats, one data source:

  - :func:`chrome_trace` / :func:`dump_chrome_trace`: Chrome/Perfetto
    ``trace_event`` JSON (load in chrome://tracing or ui.perfetto.dev).
    One pseudo-thread per layer (named via ``thread_name`` metadata
    events); spans with dt > 0 are complete events ("X"), instant
    emit points are thread-scoped instants ("i").
  - :func:`dump_jsonl`: one JSON object per span (the tracer sink's
    line format), for ad-hoc grep/pandas analysis.
  - :func:`prometheus_text`: text exposition of every registered pvar
    (``ompitpu_<name>``), served by the ``tpu_server`` metrics RPC and
    rendered live by ``tpu_top --metrics``. HISTOGRAM pvars become
    real Prometheus histograms (cumulative ``_bucket{le=...}`` +
    ``_sum``/``_count``), AGGREGATE pvars a gauge family.
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional, Sequence

from ..mca import pvar as _pvar
from .journal import JOURNAL as _JOURNAL
from .journal import Span

# ---------------------------------------------------------------------------
# Chrome/Perfetto trace_event
# ---------------------------------------------------------------------------


def span_event(s: Dict[str, Any], pid: int, tid: int,
               ts_s: Optional[float] = None) -> Dict[str, Any]:
    """One journal span (``Span.asdict`` form) as a Chrome
    ``trace_event`` — THE conversion shared by the single-rank
    :func:`chrome_trace` and tpu-doctor's multi-rank merge, so the two
    trace shapes cannot drift. ``ts_s`` overrides the span's own
    timestamp (the merge passes clock-offset-corrected seconds)."""
    args = {"bytes": s.get("bytes", 0), "peer": s.get("peer", -1),
            "comm": s.get("comm", -1), "seq": s.get("seq", -1)}
    if s.get("flow"):
        args["flow"] = s["flow"]
        args["flow_side"] = s.get("fs", "")
    ev: Dict[str, Any] = {
        "name": s["op"], "cat": s["layer"], "pid": pid, "tid": tid,
        # trace_event wants microseconds
        "ts": (s["t"] if ts_s is None else ts_s) * 1e6,
        "args": args,
    }
    if s["dt"] > 0:
        ev["ph"] = "X"
        ev["dur"] = s["dt"] * 1e6
    else:
        ev["ph"] = "i"
        ev["s"] = "t"  # thread-scoped instant
    return ev


def chrome_trace(spans: Optional[Sequence[Span]] = None) -> Dict[str, Any]:
    """The journal as a ``trace_event`` JSON document (dict form)."""
    if spans is None:
        spans = _JOURNAL.snapshot()
    tids: Dict[str, int] = {}
    events: List[Dict[str, Any]] = []
    for s in spans:
        tid = tids.setdefault(s.layer, len(tids) + 1)
        events.append(span_event(s.asdict(), pid=0, tid=tid))
    meta = [
        {"name": "process_name", "ph": "M", "pid": 0,
         "args": {"name": "ompi_release_tpu"}},
    ] + [
        {"name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
         "args": {"name": layer}}
        for layer, tid in sorted(tids.items(), key=lambda kv: kv[1])
    ]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def dump_chrome_trace(path: str,
                      spans: Optional[Sequence[Span]] = None) -> str:
    with open(path, "w") as f:
        json.dump(chrome_trace(spans), f)
    return path


def dump_jsonl(path: str, spans: Optional[Sequence[Span]] = None) -> str:
    if spans is None:
        spans = _JOURNAL.snapshot()
    with open(path, "w") as f:
        for s in spans:
            f.write(json.dumps(s.asdict()) + "\n")
    return path


# ---------------------------------------------------------------------------
# per-rank journal dump (the tpu-doctor merge input)
# ---------------------------------------------------------------------------


def rank_dump(clock_sync: bool = True) -> Dict[str, Any]:
    """This process's journal + identity + OOB clock offset as one
    JSON-able document — the unit ``tpu-doctor merge`` joins across
    ranks. ``clock_sync=True`` refreshes the offset against the HNP
    when an agent link exists (a few OOB round trips)."""
    from .. import obs as _obs

    meta: Dict[str, Any] = _obs.rank_identity()
    if clock_sync:
        try:
            from ..runtime.runtime import Runtime

            rt = Runtime._instance
            if rt is not None and rt.agent is not None:
                off, rtt = rt.agent.clock_sync()
                _obs.set_clock(off, rtt)
        except Exception:
            pass  # offset stays at its last/None value
    meta["clock_offset_s"] = _obs._clock_state["offset_s"]
    meta["clock_rtt_s"] = _obs._clock_state["rtt_s"]
    from . import sentinel as _sentinel

    if _sentinel.enabled:
        # the per-comm signature chains ride the finalize dump: the
        # doctor's contracts alignment can cross-check chain values
        # even when the journal ring wrapped past early rounds
        meta["sentinel"] = _sentinel.chains_snapshot()
    return {"meta": meta,
            "spans": [s.asdict() for s in _JOURNAL.snapshot()]}


def dump_rank_journal(path: str, clock_sync: bool = True) -> str:
    with open(path, "w") as f:
        json.dump(rank_dump(clock_sync=clock_sync), f)
    return path


def maybe_dump_rank_journal(runtime=None) -> Optional[str]:
    """Finalize hook: when ``obs_dump_dir`` is set (and obs is on),
    write this rank's journal dump there. Returns the path or None."""
    import os

    from ..mca import var as _var

    d = str(_var.get("obs_dump_dir", "") or "")
    if not d:
        return None
    os.makedirs(d, exist_ok=True)
    pidx = 0
    if runtime is not None and runtime.bootstrap:
        pidx = int(runtime.bootstrap.get("process_index", 0))
    return dump_rank_journal(os.path.join(d, f"journal-p{pidx}.json"))


# ---------------------------------------------------------------------------
# continuous time-series (the sampler ring, obs/sampler.py)
# ---------------------------------------------------------------------------


def series_dump() -> Dict[str, Any]:
    """This process's continuous sampler ring + identity + clock
    offset as one JSON-able document — the TAG_SERIES RPC unit and
    the per-rank series-dump payload (same meta shape as
    :func:`rank_dump`, so the doctor's clock correction is shared)."""
    from .. import obs as _obs
    from . import sampler as _sampler

    meta: Dict[str, Any] = _obs.rank_identity()
    meta["clock_offset_s"] = _obs._clock_state["offset_s"]
    meta["clock_rtt_s"] = _obs._clock_state["rtt_s"]
    return {"meta": meta, "points": _sampler.snapshot()}


def dump_series_jsonl(path: str,
                      doc: Optional[Dict[str, Any]] = None) -> str:
    """Series dump as JSONL: first line is the meta header (tagged
    ``"meta"``), then one point per line — greppable, streamable, and
    what ``tpu-doctor`` merges with clock correction."""
    if doc is None:
        doc = series_dump()
    with open(path, "w") as f:
        f.write(json.dumps({"meta": doc["meta"]}) + "\n")
        for p in doc["points"]:
            f.write(json.dumps(p) + "\n")
    return path


def maybe_dump_series(runtime=None) -> Optional[str]:
    """Finalize hook: when ``obs_dump_dir`` is set (and obs is on),
    write this rank's time-series ring there as
    ``series-p<pidx>.jsonl``. Empty rings write nothing (sampler was
    never armed)."""
    import os

    from ..mca import var as _var
    from . import sampler as _sampler

    d = str(_var.get("obs_dump_dir", "") or "")
    if not d or not _sampler.snapshot():
        return None
    os.makedirs(d, exist_ok=True)
    pidx = 0
    if runtime is not None and runtime.bootstrap:
        pidx = int(runtime.bootstrap.get("process_index", 0))
    return dump_series_jsonl(os.path.join(d, f"series-p{pidx}.jsonl"))


def maybe_dump_ledger(runtime=None) -> Optional[str]:
    """Finalize hook: when ``obs_dump_dir`` is set, write this rank's
    compiled-fire flight recorder there as ``ledger-p<pidx>.json``
    (frozen-plan metadata + fixed-size fire records; tpu-doctor
    expands it into synthetic spans next to the journal dump). Empty
    rings write nothing (no compiled fire was observed)."""
    import os

    from ..mca import var as _var
    from . import ledger as _ledger

    d = str(_var.get("obs_dump_dir", "") or "")
    if not d or not _ledger.records():
        return None
    os.makedirs(d, exist_ok=True)
    pidx = 0
    if runtime is not None and runtime.bootstrap:
        pidx = int(runtime.bootstrap.get("process_index", 0))
    return _ledger.dump(os.path.join(d, f"ledger-p{pidx}.json"))


def maybe_dump_nativeev(runtime=None) -> Optional[str]:
    """Finalize hook: when ``obs_dump_dir`` is set and the native
    event ring is installed (``btl_nativewire_events``), write its
    decoded records there as ``nativeev-p<pidx>.json`` — tpu-doctor
    expands them into wire-layer spans whose flow ids pair across
    processes. No ring (the default) writes nothing."""
    import os

    from ..mca import var as _var
    from . import nativeev as _nativeev

    d = str(_var.get("obs_dump_dir", "") or "")
    if not d or _nativeev.get_ring() is None:
        return None
    os.makedirs(d, exist_ok=True)
    pidx = 0
    if runtime is not None and runtime.bootstrap:
        pidx = int(runtime.bootstrap.get("process_index", 0))
    return _nativeev.dump(os.path.join(d, f"nativeev-p{pidx}.json"))


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _metric_name(name: str) -> str:
    n = _NAME_BAD.sub("_", name)
    if n and n[0].isdigit():
        n = "_" + n
    return "ompitpu_" + n


def _help_line(m: str, help: str) -> str:
    return f"# HELP {m} " + " ".join(str(help).split())


def prometheus_text(registry: Optional[_pvar.PvarRegistry] = None) -> str:
    """Every registered pvar as Prometheus text exposition format."""
    reg = registry if registry is not None else _pvar.PVARS
    out: List[str] = []
    for d in reg.describe_all():
        name, pclass, value = d["name"], d["class"], d["value"]
        m = _metric_name(name)
        if pclass == "histogram" and isinstance(value, dict):
            out.append(_help_line(m, d["help"]))
            out.append(f"# TYPE {m} histogram")
            cum = 0
            for le in sorted(value.get("buckets", {})):
                cum += value["buckets"][le]
                out.append(f'{m}_bucket{{le="{float(le):g}"}} {cum}')
            out.append(f'{m}_bucket{{le="+Inf"}} {value["count"]}')
            out.append(f"{m}_sum {float(value['sum']):g}")
            out.append(f"{m}_count {value['count']}")
        elif pclass == "aggregate" and isinstance(value, dict):
            out.append(_help_line(m, d["help"]))
            for suffix in ("count", "sum", "min", "max"):
                out.append(f"# TYPE {m}_{suffix} gauge")
                out.append(f"{m}_{suffix} {float(value[suffix]):g}")
        else:
            try:
                fv = float(value)
            except (TypeError, ValueError):
                continue  # non-numeric getter pvar: not exposable
            ptype = "counter" if pclass in ("counter", "timer") else "gauge"
            out.append(_help_line(m, d["help"]))
            out.append(f"# TYPE {m} {ptype}")
            out.append(f"{m} {fv:g}")
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# OpenMetrics with timestamps (the time-series exposition)
# ---------------------------------------------------------------------------


def openmetrics_series(points: Optional[Sequence[Dict[str, Any]]] = None,
                       pidx: Optional[int] = None,
                       clock_offset_s: float = 0.0) -> str:
    """Sampler points as an OpenMetrics exposition **with
    timestamps** — every sample line carries its sample time (plus
    the given clock offset, so a merged fleet page sits on one
    timebase), labelled by communicator scope (``cid``) and owning
    process (``pidx`` — the argument, or each point's own ``pidx``
    key for pre-merged fleet points). Delta points are exposed as
    gauges (each point IS a per-interval delta — rate numerators);
    dict deltas (AGGREGATE/HISTOGRAM) expand to ``_count``/``_sum``
    plus ``p50``/``p99`` quantile-estimate gauges from the delta
    buckets. Spec discipline: every emitted sample name is its own
    gauge family, all of a family's samples are contiguous under ONE
    ``# TYPE`` line, and the text ends with ``# EOF`` — so one call
    over merged multi-process points yields a parseable page (never
    concatenate two expositions)."""
    from . import sampler as _sampler

    if points is None:
        points = _sampler.snapshot()
    # family name -> sample lines (insertion-ordered: families stay
    # grouped and contiguous as the spec requires)
    fams: Dict[str, List[str]] = {}

    def sample(fam: str, lab: str, value: float, ts: str) -> None:
        fams.setdefault(fam, []).append(f"{fam}{lab} {value:g} {ts}")

    for p in points:
        m = _metric_name(str(p.get("name", ""))) + "_delta"
        own = pidx if pidx is not None else p.get("pidx")
        labels = [f'cid="{int(p.get("cid", -1))}"']
        if own is not None:
            labels.insert(0, f'pidx="{int(own)}"')
        lab = "{" + ",".join(labels) + "}"
        ts = f"{float(p['t']) + clock_offset_s:.6f}"
        v = p.get("v")
        if isinstance(v, dict):
            sample(m + "_count", lab, float(v.get("count", 0)), ts)
            sample(m + "_sum", lab, float(v.get("sum", 0.0)), ts)
            buckets = v.get("buckets")
            if isinstance(buckets, dict) and buckets:
                for q, tag in ((0.5, "p50"), (0.99, "p99")):
                    est = _sampler.percentile(buckets, q)
                    if est is not None:
                        sample(f"{m}_{tag}", lab, est, ts)
        else:
            try:
                fv = float(v)
            except (TypeError, ValueError):
                continue
            sample(m, lab, fv, ts)
    out: List[str] = []
    for fam, lines in fams.items():
        out.append(f"# TYPE {fam} gauge")
        out.extend(lines)
    out.append("# EOF")
    return "\n".join(out) + "\n"
