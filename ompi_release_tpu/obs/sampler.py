"""Continuous pvar time-series sampler — the fleet metrics plane's
per-process source.

PRs 1 and 4 built the *event* side (span journal, flow ids,
postmortems); pvars were still read only at snapshot points (bench
labels, ``tpu_top --metrics`` polling one server page). This module is
the *continuous* side: a gated background thread takes periodic
**delta** snapshots of every registered pvar (COUNTER/TIMER deltas,
AGGREGATE/HISTOGRAM element-wise deltas — the MPI_T session-delta
semantic from ``mca/mpit.py``) into a bounded ring of
:class:`SeriesPoint`-shaped dicts, each stamped with the sample time
and a **communicator scope** (cid) so future multi-tenant consumers
(ROADMAP item 4) get isolated series per tenant:

- process-wide pvar deltas carry ``cid == -1`` (the process scope);
- journal-derived collective series (``coll_ops`` / ``coll_bytes`` /
  ``coll_seconds`` per communicator, folded from the spans recorded
  since the previous tick) carry the real cid.

Arm/disarm rides ``Runtime.init``/``finalize`` behind the
``obs_sample_interval`` cvar (0 = off). Cost discipline is the PR-1
contract: when off, NOTHING runs — no thread, no clock reads — and
every emit site in this file is gated on ``_obs.enabled`` (enforced
by ``tests/test_obs_gating.py``'s AST scan). When on, each tick's cost
is accounted in the ``obs_sample_overhead_seconds`` pvar so the
overhead claim is *measured*, not asserted; ``obs_series_points``
counts every point ever recorded (ring wraps included).

When the process runs under tpurun, each tick also **pushes** the new
points to the HNP over the coordinator's TAG_SERIES channel (gated by
``obs_sample_push``), giving the job one fleet-wide store that
``tpu_top --fleet`` renders live and ``tpu-doctor`` merges offline.

The pvar scan is registry-driven, so counters that live OUTSIDE
Python fold in with no sampler change: ``btl/nativewire.py`` exposes
the C-side ring/endpoint telemetry blocks (``wire_native_bytes``
deltas split native-vs-staged throughput in ``tpu_top``;
``wire_native_ring_stalls`` / ``wire_native_stall_seconds`` /
``wire_native_ring_hwm_frac`` are the backpressure series) as getter
pvars that read shared memory on each tick — the native byte path
itself never executes a Python emit site.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..mca import pvar as _pvar
from ..mca import var as _var
from .. import obs as _obs

DEFAULT_RING = 4096
#: pushes failing this many consecutive times stop trying (the HNP is
#: gone or never existed; local ring + finalize dump still work)
PUSH_FAIL_LIMIT = 5

_points_total = _pvar.counter(
    "obs_series_points",
    "time-series points ever recorded by the continuous pvar sampler "
    "(ring wraps included)",
)
_overhead = _pvar.timer(
    "obs_sample_overhead_seconds",
    "accumulated seconds the background sampler spent taking delta "
    "snapshots (the measured cost of the continuous metrics plane)",
)
_ticks = _pvar.counter(
    "obs_sample_ticks", "sampler ticks taken since process start",
)

#: observability-of-observability pvars are excluded from the delta
#: scan: the sampler's own counters change on every tick by
#: construction, and the journal bookkeeping moves whenever the
#: sampler records its own tick span — sampling either means no tick
#: is ever quiet, so an idle fleet would push self-observation frames
#: forever and slowly evict real data from the ring. All stay
#: readable through the pvar snapshot / metrics RPC.
_SELF_PVARS = frozenset((
    "obs_sample_ticks", "obs_series_points",
    "obs_sample_overhead_seconds",
    "obs_journal_events", "obs_journal_dropped",
))


def register_vars() -> None:
    _var.register(
        "obs_sample_interval", "float", 0.0,
        "Seconds between continuous pvar delta snapshots (the fleet "
        "metrics plane's sampling period); 0 = sampler off — no "
        "thread, no clock reads (needs the obs plane enabled)",
    )
    _var.register(
        "obs_sample_ring", "int", DEFAULT_RING,
        "Bounded time-series ring capacity in points (oldest points "
        "are overwritten); applied when the sampler starts",
    )
    _var.register(
        "obs_sample_push", "bool", True,
        "Push each tick's new series points to the HNP over "
        "TAG_SERIES when running under tpurun (the fleet aggregation "
        "tpu_top --fleet renders); local ring + finalize dump work "
        "either way",
    )


register_vars()  # idempotent; cvars must exist before any start()


# ---------------------------------------------------------------------------
# histogram percentile math (log2 buckets -> quantile estimate)
# ---------------------------------------------------------------------------


def percentile(buckets: Dict[Any, float], q: float) -> Optional[float]:
    """Quantile estimate from a log2-bucketed histogram ``{upper_bound:
    count}`` (the :class:`mca.pvar.Histogram` read/delta form, JSON
    string keys tolerated). Returns the geometric midpoint of the
    bucket holding the q-quantile observation — the best unbiased
    point estimate when only the bucket is known — or the bound itself
    for the 0-bucket. None when the histogram is empty."""
    if not buckets:
        return None
    items = sorted(((float(k), float(v)) for k, v in buckets.items()
                    if float(v) > 0), key=lambda kv: kv[0])
    total = sum(v for _, v in items)
    if total <= 0:
        return None
    target = max(1.0, q * total)
    cum = 0.0
    for ub, count in items:
        cum += count
        if cum >= target:
            if ub <= 0:
                return 0.0
            # log2 buckets: the bucket spans (ub/2, ub]
            return (ub / 2.0 + ub) / 2.0
    return items[-1][0]


# ---------------------------------------------------------------------------
# delta math (shared shape with mpit's session deltas)
# ---------------------------------------------------------------------------


def _delta(cur: Any, base: Any) -> Any:
    """Delta of one pvar read against the previous tick's read.
    Scalars subtract; dict reads (AGGREGATE/HISTOGRAM) subtract
    elementwise with extrema passing through (not invertible over a
    window) — the ``mca/mpit.py`` session-delta rule."""
    if isinstance(cur, dict):
        bd = base if isinstance(base, dict) else {}
        return {k: (v if k in ("min", "max")
                    else _delta(v, bd.get(k, 0)))
                for k, v in cur.items()}
    if isinstance(cur, (int, float)) and isinstance(base, (int, float)):
        return float(cur) - float(base)
    return cur


def _is_zero(v: Any) -> bool:
    if isinstance(v, dict):
        return all(_is_zero(x) for k, x in v.items()
                   if k not in ("min", "max"))
    if isinstance(v, (int, float)):
        return float(v) == 0.0
    return False


# ---------------------------------------------------------------------------
# the bounded series ring
# ---------------------------------------------------------------------------


class SeriesRing:
    """Bounded ring of time-series points. A point is a plain dict
    ``{"i": monotonic index, "t": perf_counter seconds, "cid": scope,
    "name": series name, "v": float | dict delta}`` — JSON-able as-is,
    so exporters and the push path never reshape it."""

    def __init__(self, size: int = DEFAULT_RING) -> None:
        self._lock = threading.Lock()
        self._size = max(1, int(size))
        self._buf: deque = deque(maxlen=self._size)
        self._next_i = 0

    @property
    def size(self) -> int:
        return self._size

    @property
    def total_recorded(self) -> int:
        with self._lock:
            return self._next_i

    def append(self, t: float, cid: int, name: str, value: Any,
               tenant: int = -1) -> None:
        with self._lock:
            pt = {"i": self._next_i, "t": t, "cid": cid,
                  "name": name, "v": value}
            if tenant >= 0:
                # the multi-tenant dimension (service plane): points
                # whose cid falls in a tenant band carry the tenant
                # id, so fleet/daemon consumers can aggregate "who is
                # burning the fabric" without re-deriving band math
                pt["tenant"] = tenant
            self._buf.append(pt)
            self._next_i += 1

    def snapshot(self) -> List[Dict[str, Any]]:
        """Buffered points, oldest first."""
        with self._lock:
            return list(self._buf)

    def drain_since(self, cursor: int) -> Tuple[List[Dict[str, Any]], int]:
        """Points with index >= cursor plus the new cursor — the push
        path's incremental read (points are never removed here; the
        ring itself bounds memory)."""
        with self._lock:
            pts = [p for p in self._buf if p["i"] >= cursor]
            return pts, self._next_i

    def resize(self, size: int) -> None:
        with self._lock:
            self._size = max(1, int(size))
            self._buf = deque(self._buf, maxlen=self._size)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()


#: process-global ring (identity stable across start/stop cycles so
#: the tpu_server series RPC and finalize dump read one store)
RING = SeriesRing()

#: post-tick hooks, invoked (no arguments) after every delta snapshot
#: — the online re-tuner (:mod:`..tuning.retune`) registers here when
#: armed. Empty by default: one tuple() per tick when nothing consumes
#: the plane, and a raising hook never kills the sampler.
TICK_HOOKS: List[Callable[[], None]] = []


# ---------------------------------------------------------------------------
# the sampler
# ---------------------------------------------------------------------------


class Sampler:
    def __init__(self, ring: SeriesRing = RING) -> None:
        self.ring = ring
        self._prev: Dict[str, Any] = {}
        self._last_seq = 0   # journal cursor for per-cid folding
        self._ledger_seq = -1  # flight-recorder cursor (same folding)
        self._push_cursor = 0
        self._push_failures = 0
        self._agent = None   # tpurun WorkerAgent (fleet push target)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._armed = False  # ever started — stop()'s final tick gate

    # -- one tick ----------------------------------------------------------
    def sample_once(self) -> int:
        """Take one delta snapshot; returns the number of points
        recorded. Safe to call without the thread (selftest, tests,
        final flush)."""
        if not _obs.enabled:
            return 0
        t0 = time.perf_counter()
        n = 0
        # 1. pvar deltas (process scope, cid = -1)
        cur = _pvar.PVARS.read_all()
        for name, value in cur.items():
            if name in _SELF_PVARS:
                continue  # self-observation feedback loop (see above)
            if not isinstance(value, (int, float, dict)):
                continue  # non-numeric getter pvar: not a series
            d = _delta(value, self._prev.get(name, 0))
            if name in self._prev and _is_zero(d):
                continue  # quiet series: no point, no ring churn
            self.ring.append(t0, -1, name, d)
            n += 1
        self._prev = cur
        # 2. journal-derived per-communicator series: fold the spans
        # recorded since the previous tick into per-cid rate points —
        # the scope future tenants are isolated by
        by_cid: Dict[int, List[float]] = {}
        for s in _obs.journal.snapshot():
            if s.seq < self._last_seq or s.layer != "coll":
                continue
            acc = by_cid.setdefault(s.comm_id, [0.0, 0.0, 0.0])
            acc[0] += 1
            acc[1] += float(s.nbytes)
            acc[2] += float(s.dt)
        self._last_seq = _obs.journal.total_recorded
        # 2b. flight-recorder fold: compiled DEVICE fires never touch
        # the journal (one fixed-size binary ledger record each), so
        # their per-cid series fold from the ledger's delta since the
        # last tick. Spanning compiled fires already stamp one
        # coll-layer journal span per round (hier's _round_end runs
        # under planned replay too), so only device records fold here
        # — the series never double count.
        from . import ledger as _ledger

        new_recs = _ledger.records(self._ledger_seq)
        if new_recs:
            plan_meta = _ledger.plans()
            for r in new_recs:
                if r["kind"] == _ledger.KIND_DEVICE:
                    acc = by_cid.setdefault(r["cid"], [0.0, 0.0, 0.0])
                    acc[0] += 1
                    acc[1] += float((plan_meta.get(r["plan"]) or {})
                                    .get("nbytes", 0))
                    acc[2] += max(0.0, r["t_end"] - r["t_start"])
            self._ledger_seq = new_recs[-1]["seq"]
        if by_cid:
            from ..ft.ulfm import tenant_of_cid  # import-light
        for cid, (ops, nbytes, secs) in sorted(by_cid.items()):
            tid = tenant_of_cid(cid)
            self.ring.append(t0, cid, "coll_ops", ops, tenant=tid)
            self.ring.append(t0, cid, "coll_bytes", nbytes, tenant=tid)
            self.ring.append(t0, cid, "coll_seconds", secs, tenant=tid)
            n += 3
        dt = time.perf_counter() - t0
        _ticks.add(1)
        _points_total.add(n)
        _overhead.add(dt)
        # the tick's own journal span only when something was seen: an
        # idle tick must leave NO trace anywhere, or idleness detection
        # (quiet-series skip, empty push) can never converge
        if _obs.enabled and n:
            _obs.record("sample", "obs", t0, dt, nbytes=n)
        for hook in tuple(TICK_HOOKS):
            try:
                hook()
            except Exception:
                pass  # a broken consumer must not kill the plane
        return n

    # -- fleet push --------------------------------------------------------
    def push(self) -> bool:
        """Send the points recorded since the last push to the HNP.
        Returns True when something was sent. Failures back off and
        eventually stop trying (the local ring and finalize dump do
        not depend on the HNP)."""
        agent = self._agent
        if agent is None or self._push_failures >= PUSH_FAIL_LIMIT:
            return False
        pts, cursor = self.ring.drain_since(self._push_cursor)
        if not pts:
            return False
        try:
            agent.push_series(pts, offset_s=_obs.clock_offset(),
                              meta=_obs.rank_identity())
            self._push_cursor = cursor
            self._push_failures = 0
            return True
        except Exception:
            self._push_failures += 1
            return False

    # -- lifecycle ---------------------------------------------------------
    def _loop(self, interval: float) -> None:
        while not self._stop.wait(interval):
            if not _obs.enabled:
                continue  # obs flipped off mid-run: idle, don't emit
            try:
                self.sample_once()
                if bool(_var.get("obs_sample_push", True)):
                    self.push()
            except Exception:
                # one bad tick (a getter pvar raising, a torn-down
                # agent) must not kill the plane for the process
                continue

    def start(self, interval: float, runtime=None) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self.ring.resize(int(_var.get("obs_sample_ring", DEFAULT_RING)))
        self._agent = getattr(runtime, "agent", None)
        self._armed = True
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, args=(max(0.01, float(interval)),),
            daemon=True, name="obs-sampler")
        self._thread.start()

    def stop(self, final_push: bool = True) -> None:
        """Disarm: one last delta snapshot (so the finalize dump holds
        the tail of the run), one last push over the still-live HNP
        link, then retire the thread. A sampler that was never armed
        stays inert — a bare obs-enabled finalize must not suddenly
        grow a series ring."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2)
        self._thread = None
        if _obs.enabled and self._armed:
            try:
                self.sample_once()
                if final_push and bool(_var.get("obs_sample_push", True)):
                    self.push()
            except Exception:
                pass
        self._armed = False
        self._agent = None

    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()


#: process-global sampler (Runtime.init arms it, finalize disarms)
SAMPLER = Sampler()


def maybe_start(runtime=None) -> bool:
    """Runtime.init hook: arm the sampler iff obs is enabled AND
    ``obs_sample_interval`` > 0. Zero-cost when off — the caller's
    ``_obs.enabled`` gate plus this interval check are all that runs."""
    if not _obs.enabled:
        return False
    interval = float(_var.get("obs_sample_interval", 0.0) or 0.0)
    if interval <= 0:
        return False
    SAMPLER.start(interval, runtime=runtime)
    return True


def stop(final_push: bool = True) -> None:
    SAMPLER.stop(final_push=final_push)


def snapshot() -> List[Dict[str, Any]]:
    return RING.snapshot()


def _reset_for_tests() -> None:
    del TICK_HOOKS[:]
    SAMPLER._stop.set()
    t = SAMPLER._thread
    if t is not None:
        t.join(timeout=2)
    SAMPLER._thread = None
    SAMPLER._agent = None
    SAMPLER._armed = False
    SAMPLER._prev = {}
    SAMPLER._last_seq = 0
    SAMPLER._ledger_seq = -1
    SAMPLER._push_cursor = 0
    SAMPLER._push_failures = 0
    RING.clear()
