"""Stall watchdog + postmortem flight recorder.

The operator question that actually pages people — "the job is stuck;
who is waiting in what?" — needs an answer that survives the hang: a
hung job leaves no artifact, and the evidence (posted/unexpected
queues, hier round state, window lock tables, thread stacks) dies with
the process or is unreachable from outside it.

This module keeps a registry of **armed waits**: every blocking
collective / p2p / RMA wait registers itself (``arm``/``disarm``, one
module-attribute check when off) and a monitor thread dumps a
**postmortem file** the moment any wait exceeds ``obs_stall_timeout``
seconds. The dump carries everything a ``tpu-doctor`` postmortem needs:

  - the stalled wait(s): op, comm, how long, and who has not arrived
  - the journal tail (most recent spans, flow ids included)
  - the full pvar snapshot
  - the PML posted/unexpected queues (``tools/msgq.py`` — the message
    queue debugging DLL's data, ``ompi/debuggers``)
  - layer contributors: hier round state, window-service lock tables
  - per-thread Python stacks (``faulthandler``)
  - the rank identity + OOB clock offset so ``tpu-doctor`` can merge
    postmortems from several ranks onto one timeline

The same dump fires on SIGUSR1 (``kill -USR1 <pid>`` against a live
rank — the process continues) and, stacks-only, on fatal signals
(SIGSEGV/SIGFPE/SIGABRT/SIGBUS via ``faulthandler.enable``).

Cost discipline: ``enabled`` is True only when the obs plane is on AND
``obs_stall_timeout`` > 0; every call site gates on it, so the off
path is one attribute check — the PR-1 contract.
"""

from __future__ import annotations

import itertools
import json
import os
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..mca import pvar as _pvar
from ..mca import var as _var

#: THE gate: arm/disarm sites check this and do nothing else when
#: False. Recomputed by refresh() on obs enable/disable.
enabled: bool = False

_timeout: float = 0.0
_tokens: Dict[int, "WaitToken"] = {}
_tokens_lock = threading.Lock()
_token_ids = itertools.count(1)
_monitor: Optional[threading.Thread] = None
_monitor_stop = threading.Event()
_dump_lock = threading.Lock()
_dump_seq = itertools.count(1)
#: backstop against a pathological stall storm filling the disk —
#: applies ONLY to watchdog-initiated stall dumps; operator-requested
#: SIGUSR1 dumps are human-bounded and always write
MAX_STALL_DUMPS = 8
_stall_dumps = 0

#: dump contributors: (name -> zero-arg callable returning JSON-able
#: state). Layers register the state only they can see (hier round
#: tables, window lock tables); contributors run best-effort at dump
#: time and a failing one is reported, never fatal.
_contributors: Dict[str, Callable[[], Any]] = {}

_stalls_detected = _pvar.counter(
    "obs_stalls_detected",
    "waits that exceeded obs_stall_timeout (each dumps a postmortem)",
)
_postmortems_written = _pvar.counter(
    "obs_postmortems_written", "postmortem files written"
)


def register_vars() -> None:
    _var.register(
        "obs_stall_timeout", "float", 0.0,
        "Seconds a monitored collective/p2p/RMA wait may block before "
        "the flight recorder dumps a postmortem (0 = watchdog off; "
        "needs the obs plane enabled)",
    )
    _var.register(
        "obs_postmortem_dir", "str", "",
        "Directory for postmortem dumps (stall watchdog, SIGUSR1, "
        "fatal-signal stacks); empty = "
        "$TMPDIR/ompitpu-postmortem-<uid>",
    )
    _var.register(
        "obs_dump_dir", "str", "",
        "When set (and obs is enabled), every rank writes its journal "
        "+ clock offset to <dir>/journal-p<pidx>.json at finalize — "
        "the per-rank input tpu-doctor merges into one Perfetto trace",
    )


register_vars()  # idempotent; cvars must exist before any refresh()


class WaitToken:
    __slots__ = ("id", "op", "comm_id", "peer", "t0", "info", "dumped",
                 "detected")

    def __init__(self, op: str, comm_id: int, peer: int,
                 info: Any) -> None:
        self.id = next(_token_ids)
        self.op = op
        self.comm_id = comm_id
        self.peer = peer
        self.t0 = time.perf_counter()
        #: dict, or zero-arg callable resolved at dump time (so a
        #: pending-peer set reflects arrivals since arming)
        self.info = info
        self.dumped = False
        self.detected = False  # counted once, even across dump retries

    def describe(self) -> Dict[str, Any]:
        info = self.info
        if callable(info):
            try:
                info = info()
            except Exception as e:
                info = {"error": f"{type(e).__name__}: {e}"}
        return {"op": self.op, "comm": self.comm_id, "peer": self.peer,
                "waited_s": round(time.perf_counter() - self.t0, 3),
                "info": info}


def refresh(obs_enabled: Optional[bool] = None) -> None:
    """Recompute the gate from the obs flag + obs_stall_timeout."""
    global enabled, _timeout
    if obs_enabled is None:
        from . import is_enabled

        obs_enabled = is_enabled()
    _timeout = float(_var.get("obs_stall_timeout", 0.0) or 0.0)
    enabled = bool(obs_enabled and _timeout > 0)
    if not enabled:
        # retire the monitor thread: arm sites check the gate, so no
        # new tokens arrive, and a forever-polling daemon would
        # outlive the feature (arm() restarts it on re-enable)
        _monitor_stop.set()
    else:
        # waits armed BEFORE a disable->enable flip can never re-arm
        # (their threads are blocked inside the wait), so arm() alone
        # won't resurrect the monitor for exactly the hung wait the
        # operator re-enabled obs to diagnose
        with _tokens_lock:
            have_tokens = bool(_tokens)
        if have_tokens:
            _ensure_monitor()


def arm(op: str, comm_id: int = -1, peer: int = -1,
        info: Any = None) -> WaitToken:
    """Register a blocking wait with the monitor. Callers gate on
    ``watchdog.enabled`` themselves (the one-attr-check contract) and
    MUST pair with disarm() in a finally block."""
    tok = WaitToken(op, comm_id, peer, info)
    with _tokens_lock:
        _tokens[tok.id] = tok
    _ensure_monitor()
    return tok


def disarm(tok: Optional[WaitToken]) -> None:
    if tok is None:
        return
    with _tokens_lock:
        _tokens.pop(tok.id, None)


def active_waits() -> List[Dict[str, Any]]:
    with _tokens_lock:
        toks = list(_tokens.values())
    return [t.describe() for t in toks]


def add_contributor(name: str, fn: Callable[[], Any]) -> None:
    """Register a dump-time state contributor (idempotent by name)."""
    _contributors[name] = fn


def _ensure_monitor() -> None:
    global _monitor, _monitor_stop
    if (_monitor is not None and _monitor.is_alive()
            and not _monitor_stop.is_set()):
        return  # hot-path fast check; the lock below settles races
    with _tokens_lock:
        if (_monitor is not None and _monitor.is_alive()
                and not _monitor_stop.is_set()):
            return
        # each monitor generation OWNS its stop event: a disable ->
        # enable flip must not leave a dying-but-alive old thread
        # absorbing the cleared event (no monitor for an armed wait)
        # or resurrect the old thread alongside a new one
        _monitor_stop = threading.Event()
        _monitor = threading.Thread(target=_monitor_loop,
                                    args=(_monitor_stop,), daemon=True,
                                    name="obs-stall-watchdog")
        _monitor.start()


def _monitor_loop(stop: threading.Event) -> None:
    # after a FAILED dump (read-only/full postmortem dir) retries back
    # off exponentially: without this the loop would re-run the heavy
    # dump path and warn every poll period for the rest of the hang
    retry_at, backoff = 0.0, 1.0
    while not stop.is_set():
        period = max(0.05, min(0.5, (_timeout or 1.0) / 4))
        if stop.wait(period):
            return
        if not enabled:
            continue
        now = time.perf_counter()
        if now < retry_at:
            continue
        with _tokens_lock:
            stalled = [t for t in _tokens.values()
                       if not t.dumped and now - t.t0 > _timeout]
            fresh = sum(1 for t in stalled if not t.detected)
            for t in stalled:
                t.detected = True
                t.dumped = True  # one postmortem per stalled wait
        if stalled:
            if fresh:
                _stalls_detected.add(fresh)
            # the recorder must never take the job down — but a FAILED
            # dump (read-only/full postmortem dir) must still leave a
            # log line, so the write attempt and the reporting are
            # guarded separately
            path, dump_err = "", None
            try:
                path = dump_postmortem("stall", stalled=stalled)
                backoff = 1.0
            except Exception as e:
                dump_err = f"{type(e).__name__}: {e}"
                # a transient failure (dir full, read-only mount) must
                # not permanently consume each wait's one postmortem:
                # un-mark so a LATER poll retries once the disk heals
                # (gated by the backoff above, not every period)
                with _tokens_lock:
                    for t in stalled:
                        t.dumped = False
                retry_at = time.perf_counter() + backoff
                backoff = min(backoff * 2, 30.0)
            try:
                from ..utils import output

                if dump_err is not None:
                    detail = f"postmortem dump FAILED: {dump_err}"
                elif path:
                    detail = f"postmortem -> {path}"
                else:
                    detail = (f"postmortem SUPPRESSED (cap of "
                              f"{MAX_STALL_DUMPS} stall dumps reached; "
                              "the first dumps hold the story)")
                output.stream("obs").warn(
                    f"stall watchdog: {len(stalled)} wait(s) exceeded "
                    f"obs_stall_timeout={_timeout:g}s "
                    f"({', '.join(t.op for t in stalled)}); {detail}")
            except Exception:
                pass


def postmortem_dir() -> str:
    d = str(_var.get("obs_postmortem_dir", "") or "")
    if not d:
        d = os.path.join(tempfile.gettempdir(),
                         f"ompitpu-postmortem-{os.getuid()}")
    os.makedirs(d, exist_ok=True)
    return d


def _rank_identity() -> Dict[str, Any]:
    from . import rank_identity

    return rank_identity()


def _thread_stacks() -> List[str]:
    """Every thread's Python stack via faulthandler (the only dumper
    that works mid-deadlock: it never takes locks)."""
    import faulthandler

    fd, path = tempfile.mkstemp(prefix="ompitpu-stacks-", suffix=".txt")
    try:
        with os.fdopen(fd, "w") as f:
            faulthandler.dump_traceback(file=f, all_threads=True)
        with open(path) as f:
            return f.read().splitlines()
    finally:
        try:
            os.unlink(path)
        except OSError:
            pass


def dump_postmortem(reason: str,
                    stalled: Optional[List[WaitToken]] = None,
                    path: Optional[str] = None) -> str:
    """Write one postmortem JSON file; returns its path. Everything
    inside is best-effort: a hung subsystem must not be able to hang
    its own flight recorder."""
    # NOTE: the obs package binds the attribute ``journal`` to the
    # Journal INSTANCE, so ``from . import journal`` would shadow the
    # submodule — import the instance through the submodule directly
    from .journal import JOURNAL as _journal

    global _stall_dumps
    with _dump_lock:
        n = next(_dump_seq)
        counts_against_cap = reason == "stall" and path is None
        if counts_against_cap and _stall_dumps >= MAX_STALL_DUMPS:
            return ""  # flood backstop (stall storms only)
        ident = _rank_identity()
        doc: Dict[str, Any] = {
            "reason": reason,
            "time_unix": time.time(),
            "perf_counter": time.perf_counter(),
            "rank": ident,
            "obs_stall_timeout": _timeout,
        }
        try:
            from . import _clock_state

            doc["clock"] = dict(_clock_state)
        except Exception:
            pass
        if stalled:
            doc["stalled"] = [t.describe() for t in stalled]
        try:
            doc["active_waits"] = active_waits()
        except Exception as e:
            doc["active_waits"] = f"unavailable: {e}"
        try:
            doc["journal_tail"] = [
                s.asdict() for s in _journal.snapshot()[-256:]
            ]
        except Exception as e:
            doc["journal_tail"] = f"unavailable: {e}"
        try:
            doc["pvars"] = _pvar.PVARS.read_all()
        except Exception as e:
            doc["pvars"] = f"unavailable: {e}"
        try:
            from ..tools import msgq

            doc["msg_queues"] = msgq.dump_all()
        except Exception as e:
            doc["msg_queues"] = f"unavailable: {e}"
        for name, fn in list(_contributors.items()):
            try:
                doc[name] = fn()
            except Exception as e:
                doc[name] = f"unavailable: {type(e).__name__}: {e}"
        try:
            doc["thread_stacks"] = _thread_stacks()
        except Exception as e:
            doc["thread_stacks"] = f"unavailable: {e}"
        if path is None:
            ident_tag = f"p{ident.get('pidx', 'x')}-{os.getpid()}"
            path = os.path.join(
                postmortem_dir(),
                f"postmortem-{ident_tag}-{reason}-{n}.json",
            )
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, default=str)
        os.replace(tmp, path)
        try:
            # the full compiled-fire flight recorder rides beside the
            # postmortem (the inline ledger_tail contributor carries
            # only the newest records): tpu-doctor expands it into
            # synthetic spans for the stalled rank's compiled traffic
            from . import ledger as _ledger

            if _ledger.records():
                pidx = ident.get("pidx", 0)
                _ledger.dump(os.path.join(
                    os.path.dirname(path), f"ledger-p{pidx}.json"))
        except Exception:
            pass  # best-effort, like every other dump section
        try:
            # likewise the native event ring (zero-copy datapath
            # fragments): tpu-doctor expands nativeev-p*.json into
            # wire-layer spans with paired flow ids — the stalled
            # rank's byte-path story, even though Python never saw
            # the bytes
            from . import nativeev as _nativeev

            if _nativeev.get_ring() is not None:
                pidx = ident.get("pidx", 0)
                _nativeev.dump(os.path.join(
                    os.path.dirname(path), f"nativeev-p{pidx}.json"))
        except Exception:
            pass  # best-effort, like every other dump section
        if counts_against_cap:
            # budget counts dumps that REACHED disk: a failed write
            # (raised above) must not spend it, or a transient full
            # disk could silently suppress every later real stall
            _stall_dumps += 1
        _postmortems_written.add()
        return path


_signals_installed = False


def install_signal_handlers() -> None:
    """SIGUSR1 -> full postmortem (process continues); fatal signals
    (SIGSEGV/SIGFPE/SIGABRT/SIGBUS) -> faulthandler stack dump into the
    postmortem dir. Main-thread only (signal.signal's own rule); a
    non-main caller is a silent no-op so library init never breaks."""
    global _signals_installed
    if _signals_installed:
        return
    import signal as _signal

    if threading.current_thread() is not threading.main_thread():
        return
    try:
        import faulthandler

        crash_path = os.path.join(
            postmortem_dir(), f"crash-stacks-{os.getpid()}.txt")
        _crash_file = open(crash_path, "w")
        faulthandler.enable(file=_crash_file, all_threads=True)
        # keep a module ref so the fd outlives this frame
        globals()["_crash_file"] = _crash_file

        # chain: an application using SIGUSR1 for its own trigger
        # (checkpoint-now, log rotate) keeps working under obs —
        # SIG_DFL/SIG_IGN are ints, so `callable` filters them
        prev = _signal.getsignal(_signal.SIGUSR1)
        chain = prev if callable(prev) else None

        # the dump must NOT run in signal context: the handler
        # interrupts the main thread between bytecodes, and
        # dump_postmortem takes non-reentrant locks the interrupted
        # frame may hold (journal._lock inside record(), _tokens_lock,
        # window-service state locks via contributors) — dumping
        # inline would deadlock the rank the poke was meant to
        # diagnose. The handler only sets an event; this worker
        # thread does the dump.
        usr1_event = threading.Event()

        def usr1_worker() -> None:
            while True:
                usr1_event.wait()
                usr1_event.clear()
                try:
                    dump_postmortem("sigusr1")
                except Exception:
                    pass

        threading.Thread(target=usr1_worker, daemon=True,
                         name="obs-sigusr1-dumper").start()

        def on_usr1(signum, frame):
            usr1_event.set()
            if chain is not None:
                chain(signum, frame)

        _signal.signal(_signal.SIGUSR1, on_usr1)
        _signals_installed = True
    except (ValueError, OSError):
        pass  # exotic embedding (no usable signals): diagnosis only
