"""``python -m ompi_release_tpu.obs`` — observability selftest.

``--selftest`` registers one pvar of every class, bumps each, drives
the journal through a ring wrap, runs a skew-timer cycle, exports
through every exporter, and verifies the round-trip — device-free and
fast, so the tier-1 suite can run it as a subprocess smoke test.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time


def selftest() -> int:
    from ..mca import mpit, pvar
    from . import disable, enable, flow_id, journal
    from . import export, skew

    # 1. every pvar class: register, bump, read
    c = pvar.counter("obs_selftest_counter", "selftest")
    c.add(2)
    t = pvar.timer("obs_selftest_timer", "selftest")
    with t.timing():
        pass
    hw = pvar.highwatermark("obs_selftest_hwm", "selftest")
    hw.set(5)
    hw.set(3)
    assert hw.read() == 5, "highwatermark must keep the max"
    hist = pvar.histogram("obs_selftest_hist", "selftest")
    for v in (0.0, 1e-4, 3.0, 4.0, 1024.0):
        hist.observe(v)
    snap = hist.read()
    assert snap["count"] == 5 and snap["max"] == 1024.0, snap
    assert sum(snap["buckets"].values()) == 5, snap
    agg = pvar.aggregate("obs_selftest_agg", "selftest")
    agg.observe(2.0)
    agg.observe(-1.0)
    a = agg.read()
    assert a["count"] == 2 and a["min"] == -1.0 and a["max"] == 2.0, a

    # 2. MPI_T session round-trip: session-relative deltas per class
    sess = mpit.Mpit().pvar_session()
    hc = sess.handle("obs_selftest_counter")
    hc.start()
    c.add(3)
    assert hc.read() == 3.0, hc.read()
    hh = sess.handle("obs_selftest_hist")
    hh.start()
    hist.observe(7.0)
    d = hh.read()
    assert d["count"] == 1.0 and d["sum"] == 7.0, d
    assert sum(d["buckets"].values()) == 1.0, d
    ha = sess.handle("obs_selftest_agg")
    ha.start()
    ha.reset()
    assert ha.read()["count"] == 0.0
    sess.free()

    # 3. journal ring wrap + skew cycle
    enable(size=8)
    for i in range(12):
        journal.record(f"op{i}", "selftest", time.perf_counter(), 1e-5,
                       nbytes=i)
    spans = journal.snapshot()
    assert len(spans) == 8 and spans[-1].op == "op11", spans
    assert spans[0].seq < spans[-1].seq
    # flow context round-trip: deterministic id, side survives asdict
    fid = flow_id("selftest", 1, 2)
    assert fid == flow_id("selftest", 1, 2) and fid != flow_id("x")
    journal.record("flow_s", "selftest", time.perf_counter(), 1e-6,
                   flow=fid, flow_side="s")
    fs = journal.snapshot()[-1]
    assert fs.flow == fid and fs.asdict()["fs"] == "s", fs.asdict()
    tok = skew.begin("selftest")
    skew.body(tok)
    skew.end(tok, nbytes=64)
    sk = pvar.PVARS.lookup("coll_selftest_skew_seconds")
    assert sk is not None and sk.read()["count"] == 1

    # 4. exporters round-trip
    with tempfile.TemporaryDirectory() as td:
        tp = export.dump_chrome_trace(os.path.join(td, "trace.json"))
        with open(tp) as f:
            doc = json.load(f)
        evs = [e for e in doc["traceEvents"] if e.get("ph") != "M"]
        assert evs, "chrome trace has no events"
        assert all("name" in e and "ts" in e and "ph" in e for e in evs)
        jp = export.dump_jsonl(os.path.join(td, "journal.jsonl"))
        with open(jp) as f:
            lines = [json.loads(line) for line in f]
        assert len(lines) == len(journal.snapshot())
        assert lines[-1]["op"] == "selftest"
    page = export.prometheus_text()
    for needle in (
        "ompitpu_obs_selftest_counter 5",
        "ompitpu_obs_selftest_hist_bucket",
        "ompitpu_obs_selftest_hist_count 6",
        "ompitpu_obs_selftest_agg_min -1",
        "ompitpu_coll_selftest_skew_seconds_count 1",
        "ompitpu_obs_journal_events",
    ):
        assert needle in page, f"{needle!r} missing from exposition"

    # 5. continuous sampler: delta snapshots, per-cid scoping, the
    # OpenMetrics-with-timestamps exposition, and the overhead pvar
    from . import sampler as _sampler

    _sampler._reset_for_tests()
    sc = pvar.counter("obs_selftest_series_ctr", "selftest")
    base_pts = _sampler.SAMPLER.sample_once()  # baseline tick
    assert base_pts >= 0
    sc.add(4)
    hist.observe(9.0)
    journal.record("allreduce", "coll", time.perf_counter(), 2e-3,
                   nbytes=1024, comm_id=7)
    n = _sampler.SAMPLER.sample_once()
    assert n > 0, "second tick must record deltas"
    pts = _sampler.snapshot()
    by_name = {}
    for p in pts:
        by_name.setdefault(p["name"], []).append(p)
    assert any(p["v"] == 4.0 for p in by_name["obs_selftest_series_ctr"])
    assert any(p["cid"] == 7 for p in by_name.get("coll_ops", [])), (
        "per-communicator coll series missing")
    ov = pvar.PVARS.lookup("obs_sample_overhead_seconds")
    assert ov is not None and float(ov.read()) > 0.0
    assert float(pvar.PVARS.lookup("obs_series_points").read()) >= n
    om = export.openmetrics_series(pts)
    assert om.endswith("# EOF\n") and "ompitpu_" in om
    assert 'cid="7"' in om, om[:400]
    # percentile math: all mass in one log2 bucket -> its midpoint
    est = _sampler.percentile({8.0: 10}, 0.5)
    assert est is not None and 4.0 < est <= 8.0, est
    # series dump/reload round-trip (the finalize-dump unit)
    with tempfile.TemporaryDirectory() as td:
        sp = export.dump_series_jsonl(os.path.join(td, "series-p0.jsonl"))
        from . import doctor as _doctor_mod

        doc = _doctor_mod.load_series_dump(sp)
        assert len(doc["points"]) == len(pts)
    print(f"sampler: {len(pts)} points "
          f"(overhead {float(ov.read()) * 1e3:.3f} ms)")

    # 6. collective contract sentinel: hash-chain determinism across
    # two identical op sequences, divergence detected on the third,
    # and the journal-event round-trip the doctor's contracts
    # alignment parses
    from ..mca import var as _var
    from . import sentinel as _sentinel

    _sentinel._reset_for_tests()
    _var.set_value("obs_sentinel", 1)
    _sentinel.refresh(True)
    assert _sentinel.enabled and _sentinel.mode() == 1
    seqs = (("allreduce", "sum", "float32", 1024, -1),
            ("bcast", "-", "float32", 1024, 0),
            ("reduce", "max", "int32", 64, 2))
    for cid in (101, 102):
        for fam, op_n, dt, cnt, root in seqs:
            _sentinel.record_sig(cid, fam, op_n, dt, cnt, root,
                                 site="selftest.py:1")
    assert _sentinel.chain_of(101) == _sentinel.chain_of(102) != 0, (
        "identical op sequences must fold to identical chains")
    _sentinel.record_sig(101, "allreduce", "sum", "float64", 1024, -1,
                         site="selftest.py:2")
    _sentinel.record_sig(102, "allreduce", "sum", "float32", 1024, -1,
                         site="selftest.py:2")
    assert _sentinel.chain_of(101) != _sentinel.chain_of(102), (
        "divergent third op must split the chains")
    last = [s for s in journal.snapshot() if s.layer == "sentinel"][-1]
    parsed = _sentinel.parse_op(last.op)
    assert parsed is not None and parsed["site"] == "selftest.py:2"
    assert parsed["canon"] == "allreduce|sum|float32|1024|-1", parsed
    snap = _sentinel.chains_snapshot()
    assert snap["comms"]["101"]["next_seq"] == 4
    assert float(pvar.PVARS.lookup("sentinel_ops_hashed").read()) >= 8
    _var.VARS.unset("obs_sentinel")
    _sentinel.refresh(True)
    assert not _sentinel.enabled
    print("sentinel: chain determinism + divergence detection ok "
          f"(chain {snap['comms']['101']['chain']})")

    # 7. coll driver plan-cache statistics (registered at driver
    # import; sum = hits, count = invocations → sum/count = hit ratio)
    from ..coll import driver as _coll_driver  # noqa: F401

    pc = pvar.PVARS.lookup("coll_plan_cache_hits")
    assert pc is not None, "coll driver must register coll_plan_cache_hits"
    st = pc.read()
    hits, total = int(st["sum"]), int(st["count"])
    ratio = (hits / total) if total else 0.0
    print(f"plan cache: {hits}/{total} hits "
          f"(ratio {ratio:.2f}; compiled="
          f"{pvar.PVARS.lookup('coll_programs_compiled').read():.0f}, "
          f"invocations="
          f"{pvar.PVARS.lookup('coll_invocations').read():.0f})")

    # 8. pytree planned-collective plan cache (parallel/tree): an
    # identical tree signature must fetch the cached plan (1=hit), a
    # different bucket capacity must build a fresh one (0), and the
    # counts are operator-visible here
    from ..parallel import tree as _tree

    sig = [((64, 64), "float32"), ((17,), "float32"), ((8,), "int32")]
    tp1 = _tree.plan_from_meta(sig, 1 << 20)
    assert _tree.plan_from_meta(sig, 1 << 20) is tp1, (
        "identical tree signatures must fetch the cached plan")
    assert _tree.plan_from_meta(sig, 1 << 4) is not tp1
    tc = pvar.PVARS.lookup("tree_plan_cache_hits")
    assert tc is not None, "parallel/tree must register tree_plan_cache_hits"
    ts = tc.read()
    assert ts["count"] >= 3 and ts["sum"] >= 1, ts
    print(f"tree plan cache: {int(ts['sum'])}/{int(ts['count'])} hits "
          f"({pvar.PVARS.lookup('tree_buckets_planned').read():.0f} "
          f"buckets planned)")

    # 9. compiled-schedule plan cache (coll/plan): signatures are
    # stable metadata (identical calls share a plan, different shapes
    # do not), frozen frame templates round-trip through the DSS wire
    # format the receivers parse, and the hit ratio is operator-
    # visible here — all device-free (no jax dispatch)
    import numpy as _np

    from ..btl import components as _btlc
    from ..coll import plan as _plan
    from ..native import DssBuffer as _Dss

    s1 = _plan.signature_of("allreduce", (_np.zeros((4, 8), _np.float32),),
                            {})
    s2 = _plan.signature_of("allreduce", (_np.zeros((4, 8), _np.float32),),
                            {})
    s3 = _plan.signature_of("allreduce", (_np.zeros((4, 9), _np.float32),),
                            {})
    assert s1 == s2 and s1 != s3, (s1, s3)
    assert _plan.signature_of("allgatherv",
                              ([_np.zeros(3)], [_np.zeros(2)]),
                              {}) is None, "ragged lists must not plan"
    tpl = _btlc.plan_frame_template((16, 16), "float32", 256)
    hdr = _Dss(tpl.header(xfer=9, crc=12345))
    assert hdr.unpack_string() == "SGH2"
    assert hdr.unpack_int64() == [9]
    assert hdr.unpack_string() == "float32"
    assert hdr.unpack_string() == "16,16"
    assert hdr.unpack_int64(2) == [tpl.nchunks, tpl.chunk]
    assert hdr.unpack_int64() == [12345]
    cs = _plan.cache_stats()
    pc = pvar.PVARS.lookup("coll_compiled_cache_hits")
    assert pc is not None, "coll/plan must register coll_compiled_cache_hits"
    st = pc.read()
    fires, hits = int(st["count"]), int(st["sum"])
    ratio = (hits / fires) if fires else 0.0
    print(f"compiled-plan cache: {hits}/{fires} hits (ratio "
          f"{ratio:.2f}; {cs['device_plans']} device plans, "
          f"{cs['spanning_plans']} spanning plans; frame template "
          f"{tpl.nchunks}x{tpl.chunk}B precomposed)")

    # 10. tuning plane: topology fingerprint round-trip, the versioned
    # tuning-db register/select cycle, dynamic-rules auto-selection
    # from the DB, and the active fingerprint + rules source printed
    # for the operator — all device-free
    from ..coll import components as _coll_components  # noqa: F401
    from ..coll import dynamic_rules as _dyn
    from ..coll.base import COLL_FRAMEWORK
    from ..tuning import db as _tdb

    COLL_FRAMEWORK.lookup("tuned").register_vars()  # the rules cvars
    fp = _tdb.active()
    assert _tdb.Fingerprint.parse(fp.canon()) == fp, fp
    with tempfile.TemporaryDirectory() as td:
        tdb = _tdb.TuningDb(td)
        p1 = tdb.register("hier_allreduce  0  0  recursive_doubling\n",
                          fp)
        p2 = tdb.register("hier_allreduce  0  0  torus2d\n", fp)
        assert p1 != p2 and tdb.best_match(fp) == p2, (p1, p2)
        fp2, v2 = _tdb.read_header(p2)
        assert fp2 == fp and v2 == 2, (fp2, v2)
        _var.set_value("coll_tuned_use_dynamic_rules", True)
        _var.set_value("coll_tuning_db_dir", td)
        try:
            assert _dyn.lookup("hier_allreduce", 8, 1 << 20) \
                == "torus2d", "db auto-selection failed"
            src = _dyn.rules_source()
            assert src["mode"] == "db" and src["path"] == p2, src
            assert src["fingerprint"] == fp.canon(), src
        finally:
            _var.VARS.unset("coll_tuned_use_dynamic_rules")
            _var.VARS.unset("coll_tuning_db_dir")
    src = _dyn.rules_source()
    print(f"tuning: fingerprint {fp.canon()}; rules source "
          f"{src['mode']}"
          + (f" ({src['path']})" if src.get("path") else "")
          + "; db register/select round-trip ok")

    # 11. plan-relative flight recorder (obs/ledger): a spanning fire
    # record encodes fixed-size, decodes losslessly, and expands
    # against its frozen plan metadata into synthetic spans whose
    # flow ids pair with the complementary rank's expansion — all
    # device-free (no plan ever fires here)
    from types import SimpleNamespace as _NS

    from . import ledger as _ledger

    _ledger._reset_for_tests()
    arrs = [((64,), "float32")]
    lp0 = _ledger.register_spanning_plan(
        7, "allreduce", 0, [_NS(sends_meta=[(1, arrs)], recvs_t=[])])
    lp1 = _ledger.register_spanning_plan(
        7, "allreduce", 1, [_NS(sends_meta=[], recvs_t=[(0, 1)])])
    seq = _ledger.record_fire(_ledger.KIND_SPANNING, lp0, 7,
                              1.0, 2.0, round0=5, round_ts=(1.5,))
    rec = _ledger.records()[-1]
    assert rec["seq"] == seq and rec["round_ts"] == [1.5], rec
    assert rec["plan"] == lp0 and rec["round0"] == 5, rec
    docs = {str(k): v for k, v in _ledger.plans().items()}
    send_spans = _ledger.expand_record(rec, docs)
    recv_spans = _ledger.expand_record(dict(rec, plan=lp1), docs)
    s_flows = [s["flow"] for s in send_spans if s.get("fs") == "s"]
    t_flows = [s["flow"] for s in recv_spans if s.get("fs") == "t"]
    assert s_flows and s_flows == t_flows, (s_flows, t_flows)
    assert any(s["op"] == "allreduce_wire_round0" for s in send_spans)
    rb = _ledger.snapshot()["record_bytes"] + 8 * len(rec["round_ts"])
    print(f"flight recorder: {rb}B/record, "
          f"{len(send_spans)} spans expanded, flow ids pair "
          f"({s_flows[0]:#x})")

    # 12. nativewire datapath (device-free): a shared-memory ring
    # moves precomposed SGH2 scatter-gather fragments bit-exactly into
    # a preallocated buffer, the SG framing joins byte-identical to
    # the staged header, and the enable switch withdraws the MCA
    # component cleanly. With the native symbols absent the leg
    # reduces to the withdrawal checks — the portable-fallback
    # contract, not a failure.
    import zlib as _zlib

    from ..btl import nativewire as _nw

    assert pvar.PVARS.lookup("wire_native_bytes") is not None
    assert pvar.PVARS.lookup("wire_native_copies_per_mib") is not None
    if _nw.nativewire_ready():
        from ..native import ShmRing as _Ring

        tpl2 = _btlc.plan_frame_template((256,), "int32", 256)
        src_arr = _np.arange(256, dtype=_np.int32)
        smv = memoryview(src_arr.view(_np.uint8))
        crc2 = _zlib.crc32(smv)
        frames2 = list(tpl2.sg_lists(smv, 11, crc2))
        assert b"".join(frames2[0]) == tpl2.header(11, crc2)
        name = f"/onw-selftest-{os.getpid():x}"
        _Ring.unlink(name)
        prod = _Ring.create(name, 1 << 16, os.getpid())
        assert prod is not None, "selftest ring create failed"
        cons = _Ring.attach(name, os.getpid())
        _Ring.unlink(name)
        assert cons is not None, "selftest ring attach failed"
        for parts in frames2[1:]:
            assert prod.writev(500, parts, 1000) == 0
        out = bytearray(tpl2.nbytes)
        for _ in range(tpl2.nchunks):
            rc = cons.read_frag(500, 11, tpl2.nchunks, tpl2.chunk,
                                out, 1000)
            assert rc >= 0, f"ring read_frag rc {rc}"
        assert bytes(out) == src_arr.tobytes(), (
            "ring fragments must land bit-exact")
        prod.close()
        cons.close()
        print(f"nativewire: ring moved {tpl2.nchunks}x{tpl2.chunk}B "
              "fragments bit-exact; SG framing joins byte-identical "
              "to the staged header")
    else:
        print("nativewire: capability absent — portable staged path "
              "in force")
    prior = os.environ.get("OMPITPU_NATIVEWIRE")
    os.environ["OMPITPU_NATIVEWIRE"] = "0"
    try:
        assert not _nw.nativewire_ready()
        assert _nw.modex_entry() == {}
        assert _nw.NativeWireComponent().query() is None
    finally:
        if prior is None:
            os.environ.pop("OMPITPU_NATIVEWIRE", None)
        else:
            os.environ["OMPITPU_NATIVEWIRE"] = prior
    print("nativewire: disable switch withdraws the component cleanly")

    # 13. frozen RMA access plans (osc/plan, device-free): epoch
    # signatures are stable metadata (identical op sequences share a
    # plan, a different target does not), the frozen wire BatchTemplate
    # renders BYTE-identical frames to the interpreted _pack_batch, and
    # a KIND_RMA ledger fire expands into an "osc"-layer span — no
    # fused program ever fires here
    from .. import ops as _ops
    from ..osc import plan as _osc_plan
    from ..osc.window import _PendingOp as _POp
    from ..osc.wire_win import _pack_batch as _pack

    def _rma_todo(tgt=1):
        return [
            _POp("put", tgt, data=_np.arange(4, dtype=_np.float32),
                 op=_ops.REPLACE),
            _POp("acc", 0, data=_np.full(4, 2.0, _np.float32),
                 op=_ops.SUM),
        ]

    rs1 = _osc_plan.epoch_signature(_rma_todo())
    rs2 = _osc_plan.epoch_signature(_rma_todo())
    rs3 = _osc_plan.epoch_signature(_rma_todo(tgt=0))
    assert rs1 == rs2 and rs1 != rs3, (rs1, rs3)
    todo = _rma_todo()
    tpl3 = _osc_plan.BatchTemplate(_var.VARS.generation, todo)
    assert tpl3.render(todo).tobytes() == _pack(todo).tobytes(), (
        "frozen frame template must render byte-identical to "
        "_pack_batch")
    rlid = _ledger.register_rma_plan(9, "epoch[2]", 32, rs1)
    _ledger.record_fire(_ledger.KIND_RMA, rlid, 9, 3.0, 3.5)
    rrec = _ledger.records()[-1]
    rdocs = {str(k): v for k, v in _ledger.plans().items()}
    rspans = _ledger.expand_record(rrec, rdocs)
    assert rspans and all(s["layer"] == "osc" for s in rspans), rspans
    rcs = _osc_plan.cache_stats()
    print(f"rma plans: signatures stable; frames byte-identical; "
          f"KIND_RMA expands to osc-layer spans; "
          f"{rcs['epoch_plans']} plans / {rcs['programs']} programs / "
          f"{rcs['fires']} fires")

    # 14. native wire telemetry (device-free): the always-on counters
    # block in the shm ring header observes a writev/read_frag
    # round-trip (frames, bytes, occupancy high-water, a timed-out
    # empty read as one stall), and the optional event ring records one
    # 32-byte record per side whose expansion pairs flow ids across
    # send and recv — the doctor's cross-process arrows, demonstrated
    # inside one process. Symbols absent = the leg reduces to the
    # pvar-presence checks (portable fallback, not a failure).
    from ..native import telemetry_symbols_available as _tele_ok
    from . import nativeev as _nativeev

    for nm in ("wire_native_ring_stalls", "wire_native_stall_seconds",
               "wire_native_ring_hwm_frac"):
        assert pvar.PVARS.lookup(nm) is not None, nm
    if _nw.nativewire_ready() and _tele_ok():
        from ..native import NativeEventRing as _EvRing
        from ..native import ShmRing as _Ring2

        evname = f"/onwev-selftest-{os.getpid():x}"
        _EvRing.unlink(evname)
        ev = _EvRing.create(evname, 256)
        assert ev is not None, "selftest event ring create failed"
        _EvRing.unlink(evname)
        ev.install()
        try:
            tpl4 = _btlc.plan_frame_template((64,), "int32", 1 << 10)
            arr4 = _np.arange(64, dtype=_np.int32)
            mv4 = memoryview(arr4.view(_np.uint8))
            frames4 = list(tpl4.sg_lists(mv4, 21, _zlib.crc32(mv4)))
            name = f"/onwt-selftest-{os.getpid():x}"
            _Ring2.unlink(name)
            prod = _Ring2.create(name, 1 << 16, os.getpid())
            cons = _Ring2.attach(name, os.getpid())
            _Ring2.unlink(name)
            assert prod is not None and cons is not None
            s0 = prod.stats()
            assert prod.writev(501, frames4[1], 1000) == 0
            out4 = bytearray(tpl4.nbytes)
            rc = cons.read_frag(501, 21, tpl4.nchunks, tpl4.chunk,
                                out4, 1000)
            assert rc >= 0, f"telemetry leg read_frag rc {rc}"
            s1 = cons.stats()
            assert s1["w_frames"] == s0["w_frames"] + 1, (s0, s1)
            assert s1["w_bytes"] > s0["w_bytes"], (s0, s1)
            assert s1["r_frames"] == s0["r_frames"] + 1, (s0, s1)
            assert s1["r_bytes"] == s1["w_bytes"], s1
            assert s1["hwm"] > 0, s1
            # a timed-out empty read is ONE stall with measured time
            rc = cons.read_frag(501, 21, tpl4.nchunks, tpl4.chunk,
                                out4, 30)
            assert rc == -1, rc
            s2 = cons.stats()
            assert s2["r_stalls"] == s1["r_stalls"] + 1, (s1, s2)
            assert s2["r_stall_ns"] > s1["r_stall_ns"], (s1, s2)
            # the event ring saw both sides of the fragment
            assert ev.count() >= 2, ev.count()
            doc = _nativeev.snapshot(ev)
            assert doc["format"] == _nativeev.FORMAT
            recs = doc["records"]
            assert any(r["recv"] for r in recs), recs
            assert any(not r["recv"] for r in recs), recs
            r0 = recs[0]
            assert r0["tag"] == 501 and r0["xfer"] == 21, r0
            assert r0["bytes"] == len(frames4[1][-1]), r0
            spans4 = _nativeev.expand_dump(doc)
            assert all(s["layer"] == "wire" for s in spans4), spans4
            sflow = {s["flow"] for s in spans4 if s["fs"] == "s"}
            tflow = {s["flow"] for s in spans4 if s["fs"] == "t"}
            assert sflow and sflow == tflow, (sflow, tflow)
            assert sflow == {_nativeev.frag_flow_id(501, 21, 0)}
            prod.close()
            cons.close()
            print(f"native telemetry: counters observed "
                  f"{s1['w_frames'] - s0['w_frames']} frame / "
                  f"{s1['w_bytes'] - s0['w_bytes']}B, stall "
                  f"{(s2['r_stall_ns'] - s1['r_stall_ns']) / 1e6:.1f} "
                  f"ms; {len(recs)} event records expand to paired "
                  f"wire spans ({next(iter(sflow)):#x})")
        finally:
            ev.uninstall()
            ev.close()
    else:
        print("native telemetry: symbols absent — counters fold to "
              "zero, event ring stays off")

    # 15. native plan executor (device-free): a frozen two-round wire
    # plan compiles into the flat descriptor table the C executor
    # walks (build_blob -> planexec_create introspection, no wire, no
    # peers), and a spanning-plan ledger fire carrying C-stamped round
    # boundaries round-trips: the timestamps come back through the
    # binary ring record exactly as the executor wrote them. Symbols
    # absent = the compile leg reduces to the graceful-withdrawal
    # check (try_compile returns None, never raises).
    from ..coll import native_exec as _nx
    from ..coll import plan as _cplan

    for nm in ("plan_pool_bytes", "plan_pool_hits",
               "plan_native_fires", "plan_native_fallbacks"):
        assert pvar.PVARS.lookup(nm) is not None, nm
    if _nx.available():
        from ..native.bindings import PlanExec as _PlanExec

        blob = _nx.build_blob(
            600, [256], [128, 256], [1, 2],
            [{"depth": 2,
              "streams": [(0, [(b"P0", b"M0", 256, 0, 256,
                                ((0, 0, 0, 256),))])],
              "rsrcs": [(1, [(0, 128, 0, 128, b"P1", b"M1")])]},
             {"depth": 2,
              "streams": [(1, [(b"P2", b"M2", 128, 0, 128,
                                ((1, 0, 0, 128),))])],
              "rsrcs": [(0, [(1, 256, 0, 256, b"P3", b"M3")])]}])
        pxn = _PlanExec(blob)
        try:
            assert pxn.round_count == 2 and pxn.input_count == 1
            assert pxn.pool_count == 2 and pxn.pool_total == 384
        finally:
            pxn.close()
        print("native plan executor: 2-round descriptor table "
              f"({len(blob)}B) compiled and introspected device-free")
    else:
        assert _nx.try_compile(
            type("S", (), {"plan": None})(), object(), None, (), {}) \
            is None
        print("native plan executor: symbols absent — try_compile "
              "withdraws, interpreted replay in force")
    rnd_n = _cplan.WireRound(((1, (((64,), "int32"),)),), ((1, 1),),
                             ((1, (None,)),), 600, 2)
    lpn = _ledger.register_spanning_plan(62, "native_selftest", 0,
                                         [rnd_n, rnd_n])
    tsn = (time.perf_counter(), time.perf_counter() + 1e-4)
    seqn = _ledger.record_fire(_ledger.KIND_SPANNING, lpn, 62,
                               tsn[0] - 1e-4, tsn[1], round0=4,
                               round_ts=tsn)
    recn = [r for r in _ledger.records() if r["seq"] == seqn][0]
    assert recn["plan"] == lpn and recn["round0"] == 4
    assert tuple(recn["round_ts"]) == tsn, recn
    spans_n = _ledger.expand_record(recn, _ledger.plans())
    assert any(s["op"].endswith("wire_round1") for s in spans_n), \
        spans_n
    print("native plan executor: C-stamped round boundaries "
          f"round-trip the ledger ({len(spans_n)} spans)")

    disable()
    print("obs selftest: ok")
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "doctor":
        # `python -m ompi_release_tpu.obs doctor ...` == tpu-doctor
        from ..tools.tpu_doctor import main as doctor_main

        return doctor_main(argv[1:])
    ap = argparse.ArgumentParser(
        prog="python -m ompi_release_tpu.obs",
        description="Observability-plane utilities ('doctor ...' "
                    "forwards to tpu-doctor: merge/report/postmortem/"
                    "collect)")
    ap.add_argument("--selftest", action="store_true",
                    help="register/bump/export/verify every pvar class "
                         "and exporter (device-free)")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
