"""Plan-relative flight recorder — compiled fires stay observable
without de-optimizing the hot path.

The compiled steady state (``coll/plan``) used to go dark the moment
obs came on: every observed fire fell back to the interpreted path so
the span/flow record stayed complete, which meant tracing *replaced*
the production path instead of observing it. This module inverts
that. A frozen plan is deterministic — its round structure, peers,
message sizes, and flow-id derivation are all fixed at freeze time —
so the plan registers that structure HERE once, and every compiled
fire appends only one fixed-size binary record into a per-rank slot
ring:

    header  ``<BHiQIIdd``  (39 bytes, little-endian, no padding)
        kind      u8   0 = device (one XLA program), 1 = spanning,
                       2 = rma (one fused epoch program, osc/plan)
        n_rounds  u16  planned wire rounds timed in this fire
        cid       i32  communicator id
        plan_id   u64  ledger plan id (per-rank registry key)
        seq       u32  per-rank posting sequence
        round0    u32  hier round counter at fire time (flow-id base)
        t_start   f64  perf_counter at fire entry
        t_end     f64  perf_counter after the fire
    tail    ``n_rounds`` f64 round-end clock reads (one per planned
            wire round, appended by ``PlannedXchg``)

The fire path is lock-free: the ring cursor and posting sequence are
``itertools.count`` objects (atomic under the GIL) and each slot
holds one immutable ``bytes`` record — no span objects, no dicts, no
header packing beyond one ``struct.pack``.

:func:`expand_record` re-derives full synthetic spans from a record
plus its frozen plan metadata: a per-round hier span, per-message
``hier_send``/``hier_recv`` instants carrying the SAME ``("hier",
cid, round, src, dst, k)`` FNV flow ids ``coll/hier.py`` emits on
the interpreted path (k accumulated per directed pair in posting
order, both sides re-deriving independently), and one ``coll``-layer
span per device fire. ``tpu-doctor`` therefore merges compiled
traffic into Perfetto flow arrows, skew reports, and the sampler's
per-comm ``coll_*`` series exactly like interpreted traffic.

``obs/export.maybe_dump_ledger`` writes the ring next to the journal
dump at finalize; watchdog postmortems drop a ledger dump beside the
postmortem file and carry the decoded tail inline.
"""

from __future__ import annotations

import itertools
import json
import struct
import threading
import time as _time
from typing import Any, Dict, List, Optional, Tuple

from .. import obs as _obs
from ..mca import pvar as _pvar
from ..mca import var as _var
from .journal import flow_id

FORMAT = "ompitpu-ledger-v1"
DEFAULT_SIZE = 16384

KIND_DEVICE = 0
KIND_SPANNING = 1
KIND_RMA = 2

_HDR = struct.Struct("<BHiQIIdd")
_TAILS: Dict[int, struct.Struct] = {}


def register_vars() -> None:
    _var.register(
        "obs_ledger_size", "size", DEFAULT_SIZE,
        "Flight-recorder ring capacity in fixed-size fire records "
        "(oldest records are overwritten); one record per compiled-"
        "plan fire while obs is on",
    )


register_vars()  # idempotent; the cvar must exist before first record

_records = _pvar.counter(
    "ledger_records",
    "compiled-plan fire records appended to the flight-recorder ring "
    "(one fixed-size binary record per observed compiled fire)",
)
_dropped = _pvar.counter(
    "ledger_dropped",
    "flight-recorder records lost to ring wrap (raise obs_ledger_size)",
)

_lock = threading.Lock()  # registration / resize / dump — never fires
#: plan id -> frozen-structure metadata (JSON-able; registered once
#: per freeze, read only at expansion/dump time)
_plans: Dict[int, Dict[str, Any]] = {}
_next_plan = itertools.count(1)
#: the fire path: next(_cursor) and a slot store, nothing else
_ring: List[Optional[bytes]] = [None] * int(
    _var.get("obs_ledger_size", DEFAULT_SIZE))
_cursor = itertools.count()
_seq = itertools.count()


def _tail(n: int) -> struct.Struct:
    s = _TAILS.get(n)
    if s is None:
        s = _TAILS[n] = struct.Struct("<%dd" % n)
    return s


# ---------------------------------------------------------------------------
# plan registration (once per freeze) + the per-fire record
# ---------------------------------------------------------------------------

def _sig_summary(sig: Any) -> str:
    s = str(sig)
    return s if len(s) <= 160 else s[:157] + "..."


def register_device_plan(cid: int, name: str, nbytes: int,
                         sig: Any = "") -> int:
    """Register one frozen device plan (a single compiled XLA
    program); returns its ledger plan id."""
    meta = {"kind": "device", "cid": int(cid), "name": name,
            "nbytes": int(nbytes), "sig": _sig_summary(sig),
            "rounds": []}
    with _lock:
        pid = next(_next_plan)
        _plans[pid] = meta
    return pid


def register_rma_plan(cid: int, name: str, nbytes: int,
                      sig: Any = "") -> int:
    """Register one frozen RMA access plan (a single fused epoch
    program — ``osc/plan``); returns its ledger plan id. Fires expand
    to ``osc``-layer spans, so the doctor's per-comm series see
    compiled RMA epochs exactly like interpreted ``win_apply``
    traffic."""
    meta = {"kind": "rma", "cid": int(cid), "name": name,
            "nbytes": int(nbytes), "sig": _sig_summary(sig),
            "rounds": []}
    with _lock:
        pid = next(_next_plan)
        _plans[pid] = meta
    return pid


def register_spanning_plan(cid: int, name: str, pidx: int,
                           wire_rounds, sig: Any = "") -> int:
    """Register one frozen wire plan's round structure: per round the
    per-peer send sizes (posting order — the k counters advance in
    this order) and receive counts. ``wire_rounds`` is the plan's
    :class:`~..coll.plan.WireRound` list."""
    import numpy as np

    rounds = []
    for rnd in wire_rounds:
        sends = []
        for p, arrs in rnd.sends_meta:
            sizes = []
            for shape, dtype in arrs:
                n = 1
                for d in shape:
                    n *= int(d)
                try:
                    sizes.append(n * int(np.dtype(dtype).itemsize))
                except TypeError:
                    sizes.append(0)
            sends.append([int(p), sizes])
        recvs = [[int(p), int(c)] for p, c in rnd.recvs_t]
        rounds.append({"sends": sends, "recvs": recvs})
    meta = {"kind": "spanning", "cid": int(cid), "name": name,
            "pidx": int(pidx), "sig": _sig_summary(sig),
            "rounds": rounds}
    with _lock:
        pid = next(_next_plan)
        _plans[pid] = meta
    return pid


def record_fire(kind: int, plan_id: int, cid: int, t_start: float,
                t_end: float, round0: int = 0,
                round_ts: Tuple[float, ...] = ()) -> int:
    """Append one fixed-size fire record (THE hot-path entry; callers
    gate on ``_obs.enabled`` themselves). Returns the posting seq."""
    seq = next(_seq) & 0xFFFFFFFF
    n = len(round_ts)
    rec = _HDR.pack(kind, n, cid, plan_id, seq, round0 & 0xFFFFFFFF,
                    t_start, t_end)
    if n:
        rec += _tail(n).pack(*round_ts)
    ring = _ring
    i = next(_cursor)
    if i >= len(ring):
        _dropped.add()  # wrapped: every write now evicts one record
    ring[i % len(ring)] = rec
    _records.add()
    return seq


# ---------------------------------------------------------------------------
# decode / snapshot / dump
# ---------------------------------------------------------------------------

def decode(rec: bytes) -> Dict[str, Any]:
    """One binary record back into its JSON-able form."""
    kind, n, cid, pid, seq, round0, t0, t1 = _HDR.unpack_from(rec)
    return {"kind": int(kind), "cid": int(cid), "plan": int(pid),
            "seq": int(seq), "round0": int(round0),
            "t_start": t0, "t_end": t1,
            "round_ts": list(_tail(n).unpack_from(rec, _HDR.size))
            if n else []}


def records(since_seq: int = -1) -> List[Dict[str, Any]]:
    """Decoded buffered records with seq > ``since_seq``, posting
    order. Wrap-safe for pollers: seq is monotonic per rank."""
    out = [decode(r) for r in list(_ring) if r is not None]
    out.sort(key=lambda d: d["seq"])
    if since_seq >= 0:
        out = [d for d in out if d["seq"] > since_seq]
    return out


def plans() -> Dict[int, Dict[str, Any]]:
    with _lock:
        return {pid: dict(meta) for pid, meta in _plans.items()}


def snapshot() -> Dict[str, Any]:
    """The full dump document tpu-doctor expands: frozen-plan
    metadata + decoded records + rank identity/clock for the merge."""
    recs = records()
    with _lock:
        plan_doc = {str(pid): dict(meta) for pid, meta in _plans.items()}
    doc = {"format": FORMAT, "record_bytes": _HDR.size,
           "meta": _obs.rank_identity(),
           "clock_offset_s": _obs.clock_offset(),
           "plans": plan_doc, "records": recs}
    if _obs.enabled:
        _obs.record("ledger_dump", "obs", _time.perf_counter(), 0.0,
                    nbytes=len(recs))
    return doc


def dump(path: str) -> str:
    with open(path, "w") as f:
        json.dump(snapshot(), f)
    return path


def _ledger_tail(n: int = 32) -> Dict[str, Any]:
    """Watchdog-postmortem contributor: the newest decoded records +
    the plans they reference (best-effort, never raises past the
    watchdog's guard)."""
    recs = records()[-n:]
    want = {r["plan"] for r in recs}
    with _lock:
        plan_doc = {str(pid): dict(meta) for pid, meta in _plans.items()
                    if pid in want}
    return {"records": recs, "plans": plan_doc,
            "total": int(_records.read()),
            "dropped": int(_dropped.read())}


# ---------------------------------------------------------------------------
# expansion: records -> synthetic spans (journal-dump span format)
# ---------------------------------------------------------------------------

def expand_record(rec: Dict[str, Any],
                  plan_docs: Dict[Any, Dict[str, Any]],
                  pidx: int = 0) -> List[Dict[str, Any]]:
    """Synthetic spans for one fire record, in journal-dump form.

    Device fires expand to one ``coll``-layer span (the per-comm
    ``coll_*`` series and round alignment see compiled device traffic
    again); RMA fires to one ``osc``-layer span per epoch replay.
    Spanning fires expand to one hier-layer span per planned
    wire round plus per-message send/recv instants carrying the
    interpreted path's exact flow ids: ``flow_id("hier", cid, round0,
    src, dst, k)`` with k accumulated per directed pair in posting
    order — each rank re-derives its own side, and the ids meet in
    the doctor's merge because the frozen structures are
    complementary by construction."""
    meta = plan_docs.get(str(rec["plan"])) or plan_docs.get(rec["plan"])
    if meta is None:
        return []
    cid = rec["cid"]
    name = meta.get("name", "coll")
    if meta.get("kind") in ("device", "rma") or not meta.get("rounds"):
        layer = "osc" if meta.get("kind") == "rma" else "coll"
        return [{"seq": rec["seq"], "op": name, "layer": layer,
                 "t": rec["t_start"],
                 "dt": max(0.0, rec["t_end"] - rec["t_start"]),
                 "bytes": int(meta.get("nbytes", 0)), "peer": -1,
                 "comm": cid, "ledger": True}]
    me = int(meta.get("pidx", pidx))
    round0 = rec["round0"]
    ts = rec.get("round_ts") or []
    spans: List[Dict[str, Any]] = []
    k: Dict[Tuple[int, int], int] = {}
    t_prev = rec["t_start"]
    for r, rmeta in enumerate(meta["rounds"]):
        t_end_r = ts[r] if r < len(ts) else rec["t_end"]
        spans.append({
            "seq": rec["seq"], "op": f"{name}_wire_round{r}",
            "layer": "hier", "t": t_prev,
            "dt": max(0.0, t_end_r - t_prev),
            "bytes": sum(int(b) for _, sizes in rmeta["sends"]
                         for b in sizes),
            "peer": -1, "comm": cid, "ledger": True})
        for p, sizes in rmeta["sends"]:
            for nb in sizes:
                kk = k.get((me, p), 0)
                k[(me, p)] = kk + 1
                spans.append({
                    "seq": rec["seq"], "op": "hier_send",
                    "layer": "hier", "t": t_prev, "dt": 0.0,
                    "bytes": int(nb), "peer": int(p), "comm": cid,
                    "flow": flow_id("hier", cid, round0, me, p, kk),
                    "fs": "s", "ledger": True})
        for p, cnt in rmeta["recvs"]:
            for _ in range(int(cnt)):
                kk = k.get((p, me), 0)
                k[(p, me)] = kk + 1
                spans.append({
                    "seq": rec["seq"], "op": "hier_recv",
                    "layer": "hier", "t": t_end_r, "dt": 0.0,
                    "bytes": 0, "peer": int(p), "comm": cid,
                    "flow": flow_id("hier", cid, round0, p, me, kk),
                    "fs": "t", "ledger": True})
        t_prev = t_end_r
    return spans


def expand_dump(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """All synthetic spans of one ledger dump document, time order."""
    plan_docs = doc.get("plans") or {}
    pidx = int((doc.get("meta") or {}).get("pidx", 0))
    spans: List[Dict[str, Any]] = []
    for rec in doc.get("records") or []:
        spans.extend(expand_record(rec, plan_docs, pidx))
    spans.sort(key=lambda s: s["t"])
    return spans


# ---------------------------------------------------------------------------
# housekeeping
# ---------------------------------------------------------------------------

def resize(size: int) -> None:
    """Change ring capacity, keeping the newest records."""
    global _ring, _cursor
    with _lock:
        recs = sorted((decode(r)["seq"], r) for r in _ring
                      if r is not None)
        size = max(1, int(size))
        newest = recs[-size:]
        _ring = [None] * size
        for i, (_, r) in enumerate(newest):
            _ring[i] = r
        _cursor = itertools.count(len(newest))


def _reset_for_tests() -> None:
    global _ring, _cursor, _seq, _next_plan
    with _lock:
        _plans.clear()
        _ring = [None] * int(_var.get("obs_ledger_size", DEFAULT_SIZE))
        _cursor = itertools.count()
        _seq = itertools.count()
        _next_plan = itertools.count(1)


from . import watchdog as _watchdog  # noqa: E402  (import order: tail)

_watchdog.add_contributor("ledger_tail", _ledger_tail)
