"""Native event-ring expansion — the zero-copy datapath's spans.

PR 17 took Python out of the byte path, which also took the byte path
out of the trace: fragments crossing ``native/btl_shm.cc`` rings and
``native/btl_tcp.cc`` writev never touched an emit site, so a merged
doctor trace showed the header handshake and then silence where the
bytes moved. This module is the PR 16 ledger discipline applied one
layer down: the C transports append one fixed 32-byte record per SGC2
fragment into a per-process mmap'd ring ("ompitpu-nativeev-v1",
cvar-gated, off by default — see ``btl/nativewire.py`` for the
lifecycle), and Python only ever decodes records at dump time.

:func:`expand_record` turns one record into a wire-layer span whose
flow id re-derives from the (tag, xfer, idx) triple already carried
in every SGC2 frame header — the sender and receiver each log their
own side with no coordination, and the ids meet in the doctor's merge
exactly like the hier/ledger flows, keeping cross-rank arrows for
bytes Python never touched.

Timebase: the C side stamps CLOCK_REALTIME nanoseconds (the only
clock two processes on one host share without a handshake); journal
spans use ``perf_counter``. Each dump records this process's
``rt_minus_pc`` bridge (``time.time() - perf_counter()``) so
expansion lands the spans on the journal's timebase, after which the
doctor's per-dump ``clock_offset_s`` correction applies unchanged.
"""

from __future__ import annotations

import json
import time as _time
from typing import Any, Dict, List, Optional

from .. import obs as _obs
from ..mca import pvar as _pvar
from .journal import flow_id

FORMAT = "ompitpu-nativeev-v1"
RECORD_BYTES = 32

_dumps_pvar = _pvar.counter(
    "obs_nativeev_dumps",
    "native event-ring dump documents produced (finalize dumps + "
    "watchdog postmortem drops + explicit tool snapshots)",
)

#: the live per-process event ring (a ``bindings.NativeEventRing``),
#: registered by the nativewire component when the cvar enables it —
#: dump/contributor entry points read through this
_ring = None


def set_ring(ring) -> None:
    """Register the process's live event ring (None detaches)."""
    global _ring
    _ring = ring


def get_ring():
    return _ring


# ---------------------------------------------------------------------------
# snapshot / dump (the ledger dump discipline, one layer down)
# ---------------------------------------------------------------------------

def snapshot(ring=None) -> Dict[str, Any]:
    """The full dump document tpu-doctor expands: decoded records +
    rank identity/clock for the merge + the realtime->perf_counter
    bridge for this process."""
    ring = ring if ring is not None else _ring
    first, recs = (0, []) if ring is None else ring.read()
    total = 0 if ring is None else ring.count()
    doc = {
        "format": FORMAT, "record_bytes": RECORD_BYTES,
        "meta": _obs.rank_identity(),
        "clock_offset_s": _obs.clock_offset(),
        "rt_minus_pc": _time.time() - _time.perf_counter(),
        "first_seq": int(first), "total": int(total),
        "records": recs,
    }
    _dumps_pvar.add()
    if _obs.enabled:
        _obs.record("nativeev_dump", "obs", _time.perf_counter(), 0.0,
                    nbytes=len(recs))
    return doc


def dump(path: str, ring=None) -> str:
    with open(path, "w") as f:
        json.dump(snapshot(ring), f)
    return path


def _nativeev_tail(n: int = 32) -> Dict[str, Any]:
    """Watchdog-postmortem contributor: the newest decoded native
    events (best-effort, never raises past the watchdog's guard)."""
    if _ring is None:
        return {"installed": False}
    first, recs = _ring.read()
    return {"installed": True, "total": int(_ring.count()),
            "first_seq": int(first), "records": recs[-n:]}


# ---------------------------------------------------------------------------
# expansion: records -> synthetic wire-layer spans
# ---------------------------------------------------------------------------

def frag_flow_id(tag: int, xfer: int, idx: int) -> int:
    """The native fragment flow id: both transfer endpoints re-derive
    it independently from the SGC2 triple their own transport logged —
    no coordination, same 64-bit FNV fold as every other flow."""
    return flow_id("nw", tag, xfer, idx)


def expand_record(rec: Dict[str, Any], rt_minus_pc: float = 0.0,
                  seq: int = 0) -> Dict[str, Any]:
    """One decoded event record as a journal-dump wire-layer span.

    Send records become the flow's "s" side, receive records the "t"
    side; ``wait_s`` carries how long the emitting call sat blocked
    (ring full on the producer, ring/queue empty on the consumer) —
    the per-fragment complement of the ring counters' aggregate
    stall_ns."""
    recv = bool(rec.get("recv"))
    t = float(rec["t_ns"]) / 1e9 - rt_minus_pc
    return {
        "seq": int(seq), "op": "nw_frag_recv" if recv else "nw_frag_send",
        "layer": "wire", "t": t, "dt": 0.0,
        "bytes": int(rec.get("bytes", 0)), "peer": -1,
        "comm": -1,
        "flow": frag_flow_id(int(rec["tag"]), int(rec["xfer"]),
                             int(rec["idx"])),
        "fs": "t" if recv else "s",
        "wait_s": float(rec.get("wait_ns", 0)) / 1e9,
        "nativeev": True,
    }


def expand_dump(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """All synthetic spans of one event-ring dump document, time
    order."""
    bridge = float(doc.get("rt_minus_pc", 0.0) or 0.0)
    base = int(doc.get("first_seq", 0) or 0)
    spans = [expand_record(rec, bridge, base + i)
             for i, rec in enumerate(doc.get("records") or [])]
    spans.sort(key=lambda s: s["t"])
    return spans


def _reset_for_tests() -> None:
    global _ring
    _ring = None


from . import watchdog as _watchdog  # noqa: E402  (import order: tail)

_watchdog.add_contributor("nativeev_tail", _nativeev_tail)
