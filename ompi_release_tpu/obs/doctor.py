"""Job-level trace assembly — merge per-rank journals, draw flows,
name the slow rank.

Input: one :func:`obs.export.rank_dump` document per controller
process (written at finalize via ``obs_dump_dir``, embedded in
postmortems, or fetched over the ``tpu_server`` journal RPC). Each
carries the rank identity and the OOB clock offset mapping that
process's ``perf_counter`` timebase into the HNP's.

Output:

- :func:`merge`: ONE Perfetto/Chrome ``trace_event`` document — pid =
  controller process (named with its world-rank span), tid = layer,
  timestamps clock-offset-corrected, and **flow arrows** joining every
  producer span ("s" side) to its consumer span ("t" side) by the
  deterministic flow ids the emit points stamped (p2p envelope seq,
  hier round/pair/index, window request token).
- :func:`skew_report`: per (comm, op) collective-round table — round k
  is the k-th occurrence of that op on each process (collective call
  order is identical everywhere, MPI's own rule), arrival spread is
  max-min corrected start, and the LAST arriver is the critical-path
  rank for that round.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional, Tuple

from .export import span_event


def load_dump(path: str) -> Dict[str, Any]:
    with open(path) as f:
        doc = json.load(f)
    if "spans" not in doc or "meta" not in doc:
        raise ValueError(f"{path}: not a rank journal dump "
                         "(missing meta/spans)")
    return doc


def load_dir(directory: str) -> List[Dict[str, Any]]:
    """Every ``journal-p*.json`` under ``directory``, plus — for ranks
    that never finalized (a hung rank killed mid-job leaves ONLY
    postmortems) — the journal tail of that rank's newest
    ``postmortem-*.json``. ``ledger-p*.json`` flight-recorder dumps
    are expanded against their frozen-plan metadata into synthetic
    spans and merged into the matching rank's span list (compiled
    fires carry the interpreted path's flow ids, so flow arrows and
    skew rounds include compiled traffic)."""
    dumps = []
    for p in sorted(glob.glob(os.path.join(directory, "journal-p*.json"))):
        dumps.append(load_dump(p))
    finalized = {int(d["meta"].get("pidx", 0)) for d in dumps}
    # one postmortem dump per missing rank: a hung rank routinely
    # writes SEVERAL postmortems (one per newly stalled wait, plus
    # operator SIGUSR1 pokes) whose journal tails overlap — merging
    # them all would render that rank's spans twice and desync the
    # skew report's tail alignment. Keep only the newest per pidx
    # (latest time_unix: the longest journal tail), and only for
    # ranks without a finalize-time journal (which supersedes tails).
    newest: Dict[int, Tuple[float, Dict[str, Any]]] = {}
    for p in sorted(glob.glob(os.path.join(directory,
                                           "postmortem-*.json"))):
        with open(p) as f:
            pm = json.load(f)
        tail = pm.get("journal_tail")
        if not isinstance(tail, list):
            continue
        rank = pm.get("rank", {})
        clock = pm.get("clock", {}) or {}
        pidx = int(rank.get("pidx", 0))
        if pidx in finalized:
            continue
        t = float(pm.get("time_unix", 0.0) or 0.0)
        prev = newest.get(pidx)
        if prev is not None and prev[0] >= t:
            continue
        newest[pidx] = (t, {
            "meta": {"pidx": pidx,
                     "rank_offset": rank.get("rank_offset", 0),
                     "local_size": rank.get("local_size", 0),
                     "pid": rank.get("pid"),
                     "clock_offset_s": clock.get("offset_s"),
                     "clock_rtt_s": clock.get("rtt_s")},
            "spans": tail,
        })
    dumps.extend(d for _, (_, d) in sorted(newest.items()))
    attach_ledgers(dumps, directory)
    attach_native_events(dumps, directory)
    dumps.sort(key=lambda d: int(d["meta"].get("pidx", 0)))
    if not dumps:
        raise FileNotFoundError(
            f"no journal-p*.json, postmortem-*.json, ledger-p*.json, "
            f"or nativeev-p*.json dumps under {directory} (set --mca "
            "obs_dump_dir, or send SIGUSR1 to the ranks first)")
    return dumps


def load_ledger_dump(path: str) -> Dict[str, Any]:
    from . import ledger as _ledger

    with open(path) as f:
        doc = json.load(f)
    if doc.get("format") != _ledger.FORMAT:
        raise ValueError(f"{path}: not a flight-recorder ledger dump "
                         f"(format != {_ledger.FORMAT})")
    return doc


def attach_ledgers(dumps: List[Dict[str, Any]],
                   directory: str) -> None:
    """Expand every ``ledger-p*.json`` under ``directory`` into
    synthetic spans and merge them into the matching rank's dump (a
    rank with no journal dump gets a fresh one from the ledger's own
    identity). The combined span list is re-sorted by start time so
    the skew report's call-order round alignment holds across real
    and synthetic spans."""
    from . import ledger as _ledger

    by_pidx = {int(d["meta"].get("pidx", 0)): d for d in dumps}
    for p in sorted(glob.glob(os.path.join(directory,
                                           "ledger-p*.json"))):
        try:
            doc = load_ledger_dump(p)
        except (ValueError, OSError):
            continue
        spans = _ledger.expand_dump(doc)
        if not spans:
            continue
        meta = doc.get("meta") or {}
        pidx = int(meta.get("pidx", 0))
        host = by_pidx.get(pidx)
        if host is None:
            host = by_pidx[pidx] = {
                "meta": {"pidx": pidx,
                         "rank_offset": meta.get("rank_offset", 0),
                         "local_size": meta.get("local_size", 0),
                         "pid": meta.get("pid"),
                         "clock_offset_s": doc.get("clock_offset_s"),
                         "clock_rtt_s": None},
                "spans": []}
            dumps.append(host)
        host["spans"] = sorted(
            list(host["spans"]) + spans,
            key=lambda s: float(s.get("t", 0.0)))
        host.pop("_corrected_spans", None)


def load_nativeev_dump(path: str) -> Dict[str, Any]:
    from . import nativeev as _nativeev

    with open(path) as f:
        doc = json.load(f)
    if doc.get("format") != _nativeev.FORMAT:
        raise ValueError(f"{path}: not a native event-ring dump "
                         f"(format != {_nativeev.FORMAT})")
    return doc


def attach_native_events(dumps: List[Dict[str, Any]],
                         directory: str) -> None:
    """Expand every ``nativeev-p*.json`` under ``directory`` into
    wire-layer spans and merge them into the matching rank's dump —
    the :func:`attach_ledgers` discipline for the zero-copy datapath.
    Send/recv records carry flow ids re-derived from the SGC2 (tag,
    xfer, idx) triple, so :func:`flow_pairs` and :func:`merge` draw
    cross-process arrows for fragments Python never touched."""
    from . import nativeev as _nativeev

    by_pidx = {int(d["meta"].get("pidx", 0)): d for d in dumps}
    for p in sorted(glob.glob(os.path.join(directory,
                                           "nativeev-p*.json"))):
        try:
            doc = load_nativeev_dump(p)
        except (ValueError, OSError):
            continue
        spans = _nativeev.expand_dump(doc)
        if not spans:
            continue
        meta = doc.get("meta") or {}
        pidx = int(meta.get("pidx", 0))
        host = by_pidx.get(pidx)
        if host is None:
            host = by_pidx[pidx] = {
                "meta": {"pidx": pidx,
                         "rank_offset": meta.get("rank_offset", 0),
                         "local_size": meta.get("local_size", 0),
                         "pid": meta.get("pid"),
                         "clock_offset_s": doc.get("clock_offset_s"),
                         "clock_rtt_s": None},
                "spans": []}
            dumps.append(host)
        host["spans"] = sorted(
            list(host["spans"]) + spans,
            key=lambda s: float(s.get("t", 0.0)))
        host.pop("_corrected_spans", None)


def _offset(meta: Dict[str, Any]) -> float:
    off = meta.get("clock_offset_s")
    return float(off) if off is not None else 0.0


def _corrected(dump: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Spans with a ``ts`` key in the merged (HNP) timebase, seconds.
    Cached on the dump: merge(), flow_pairs(), and _coll_rounds() all
    walk the same spans (a `tpu-doctor report` hits all three), and at
    job scale recomputing means millions of redundant dict copies. The
    spans are read-only downstream, so one shared list is safe."""
    cached = dump.get("_corrected_spans")
    if cached is None:
        off = _offset(dump["meta"])
        cached = []
        for s in dump["spans"]:
            c = dict(s)
            c["ts"] = float(s["t"]) + off
            cached.append(c)
        dump["_corrected_spans"] = cached
    return cached


def flow_pairs(dumps: List[Dict[str, Any]]
               ) -> List[Dict[str, Any]]:
    """Matched (producer, consumer) span pairs across dumps: one entry
    per flow id seen with both sides. Producer/consumer carry the
    owning pidx so callers can tell cross-process flows apart."""
    sides: Dict[int, Dict[str, List[Tuple[int, Dict]]]] = {}
    for d in dumps:
        pidx = int(d["meta"].get("pidx", 0))
        for s in _corrected(d):
            fl = s.get("flow")
            if not fl:
                continue
            side = "s" if s.get("fs") == "s" else "t"
            sides.setdefault(int(fl), {"s": [], "t": []})[side].append(
                (pidx, s))
    pairs = []
    for fl, ends in sorted(sides.items()):
        if not ends["s"] or not ends["t"]:
            continue
        # multiple spans per id would mean an id collision (64-bit FNV
        # over distinct identifiers: vanishingly rare) — pair in order
        for (sp, ss), (tp, ts) in zip(ends["s"], ends["t"]):
            pairs.append({"flow": fl, "src_pidx": sp, "dst_pidx": tp,
                          "src": ss, "dst": ts,
                          "cross_process": sp != tp,
                          "latency_s": ts["ts"] - (ss["ts"] + ss["dt"])})
    return pairs


def merge(dumps: List[Dict[str, Any]]) -> Dict[str, Any]:
    """One clock-aligned Perfetto trace for the whole job."""
    events: List[Dict[str, Any]] = []
    meta_events: List[Dict[str, Any]] = []
    tids: Dict[Tuple[int, str], int] = {}
    for d in sorted(dumps, key=lambda d: int(d["meta"].get("pidx", 0))):
        m = d["meta"]
        pidx = int(m.get("pidx", 0))
        off0 = int(m.get("rank_offset", 0))
        n = int(m.get("local_size", 0))
        label = (f"proc {pidx} (world ranks {off0}..{off0 + n - 1})"
                 if n else f"proc {pidx}")
        meta_events.append({"name": "process_name", "ph": "M",
                            "pid": pidx, "args": {"name": label}})
        for s in _corrected(d):
            key = (pidx, s["layer"])
            tid = tids.get(key)
            if tid is None:
                tid = tids[key] = len(tids) + 1
                meta_events.append({
                    "name": "thread_name", "ph": "M", "pid": pidx,
                    "tid": tid, "args": {"name": s["layer"]},
                })
            events.append(span_event(s, pid=pidx, tid=tid,
                                     ts_s=s["ts"]))
    flows = flow_pairs(dumps)
    for p in flows:
        src, dst = p["src"], p["dst"]
        src_tid = tids.get((p["src_pidx"], src["layer"]), 1)
        dst_tid = tids.get((p["dst_pidx"], dst["layer"]), 1)
        fid = str(p["flow"])
        events.append({
            "name": src["op"], "cat": "flow", "ph": "s", "id": fid,
            "pid": p["src_pidx"], "tid": src_tid,
            "ts": (src["ts"] + src["dt"]) * 1e6,
        })
        events.append({
            "name": src["op"], "cat": "flow", "ph": "f", "bp": "e",
            "id": fid, "pid": p["dst_pidx"], "tid": dst_tid,
            "ts": (dst["ts"] + dst["dt"]) * 1e6,
        })
    doc = {"traceEvents": meta_events + events, "displayTimeUnit": "ms"}
    doc["otherData"] = {
        "processes": len(dumps),
        "spans": sum(len(d["spans"]) for d in dumps),
        "flows": len(flows),
        "cross_process_flows": sum(1 for p in flows
                                   if p["cross_process"]),
    }
    return doc



# ---------------------------------------------------------------------------
# continuous series (obs/sampler.py rings) — load, clock-correct, merge
# ---------------------------------------------------------------------------


def load_series_dump(path: str) -> Dict[str, Any]:
    """One ``series-p*.jsonl`` file (meta header line + one point per
    line, ``obs.export.dump_series_jsonl``) back into the
    ``{"meta": ..., "points": [...]}`` document shape."""
    meta: Dict[str, Any] = {}
    points: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            doc = json.loads(line)
            if "meta" in doc and "t" not in doc:
                meta = doc["meta"]
            else:
                points.append(doc)
    if not meta and not points:
        raise ValueError(f"{path}: empty series dump")
    return {"meta": meta, "points": points}


def load_series_dir(directory: str) -> List[Dict[str, Any]]:
    """Every ``series-p*.jsonl`` under ``directory``, ordered by
    pidx. Missing files are not an error here — callers that can
    proceed without series (the report annotation) check for []."""
    docs = []
    for p in sorted(glob.glob(os.path.join(directory,
                                           "series-p*.jsonl"))):
        docs.append(load_series_dump(p))
    docs.sort(key=lambda d: int(d["meta"].get("pidx", 0)))
    return docs


def merge_series(docs: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """One clock-corrected fleet series: every point gains ``ts``
    (sample time mapped into the HNP timebase via the dump's clock
    offset — the same correction journals get) and ``pidx``, merged
    across processes and sorted by corrected time."""
    merged: List[Dict[str, Any]] = []
    for d in docs:
        off = _offset(d["meta"])
        pidx = int(d["meta"].get("pidx", 0))
        for p in d["points"]:
            c = dict(p)
            c["ts"] = float(p["t"]) + off
            c["pidx"] = pidx
            merged.append(c)
    merged.sort(key=lambda p: p["ts"])
    return merged


def fleet_to_series_docs(fleet: Dict[str, Any]) -> List[Dict[str, Any]]:
    """A live HNP fleet document (``HnpCoordinator.fleet_series``)
    reshaped into the same per-process doc list the offline loaders
    produce, so merge_series/tpu_top render both identically."""
    docs = []
    for pidx_s, ent in sorted((fleet.get("procs") or {}).items(),
                              key=lambda kv: int(kv[0])):
        meta = dict(ent.get("meta") or {})
        meta.update(pidx=int(pidx_s),
                    clock_offset_s=ent.get("clock_offset_s"),
                    push_age_s=ent.get("push_age_s"))
        docs.append({"meta": meta,
                     "points": list(ent.get("points") or ())})
    return docs


def series_rates(merged: List[Dict[str, Any]]
                 ) -> Dict[int, Dict[str, float]]:
    """Per-process sampled collective rates over the merged window:
    pidx -> {"window_s", "coll_ops_per_s", "coll_mb_per_s",
    "coll_busy_frac"} folded from the per-cid ``coll_*`` delta points.
    The doctor report annotates its critical path with these — a rank
    that is both the chronic last-arriver AND the lowest-rate rank is
    compute-bound, not network-starved."""
    by_pidx: Dict[int, Dict[str, float]] = {}
    spans: Dict[int, List[float]] = {}
    for p in merged:
        pidx = int(p.get("pidx", 0))
        name = p.get("name")
        if name not in ("coll_ops", "coll_bytes", "coll_seconds"):
            continue
        acc = by_pidx.setdefault(
            pidx, {"coll_ops": 0.0, "coll_bytes": 0.0,
                   "coll_seconds": 0.0})
        try:
            acc[name] += float(p.get("v", 0.0))
        except (TypeError, ValueError):
            continue
        spans.setdefault(pidx, []).append(float(p["ts"]))
    out: Dict[int, Dict[str, float]] = {}
    for pidx, acc in sorted(by_pidx.items()):
        ts = sorted(set(spans.get(pidx) or ()))
        if len(ts) < 2:
            # a single tick has no measurable window — omitting the
            # proc beats reporting a made-up (and wildly inflated) rate
            continue
        window = max(ts[-1] - ts[0], 1e-9)
        out[pidx] = {
            "window_s": window,
            "coll_ops_per_s": acc["coll_ops"] / window,
            "coll_mb_per_s": acc["coll_bytes"] / window / 1e6,
            "coll_busy_frac": min(acc["coll_seconds"] / window, 1.0),
        }
    return out


# ---------------------------------------------------------------------------
# collective contract alignment (obs/sentinel.py signature events)
# ---------------------------------------------------------------------------


def sentinel_records(dumps: List[Dict[str, Any]],
                     directory: Optional[str] = None
                     ) -> Dict[int, Dict[int, Dict[int, Dict[str, Any]]]]:
    """Per-comm signature records: cid -> pidx -> posting seq ->
    ``{"canon", "family", "epoch", "site"}``. Two sources, deduped by
    (pidx, cid, seq):

    - journal spans with layer ``"sentinel"`` (finalize dumps and
      postmortem journal tails — ``load_dir`` already folds both);
    - the per-comm last-N signature rings: the ``"sentinel"``
      watchdog-contributor block of postmortem files under
      ``directory`` AND the finalize dump's ``meta["sentinel"]`` —
      both survive a journal wrap past the divergent round.
    """
    from .sentinel import parse_op

    out: Dict[int, Dict[int, Dict[int, Dict[str, Any]]]] = {}

    def put(pidx: int, cid: int, seq: int, rec: Dict[str, Any]) -> None:
        out.setdefault(cid, {}).setdefault(pidx, {}).setdefault(seq, rec)

    def put_rings(pidx: int, sent: Any) -> None:
        if not isinstance(sent, dict):
            return
        for cid_s, ent in (sent.get("comms") or {}).items():
            for drec in ent.get("last") or ():
                canon = str(drec.get("canon", ""))
                put(pidx, int(cid_s), int(drec.get("seq", -1)),
                    {"canon": canon,
                     "family": canon.split("|", 1)[0],
                     "epoch": int(drec.get("epoch", 0)),
                     "site": str(drec.get("site", "?"))})

    for d in dumps:
        pidx = int(d["meta"].get("pidx", 0))
        for s in d["spans"]:
            if s.get("layer") != "sentinel":
                continue
            parsed = parse_op(str(s.get("op", "")))
            if parsed is None:
                continue
            put(pidx, int(s.get("comm", -1)), int(s.get("peer", -1)),
                parsed)
        put_rings(pidx, d["meta"].get("sentinel"))
    if directory:
        for p in sorted(glob.glob(os.path.join(directory,
                                               "postmortem-*.json"))):
            with open(p) as f:
                pm = json.load(f)
            put_rings(int((pm.get("rank") or {}).get("pidx", 0)),
                      pm.get("sentinel"))
    return out


def _first_divergence(per_pid: Dict[int, Dict[int, Dict[str, Any]]]
                      ) -> Optional[Dict[str, Any]]:
    """The first contract divergence of one comm's per-proc signature
    sequences, or None. Procs are compared only over posting seqs
    every window can still see (ring journals keep the newest spans;
    a seq below a proc's window floor is wrap loss, not evidence)."""
    participants = sorted(per_pid)
    lo = {p: min(per_pid[p]) for p in participants}
    hi = {p: max(per_pid[p]) for p in participants}
    all_seqs = sorted({s for recs in per_pid.values() for s in recs})
    for seq in all_seqs:
        present = {p: per_pid[p][seq] for p in participants
                   if seq in per_pid[p]}
        # a proc whose whole window sits PAST seq only wrapped; a proc
        # whose window ENDS before seq never posted it — the missing
        # participant (the hung-run shape: survivors at seq k+1, the
        # desynced rank's chain stops at k)
        missing = [p for p in participants
                   if seq not in per_pid[p] and hi[p] < seq]
        gapped = [p for p in participants
                  if seq not in per_pid[p]
                  and lo[p] <= seq <= hi[p]]
        if missing:
            return {"kind": "missing_participant", "seq": seq,
                    "missing": missing,
                    "posted": {p: r for p, r in present.items()},
                    "last": {p: per_pid[p][hi[p]] for p in missing}}
        if gapped or len(present) < len(participants):
            continue  # journal gap / wrap: not comparable at this seq
        canons = {p: r["canon"] for p, r in present.items()}
        if len(set(canons.values())) > 1:
            # the expected signature is the MAJORITY canon (ties break
            # to the lowest pidx's), so the culprit is attributed even
            # when proc 0 itself is the desynced rank
            votes: Dict[str, int] = {}
            for p in participants:
                votes[canons[p]] = votes.get(canons[p], 0) + 1
            expected_canon = max(
                votes, key=lambda c: (votes[c], -min(
                    p for p in participants if canons[p] == c)))
            divergent = next(p for p in participants
                             if canons[p] != expected_canon)
            agree = [p for p in participants
                     if canons[p] == expected_canon]
            authority = agree[0]
            nxt_a = per_pid[authority].get(seq + 1)
            nxt_d = per_pid[divergent].get(seq + 1)
            swap = (nxt_a is not None and nxt_d is not None
                    and nxt_d["canon"] == canons[authority]
                    and nxt_a["canon"] == canons[divergent])
            return {"kind": ("posting_order_swap" if swap
                             else "signature_mismatch"),
                    "seq": seq, "divergent": divergent,
                    "agreeing": agree,
                    "expected": present[authority],
                    "actual": present[divergent]}
        epochs = {p: int(r.get("epoch", 0)) for p, r in present.items()}
        if len(set(epochs.values())) > 1:
            # transient skew is legal: FT notices propagate
            # asynchronously over lifelines, so a healthy rank can
            # post one round with a one-behind epoch view. Only a
            # skew that never converges over the remaining common
            # window is the stale-epoch-survivor signal.
            if _epochs_converge_later(per_pid, participants, seq):
                continue
            stale = min(epochs, key=lambda p: (epochs[p], p))
            fresh = max((p for p in participants if p != stale),
                        key=lambda p: (epochs[p], -p))
            return {"kind": "epoch_skew", "seq": seq,
                    "divergent": stale, "epochs": epochs,
                    "expected": present[fresh],
                    "actual": present[stale]}
    return None


def _epochs_converge_later(per_pid, participants, seq: int) -> bool:
    """True when some LATER seq present on every participant shows one
    agreed epoch — the skew at ``seq`` was notice-propagation lag, not
    a stale survivor."""
    later = sorted(s for s in per_pid[participants[0]] if s > seq)
    for s in later:
        if any(s not in per_pid[p] for p in participants):
            continue
        es = {int(per_pid[p][s].get("epoch", 0)) for p in participants}
        if len(es) == 1:
            return True
    return False


def contract_report(dumps: List[Dict[str, Any]],
                    directory: Optional[str] = None
                    ) -> Tuple[str, Dict[str, Any]]:
    """Align per-comm posting sequences across ranks and name the
    first divergence per comm — the post-hoc half of the collective
    contract sentinel (``obs_sentinel=1``). Works from finalize-time
    journals AND from watchdog postmortems of a hung run."""
    table = sentinel_records(dumps, directory=directory)
    lines = ["tpu-doctor collective-contract report"]
    comms: Dict[str, Any] = {}
    divergences = 0
    for cid in sorted(table):
        per_pid = table[cid]
        participants = sorted(per_pid)
        n_sigs = sum(len(v) for v in per_pid.values())
        if len(participants) < 2:
            comms[str(cid)] = {"participants": participants,
                               "signatures": n_sigs,
                               "divergence": None}
            continue
        div = _first_divergence(per_pid)
        comms[str(cid)] = {"participants": participants,
                           "signatures": n_sigs, "divergence": div}
        if div is None:
            lines.append(
                f"  comm {cid}: {n_sigs} signature(s) aligned across "
                f"procs {participants} — no divergence")
            continue
        divergences += 1
        seq = div["seq"]
        if div["kind"] == "missing_participant":

            def fmt_last(p):
                r = div["last"][p]
                return f"proc {p} last posted {r['canon']} from " \
                       f"{r['site']}"

            posted = next(iter(div["posted"].values()), None)
            lines.append(
                f"  comm {cid}: DESYNC at seq {seq} — "
                f"proc(s) {div['missing']} never posted it; "
                f"procs {sorted(div['posted'])} posted "
                f"{posted['canon'] if posted else '?'} from "
                f"{posted['site'] if posted else '?'}; "
                + "; ".join(fmt_last(p) for p in div["missing"]))
        elif div["kind"] == "epoch_skew":
            lines.append(
                f"  comm {cid}: DESYNC at seq {seq} — epoch skew: "
                f"proc {div['divergent']} posted at epoch "
                f"{div['epochs'][div['divergent']]} where others were "
                f"at {max(div['epochs'].values())} (stale-epoch "
                f"survivor?)")
        else:
            exp, act = div["expected"], div["actual"]
            tag = (" [posting-order swap: the two procs posted the "
                   "same ops in opposite order at seq "
                   f"{seq}/{seq + 1}]"
                   if div["kind"] == "posting_order_swap" else "")
            lines.append(
                f"  comm {cid}: DESYNC at seq {seq} — proc "
                f"{div['divergent']} posted {act['canon']} from "
                f"{act['site']} where proc(s) {div['agreeing']} "
                f"posted {exp['canon']} from {exp['site']}{tag}")
    if not table:
        lines.append("  no sentinel signature events found (run with "
                     "--mca obs_sentinel 1, plus obs_dump_dir or a "
                     "postmortem dir)")
    elif not divergences:
        lines.append("  all collective call streams agree")
    return "\n".join(lines), {"comms": comms,
                              "divergences": divergences}


# ---------------------------------------------------------------------------
# incident timeline (ft journal events: failures, revokes, recoveries)
# ---------------------------------------------------------------------------


def incident_timeline(dumps: List[Dict[str, Any]]
                      ) -> List[Dict[str, Any]]:
    """The fleet's fault-tolerance story from merged journals: every
    ``ft_failure`` / ``ft_revoke`` / ``ft_recovery`` span (PR 9
    records them; this renders them), clock-corrected and sorted.
    Field use per event kind follows the emitters: failure carries
    (peer=failed pidx, comm=epoch), revoke (comm=cid, peer=epoch),
    recovery (comm=new cid, peer=step, dt=duration)."""
    evs: List[Dict[str, Any]] = []
    for d in dumps:
        pidx = int(d["meta"].get("pidx", 0))
        for s in _corrected(d):
            if s["layer"] != "ft":
                continue
            op = s["op"]
            ev = {"ts": s["ts"], "pidx": pidx, "op": op}
            if op == "ft_failure":
                ev.update(failed_pidx=int(s.get("peer", -1)),
                          epoch=int(s.get("comm", 0)))
            elif op == "ft_revoke":
                ev.update(cid=int(s.get("comm", -1)),
                          epoch=int(s.get("peer", 0)))
            elif op == "ft_recovery":
                ev.update(new_cid=int(s.get("comm", -1)),
                          step=int(s.get("peer", -1)),
                          duration_s=float(s.get("dt", 0.0)))
            evs.append(ev)
    evs.sort(key=lambda e: e["ts"])
    return evs


def incident_lines(events: List[Dict[str, Any]]) -> List[str]:
    """Render the timeline for the report (times relative to the
    first incident)."""
    if not events:
        return []
    t0 = events[0]["ts"]
    lines = ["  incident timeline (ft events across merged journals):"]
    for e in events:
        rel = e["ts"] - t0
        if e["op"] == "ft_failure":
            what = (f"learned process {e['failed_pidx']} FAILED "
                    f"(epoch -> {e['epoch']})")
        elif e["op"] == "ft_revoke":
            what = f"revoked cid {e['cid']} (epoch {e['epoch']})"
        elif e["op"] == "ft_recovery":
            # the peer slot carries the step the FAILURE hit (the
            # rollback target is only in the ft_steps_lost pvar)
            what = (f"recovered in {e['duration_s']:.3f}s (resumed "
                    f"on cid {e['new_cid']}, failure at step "
                    f"{e['step']})")
        else:
            what = e["op"]
        lines.append(f"    +{rel:8.3f}s proc {e['pidx']}: {what}")
    return lines


def _coll_rounds(dumps: List[Dict[str, Any]]
                 ) -> Dict[Tuple[int, str], Dict[int, List[Dict]]]:
    """(comm, op) -> pidx -> that pid's coll-layer spans in call
    order. Only the 'coll' layer counts as a round marker (hier and
    driver both stamp it)."""
    table: Dict[Tuple[int, str], Dict[int, List[Dict]]] = {}
    for d in dumps:
        pidx = int(d["meta"].get("pidx", 0))
        for s in _corrected(d):
            if s["layer"] != "coll":
                continue
            table.setdefault((int(s.get("comm", -1)), s["op"]), {}) \
                .setdefault(pidx, []).append(s)
    return table


def skew_report(dumps: List[Dict[str, Any]],
                series: Optional[List[Dict[str, Any]]] = None
                ) -> Tuple[str, Dict[str, Any]]:
    """Critical-path + rank-skew report: for every collective round
    observed on EVERY process, name the last arriver (the rank the
    round waited for) and the arrival spread. When ``series`` (the
    per-process docs from :func:`load_series_dir` or
    :func:`fleet_to_series_docs`) is given, the critical path is
    annotated with each process's sampled collective rates."""
    by_pid_ranks = {
        int(d["meta"].get("pidx", 0)): (
            int(d["meta"].get("rank_offset", 0)),
            int(d["meta"].get("local_size", 0)))
        for d in dumps
    }

    def rank_span(pidx: int) -> str:
        off, n = by_pid_ranks.get(pidx, (0, 0))
        return f"ranks {off}..{off + n - 1}" if n else "ranks ?"

    rounds_out: List[Dict[str, Any]] = []
    crit_count: Dict[int, int] = {}
    lateness: Dict[int, float] = {}
    for (comm, op), per_pid in sorted(_coll_rounds(dumps).items()):
        if len(per_pid) < 2:
            continue  # a round needs >= 2 processes to have skew
        # align rounds from the TAIL: ring journals keep the NEWEST
        # spans, so when ranks wrapped or truncated differently the
        # common suffix is the set of rounds every dump still holds —
        # head alignment would pair different rounds and blame the
        # wrong rank (finalize-time dumps all end at the job's last
        # collective, making the suffix exact)
        n_rounds = min(len(v) for v in per_pid.values())
        tails = {p: v[-n_rounds:] for p, v in per_pid.items()}
        for k in range(n_rounds):
            arrivals = {p: tails[p][k]["ts"] for p in per_pid}
            slow = max(arrivals, key=arrivals.get)
            fast = min(arrivals, key=arrivals.get)
            spread = arrivals[slow] - arrivals[fast]
            crit_count[slow] = crit_count.get(slow, 0) + 1
            lateness[slow] = lateness.get(slow, 0.0) + spread
            rounds_out.append({
                "comm": comm, "op": op, "round": k,
                "slowest_pidx": slow, "spread_s": spread,
                "arrivals": {str(p): arrivals[p] for p in arrivals},
            })
    lines = ["tpu-doctor rank-skew / critical-path report",
             f"  processes: {len(dumps)}  collective rounds: "
             f"{len(rounds_out)}"]
    worst = sorted(rounds_out, key=lambda r: -r["spread_s"])[:10]
    if worst:
        lines.append("  worst rounds by arrival spread:")
        for r in worst:
            lines.append(
                f"    comm {r['comm']} {r['op']} round {r['round']}: "
                f"spread {r['spread_s'] * 1e3:.3f} ms, slowest proc "
                f"{r['slowest_pidx']} ({rank_span(r['slowest_pidx'])})")
    if crit_count:
        lines.append("  critical-path share (times slowest / total "
                     "lateness):")
        for p in sorted(crit_count, key=lambda p: -crit_count[p]):
            lines.append(
                f"    proc {p} ({rank_span(p)}): {crit_count[p]} "
                f"round(s), {lateness[p] * 1e3:.3f} ms accumulated")
    else:
        lines.append("  no multi-process collective rounds found "
                     "(was obs enabled on every rank?)")
    rates: Dict[int, Dict[str, float]] = {}
    if series:
        rates = series_rates(merge_series(series))
        if rates:
            lines.append("  sampled rates (continuous metrics plane):")
            for p in sorted(rates):
                r = rates[p]
                lines.append(
                    f"    proc {p} ({rank_span(p)}): "
                    f"{r['coll_ops_per_s']:.1f} coll/s, "
                    f"{r['coll_mb_per_s']:.2f} MB/s, "
                    f"busy {r['coll_busy_frac'] * 100:.1f}% over "
                    f"{r['window_s']:.1f}s sampled")
    incidents = incident_timeline(dumps)
    if incidents:
        lines.extend(incident_lines(incidents))
    return "\n".join(lines), {"rounds": rounds_out,
                              "critical_path": crit_count,
                              "sampled_rates": {str(p): r for p, r
                                                in rates.items()},
                              "incidents": incidents}
