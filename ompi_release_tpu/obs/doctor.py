"""Job-level trace assembly — merge per-rank journals, draw flows,
name the slow rank.

Input: one :func:`obs.export.rank_dump` document per controller
process (written at finalize via ``obs_dump_dir``, embedded in
postmortems, or fetched over the ``tpu_server`` journal RPC). Each
carries the rank identity and the OOB clock offset mapping that
process's ``perf_counter`` timebase into the HNP's.

Output:

- :func:`merge`: ONE Perfetto/Chrome ``trace_event`` document — pid =
  controller process (named with its world-rank span), tid = layer,
  timestamps clock-offset-corrected, and **flow arrows** joining every
  producer span ("s" side) to its consumer span ("t" side) by the
  deterministic flow ids the emit points stamped (p2p envelope seq,
  hier round/pair/index, window request token).
- :func:`skew_report`: per (comm, op) collective-round table — round k
  is the k-th occurrence of that op on each process (collective call
  order is identical everywhere, MPI's own rule), arrival spread is
  max-min corrected start, and the LAST arriver is the critical-path
  rank for that round.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional, Tuple

from .export import span_event


def load_dump(path: str) -> Dict[str, Any]:
    with open(path) as f:
        doc = json.load(f)
    if "spans" not in doc or "meta" not in doc:
        raise ValueError(f"{path}: not a rank journal dump "
                         "(missing meta/spans)")
    return doc


def load_dir(directory: str) -> List[Dict[str, Any]]:
    """Every ``journal-p*.json`` under ``directory``, plus — for ranks
    that never finalized (a hung rank killed mid-job leaves ONLY
    postmortems) — the journal tail of that rank's newest
    ``postmortem-*.json``."""
    dumps = []
    for p in sorted(glob.glob(os.path.join(directory, "journal-p*.json"))):
        dumps.append(load_dump(p))
    finalized = {int(d["meta"].get("pidx", 0)) for d in dumps}
    # one postmortem dump per missing rank: a hung rank routinely
    # writes SEVERAL postmortems (one per newly stalled wait, plus
    # operator SIGUSR1 pokes) whose journal tails overlap — merging
    # them all would render that rank's spans twice and desync the
    # skew report's tail alignment. Keep only the newest per pidx
    # (latest time_unix: the longest journal tail), and only for
    # ranks without a finalize-time journal (which supersedes tails).
    newest: Dict[int, Tuple[float, Dict[str, Any]]] = {}
    for p in sorted(glob.glob(os.path.join(directory,
                                           "postmortem-*.json"))):
        with open(p) as f:
            pm = json.load(f)
        tail = pm.get("journal_tail")
        if not isinstance(tail, list):
            continue
        rank = pm.get("rank", {})
        clock = pm.get("clock", {}) or {}
        pidx = int(rank.get("pidx", 0))
        if pidx in finalized:
            continue
        t = float(pm.get("time_unix", 0.0) or 0.0)
        prev = newest.get(pidx)
        if prev is not None and prev[0] >= t:
            continue
        newest[pidx] = (t, {
            "meta": {"pidx": pidx,
                     "rank_offset": rank.get("rank_offset", 0),
                     "local_size": rank.get("local_size", 0),
                     "pid": rank.get("pid"),
                     "clock_offset_s": clock.get("offset_s"),
                     "clock_rtt_s": clock.get("rtt_s")},
            "spans": tail,
        })
    dumps.extend(d for _, (_, d) in sorted(newest.items()))
    dumps.sort(key=lambda d: int(d["meta"].get("pidx", 0)))
    if not dumps:
        raise FileNotFoundError(
            f"no journal-p*.json or postmortem-*.json dumps under "
            f"{directory} (set --mca obs_dump_dir, or send SIGUSR1 to "
            "the ranks first)")
    return dumps


def _offset(meta: Dict[str, Any]) -> float:
    off = meta.get("clock_offset_s")
    return float(off) if off is not None else 0.0


def _corrected(dump: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Spans with a ``ts`` key in the merged (HNP) timebase, seconds.
    Cached on the dump: merge(), flow_pairs(), and _coll_rounds() all
    walk the same spans (a `tpu-doctor report` hits all three), and at
    job scale recomputing means millions of redundant dict copies. The
    spans are read-only downstream, so one shared list is safe."""
    cached = dump.get("_corrected_spans")
    if cached is None:
        off = _offset(dump["meta"])
        cached = []
        for s in dump["spans"]:
            c = dict(s)
            c["ts"] = float(s["t"]) + off
            cached.append(c)
        dump["_corrected_spans"] = cached
    return cached


def flow_pairs(dumps: List[Dict[str, Any]]
               ) -> List[Dict[str, Any]]:
    """Matched (producer, consumer) span pairs across dumps: one entry
    per flow id seen with both sides. Producer/consumer carry the
    owning pidx so callers can tell cross-process flows apart."""
    sides: Dict[int, Dict[str, List[Tuple[int, Dict]]]] = {}
    for d in dumps:
        pidx = int(d["meta"].get("pidx", 0))
        for s in _corrected(d):
            fl = s.get("flow")
            if not fl:
                continue
            side = "s" if s.get("fs") == "s" else "t"
            sides.setdefault(int(fl), {"s": [], "t": []})[side].append(
                (pidx, s))
    pairs = []
    for fl, ends in sorted(sides.items()):
        if not ends["s"] or not ends["t"]:
            continue
        # multiple spans per id would mean an id collision (64-bit FNV
        # over distinct identifiers: vanishingly rare) — pair in order
        for (sp, ss), (tp, ts) in zip(ends["s"], ends["t"]):
            pairs.append({"flow": fl, "src_pidx": sp, "dst_pidx": tp,
                          "src": ss, "dst": ts,
                          "cross_process": sp != tp,
                          "latency_s": ts["ts"] - (ss["ts"] + ss["dt"])})
    return pairs


def merge(dumps: List[Dict[str, Any]]) -> Dict[str, Any]:
    """One clock-aligned Perfetto trace for the whole job."""
    events: List[Dict[str, Any]] = []
    meta_events: List[Dict[str, Any]] = []
    tids: Dict[Tuple[int, str], int] = {}
    for d in sorted(dumps, key=lambda d: int(d["meta"].get("pidx", 0))):
        m = d["meta"]
        pidx = int(m.get("pidx", 0))
        off0 = int(m.get("rank_offset", 0))
        n = int(m.get("local_size", 0))
        label = (f"proc {pidx} (world ranks {off0}..{off0 + n - 1})"
                 if n else f"proc {pidx}")
        meta_events.append({"name": "process_name", "ph": "M",
                            "pid": pidx, "args": {"name": label}})
        for s in _corrected(d):
            key = (pidx, s["layer"])
            tid = tids.get(key)
            if tid is None:
                tid = tids[key] = len(tids) + 1
                meta_events.append({
                    "name": "thread_name", "ph": "M", "pid": pidx,
                    "tid": tid, "args": {"name": s["layer"]},
                })
            events.append(span_event(s, pid=pidx, tid=tid,
                                     ts_s=s["ts"]))
    flows = flow_pairs(dumps)
    for p in flows:
        src, dst = p["src"], p["dst"]
        src_tid = tids.get((p["src_pidx"], src["layer"]), 1)
        dst_tid = tids.get((p["dst_pidx"], dst["layer"]), 1)
        fid = str(p["flow"])
        events.append({
            "name": src["op"], "cat": "flow", "ph": "s", "id": fid,
            "pid": p["src_pidx"], "tid": src_tid,
            "ts": (src["ts"] + src["dt"]) * 1e6,
        })
        events.append({
            "name": src["op"], "cat": "flow", "ph": "f", "bp": "e",
            "id": fid, "pid": p["dst_pidx"], "tid": dst_tid,
            "ts": (dst["ts"] + dst["dt"]) * 1e6,
        })
    doc = {"traceEvents": meta_events + events, "displayTimeUnit": "ms"}
    doc["otherData"] = {
        "processes": len(dumps),
        "spans": sum(len(d["spans"]) for d in dumps),
        "flows": len(flows),
        "cross_process_flows": sum(1 for p in flows
                                   if p["cross_process"]),
    }
    return doc



# ---------------------------------------------------------------------------
# continuous series (obs/sampler.py rings) — load, clock-correct, merge
# ---------------------------------------------------------------------------


def load_series_dump(path: str) -> Dict[str, Any]:
    """One ``series-p*.jsonl`` file (meta header line + one point per
    line, ``obs.export.dump_series_jsonl``) back into the
    ``{"meta": ..., "points": [...]}`` document shape."""
    meta: Dict[str, Any] = {}
    points: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            doc = json.loads(line)
            if "meta" in doc and "t" not in doc:
                meta = doc["meta"]
            else:
                points.append(doc)
    if not meta and not points:
        raise ValueError(f"{path}: empty series dump")
    return {"meta": meta, "points": points}


def load_series_dir(directory: str) -> List[Dict[str, Any]]:
    """Every ``series-p*.jsonl`` under ``directory``, ordered by
    pidx. Missing files are not an error here — callers that can
    proceed without series (the report annotation) check for []."""
    docs = []
    for p in sorted(glob.glob(os.path.join(directory,
                                           "series-p*.jsonl"))):
        docs.append(load_series_dump(p))
    docs.sort(key=lambda d: int(d["meta"].get("pidx", 0)))
    return docs


def merge_series(docs: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """One clock-corrected fleet series: every point gains ``ts``
    (sample time mapped into the HNP timebase via the dump's clock
    offset — the same correction journals get) and ``pidx``, merged
    across processes and sorted by corrected time."""
    merged: List[Dict[str, Any]] = []
    for d in docs:
        off = _offset(d["meta"])
        pidx = int(d["meta"].get("pidx", 0))
        for p in d["points"]:
            c = dict(p)
            c["ts"] = float(p["t"]) + off
            c["pidx"] = pidx
            merged.append(c)
    merged.sort(key=lambda p: p["ts"])
    return merged


def fleet_to_series_docs(fleet: Dict[str, Any]) -> List[Dict[str, Any]]:
    """A live HNP fleet document (``HnpCoordinator.fleet_series``)
    reshaped into the same per-process doc list the offline loaders
    produce, so merge_series/tpu_top render both identically."""
    docs = []
    for pidx_s, ent in sorted((fleet.get("procs") or {}).items(),
                              key=lambda kv: int(kv[0])):
        meta = dict(ent.get("meta") or {})
        meta.update(pidx=int(pidx_s),
                    clock_offset_s=ent.get("clock_offset_s"),
                    push_age_s=ent.get("push_age_s"))
        docs.append({"meta": meta,
                     "points": list(ent.get("points") or ())})
    return docs


def series_rates(merged: List[Dict[str, Any]]
                 ) -> Dict[int, Dict[str, float]]:
    """Per-process sampled collective rates over the merged window:
    pidx -> {"window_s", "coll_ops_per_s", "coll_mb_per_s",
    "coll_busy_frac"} folded from the per-cid ``coll_*`` delta points.
    The doctor report annotates its critical path with these — a rank
    that is both the chronic last-arriver AND the lowest-rate rank is
    compute-bound, not network-starved."""
    by_pidx: Dict[int, Dict[str, float]] = {}
    spans: Dict[int, List[float]] = {}
    for p in merged:
        pidx = int(p.get("pidx", 0))
        name = p.get("name")
        if name not in ("coll_ops", "coll_bytes", "coll_seconds"):
            continue
        acc = by_pidx.setdefault(
            pidx, {"coll_ops": 0.0, "coll_bytes": 0.0,
                   "coll_seconds": 0.0})
        try:
            acc[name] += float(p.get("v", 0.0))
        except (TypeError, ValueError):
            continue
        spans.setdefault(pidx, []).append(float(p["ts"]))
    out: Dict[int, Dict[str, float]] = {}
    for pidx, acc in sorted(by_pidx.items()):
        ts = sorted(set(spans.get(pidx) or ()))
        if len(ts) < 2:
            # a single tick has no measurable window — omitting the
            # proc beats reporting a made-up (and wildly inflated) rate
            continue
        window = max(ts[-1] - ts[0], 1e-9)
        out[pidx] = {
            "window_s": window,
            "coll_ops_per_s": acc["coll_ops"] / window,
            "coll_mb_per_s": acc["coll_bytes"] / window / 1e6,
            "coll_busy_frac": min(acc["coll_seconds"] / window, 1.0),
        }
    return out


def _coll_rounds(dumps: List[Dict[str, Any]]
                 ) -> Dict[Tuple[int, str], Dict[int, List[Dict]]]:
    """(comm, op) -> pidx -> that pid's coll-layer spans in call
    order. Only the 'coll' layer counts as a round marker (hier and
    driver both stamp it)."""
    table: Dict[Tuple[int, str], Dict[int, List[Dict]]] = {}
    for d in dumps:
        pidx = int(d["meta"].get("pidx", 0))
        for s in _corrected(d):
            if s["layer"] != "coll":
                continue
            table.setdefault((int(s.get("comm", -1)), s["op"]), {}) \
                .setdefault(pidx, []).append(s)
    return table


def skew_report(dumps: List[Dict[str, Any]],
                series: Optional[List[Dict[str, Any]]] = None
                ) -> Tuple[str, Dict[str, Any]]:
    """Critical-path + rank-skew report: for every collective round
    observed on EVERY process, name the last arriver (the rank the
    round waited for) and the arrival spread. When ``series`` (the
    per-process docs from :func:`load_series_dir` or
    :func:`fleet_to_series_docs`) is given, the critical path is
    annotated with each process's sampled collective rates."""
    by_pid_ranks = {
        int(d["meta"].get("pidx", 0)): (
            int(d["meta"].get("rank_offset", 0)),
            int(d["meta"].get("local_size", 0)))
        for d in dumps
    }

    def rank_span(pidx: int) -> str:
        off, n = by_pid_ranks.get(pidx, (0, 0))
        return f"ranks {off}..{off + n - 1}" if n else "ranks ?"

    rounds_out: List[Dict[str, Any]] = []
    crit_count: Dict[int, int] = {}
    lateness: Dict[int, float] = {}
    for (comm, op), per_pid in sorted(_coll_rounds(dumps).items()):
        if len(per_pid) < 2:
            continue  # a round needs >= 2 processes to have skew
        # align rounds from the TAIL: ring journals keep the NEWEST
        # spans, so when ranks wrapped or truncated differently the
        # common suffix is the set of rounds every dump still holds —
        # head alignment would pair different rounds and blame the
        # wrong rank (finalize-time dumps all end at the job's last
        # collective, making the suffix exact)
        n_rounds = min(len(v) for v in per_pid.values())
        tails = {p: v[-n_rounds:] for p, v in per_pid.items()}
        for k in range(n_rounds):
            arrivals = {p: tails[p][k]["ts"] for p in per_pid}
            slow = max(arrivals, key=arrivals.get)
            fast = min(arrivals, key=arrivals.get)
            spread = arrivals[slow] - arrivals[fast]
            crit_count[slow] = crit_count.get(slow, 0) + 1
            lateness[slow] = lateness.get(slow, 0.0) + spread
            rounds_out.append({
                "comm": comm, "op": op, "round": k,
                "slowest_pidx": slow, "spread_s": spread,
                "arrivals": {str(p): arrivals[p] for p in arrivals},
            })
    lines = ["tpu-doctor rank-skew / critical-path report",
             f"  processes: {len(dumps)}  collective rounds: "
             f"{len(rounds_out)}"]
    worst = sorted(rounds_out, key=lambda r: -r["spread_s"])[:10]
    if worst:
        lines.append("  worst rounds by arrival spread:")
        for r in worst:
            lines.append(
                f"    comm {r['comm']} {r['op']} round {r['round']}: "
                f"spread {r['spread_s'] * 1e3:.3f} ms, slowest proc "
                f"{r['slowest_pidx']} ({rank_span(r['slowest_pidx'])})")
    if crit_count:
        lines.append("  critical-path share (times slowest / total "
                     "lateness):")
        for p in sorted(crit_count, key=lambda p: -crit_count[p]):
            lines.append(
                f"    proc {p} ({rank_span(p)}): {crit_count[p]} "
                f"round(s), {lateness[p] * 1e3:.3f} ms accumulated")
    else:
        lines.append("  no multi-process collective rounds found "
                     "(was obs enabled on every rank?)")
    rates: Dict[int, Dict[str, float]] = {}
    if series:
        rates = series_rates(merge_series(series))
        if rates:
            lines.append("  sampled rates (continuous metrics plane):")
            for p in sorted(rates):
                r = rates[p]
                lines.append(
                    f"    proc {p} ({rank_span(p)}): "
                    f"{r['coll_ops_per_s']:.1f} coll/s, "
                    f"{r['coll_mb_per_s']:.2f} MB/s, "
                    f"busy {r['coll_busy_frac'] * 100:.1f}% over "
                    f"{r['window_s']:.1f}s sampled")
    return "\n".join(lines), {"rounds": rounds_out,
                              "critical_path": crit_count,
                              "sampled_rates": {str(p): r for p, r
                                                in rates.items()}}
