"""Collective contract sentinel — cross-rank call-signature hashing.

The obs plane answers "where is time going" (journal + skew pvars,
PR 1/4) and "who is stuck" (watchdog + doctor, PR 4/6); this module
makes a third defect class visible: cross-rank collective *desyncs* —
one rank posts ``bcast`` where the others posted ``allreduce``,
mismatched op/dtype/count/root, a posting-order swap, a stale-epoch
survivor calling into a rebuilt world — which otherwise surface only
as a watchdog stall or silently wrong numbers. The discipline is the
MUST-style collective-consistency check built on the reference's own
introspection pattern (PERUSE call-stream events + MPI_T, PAPER.md §1):
the library observes its own call stream.

Every collective entry (blocking, i-family, persistent ``start()``,
serialized collective IO) computes a compact **call signature**::

    (cid, per-comm posting seq, family, reduction op, dtype,
     per-rank count, root)  +  job epoch  +  call-site fingerprint

The signature folds into a per-communicator **rolling hash chain**
(FNV-1a, process-independent — the same fold :func:`obs.journal
.flow_id` uses), so two ranks that executed the same call stream hold
the same chain value, and the FIRST divergence pins the desync to one
``(cid, seq)``. The call site (user-frame ``file:line``) is forensics
only — it is *excluded* from the compared hash, because different
ranks may legitimately reach one collective from different code paths.

Two consumption modes, selected by the ``obs_sentinel`` cvar:

``obs_sentinel=1`` (post-hoc)
    Signatures are recorded as journal events (layer ``"sentinel"``)
    and kept in a per-comm last-N ring that rides every watchdog
    postmortem. ``tpu-doctor contracts DIR`` aligns the per-comm
    posting sequences across merged rank journals (finalize dumps OR
    postmortems of a hung run) and names the first divergence:
    missing participant, op/dtype/count mismatch, posting-order swap,
    epoch skew — with both call sites.

``obs_sentinel=2`` (inline)
    Additionally, the 16-byte signature digest (sig hash + site hash)
    piggybacks on the first wire/ctl frame of each spanning round
    (:meth:`~..runtime.wire.WireRouter.sentinel_exchange`): every
    member process exchanges its signature BEFORE the round's payload
    traffic, and a divergence raises the typed ``ERR_COLL_MISMATCH``
    within that round — naming the first divergent process, the
    expected-vs-actual signature fields, and both call sites —
    instead of hanging into a watchdog timeout.

Cost discipline is the PR-1 contract, enforced by
``tests/test_obs_gating.py``'s AST scan: every emit site here and at
the entry points (``coll/nbc.py``, ``comm/communicator.py``) is gated
on one module attribute (``sentinel.enabled`` / ``_obs.enabled``), so
``obs_sentinel=0`` costs a single attribute check per collective.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time as _time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from .. import obs as _obs
from ..mca import pvar as _pvar
from ..mca import var as _var
from ..utils.errors import ErrorCode, MPIError
from .journal import flow_id

#: THE gate: entry points check this and do nothing else when False.
#: Recomputed by refresh() on obs enable/disable and cvar changes.
enabled: bool = False

_mode: int = 0
_lock = threading.Lock()

#: families whose second positional argument is a reduction Op
_REDUCING = frozenset((
    "allreduce", "reduce", "reduce_scatter_block", "scan", "exscan",
))
#: family -> index (within the comm-stripped args) of the root operand
_ROOT_ARG = {"bcast": 1, "gather": 1, "scatter": 1, "reduce": 2,
             "gatherv": 1, "scatterv": 2}

#: wire frame prefix of an inline signature exchange (ctl channel)
SIG_MAGIC = b"SIG1"

DEFAULT_RING = 16

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_ops_hashed = _pvar.counter(
    "sentinel_ops_hashed",
    "collective call signatures folded into per-comm hash chains by "
    "the contract sentinel (obs_sentinel >= 1)",
)
_mismatches = _pvar.counter(
    "sentinel_mismatches",
    "cross-rank collective contract violations detected (inline "
    "signature exchanges that raised ERR_COLL_MISMATCH)",
)


def register_vars() -> None:
    _var.register(
        "obs_sentinel", "int", 0,
        "Collective contract sentinel mode: 0 = off (one attribute "
        "check per collective), 1 = post-hoc — record call signatures "
        "as journal events for tpu-doctor contracts, 2 = inline — "
        "additionally exchange the signature on the comm's ctl "
        "channel before each spanning round and raise "
        "ERR_COLL_MISMATCH on divergence (needs the obs plane "
        "enabled)",
    )
    _var.register(
        "obs_sentinel_ring", "int", DEFAULT_RING,
        "Last-N call signatures kept per communicator for watchdog "
        "postmortems (the tpu-doctor contracts input when the "
        "journal ring has wrapped past them)",
    )


register_vars()  # idempotent; cvars must exist before any refresh()


class _Chain:
    """Per-communicator sentinel state: the next posting seq, the
    rolling hash chain, and the last-N signature ring."""

    __slots__ = ("seq", "chain", "ring")

    def __init__(self, ring: int) -> None:
        self.seq = 0
        self.chain = 0
        self.ring: deque = deque(maxlen=max(1, int(ring)))


_chains: Dict[int, _Chain] = {}


def refresh(obs_enabled: Optional[bool] = None) -> None:
    """Recompute the gate from the obs flag + the obs_sentinel cvar."""
    global enabled, _mode
    if obs_enabled is None:
        from . import is_enabled

        obs_enabled = is_enabled()
    _mode = int(_var.get("obs_sentinel", 0) or 0)
    enabled = bool(obs_enabled and _mode > 0)


def mode() -> int:
    """The active sentinel mode (0 when the gate is off)."""
    return _mode if enabled else 0


# ---------------------------------------------------------------------------
# signature derivation
# ---------------------------------------------------------------------------


def _call_site() -> str:
    """User-frame ``file:line`` fingerprint: the first stack frame
    outside this package (basename only — compact, and the postmortem
    already carries full paths in its thread stacks)."""
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.startswith(_PKG_DIR) and not fn.startswith("<"):
            return f"{os.path.basename(fn)}:{f.f_lineno}"
        f = f.f_back
    return "<internal>"


def _describe(comm, family: str, args: Tuple, kw: Dict
              ) -> Tuple[str, str, int, int]:
    """Best-effort (op, dtype, per-rank count, root) from a collective
    entry's arguments. The leading local-rank axis of driver-mode
    buffers is STRIPPED from the count: each controller process passes
    arrays for its own rank span, so the cross-rank invariant is the
    per-rank payload, not the stacked buffer. Ragged v-variant buffer
    lists hash as count -1 (their per-rank counts differ by design)."""
    if args and args[0] is comm:
        args = args[1:]
    x = args[0] if args else None
    op_name = "-"
    if family in _REDUCING:
        op = kw.get("op") if kw else None
        if op is None and len(args) > 1:
            op = args[1]
        op_name = str(getattr(op, "name", op if op is not None else "-"))
    elif family == "reduce_scatter":
        op = (kw.get("op") if kw else None) or \
            (args[2] if len(args) > 2 else None)
        op_name = str(getattr(op, "name", op if op is not None else "-"))
    root = -1
    ri = _ROOT_ARG.get(family)
    if ri is not None:
        if kw and "root" in kw:
            root = int(kw["root"])
        elif len(args) > ri:
            try:
                root = int(args[ri])
            except (TypeError, ValueError):
                root = -1
    dtype, count = "-", 0
    if x is not None:
        dt = getattr(x, "dtype", None)
        if dt is not None:
            dtype = str(dt)
            shape = tuple(getattr(x, "shape", ()))
            per_rank = shape[1:] if len(shape) >= 1 else shape
            count = 1
            for s in per_rank:
                count *= int(s)
        elif isinstance(x, (list, tuple)):
            count = -1  # ragged per-rank buffers (v-variants)
            if x:
                dt0 = getattr(x[0], "dtype", None)
                if dt0 is not None:
                    dtype = str(dt0)
    return op_name, dtype, count, root


class CallSig:
    """One collective entry's signature. ``sig_hash`` covers the
    cross-rank-invariant fields (cid, seq, canon); ``site_hash``
    covers the call site — together the 16-byte wire digest. The
    chain value is the per-comm rolling fold AFTER this call."""

    __slots__ = ("cid", "seq", "family", "canon", "epoch", "site",
                 "sig_hash", "site_hash", "chain")

    def __init__(self, cid: int, seq: int, family: str, canon: str,
                 epoch: int, site: str, chain_prev: int) -> None:
        self.cid = cid
        self.seq = seq
        self.family = family
        self.canon = canon
        self.epoch = epoch
        self.site = site
        # the cid stays OUT of the hash: it is already the chain's
        # key, and excluding it makes two identical call streams on
        # different comms (the selftest's determinism witness) fold
        # to the same chain value
        self.sig_hash = flow_id("sig", seq, canon)
        self.site_hash = flow_id(site)
        self.chain = flow_id(chain_prev, self.sig_hash)

    def digest(self) -> bytes:
        """The 16-byte signature: sig hash + site hash, big-endian."""
        return (self.sig_hash.to_bytes(8, "big")
                + self.site_hash.to_bytes(8, "big"))

    def descriptor(self) -> Dict[str, Any]:
        """JSON-able form: the inline wire payload, the postmortem
        ring entry, and the doctor's alignment record share it."""
        return {"seq": self.seq, "canon": self.canon,
                "epoch": self.epoch, "site": self.site,
                "sig": self.sig_hash}


def encode_op(canon: str, epoch: int, site: str) -> str:
    """The journal-event op-string form of one signature (the Span
    schema has no free-form dict, so the signature fields ride the op
    string; cid/seq ride the span's comm/peer slots)."""
    return f"{canon}|e{epoch}|{site}"


def parse_op(op: str) -> Optional[Dict[str, Any]]:
    """Invert :func:`encode_op`; None when ``op`` is not a sentinel
    signature event (THE parser — doctor and tests share it)."""
    parts = op.split("|")
    if len(parts) != 7 or not parts[5].startswith("e"):
        return None
    try:
        epoch = int(parts[5][1:])
    except ValueError:
        return None
    return {"canon": "|".join(parts[:5]), "family": parts[0],
            "epoch": epoch, "site": parts[6]}


def make_canon(family: str, op_name: str, dtype: str, count: int,
               root: int) -> str:
    """Canonical cross-rank-invariant signature text (compared
    verbatim by the doctor; hashed into ``sig_hash`` inline)."""
    return f"{family}|{op_name}|{dtype}|{count}|{root}"


# ---------------------------------------------------------------------------
# recording (the entry points' API)
# ---------------------------------------------------------------------------


def record_sig(cid: int, family: str, op_name: str = "-",
               dtype: str = "-", count: int = 0, root: int = -1,
               epoch: int = 0, site: Optional[str] = None
               ) -> Optional[CallSig]:
    """Fold one signature into ``cid``'s chain (the low-level core of
    :func:`note`, driven directly by the selftest). Returns None when
    the gate is off."""
    if not enabled:
        return None
    if site is None:
        site = _call_site()
    canon = make_canon(family, op_name, dtype, count, root)
    with _lock:
        ch = _chains.get(cid)
        if ch is None:
            ch = _chains[cid] = _Chain(
                int(_var.get("obs_sentinel_ring", DEFAULT_RING)
                    or DEFAULT_RING))
        sig = CallSig(cid, ch.seq, family, canon, epoch, site, ch.chain)
        ch.seq = sig.seq + 1
        ch.chain = sig.chain
        ch.ring.append(sig.descriptor())
    _ops_hashed.add()
    if _obs.enabled:
        _obs.record(encode_op(canon, epoch, site), "sentinel",
                    _time.perf_counter(), 0.0, nbytes=max(count, 0),
                    peer=sig.seq, comm_id=cid, flow=sig.chain,
                    flow_side="g")
    return sig


def note(comm, family: str, args: Tuple = (),
         kw: Optional[Dict] = None) -> Optional[CallSig]:
    """Record one collective entry on ``comm``. Callers gate on
    ``sentinel.enabled`` themselves (the one-attr-check contract).
    Skipped (returns None) for:

    - runtime-internal comms (negative cid — e.g. the hier module's
      process-local shadow, whose cids are NOT SPMD-agreed);
    - a collective nested inside a running schedule on the SAME comm
      (two-phase IO's closing barrier): it is part of the outer op's
      schedule, and chaining it would desync the posting seq between
      a proc whose progress thread ran the outer op early and one
      that ran it at wait().
    """
    if not enabled:
        return None
    cid = int(comm.cid)
    if cid < 0:
        return None
    if comm.spans_processes:
        from ..runtime import progress as _progress

        cur = _progress.engine().executing()
        if cur is not None and cur.key == ("comm", cid):
            return None
    try:
        from ..ft import ulfm as _ulfm

        epoch = int(_ulfm.state().epoch)
    except Exception:
        epoch = 0
    op_name, dtype, count, root = _describe(comm, family,
                                            tuple(args), kw or {})
    return record_sig(cid, family, op_name, dtype, count, root,
                      epoch=epoch, site=_call_site())


# ---------------------------------------------------------------------------
# inline verification (obs_sentinel=2, spanning comms)
# ---------------------------------------------------------------------------


class InlineFrameTemplate:
    """FrameTemplate-style precomposed inline-check payload (the ctl
    frame :func:`inline_check` exchanges): the constant descriptor
    fragments — canonical signature text and call site — are
    JSON-encoded ONCE (at plan time, cached on the frozen plan
    state), and :meth:`render` splices only the per-fire fields
    (digest, posting seq, epoch, sig hash). The bytes are IDENTICAL
    to the interpreted ``digest + json.dumps(descriptor())`` payload,
    so receivers need no changes and templated/untemplated ranks
    interoperate — this is what lets sentinel level 2 ride the
    compiled planned path instead of forcing interpretation."""

    __slots__ = ("key", "_pre_seq", "_pre_epoch", "_pre_sig")

    def __init__(self, canon: str, site: str) -> None:
        self.key = (canon, site)
        self._pre_seq = b'{"seq": '
        self._pre_epoch = (', "canon": %s, "epoch": '
                           % json.dumps(canon)).encode()
        self._pre_sig = (', "site": %s, "sig": '
                         % json.dumps(site)).encode()

    def render(self, sig: CallSig) -> bytes:
        # json.dumps of an int IS str(int), and descriptor() insertion
        # order is (seq, canon, epoch, site, sig) — splicing here is
        # byte-for-byte the interpreted payload
        return (sig.digest() + self._pre_seq + str(sig.seq).encode()
                + self._pre_epoch + str(sig.epoch).encode()
                + self._pre_sig + str(sig.sig_hash).encode() + b"}")


def wrap_inline(comm, sig: Optional[CallSig], fn,
                template: Optional[InlineFrameTemplate] = None):
    """Wrap a spanning round's schedule fn so the signature exchange
    runs at EXECUTION start — strictly before the round's first
    payload frame, in the comm's posting order on every process. A
    no-op (returns ``fn``) outside inline mode. ``template``: a
    plan-cached :class:`InlineFrameTemplate` so the steady state
    skips per-fire JSON encoding."""
    if sig is None or _mode < 2 or not comm.spans_processes:
        return fn

    def checked(*a, **k):
        inline_check(comm, sig, template)
        return fn(*a, **k)

    return checked


def _rank_of(comm, pidx: int) -> int:
    """First comm rank owned by process ``pidx`` (error naming)."""
    try:
        from ..runtime.wire import proc_topology

        members = proc_topology(comm).members_of.get(pidx) or ()
        return int(members[0]) if members else -1
    except Exception:
        return -1


def inline_check(comm, sig: CallSig,
                 template: Optional[InlineFrameTemplate] = None
                 ) -> None:
    """Exchange ``sig`` with every member process of ``comm`` and
    raise ``ERR_COLL_MISMATCH`` naming the first divergent process
    when any peer's signature differs. Site hashes are excluded from
    the comparison (ranks may legitimately reach one collective from
    different code paths); posting seq and the canonical fields are
    not."""
    router = getattr(comm.runtime, "wire", None)
    if router is None:
        return
    payload = (template.render(sig) if template is not None
               else sig.digest() + json.dumps(sig.descriptor()).encode())
    frames = router.sentinel_exchange(comm, payload)
    for p in sorted(frames):
        raw = frames[p]
        try:
            theirs = json.loads(raw[16:])
        except ValueError:
            theirs = {}
        if (raw[:8] == sig.digest()[:8]
                and int(theirs.get("seq", -1)) == sig.seq):
            continue
        _mismatches.add()
        if _obs.enabled:
            _obs.record("sentinel_mismatch", "sentinel",
                        _time.perf_counter(), 0.0, peer=p,
                        comm_id=sig.cid)
        mine = sig.descriptor()
        raise MPIError(
            ErrorCode.ERR_COLL_MISMATCH,
            f"collective contract violation on {comm.name} (cid "
            f"{sig.cid}): process {p} (comm rank "
            f"{_rank_of(comm, p)}) posted "
            f"{theirs.get('canon', '<unparseable>')} at seq "
            f"{theirs.get('seq', '?')} from "
            f"{theirs.get('site', '?')} where this process posted "
            f"{mine['canon']} at seq {mine['seq']} from "
            f"{mine['site']} (epochs: theirs "
            f"{theirs.get('epoch', '?')}, ours {mine['epoch']})",
        )


# ---------------------------------------------------------------------------
# introspection (postmortems, finalize dumps, selftest)
# ---------------------------------------------------------------------------


def clear_chain(cid: int) -> None:
    """Drop ``cid``'s chain state. Called when a communicator is
    freed (the contract story is closed — journal events persist for
    post-hoc alignment, and chains must not accumulate over comm
    churn) and on the explicit-cid rebuild path's slot eviction: a
    survivor's leftover chain resuming at seq > 0 against a
    restarted-from-zero replacement's fresh seq 0 would be a FALSE
    mismatch on a healthy rebuilt world. Cheap when the sentinel
    never ran (one falsy dict check, no lock)."""
    if not _chains:
        return
    with _lock:
        _chains.pop(cid, None)


def clear_band(lo: int, hi: int) -> None:
    """Drop every chain with ``lo <= cid < hi`` — the tenant-eviction
    / tenant-slot-reuse sweep (service plane): a dead tenant's
    leftover posting seqs must not false-mismatch the NEXT tenant
    admitted into the same cid band. Cheap when the sentinel never
    ran (one falsy dict check, no lock)."""
    if not _chains:
        return
    with _lock:
        for cid in [c for c in _chains if lo <= c < hi]:
            _chains.pop(cid, None)


def chain_of(cid: int) -> int:
    """Current rolling chain value for ``cid`` (0 = no calls seen)."""
    with _lock:
        ch = _chains.get(cid)
        return ch.chain if ch is not None else 0


def chains_snapshot() -> Dict[str, Any]:
    """Per-comm sentinel state for the watchdog postmortem and the
    finalize dump's meta: mode, and per cid the next posting seq, the
    chain value, and the last-N signature descriptors (the doctor's
    alignment input when the journal ring wrapped past them)."""
    with _lock:
        comms = {
            str(cid): {"next_seq": ch.seq,
                       "chain": f"{ch.chain:016x}",
                       "last": list(ch.ring)}
            for cid, ch in _chains.items()
        }
    return {"mode": _mode, "comms": comms}


def _reset_for_tests() -> None:
    global enabled, _mode
    with _lock:
        _chains.clear()
    enabled = False
    _mode = 0


# every watchdog postmortem carries the per-comm signature rings, so a
# hung mismatched run's dumps feed `tpu-doctor contracts` even when
# the journal tail wrapped past the divergent round
from . import watchdog as _watchdog  # noqa: E402

_watchdog.add_contributor("sentinel", chains_snapshot)
