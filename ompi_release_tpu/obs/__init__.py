"""Always-on observability plane — journal, skew metrics, exporters.

The reference instruments itself at every layer (MPI_T pvars, PERUSE
events, PMPI interposition, orte-top sampling); this package is the
TPU-native unification: emit points *inside* the framework (coll
driver, vcoll edge, pml, btl, request wait, sharded IO) write spans
into one ring-buffer journal (:mod:`obs.journal`) and bump per-op /
per-BTL histogram, aggregate, and rank-skew pvars
(:mod:`obs.skew`), all readable through the existing MPI_T handles
(``mca/mpit.py``) and exportable as Chrome/Perfetto ``trace_event``
JSON, JSONL, or Prometheus text (:mod:`obs.export`).

Switching on (any one of):

  - env var ``OMPI_TPU_OBS=1`` (read at import)
  - MCA cvar ``obs_enable`` (``OMPITPU_MCA_obs_enable=1``)
  - :func:`enable` at runtime

The hot-path cost when off is a single module-attribute check
(``obs.enabled``) per instrumented call site — no locks, no clock
reads, no allocation. ``python -m ompi_release_tpu.obs --selftest``
exercises every pvar class and exporter round-trip, device-free.
"""

from __future__ import annotations

import os

from ..mca import pvar as _pvar
from ..mca import var as _var
from . import journal as journal_mod
from .journal import Journal, Span, flow_id  # noqa: F401  (public API)

#: THE hot-path gate: emit points check ``obs.enabled`` and do nothing
#: else when False. One module attribute, mutated only by
#: enable()/disable().
enabled: bool = False

#: process-global journal (identity is stable across enable/resize)
journal = journal_mod.JOURNAL


def register_vars() -> None:
    _var.register(
        "obs_enable", "bool", False,
        "Enable the observability plane (event journal + per-op "
        "histogram/skew pvars) at import — same effect as "
        "OMPI_TPU_OBS=1 or obs.enable()",
    )
    _var.register(
        "obs_journal_size", "size", journal_mod.DEFAULT_SIZE,
        "Ring-buffer event-journal capacity in spans (oldest spans are "
        "overwritten); applied when obs.enable() runs",
    )


register_vars()  # idempotent; cvars must exist before any enable()

_pvar.PVARS.register(
    "obs_journal_events", _pvar.PvarClass.COUNTER,
    "spans ever recorded in the obs event journal",
    getter=lambda: journal.total_recorded,
)
_pvar.PVARS.register(
    "obs_journal_dropped", _pvar.PvarClass.COUNTER,
    "journal spans lost to ring wrap (raise obs_journal_size)",
    getter=lambda: journal.dropped,
)


#: cross-controller clock alignment (runtime/coordinator.py ping-pong
#: estimator): offset_s maps THIS process's perf_counter timebase into
#: the HNP's; tpu-doctor subtracts per-rank offsets to merge journals
#: onto one timeline. None = never estimated (singleton, or no HNP).
_clock_state: dict = {"offset_s": None, "rtt_s": None, "source": None}


def rank_identity() -> dict:
    """Best-effort process identity (pid, pidx, world-rank span) — THE
    shared derivation behind both the postmortem's ``rank`` block and
    the finalize dump's ``meta``, so the doctor's two input formats
    can never drift. Never raises (dumps run from signal handlers and
    half-initialized runtimes)."""
    import os as _os

    ident = {"pid": _os.getpid(), "pidx": 0, "rank_offset": 0,
             "local_size": 0}
    try:
        from ..runtime.runtime import Runtime

        rt = Runtime._instance
        if rt is not None and rt.bootstrap:
            ident["pidx"] = int(rt.bootstrap.get("process_index", 0))
            ident["rank_offset"] = int(rt.local_rank_offset)
            ident["local_size"] = int(
                rt.local_size or len(rt.endpoints or ())
            )
    except Exception:
        pass
    return ident


def set_clock(offset_s: float, rtt_s: float, source: str = "oob") -> None:
    _clock_state.update(offset_s=offset_s, rtt_s=rtt_s, source=source)


def clock_offset():
    return _clock_state["offset_s"]


def enable(size: int = None) -> None:
    """Turn the plane on; the journal takes ``obs_journal_size`` (or
    the explicit ``size``) without losing already-buffered spans."""
    global enabled
    if size is None:
        size = int(_var.get("obs_journal_size", journal_mod.DEFAULT_SIZE))
    if int(size) != journal.size:
        journal.resize(int(size))
    enabled = True
    from . import sentinel as _sentinel
    from . import watchdog as _wd

    _wd.refresh(True)
    _sentinel.refresh(True)
    # obs turned on AFTER mpi.init() (Runtime.init only installs the
    # flight-recorder signal handlers when obs was already on): the
    # documented `kill -USR1` dump must work for mid-run enables too.
    # Only when a runtime is live — a bare tracing-unit enable() in a
    # host process (pytest, bench) must not hijack its faulthandler —
    # so probe sys.modules rather than importing the runtime (a live
    # runtime implies the module is imported; a light obs import must
    # not drag it in).
    try:
        import sys as _sys

        _rt_mod = _sys.modules.get("ompi_release_tpu.runtime.runtime")
        rt = (_rt_mod.Runtime._instance
              if _rt_mod is not None else None)
        if rt is not None and rt.initialized and not rt.finalized:
            _wd.install_signal_handlers()
    except Exception:
        pass


def disable() -> None:
    global enabled
    enabled = False
    from . import sentinel as _sentinel
    from . import watchdog as _wd

    _wd.refresh(False)
    _sentinel.refresh(False)


def is_enabled() -> bool:
    return enabled


def record(op: str, layer: str, t_start: float, dt: float,
           nbytes: int = 0, peer: int = -1, comm_id: int = -1,
           flow: int = 0, flow_side: str = "") -> Span:
    """Emit-point helper: journal one span. Callers gate on
    ``obs.enabled`` themselves so the off cost stays one attr check."""
    return journal.record(op, layer, t_start, dt, nbytes, peer, comm_id,
                          flow, flow_side)


# the always-on switch: env var wins, then the MCA cvar
if (os.environ.get("OMPI_TPU_OBS", "").strip().lower()
        in ("1", "true", "yes", "on")
        or bool(_var.get("obs_enable", False))):
    enable()

# convenience: obs.export.dump_chrome_trace(...), obs.skew, the stall
# watchdog, the continuous sampler, the collective contract sentinel,
# the compiled-fire flight recorder, and the doctor merge — imported
# last so their journal/pvar imports see a fully-initialized package
# (sampler import also registers the obs_sample_* cvars and the
# obs_series_points / obs_sample_overhead_seconds pvars; sentinel
# registers obs_sentinel and the sentinel_ops_hashed /
# sentinel_mismatches pvars; ledger registers obs_ledger_size and the
# ledger_records / ledger_dropped pvars)
from . import export, ledger, sampler, sentinel  # noqa: E402,F401
from . import skew, watchdog  # noqa: E402,F401
