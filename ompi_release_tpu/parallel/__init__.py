"""Parallelism-strategy layers built on the communication substrate.

The reference is a message-passing substrate with no model layer; the
strategies here are the first-class demo layers SURVEY §2.4 requires,
each built on the communication pattern the reference provides for it:

  DP  — ring/bucketed gradient allreduce (coll_tuned_allreduce.c:361)
  TP  — sharded matmul + psum/all_gather (coll_tuned_allgather.c)
  PP  — stage-to-stage ppermute rings (examples/ring_c.c:39-61)
  SP  — Ulysses head<->sequence all-to-all (coll_tuned_alltoall.c)
  CP  — ring attention: blockwise K/V rotation (ring allreduce pattern,
        coll_tuned_allreduce.c:297-361)
  EP  — expert token routing all-to-all (coll_tuned_alltoallv.c)
  ZeRO — reduce_scatter gradient/optimizer sharding
        (coll_tuned_reduce_scatter.c)
"""

from .mesh_axes import (  # noqa: F401
    AXIS_DP, AXIS_PP, AXIS_TP, AXIS_SP, AXIS_EP,
    build_parallel_mesh, axis_size_or_1,
)
from . import dp, tp, pp, sp, cp, ep, tree, zero  # noqa: F401
from .elastic import ElasticStep  # noqa: F401
from .tree import (  # noqa: F401
    TreeSync, match_partition_rules, named_tree_map, tree_allgather,
    tree_allreduce, tree_reduce_scatter,
)
