"""Elastic training-step driver — roll a running job through rank
death without a restart.

The ULFM recovery loop the paper's ORTE layer exists to enable
("process launch, wire-up, FT, I/O fwd", PAPER.md §1), composed from
the pieces the runtime already provides:

  detect    a collective raises ``ERR_PROC_FAILED`` (the coordinator's
            heartbeat/waitpid promotion bumped the job epoch and the
            bounded wire waits stopped parking) or ``ERR_REVOKED`` (a
            peer poisoned the comm first);
  revoke    the survivor that caught the error revokes the comm so
            every peer's pending op is interrupted too;
  rebuild   ``errmgr.recover`` either shrinks (degraded world) or
            waits out the launcher's respawn and rebuilds full-size;
  rollback  the survivors agree (MIN-allreduce on the NEW comm) on the
            last checkpoint step everyone holds committed, restore it,
            and continue — deterministic replay from the snapshot.

A step function sees the CURRENT communicator (``step_fn(step, state,
comm)``) because recovery swaps it. Checkpoints must live in a
process-private directory (``ft/checkpoint.py``'s ``private_dir``
contract); the rollback agreement is what keeps them consistent.

Chaos hooks: the ``sensor_ft_*`` cvars (see ``ft/sensor.py``) arm an
:class:`~..ft.sensor.FtTester` per driver — probabilistic or
every-N-steps ``InjectedFault``s (recovered locally, no comm rebuild)
and the ``tpurun --ft-inject rank:step`` hard SIGKILL used by the
recovery job tests.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from .. import obs as _obs
from ..ft import ulfm as _ulfm
from ..ft import errmgr as _errmgr
from ..ft.checkpoint import Checkpointer
from ..ft.sensor import FtTester, InjectedFault
from ..mca import pvar
from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.stream("elastic")

_recovery_seconds = pvar.timer(
    "ft_recovery_seconds",
    "wall time from catching a failure in the step loop to resuming "
    "with a rebuilt communicator and restored checkpoint",
)
_steps_lost = pvar.counter(
    "ft_steps_lost",
    "training steps recomputed after rollbacks (failure step minus "
    "resume step, summed over recoveries)",
)

#: error classes that mean "a peer is gone" outright
_CONFIRMED = (ErrorCode.ERR_PROC_FAILED, ErrorCode.ERR_REVOKED)
#: error classes that SUGGEST a peer died before the epoch bump landed
#: (mid-transfer truncation, link loss, a reap timeout); recovery only
#: proceeds once the coordinator's failure picture confirms
_SUSPECT = (ErrorCode.ERR_TRUNCATE, ErrorCode.ERR_UNREACH,
            ErrorCode.ERR_PENDING)


class ElasticStep:
    """Drive ``state = step_fn(step, state, comm)`` with ULFM
    revoke/rebuild/rollback fault tolerance.

    ``policy``: ``"shrink"`` continues degraded on the survivors;
    ``"respawn"`` (under ``tpurun --enable-recovery``) waits for the
    replacement and continues full-size. ``InjectedFault`` from the
    armed :class:`FtTester` is always recovered locally (rollback
    only — the fleet is intact).
    """

    def __init__(self, comm, step_fn: Callable[[int, Any, Any], Any],
                 checkpointer: Checkpointer, *,
                 policy: str = "shrink",
                 checkpoint_every: int = 1,
                 max_recoveries: int = 3,
                 confirm_timeout_s: float = 15.0,
                 recover_timeout_s: float = 60.0,
                 tester: Optional[FtTester] = None) -> None:
        if policy not in ("shrink", "respawn"):
            raise MPIError(ErrorCode.ERR_ARG,
                           f"unknown elastic policy '{policy}'")
        self.comm = comm
        self.step_fn = step_fn
        self.checkpointer = checkpointer
        self.policy = policy
        self.checkpoint_every = max(1, int(checkpoint_every))
        self.max_recoveries = max_recoveries
        self.confirm_timeout_s = confirm_timeout_s
        self.recover_timeout_s = recover_timeout_s
        # chaos hook: armed from the sensor_ft_* cvars unless the
        # caller provides a tester (tests)
        self.tester = tester if tester is not None else FtTester.from_cvars(
            process_index=int(getattr(comm, "runtime").bootstrap.get(
                "process_index", 0)) if getattr(comm, "runtime", None)
            else 0)
        if (getattr(comm, "spans_processes", False)
                and self.tester.fail_prob > 0
                and getattr(self.tester, "seed", None) is None):
            # UNSEEDED probabilistic injection desynchronizes a
            # spanning comm: one rank rolls back (and posts the
            # rollback agreement collective) while peers post the
            # step's collective — mismatched schedules pair on the
            # comm's channel. Seeded injection fires at the SAME step
            # on every rank (same seed, same call sequence), which is
            # also what makes chaos runs replayable; every-N and the
            # armed kill are synchronized/real by construction.
            raise MPIError(
                ErrorCode.ERR_ARG,
                "unseeded probabilistic fault injection on a "
                "communicator spanning controller processes would "
                "desynchronize the collective schedule across ranks — "
                "set the sensor_ft_seed cvar (same seed fleet-wide) "
                "or use sensor_ft_every_n",
            )
        self.stats: Dict[str, Any] = {
            "recoveries": 0, "injected_rollbacks": 0,
            "failures": [], "steps_lost": 0, "policy": policy,
        }

    # -- helpers -----------------------------------------------------------
    def _agent(self):
        return getattr(self.comm.runtime, "agent", None)

    def _is_replacement(self) -> bool:
        """A respawned incarnation in a recovering job must not
        resume on the original comm — the survivors are waiting at
        the rebuild. The discriminator is the launcher's
        ``OMPITPU_INCARNATION`` marker (exported into respawned
        children only): it is authoritative and race-free, unlike any
        read of the failure picture — the rejoin epoch bump can land
        before OR after the moment the app samples it, and the
        cumulative rejoined set also names long-recovered survivors."""
        import os as _os

        if self.policy != "respawn" or self._agent() is None:
            return False
        return bool(int(_os.environ.get("OMPITPU_INCARNATION", "0")
                        or 0))

    def _confirm_failure(self, exc: MPIError) -> None:
        """Suspect errors recover only once the coordinator confirms a
        failure — a flaky transfer without a dead peer must surface,
        not trigger a silent rollback. Confirmation keys on the
        PERMANENT episode record (``dead_for`` against this comm's
        birth epoch), not the transient ``failed`` set: under the
        respawn policy the coordinator moves a corpse from failed to
        restarted milliseconds after promotion, and a suspect error
        surfacing after that bump must still confirm."""
        if exc.code in _CONFIRMED:
            return
        agent = self._agent()
        procs = set(self.comm._member_procs())
        epoch0 = getattr(self.comm, "_ft_epoch0", 0)
        deadline = time.monotonic() + self.confirm_timeout_s
        while time.monotonic() < deadline:
            if _ulfm.state().dead_for(procs, epoch0):
                return
            if agent is not None:
                try:
                    doc = agent.ft_query(timeout_ms=2000)
                    _ulfm.state().apply_notice(doc)
                    if _ulfm.state().dead_for(procs, epoch0):
                        return
                except MPIError:
                    pass
            time.sleep(0.1)
        raise exc

    def _rollback(self, init_like: Any) -> Tuple[Any, int]:
        """Agree on the rollback step (MIN over the new comm of each
        process's latest committed checkpoint), restore it, and return
        ``(state, resume_step)``. A process with no committed
        checkpoint forces a from-scratch restart for everyone —
        deterministic replay needs one common snapshot."""
        from .. import ops as _ops

        latest = self.checkpointer.latest_step()
        mine = -1 if latest is None else int(latest)
        if self.comm.size > 1 or self.comm.spans_processes:
            local_n = max(1, len(self.comm.local_comm_ranks))
            x = np.full((local_n, 1), mine, np.int32)
            agreed = int(np.asarray(
                self.comm.allreduce(x, _ops.MIN))[0][0])
        else:
            agreed = mine
        if agreed < 0:
            return init_like, 0
        state = self.checkpointer.restore(init_like, agreed)
        return state, agreed + 1

    def _recover(self, step: int, exc: MPIError) -> int:
        """Revoke -> rebuild -> rollback; returns the resume step."""
        self.stats["recoveries"] += 1
        self.stats["failures"].append((step, repr(exc)))
        if self.stats["recoveries"] > self.max_recoveries:
            raise exc
        rec = _obs.enabled  # capture once: flag may flip mid-recovery
        t0 = time.perf_counter()
        try:
            self.comm.revoke()
        except MPIError:
            pass  # already revoked / peers already told
        self.checkpointer.abort()  # in-flight snapshot is suspect
        self.comm = _errmgr.recover(self.comm, self.policy,
                                    timeout_s=self.recover_timeout_s)
        self._state, resume = self._rollback(self._init_like)
        lost = max(0, step - resume)
        self.stats["steps_lost"] += lost
        for _ in range(lost):
            _steps_lost.add()
        dt = time.perf_counter() - t0
        _recovery_seconds.add(dt)
        if rec and _obs.enabled:
            _obs.record("ft_recovery", "ft", t0, dt,
                        comm_id=self.comm.cid, peer=step)
        _log.verbose(
            0, f"recovered from failure at step {step} in {dt:.3f}s "
               f"({self.policy}); resuming at {resume} on "
               f"{self.comm.name}")
        return resume

    # -- the loop ----------------------------------------------------------
    def run(self, init_state: Any, num_steps: int) -> Tuple[Any, Dict]:
        self._init_like = init_state
        self._state = init_state
        if self._is_replacement():
            # replacement fast path: rebuild with the waiting
            # survivors, then restore the agreed snapshot
            self.comm = _errmgr.recover(
                self.comm, "respawn", timeout_s=self.recover_timeout_s)
            self._state, step = self._rollback(init_state)
            _log.verbose(0, f"replacement rejoined on {self.comm.name}; "
                            f"resuming at step {step}")
        else:
            latest = self.checkpointer.latest_step()
            if latest is not None:
                self._state = self.checkpointer.restore(init_state,
                                                        latest)
                step = latest + 1
            else:
                step = 0
        while step < num_steps:
            try:
                self.tester.step()  # chaos: may raise / may SIGKILL us
                self._state = self.step_fn(step, self._state, self.comm)
                if step % self.checkpoint_every == 0:
                    self.checkpointer.save(step, self._state,
                                           async_=False)
                step += 1
            except InjectedFault as e:
                # local injected fault: the fleet is intact — rollback
                # without touching the communicator
                self.stats["injected_rollbacks"] += 1
                self.stats["failures"].append((step, repr(e)))
                if self.stats["injected_rollbacks"] > self.max_recoveries:
                    raise
                self.checkpointer.abort()
                self._state, step = self._rollback(init_state)
            except MPIError as e:
                if e.code not in _CONFIRMED + _SUSPECT:
                    raise
                self._confirm_failure(e)  # re-raises if unconfirmed
                step = self._recover(step, e)
        self.checkpointer.wait()
        return self._state, self.stats
