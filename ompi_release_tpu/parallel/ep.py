"""Expert parallelism — capacity-bounded token routing over all-to-all.

The alltoallv pattern (``coll_tuned_alltoallv.c``) made static-shape
for XLA: top-1 (switch) routing with a fixed per-expert capacity so the
dispatch/combine tensors have compile-time shapes; the two
``lax.all_to_all`` calls move each token to its expert's rank and back.
Tokens over capacity are dropped (standard switch-transformer
semantics) and their outputs fall back to zero (residual carries them).
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _one_hot_dispatch(logits: jax.Array, n_experts: int, capacity: int
                      ) -> Tuple[jax.Array, jax.Array]:
    """Build (dispatch, combine) for top-1 routing.

    logits: (T, E). dispatch: (T, E, C) one-hot slot assignment;
    combine: (T, E, C) = dispatch * gate prob.
    """
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # (T,)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]
    eh = jax.nn.one_hot(expert, n_experts, dtype=jnp.int32)  # (T, E)
    # position of each token within its expert's queue
    pos = jnp.cumsum(eh, axis=0) * eh - eh  # (T, E), valid where eh==1
    keep = (pos < capacity) & (eh == 1)
    slot = jnp.where(keep, pos, 0)
    dispatch = (
        jax.nn.one_hot(slot, capacity, dtype=jnp.float32)
        * keep[..., None]
    )  # (T, E, C)
    combine = dispatch * gate[:, None, None]
    return dispatch, combine


def moe_layer(x: jax.Array, router_w: jax.Array, expert_fn: Callable,
              expert_params, *, axis_name: str = "ep",
              capacity_factor: float = 1.25) -> Tuple[jax.Array, jax.Array]:
    """Switch-MoE layer under shard_map over the ep axis.

    x: (T, D) this rank's tokens; router_w: (D, E_global) replicated;
    expert_params: this rank's local experts' params with leading axis
    E_local; ``expert_fn(params_e, tokens) -> tokens`` applied per local
    expert via vmap. Returns (output (T, D), aux_loss scalar).
    """
    n = lax.psum(1, axis_name)
    t, dmodel = x.shape
    e_global = router_w.shape[1]
    if e_global % n:
        raise ValueError(f"{e_global} experts not divisible by ep={n}")
    e_local = e_global // n
    capacity = max(1, int(capacity_factor * t / e_global))

    logits = jnp.matmul(x, router_w, preferred_element_type=jnp.float32)
    dispatch, combine = _one_hot_dispatch(logits, e_global, capacity)

    # load-balancing aux loss (Switch eq. 4): E * sum_e f_e * p_e
    probs = jax.nn.softmax(logits, axis=-1)
    frac_tokens = jnp.mean(dispatch.sum(-1), axis=0)  # (E,)
    frac_probs = jnp.mean(probs, axis=0)
    aux = e_global * jnp.sum(frac_tokens * frac_probs)
    aux = lax.pmean(aux, axis_name)

    # local tokens -> (E, C, D) expert queues
    sent = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
    # route: (E, C, D) -> (n, E_local, C, D): each rank keeps its experts'
    # queues from every peer
    sent = sent.reshape(n, e_local, capacity, dmodel)
    recv = lax.all_to_all(sent, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)  # (n, E_local, C, D)
    # run local experts over all peers' tokens
    per_expert = recv.transpose(1, 0, 2, 3).reshape(
        e_local, n * capacity, dmodel
    ).astype(x.dtype)
    done = jax.vmap(expert_fn)(expert_params, per_expert)
    done = done.reshape(e_local, n, capacity, dmodel).transpose(1, 0, 2, 3)
    # route back
    back = lax.all_to_all(done.astype(jnp.float32), axis_name,
                          split_axis=0, concat_axis=0, tiled=False)
    back = back.reshape(e_global, capacity, dmodel)
    out = jnp.einsum("tec,ecd->td", combine, back)
    return out.astype(x.dtype), aux
