"""Pipeline parallelism: GPipe-style microbatch schedule over a ppermute
ring.

The stage-to-stage activation transfer is exactly the reference's
point-to-point ring (``examples/ring_c.c:39-61``) compiled into one XLA
program: each tick every stage computes its block and ppermutes the
activation to stage+1. Runs under ``shard_map`` over the ``pp`` axis;
each rank holds only its own stage's parameters (stacked stage params
are sharded over pp by the caller's PartitionSpec).

Schedule: M microbatches through S stages in M+S-1 ticks via
``lax.scan`` — static shapes, no data-dependent control flow; the
bubble is (S-1)/(M+S-1), so callers pick M >= 4*S.
"""

from __future__ import annotations

import time as _time
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..mca import pvar

_boundary_msgs = pvar.counter(
    "pp_boundary_msgs", "host-pipeline stage-boundary activations sent"
)
_boundary_wait = pvar.timer(
    "pp_boundary_wait_seconds",
    "EXPOSED host-pipeline boundary-transfer time (recv wait the "
    "stage could not hide in its microbatch compute)",
)


def pipeline(stage_fn: Callable, stage_params, x_microbatches: jax.Array, *,
             axis_name: str = "pp", remat: bool = False) -> jax.Array:
    """Run microbatches through the stage pipeline.

    stage_fn(params, x) -> y with y.shape == x.shape (transformer blocks
    satisfy this; stage 0/S-1 asymmetries like embed/unembed belong
    outside the pipelined trunk).

    x_microbatches: (M, ...) — the microbatched input, meaningful on
    stage 0 (other stages may pass anything of the same shape, e.g. the
    same array; only stage 0's values are consumed).
    Returns (M, ...) — meaningful on the last stage.

    ``remat=True`` wraps the stage body in ``jax.checkpoint``: the
    backward pass recomputes each tick's activations instead of
    keeping all M x S of them live — the TPU-idiomatic answer to the
    activation-memory problem 1F1B schedules solve by hand elsewhere
    (the schedule stays the compiled scan; XLA plans the recompute).
    Gradients are bitwise-equivalent math, just cheaper to hold.
    """
    if remat:
        # prevent_cse=False is the documented form for checkpoint
        # under scan: the CSE hazard the default guards against cannot
        # occur here, and its barriers would block XLA fusion across
        # the remat boundary
        stage_fn = jax.checkpoint(stage_fn, prevent_cse=False)
    n = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    m = x_microbatches.shape[0]
    ticks = m + n - 1
    fwd = [(i, i + 1) for i in range(n - 1)]

    from .mesh_axes import vary_like, vary_over

    # carries end up varying over pp (stage-dependent) on top of the
    # input's own varying axes; type the initial values to match
    ref = vary_over(x_microbatches, (axis_name,))
    outputs = vary_like(jnp.zeros_like(x_microbatches), ref)
    recv0 = vary_like(jnp.zeros_like(x_microbatches[0]), ref)
    x_microbatches = ref

    def tick(carry, t):
        recv, outputs = carry
        mb = lax.dynamic_index_in_dim(
            x_microbatches, jnp.clip(t, 0, m - 1), 0, keepdims=False
        )
        inp = jnp.where(stage == 0, mb, recv)
        out = stage_fn(stage_params, inp)
        # last stage stores microbatch t-(n-1) once it exists
        oidx = jnp.clip(t - (n - 1), 0, m - 1)
        cur = lax.dynamic_index_in_dim(outputs, oidx, 0, keepdims=False)
        store = jnp.where((t >= n - 1) & (stage == n - 1), out, cur)
        outputs = lax.dynamic_update_index_in_dim(outputs, store, oidx, 0)
        recv = lax.ppermute(out, axis_name, fwd) if n > 1 else recv
        return (recv, outputs), None

    (_, outputs), _ = lax.scan(tick, (recv0, outputs), jnp.arange(ticks))
    return outputs


def pipeline_loss(stage_fn: Callable, loss_fn: Callable, stage_params,
                  x_microbatches: jax.Array, target_microbatches, *,
                  axis_name: str = "pp", remat: bool = False) -> jax.Array:
    """Forward pipeline + last-stage loss, broadcast to all stages.

    ``loss_fn(y, targets) -> scalar`` runs on the last stage's outputs;
    the psum-of-masked-value broadcast gives every stage the same scalar
    so ``jax.grad`` through this function produces each stage's local
    parameter gradients (XLA transposes the ppermutes into the backward
    ring automatically — the reference's reverse activation ring).
    """
    n = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    y = pipeline(stage_fn, stage_params, x_microbatches,
                 axis_name=axis_name, remat=remat)
    local = loss_fn(y, target_microbatches)
    # Only the last stage's loss is real. The value is broadcast with a
    # psum of the masked term, but the psum must be OUTSIDE the grad
    # path: psum's transpose is psum, so differentiating the broadcast
    # on every rank would scale gradients by n. stop_gradient routes
    # backward flow solely through the last stage's local term (whose
    # cotangent then rides the transposed ppermute ring to every stage).
    masked = jnp.where(stage == n - 1, local, jnp.zeros_like(local))
    bcast = lax.psum(masked, axis_name)
    return masked + lax.stop_gradient(bcast - masked)


# ---------------------------------------------------------------------------
# host-driver microbatch schedule (spanning comms; nonblocking boundaries)
# ---------------------------------------------------------------------------

class HostPipeline:
    """GPipe microbatch schedule driven from the host over a
    communicator: each member rank is one stage, boundary activations
    ride rank-to-rank messages instead of a compiled ppermute ring
    (the multi-process trainer shape, where stages live in different
    controller processes).

    With ``nonblocking=True`` (default) every boundary transfer is an
    ``irecv`` posted UP FRONT and an ``isend`` never waited mid-
    schedule — the PR 7 progress engine moves the bytes while the
    stage computes its next microbatch, so the pipeline bubble hides
    the communication (exposed remainder witnessed by the
    ``pp_boundary_wait_seconds`` pvar; with the ``progress_thread``
    cvar on, spanning transfers complete off the caller entirely).
    ``nonblocking=False`` is the blocking reference leg: every
    boundary send+recv runs exposed between two computes — the shape
    the bench's ``tree_pp`` lines compare against.

    The schedule is the same M+S-1-tick GPipe wavefront as
    :func:`pipeline`; results are bitwise-identical between the two
    legs (same stage_fn calls in the same order, comm is pure data
    movement).
    """

    def __init__(self, comm, stage_fn: Callable, *,
                 stage: Optional[int] = None, tag: int = 71,
                 nonblocking: bool = True) -> None:
        self.comm = comm
        self.stage_fn = stage_fn
        if stage is None:
            ranks = getattr(comm, "local_comm_ranks", None)
            stage = ranks[0] if ranks else 0
        self.stage = int(stage)
        self.tag = tag
        self.nonblocking = nonblocking

    def run(self, microbatches: Sequence[Any]) -> List[Any]:
        """Stream ``microbatches`` through this process's stage.
        Stage 0 consumes the inputs; the last stage returns the list
        of outputs (other stages return [])."""
        comm, s, tag = self.comm, self.stage, self.tag
        n_stages = comm.size
        m = len(microbatches)
        nb = self.nonblocking
        recvs: List[Any] = []
        if s > 0 and nb:
            # every boundary irecv posts before the first compute:
            # upstream activations land during our earlier-microbatch
            # computes (the bubble), not in an exposed wait
            recvs = [comm.irecv(s - 1, tag, rank=s) for _ in range(m)]
        outs: List[Any] = []
        sends: List[Any] = []
        for k in range(m):
            if s == 0:
                x = microbatches[k]
            else:
                t0 = _time.perf_counter()
                if nb:
                    req = recvs[k]
                    req.wait()
                    x = req.value
                else:
                    x, _st = comm.recv(s - 1, tag, rank=s)
                _boundary_wait.add(_time.perf_counter() - t0)
            y = self.stage_fn(x)
            if s < n_stages - 1:
                _boundary_msgs.add()
                if nb:
                    # fire and keep computing; drained at schedule end
                    sends.append(comm.isend(y, s + 1, tag, rank=s))
                else:
                    comm.send(y, s + 1, tag, rank=s)
            else:
                outs.append(y)
        for req in sends:
            req.wait()
        return outs
