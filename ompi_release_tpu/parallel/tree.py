"""Pytree-native planned collectives: one fused, overlapped pass over
whole parameter trees.

A training step at scale is not one collective — it is thousands of
per-leaf calls. The coll/tuned discipline picks one good algorithm per
call; this layer plans the whole TREE once (the ZeRO / DDP-bucketing
shape: Rajbhandari et al. 2020, Li et al. VLDB 2020) and then drives
allreduce / allgather / reduce-scatter over every leaf through a
handful of fused, overlappable transfers:

rules → plan
    :func:`match_partition_rules` turns regex rules into a
    PartitionSpec pytree (the fmengine/alpa interface: name-matched
    specs, scalar leaves never partitioned). :func:`plan_tree` buckets
    the leaves per (op, dtype) through the ONE shared fusion planner
    (:func:`coll.fusion.plan_buckets`) and caches the plan per tree
    signature — plan once, fire every step.

SPMD pass (inside ``shard_map``)
    :func:`tree_allreduce` / :func:`tree_reduce_scatter` /
    :func:`tree_allgather`: one ``lax.psum`` / ``psum_scatter`` /
    ``all_gather`` per bucket instead of one per leaf.
    ``parallel/zero.py`` and ``parallel/dp.py`` are thin wrappers over
    these. ``bucket_bytes=0`` selects the per-leaf reference path; the
    planned path is bitwise-identical to it (buckets pack a rank-major
    interleaved layout, so every element is reduced/scattered across
    exactly the same participants in the same slot).

driver pass (host-driver comms, the progress-engine payoff)
    :class:`TreeSync`: one nonblocking collective per bucket issued up
    front, caller compute overlaps the wire traffic, ``wait()`` lands
    at the step boundary (``parallel/dp.GradientSync`` is now the
    allreduce specialization). Hidden comm time is witnessed by the
    ``tree_hidden_seconds`` pvar (the per-schedule accounting of
    ``runtime/progress.py``, summed per pass).

Bucket sizing is tunable: explicit argument > ``tree_buckets`` dynamic
rule lines (``tpu-tune --tree-buckets`` emits them; the 5th column is
the bucket size, the algorithm column is ``fused``/``per_leaf``) >
``tree_bucket_bytes`` cvar > ``dp_bucket_bytes``.

pvars: ``tree_buckets_planned``, ``tree_plan_cache_hits`` (1=hit,
0=build; sum/count = hit ratio, printed by ``obs --selftest``),
``tree_passes``, ``tree_hidden_seconds``. Journal spans are gated on
``_obs.enabled`` so the hot path stays one attribute check.
"""

from __future__ import annotations

import re
import threading
import time as _time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import obs as _obs
from ..coll.fusion import plan_buckets
from ..mca import pvar
from ..mca import var as mca_var

_buckets_planned = pvar.counter(
    "tree_buckets_planned",
    "fused buckets produced by tree-collective plan builds "
    "(big per-leaf transfers count as their own bucket)",
)
_plan_hits = pvar.aggregate(
    "tree_plan_cache_hits",
    "tree-plan cache outcome per planned pass (1=hit, 0=build); "
    "sum/count = hit ratio",
)
_passes = pvar.counter(
    "tree_passes",
    "whole-tree planned DRIVER passes issued (TreeSync; the SPMD "
    "passes trace into a compiled program, so they count plan builds "
    "and cache hits instead — per-execution Python counters cannot "
    "exist inside a jitted body)",
)
_hidden = pvar.timer(
    "tree_hidden_seconds",
    "tree-pass collective time that ran while the caller computed "
    "(per-schedule progress-engine accounting, summed at wait())",
)


def register_vars() -> None:
    mca_var.register(
        "tree_bucket_bytes", "size", 0,
        "Bucket capacity in bytes for planned whole-tree collectives "
        "(leaves below it fuse per dtype, at/above it transfer "
        "individually); 0 = defer to tree_buckets dynamic rules, "
        "then dp_bucket_bytes",
    )


register_vars()  # idempotent; cvars must exist before the first plan


# ---------------------------------------------------------------------------
# regex partition rules -> PartitionSpec pytree (the fmengine interface)
# ---------------------------------------------------------------------------

def tree_path_str(path, sep: str = "/") -> str:
    """Render a jax key path as a ``sep``-joined name usable in regex
    partition rules."""
    import jax

    tu = jax.tree_util
    keys: List[str] = []
    for k in path:
        if isinstance(k, tu.SequenceKey):
            keys.append(str(k.idx))
        elif isinstance(k, tu.DictKey):
            keys.append(str(k.key))
        elif isinstance(k, tu.GetAttrKey):
            keys.append(str(k.name))
        elif isinstance(k, tu.FlattenedIndexKey):
            keys.append(str(k.key))
        else:
            keys.append(str(k))
    return sep.join(keys)


def named_tree_map(f, tree, *, sep: str = "/", is_leaf=None):
    """``jax.tree.map`` with the leaf's path name as first argument."""
    import jax

    return jax.tree_util.tree_map_with_path(
        lambda p, x: f(tree_path_str(p, sep), x), tree, is_leaf=is_leaf
    )


def is_scalar_leaf(leaf) -> bool:
    """Scalar (or single-element) leaves are never partitioned — there
    is no axis to shard."""
    shape = tuple(getattr(leaf, "shape", ()))
    return len(shape) == 0 or int(np.prod(shape, dtype=np.int64)) == 1


def match_partition_rules(rules: Sequence[Tuple[str, Any]], tree, *,
                          sep: str = "/"):
    """PartitionSpec pytree from ``[(regex, spec)]`` rules matched
    against each leaf's path name (first match wins; scalar leaves are
    unpartitioned regardless of rules). Raises ``ValueError`` naming
    the leaf when no rule matches — a silent default would desync the
    sharding the operator thinks they configured."""
    from jax.sharding import PartitionSpec

    def pick(name, leaf):
        if is_scalar_leaf(leaf):
            return PartitionSpec()
        for pat, spec in rules:
            if re.search(pat, name) is not None:
                return spec
        raise ValueError(f"no partition rule matches leaf {name!r}")

    return named_tree_map(pick, tree, sep=sep)


# ---------------------------------------------------------------------------
# the plan: per-(dtype) buckets over leaf metadata, cached per signature
# ---------------------------------------------------------------------------

class TreePlan:
    """One planned pass over a tree signature: which leaves transfer
    alone (``big``) and which fuse into which bucket (``buckets``,
    index lists in leaf order, one dtype per bucket)."""

    __slots__ = ("meta", "big", "buckets", "bucket_bytes", "total_bytes")

    def __init__(self, meta, big, buckets, bucket_bytes, total_bytes):
        self.meta = meta  # ((shape, dtype_str, size, nbytes), ...)
        self.big = big
        self.buckets = buckets
        self.bucket_bytes = bucket_bytes
        self.total_bytes = total_bytes

    def n_transfers(self) -> int:
        return len(self.big) + len(self.buckets)


_plans: Dict[Tuple, TreePlan] = {}
_plans_lock = threading.Lock()


def _meta_of(shapes_dtypes) -> Tuple:
    meta = []
    for shape, dt in shapes_dtypes:
        shape = tuple(int(d) for d in shape)
        size = int(np.prod(shape, dtype=np.int64)) if shape else 1
        nbytes = size * int(np.dtype(dt).itemsize)
        meta.append((shape, str(dt), size, nbytes))
    return tuple(meta)


def plan_from_meta(shapes_dtypes: Sequence[Tuple[Tuple, Any]],
                   bucket_bytes: int) -> TreePlan:
    """Build (or fetch) the plan for a sequence of ``(shape, dtype)``
    leaf signatures. Pure metadata — no arrays, no jax — so the plan
    cache can be exercised device-free (``obs --selftest``)."""
    meta = _meta_of(shapes_dtypes)
    key = (meta, int(bucket_bytes))
    with _plans_lock:
        plan = _plans.get(key)
    if plan is not None:
        _plan_hits.observe(1)
        return plan
    _plan_hits.observe(0)
    big: List[int] = []
    small: List[Tuple[int, int, str]] = []
    for i, (_shape, dt, _size, nbytes) in enumerate(meta):
        if bucket_bytes > 0 and nbytes < bucket_bytes:
            small.append((i, nbytes, dt))
        else:
            big.append(i)
    buckets = plan_buckets(iter(small), bucket_bytes)
    _buckets_planned.add(len(big) + len(buckets))
    plan = TreePlan(meta, big, buckets, int(bucket_bytes),
                    sum(m[3] for m in meta))
    with _plans_lock:
        _plans[key] = plan
    return plan


def plan_tree(tree_, bucket_bytes: Optional[int] = None,
              comm_size: int = 0) -> Tuple[TreePlan, Any, List[Any]]:
    """Flatten ``tree_`` and plan it; returns (plan, treedef, leaves).
    ``bucket_bytes=None`` resolves through rules/cvars (see
    :func:`resolve_bucket_bytes`); ``0`` forces the per-leaf path."""
    import jax

    leaves, treedef = jax.tree.flatten(tree_)
    if bucket_bytes is None:
        total = sum(
            int(np.prod(tuple(l.shape), dtype=np.int64))
            * int(np.dtype(l.dtype).itemsize) if tuple(l.shape)
            else int(np.dtype(l.dtype).itemsize)
            for l in leaves
        )
        bucket_bytes = resolve_bucket_bytes(comm_size, total)
    plan = plan_from_meta([(l.shape, l.dtype) for l in leaves],
                          bucket_bytes)
    return plan, treedef, leaves


def resolve_bucket_bytes(comm_size: int, tree_bytes: int) -> int:
    """Bucket capacity for a planned pass, in tuned precedence order:
    ``tree_buckets`` dynamic rule (algorithm ``per_leaf`` -> 0, else
    the rule's 5th column) > ``tree_bucket_bytes`` cvar >
    ``dp_bucket_bytes`` cvar. ``comm_size``/``tree_bytes`` are the
    rule-match keys (min_comm_size / min_msg_bytes)."""
    from ..coll import dynamic_rules

    alg = dynamic_rules.lookup("tree_buckets", comm_size, tree_bytes)
    if alg == "per_leaf":
        return 0
    if alg == "fused":
        seg = dynamic_rules.lookup_segsize("tree_buckets", comm_size,
                                           tree_bytes)
        if seg is not None:
            return int(seg)
    v = int(mca_var.get("tree_bucket_bytes", 0))
    if v > 0:
        return v
    return int(mca_var.get("dp_bucket_bytes", 4 * 1024 * 1024))


def _record_pass(kind: str, plan: TreePlan, t0: float,
                 comm_id: int = -1) -> None:
    """Driver-pass accounting (issue/wait run per step on the host)."""
    _passes.add()
    if _obs.enabled:
        _obs.record("tree_" + kind, "tree", t0,
                    _time.perf_counter() - t0, nbytes=plan.total_bytes,
                    comm_id=comm_id)


def _record_plan(kind: str, plan: TreePlan, t0: float) -> None:
    """SPMD-pass accounting: the body runs at TRACE time only (the
    executed pass is the compiled program), so what is countable here
    is the plan/trace construction — named tree_plan_* to say so, and
    deliberately NOT bumping tree_passes."""
    if _obs.enabled:
        _obs.record("tree_plan_" + kind, "tree", t0,
                    _time.perf_counter() - t0,
                    nbytes=plan.total_bytes)


# ---------------------------------------------------------------------------
# SPMD planned passes (inside shard_map; XLA pipelines the buckets)
# ---------------------------------------------------------------------------

def _chunk(size: int, n: int) -> int:
    return -(-size // n)  # ceil(size / n)


def _maybe_mean(x, dtype, n, mean: bool):
    import jax.numpy as jnp

    return x / n if mean and jnp.issubdtype(dtype, jnp.inexact) else x


def tree_allreduce(tree_, axis_name: str, *, mean: bool = False,
                   bucket_bytes: Optional[int] = None):
    """Allreduce every leaf over ``axis_name`` in one planned pass:
    one ``lax.psum`` per bucket / big leaf. Bitwise-identical to the
    per-leaf loop (``bucket_bytes=0``) — packing is pure layout."""
    import jax
    from jax import lax

    t0 = _time.perf_counter()
    n = lax.psum(1, axis_name)  # static under shard_map
    plan, treedef, leaves = plan_tree(tree_, bucket_bytes, int(n))
    out: List[Any] = [None] * len(leaves)
    for i in plan.big:
        out[i] = _maybe_mean(lax.psum(leaves[i], axis_name),
                             leaves[i].dtype, n, mean)
    import jax.numpy as jnp

    for bucket in plan.buckets:
        flat = jnp.concatenate([leaves[i].reshape(-1) for i in bucket])
        red = lax.psum(flat, axis_name)
        off = 0
        for i in bucket:
            size = plan.meta[i][2]
            out[i] = _maybe_mean(
                red[off:off + size].reshape(plan.meta[i][0]),
                leaves[i].dtype, n, mean)
            off += size
    _record_plan("allreduce", plan, t0)
    return jax.tree.unflatten(treedef, out)


def _padded_rows(leaf, n: int):
    """Leaf flattened and zero-padded to a (n, chunk) rank-major view:
    row r is the slice rank r owns after a tiled scatter."""
    import jax.numpy as jnp

    flat = leaf.reshape(-1)
    pad = (-flat.size) % n
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.zeros((pad,), leaf.dtype)])
    return flat.reshape(n, -1)


def tree_reduce_scatter(tree_, axis_name: str, *, mean: bool = True,
                        bucket_bytes: Optional[int] = None):
    """reduce_scatter every leaf over ``axis_name`` in one planned
    pass; returns the per-leaf flat shard pytree (leaf i -> 1-D array
    of ceil(size/n) elements — the same contract as the per-leaf
    path). Buckets pack the RANK-MAJOR interleaved layout (rank r's
    slice of the packed buffer is the concatenation of each member
    leaf's own shard r), so the fused ``psum_scatter`` hands every
    element to the same rank the per-leaf scatter would — bitwise."""
    import jax
    from jax import lax

    t0 = _time.perf_counter()
    n = lax.psum(1, axis_name)
    plan, treedef, leaves = plan_tree(tree_, bucket_bytes, int(n))
    out: List[Any] = [None] * len(leaves)

    def rs(flat):
        return lax.psum_scatter(flat, axis_name, scatter_dimension=0,
                                tiled=True)

    for i in plan.big:
        red = rs(_padded_rows(leaves[i], int(n)).reshape(-1))
        out[i] = _maybe_mean(red, leaves[i].dtype, n, mean)
    import jax.numpy as jnp

    for bucket in plan.buckets:
        packed = jnp.concatenate(
            [_padded_rows(leaves[i], int(n)) for i in bucket], axis=1)
        red = rs(packed.reshape(-1))  # (sum chunks,) for this rank
        off = 0
        for i in bucket:
            c = _chunk(plan.meta[i][2], int(n))
            out[i] = _maybe_mean(red[off:off + c], leaves[i].dtype, n,
                                 mean)
            off += c
    _record_plan("reduce_scatter", plan, t0)
    return jax.tree.unflatten(treedef, out)


def tree_allgather(shards, shapes, axis_name: str, *,
                   bucket_bytes: Optional[int] = None):
    """all_gather every flat shard back to its full (reshaped) leaf in
    one planned pass. ``shapes`` mirrors ``shards``' structure with
    target shapes as leaves. Pure data movement — bitwise by
    construction."""
    import jax
    from jax import lax

    t0 = _time.perf_counter()
    n = int(lax.psum(1, axis_name))
    plan, treedef, leaves = plan_tree(shards, bucket_bytes, n)
    shape_list = treedef.flatten_up_to(shapes)
    out: List[Any] = [None] * len(leaves)

    def ag(shard):
        return lax.all_gather(shard, axis_name, axis=0, tiled=True)

    def finish(i, full_flat):
        shape = tuple(shape_list[i])
        size = int(np.prod(shape, dtype=np.int64)) if shape else 1
        out[i] = full_flat[:size].reshape(shape)

    for i in plan.big:
        finish(i, ag(leaves[i]))
    import jax.numpy as jnp

    for bucket in plan.buckets:
        packed = jnp.concatenate([leaves[i] for i in bucket])  # (C,)
        rows = ag(packed).reshape(n, -1)  # (n, C)
        off = 0
        for i in bucket:
            c = leaves[i].shape[0]
            finish(i, rows[:, off:off + c].reshape(-1))
            off += c
    _record_plan("allgather", plan, t0)
    return jax.tree.unflatten(treedef, out)


# ---------------------------------------------------------------------------
# compiled whole-tree pass: ONE jitted program for the whole schedule
# ---------------------------------------------------------------------------

def run_tree_pass(comm, tree_, *, kind: str = "allreduce",
                  mean: bool = False,
                  bucket_bytes: Optional[int] = None):
    """Run a whole planned tree pass as ONE compiled XLA program on a
    host-driver communicator (leaves follow the driver convention:
    leading axis == comm.size). Every bucket's pack / collective /
    unpack — the entire fused schedule — traces into a single jitted
    ``shard_map`` program cached per (kind, plan signature) in the
    driver's per-comm plan cache, so steady-state steps launch one
    program with zero per-bucket Python work (the coll/plan
    discipline applied to trees). ``kind``: ``allreduce`` returns the
    reduced tree; ``reduce_scatter`` returns the per-leaf flat shard
    tree (same contract as :func:`tree_reduce_scatter`).

    Bitwise-identical to the per-leaf and planned SPMD paths — the
    body IS :func:`tree_allreduce` / :func:`tree_reduce_scatter`."""
    import jax

    from ..coll import driver as _driver

    if kind not in ("allreduce", "reduce_scatter"):
        raise ValueError(f"run_tree_pass kind {kind!r} not in "
                         "('allreduce', 'reduce_scatter')")
    leaves, treedef = jax.tree.flatten(tree_)
    if not leaves:
        return tree_
    if bucket_bytes is None:
        total = sum(
            int(np.prod(tuple(l.shape[1:]), dtype=np.int64))
            * int(np.dtype(l.dtype).itemsize) for l in leaves
        )
        bucket_bytes = resolve_bucket_bytes(comm.size, total)
    # plan over the PER-RANK leaf signatures (leading axis stripped:
    # inside shard_map each block is one rank's slice)
    plan = plan_from_meta([(l.shape[1:], l.dtype) for l in leaves],
                          int(bucket_bytes))
    key = ("tree", kind, bool(mean), int(bucket_bytes), plan.meta)

    def body(*blocks):
        sub = jax.tree.unflatten(treedef, list(blocks))
        if kind == "allreduce":
            out = tree_allreduce(sub, "rank", mean=mean,
                                 bucket_bytes=int(bucket_bytes))
        else:
            out = tree_reduce_scatter(sub, "rank", mean=mean,
                                      bucket_bytes=int(bucket_bytes))
        return tuple(jax.tree.flatten(out)[0])

    outs = _driver.run_sharded(comm, key, body, leaves[0],
                               extra_arrays=tuple(leaves[1:]))
    return jax.tree.unflatten(treedef, list(outs))


# ---------------------------------------------------------------------------
# driver pass: one nonblocking collective per bucket, overlapped
# ---------------------------------------------------------------------------

def _op_hidden_seconds(req) -> float:
    """The progress engine's own accounting of how much of this
    schedule's run the caller spent elsewhere (0 for polling-mode and
    in-process requests) — ScheduledOp.hidden_seconds is the ONE
    definition, shared with the engine's nbc_hidden_seconds fold."""
    op = getattr(req, "_sched_op", None)
    return op.hidden_seconds() if op is not None else 0.0


class PendingTreePass:
    """In-flight overlapped tree pass: ``wait()`` completes every
    bucket, folds the engine's hidden-time accounting into
    ``tree_hidden_seconds``, and returns the reassembled pytree.
    Holds leaf METADATA only — issue()'s host staging is released for
    the whole overlap window."""

    def __init__(self, sync: "TreeSync", kind: str, treedef,
                 plan: TreePlan, reqs: Dict[Any, Any], lead: int,
                 shapes: Optional[List[Tuple]] = None) -> None:
        self._sync = sync
        self._kind = kind  # allreduce | reduce_scatter | allgather
        self._treedef = treedef
        self._plan = plan
        self._reqs = reqs
        self._lead = lead
        self._shapes = shapes  # allgather: target shapes per leaf

    def hidden_seconds(self) -> float:
        return sum(_op_hidden_seconds(r) for r in self._reqs.values())

    def wait(self):
        import jax
        import jax.numpy as jnp

        from ..request import request as _req

        t0 = _time.perf_counter()
        _req.wait_all(list(self._reqs.values()))
        hidden = self.hidden_seconds()
        if hidden > 0:
            _hidden.add(hidden)
        plan, reqs = self._plan, self._reqs
        comm = self._sync.comm
        n, lead = comm.size, self._lead
        mean = self._sync.mean
        out: List[Any] = [None] * len(plan.meta)

        def fin(i, arr, shape):
            arr = np.asarray(arr).reshape(shape)
            if mean and self._kind != "allgather" \
                    and np.issubdtype(np.dtype(plan.meta[i][1]),
                                      np.inexact):
                arr = arr / n
            out[i] = jnp.asarray(arr)

        if self._kind == "allreduce":
            for i in plan.big:
                fin(i, reqs[("big", i)].value, plan.meta[i][0])
            for k, bucket in enumerate(plan.buckets):
                flat = np.asarray(reqs[("bucket", k)].value)
                flat = flat.reshape(lead, -1)
                off = 0
                for i in bucket:
                    w = plan.meta[i][2] // lead
                    fin(i, flat[:, off:off + w], plan.meta[i][0])
                    off += w
        elif self._kind == "reduce_scatter":
            # values are this member-rank's blocks: (lead, chunk_i)
            for i in plan.big:
                c = _chunk(plan.meta[i][2] // lead, n)
                fin(i, reqs[("big", i)].value, (lead, c))
            for k, bucket in enumerate(plan.buckets):
                flat = np.asarray(reqs[("bucket", k)].value)
                flat = flat.reshape(lead, -1)
                off = 0
                for i in bucket:
                    c = _chunk(plan.meta[i][2] // lead, n)
                    fin(i, flat[:, off:off + c], (lead, c))
                    off += c
        else:  # allgather: rows are (lead, n * C) concatenations
            shapes = self._shapes
            for i in plan.big:
                full = np.asarray(reqs[("big", i)].value)
                full = full.reshape(lead, -1)
                size = int(np.prod(shapes[i], dtype=np.int64))
                fin(i, full[:, :size], (lead,) + tuple(shapes[i]))
            for k, bucket in enumerate(plan.buckets):
                flat = np.asarray(reqs[("bucket", k)].value)
                bc = sum(plan.meta[i][2] // lead for i in bucket)
                rows = flat.reshape(lead, n, bc)
                off = 0
                for i in bucket:
                    c = plan.meta[i][2] // lead
                    size = int(np.prod(shapes[i], dtype=np.int64))
                    piece = rows[:, :, off:off + c].reshape(lead, -1)
                    fin(i, piece[:, :size],
                        (lead,) + tuple(shapes[i]))
                    off += c
        if _obs.enabled:
            _obs.record("tree_wait_" + self._kind, "tree", t0,
                        _time.perf_counter() - t0,
                        nbytes=plan.total_bytes, comm_id=comm.cid)
        return jax.tree.unflatten(self._treedef, out)


class TreeSync:
    """Overlapped whole-tree collectives for the host-driver path.

    Buffers follow the communicator's driver convention (leading axis
    = this process's member slices). One nonblocking collective per
    plan bucket issues up front; the caller computes; ``wait()`` at
    the step boundary reassembles the tree. With the
    ``progress_thread`` cvar on, the engine runs the bucket schedules
    off the caller (true overlap, witnessed by ``tree_hidden_seconds``
    / ``nbc_hidden_seconds``); in polling mode the buckets drain at
    ``wait()``. Bitwise parity with the per-leaf blocking path is
    structural: each bucket runs the identical collective the blocking
    call would, via the progress engine.
    """

    def __init__(self, comm, *, mean: bool = False,
                 bucket_bytes: Optional[int] = None) -> None:
        self.comm = comm
        self.mean = mean
        self._bucket_bytes = bucket_bytes

    def _resolve(self, leaves: List[np.ndarray]) -> int:
        if self._bucket_bytes is not None:
            return int(self._bucket_bytes)
        total = sum(int(l.nbytes) for l in leaves)
        return resolve_bucket_bytes(self.comm.size, total)

    def _flatten(self, tree_) -> Tuple[Any, List[np.ndarray], int]:
        import jax

        leaves_raw, treedef = jax.tree.flatten(tree_)
        leaves = [np.asarray(l) for l in leaves_raw]
        if not leaves or any(l.ndim == 0 for l in leaves):
            raise ValueError(
                "TreeSync needs non-empty driver-mode leaves, each "
                "with a leading (member-slice) axis — 0-d scalar "
                "leaves cannot carry the per-member axis; reshape "
                "them to (lead, 1) or drop them from the pytree")
        leads = {l.shape[0] for l in leaves}
        if len(leads) != 1:
            raise ValueError(
                "TreeSync leaves must share one leading "
                f"(member-slice) axis; got leading axes {sorted(leads)}")
        return treedef, leaves, leads.pop()

    def issue(self, tree_) -> PendingTreePass:
        """Overlapped tree ALLREDUCE: one ``iallreduce`` per bucket;
        returns without completing any of them."""
        t0 = _time.perf_counter()
        treedef, leaves, lead = self._flatten(tree_)
        plan = plan_from_meta([(l.shape, l.dtype) for l in leaves],
                              self._resolve(leaves))
        reqs: Dict[Any, Any] = {}
        for i in plan.big:
            reqs[("big", i)] = self.comm.iallreduce(leaves[i])
        for k, bucket in enumerate(plan.buckets):
            flat = np.concatenate(
                [leaves[i].reshape(lead, -1) for i in bucket], axis=1)
            reqs[("bucket", k)] = self.comm.iallreduce(flat)
        _record_pass("issue_allreduce", plan, t0, self.comm.cid)
        return PendingTreePass(self, "allreduce", treedef, plan, reqs,
                               lead)

    def issue_reduce_scatter(self, tree_) -> PendingTreePass:
        """Overlapped tree REDUCE_SCATTER: each leaf's row is padded
        to ``n`` chunks and packed rank-major, one
        ``ireduce_scatter_block`` per bucket; ``wait()`` returns the
        per-leaf shard tree (leaf i -> (lead, ceil(row/n)))."""
        t0 = _time.perf_counter()
        n = self.comm.size
        treedef, leaves, lead = self._flatten(tree_)

        def rows(l: np.ndarray) -> np.ndarray:
            flat = l.reshape(lead, -1)
            pad = (-flat.shape[1]) % n
            if pad:
                flat = np.concatenate(
                    [flat, np.zeros((lead, pad), flat.dtype)], axis=1)
            return flat.reshape(lead, n, -1)

        plan = plan_from_meta([(l.shape, l.dtype) for l in leaves],
                              self._resolve(leaves))
        reqs: Dict[Any, Any] = {}
        for i in plan.big:
            reqs[("big", i)] = self.comm.ireduce_scatter_block(
                rows(leaves[i]).reshape(lead, -1))
        for k, bucket in enumerate(plan.buckets):
            packed = np.concatenate([rows(leaves[i]) for i in bucket],
                                    axis=2)
            reqs[("bucket", k)] = self.comm.ireduce_scatter_block(
                packed.reshape(lead, -1))
        _record_pass("issue_reduce_scatter", plan, t0, self.comm.cid)
        return PendingTreePass(self, "reduce_scatter", treedef, plan,
                               reqs, lead)

    def issue_allgather(self, shards, shapes) -> PendingTreePass:
        """Overlapped tree ALLGATHER of flat shards back to full
        leaves: one ``iallgather`` per bucket; ``wait()`` returns
        leaves of shape ``(lead,) + shapes[leaf]``."""
        t0 = _time.perf_counter()
        treedef, leaves, lead = self._flatten(shards)
        shape_list = [tuple(s) for s in treedef.flatten_up_to(shapes)]
        plan = plan_from_meta([(l.shape, l.dtype) for l in leaves],
                              self._resolve(leaves))
        reqs: Dict[Any, Any] = {}
        for i in plan.big:
            reqs[("big", i)] = self.comm.iallgather(
                leaves[i].reshape(lead, -1))
        for k, bucket in enumerate(plan.buckets):
            packed = np.concatenate(
                [leaves[i].reshape(lead, -1) for i in bucket], axis=1)
            reqs[("bucket", k)] = self.comm.iallgather(packed)
        _record_pass("issue_allgather", plan, t0, self.comm.cid)
        return PendingTreePass(self, "allgather", treedef, plan, reqs,
                               lead, shapes=shape_list)
