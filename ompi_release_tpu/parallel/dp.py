"""Data parallelism: bucketed gradient allreduce.

The Horovod-style pattern the reference's ring allreduce serves
(``ompi/mca/coll/tuned/coll_tuned_allreduce.c:361``): every dp replica
holds a full gradient pytree; replicas psum (or mean) them. Bucketing
mirrors the reference's segmentation decision rules
(``coll_tuned_decision_fixed.c:70-80``) — small leaves are fused into
one flat collective so per-collective latency is amortized, exactly why
tuned switches algorithms by message size. Under XLA one psum per
bucket compiles to one fused ICI collective.

The fusion decision itself (greedy in-order same-dtype packing up to a
byte capacity) is :func:`coll.fusion.plan_buckets` — ONE definition
shared with the host-driver fusion buffer (``comm.fusion_buffer()``),
so the SPMD gradient path and the driver path coalesce identically.

Two execution modes:

:func:`allreduce_gradients`
    SPMD, inside ``shard_map``: one ``lax.psum`` per bucket (XLA
    pipelines the compiled collectives itself).

:class:`GradientSync`
    HOST-DRIVER, the async-progress-engine payoff: one
    ``comm.iallreduce`` per bucket issued up front, caller compute
    overlaps the wire traffic, ``wait()`` lands at the step boundary.
    The bucket plan is built ONCE per (tree structure, shapes, dtypes,
    bucket size) and cached — the persistent-collective shape: plan
    once, fire per step. With the ``progress_thread`` cvar on, the
    engine runs the bucket schedules off the caller (true overlap,
    measured by ``nbc_hidden_seconds`` and the bench ``overlap``
    suite); in polling mode the buckets drain at ``wait()``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..mca import var as mca_var


def register_vars() -> None:
    mca_var.register(
        "dp_bucket_bytes", "int", 4 * 1024 * 1024,
        "Gradient-allreduce bucket size in bytes (small leaves are "
        "flattened+concatenated up to this size per collective)",
    )


def allreduce_gradients(grads: Any, axis_name: str, *, mean: bool = True,
                        bucket_bytes: Optional[int] = None) -> Any:
    """Allreduce a gradient pytree over the dp axis.

    Leaves smaller than ``bucket_bytes`` (default: the dp_bucket_bytes
    config variable) are packed into flat buckets so each bucket is ONE
    psum; large leaves go through psum individually (XLA already
    tiles/pipelines a single large collective well).
    """
    if bucket_bytes is None:
        bucket_bytes = mca_var.get("dp_bucket_bytes", 4 * 1024 * 1024)
    leaves, treedef = jax.tree.flatten(grads)
    n = lax.psum(1, axis_name)

    big, small = [], []  # (index, leaf)
    for i, leaf in enumerate(leaves):
        (big if leaf.size * leaf.dtype.itemsize >= bucket_bytes
         else small).append((i, leaf))

    out = [None] * len(leaves)
    for i, leaf in big:
        r = lax.psum(leaf, axis_name)
        out[i] = r / n if mean and jnp.issubdtype(leaf.dtype, jnp.inexact) else r

    # pack small leaves into flat buckets, one psum per bucket — the
    # bucket plan comes from the shared fusion planner
    from ..coll.fusion import plan_buckets

    buckets = plan_buckets(
        (((i, leaf), leaf.size * leaf.dtype.itemsize, leaf.dtype)
         for i, leaf in small),
        bucket_bytes,
    )
    for bucket in buckets:
        flat = jnp.concatenate([l.reshape(-1) for _, l in bucket])
        red = lax.psum(flat, axis_name)
        off = 0
        for i, l in bucket:
            piece = red[off:off + l.size].reshape(l.shape)
            if mean and jnp.issubdtype(l.dtype, jnp.inexact):
                piece = piece / n
            out[i] = piece
            off += l.size

    return jax.tree.unflatten(treedef, out)


class PendingGradSync:
    """In-flight overlapped gradient sync: ``wait()`` at the step
    boundary completes every bucket (one shared engine tick advances
    them all) and returns the reduced pytree. Holds only leaf
    METADATA (shape, dtype) — not the gradient copies — so issue()'s
    host staging is released for the whole overlap window."""

    def __init__(self, sync: "GradientSync", treedef,
                 meta: List[Tuple], reqs: Dict[Any, Any], plan) -> None:
        self._sync = sync
        self._treedef = treedef
        self._meta = meta  # [(shape, dtype)] per leaf
        self._reqs = reqs  # {("big", i) | ("bucket", k): Request}
        self._plan = plan

    def wait(self) -> Any:
        from ..request import request as _req

        _req.wait_all(list(self._reqs.values()))
        big, buckets = self._plan
        comm = self._sync.comm
        n = comm.size
        mean = self._sync.mean
        out: List[Any] = [None] * len(self._meta)

        def finish(i, red):
            shape, dtype = self._meta[i]
            red = np.asarray(red).reshape(shape)
            if mean and np.issubdtype(dtype, np.inexact):
                red = red / n
            out[i] = jnp.asarray(red)

        for i in big:
            finish(i, self._reqs[("big", i)].value)
        for k, bucket in enumerate(buckets):
            flat = np.asarray(self._reqs[("bucket", k)].value)
            lead = flat.shape[0]
            flat = flat.reshape(lead, -1)
            off = 0
            for i in bucket:
                shape, _ = self._meta[i]
                w = int(np.prod(shape[1:], dtype=np.int64)) \
                    if len(shape) > 1 else 1
                finish(i, flat[:, off:off + w])
                off += w
        return jax.tree.unflatten(self._treedef, out)


class GradientSync:
    """Overlapped gradient-bucket allreduce for the host-driver path.

    Buffers follow the communicator's driver convention (leading axis
    = this process's member slices). Usage per step::

        pending = sync.issue(grads)   # one iallreduce per bucket
        ... compute (fwd/bwd of the next microbatch, optimizer prep)
        new_grads = pending.wait()    # step boundary

    Bitwise parity with the blocking path is structural: each bucket
    runs the identical allreduce the blocking call would, via the
    progress engine.
    """

    def __init__(self, comm, *, mean: bool = True,
                 bucket_bytes: Optional[int] = None) -> None:
        self.comm = comm
        self.mean = mean
        self._bucket_bytes = bucket_bytes
        # (shapes/dtypes signature, bucket_bytes) -> (big, buckets);
        # the plan is built once and fired every step
        self._plans: Dict[Tuple, Tuple[List[int], List[List[int]]]] = {}

    def _plan(self, leaves: List[np.ndarray],
              bucket_bytes: int) -> Tuple[List[int], List[List[int]]]:
        key = (tuple((l.shape, str(l.dtype)) for l in leaves),
               bucket_bytes)
        plan = self._plans.get(key)
        if plan is None:
            from ..coll.fusion import plan_buckets

            big: List[int] = []
            small = []
            for i, leaf in enumerate(leaves):
                nbytes = int(leaf.size) * int(leaf.dtype.itemsize)
                if nbytes >= bucket_bytes:
                    big.append(i)
                else:
                    small.append((i, nbytes, leaf.dtype))
            buckets = plan_buckets(
                ((i, nb, str(dt)) for i, nb, dt in small),
                bucket_bytes)
            plan = self._plans[key] = (big, buckets)
        return plan

    def issue(self, grads: Any) -> PendingGradSync:
        """Issue one nonblocking allreduce per plan bucket; returns
        without completing any of them (dispatch never blocks)."""
        bucket_bytes = self._bucket_bytes
        if bucket_bytes is None:
            bucket_bytes = int(
                mca_var.get("dp_bucket_bytes", 4 * 1024 * 1024))
        leaves_raw, treedef = jax.tree.flatten(grads)
        leaves = [np.asarray(l) for l in leaves_raw]
        if not leaves or any(l.ndim == 0 for l in leaves):
            raise ValueError(
                "GradientSync needs non-empty driver-mode leaves, "
                "each with a leading (member-slice) axis — 0-d scalar "
                "leaves cannot carry the per-member axis; reshape "
                "them to (lead, 1) or drop them from the pytree")
        leads = {l.shape[0] for l in leaves}
        if len(leads) != 1:
            raise ValueError(
                "GradientSync leaves must share one leading "
                f"(member-slice) axis; got leading axes {sorted(leads)}")
        lead = leads.pop()
        big, buckets = self._plan(leaves, bucket_bytes)
        reqs: Dict[Any, Any] = {}
        for i in big:
            reqs[("big", i)] = self.comm.iallreduce(leaves[i])
        for k, bucket in enumerate(buckets):
            flat = np.concatenate(
                [leaves[i].reshape(lead, -1) for i in bucket], axis=1)
            reqs[("bucket", k)] = self.comm.iallreduce(flat)
        meta = [(l.shape, l.dtype) for l in leaves]
        return PendingGradSync(self, treedef, meta, reqs,
                               (big, buckets))


def replicate_check(x: jax.Array, axis_name: str) -> jax.Array:
    """Debug guard: max |x - bcast(x from rank0)| across the dp axis —
    the memchecker-style replica-divergence detector (SURVEY §5 race
    detection); 0 when replicas agree."""
    rank = lax.axis_index(axis_name)
    root = lax.psum(jnp.where(rank == 0, x, jnp.zeros_like(x)), axis_name)
    return lax.pmax(jnp.max(jnp.abs(x - root)), axis_name)
