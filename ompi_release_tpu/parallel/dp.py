"""Data parallelism: bucketed gradient allreduce.

The Horovod-style pattern the reference's ring allreduce serves
(``ompi/mca/coll/tuned/coll_tuned_allreduce.c:361``): every dp replica
holds a full gradient pytree; replicas psum (or mean) them. Bucketing
mirrors the reference's segmentation decision rules
(``coll_tuned_decision_fixed.c:70-80``) — small leaves are fused into
one flat collective so per-collective latency is amortized, exactly why
tuned switches algorithms by message size. Under XLA one psum per
bucket compiles to one fused ICI collective.

The fusion decision itself (greedy in-order same-dtype packing up to a
byte capacity) is :func:`coll.fusion.plan_buckets` — ONE definition
shared with the host-driver fusion buffer (``comm.fusion_buffer()``),
so the SPMD gradient path and the driver path coalesce identically.

Two execution modes:

:func:`allreduce_gradients`
    SPMD, inside ``shard_map``: one ``lax.psum`` per bucket (XLA
    pipelines the compiled collectives itself).

:class:`GradientSync`
    HOST-DRIVER, the async-progress-engine payoff: one
    ``comm.iallreduce`` per bucket issued up front, caller compute
    overlaps the wire traffic, ``wait()`` lands at the step boundary.
    The bucket plan is built ONCE per (tree structure, shapes, dtypes,
    bucket size) and cached — the persistent-collective shape: plan
    once, fire per step. With the ``progress_thread`` cvar on, the
    engine runs the bucket schedules off the caller (true overlap,
    measured by ``nbc_hidden_seconds`` and the bench ``overlap``
    suite); in polling mode the buckets drain at ``wait()``.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..mca import var as mca_var
from . import tree as _tree_mod


def register_vars() -> None:
    mca_var.register(
        "dp_bucket_bytes", "int", 4 * 1024 * 1024,
        "Gradient-allreduce bucket size in bytes (small leaves are "
        "flattened+concatenated up to this size per collective)",
    )


def allreduce_gradients(grads: Any, axis_name: str, *, mean: bool = True,
                        bucket_bytes: Optional[int] = None) -> Any:
    """Allreduce a gradient pytree over the dp axis.

    Leaves smaller than ``bucket_bytes`` (default: the dp_bucket_bytes
    config variable / tree_buckets tuned rules) are packed into flat
    buckets so each bucket is ONE psum; large leaves go through psum
    individually (XLA already tiles/pipelines a single large
    collective well). The planned pass itself is
    :func:`parallel.tree.tree_allreduce` — one planner, one plan
    cache, one packing layout for every tree-shaped collective.
    """
    # bucket_bytes=None resolves inside the tree pass through the
    # shared precedence (tree_buckets tuned rules > tree_bucket_bytes
    # > dp_bucket_bytes) — resolving here would bypass the rules
    return _tree_mod.tree_allreduce(grads, axis_name, mean=mean,
                                    bucket_bytes=bucket_bytes)


class GradientSync(_tree_mod.TreeSync):
    """Overlapped gradient-bucket allreduce for the host-driver path —
    the ALLREDUCE specialization of :class:`parallel.tree.TreeSync`
    (which also drives whole-tree reduce-scatter and allgather).

    Buffers follow the communicator's driver convention (leading axis
    = this process's member slices). Usage per step::

        pending = sync.issue(grads)   # one iallreduce per bucket
        ... compute (fwd/bwd of the next microbatch, optimizer prep)
        new_grads = pending.wait()    # step boundary

    Bitwise parity with the blocking path is structural: each bucket
    runs the identical allreduce the blocking call would, via the
    progress engine.
    """

    def __init__(self, comm, *, mean: bool = True,
                 bucket_bytes: Optional[int] = None) -> None:
        # bucket_bytes=None resolves per issue() through the shared
        # precedence (tree_buckets rules > tree_bucket_bytes >
        # dp_bucket_bytes), so runtime cvar tuning still applies
        super().__init__(comm, mean=mean, bucket_bytes=bucket_bytes)


#: back-compat alias: the pending handle is the shared tree-pass one
PendingGradSync = _tree_mod.PendingTreePass


def replicate_check(x: jax.Array, axis_name: str) -> jax.Array:
    """Debug guard: max |x - bcast(x from rank0)| across the dp axis —
    the memchecker-style replica-divergence detector (SURVEY §5 race
    detection); 0 when replicas agree."""
    rank = lax.axis_index(axis_name)
    root = lax.psum(jnp.where(rank == 0, x, jnp.zeros_like(x)), axis_name)
    return lax.pmax(jnp.max(jnp.abs(x - root)), axis_name)
