"""ZeRO-style sharded optimizer state over the dp axis.

The reduce_scatter_block pattern (``coll_tuned_reduce_scatter.c``;
BASELINE.json config #4 "ZeRO-style gradient shard"): instead of every
dp replica allreducing and holding full gradients + optimizer state,
gradients are reduce_scattered so each replica owns 1/n of them,
updates its shard, and all_gathers fresh params — same total ICI bytes
as allreduce (reduce_scatter + allgather IS the ring allreduce), but
optimizer memory drops by n.

Both legs now run as PLANNED whole-tree passes through
:mod:`parallel.tree` (one fused ``psum_scatter`` / ``all_gather`` per
bucket instead of one per leaf), bitwise-identical to the per-leaf
loop (``bucket_bytes=0``) — the fused buffers pack a rank-major
interleaved layout, so every element lands on the same rank in the
same slot as the per-leaf scatter.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from . import tree as _tree_mod


def _pad_len(size: int, n: int) -> int:
    return (-size) % n


def shard_gradients(grads: Any, axis_name: str, *, mean: bool = True,
                    bucket_bytes: Optional[int] = None) -> Any:
    """reduce_scatter every leaf over dp: returns rank's flat shard pytree
    (leaf i -> 1-D array of ceil(size/n) elements), one planned fused
    pass over the whole tree."""
    return _tree_mod.tree_reduce_scatter(grads, axis_name, mean=mean,
                                         bucket_bytes=bucket_bytes)


def unshard_params(param_shards: Any, shapes: Any, axis_name: str, *,
                   bucket_bytes: Optional[int] = None) -> Any:
    """all_gather each flat shard back to the full (reshaped) leaf, one
    planned fused pass over the whole tree."""
    return _tree_mod.tree_allgather(param_shards, shapes, axis_name,
                                    bucket_bytes=bucket_bytes)


def shard_like(params: Any, axis_name: str) -> Any:
    """Slice each leaf to this rank's flat shard (for building sharded
    optimizer state at init)."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)

    def sl(p):
        flat = p.reshape(-1)
        pad = _pad_len(flat.size, n)
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), p.dtype)])
        chunk = flat.size // n
        return lax.dynamic_slice_in_dim(flat, idx * chunk, chunk)

    return jax.tree.map(sl, params)


def zero_step(params: Any, grads: Any, opt_state_shards: Any, opt_update,
              axis_name: str, *,
              bucket_bytes: Optional[int] = None) -> Tuple[Any, Any]:
    """One ZeRO-1 step: shard grads, update the owned shard, regather —
    both collective legs ride the planned tree pass.

    ``opt_update(grad_shard_tree, state_shards, param_shard_tree)`` must
    follow optax's transform signature over the flat-shard pytrees.
    """
    gshards = shard_gradients(grads, axis_name,
                              bucket_bytes=bucket_bytes)
    pshards = shard_like(params, axis_name)
    updates, new_state = opt_update(gshards, opt_state_shards, pshards)
    new_pshards = jax.tree.map(lambda p, u: p + u, pshards, updates)
    shapes = jax.tree.map(lambda p: p.shape, params)
    return unshard_params(new_pshards, shapes, axis_name,
                          bucket_bytes=bucket_bytes), new_state
