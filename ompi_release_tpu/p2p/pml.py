"""Host PML — dynamic (rank, tag, comm) matching over device transfers.

The ob1 engine's structure (``ompi/mca/pml/ob1/``) kept where it still
carries meaning on TPU, dropped where it does not:

- KEPT: the matching machinery — per-(comm, rank) posted-recv queues
  and unexpected queues with MPI ordering and ANY_SOURCE/ANY_TAG
  wildcards (``pml_ob1_recvfrag.c:106,502,550`` match_one/unexpected);
  protocol selection by message size (eager / rendezvous / pipelined,
  ``pml_ob1_sendreq.c:480,785``) with btl-style size variables.
- REIMAGINED: "wire transfer" is a device-to-device array move managed
  by the runtime (ICI within a slice, DCN across). Eager = move at
  send time (sender's HBM freed early); rendezvous = move only when
  the matching recv posts (receiver-side pull, the RGET analogue);
  pipelined = segmented moves for buffers over max_send so segments
  overlap (``btl_rdma_pipeline`` analogue).
- DROPPED: byte-level fragments/progress polling — jax arrays are
  immutable futures, so completion is array readiness, not FIFO polls.
"""

from __future__ import annotations

import collections
import threading
import time as _time
from typing import Any, Deque, Dict, List, Optional, Tuple

from .. import obs as _obs
from ..mca import component as mca_component
from ..obs import watchdog as _watchdog
from ..mca import pvar
from ..mca import var as mca_var
from ..request.request import Request, Status
from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.stream("pml")

ANY_SOURCE = -1
ANY_TAG = -1

_unexpected_count = pvar.counter(
    "pml_unexpected_msgs", "sends queued before a matching recv was posted"
)
_eager_count = pvar.counter("pml_eager_sends", "eager-protocol sends")
_rndv_count = pvar.counter("pml_rndv_sends", "rendezvous-protocol sends")
_pipeline_count = pvar.counter(
    "pml_pipelined_sends", "segmented (pipelined) large sends"
)

PML_FRAMEWORK = mca_component.framework(
    "pml", "point-to-point management (ompi/mca/pml analogue)"
)


def _as_device_payload(data):
    """Convert a send payload to a device array, turning the raw jax
    TypeError for structured/byte-string data into MPI's own answer:
    describe it with a Datatype and pack it to a numeric buffer (the
    reference never sends raw C structs either — ``MPI_Type_struct``
    + pack/unpack is the contract)."""
    import jax.numpy as jnp

    try:
        return jnp.asarray(data)
    except TypeError as e:
        raise MPIError(
            ErrorCode.ERR_TYPE,
            f"p2p payload of type {type(data).__name__} is not a "
            "numeric array; describe structured/byte data with a "
            "datatype and pack it (datatype.pack / Convertor) before "
            f"sending, then unpack at the receiver ({e})",
        )


def register_vars() -> None:
    mca_var.register(
        "pml_eager_limit", "size", 0,
        "Override: messages up to this many bytes move at send time; "
        "0 = use the selected btl endpoint's eager_limit "
        "(btl_tcp_component.c:268 analogue)",
    )
    mca_var.register(
        "pml_max_send_size", "size", 0,
        "Override: messages beyond this many bytes move as overlapping "
        "segments; 0 = use the btl endpoint's max_send_size "
        "(btl.h:802 rdma pipeline)",
    )
    mca_var.register(
        "pml_wire_timeout", "float", 30.0,
        "Seconds a blocking cross-process recv/ssend waits for its "
        "match over the wire before raising ERR_PENDING (raise it for "
        "jobs with long compute phases between communication)",
    )


class _SendEntry:
    """A send awaiting (or delivering to) its match."""

    __slots__ = ("src", "dst", "tag", "data", "request", "sync",
                 "transferred")

    def __init__(self, src, dst, tag, data, request, sync) -> None:
        self.src = src
        self.dst = dst
        self.tag = tag
        self.data = data
        self.request = request
        self.sync = sync  # ssend: complete only on match
        self.transferred = False


class _RecvEntry:
    __slots__ = ("dst", "source", "tag", "request")

    def __init__(self, dst, source, tag, request) -> None:
        self.dst = dst
        self.source = source
        self.tag = tag
        self.request = request


def _tag_match(posted_tag: int, tag: int) -> bool:
    return posted_tag == ANY_TAG or posted_tag == tag


class PmlEngine:
    """Per-communicator matching engine (single-controller: it sees all
    ranks' posts, so matching is a local queue operation; the reference
    does the same work after the wire delivers the MATCH header)."""

    def __init__(self, comm) -> None:
        self.comm = comm
        self._lock = threading.RLock()
        # per destination rank: unexpected sends (FIFO — MPI ordering)
        self._unexpected: Dict[int, Deque[_SendEntry]] = (
            collections.defaultdict(collections.deque)
        )
        # per destination rank: posted recvs (FIFO)
        self._posted: Dict[int, Deque[_RecvEntry]] = (
            collections.defaultdict(collections.deque)
        )
        self._logger = None  # vprotocol message log, when attached
        # per-peer transfer plans through the btl framework (bml/r2)
        from ..btl import BmlR2

        self._bml = BmlR2(comm)

    # -- helpers -----------------------------------------------------------
    def _purge_cancelled(self, dst: int) -> None:
        """Drop cancelled entries so they never match a live message
        (MPI_Cancel semantics: a cancelled recv must not consume a
        send, and vice versa)."""
        self._posted[dst] = collections.deque(
            r for r in self._posted[dst] if not r.request.is_cancelled
        )
        self._unexpected[dst] = collections.deque(
            s for s in self._unexpected[dst] if not s.request.is_cancelled
        )

    def _check_rank(self, r: int, what: str) -> None:
        if not 0 <= r < self.comm.size:
            raise MPIError(
                ErrorCode.ERR_RANK,
                f"{what} rank {r} out of range on {self.comm.name}",
            )

    def _nbytes(self, data) -> int:
        return int(data.size * data.dtype.itemsize)

    def _eager_limit(self, src_rank: int, dst_rank: int) -> int:
        """Per-peer eager threshold: pml override, else the btl
        endpoint's (ob1 reads the btl's eager size the same way)."""
        override = mca_var.get("pml_eager_limit", 0)
        if override:
            return int(override)
        return self._bml.endpoint(src_rank, dst_rank).eager_limit

    def _move(self, data, src_rank: int, dst_rank: int):
        """Transfer through the per-peer BML endpoint: the btl
        framework picks the fabric (self/ici/dcn/host) and segments
        beyond max_send_size so segments overlap in flight."""
        ep = self._bml.endpoint(src_rank, dst_rank)
        max_send = int(mca_var.get("pml_max_send_size", 0)) or None
        return ep.move(data, max_send=max_send,
                       on_pipeline=_pipeline_count.add)

    # -- send --------------------------------------------------------------
    def isend(self, data, dst: int, tag: int = 0, *, src: int,
              sync: bool = False, ready: bool = False) -> Request:
        """Nonblocking send from rank ``src`` to rank ``dst``.

        sync=True  -> ssend: completes only when matched.
        ready=True -> rsend: raises unless a matching recv is posted.
        """
        import jax.numpy as jnp

        self._check_rank(dst, "destination")
        self._check_rank(src, "source")
        data = _as_device_payload(data)
        if _obs.enabled:  # instant emit point: the send posting itself
            _obs.record("isend", "pml", _time.perf_counter(), 0.0,
                        nbytes=self._nbytes(data), peer=dst,
                        comm_id=self.comm.cid)
        req = Request()
        entry = _SendEntry(src, dst, tag, data, req, sync)
        from . import peruse

        peruse.fire(self.comm, peruse.REQ_ACTIVATE, kind="send",
                    src=src, dst=dst, tag=tag)
        with self._lock:
            if self._logger is not None:
                # logged UNDER the matching lock like recv postings:
                # the log's event order must equal the queue order or
                # replay swaps same-(src, tag) deliveries
                self._logger.record(src, dst, tag, data, sync)
            self._purge_cancelled(dst)
            posted = self._posted[dst]
            match = next(
                (r for r in posted
                 if (r.source in (ANY_SOURCE, src))
                 and _tag_match(r.tag, tag)),
                None,
            )
            if match is not None:
                posted.remove(match)
                self._deliver(entry, match)
                return req
            if ready:
                raise MPIError(
                    ErrorCode.ERR_PENDING,
                    f"rsend with no posted recv (src={src} dst={dst} "
                    f"tag={tag})",
                )
            if self._nbytes(data) <= self._eager_limit(src, dst):
                # eager: move now; sender side is complete immediately
                _eager_count.add()
                entry.data = self._move(data, src, dst)
                entry.transferred = True
                if not sync:
                    req.complete(status=Status(source=src, tag=tag))
            else:
                # rendezvous: hold the (immutable) buffer; the move
                # happens when the matching recv posts
                _rndv_count.add()
            _unexpected_count.add()
            self._unexpected[dst].append(entry)
        peruse.fire(self.comm, peruse.MSG_UNEX_INSERT, src=src, dst=dst,
                    tag=tag)
        return req

    def send(self, data, dst: int, tag: int = 0, *, src: int,
             sync: bool = False) -> None:
        """Blocking send. MPI_Send may return once the buffer is
        reusable; jax arrays are immutable so that is ALWAYS true — a
        plain blocking send never blocks (bsend-like), regardless of
        the eager/rendezvous data-movement protocol. Only ssend
        (sync=True) must wait for the match, which in single-controller
        driver mode requires the recv to already be posted.
        """
        req = self.isend(data, dst, tag, src=src, sync=sync)
        if sync:
            req.wait()

    # -- recv --------------------------------------------------------------
    def irecv(self, source: int = ANY_SOURCE, tag: int = ANY_TAG, *,
              dst: int) -> Request:
        """Nonblocking receive posted by rank ``dst``."""
        self._check_rank(dst, "destination")
        if source != ANY_SOURCE:
            self._check_rank(source, "source")
        if _obs.enabled:
            _obs.record("irecv", "pml", _time.perf_counter(), 0.0,
                        peer=source, comm_id=self.comm.cid)
        req = Request()
        entry = _RecvEntry(dst, source, tag, req)
        from . import peruse

        peruse.fire(self.comm, peruse.REQ_ACTIVATE, kind="recv",
                    src=source, dst=dst, tag=tag)
        with self._lock:
            if self._logger is not None:
                # pessimist determinant: logged UNDER the matching
                # lock so the event order equals the match order
                # (concurrent posters would otherwise log in a
                # different order than they match — replay would
                # swap their deliveries); the matched (src, tag) is
                # filled in at completion
                self._logger.record_recv_post(dst, source, tag, req)
            self._purge_cancelled(dst)
            unex = self._unexpected[dst]
            match = next(
                (s for s in unex
                 if (source in (ANY_SOURCE, s.src))
                 and _tag_match(tag, s.tag)),
                None,
            )
            if match is not None:
                unex.remove(match)
                peruse.fire(self.comm, peruse.REQ_MATCH_UNEX,
                            src=match.src, dst=dst, tag=match.tag)
                self._deliver(match, entry)
            else:
                self._posted[dst].append(entry)
        return req

    def recv(self, source: int = ANY_SOURCE, tag: int = ANY_TAG, *,
             dst: int) -> Tuple[Any, Status]:
        req = self.irecv(source, tag, dst=dst)
        st = req.wait()
        return req.value, st

    # -- probe -------------------------------------------------------------
    def iprobe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG, *,
               dst: int) -> Optional[Status]:
        """Nonblocking probe of the unexpected queue (MPI_Iprobe)."""
        with self._lock:
            self._purge_cancelled(dst)
            for s in self._unexpected[dst]:
                if (source in (ANY_SOURCE, s.src)) and _tag_match(tag, s.tag):
                    return Status(source=s.src, tag=s.tag,
                                  count=int(s.data.size))
        return None

    # -- matched probe (MPI_Mprobe / MPI_Mrecv) ----------------------------
    def improbe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG, *,
                dst: int):
        """Nonblocking matched probe: removes the matched message from
        the unexpected queue and returns a message handle (so a later
        wildcard recv cannot steal it); None when nothing matches."""
        with self._lock:
            self._purge_cancelled(dst)
            unex = self._unexpected[dst]
            match = next(
                (s for s in unex
                 if (source in (ANY_SOURCE, s.src))
                 and _tag_match(tag, s.tag)),
                None,
            )
            if match is None:
                return None
            unex.remove(match)
            if self._logger is not None:
                # improbe IS the nondeterministic match decision the
                # pessimist log exists to capture; without this the
                # restarted consumer would silently be delivered one
                # message fewer
                self._logger.record_matched_recv(
                    dst, source, tag, match.src, match.tag
                )
            return match  # the message handle

    def mrecv(self, message: "_SendEntry", *, dst: int):
        """Receive a message handle returned by improbe."""
        entry = _RecvEntry(dst, message.src, message.tag, Request())
        self._deliver(message, entry)
        return entry.request.value, entry.request.status

    def dump_queues(self, lock_timeout_s: float = 0.5) -> Dict[str, list]:
        """Debugger message-queue dump (the TotalView DLL contract,
        ``ompi/debuggers``): every pending send/recv with its
        match envelope. Lock acquisition is BOUNDED: the flight
        recorder calls this while diagnosing hangs, and a thread
        wedged inside a match-lock critical section (e.g. a
        rendezvous pull whose peer died) must not hang the dump."""
        if not self._lock.acquire(timeout=lock_timeout_s):
            return {"unexpected": [], "posted": [],
                    "error": "match lock held (a thread is wedged "
                             "inside the matching engine)"}
        try:
            for dst in set(self._unexpected) | set(self._posted):
                self._purge_cancelled(dst)
            return {
                "unexpected": [
                    {"src": s.src, "dst": s.dst, "tag": s.tag,
                     "bytes": self._nbytes(s.data),
                     "protocol": "eager" if s.transferred else "rndv"}
                    for q in self._unexpected.values() for s in q
                ],
                "posted": [
                    {"dst": r.dst, "source": r.source, "tag": r.tag}
                    for q in self._posted.values() for r in q
                ],
            }
        finally:
            self._lock.release()

    # -- persistent --------------------------------------------------------
    def send_init(self, data, dst: int, tag: int = 0, *, src: int) -> Request:
        def start(req):
            inner = self.isend(data, dst, tag, src=src)
            inner.on_complete(
                lambda r: req.complete(status=r.status)
            )

        return Request(persistent_start=start)

    def recv_init(self, source: int = ANY_SOURCE, tag: int = ANY_TAG, *,
                  dst: int) -> Request:
        def start(req):
            inner = self.irecv(source, tag, dst=dst)
            inner.on_complete(
                lambda r: req.complete(value=r.value, status=r.status)
            )

        return Request(persistent_start=start)

    # -- delivery ----------------------------------------------------------
    def _deliver(self, send: _SendEntry, recv: _RecvEntry) -> None:
        from . import peruse

        rec = _obs.enabled  # capture once: flag may flip mid-delivery
        t0 = _time.perf_counter() if rec else 0.0
        data = send.data
        if not send.transferred:
            peruse.fire(self.comm, peruse.REQ_XFER_BEGIN, src=send.src,
                        dst=recv.dst, tag=send.tag)
            data = self._move(data, send.src, recv.dst)  # rendezvous pull
        st = Status(source=send.src, tag=send.tag, count=int(data.size))
        recv.request.complete(value=data, status=st)
        send.request.complete(status=Status(source=send.src, tag=send.tag))
        peruse.fire(self.comm, peruse.REQ_XFER_END, src=send.src,
                    dst=recv.dst, tag=send.tag, count=int(data.size))
        peruse.fire(self.comm, peruse.REQ_COMPLETE, src=send.src,
                    dst=recv.dst, tag=send.tag)
        if rec and _obs.enabled:  # matched delivery incl. rndv pull
            _obs.record("deliver", "pml", t0, _time.perf_counter() - t0,
                        nbytes=self._nbytes(data), peer=send.src,
                        comm_id=self.comm.cid)
        _log.verbose(
            3,
            f"{self.comm.name}: delivered src={send.src} dst={send.dst} "
            f"tag={send.tag} n={data.size}",
        )

    # -- teardown ----------------------------------------------------------
    def pending_counts(self) -> Tuple[int, int]:
        with self._lock:
            for dst in set(self._unexpected) | set(self._posted):
                self._purge_cancelled(dst)
            return (
                sum(len(q) for q in self._unexpected.values()),
                sum(len(q) for q in self._posted.values()),
            )


class WirePmlEngine(PmlEngine):
    """PML for communicators spanning controller processes: local pairs
    use the in-process matching machinery unchanged; pairs crossing a
    process boundary ride the runtime's wire router (shm handoff on one
    host, DCN staging across hosts) — the ``btl/tcp``-under-ob1 role,
    with no caller-visible API difference (``btl_tcp_component.c:883``).

    Driver-mode contract: each process acts only as its LOCAL ranks —
    an isend must name a local ``src``, a recv a local ``dst``. Wire
    arrivals are pumped into the normal unexpected queues during
    recv/probe progress, so ordering, ANY_SOURCE/ANY_TAG and matched
    probes keep their MPI semantics across the boundary.
    """

    def __init__(self, comm) -> None:
        super().__init__(comm)
        self._router = comm.runtime.wire
        self._local_set = set(comm.local_comm_ranks)

    def _require_local(self, rank: int, what: str) -> None:
        if rank not in self._local_set:
            owner = self._router.owner_of(self.comm.group.world_rank(rank))
            raise MPIError(
                ErrorCode.ERR_RANK,
                f"{what} rank {rank} on {self.comm.name} is owned by "
                f"process {owner}; each process acts only as its local "
                "ranks (the acting-rank driver convention)",
            )

    # -- send --------------------------------------------------------------
    def isend(self, data, dst: int, tag: int = 0, *, src: int,
              sync: bool = False, ready: bool = False) -> Request:
        self._check_rank(dst, "destination")
        self._check_rank(src, "source")
        self._require_local(src, "acting source")
        if dst in self._local_set:
            return super().isend(data, dst, tag, src=src, sync=sync,
                                 ready=ready)
        # cross-process: rsend legally degrades to a standard send (an
        # implementation MAY treat ready mode as standard; verifying
        # the remote posted-recv would cost a round trip)
        data = _as_device_payload(data)
        from . import peruse

        peruse.fire(self.comm, peruse.REQ_ACTIVATE, kind="send",
                    src=src, dst=dst, tag=tag)
        if self._logger is not None:
            with self._lock:
                self._logger.record(src, dst, tag, data, sync)
        import numpy as _np

        seq = self._router.send_p2p(self.comm, src, dst, tag,
                                    _np.asarray(data), sync)
        if not sync:
            req = Request()
            req.complete(status=Status(source=src, tag=tag))
            return req
        # ssend: completes when the receiver's match acks back
        router, cid = self._router, self.comm.cid
        src_world = self.comm.group.world_rank(src)

        def progress(r) -> None:
            router.poll_acks(src_world)
            if router.has_ack(cid, seq):
                router.take_ack(cid, seq)
                r.complete(status=Status(source=src, tag=tag))

        def block() -> None:
            import time as _time

            tok = None
            if _watchdog.enabled:
                tok = _watchdog.arm(
                    "p2p_ssend_ack", comm_id=cid, peer=dst,
                    info={"src": src, "dst": dst, "tag": tag,
                          "seq": seq},
                )
            try:
                limit = float(mca_var.get("pml_wire_timeout", 30.0))
                deadline = _time.monotonic() + limit
                while _time.monotonic() < deadline:
                    router.poll_acks(src_world, timeout_ms=100)
                    if router.take_ack(cid, seq):
                        return
                raise MPIError(
                    ErrorCode.ERR_PENDING,
                    f"ssend to rank {dst} never matched (no ack within "
                    f"{limit}s; pml_wire_timeout raises the limit)",
                )
            finally:
                if tok is not None:
                    _watchdog.disarm(tok)

        req = Request(progress_fn=progress, block_fn=block)
        # the block() completion path reaches Request.wait()'s bare
        # complete(): pre-set the status so both completion paths
        # report the same (source, tag)
        req.status = Status(source=src, tag=tag)
        return req

    # -- recv --------------------------------------------------------------
    def _drain(self, dst: int, timeout_ms: int = 0) -> bool:
        return self._router.drain_p2p(
            self.comm.group.world_rank(dst), timeout_ms=max(1, timeout_ms)
        )

    def irecv(self, source: int = ANY_SOURCE, tag: int = ANY_TAG, *,
              dst: int) -> Request:
        self._check_rank(dst, "destination")
        self._require_local(dst, "receiving")
        may_cross = source == ANY_SOURCE or source not in self._local_set
        if may_cross:
            # pump anything already queued before posting, so an
            # earlier wire arrival matches in order
            while self._drain(dst):
                pass
        req = super().irecv(source, tag, dst=dst)
        if may_cross and not req.is_complete:
            engine = self

            def progress(r) -> None:
                engine._drain(dst)

            def block() -> None:
                import time as _time

                tok = None
                if _watchdog.enabled:
                    tok = _watchdog.arm(
                        "p2p_recv", comm_id=engine.comm.cid,
                        peer=source,
                        info={"source": source, "tag": tag, "dst": dst},
                    )
                try:
                    from ..ft import ulfm as _ulfm
                    from ..runtime.wire import proc_topology

                    comm = engine.comm
                    if source == ANY_SOURCE:
                        ft_peers = list(proc_topology(comm).peers)
                    else:
                        ft_peers = [proc_topology(comm).owner[source]]
                    limit = float(mca_var.get("pml_wire_timeout", 30.0))
                    deadline = _time.monotonic() + limit
                    while (not req.is_complete
                           and _time.monotonic() < deadline):
                        # ULFM bound: a recv whose (possible) sender
                        # died — or whose comm was revoked — raises
                        # the typed error within one drain slice, not
                        # after the full pml_wire_timeout
                        _ulfm.state().check_wait(
                            comm.cid, ft_peers,
                            f"p2p recv(source={source}) awaiting",
                            epoch0=getattr(comm, "_ft_epoch0", 0))
                        engine._drain(dst, timeout_ms=100)
                    if not req.is_complete:
                        raise MPIError(
                            ErrorCode.ERR_PENDING,
                            f"recv(source={source}, tag={tag}) at rank "
                            f"{dst}: no matching message within "
                            f"{limit}s (pml_wire_timeout raises the "
                            "limit)",
                        )
                finally:
                    if tok is not None:
                        _watchdog.disarm(tok)

            req._progress_fn = progress
            req._block_fn = block
        return req

    def iprobe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG, *,
               dst: int):
        self._require_local(dst, "probing")
        while self._drain(dst):
            pass
        return super().iprobe(source, tag, dst=dst)

    def improbe(self, source: int = ANY_SOURCE, tag: int = ANY_TAG, *,
                dst: int):
        self._require_local(dst, "probing")
        while self._drain(dst):
            pass
        return super().improbe(source, tag, dst=dst)

    # -- wire delivery (called by the router's drain) ----------------------
    def _enqueue_wire(self, src_rank: int, dst_rank: int, user_tag: int,
                      data, on_matched=None) -> None:
        """Insert one wire arrival into the matching machinery exactly
        where a local eager send would land (payload already moved, so
        the entry is 'transferred')."""
        from . import peruse

        req = Request()
        if on_matched is not None:
            req.on_complete(on_matched)
        entry = _SendEntry(src_rank, dst_rank, user_tag, data, req, False)
        entry.transferred = True
        with self._lock:
            if self._logger is not None:
                # a wire arrival IS a send landing in this process's
                # queues: log it under the matching lock exactly like a
                # local isend, or pessimist-log replay would deliver
                # fewer messages than the original run
                self._logger.record(src_rank, dst_rank, user_tag, data,
                                    False)
            self._purge_cancelled(dst_rank)
            posted = self._posted[dst_rank]
            match = next(
                (r for r in posted
                 if (r.source in (ANY_SOURCE, src_rank))
                 and _tag_match(r.tag, user_tag)),
                None,
            )
            if match is not None:
                posted.remove(match)
                self._deliver(entry, match)
                return
            _unexpected_count.add()
            self._unexpected[dst_rank].append(entry)
        peruse.fire(self.comm, peruse.MSG_UNEX_INSERT, src=src_rank,
                    dst=dst_rank, tag=user_tag)


class Ob1TpuComponent(mca_component.Component):
    """Default PML component ("ob1" kept as the name users know)."""

    NAME = "ob1"
    PRIORITY = 20

    def register_vars(self) -> None:
        register_vars()

    def query(self, ctx=None):
        if ctx is None:
            return (self.priority, self)
        if getattr(ctx, "spans_processes", False):
            return (self.priority, WirePmlEngine(ctx))
        return (self.priority, PmlEngine(ctx))


PML_FRAMEWORK.register(Ob1TpuComponent())


def comm_select(comm) -> PmlEngine:
    """Install the per-comm PML engine (mca_pml_base_select analogue)."""
    avail = PML_FRAMEWORK.available(comm)
    if not avail:
        raise MPIError(ErrorCode.ERR_NOT_AVAILABLE,
                       "no PML component available")
    _, comp, engine = avail[0]
    _log.verbose(2, f"{comm.name}: pml -> {comp.NAME}")
    return engine
