"""Sender-based message logging — the vprotocol/pessimist analogue.

The reference's pessimistic message-logging FT
(``ompi/mca/vprotocol/pessimist/vprotocol_pessimist.h:19-35``) keeps a
sender-side payload log + event order so a restarted process can be
fed exactly the messages it saw. Driver-mode recast: attach a logger
to a communicator's PML and every send is recorded (payload handles
are immutable jax arrays — the log IS the sender-based payload log);
``replay`` re-issues them in order against a fresh engine, and the
deterministic matching engine reproduces the original delivery order.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional

from ..mca import pvar
from ..utils import output

_log = output.stream("vprotocol")
_logged = pvar.counter("vprotocol_logged_sends", "sends captured in the log")


@dataclasses.dataclass
class LoggedSend:
    seq: int
    src: int
    dst: int
    tag: int
    data: Any
    sync: bool


class MessageLog:
    def __init__(self) -> None:
        self.events: List[LoggedSend] = []

    def record(self, src: int, dst: int, tag: int, data, sync: bool
               ) -> None:
        _logged.add()
        self.events.append(
            LoggedSend(len(self.events), src, dst, tag, data, sync)
        )

    def replay(self, pml) -> int:
        """Re-issue every logged send in order on ``pml``; the
        deterministic matching engine reproduces delivery order."""
        for ev in self.events:
            pml.isend(ev.data, ev.dst, ev.tag, src=ev.src, sync=False)
        return len(self.events)

    def clear(self) -> None:
        self.events.clear()


def attach(comm) -> MessageLog:
    """Enable pessimistic send logging on this communicator's PML."""
    log = MessageLog()
    comm.pml._logger = log
    return log


def detach(comm) -> None:
    pml = getattr(comm, "_pml", None)
    if pml is not None:
        pml._logger = None
