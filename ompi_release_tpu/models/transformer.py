"""TpuLM — the flagship decoder-only transformer, SPMD over the full
5-axis mesh (dp, pp, sp, ep, tp from ``parallel.mesh_axes``).

Every parallelism strategy of SURVEY §2.4 is load-bearing here:

  - batch sharded over (dp, ep); gradients of replicated params are
    psummed by shard_map's replication-tracking transpose (the ring
    allreduce of coll_tuned_allreduce.c:361, inserted by XLA)
  - trunk layers sharded over pp and pipelined with microbatch
    ppermute rings (``parallel.pp``)
  - sequence sharded over sp; attention is exact ring attention
    (``parallel.cp``) with RoPE carrying global positions
  - attention heads / FFN / vocab sharded over tp (``parallel.tp``)
  - optional switch-MoE FFN with experts sharded over ep
    (``parallel.ep``)

Pure-functional params (plain dict pytree), bf16 activations / f32
accumulation by default for the MXU.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import jaxcompat as _jaxcompat

_jaxcompat.install()  # jax.shard_map/typeof on 0.4.x jaxlibs

from ..parallel import cp, ep as ep_mod, pp as pp_mod, tp as tp_mod
from ..parallel import tree as tree_mod


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    vocab: int = 32000
    d_model: int = 512
    n_layers: int = 8
    n_heads: int = 8
    head_dim: int = 64
    d_ff: int = 2048
    max_seq: int = 2048
    n_experts: int = 0  # 0 = dense FFN; >0 = switch-MoE every layer
    capacity_factor: float = 1.25
    microbatches: int = 1  # per-rank microbatch count for the pp schedule
    remat: bool = False  # jax.checkpoint the pipelined trunk (trade
    #                      recompute for activation memory)
    dtype: Any = jnp.bfloat16
    rope_base: float = 10000.0
    # attention implementation: "auto" = Pallas flash kernel on TPU when
    # the sequence is unsharded, ring attention otherwise; "ring" /
    # "flash" force one path (flash runs interpreted off-TPU)
    attn_impl: str = "auto"

    def validate(self, mesh: Mesh) -> None:
        ax = dict(mesh.shape)
        if self.n_layers % ax.get("pp", 1):
            raise ValueError("n_layers must divide by pp")
        if self.n_heads % ax.get("tp", 1):
            raise ValueError("n_heads must divide by tp")
        if self.vocab % ax.get("tp", 1):
            raise ValueError("vocab must divide by tp")
        if self.d_ff % ax.get("tp", 1):
            raise ValueError("d_ff must divide by tp")
        if self.n_experts and self.n_experts % ax.get("ep", 1):
            raise ValueError("n_experts must divide by ep")


# ---------------------------------------------------------------------------
# params
# ---------------------------------------------------------------------------

def init_params(rng: jax.Array, cfg: ModelConfig) -> Dict:
    """Global (unsharded) parameter pytree; shard with param_specs."""
    k = jax.random.split(rng, 10)
    d, l = cfg.d_model, cfg.n_layers
    hdim = cfg.n_heads * cfg.head_dim
    dt = cfg.dtype

    def norm(key, *shape, scale=None):
        scale = scale if scale is not None else 1.0 / math.sqrt(shape[-2])
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dt)

    params = {
        "embed": norm(k[0], cfg.vocab, d, scale=0.02),
        "ln_f": jnp.ones((d,), jnp.float32),
        "layers": {
            "ln1": jnp.ones((l, d), jnp.float32),
            "wq": norm(k[1], l, d, hdim),
            "wk": norm(k[2], l, d, hdim),
            "wv": norm(k[3], l, d, hdim),
            "wo": norm(k[4], l, hdim, d),
            "ln2": jnp.ones((l, d), jnp.float32),
        },
    }
    if cfg.n_experts:
        params["layers"]["router"] = norm(
            k[5], l, d, cfg.n_experts, scale=0.02
        ).astype(jnp.float32)
        params["layers"]["we1"] = norm(k[6], l, cfg.n_experts, d, cfg.d_ff)
        params["layers"]["we2"] = norm(k[7], l, cfg.n_experts, cfg.d_ff, d)
    else:
        params["layers"]["w1"] = norm(k[6], l, d, cfg.d_ff)
        params["layers"]["w2"] = norm(k[7], l, cfg.d_ff, d)
    return params


#: regex partition rules, first match wins — the user-facing sharding
#: interface (``parallel.tree.match_partition_rules``): which mesh
#: axis owns which tensor dimension, keyed by parameter path name.
#: Scalar/single-element leaves are never partitioned (the planner's
#: fmengine rule), so the table only needs the real tensors.
PARTITION_RULES = (
    (r"^embed$", P("tp", None)),
    (r"^ln_f$", P()),
    (r"layers/ln[12]$", P("pp", None)),
    (r"layers/w[qkv]$", P("pp", None, "tp")),
    (r"layers/wo$", P("pp", "tp", None)),
    (r"layers/router$", P("pp", None, None)),
    (r"layers/we[12]$", P("pp", "ep", None, None)),
    (r"layers/w1$", P("pp", None, "tp")),
    (r"layers/w2$", P("pp", "tp", None)),
)


def param_specs(cfg: ModelConfig) -> Dict:
    """PartitionSpecs matching init_params' structure, derived by
    matching :data:`PARTITION_RULES` against an abstract parameter
    skeleton (``jax.eval_shape`` — no arrays materialize). An
    unmatched leaf raises at build time, so adding a parameter without
    a rule cannot silently default to replicated."""
    skeleton = jax.eval_shape(
        lambda: init_params(jax.random.PRNGKey(0), cfg))
    return tree_mod.match_partition_rules(PARTITION_RULES, skeleton)


def batch_spec() -> P:
    return P(("dp", "ep"), "sp")


# ---------------------------------------------------------------------------
# layers (per-rank SPMD code)
# ---------------------------------------------------------------------------

def _rmsnorm(x: jax.Array, g: jax.Array) -> jax.Array:
    x32 = x.astype(jnp.float32)
    r = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + 1e-6)
    return (x32 * r * g).astype(x.dtype)


def _rope(x: jax.Array, pos: jax.Array, base: float) -> jax.Array:
    """x: (mb, S, H, Dh); pos: (S,) global positions."""
    dh = x.shape[-1]
    half = dh // 2
    freq = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = pos[:, None].astype(jnp.float32) * freq[None]  # (S, half)
    cos = jnp.cos(ang)[None, :, None, :]
    sin = jnp.sin(ang)[None, :, None, :]
    x1, x2 = x[..., :half].astype(jnp.float32), x[..., half:].astype(
        jnp.float32
    )
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    ).astype(x.dtype)


def _layer(cfg: ModelConfig, lp: Dict, x: jax.Array) -> jax.Array:
    """One transformer block. x: (mb, S_loc, D) per rank."""
    sp_n = lax.psum(1, "sp")
    sp_idx = lax.axis_index("sp")
    s_loc = x.shape[1]
    pos = sp_idx * s_loc + jnp.arange(s_loc)

    h = _rmsnorm(x, lp["ln1"])
    mb = x.shape[0]
    hl = lp["wq"].shape[-1] // cfg.head_dim  # local heads (H/tp)

    def qkv(w):
        y = tp_mod.column_parallel(h, w, axis_name="tp")
        return y.reshape(mb, s_loc, hl, cfg.head_dim)

    q = _rope(qkv(lp["wq"]), pos, cfg.rope_base)
    k = _rope(qkv(lp["wk"]), pos, cfg.rope_base)
    v = qkv(lp["wv"])

    # attention: Pallas flash kernel when the sequence is local to one
    # device; exact ring attention over the sp axis otherwise
    if cfg.attn_impl == "flash" and sp_n > 1:
        raise ValueError(
            "attn_impl='flash' is single-shard attention; with sp>1 "
            "use 'ring' (or 'auto', which picks ring for sharded seq)"
        )
    use_flash = cfg.attn_impl == "flash" or (
        cfg.attn_impl == "auto" and sp_n == 1
        and jax.default_backend() == "tpu"
    )
    if use_flash:
        from ..ops.pallas_attention import flash_attention

        attn_fn = lambda q1, k1, v1: flash_attention(q1, k1, v1, True)
    else:
        attn_fn = lambda q1, k1, v1: cp.ring_attention(
            q1, k1, v1, axis_name="sp", causal=True
        )
    attn = jax.vmap(attn_fn)(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3))
    attn = attn.transpose(0, 2, 1, 3).reshape(mb, s_loc, hl * cfg.head_dim)
    x = x + tp_mod.row_parallel(attn, lp["wo"], axis_name="tp")

    h2 = _rmsnorm(x, lp["ln2"])
    if cfg.n_experts:
        tokens = h2.reshape(mb * s_loc, cfg.d_model)

        def expert_fn(pe, t):
            w1, w2 = pe
            u = jnp.matmul(t, w1, preferred_element_type=jnp.float32)
            u = jax.nn.gelu(u).astype(t.dtype)
            return jnp.matmul(u, w2,
                              preferred_element_type=jnp.float32).astype(
                t.dtype
            )

        out, _aux = ep_mod.moe_layer(
            tokens, lp["router"], expert_fn, (lp["we1"], lp["we2"]),
            axis_name="ep", capacity_factor=cfg.capacity_factor,
        )
        x = x + out.reshape(mb, s_loc, cfg.d_model)
    else:
        u = tp_mod.column_parallel(h2, lp["w1"], axis_name="tp")
        u = jax.nn.gelu(u.astype(jnp.float32)).astype(x.dtype)
        x = x + tp_mod.row_parallel(u, lp["w2"], axis_name="tp")
    return x


def _trunk(cfg: ModelConfig, stage_layers: Dict, x: jax.Array) -> jax.Array:
    """This pp rank's layers, scanned. x: (mb, S_loc, D)."""
    def body(x, lp):
        return _layer(cfg, lp, x), None

    x, _ = lax.scan(body, x, stage_layers)
    return x


# ---------------------------------------------------------------------------
# full forward / loss (runs under shard_map over the 5-axis mesh)
# ---------------------------------------------------------------------------

def forward_loss(cfg: ModelConfig, params: Dict, tokens: jax.Array,
                 targets: jax.Array) -> jax.Array:
    """Replicated scalar mean-xent loss. tokens/targets: (b_loc, S_loc)."""
    pp_n = lax.psum(1, "pp")
    pp_idx = lax.axis_index("pp")
    b_loc, s_loc = tokens.shape
    m = cfg.microbatches
    mb = b_loc // m

    emb = tp_mod.vocab_parallel_embedding(
        tokens, params["embed"], axis_name="tp"
    ).astype(cfg.dtype)
    x_mb = emb.reshape(m, mb, s_loc, cfg.d_model)

    y = pp_mod.pipeline(
        partial(_trunk, cfg), params["layers"], x_mb, axis_name="pp",
        remat=cfg.remat,
    )  # (m, mb, S_loc, D), meaningful on the last stage

    h = _rmsnorm(y.reshape(b_loc, s_loc, cfg.d_model), params["ln_f"])
    nll = tp_mod.vocab_parallel_xent(
        h.astype(jnp.float32), params["embed"].astype(jnp.float32),
        targets, axis_name="tp",
    )  # (b_loc, S_loc)

    # global mean over all tokens: local sum / static global count
    dp_n, ep_n, sp_n = (lax.psum(1, a) for a in ("dp", "ep", "sp"))
    total = b_loc * s_loc * dp_n * ep_n * sp_n
    local = jnp.sum(nll) / total
    # only the last pp stage's value is real; psum over every axis both
    # broadcasts it and (through shard_map's replication-tracked
    # transpose) routes gradient flow correctly
    masked = jnp.where(pp_idx == pp_n - 1, local, jnp.zeros_like(local))
    return lax.psum(masked, ("dp", "pp", "sp", "ep"))


# ---------------------------------------------------------------------------
# jitted entry points
# ---------------------------------------------------------------------------

def _loss_spmd(cfg: ModelConfig, mesh: Mesh):
    # interpret-mode pallas (flash off-TPU, the CI simulator) trips
    # jax's vma checker inside the HLO interpreter (dynamic_slice
    # "varying manual axes must match", jax-ml/jax — the checker, not
    # the math: the compiled TPU path type-checks and the kernel is
    # verified against the dense reference both directions in
    # tests/test_pallas.py). Disable the check exactly there, keeping
    # it live for every other configuration.
    check_vma = not (
        cfg.attn_impl == "flash" and jax.default_backend() != "tpu"
    )
    return jax.shard_map(
        partial(forward_loss, cfg),
        mesh=mesh,
        in_specs=(param_specs(cfg), batch_spec(), batch_spec()),
        out_specs=P(),
        check_vma=check_vma,
    )


def make_forward(cfg: ModelConfig, mesh: Mesh):
    """Jitted loss-evaluation forward step (the flagship inference/eval
    path); returns fn(params, tokens, targets) -> scalar loss."""
    cfg.validate(mesh)
    return jax.jit(_loss_spmd(cfg, mesh))


def make_train_step(cfg: ModelConfig, mesh: Mesh, optimizer):
    """Jitted full train step over the mesh.

    The grad is taken through the shard_map'd loss; optimizer update
    runs under the same jit with shardings propagated from the params,
    so the whole step is ONE compiled program (no per-step retrace, the
    north-star requirement of SURVEY §6).
    """
    cfg.validate(mesh)
    loss_fn = _loss_spmd(cfg, mesh)

    @jax.jit
    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u.astype(p.dtype),
                              params, updates)
        return params, opt_state, loss

    return step


def shard_params(params: Dict, cfg: ModelConfig, mesh: Mesh) -> Dict:
    """Device_put the global params onto the mesh per param_specs."""
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, param_specs(cfg),
    )


def make_batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, batch_spec())
