"""Failure detection + fault injection — the ``orte/mca/sensor``
analogue.

- Heartbeat: periodic beats with a miss limit; missing beats fires the
  failure callback (``sensor_heartbeat.c:61,78`` check_heartbeat).
- FtTester: probabilistic fault injection for exercising errmgr paths
  (``sensor_ft_tester.c:67-106`` random kills, here raised as
  InjectedFault so tests/restart loops can exercise recovery).
- resource_usage: /proc vmsize/rss sampling (``pstat_linux_module``).
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Callable, Dict, Optional

from ..mca import var as mca_var
from ..utils import output

_log = output.stream("sensor")


class InjectedFault(RuntimeError):
    """Raised by FtTester to simulate a process failure."""


class Heartbeat:
    """Monitor thread: the watched party calls beat(); if more than
    ``miss_limit`` intervals pass without one, ``on_failure`` fires."""

    def __init__(self, interval_s: float = 1.0, miss_limit: int = 3,
                 on_failure: Optional[Callable[[], None]] = None) -> None:
        self.interval_s = interval_s
        self.miss_limit = miss_limit
        self.on_failure = on_failure
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._failed = False
        self._thread: Optional[threading.Thread] = None

    def beat(self) -> None:
        self._last = time.monotonic()

    @property
    def failed(self) -> bool:
        return self._failed

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s / 2):
            silent = time.monotonic() - self._last
            if silent > self.interval_s * self.miss_limit:
                self._failed = True
                _log.verbose(
                    1, f"heartbeat missed for {silent:.2f}s -> failure"
                )
                if self.on_failure is not None:
                    self.on_failure()
                return

    def start(self) -> "Heartbeat":
        # the clock starts when monitoring starts — construction-to-
        # start delay must not count as missed beats
        self._last = time.monotonic()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)


class FtTester:
    """Fault injector (``sensor/ft_tester``), three modes composable
    per step/call:

    - probabilistic: ``maybe_fail()`` raises :class:`InjectedFault`
      with probability ``fail_prob``. Seeded via ``ft_seed``
      (``sensor_ft_seed`` cvar) so chaos runs REPLAY: the same seed
      injects at the same call sequence — a flake found in CI can be
      reproduced exactly.
    - every-N deterministic: ``step()`` raises at every ``every_n``-th
      step (``sensor_ft_every_n`` cvar) — the job tests' scheduled
      soft fault.
    - hard kill: ``step()`` SIGKILLs the process at ``kill_step``
      (``sensor_ft_kill_step`` / ``sensor_ft_kill_rank`` cvars; the
      ``tpurun --ft-inject rank:step`` chaos flag arms exactly this in
      the chosen child) — the real rank-death the ULFM recovery plane
      exists for. SIGKILL, deliberately: no atexit, no FIN, no flushed
      heartbeat — the corpse the detectors must find.
    """

    def __init__(self, fail_prob: Optional[float] = None,
                 seed: Optional[int] = None,
                 every_n: int = 0,
                 kill_step: int = -1) -> None:
        if fail_prob is None:
            fail_prob = float(mca_var.get("sensor_ft_tester_prob", 0.0))
        if seed is None:
            cvar_seed = int(mca_var.get("sensor_ft_seed", 0) or 0)
            seed = cvar_seed if cvar_seed else None
        self.fail_prob = fail_prob
        self.every_n = int(every_n)
        self.kill_step = int(kill_step)
        self.seed = seed  # retained: replayability is inspectable
        self._rng = random.Random(seed)
        self.injected = 0
        self.steps = 0

    @classmethod
    def from_cvars(cls, process_index: int = 0) -> "FtTester":
        """A tester armed purely from the ``sensor_ft_*`` cvars, with
        the kill scoped to ``sensor_ft_kill_rank`` (-1 = any process
        that has ``sensor_ft_kill_step`` set — tpurun's --ft-inject
        exports the step cvar only into the chosen child)."""
        kill_step = int(mca_var.get("sensor_ft_kill_step", -1))
        kill_rank = int(mca_var.get("sensor_ft_kill_rank", -1))
        if kill_rank >= 0 and kill_rank != int(process_index):
            kill_step = -1
        return cls(every_n=int(mca_var.get("sensor_ft_every_n", 0) or 0),
                   kill_step=kill_step)

    def maybe_fail(self, where: str = "") -> None:
        if self._rng.random() < self.fail_prob:
            self.injected += 1
            _log.verbose(1, f"ft_tester: injecting fault at {where}")
            raise InjectedFault(f"injected fault at {where or 'unknown'}")

    def kill_now(self, why: str = "") -> None:
        """The sensor's hard kill: SIGKILL self (no teardown runs)."""
        import signal
        import sys

        _log.verbose(0, f"ft_tester: SIGKILL self "
                        f"({why or 'armed kill'})")
        sys.stdout.flush()
        sys.stderr.flush()
        os.kill(os.getpid(), signal.SIGKILL)

    def step(self) -> int:
        """Advance the per-step injection clock: fires the armed hard
        kill at ``kill_step``, raises the deterministic every-N fault,
        then runs the probabilistic check. Returns the step index
        just accounted."""
        s = self.steps
        self.steps += 1
        if self.kill_step >= 0 and s == self.kill_step:
            self.kill_now(f"--ft-inject at step {s}")
        if self.every_n > 0 and s > 0 and s % self.every_n == 0:
            self.injected += 1
            raise InjectedFault(
                f"deterministic every-{self.every_n} fault at step {s}")
        self.maybe_fail(f"step {s}")
        return s


def register_vars() -> None:
    mca_var.register(
        "sensor_ft_tester_prob", "float", 0.0,
        "Probability of injected failure per maybe_fail() call "
        "(sensor_ft_tester.c analogue)",
    )
    mca_var.register(
        "sensor_ft_seed", "int", 0,
        "Seed for the probabilistic fault injector (0 = unseeded); a "
        "seeded chaos run injects at a reproducible call sequence",
    )
    mca_var.register(
        "sensor_ft_every_n", "int", 0,
        "Deterministic injection: FtTester.step() raises at every "
        "N-th step (0 = off) — the job tests' scheduled soft fault",
    )
    mca_var.register(
        "sensor_ft_kill_step", "int", -1,
        "Hard chaos: FtTester.step() SIGKILLs this process at the "
        "given step (-1 = off); armed per child by "
        "tpurun --ft-inject rank:step",
    )
    mca_var.register(
        "sensor_ft_kill_rank", "int", -1,
        "Scope sensor_ft_kill_step to one process index when the cvar "
        "reaches every worker (-1 = any process with the step set)",
    )
    mca_var.register(
        "sensor_heartbeat_interval", "float", 1.0,
        "Heartbeat period in seconds",
    )


register_vars()  # idempotent; the ft cvars must resolve their
#                  OMPITPU_MCA_* env overrides before the first tester


def resource_usage() -> Dict[str, int]:
    """vmsize/rss in bytes from /proc/self/status (pstat/linux)."""
    out = {"vmsize": 0, "rss": 0}
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmSize:"):
                    out["vmsize"] = int(line.split()[1]) * 1024
                elif line.startswith("VmRSS:"):
                    out["rss"] = int(line.split()[1]) * 1024
    except OSError:
        pass
    return out
