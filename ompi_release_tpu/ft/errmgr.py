"""Error management policies — the ``orte/mca/errmgr`` analogue.

The reference installs a per-role policy component reacting to error
states posted on the state machine (``errmgr_default_orted.c:118-121``);
the TPU-native response to an unsurvivable failure is job-level
restart-from-checkpoint (SURVEY §5: ICI failures are not survivable
in-place), which ``run_with_restart`` implements: run the step loop,
checkpoint on cadence, and on failure restore the last committed
checkpoint and continue.

``recover`` is the ULFM-era policy layered on top: given a
communicator poisoned by a process failure, either **shrink** (agree
on the survivor group through the coordinator and continue degraded)
or **respawn** (wait for the launcher's resilient respawn to rejoin a
replacement, refresh the modex cards at the new epoch, re-dial the
replacement's wire link, and rebuild a full-size communicator with an
epoch-derived cid). Out-of-job replacement capacity — a controller
that is not under a recovery-enabled ``tpurun`` — is launched through
``comm/spawn.py`` (:func:`spawn_replacements`).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..mca import pvar
from ..utils import output
from ..utils.errors import ErrorCode, MPIError
from .checkpoint import Checkpointer
from .sensor import InjectedFault

_log = output.stream("errmgr")
_restarts = pvar.counter("errmgr_restarts", "restart-from-checkpoint events")
_recoveries = pvar.counter(
    "ft_recoveries",
    "successful ULFM recoveries (shrink or respawn rebuild) completed "
    "by errmgr.recover",
)


class ErrMgr:
    """Callback registry per error class (policy component analogue)."""

    def __init__(self) -> None:
        self._handlers: Dict[type, List[Callable]] = {}

    def register(self, exc_type: type, handler: Callable) -> None:
        self._handlers.setdefault(exc_type, []).append(handler)

    def handle(self, exc: BaseException) -> bool:
        """Run matching handlers; True if any claimed the error."""
        claimed = False
        for t, hs in self._handlers.items():
            if isinstance(exc, t):
                for h in hs:
                    h(exc)
                    claimed = True
        return claimed


def respawn_ready(doc: Optional[Dict]) -> bool:
    """Is the failure picture ready for a full-size rebuild? Nothing
    currently failed, at least one respawn granted, and every granted
    respawn rejoined (``restarted`` is a subset of ``rejoined`` — both
    sets are cumulative across recoveries, so the subset test is what
    distinguishes 'the NEW replacement is wired' from 'some OLD
    recovery's replacement is still in the list')."""
    if not doc or not doc.get("epoch", 0) or doc.get("failed"):
        return False
    restarted = set(doc.get("restarted") or ())
    rejoined = set(doc.get("rejoined") or ())
    return bool(restarted) and restarted <= rejoined


def recover(comm, policy: str = "shrink", *,
            timeout_s: float = 60.0):
    """Recover a working communicator after a member-process failure.

    ``shrink``: ULFM degraded-world recovery — agree on the survivor
    group via the coordinator, return the shrunk communicator (fresh
    epoch-derived cid, rebuilt per-comm collective topology).

    ``respawn``: full-size recovery under a ``tpurun
    --enable-recovery`` job — wait until the launcher's resilient
    respawn brings the replacement through the rejoin service (failure
    picture: ``failed`` empties, the pidx lands in ``rejoined``),
    re-JOIN to refresh the modex card list at the new epoch, re-dial
    the replacement's new OOB listener (``oob_connect`` replaces the
    dead fd), then rebuild a communicator over the FULL original
    group with the epoch-derived cid. The replacement runs this same
    function: on its side the failure picture already shows itself
    rejoined, its bootstrap wire-up already dialed the survivors, and
    the epoch-derived cid makes both sides mint the same channel.

    Returns the recovered communicator; the old one stays revoked.
    """
    if policy == "shrink":
        new = comm.shrink(timeout_ms=int(timeout_s * 1000))
        _recoveries.add()
        return new
    if policy != "respawn":
        raise MPIError(ErrorCode.ERR_ARG,
                       f"unknown recovery policy '{policy}'")
    rt = comm.runtime
    agent = getattr(rt, "agent", None)
    if agent is None or not comm.spans_processes:
        raise MPIError(
            ErrorCode.ERR_NOT_AVAILABLE,
            "respawn recovery needs a tpurun job with "
            "--enable-recovery (the rejoin service respawns the "
            "rank); outside one, launch replacement capacity with "
            "errmgr.spawn_replacements (comm/spawn.py)",
        )
    from ..ft import ulfm as _ulfm
    from ..runtime.wire import proc_topology

    # 1. wait for the replacement: failed drains, and EVERY granted
    # respawn has completed its rejoin — restarted/rejoined are
    # cumulative across recoveries, so "rejoined non-empty" would be
    # satisfied by a PREVIOUS recovery's survivor the instant a new
    # failure's respawn is granted (before the new replacement is
    # anywhere near wired)
    deadline = time.monotonic() + timeout_s
    doc = None
    while time.monotonic() < deadline:
        doc = agent.ft_query()
        if respawn_ready(doc):
            break
        time.sleep(0.1)
    else:
        raise MPIError(
            ErrorCode.ERR_PROC_FAILED,
            f"respawn recovery timed out after {timeout_s}s waiting "
            f"for the replacement to rejoin (picture: {doc})",
        )
    _ulfm.state().apply_notice(doc)
    rejoined = [int(p) for p in doc.get("rejoined", ())]

    # 2. refresh the modex cards at the new epoch (the rejoin service
    # answers JOINs with the CURRENT card list) — in place, so the
    # wire router's reference sees the replacement's new address
    me = int(rt.bootstrap["process_index"])
    my_card = agent.cards[me] if me < len(agent.cards) else {}
    cards = agent.run_modex(dict(my_card), timeout_ms=int(
        max(1.0, deadline - time.monotonic()) * 1000))
    rt.bootstrap["peer_cards"][:] = cards
    agent.cards = rt.bootstrap["peer_cards"]

    # 3. re-dial each replacement's new listener (survivors hold a
    # dead fd; the replacement itself skips — its bootstrap wire-up
    # already dialed every survivor). Only THIS recovery's
    # replacements: rejoined is cumulative across recoveries, and a
    # long-rejoined survivor from an earlier one needs no dial — its
    # episode predates this comm
    fat = _ulfm.failed_at_of(doc)
    epoch0 = getattr(comm, "_ft_epoch0", 0)
    for pidx in rejoined:
        if pidx == me:
            continue
        if fat.get(pidx, epoch0) < epoch0:
            continue  # rejoined long before this comm's failure
        card = cards[pidx]
        try:
            agent.ep.connect(pidx + 1, card["oob_host"],
                             int(card["oob_port"]))
        except MPIError as e:
            raise MPIError(
                ErrorCode.ERR_UNREACH,
                f"re-dial of respawned process {pidx} at "
                f"{card.get('oob_host')}:{card.get('oob_port')} "
                f"failed: {e}",
            )

    # 4. rebuild the full-size communicator at the agreed epoch; the
    # agreement doubles as the survivors<->replacement sync point.
    # Keyed on the comm's LINEAGE, not its cid: after recovery #1 a
    # survivor holds rebuild#1 while a fresh replacement holds only
    # its world — the lineage is the one identity both share, so
    # recovery #2's agreement pairs and both mint the same cid
    lineage = getattr(comm, "_ft_lineage", comm.cid)
    adoc = agent.ft_agree(lineage, 1_000_000 + int(doc["epoch"]), 1,
                          proc_topology(comm).procs,
                          timeout_ms=int(
                              max(1.0, deadline - time.monotonic())
                              * 1000))
    epoch = int(adoc.get("epoch", doc["epoch"]))
    from ..comm.communicator import Communicator

    new = Communicator(rt, comm.group,
                       name=f"rebuild({comm.name})", parent=comm,
                       cid=_ulfm.ft_cid(epoch, lineage))
    rt.wire.proc_barrier(new, proc_topology(new).procs)
    _recoveries.add()
    _log.verbose(1, f"respawn recovery: rebuilt {comm.name} -> "
                    f"{new.name} cid={new.cid} at epoch {epoch}")
    return new


def spawn_replacements(argv: List[str], nprocs: int, *,
                       mca: Optional[List[tuple]] = None,
                       timeout_s: float = 300.0):
    """Launch replacement controller capacity as a child job through
    ``comm/spawn.py`` (the MPI_Comm_spawn path) — the out-of-job leg
    of the respawn policy: when THIS controller is not under a
    recovery-enabled tpurun, a dead peer cannot be respawned in
    place, but fresh capacity can be spawned and handed the publish/
    lookup rendezvous to take the failed worker's role. Returns the
    :class:`~..comm.spawn.SpawnedJob` handle once the children
    completed wire-up."""
    from ..comm.spawn import comm_spawn

    job = comm_spawn(argv, nprocs, mca=mca, timeout_s=timeout_s)
    job.wait_running()
    return job


def run_with_restart(
    step_fn: Callable[[int, Any], Any],
    init_state: Any,
    *,
    num_steps: int,
    checkpointer: Checkpointer,
    checkpoint_every: int = 10,
    max_restarts: int = 5,
    recoverable: Tuple[type, ...] = (InjectedFault,),
) -> Tuple[Any, Dict]:
    """Drive ``state = step_fn(step, state)`` for num_steps with
    checkpoint/restart fault tolerance.

    On a recoverable failure: restore the last committed checkpoint
    and resume from its step (deterministic replay of the collective
    schedule — SURVEY §5's recovery model). Non-recoverable exceptions
    propagate.
    """
    stats = {"restarts": 0, "failures": []}
    start = 0
    latest = checkpointer.latest_step()
    state = init_state
    if latest is not None:
        state = checkpointer.restore(init_state, latest)
        start = latest + 1
        _log.verbose(1, f"resuming from checkpoint step {latest}")

    step = start
    while step < num_steps:
        try:
            state = step_fn(step, state)
            if step % checkpoint_every == 0:
                checkpointer.save(step, state)
            step += 1
        except recoverable as e:
            stats["restarts"] += 1
            stats["failures"].append((step, repr(e)))
            _restarts.add()
            if stats["restarts"] > max_restarts:
                raise
            checkpointer.abort()  # in-flight snapshot is suspect
            latest = checkpointer.latest_step()
            if latest is None:
                state = init_state
                step = 0
            else:
                state = checkpointer.restore(init_state, latest)
                step = latest + 1
            _log.verbose(
                1, f"restarted after failure at step {stats['failures'][-1][0]}"
                   f" -> resume at {step}"
            )
    checkpointer.wait()
    return state, stats
