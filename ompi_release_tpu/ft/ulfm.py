"""ULFM-style fault-tolerance state — the process-local view of the
job epoch, the known-failed process set, and revoked communicators.

The MPI User-Level Failure Mitigation model (MPIX_Comm_revoke /
_shrink / _agree / _failure_ack) hangs off two pieces of shared
state, and this module is both for the TPU runtime:

- the **job epoch**: a monotone counter owned by the HNP coordinator,
  bumped every time the failure picture changes (a worker promoted to
  failed, a replacement respawned, a replacement rejoined). Workers
  learn bumps through ``TAG_PROC_FAILED`` notices pushed over the
  lifeline (see :meth:`~..runtime.coordinator.WorkerAgent
  .start_ft_watcher`) and through ``TAG_FT`` queries/agreements.
- the **failed/restarted/rejoined sets** (process indices): the
  authoritative copy lives at the HNP; this module caches the last
  notice so hot-path waits (``runtime/wire.py`` reaps, ctl waits) can
  consult it with one lock-free-ish read per bounded slice instead of
  an RPC.

Revocation is comm-scoped poison: :meth:`Communicator.revoke` marks
the cid here and pushes ``TAG_FT_REVOKE`` frames to the comm's peer
processes, whose FT watchers call :func:`state`.``apply_revoke`` —
every bounded wire wait on that cid then raises ``ERR_REVOKED``
within one slice, and queued progress-engine schedules on the cid are
completed in error without running (the "interrupt peers' pending
ops" half of ULFM revoke).

No jax imports here: this module sits under the wire router's hot
path and must stay import-light.
"""

from __future__ import annotations

import threading
import time as _time
from typing import Callable, Dict, List, Optional, Set

from .. import obs as _obs
from ..mca import pvar
from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.stream("ulfm")

#: failures learned by THIS process (first time a pidx appears in a
#: notice's failed set) — the "exactly one failure" witness of the
#: recovery job test
failures_detected = pvar.counter(
    "ft_failures_detected",
    "process failures learned from coordinator TAG_PROC_FAILED "
    "notices (counted once per failed process index)",
)
revokes = pvar.counter(
    "ft_revokes",
    "communicator revocations observed (local revoke() calls plus "
    "TAG_FT_REVOKE poison frames from peers)",
)

#: rebuilt communicators draw their cid from this base so every
#: participant — survivors and a respawned replacement whose local
#: cid counter restarted from zero — derives the SAME cid from the
#: HNP-agreed epoch instead of a per-process counter.  Must stay
#: below the wire tag space bound (cid < 1<<20).
FT_CID_BASE = 1 << 19


#: multi-tenant cid banding (the service plane, ROADMAP item 2):
#: tenant t's communicators draw cids from the band
#: [TENANT_CID_BASE + t*TENANT_CID_SLOT, +TENANT_CID_SLOT), which sits
#: directly below the FT band and above every per-process counter a
#: realistic job reaches — so revoking ONE tenant's comms is a range
#: operation that can never touch another tenant or the daemon's own
#: communicators. Each 4096-cid slot is split in half: app comms use
#: the lower 2048 ids, shrink/rebuild comms (:func:`ft_cid` with a
#: tenant) the upper 2048 (8 epochs x 256 parent slots — the PR 9
#: wrap-eviction discipline, scoped per tenant).
TENANT_CID_BASE = 1 << 18
TENANT_CID_SLOT = 4096
MAX_TENANTS = (FT_CID_BASE - TENANT_CID_BASE) // TENANT_CID_SLOT  # 64
_TENANT_APP_SLOTS = TENANT_CID_SLOT // 2


def tenant_band(tenant: int) -> tuple:
    """``[lo, hi)`` cid range owned by ``tenant`` — THE range every
    band-scoped operation (revoke, sentinel clear, sampler scoping)
    keys on."""
    t = int(tenant)
    if not 0 <= t < MAX_TENANTS:
        raise MPIError(ErrorCode.ERR_ARG,
                       f"tenant id {t} outside [0, {MAX_TENANTS})")
    lo = TENANT_CID_BASE + t * TENANT_CID_SLOT
    return lo, lo + TENANT_CID_SLOT


def tenant_cid(tenant: int, k: int) -> int:
    """The ``k``-th application cid of ``tenant``'s band (the lower
    half of the slot; rebuild cids live in the upper half via
    :func:`ft_cid`)."""
    lo, _hi = tenant_band(tenant)
    return lo + int(k) % _TENANT_APP_SLOTS


def tenant_of_cid(cid: int) -> int:
    """Which tenant's band ``cid`` falls in, or -1 for every cid
    outside the tenant band (process-wide comms, the FT band, internal
    negative cids) — pure math, safe on any hot path."""
    c = int(cid)
    if TENANT_CID_BASE <= c < FT_CID_BASE:
        return (c - TENANT_CID_BASE) // TENANT_CID_SLOT
    return -1


def ft_cid(epoch: int, parent_cid: int, tenant: int = -1) -> int:
    """Deterministic cid for a shrink/rebuild communicator: derived
    from the agreed epoch plus the parent comm's (SPMD-agreed) cid, so
    no process-local counter is involved. The FT band (1<<19 ids) is
    split 32 epochs x 16384 parent slots: the shrink-every-comm ULFM
    recovery pattern needs DISTINCT cids for distinct parents at one
    epoch (16384 slots cover any realistic comm count), while the
    epoch wraps — a wrap collision can only hit the same parent 32
    recovery epochs later, where the occupant is that lineage's old
    REVOKED comm, which Communicator evicts on explicit-cid rebuild.

    ``tenant >= 0`` scopes the rebuild to that tenant's cid band (the
    upper half of its slot, 8 epochs x 256 parent slots): a tenant's
    recovered comms stay inside its band, so the tenant-wide revoke
    sweep covers rebuilds too and two tenants recovering at the same
    epoch can never collide."""
    if tenant >= 0:
        lo, hi = tenant_band(tenant)
        cid = (lo + _TENANT_APP_SLOTS + (int(epoch) % 8) * 256
               + (abs(int(parent_cid)) % 256))
        if cid >= hi:  # pragma: no cover - arithmetic bound
            raise MPIError(
                ErrorCode.ERR_INTERN,
                f"tenant ft cid {cid} escapes band [{lo}, {hi})",
            )
        return cid
    cid = (FT_CID_BASE + (int(epoch) % 32) * 16384
           + (abs(int(parent_cid)) % 16384))
    if cid >= (1 << 20):
        raise MPIError(
            ErrorCode.ERR_INTERN,
            f"ft cid {cid} (epoch {epoch}) exceeds the wire tag space",
        )
    return cid


def failed_at_of(doc: Optional[dict]) -> Dict[int, int]:
    """THE parser for a failure document's ``failed_at`` wire map
    (JSON stringifies the pidx keys): pidx -> epoch its current
    failure episode began. Malformed entries are dropped — one
    place, one behavior, for every consumer (apply_notice, shrink,
    errmgr.recover)."""
    out: Dict[int, int] = {}
    for k, e in ((doc or {}).get("failed_at") or {}).items():
        try:
            out[int(k)] = int(e)
        except (TypeError, ValueError):
            continue
    return out


class FtState:
    """Process-local cache of the job's failure picture."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.epoch = 0
        self.failed: Set[int] = set()      # process indices
        self.restarted: Set[int] = set()   # respawn granted
        self.rejoined: Set[int] = set()    # replacement re-wired
        self._ever_failed: Set[int] = set()
        #: pidx -> epoch at which its CURRENT failure episode began.
        #: ULFM failures are permanent PER COMMUNICATOR: a comm
        #: created at epoch E0 treats a peer as dead forever once it
        #: failed at any epoch >= E0, even after a replacement with
        #: the same process index rejoins (the replacement is a NEW
        #: incarnation, visible only to comms built at later epochs —
        #: the rebuild path). Without this, the failed->restarted
        #: transition is a milliseconds-wide window bounded waits
        #: could miss entirely.
        self.failed_at: Dict[int, int] = {}
        self.revoked: Dict[int, int] = {}  # cid -> epoch at revoke
        #: (lo, hi) -> epoch: whole revoked cid BANDS (a tenant's
        #: eviction poisons its entire range, including cids not yet
        #: minted — a dead tenant's future rebuild attempt must fail
        #: typed, not silently reuse the namespace). Empty for every
        #: single-job process: one falsy-dict check on the hot path.
        self.revoked_bands: Dict[tuple, int] = {}
        self._listeners: List[Callable[[dict], None]] = []

    # -- notices (coordinator -> worker) -----------------------------------
    def apply_notice(self, doc: dict) -> None:
        """Fold one TAG_PROC_FAILED / TAG_FT document into the local
        view. Documents are authoritative snapshots (epoch + full
        sets); stale epochs are ignored so a reordered notice can
        never roll the picture backwards."""
        try:
            epoch = int(doc.get("epoch", 0))
            failed = set(int(p) for p in doc.get("failed", ()))
            restarted = set(int(p) for p in doc.get("restarted", ()))
            rejoined = set(int(p) for p in doc.get("rejoined", ()))
        except (TypeError, ValueError):
            return  # malformed notice: never poison the cache
        new_failures: Set[int] = set()
        with self._lock:
            if epoch < self.epoch:
                return
            self.epoch = epoch
            new_failures = failed - self._ever_failed
            self._ever_failed |= failed
            for p in failed - self.failed:
                # a NEW failure episode for this pidx starts now
                self.failed_at[p] = epoch
            # the coordinator's authoritative episode record (carried
            # by TAG_FT replies and newer notices) overrides the
            # locally-derived first-seen epochs: a worker that missed
            # the promotion notice still learns WHEN each episode
            # began, which per-comm deadness depends on
            for p, e in failed_at_of(doc).items():
                self._ever_failed.add(p)
                if e > self.failed_at.get(p, -1):
                    self.failed_at[p] = e
            self.failed = failed
            self.restarted = restarted
            self.rejoined = rejoined
        for _ in new_failures:
            failures_detected.add()
        if new_failures:
            _log.verbose(1, f"epoch {epoch}: process(es) "
                            f"{sorted(new_failures)} failed")
            if _obs.enabled:
                # the failure event lands in the span journal so the
                # doctor's merged timeline shows WHEN each rank
                # learned of the death relative to its stalled round
                for p in sorted(new_failures):
                    _obs.record("ft_failure", "ft",
                                _time.perf_counter(), 0.0,
                                peer=int(p), comm_id=epoch)
        for cb in list(self._listeners):
            try:
                cb(doc)
            except Exception as e:  # a listener must not kill the watcher
                _log.verbose(1, f"ft notice listener failed: {e}")

    def add_listener(self, cb: Callable[[dict], None]) -> None:
        self._listeners.append(cb)

    # -- revocation --------------------------------------------------------
    def apply_revoke(self, cid: int, epoch: int = -1) -> bool:
        """Mark ``cid`` revoked (idempotent). Returns True when this
        call was the first to poison the cid. Also completes queued
        progress-engine schedules on the cid in error — the revoke
        must interrupt pending ops, not only future ones."""
        with self._lock:
            first = cid not in self.revoked
            if first:
                self.revoked[cid] = (epoch if epoch >= 0 else self.epoch)
        if first:
            revokes.add()
            _log.verbose(1, f"cid {cid} revoked")
            if _obs.enabled:
                # the revoke lands in the span journal (epoch in the
                # peer slot) so tpu-doctor report's incident timeline
                # can place it between the failure and the recovery
                _obs.record("ft_revoke", "ft", _time.perf_counter(),
                            0.0, peer=(epoch if epoch >= 0
                                       else self.epoch), comm_id=cid)
            # queued (not yet running) schedules on the revoked comm
            # complete in error without running: their wire exchanges
            # would only park peers on a poisoned channel
            try:
                from ..runtime import progress as _progress

                _progress.engine().fail_queued(
                    ("comm", cid),
                    lambda: MPIError(
                        ErrorCode.ERR_REVOKED,
                        f"communicator cid {cid} was revoked with this "
                        "schedule still queued",
                    ),
                )
            except Exception as e:
                _log.verbose(1, f"revoke: queued-schedule sweep "
                                f"failed: {e}")
            # mirror onto the live comm object for the cheap
            # _check_usable() flag test on every op entry
            try:
                from ..comm.communicator import _comm_registry

                c = _comm_registry.get(cid)
                if c is not None:
                    c._revoked = True
            except Exception:
                pass
        return first

    def is_revoked(self, cid: int) -> bool:
        return (cid in self.revoked
                or (bool(self.revoked_bands)
                    and self._band_of(cid) is not None))

    def _band_of(self, cid: int):
        for band in self.revoked_bands:
            if band[0] <= cid < band[1]:
                return band
        return None

    # -- tenant-band revocation (service plane) ----------------------------
    def revoke_band(self, lo: int, hi: int, epoch: int = -1) -> int:
        """Poison every cid in ``[lo, hi)`` — the tenant-eviction
        sweep: live communicators in the band are revoked through the
        normal :meth:`apply_revoke` path (queued schedules fail,
        mirror flags set), and the band itself is recorded so any
        FUTURE cid a dead tenant's straggler mints in the range fails
        typed at its first bounded wait. Returns the number of LIVE
        communicators revoked. Idempotent."""
        with self._lock:
            first = (lo, hi) not in self.revoked_bands
            if first:
                self.revoked_bands[(lo, hi)] = (
                    epoch if epoch >= 0 else self.epoch)
        n = 0
        try:
            from ..comm.communicator import _comm_registry

            live = [c for c in list(_comm_registry)
                    if lo <= c < hi]
        except Exception:
            live = []
        for cid in live:
            if self.apply_revoke(cid, epoch):
                n += 1
        if first:
            _log.verbose(1, f"cid band [{lo}, {hi}) revoked "
                            f"({n} live comm(s))")
            if _obs.enabled:
                # one band-level incident event (per-cid revokes
                # journal themselves through apply_revoke)
                _obs.record("ft_revoke_band", "ft",
                            _time.perf_counter(), 0.0,
                            peer=(epoch if epoch >= 0 else self.epoch),
                            comm_id=lo, nbytes=hi - lo)
        return n

    def clear_band(self, lo: int, hi: int) -> None:
        """Forget a band's revocation record plus every per-cid record
        inside it — the tenant-slot reuse path (a freed tenant id
        re-admitted later must start with a clean namespace, exactly
        like the explicit-cid rebuild's ``clear_revoked``)."""
        with self._lock:
            self.revoked_bands.pop((lo, hi), None)
            for cid in [c for c in self.revoked if lo <= c < hi]:
                self.revoked.pop(cid, None)

    def clear_revoked(self, cid: int) -> None:
        """Forget a cid's revocation record — the rebuild path's
        epoch-wrapped slot reuse: the record belonged to the evicted
        ancestor, and keeping it would poison the fresh comm minted
        at the same cid."""
        with self._lock:
            self.revoked.pop(cid, None)

    # -- hot-path checks (wire waits) --------------------------------------
    def dead_for(self, peers, epoch0: int = 0) -> List[int]:
        """The subset of ``peers`` dead for a communicator created at
        ``epoch0``: currently failed, or failed at ANY epoch since the
        comm existed (permanence — a same-pidx replacement is a new
        incarnation only comms built at later epochs may talk to)."""
        if not self.failed and not self.failed_at:
            return []
        return sorted(
            p for p in peers
            if p in self.failed or self.failed_at.get(p, -1) >= epoch0)

    def check_wait(self, cid: int, peers, what: str = "wait",
                   epoch0: int = 0) -> None:
        """Raise if ``cid`` is revoked or any process in ``peers`` is
        dead for a comm created at ``epoch0`` — the bounded-slice
        check that turns a would-be indefinite hang on a dead peer
        into ERR_PROC_FAILED within one detection interval."""
        if cid in self.revoked:
            raise MPIError(
                ErrorCode.ERR_REVOKED,
                f"{what} interrupted: communicator cid {cid} revoked",
            )
        if self.revoked_bands and self._band_of(cid) is not None:
            raise MPIError(
                ErrorCode.ERR_REVOKED,
                f"{what} interrupted: cid {cid} falls in a revoked "
                f"tenant band (tenant {tenant_of_cid(cid)} evicted)",
            )
        dead = self.dead_for(peers, epoch0)
        if dead:
            raise MPIError(
                ErrorCode.ERR_PROC_FAILED,
                f"{what} on process(es) {dead} which the job epoch "
                f"({self.epoch}) marks failed (comm epoch {epoch0})",
            )

    def check_peer(self, pidx: int, what: str = "send",
                   epoch0: int = 0) -> None:
        if self.dead_for((pidx,), epoch0):
            raise MPIError(
                ErrorCode.ERR_PROC_FAILED,
                f"{what} to process {pidx} which the job epoch "
                f"({self.epoch}) marks failed (comm epoch {epoch0})",
            )

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "epoch": self.epoch,
                "failed": sorted(self.failed),
                "restarted": sorted(self.restarted),
                "rejoined": sorted(self.rejoined),
                "revoked_cids": sorted(self.revoked),
                "revoked_bands": sorted(list(b)
                                        for b in self.revoked_bands),
                "failed_at": dict(self.failed_at),
            }

    def reset(self) -> None:
        """Test hook: wipe the process-local picture."""
        with self._lock:
            self.epoch = 0
            self.failed.clear()
            self.restarted.clear()
            self.rejoined.clear()
            self._ever_failed.clear()
            self.failed_at.clear()
            self.revoked.clear()
            self.revoked_bands.clear()
            self._listeners.clear()


#: THE process-local FT state (the failure picture is per controller
#: process, like opal's process-global error manager)
STATE = FtState()


def state() -> FtState:
    return STATE


# postmortems must name known-failed ranks rather than listing them as
# merely "awaiting": the flight recorder gets the whole picture
from ..obs import watchdog as _watchdog  # noqa: E402

_watchdog.add_contributor("ft_state", lambda: STATE.snapshot())
