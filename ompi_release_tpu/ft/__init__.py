"""Fault tolerance — checkpoint/restart, failure detection, injection.

The reference's FT stack (SURVEY §5): crs (process image capture),
crcp (network quiescence before checkpoint), snapc (distributed
snapshot orchestration), sstore (image storage), sensor/heartbeat +
errmgr (detection/response), sensor/ft_tester (random fault
injection).
"""

from .checkpoint import Checkpointer  # noqa: F401
from .sensor import Heartbeat, FtTester, resource_usage  # noqa: F401
from .errmgr import (  # noqa: F401
    ErrMgr, recover, run_with_restart, spawn_replacements,
)
from . import ulfm  # noqa: F401
