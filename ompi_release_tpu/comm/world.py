"""WORLD/SELF communicator creation (``ompi_comm_init`` analogue)."""

from __future__ import annotations

from typing import Tuple

from .communicator import Communicator
from .group import Group


def create_world(runtime) -> Tuple[Communicator, Communicator]:
    world_group = Group(range(runtime.world_size))
    world = Communicator(runtime, world_group, name="MPI_COMM_WORLD")
    self_group = Group([0])
    comm_self = Communicator(runtime, self_group, name="MPI_COMM_SELF")
    return world, comm_self
