"""MPI-2 dynamics: connect/accept + name publish/lookup (dpm/pubsub).

Reference analogues: ``ompi/mca/dpm/dpm_orte/dpm_orte.c`` (the
connect/accept handshake over the runtime's OOB) and
``ompi/mca/pubsub/orte/pubsub_orte.c`` (name service hosted by the
HNP / orte-server). Here the rendezvous service has two backends:

* **in-process** (singleton/driver mode): a module-level registry with
  condition variables, so accept/connect work across threads of one
  controller — the analogue of dpm_orte's same-job shortcut.
* **OOB-backed** (tpurun jobs): the HNP coordinator serves
  publish/lookup frames over the native OOB (see
  ``runtime.coordinator.HnpCoordinator.start_name_server`` /
  ``WorkerAgent.publish_name/lookup_name``) — the orte-server role.
  The module-level publish/lookup/unpublish below route there
  automatically when this process is part of a job; the standalone
  ``tools.tpu_server`` covers names ACROSS jobs.

Scope note (design honesty): the NAME service spans processes and
jobs; the ``comm_accept``/``comm_connect`` RENDEZVOUS below forms an
:class:`~.intercomm.Intercommunicator`, which is a single-controller
object — so accept/connect pair up threads/comms of one controller.
Cross-controller pairing exchanges addresses through the name service
and then talks via the transports built for that boundary
(``DcnBtl.send_staged`` / ``ShmBtl.send_shm`` /
``comm.spawn.SpawnedJob`` messaging); a cross-controller device-data
intercommunicator would be a lie in this runtime (see
``comm/spawn.py``'s scope note).

A *port* (``MPI_Open_port``) is an opaque string naming a pending
acceptor. ``comm_accept`` registers the port and blocks (with
timeout) until a connector arrives; ``comm_connect`` completes the
rendezvous; both sides receive mirrored
:class:`~.intercomm.Intercommunicator` handles over the two groups —
exactly the reference flow where both jobs end with an
intercommunicator whose remote group is the peer job.
"""

from __future__ import annotations

import itertools
import threading
from typing import Dict, Optional, Tuple

from ..utils import output
from ..utils.errors import ErrorCode, MPIError
from .communicator import Communicator
from .intercomm import Intercommunicator

_log = output.stream("dpm")

_port_counter = itertools.count(0)
_lock = threading.Condition()

# port -> rendezvous slot
_pending: Dict[str, "_Rendezvous"] = {}
# published service name -> port (MPI_Publish_name)
_names: Dict[str, str] = {}


class _Rendezvous:
    """One port's accept/connect meeting point."""

    def __init__(self, port: str) -> None:
        self.port = port
        self.acceptor: Optional[Communicator] = None
        self.connector: Optional[Communicator] = None
        self.building = False  # one side claimed the construction
        self.result: Optional[Tuple[Intercommunicator,
                                    Intercommunicator]] = None
        self.error: Optional[BaseException] = None
        # ULFM epoch fencing: the port remembers the job epoch it was
        # opened at; comm_accept rejects joiners carrying a STALE
        # epoch (a connector that formed its plan before a failure
        # must re-learn the world, not be paired into it)
        self.epoch = _ft_epoch()


def _ft_epoch() -> int:
    from ..ft import ulfm

    return ulfm.state().epoch


def _check_counterpart(comm: Optional[Communicator],
                       port: str, side: str) -> None:
    """Fast-fail instead of burning the caller's whole timeout: a
    rendezvous whose registered counterpart communicator has been
    revoked (or belongs to a failed process picture) is DEAD — raise
    the typed ULFM error now."""
    if comm is None:
        return
    if getattr(comm, "_revoked", False) or getattr(comm, "_freed",
                                                   False):
        raise MPIError(
            ErrorCode.ERR_REVOKED,
            f"{side} on '{port}': the parked peer's communicator "
            f"({comm.name}) was revoked/freed — the rendezvous is dead",
        )
    from ..ft import ulfm

    ulfm.state().check_wait(comm.cid, comm._member_procs(),
                            f"{side} on '{port}' awaiting process",
                            epoch0=getattr(comm, "_ft_epoch0", 0))


def _check_disjoint(a: Communicator, b: Communicator) -> None:
    if set(a.group.world_ranks) & set(b.group.world_ranks):
        raise MPIError(ErrorCode.ERR_GROUP,
                       "connect/accept groups must be disjoint")


def _build_intercomm(rv: _Rendezvous, runtime, acceptor: Communicator,
                     connector: Communicator) -> None:
    """Construct the mirrored pair OUTSIDE the lock (submesh build +
    coll selection can be slow — unrelated ports must not stall), then
    publish result/error under the lock. ``acceptor``/``connector``
    are snapshots taken under the lock: the parked side may withdraw
    (timeout) while we build."""
    try:
        pair = Intercommunicator.create(
            runtime, acceptor.group, connector.group,
            name=f"accept({rv.port})",
        )
    except BaseException as exc:
        with _lock:
            rv.error = exc
            rv.acceptor = None
            rv.connector = None
            _lock.notify_all()
        raise
    with _lock:
        rv.result = pair
        _lock.notify_all()


def _await_result(rv: _Rendezvous, deadline: float, side: str):
    """Wait under the lock for result/error; caller holds _lock.
    Parks in bounded slices so a counterpart communicator revoked (or
    its process failed) MID-WAIT surfaces as the typed ULFM error
    within one slice instead of silently burning the deadline."""
    import time

    while rv.result is None and rv.error is None:
        other = rv.connector if side == "accept" else rv.acceptor
        try:
            _check_counterpart(other, rv.port, side)
        except MPIError as err:
            if side == "accept":
                rv.acceptor = None
            else:
                rv.connector = None
            rv.error = err
            _reset_slot(rv)
            _lock.notify_all()
            raise
        left = deadline - time.monotonic()
        if left <= 0 or (not _lock.wait(timeout=min(left, 0.2))
                         and deadline - time.monotonic() <= 0):
            if rv.result is not None or rv.error is not None:
                break
            # the rendezvous is DEAD, not just this side: poison the
            # slot and retire the port, else a build completing after
            # our withdrawal would publish a result carrying OUR group
            # into a later retry with a different communicator
            if side == "accept":
                rv.acceptor = None
            else:
                rv.connector = None
            err = MPIError(ErrorCode.ERR_PORT,
                           f"{side} on '{rv.port}' timed out")
            rv.error = err
            _reset_slot(rv)  # port stays valid for later attempts
            _lock.notify_all()
            raise err
    if rv.error is not None:
        err = rv.error
        _reset_slot(rv)
        raise err
    return rv.result


def open_port() -> str:
    """``MPI_Open_port``: mint an opaque port name."""
    port = f"tpu-port:{next(_port_counter)}"
    with _lock:
        _pending[port] = _Rendezvous(port)
    return port


def close_port(port: str) -> None:
    with _lock:
        _pending.pop(port, None)


def _job_agent():
    """The tpurun WorkerAgent when this process is part of a job —
    the public pubsub API must reach the JOB-global name table (the
    HNP server) there, not this process's local dict (which no other
    worker can see)."""
    from ..runtime.runtime import Runtime

    rt = Runtime._instance
    return getattr(rt, "agent", None) if rt is not None else None


def publish_name(service: str, port: str) -> None:
    """``MPI_Publish_name`` (pubsub_orte: HNP-hosted name table).

    Under tpurun this routes to the HNP's OOB name server so every
    worker sees it; in singleton/driver mode the table is local."""
    agent = _job_agent()
    if agent is not None:
        agent.publish_name(service, port)
        return
    with _lock:
        if service in _names:
            raise MPIError(ErrorCode.ERR_NAME,
                           f"service '{service}' already published")
        _names[service] = port
        _lock.notify_all()


def unpublish_name(service: str) -> None:
    agent = _job_agent()
    if agent is not None:
        agent.unpublish_name(service)
        return
    with _lock:
        if _names.pop(service, None) is None:
            raise MPIError(ErrorCode.ERR_NAME,
                           f"service '{service}' not published")


def lookup_name(service: str, *, timeout_s: float = 10.0) -> str:
    """``MPI_Lookup_name``: blocks until published (the reference's
    pubsub lookup spins on the server) or times out. In singleton
    (in-process) mode, a name resolving to a DEAD port — closed, or
    with a parked acceptor whose comm was revoked / whose process
    failed — raises the typed ULFM error immediately instead of
    handing back a port every connect on which would burn its own
    timeout. Under tpurun the lookup is served by the HNP name table,
    which tracks no port liveness — a stale cross-job port surfaces
    at connect time, not here."""
    import time

    agent = _job_agent()
    if agent is not None:
        return agent.lookup_name(service,
                                 timeout_ms=int(timeout_s * 1000))
    deadline = time.monotonic() + timeout_s
    with _lock:
        while service not in _names:
            left = deadline - time.monotonic()
            if left <= 0 or not _lock.wait(timeout=left):
                if service in _names:  # published at the deadline edge
                    break
                raise MPIError(ErrorCode.ERR_NAME,
                               f"service '{service}' not found")
        port = _names[service]
        rv = _pending.get(port)
        if rv is None:
            if port.startswith("tpu-port:"):
                raise MPIError(
                    ErrorCode.ERR_PROC_FAILED,
                    f"service '{service}' names port '{port}' which "
                    "has been closed (publisher died or retired the "
                    "port without unpublishing)",
                )
            return port  # opaque non-port payload: hand it through
        _check_counterpart(rv.acceptor, port, f"lookup '{service}'")
        return port


def _reset_slot(rv: _Rendezvous) -> None:
    """Replace a consumed/dead rendezvous with a fresh slot so the
    PORT stays valid (MPI keeps a port open until MPI_Close_port — a
    server loops accept on one published port). Only replaces if the
    port still maps to ``rv`` (close_port may have retired it)."""
    if _pending.get(rv.port) is rv:
        _pending[rv.port] = _Rendezvous(rv.port)


def _rendezvous(comm: Communicator, port: str, side: str,
                timeout_s: float,
                epoch: Optional[int] = None) -> Intercommunicator:
    """The shared accept/connect protocol; ``side`` picks which slot
    this caller fills and which handle of the pair it receives.
    ``epoch`` is the epoch the connector's PLAN was formed at
    (default: the connecting communicator's birth epoch): a joiner
    whose plan predates the port's world view — the port was opened
    after a failure the connector's comm has never heard of — is
    rejected immediately and must re-learn the world before pairing
    (the comm_accept stale-epoch fence)."""
    import time

    mine, theirs = (
        ("acceptor", "connector") if side == "accept"
        else ("connector", "acceptor")
    )
    if epoch is None:
        epoch = getattr(comm, "_ft_epoch0", 0)
    deadline = time.monotonic() + timeout_s
    with _lock:
        rv = _pending.get(port)
        if rv is None:
            raise MPIError(ErrorCode.ERR_PORT, f"unknown port '{port}'")
        if side == "connect" and epoch < rv.epoch:
            raise MPIError(
                ErrorCode.ERR_REVOKED,
                f"connect on '{port}': joiner epoch {epoch} is stale "
                f"(port opened at epoch {rv.epoch}) — rebuild the "
                "communicator against the current failure picture "
                "and retry",
            )
        if getattr(rv, mine) is not None:
            raise MPIError(ErrorCode.ERR_PORT,
                           f"port '{port}' already has an {mine}")
        other = getattr(rv, theirs)
        # fast-fail on a DEAD rendezvous before registering: a parked
        # peer whose comm was revoked / whose process failed means
        # this pairing can never complete — return the error class
        # now instead of burning the caller's whole timeout
        _check_counterpart(other, port, side)
        if other is not None:
            _check_disjoint(comm, other)  # before registering
        setattr(rv, mine, comm)
        _lock.notify_all()
        build = other is not None and not rv.building
        if build:
            rv.building = True
            acceptor, connector = rv.acceptor, rv.connector
    if build:
        _build_intercomm(rv, comm.runtime, acceptor, connector)
    with _lock:
        server_side, client_side = _await_result(rv, deadline, side)
        _reset_slot(rv)  # port stays valid for the next accept
        return server_side if side == "accept" else client_side


def comm_accept(comm: Communicator, port: str, *,
                timeout_s: float = 30.0) -> Intercommunicator:
    """``MPI_Comm_accept``: block on ``port`` until a connector
    arrives; returns this (server) side's intercomm handle. The port
    remains valid afterwards — a server can loop accept on one
    published port (dpm_orte server pattern). Joiners carrying a
    stale job epoch are rejected (see :func:`_rendezvous`), and a
    parked accept whose connector's comm gets revoked fails within
    one bounded slice with the typed ULFM error."""
    return _rendezvous(comm, port, "accept", timeout_s)


def comm_connect(comm: Communicator, port: str, *,
                 timeout_s: float = 30.0,
                 epoch: Optional[int] = None) -> Intercommunicator:
    """``MPI_Comm_connect``: rendezvous with the acceptor on ``port``;
    returns this (client) side's intercomm handle. A connect to a
    dead/revoked port (parked acceptor's comm revoked or owned by a
    failed process) raises ERR_REVOKED/ERR_PROC_FAILED immediately
    instead of burning the full timeout; ``epoch`` (default: current)
    is fenced against the port's epoch."""
    return _rendezvous(comm, port, "connect", timeout_s, epoch=epoch)


def clear() -> None:
    """Finalize-time teardown: fail parked waiters immediately (they
    must not sleep out their deadlines against wiped state), then drop
    ports and names."""
    with _lock:
        err = MPIError(ErrorCode.ERR_PORT, "dpm torn down (finalize)")
        for rv in _pending.values():
            if rv.result is None and rv.error is None:
                rv.error = err
        _pending.clear()
        _names.clear()
        _lock.notify_all()
