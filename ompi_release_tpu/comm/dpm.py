"""MPI-2 dynamics: connect/accept + name publish/lookup (dpm/pubsub).

Reference analogues: ``ompi/mca/dpm/dpm_orte/dpm_orte.c`` (the
connect/accept handshake over the runtime's OOB) and
``ompi/mca/pubsub/orte/pubsub_orte.c`` (name service hosted by the
HNP / orte-server). Here the rendezvous service has two backends:

* **in-process** (singleton/driver mode): a module-level registry with
  condition variables, so accept/connect work across threads of one
  controller — the analogue of dpm_orte's same-job shortcut.
* **OOB-backed** (tpurun jobs): the HNP coordinator serves
  publish/lookup frames over the native OOB (see
  ``runtime.coordinator.HnpCoordinator.start_name_server`` /
  ``WorkerAgent.publish_name/lookup_name``) — the orte-server role.
  The module-level publish/lookup/unpublish below route there
  automatically when this process is part of a job; the standalone
  ``tools.tpu_server`` covers names ACROSS jobs.

Scope note (design honesty): the NAME service spans processes and
jobs; the ``comm_accept``/``comm_connect`` RENDEZVOUS below forms an
:class:`~.intercomm.Intercommunicator`, which is a single-controller
object — so accept/connect pair up threads/comms of one controller.
Cross-controller pairing exchanges addresses through the name service
and then talks via the transports built for that boundary
(``DcnBtl.send_staged`` / ``ShmBtl.send_shm`` /
``comm.spawn.SpawnedJob`` messaging); a cross-controller device-data
intercommunicator would be a lie in this runtime (see
``comm/spawn.py``'s scope note).

A *port* (``MPI_Open_port``) is an opaque string naming a pending
acceptor. ``comm_accept`` registers the port and blocks (with
timeout) until a connector arrives; ``comm_connect`` completes the
rendezvous; both sides receive mirrored
:class:`~.intercomm.Intercommunicator` handles over the two groups —
exactly the reference flow where both jobs end with an
intercommunicator whose remote group is the peer job.
"""

from __future__ import annotations

import itertools
import threading
from typing import Dict, List, Optional, Tuple

from ..utils import output
from ..utils.errors import ErrorCode, MPIError
from .communicator import Communicator
from .intercomm import Intercommunicator

_log = output.stream("dpm")

_port_counter = itertools.count(0)
_lock = threading.Condition()

# port -> rendezvous slot
_pending: Dict[str, "_Rendezvous"] = {}
# published service name -> port (MPI_Publish_name)
_names: Dict[str, str] = {}


class _Party:
    """One parked accept/connect caller. ``pairing`` is set when the
    matchmaker pairs it; ``error`` fails it individually (dead-peer
    fast-fail, close_port, finalize teardown)."""

    __slots__ = ("comm", "side", "pairing", "error")

    def __init__(self, comm: Communicator, side: str) -> None:
        self.comm = comm
        self.side = side
        self.pairing: Optional["_Pairing"] = None
        self.error: Optional[BaseException] = None


class _Pairing:
    """One matched (acceptor, connector) pair mid-construction. Each
    pairing carries its OWN result/error — the multi-tenant fix: a
    port is a meeting point for MANY concurrent pairings, so one slow
    or failed construction can never serialize or poison another
    tenant's rendezvous on the same port."""

    __slots__ = ("port", "acceptor", "connector", "result", "error")

    def __init__(self, port: str, acceptor: _Party,
                 connector: _Party) -> None:
        self.port = port
        self.acceptor = acceptor
        self.connector = connector
        self.result: Optional[Tuple[Intercommunicator,
                                    Intercommunicator]] = None
        self.error: Optional[BaseException] = None


class _Rendezvous:
    """One port's accept/connect meeting point: FIFO queues of parked
    parties per side. Arrivals pair with the head of the opposite
    queue (skipping none — a dead parked head fast-fails the arrival,
    the ULFM contract below); unmatched arrivals park in their own
    queue, so concurrent connectors from different tenants are each
    served as soon as an acceptor shows up instead of the second one
    bouncing off a single occupied slot."""

    def __init__(self, port: str) -> None:
        self.port = port
        self.acceptors: List[_Party] = []
        self.connectors: List[_Party] = []
        # ULFM epoch fencing: the port remembers the job epoch it was
        # opened at; comm_accept rejects joiners carrying a STALE
        # epoch (a connector that formed its plan before a failure
        # must re-learn the world, not be paired into it)
        self.epoch = _ft_epoch()


def _ft_epoch() -> int:
    from ..ft import ulfm

    return ulfm.state().epoch


def _check_counterpart(comm: Optional[Communicator],
                       port: str, side: str) -> None:
    """Fast-fail instead of burning the caller's whole timeout: a
    rendezvous whose registered counterpart communicator has been
    revoked (or belongs to a failed process picture) is DEAD — raise
    the typed ULFM error now."""
    if comm is None:
        return
    if getattr(comm, "_revoked", False) or getattr(comm, "_freed",
                                                   False):
        raise MPIError(
            ErrorCode.ERR_REVOKED,
            f"{side} on '{port}': the parked peer's communicator "
            f"({comm.name}) was revoked/freed — the rendezvous is dead",
        )
    from ..ft import ulfm

    ulfm.state().check_wait(comm.cid, comm._member_procs(),
                            f"{side} on '{port}' awaiting process",
                            epoch0=getattr(comm, "_ft_epoch0", 0))


def _check_disjoint(a: Communicator, b: Communicator) -> None:
    if set(a.group.world_ranks) & set(b.group.world_ranks):
        raise MPIError(ErrorCode.ERR_GROUP,
                       "connect/accept groups must be disjoint")


def _build_intercomm(pr: _Pairing, runtime) -> None:
    """Construct one pairing's mirrored pair OUTSIDE the lock
    (submesh build + coll selection can be slow — OTHER pairings on
    the same port, and unrelated ports, must not stall), then publish
    result/error on the pairing under the lock."""
    try:
        pair = Intercommunicator.create(
            runtime, pr.acceptor.comm.group, pr.connector.comm.group,
            name=f"accept({pr.port})",
        )
    except BaseException as exc:
        with _lock:
            pr.error = exc
            _lock.notify_all()
        return
    with _lock:
        pr.result = pair
        _lock.notify_all()


def _withdraw(rv: _Rendezvous, me: _Party) -> None:
    """Remove a parked party from its queue (timeout path). Caller
    holds _lock."""
    q = rv.acceptors if me.side == "accept" else rv.connectors
    try:
        q.remove(me)
    except ValueError:
        pass  # already matched or evicted


def _await_party(rv: _Rendezvous, me: _Party, deadline: float):
    """Wait under the lock until this party's pairing completes.
    Parks in bounded slices so a counterpart communicator revoked (or
    its process failed) MID-BUILD surfaces as the typed ULFM error
    within one slice instead of silently burning the deadline; the
    timeout of an UNMATCHED party withdraws only itself — other
    parties parked on the port are untouched. Caller holds _lock."""
    import time

    while True:
        if me.error is not None:
            raise me.error
        pr = me.pairing
        if pr is not None:
            if pr.error is not None:
                raise pr.error
            if pr.result is not None:
                server_side, client_side = pr.result
                return (server_side if me.side == "accept"
                        else client_side)
            other = (pr.connector if me.side == "accept"
                     else pr.acceptor).comm
            try:
                _check_counterpart(other, rv.port, me.side)
            except MPIError as err:
                pr.error = err
                _lock.notify_all()
                raise
        left = deadline - time.monotonic()
        if left <= 0:
            err = MPIError(ErrorCode.ERR_PORT,
                           f"{me.side} on '{rv.port}' timed out")
            if pr is None:
                _withdraw(rv, me)
            else:
                # matched but the build never finished: poison THIS
                # pairing (its counterpart must not inherit a result
                # built against a withdrawn group), not the port
                pr.error = err
            _lock.notify_all()
            raise err
        _lock.wait(timeout=min(left, 0.2))


def open_port() -> str:
    """``MPI_Open_port``: mint an opaque port name."""
    port = f"tpu-port:{next(_port_counter)}"
    with _lock:
        _pending[port] = _Rendezvous(port)
    return port


def close_port(port: str) -> None:
    """``MPI_Close_port``: retire the port and fail every parked
    party promptly (they must not sleep out their deadlines against a
    port that can never pair them)."""
    with _lock:
        rv = _pending.pop(port, None)
        if rv is not None:
            err = MPIError(ErrorCode.ERR_PORT,
                           f"port '{port}' closed")
            for party in rv.acceptors + rv.connectors:
                if party.error is None and party.pairing is None:
                    party.error = err
            rv.acceptors.clear()
            rv.connectors.clear()
            _lock.notify_all()


def _job_agent():
    """The tpurun WorkerAgent when this process is part of a job —
    the public pubsub API must reach the JOB-global name table (the
    HNP server) there, not this process's local dict (which no other
    worker can see)."""
    from ..runtime.runtime import Runtime

    rt = Runtime._instance
    return getattr(rt, "agent", None) if rt is not None else None


def publish_name(service: str, port: str) -> None:
    """``MPI_Publish_name`` (pubsub_orte: HNP-hosted name table).

    Under tpurun this routes to the HNP's OOB name server so every
    worker sees it; in singleton/driver mode the table is local."""
    agent = _job_agent()
    if agent is not None:
        agent.publish_name(service, port)
        return
    with _lock:
        if service in _names:
            raise MPIError(ErrorCode.ERR_NAME,
                           f"service '{service}' already published")
        _names[service] = port
        _lock.notify_all()


def unpublish_name(service: str) -> None:
    agent = _job_agent()
    if agent is not None:
        agent.unpublish_name(service)
        return
    with _lock:
        if _names.pop(service, None) is None:
            raise MPIError(ErrorCode.ERR_NAME,
                           f"service '{service}' not published")


def lookup_name(service: str, *, timeout_s: float = 10.0) -> str:
    """``MPI_Lookup_name``: blocks until published (the reference's
    pubsub lookup spins on the server) or times out. In singleton
    (in-process) mode, a name resolving to a DEAD port — closed, or
    with a parked acceptor whose comm was revoked / whose process
    failed — raises the typed ULFM error immediately instead of
    handing back a port every connect on which would burn its own
    timeout. Under tpurun the lookup is served by the HNP name table,
    which tracks no port liveness — a stale cross-job port surfaces
    at connect time, not here."""
    import time

    agent = _job_agent()
    if agent is not None:
        return agent.lookup_name(service,
                                 timeout_ms=int(timeout_s * 1000))
    deadline = time.monotonic() + timeout_s
    with _lock:
        while service not in _names:
            left = deadline - time.monotonic()
            if left <= 0 or not _lock.wait(timeout=left):
                if service in _names:  # published at the deadline edge
                    break
                raise MPIError(ErrorCode.ERR_NAME,
                               f"service '{service}' not found")
        port = _names[service]
        rv = _pending.get(port)
        if rv is None:
            if port.startswith("tpu-port:"):
                raise MPIError(
                    ErrorCode.ERR_PROC_FAILED,
                    f"service '{service}' names port '{port}' which "
                    "has been closed (publisher died or retired the "
                    "port without unpublishing)",
                )
            return port  # opaque non-port payload: hand it through
        _check_counterpart(rv.acceptors[0].comm if rv.acceptors
                           else None, port, f"lookup '{service}'")
        return port


def _rendezvous(comm: Communicator, port: str, side: str,
                timeout_s: float,
                epoch: Optional[int] = None) -> Intercommunicator:
    """The shared accept/connect protocol; ``side`` picks which queue
    this caller parks in and which handle of the pair it receives.
    Arrivals pair FIFO with the opposite queue's head, each pairing
    built and completed independently — concurrent connectors from
    different tenants are served concurrently, never serialized
    behind (or bounced off) one parked rendezvous slot. ``epoch`` is
    the epoch the connector's PLAN was formed at (default: the
    connecting communicator's birth epoch): a joiner whose plan
    predates the port's world view — the port was opened after a
    failure the connector's comm has never heard of — is rejected
    immediately and must re-learn the world before pairing (the
    comm_accept stale-epoch fence)."""
    import time

    if epoch is None:
        epoch = getattr(comm, "_ft_epoch0", 0)
    deadline = time.monotonic() + timeout_s
    me = _Party(comm, side)
    with _lock:
        rv = _pending.get(port)
        if rv is None:
            raise MPIError(ErrorCode.ERR_PORT, f"unknown port '{port}'")
        if side == "connect" and epoch < rv.epoch:
            raise MPIError(
                ErrorCode.ERR_REVOKED,
                f"connect on '{port}': joiner epoch {epoch} is stale "
                f"(port opened at epoch {rv.epoch}) — rebuild the "
                "communicator against the current failure picture "
                "and retry",
            )
        theirs = rv.connectors if side == "accept" else rv.acceptors
        pairing = None
        if theirs:
            cand = theirs[0]
            # fast-fail on a DEAD parked head before pairing: a peer
            # whose comm was revoked / whose process failed can never
            # complete a pairing — return the error class NOW instead
            # of burning the caller's whole timeout, and retire the
            # corpse with the same error so its own wait wakes typed
            try:
                _check_counterpart(cand.comm, port, side)
            except MPIError as err:
                theirs.pop(0)
                cand.error = err
                _lock.notify_all()
                raise
            _check_disjoint(comm, cand.comm)  # before dequeuing
            theirs.pop(0)
            if side == "accept":
                pairing = _Pairing(port, me, cand)
            else:
                pairing = _Pairing(port, cand, me)
            me.pairing = cand.pairing = pairing
        else:
            (rv.acceptors if side == "accept"
             else rv.connectors).append(me)
        _lock.notify_all()
    if pairing is not None:
        # the matchmaker builds its own pairing outside the lock;
        # other pairings on this port build in their own callers
        _build_intercomm(pairing, comm.runtime)
    with _lock:
        return _await_party(rv, me, deadline)


def comm_accept(comm: Communicator, port: str, *,
                timeout_s: float = 30.0) -> Intercommunicator:
    """``MPI_Comm_accept``: block on ``port`` until a connector
    arrives; returns this (server) side's intercomm handle. The port
    remains valid afterwards — a server can loop accept on one
    published port (dpm_orte server pattern). Joiners carrying a
    stale job epoch are rejected (see :func:`_rendezvous`), and a
    parked accept whose connector's comm gets revoked fails within
    one bounded slice with the typed ULFM error."""
    return _rendezvous(comm, port, "accept", timeout_s)


def comm_connect(comm: Communicator, port: str, *,
                 timeout_s: float = 30.0,
                 epoch: Optional[int] = None) -> Intercommunicator:
    """``MPI_Comm_connect``: rendezvous with the acceptor on ``port``;
    returns this (client) side's intercomm handle. A connect to a
    dead/revoked port (parked acceptor's comm revoked or owned by a
    failed process) raises ERR_REVOKED/ERR_PROC_FAILED immediately
    instead of burning the full timeout; ``epoch`` (default: current)
    is fenced against the port's epoch."""
    return _rendezvous(comm, port, "connect", timeout_s, epoch=epoch)


def clear() -> None:
    """Finalize-time teardown: fail parked waiters immediately (they
    must not sleep out their deadlines against wiped state), then drop
    ports and names."""
    with _lock:
        err = MPIError(ErrorCode.ERR_PORT, "dpm torn down (finalize)")
        for rv in _pending.values():
            for party in rv.acceptors + rv.connectors:
                if party.error is None:
                    party.error = err
        _pending.clear()
        _names.clear()
        _lock.notify_all()
