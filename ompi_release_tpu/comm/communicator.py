"""Communicators — the ``ompi/communicator`` analogue, mesh-native.

A communicator binds a :class:`Group` to a sub-mesh of the world device
mesh, carries a CID, attributes, an error handler, and — the load-
bearing part, exactly as in the reference — a per-communicator table of
collective implementations installed by priority query over the coll
framework (``ompi/mca/coll/base/coll_base_comm_select.c:66-88``).

Driver-mode data convention (single-controller SPMD): operations whose
MPI result is rank-dependent take/return arrays with a leading ``size``
axis (slice i = rank i's buffer, matching the reference's oversubscribed
-mpirun test style, SURVEY §4); operations whose result is identical on
every rank return it once. The in-jit SPMD API (``coll.allreduce`` under
``shard_map``) is the performance path; this host API is the semantic
(MPI-compatible) path and compiles one persistent program per
(op, shape, dtype, algorithm).

CID allocation: the reference runs an iterated MAX-allreduce agreement
(``ompi/communicator/comm_cid.c:190,264-318``); under a static mesh
with a single controller the agreement outcome is a deterministic
monotone counter, so that is what we use.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..mca import pvar
from ..obs import sentinel as _sentinel
from ..utils import output
from ..utils.errors import Errhandler, ErrorCode, MPIError, ERRORS_ARE_FATAL
from .group import Group, UNDEFINED

_log = output.stream("comm")
_cid_counter = itertools.count(0)
#: internal (runtime-private) communicators — e.g. the hier module's
#: process-local shadow — draw NEGATIVE cids from a separate counter:
#: their creation is conditional on local membership, so letting them
#: consume the global counter would desynchronize cid allocation
#: across controller processes (cids must agree SPMD-wide because the
#: wire router addresses communicators by cid)
_internal_cid_counter = itertools.count(-1, -1)
_cid_lock = threading.Lock()
_comm_registry: Dict[int, "Communicator"] = {}

_comm_count = pvar.counter("comm_active_count", "live communicators")

#: serializes lazy FusionBuffer creation (comm.fusion_buffer): the
#: buffer itself is thread-safe, so first use may race — an orphaned
#: second instance would silently escape free()'s drain
_fusion_create_lock = threading.Lock()


def _next_cid(internal: bool = False) -> int:
    with _cid_lock:
        return next(_internal_cid_counter if internal else _cid_counter)


def clear_comm_registry() -> None:
    """Finalize-time teardown: mark every live communicator freed (so
    stale handles raise instead of silently working) and keep the
    comm_active_count pvar honest."""
    for c in list(_comm_registry.values()):
        c._freed = True
        _comm_count.add(-1)
    _comm_registry.clear()


class Keyval:
    """MPI_Comm_create_keyval analogue."""

    _counter = itertools.count(0)

    def __init__(self, copy_fn: Optional[Callable] = None,
                 delete_fn: Optional[Callable] = None,
                 extra_state: Any = None) -> None:
        self.id = next(Keyval._counter)
        self.copy_fn = copy_fn
        self.delete_fn = delete_fn
        self.extra_state = extra_state


class Communicator:
    is_inter = False  # Intercommunicator overrides (MPI_Comm_test_inter)

    def __init__(self, runtime, group: Group, *, name: str = "",
                 parent: Optional["Communicator"] = None,
                 topo: Optional[Any] = None,
                 internal: bool = False,
                 cid: Optional[int] = None) -> None:
        from ..runtime.mesh import build_submesh  # local: avoid cycle

        self.runtime = runtime
        self.group = group
        if cid is not None:
            # explicit cid: the ULFM shrink/rebuild path derives the
            # cid from the HNP-agreed job epoch so survivors and a
            # respawned replacement (whose local counter restarted
            # from zero) mint the SAME cid without agreement traffic.
            # A REVOKED/freed occupant (the epoch-wrapped slot of this
            # lineage's own poisoned ancestor) is evicted — it can
            # never be used again by ULFM rule; a LIVE occupant is a
            # real collision and stays a loud error.
            occupant = _comm_registry.get(cid)
            if occupant is not None and (occupant._revoked
                                         or occupant._freed):
                if not occupant._freed:
                    # real teardown, not flag-poking: the evicted
                    # comm's _on_free hooks (hier shadow, fusion
                    # buffer) must run or they leak registry entries
                    # for the process lifetime
                    try:
                        occupant.free()
                    except MPIError:
                        pass  # a poisoned drain must not block rebuild
                _comm_registry.pop(cid, None)
                occupant = None
            if occupant is not None:
                raise MPIError(
                    ErrorCode.ERR_COMM,
                    f"explicit cid {cid} already registered "
                    f"({_comm_registry[cid].name}) — free it before "
                    "rebuilding at the same epoch",
                )
            # any stale revocation record for this slot belongs to an
            # ANCESTOR's epoch (evicted above, or revoked-then-freed
            # by the app long ago), not to the comm being built — a
            # leftover entry would make every wire wait on the fresh
            # cid raise ERR_REVOKED immediately
            from ..ft import ulfm as _ulfm_slot

            _ulfm_slot.state().clear_revoked(cid)
            # the evicted ancestor's sentinel chain goes with it: a
            # leftover posting seq would false-mismatch the rebuilt
            # comm against a restarted-from-zero replacement
            _sentinel.clear_chain(cid)
            self.cid = cid
        else:
            self.cid = _next_cid(internal)
        self._revoked = False  # ULFM revocation flag (see revoke())
        # ULFM lineage anchor: shrink/rebuild children inherit the
        # ORIGINAL comm's identity, so across ANY number of
        # recoveries every participant — a survivor holding
        # rebuild#N, a fresh replacement holding only its world —
        # keys the recovery agreement and the epoch-derived cid on
        # the same value. The lineage is also the constant ft_cid
        # parent slot, which is what makes an epoch-wrapped slot
        # collision land on this lineage's own revoked ancestor.
        if cid is not None and parent is not None:
            self._ft_lineage = getattr(parent, "_ft_lineage",
                                       parent.cid)
        else:
            self._ft_lineage = self.cid
        # the job epoch this comm was born at: ULFM failures are
        # permanent per communicator, so bounded waits compare each
        # peer's failure episode against THIS epoch — a replacement
        # incarnation is visible only to comms built after its rejoin
        from ..ft import ulfm as _ulfm_mod

        self._ft_epoch0 = _ulfm_mod.state().epoch
        # multi-tenant QoS class (service plane): children inherit the
        # parent's stamp so a tenant's whole comm tree rides its lane
        # class; None defers to the process-wide wire_qos_class cvar
        self._qos_class: Optional[str] = getattr(parent, "_qos_class",
                                                 None)
        self.name = name or f"comm{self.cid}"
        self.errhandler: Errhandler = (
            parent.errhandler if parent else ERRORS_ARE_FATAL
        )
        from .info import Info

        parent_info = getattr(parent, "info", None)
        self.info: Info = (parent_info.dup() if isinstance(parent_info, Info)
                           else Info())  # MPI_Comm_set/get_info object
        self.topo = topo  # topology module (cart/graph), if any
        self._attrs: Dict[int, Any] = {}
        self._freed = False

        # Local membership: under a unified multi-controller world this
        # process owns only a span of world ranks; the submesh (and
        # every compiled collective) covers the LOCAL members, while
        # cross-process traffic rides the wire (hier coll + wire pml).
        # Single-controller: every member is local and nothing changes.
        if getattr(runtime, "unified", False):
            off = runtime.local_rank_offset
            cnt = runtime.local_size
            self.local_comm_ranks = [
                i for i, wr in enumerate(group.world_ranks)
                if off <= wr < off + cnt
            ]
            self.spans_processes = len(self.local_comm_ranks) < group.size
            local_positions = [
                group.world_rank(i) - off for i in self.local_comm_ranks
            ]
        else:
            self.local_comm_ranks = list(range(group.size))
            self.spans_processes = False
            local_positions = list(group.world_ranks)

        # sub-mesh over this group's LOCAL devices, 1-D "rank" axis:
        # collectives ride ICI in world-mesh order regardless of group
        # order (a comm with no local members carries no submesh and
        # installs no engines — its operations are never invoked here)
        if local_positions:
            self.submesh = build_submesh(runtime.mesh, local_positions)
        else:
            self.submesh = None

        # per-comm collective table (c_coll analogue), installed at
        # creation time exactly like coll_base_comm_select
        from ..coll import base as coll_base

        if self.submesh is not None:
            self.c_coll = coll_base.comm_select(self)
        else:
            self.c_coll = {}

        _comm_registry[self.cid] = self
        _comm_count.add()
        _log.verbose(2, f"created {self.name} cid={self.cid} size={self.size}")

    # -- queries -----------------------------------------------------------
    @property
    def size(self) -> int:
        return self.group.size

    def rank_of(self, world_rank: int) -> int:
        return self.group.rank_of(world_rank)

    @property
    def is_self(self) -> bool:
        return self.size == 1

    def _check_alive(self) -> None:
        if self._freed:
            raise MPIError(ErrorCode.ERR_COMM, f"{self.name} already freed")

    def _check_usable(self) -> None:
        """Alive AND not revoked: every communication entry point runs
        this (ULFM: all ops except agree/shrink/get_failed fail with
        ERR_REVOKED on a revoked communicator). One bool check — the
        flag is set by revoke() locally and by the FT watcher when a
        peer's poison frame arrives."""
        self._check_alive()
        if self._revoked:
            raise MPIError(
                ErrorCode.ERR_REVOKED,
                f"{self.name} (cid {self.cid}) has been revoked — "
                "shrink() or rebuild it to continue",
            )

    # -- construction ------------------------------------------------------
    def dup(self, name: str = "") -> "Communicator":
        self._check_alive()
        c = Communicator(
            self.runtime, self.group,
            name=name or f"dup({self.name})", parent=self, topo=self.topo,
        )
        # MPI_Comm_dup runs attribute copy callbacks
        for kv_id, value in list(self._attrs.items()):
            kv = _keyval_table.get(kv_id)
            if kv and kv.copy_fn:
                keep, new_val = kv.copy_fn(self, kv, value, kv.extra_state)
                if keep:
                    c._attrs[kv_id] = new_val
            elif kv:
                c._attrs[kv_id] = value
        return c

    def create(self, group: Group, name: str = "") -> Optional["Communicator"]:
        """MPI_Comm_create: new comm over a subgroup (None if empty)."""
        self._check_alive()
        if group.size == 0:
            return None
        for r in group.world_ranks:
            if self.group.rank_of(r) == UNDEFINED:
                raise MPIError(
                    ErrorCode.ERR_GROUP,
                    f"rank {r} not in parent {self.name}",
                )
        return Communicator(self.runtime, group, name=name, parent=self)

    def split(self, colors: Sequence[int], keys: Optional[Sequence[int]] = None
              ) -> List[Optional["Communicator"]]:
        """MPI_Comm_split, driver mode: per-rank colors/keys vectors.

        Returns one entry per local rank: the communicator that rank
        landed in (ranks sharing a color share the object), or None for
        color=UNDEFINED. Single-controller makes the exchange the
        reference does (allgather of color/key) a local sort.
        """
        self._check_alive()
        if len(colors) != self.size:
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"need {self.size} colors, got {len(colors)}",
            )
        keys = list(keys) if keys is not None else [0] * self.size
        buckets: Dict[int, List[Tuple[int, int]]] = {}
        for local, (color, key) in enumerate(zip(colors, keys)):
            if color == UNDEFINED:
                continue
            if color < 0:
                raise MPIError(ErrorCode.ERR_ARG, f"negative color {color}")
            buckets.setdefault(color, []).append((key, local))
        result: List[Optional[Communicator]] = [None] * self.size
        for color in sorted(buckets):
            members = sorted(buckets[color])  # by (key, local-rank), MPI rule
            g = Group([self.group.world_rank(l) for _, l in members])
            sub = Communicator(
                self.runtime, g,
                name=f"split({self.name},{color})", parent=self,
            )
            for _, local in members:
                result[local] = sub
        return result

    def split_type_shared(self) -> List["Communicator"]:
        """MPI_Comm_split_type(COMM_TYPE_SHARED): group by host process."""
        eps = {e.rank: e for e in self.runtime.endpoints}
        colors = [
            eps[self.group.world_rank(i)].process_index
            for i in range(self.size)
        ]
        return self.split(colors)  # type: ignore[return-value]

    def free(self) -> None:
        self._check_alive()
        fb = getattr(self, "_fusion_buffer", None)
        if fb is not None:
            # pending fused tensors drain before the comm dies —
            # freeing with queued submissions is a late flush, not a
            # lost handle
            fb.flush()
            self._fusion_buffer = None
        if self.spans_processes:
            # outstanding i-collectives must drain FIRST — before the
            # _on_free hooks free the hier shadow comm and the cid
            # leaves the registry, both of which a mid-flight spanning
            # collective still uses (MPI_Comm_free after pending
            # nonblocking ops is erroneous; draining turns it into a
            # late completion, not a crash)
            from ..coll import nbc as _nbc

            _nbc.drain_comm(self)
        for kv_id, value in list(self._attrs.items()):
            kv = _keyval_table.get(kv_id)
            if kv and kv.delete_fn:
                kv.delete_fn(self, kv, value, kv.extra_state)
        self._attrs.clear()
        # runtime-private dependents (e.g. the hier module's shadow
        # comm) registered teardown hooks: free them with their owner
        # or they leak registry entries for the owner's lifetime
        for cb in getattr(self, "_on_free", ()):
            try:
                cb()
            except MPIError:
                pass  # already freed
        _comm_registry.pop(self.cid, None)
        _sentinel.clear_chain(self.cid)
        from ..coll import plan as _coll_plan

        # frozen schedule plans die with their comm: a reused cid must
        # never fire a dead comm's compiled programs or wire rounds
        _coll_plan.clear_comm(self.cid)
        self._freed = True
        _comm_count.add(-1)

    # -- attributes (MPI keyvals) ------------------------------------------
    def set_attr(self, keyval: Keyval, value: Any) -> None:
        self._check_alive()
        self._attrs[keyval.id] = value

    def get_attr(self, keyval: Keyval) -> Tuple[bool, Any]:
        v = self._attrs.get(keyval.id, _MISSING)
        if v is _MISSING:
            return False, None
        return True, v

    def delete_attr(self, keyval: Keyval) -> None:
        v = self._attrs.pop(keyval.id, _MISSING)
        if v is not _MISSING and keyval.delete_fn:
            keyval.delete_fn(self, keyval, v, keyval.extra_state)

    # -- QoS (multi-tenant service plane) ----------------------------------
    @property
    def qos_class(self) -> Optional[str]:
        return self._qos_class

    def set_qos_class(self, cls: Optional[str]) -> None:
        """Stamp this communicator's QoS class (``wire_qos_classes``
        lane class + fair-share weight): a tenant job stamps its
        comms at admission, overriding the process-wide
        ``wire_qos_class`` cvar for exactly this comm tree (children
        created afterwards inherit). None reverts to the cvar."""
        self._check_alive()
        self._qos_class = str(cls) if cls else None

    # -- errors ------------------------------------------------------------
    def set_errhandler(self, handler: Errhandler) -> None:
        self.errhandler = handler

    def call_errhandler(self, err: MPIError) -> None:
        self.errhandler.invoke(self, err)

    def abort(self, errorcode: int = 1):
        """MPI_Abort analogue."""
        raise SystemExit(
            f"MPI_Abort on {self.name} with errorcode {errorcode}"
        )

    # -- ULFM fault tolerance (MPIX_Comm_revoke/shrink/agree) --------------
    def _member_procs(self) -> List[int]:
        """Process indices owning this comm's ranks (spanning comms;
        [my process] otherwise)."""
        if not self.spans_processes:
            return [int(self.runtime.bootstrap.get("process_index", 0))]
        from ..runtime.wire import proc_topology

        return proc_topology(self).procs

    @property
    def revoked(self) -> bool:
        return self._revoked

    def revoke(self) -> None:
        """``MPIX_Comm_revoke``: epoch-stamped poison. Marks the comm
        revoked locally (every pending bounded wait on its wire
        channels raises ERR_REVOKED within one slice, and queued
        progress-engine schedules complete in error without running),
        then pushes TAG_FT_REVOKE frames to every live peer process so
        THEIR pending ops are interrupted too. Idempotent; never
        raises on a dead peer — a corpse needs no poison."""
        self._check_alive()
        from ..ft import ulfm as _ulfm

        st = _ulfm.state()
        self._revoked = True
        first = st.apply_revoke(self.cid, st.epoch)
        agent = getattr(self.runtime, "agent", None)
        if not first or agent is None or not self.spans_processes:
            return
        from ..runtime.wire import proc_topology

        topo = proc_topology(self)
        for p in topo.peers:
            if p in st.failed:
                continue
            try:
                agent.ft_revoke_notify(p, self.cid, st.epoch)
            except MPIError:
                pass  # peer died between the check and the send
        _log.verbose(1, f"{self.name} revoked (epoch {st.epoch})")

    def get_failed(self) -> List[int]:
        """``MPIX_Comm_get_failed``: this comm's ranks owned by
        processes the job epoch marks failed."""
        self._check_alive()
        from ..ft import ulfm as _ulfm

        if not self.spans_processes:
            return []
        from ..runtime.wire import proc_topology

        topo = proc_topology(self)
        dead = set(_ulfm.state().dead_for(set(topo.owner),
                                          self._ft_epoch0))
        return [i for i in range(self.size) if topo.owner[i] in dead]

    def agree(self, flag: bool = True, *, aseq: Optional[int] = None,
              timeout_ms: int = 60_000) -> bool:
        """``MPIX_Comm_agree``: fault-tolerant AND of ``flag`` across
        the comm's LIVE member processes, arbitrated by the HNP
        coordinator (failed contributors are excused as the epoch
        marks them). Works on a revoked communicator — it is the one
        collective ULFM guarantees through failures."""
        self._check_alive()
        agent = getattr(self.runtime, "agent", None)
        if agent is None or not self.spans_processes:
            return bool(flag)
        if aseq is None:
            aseq = self._agree_counter = getattr(
                self, "_agree_counter", 0) + 1
        doc = agent.ft_agree(self.cid, aseq, 1 if flag else 0,
                             self._member_procs(), timeout_ms=timeout_ms)
        return bool(doc.get("flag", 0))

    def shrink(self, name: str = "", *,
               timeout_ms: int = 60_000) -> "Communicator":
        """``MPIX_Comm_shrink``: agree on the surviving group through
        the coordinator (every survivor receives ONE consistent
        epoch/failed snapshot), build a new communicator over it with
        a fresh epoch-derived cid — fresh wire channels, rebuilt
        hier/leader topology via the normal per-comm coll selection —
        and barrier the survivors on it to prove the wiring. Valid on
        a revoked (or failure-poisoned) communicator; the parent is
        left revoked."""
        self._check_alive()
        from ..ft import ulfm as _ulfm

        agent = getattr(self.runtime, "agent", None)
        if agent is None or not self.spans_processes:
            # no failure domain beyond this process: ULFM shrink of a
            # fault-free comm is a plain dup
            return self.dup(name or f"shrink({self.name})")
        from ..runtime.wire import proc_topology

        topo = proc_topology(self)
        aseq = self._agree_counter = getattr(
            self, "_agree_counter", 0) + 1
        doc = agent.ft_agree(self._ft_lineage, aseq, 1, topo.procs,
                             timeout_ms=timeout_ms)
        epoch = int(doc.get("epoch", 0))
        # dead FOR THIS COMM, from the agreement's ONE shared
        # snapshot: the transient failed set PLUS every process whose
        # failure episode began at/after this comm's birth epoch —
        # under the restart policy a corpse moves failed->restarted
        # within milliseconds of promotion, and a shrink that
        # re-included it would park the survivor barrier on a process
        # that never builds this cid
        failed = set(int(p) for p in doc.get("failed", ()))
        failed |= {p for p, e in _ulfm.failed_at_of(doc).items()
                   if e >= self._ft_epoch0}
        survivors = Group([
            self.group.world_rank(i) for i in range(self.size)
            if topo.owner[i] not in failed
        ])
        if survivors.size == 0:
            raise MPIError(ErrorCode.ERR_GROUP,
                           f"shrink({self.name}): no survivors")
        new = Communicator(
            self.runtime, survivors,
            name=name or f"shrink({self.name})", parent=self,
            cid=_ulfm.ft_cid(epoch, self._ft_lineage),
        )
        if new.spans_processes:
            wire = self.runtime.wire
            wire.proc_barrier(new, proc_topology(new).procs,
                              timeout_ms=timeout_ms)
        _log.verbose(
            1, f"shrink({self.name}) -> {new.name} cid={new.cid} "
               f"size={new.size} (epoch {epoch}, "
               f"failed procs {sorted(failed)})")
        return new

    # -- point-to-point (dispatched through the selected PML engine) -------
    @property
    def pml(self):
        """Per-comm PML engine, installed on first use
        (mca_pml_base_select analogue)."""
        eng = getattr(self, "_pml", None)
        if eng is None:
            self._check_alive()
            if self.submesh is None:
                raise MPIError(
                    ErrorCode.ERR_COMM,
                    f"{self.name} has no members on this controller "
                    "process — its operations can only be invoked on "
                    "the processes that own its ranks",
                )
            from ..p2p import pml as pml_mod

            eng = pml_mod.comm_select(self)
            self._pml = eng
        return eng

    def isend(self, data, dest: int, tag: int = 0, *, rank: int, **kw):
        """Nonblocking send issued by ``rank`` (driver mode: the acting
        rank is explicit because one controller plays every rank)."""
        self._check_usable()
        return self.pml.isend(data, dest, tag, src=rank, **kw)

    def send(self, data, dest: int, tag: int = 0, *, rank: int, **kw):
        self._check_usable()
        return self.pml.send(data, dest, tag, src=rank, **kw)

    def irecv(self, source: int = -1, tag: int = -1, *, rank: int):
        self._check_usable()
        return self.pml.irecv(source, tag, dst=rank)

    def recv(self, source: int = -1, tag: int = -1, *, rank: int):
        self._check_usable()
        return self.pml.recv(source, tag, dst=rank)

    def iprobe(self, source: int = -1, tag: int = -1, *, rank: int):
        self._check_usable()
        return self.pml.iprobe(source, tag, dst=rank)

    def sendrecv(self, sendbufs, dests, sendtag: int = 0,
                 sources=None, recvtag: int = -1):
        """MPI_Sendrecv, driver mode: EVERY rank's exchange in one call
        (like split's per-rank vectors) — all sends post first, then
        all recvs complete, which is what makes it deadlock-free. A
        per-rank blocking sendrecv cannot work under a single
        controller: rank 0's recv would block before rank 1 ever ran.

        sendbufs/dests (and optional sources): sequences of length
        ``size``. Returns (values, statuses) lists.
        """
        self._check_usable()
        if self.spans_processes:
            raise MPIError(
                ErrorCode.ERR_NOT_AVAILABLE,
                "driver-mode sendrecv acts as every rank at once; on a "
                "communicator spanning controller processes use "
                "per-rank isend/recv (each process acts only as its "
                "local ranks)",
            )
        n = self.size
        if (len(sendbufs) != n or len(dests) != n
                or (sources is not None and len(sources) != n)):
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"sendrecv needs {n} sendbufs/dests/sources "
                "(one per rank)",
            )
        sreqs = [
            self.pml.isend(sendbufs[r], dests[r], sendtag, src=r)
            for r in range(n)
        ]
        values, statuses = [], []
        for r in range(n):
            src = sources[r] if sources is not None else -1
            v, st = self.pml.recv(src, recvtag, dst=r)
            values.append(v)
            statuses.append(st)
        for sr in sreqs:
            sr.wait()
        return values, statuses

    # -- collectives (dispatch through the installed c_coll table) ---------
    def _coll(self, op_name: str) -> Callable:
        self._check_usable()
        fn = self.c_coll.get(op_name)
        if fn is None:
            raise MPIError(
                ErrorCode.ERR_INTERN,
                f"no {op_name} implementation installed on {self.name}",
            )
        if not self.spans_processes:
            # steady-state compiled dispatch (coll/plan): a signature
            # seen before fires its frozen compiled program — the
            # interpreted decision path runs once per (signature,
            # cvar generation), not once per call
            from ..coll import plan as _plan

            if _sentinel.enabled:
                # contract sentinel: in-process collectives fold into
                # the comm's signature chain too (chain determinism,
                # the post-hoc journal record); spanning comms note
                # inside nbc.run_blocking where the args are bound
                def noted(comm_, *a, **k):
                    _sentinel.note(self, op_name, a, k)
                    return _plan.dispatch(comm_, op_name, fn, a, k)

                return noted
            return lambda comm_, *a, **k: _plan.dispatch(
                comm_, op_name, fn, a, k)
        # fast ULFM fail: a collective involves every member, so a
        # known-failed member process fails the op NOW with the typed
        # error instead of posting a schedule doomed to park
        from ..ft import ulfm as _ulfm

        _ulfm.state().check_wait(
            self.cid, self._member_procs(),
            f"collective {op_name} on {self.name} with member process",
            epoch0=self._ft_epoch0)
        # spanning comms: EVERY collective — blocking or not — goes
        # through the async progress engine as "post schedule + wait",
        # so blocking and nonblocking calls execute in posting order on
        # every process (their wire exchanges share one per-cid
        # channel, and two concurrently-running collectives would
        # interleave frames on it) and there is ONE round-advancing
        # code path (coll/nbc + runtime/progress)
        from ..coll import nbc as _nbc

        return lambda comm_, *a, **k: _nbc.run_blocking(
            self, op_name, fn, (comm_,) + a, k)

    def _run_serialized(self, fn, *args, **kw):
        """Run ``fn`` in the comm's collective posting order, blocking
        (the two-phase collective-IO path): fire + wait through the
        progress engine on spanning comms, a direct call otherwise."""
        if not self.spans_processes:
            return fn(*args, **kw)
        from ..coll import nbc as _nbc

        return _nbc.run_blocking(
            self, getattr(fn, "__name__", "serialized"), fn, args, kw)

    def _submit_serialized(self, fn, *args, **kw):
        """Nonblocking run of ``fn`` in the comm's collective posting
        order (the nonblocking collective-IO path): returns a Request
        backed by a schedule posted to the progress engine."""
        from ..coll import nbc as _nbc

        return _nbc.submit(self, getattr(fn, "__name__", "serialized"),
                           fn, args, kw)

    def _async(self, value):
        """Wrap already-dispatched future arrays as a Request (XLA
        async dispatch is the round schedule; see coll/nbc)."""
        from ..coll import nbc as _nbc

        return _nbc.async_request(value)

    def allreduce(self, x, op=None, **kw):
        from .. import ops as ops_mod

        return self._coll("allreduce")(self, x, op or ops_mod.SUM, **kw)

    def reduce(self, x, op=None, root: int = 0, **kw):
        from .. import ops as ops_mod

        return self._coll("reduce")(self, x, op or ops_mod.SUM, root, **kw)

    def bcast(self, x, root: int = 0, **kw):
        return self._coll("bcast")(self, x, root, **kw)

    def allgather(self, x, **kw):
        return self._coll("allgather")(self, x, **kw)

    def gather(self, x, root: int = 0, **kw):
        return self._coll("gather")(self, x, root, **kw)

    def scatter(self, x, root: int = 0, **kw):
        return self._coll("scatter")(self, x, root, **kw)

    def reduce_scatter_block(self, x, op=None, **kw):
        from .. import ops as ops_mod

        return self._coll("reduce_scatter_block")(
            self, x, op or ops_mod.SUM, **kw
        )

    def alltoall(self, x, **kw):
        return self._coll("alltoall")(self, x, **kw)

    def scan(self, x, op=None, **kw):
        from .. import ops as ops_mod

        return self._coll("scan")(self, x, op or ops_mod.SUM, **kw)

    def exscan(self, x, op=None, **kw):
        from .. import ops as ops_mod

        return self._coll("exscan")(self, x, op or ops_mod.SUM, **kw)

    def barrier(self) -> None:
        self._coll("barrier")(self)

    # -- small-message fusion (coll/fusion.py) -----------------------------
    def fusion_buffer(self):
        """This communicator's small-message fusion buffer (Horovod
        fusion-buffer / BTL-coalescing analogue): collectives below
        ``coll_fusion_threshold`` pack into one fused device
        collective per (op, dtype). Created lazily, one per comm;
        FusionBuffer is documented thread-safe, so first use may be
        concurrent — creation must not orphan a racing instance."""
        fb = getattr(self, "_fusion_buffer", None)
        if fb is None:
            from ..coll.fusion import FusionBuffer

            with _fusion_create_lock:
                fb = getattr(self, "_fusion_buffer", None)
                if fb is None:
                    fb = FusionBuffer(self)
                    self._fusion_buffer = fb
        return fb

    def fused_allreduce(self, x, op=None):
        """Allreduce through the fusion buffer: small tensors coalesce
        with concurrent submissions (flush with
        ``comm.fusion_buffer().flush()`` or the handle's ``result()``);
        large ones dispatch immediately. Returns a
        :class:`~..coll.fusion.FusedHandle`."""
        return self.fusion_buffer().allreduce(x, op)

    # -- v-variant collectives (per-rank counts; ragged driver edge) -------
    def alltoallv(self, sendbufs, sendcounts):
        """MPI_Alltoallv: ``sendbufs[i]`` holds rank i's chunks for
        ranks 0..n-1 back to back, ``sendcounts[i][j]`` elements for
        rank j. Returns ``recv[i]`` = chunks from each source, in
        source order."""
        return self._coll("alltoallv")(self, sendbufs, sendcounts)

    def allgatherv(self, sendbufs):
        """MPI_Allgatherv: ragged per-rank buffers, concatenated in
        rank order (identical on all ranks — returned once)."""
        return self._coll("allgatherv")(self, sendbufs)

    def gatherv(self, sendbufs, root: int = 0):
        return self._coll("gatherv")(self, sendbufs, root)

    def scatterv(self, sendbuf, counts, root: int = 0):
        """MPI_Scatterv: root's buffer split into counts[i] elements
        per rank; returns one array per rank."""
        return self._coll("scatterv")(self, sendbuf, counts, root)

    def reduce_scatter(self, x, recvcounts, op=None):
        """General MPI_Reduce_scatter with per-rank recv counts."""
        from .. import ops as ops_mod

        return self._coll("reduce_scatter")(
            self, x, recvcounts, op or ops_mod.SUM
        )

    # -- nonblocking collectives (libnbc analogue; coll/nbc.py) ------------
    # In-process comms: XLA dispatch is already asynchronous — the
    # compiled program IS the libnbc round schedule, and the Request
    # wraps its future arrays. Spanning comms: the whole schedule posts
    # to the async progress engine (runtime/progress.py) — dispatch
    # returns before any wire traffic; execution happens in posting
    # order, at wait() (polling mode) or off the caller on the
    # dedicated progress thread (``progress_thread`` cvar).
    def _icoll(self, name: str, *args, **kw):
        from ..coll import nbc as _nbc

        return _nbc.icoll(self, name, args, kw)

    def iallreduce(self, x, op=None, **kw):
        from .. import ops as ops_mod

        return self._icoll("allreduce", x, op or ops_mod.SUM, **kw)

    def ireduce(self, x, op=None, root: int = 0, **kw):
        from .. import ops as ops_mod

        return self._icoll("reduce", x, op or ops_mod.SUM, root, **kw)

    def ibcast(self, x, root: int = 0, **kw):
        return self._icoll("bcast", x, root, **kw)

    def iallgather(self, x, **kw):
        return self._icoll("allgather", x, **kw)

    def igather(self, x, root: int = 0, **kw):
        return self._icoll("gather", x, root, **kw)

    def iscatter(self, x, root: int = 0, **kw):
        return self._icoll("scatter", x, root, **kw)

    def ireduce_scatter_block(self, x, op=None, **kw):
        from .. import ops as ops_mod

        return self._icoll("reduce_scatter_block", x,
                           op or ops_mod.SUM, **kw)

    def ireduce_scatter(self, x, recvcounts, op=None):
        from .. import ops as ops_mod

        return self._icoll("reduce_scatter", x, recvcounts,
                           op or ops_mod.SUM)

    def ialltoall(self, x, **kw):
        return self._icoll("alltoall", x, **kw)

    def iscan(self, x, op=None, **kw):
        from .. import ops as ops_mod

        return self._icoll("scan", x, op or ops_mod.SUM, **kw)

    def iexscan(self, x, op=None, **kw):
        from .. import ops as ops_mod

        return self._icoll("exscan", x, op or ops_mod.SUM, **kw)

    def ialltoallv(self, sendbufs, sendcounts):
        return self._icoll("alltoallv", sendbufs, sendcounts)

    def iallgatherv(self, sendbufs):
        return self._icoll("allgatherv", sendbufs)

    # -- persistent collectives (MPI-4 *_init; coll/nbc.persistent) --------
    # The plan — resolved dispatch entry, op object, bound buffers —
    # is built ONCE here; Request.start() fires it against the
    # buffers' CURRENT contents each time without blocking (compiled
    # programs / fusion plans are cached, so starts after the first
    # fire cached plans).
    def allreduce_init(self, x, op=None, **kw):
        from .. import ops as ops_mod
        from ..coll import nbc as _nbc

        return _nbc.persistent(self, "allreduce",
                               (x, op or ops_mod.SUM), kw)

    def bcast_init(self, x, root: int = 0, **kw):
        from ..coll import nbc as _nbc

        return _nbc.persistent(self, "bcast", (x, root), kw)

    def allgather_init(self, x, **kw):
        from ..coll import nbc as _nbc

        return _nbc.persistent(self, "allgather", (x,), kw)

    def reduce_scatter_init(self, x, recvcounts, op=None):
        from .. import ops as ops_mod
        from ..coll import nbc as _nbc

        return _nbc.persistent(self, "reduce_scatter",
                               (x, recvcounts, op or ops_mod.SUM))

    def alltoall_init(self, x, **kw):
        from ..coll import nbc as _nbc

        return _nbc.persistent(self, "alltoall", (x,), kw)

    def barrier_init(self):
        from ..coll import nbc as _nbc

        return _nbc.persistent(self, "barrier", ())

    def ibarrier(self):
        """Nonblocking barrier that really is nonblocking: the
        compiled barrier program is dispatched asynchronously and the
        returned request's readiness is the dispatch's readiness (the
        reference's libnbc round schedule, ``nbc.c``, becomes the
        compiled program; XLA async dispatch is the progress engine).
        Spanning comms post the barrier schedule to the progress
        engine — an ibarrier posted between two iallreduces keeps its
        posting-order slot across every process. Providers without an
        async dispatch path run the blocking barrier on a completion
        thread instead — either way ibarrier returns before the
        barrier completes."""
        self._check_usable()
        from ..coll import nbc as _nbc

        if self.spans_processes:
            return _nbc.icoll(self, "barrier", ())
        fn = self.c_coll.get("ibarrier")
        if fn is not None:
            if _sentinel.enabled:
                # the native async-dispatch branch bypasses both the
                # _coll wrapper and nbc.icoll — without this note it
                # would be the one unhashed collective entry
                _sentinel.note(self, "barrier")
            return _nbc.async_request(fn(self))

        import threading

        from ..request.request import Request

        done = threading.Event()
        errs: list = []

        def run() -> None:
            try:
                self.barrier()
            except Exception as exc:  # surfaced at wait()
                errs.append(exc)
            finally:
                done.set()

        threading.Thread(target=run, daemon=True).start()

        def block() -> None:
            done.wait()
            if errs:
                raise errs[0]

        # a failed barrier must surface through test() as well as
        # wait(): the progress hook (polled by test) raises the stored
        # error — the MPI_ERRORS_ARE_FATAL convention this layer uses
        # — instead of reporting completion or pending forever
        def progress(req) -> None:
            if done.is_set() and errs:
                raise errs[0]

        return Request(
            progress_fn=progress,
            ready_fn=lambda: done.is_set() and not errs,
            block_fn=block,
        )

    def __repr__(self) -> str:
        return (
            f"Communicator({self.name}, cid={self.cid}, size={self.size})"
        )


_MISSING = object()
_keyval_table: Dict[int, Keyval] = {}


def create_keyval(copy_fn=None, delete_fn=None, extra_state=None) -> Keyval:
    kv = Keyval(copy_fn, delete_fn, extra_state)
    _keyval_table[kv.id] = kv
    return kv


def free_keyval(kv: Keyval) -> None:
    _keyval_table.pop(kv.id, None)
