"""Communicator/group layer (ompi/communicator + ompi/group analogue)."""

from .group import EMPTY, IDENT, SIMILAR, UNDEFINED, UNEQUAL, Group
from .communicator import (
    Communicator, Keyval, clear_comm_registry, create_keyval, free_keyval,
)
from .world import create_world

__all__ = [
    "Group", "EMPTY", "IDENT", "SIMILAR", "UNEQUAL", "UNDEFINED",
    "Communicator", "Keyval", "create_keyval", "free_keyval",
    "clear_comm_registry", "create_world",
]
