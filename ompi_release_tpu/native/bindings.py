"""ctypes wrappers over the native DSS + OOB library."""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import threading
from typing import List, Optional, Tuple, Union

from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.stream("native")

#: OOB tag space: tags below this are reserved for the control plane
#: (coordinator wire-up 1-8, pubsub 9-12); user payload transports
#: (staged DCN, shm handoff, spawn messaging) must use tags >= this
USER_TAG_BASE = 100

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "build", "libompitpu_native.so")

_lib = None
_lib_lock = threading.Lock()

#: stamp inputs — must match the Makefile's STAMP_SRCS list (same
#: files; order is irrelevant, the comparison is by name)
_STAMP_INPUTS = ("dss.cc", "oob.cc", "btl_tcp.cc", "btl_shm.cc",
                 "nativeev.cc", "planexec.cc", "oob_endpoint.h",
                 "nativeev.h", "Makefile")
_STAMP_PATH = os.path.join(_NATIVE_DIR, "build", ".srcstamp")


def _stamp_current() -> bool:
    """True when build/.srcstamp matches the sha256 of every stamp
    input — i.e. the .so was linked from exactly these sources and
    `make` would be a no-op. Content hashes, not mtimes: fresh git
    checkouts and build caches produce equal/reordered mtimes where a
    newer-than check lies in both directions. A missing or short
    stamp (pre-stamp build tree) just means 'run make once'."""
    import hashlib

    try:
        with open(_STAMP_PATH) as f:
            stamped = {}
            for line in f:
                parts = line.split()
                if len(parts) >= 2:
                    stamped[parts[-1]] = parts[0]
    except OSError:
        return False
    for name in _STAMP_INPUTS:
        path = os.path.join(_NATIVE_DIR, name)
        if not os.path.exists(path):
            continue  # optional source absent on both sides is fine
        try:
            with open(path, "rb") as f:
                digest = hashlib.sha256(f.read()).hexdigest()
        except OSError:
            return False
        if stamped.get(name) != digest:
            return False
    return True


def load_library() -> ctypes.CDLL:
    """Load (building if needed) the native library.

    An up-to-date .so skips the compiler entirely: the Makefile stamps
    each successful link with the sha256 of its inputs, and this check
    re-hashes them in-process — a few hashlib calls per interpreter
    instead of a `make -s all` subprocess whose no-op still costs a
    fork+exec+stat storm (tier-1 job tests pay it once per worker)."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO_PATH) or not _stamp_current():
            _log.verbose(1, "building native control-plane library")
            r = subprocess.run(
                ["make", "-s", "all"], cwd=_NATIVE_DIR,
                capture_output=True, text=True,
            )
            if r.returncode != 0:
                raise MPIError(
                    ErrorCode.ERR_OTHER,
                    f"native build failed:\n{r.stdout}\n{r.stderr}",
                )
        lib = ctypes.CDLL(_SO_PATH)
        _declare(lib)
        _lib = lib
        return lib


def _declare(lib: ctypes.CDLL) -> None:
    P = ctypes.c_void_p
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f64p = ctypes.POINTER(ctypes.c_double)
    i32p = ctypes.POINTER(ctypes.c_int32)

    lib.dss_new.restype = P
    lib.dss_free.argtypes = [P]
    lib.dss_data.argtypes = [P]
    lib.dss_data.restype = u8p
    lib.dss_size.argtypes = [P]
    lib.dss_size.restype = ctypes.c_int64
    lib.dss_rewind.argtypes = [P]
    lib.dss_from_bytes.argtypes = [u8p, ctypes.c_int64]
    lib.dss_from_bytes.restype = P
    lib.dss_pack_int64.argtypes = [P, i64p, ctypes.c_int32]
    lib.dss_pack_double.argtypes = [P, f64p, ctypes.c_int32]
    lib.dss_pack_string.argtypes = [P, ctypes.c_char_p]
    lib.dss_pack_bytes.argtypes = [P, u8p, ctypes.c_int32]
    lib.dss_peek.argtypes = [P, i32p, i32p]
    lib.dss_unpack_int64.argtypes = [P, i64p, ctypes.c_int32]
    lib.dss_unpack_double.argtypes = [P, f64p, ctypes.c_int32]
    lib.dss_unpack_string.argtypes = [P, ctypes.c_char_p, ctypes.c_int32]
    lib.dss_unpack_bytes.argtypes = [P, u8p, ctypes.c_int32]

    lib.oob_create.argtypes = [ctypes.c_int32, ctypes.c_int]
    lib.oob_create.restype = P
    lib.oob_create_bound.argtypes = [ctypes.c_int32, ctypes.c_int,
                                     ctypes.c_char_p]
    lib.oob_create_bound.restype = P
    lib.oob_port.argtypes = [P]
    lib.oob_port.restype = ctypes.c_int
    lib.oob_connect.argtypes = [P, ctypes.c_int32, ctypes.c_char_p,
                                ctypes.c_int]
    lib.oob_connect.restype = ctypes.c_int
    lib.oob_add_route.argtypes = [P, ctypes.c_int32, ctypes.c_int32]
    lib.oob_send.argtypes = [P, ctypes.c_int32, ctypes.c_int32, u8p,
                             ctypes.c_int32]
    lib.oob_send.restype = ctypes.c_int
    lib.oob_recv.argtypes = [P, i32p, i32p, u8p, ctypes.c_int32,
                             ctypes.c_int]
    lib.oob_recv.restype = ctypes.c_int
    lib.oob_pending.argtypes = [P]
    lib.oob_pending.restype = ctypes.c_int
    lib.oob_ttl_dropped.argtypes = [P]
    lib.oob_ttl_dropped.restype = ctypes.c_int
    lib.oob_create_auth.argtypes = [ctypes.c_int32, ctypes.c_int,
                                    ctypes.c_char_p, u8p,
                                    ctypes.c_int32]
    lib.oob_create_auth.restype = P
    lib.oob_auth_rejected.argtypes = [P]
    lib.oob_auth_rejected.restype = ctypes.c_int
    lib.oob_next_len.argtypes = [P, ctypes.c_int32, ctypes.c_int]
    lib.oob_next_len.restype = ctypes.c_int
    lib.oob_destroy.argtypes = [P]

    # nativewire datapath symbols are OPTIONAL: a stale .so built from
    # pre-nativewire sources simply lacks them, and the component
    # withdraws from selection (wire_symbols_available) — declaring
    # them is therefore guarded, never assumed
    vpp = ctypes.POINTER(ctypes.c_void_p)
    if hasattr(lib, "wire_sendv"):
        lib.wire_sendv.argtypes = [P, ctypes.c_int32, ctypes.c_int32,
                                   vpp, i64p, ctypes.c_int32]
        lib.wire_sendv.restype = ctypes.c_int
        lib.wire_recv_frag.argtypes = [
            P, ctypes.c_int32, ctypes.c_int32, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, P, ctypes.c_int64,
            ctypes.c_int,
        ]
        lib.wire_recv_frag.restype = ctypes.c_int64
    if hasattr(lib, "wire_stats"):
        lib.wire_stats.argtypes = [P, ctypes.c_int32]
        lib.wire_stats.restype = ctypes.c_int64
    if hasattr(lib, "shmring_create"):
        lib.shmring_create.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                       ctypes.c_int64]
        lib.shmring_create.restype = P
        lib.shmring_attach.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.shmring_attach.restype = P
        lib.shmring_unlink.argtypes = [ctypes.c_char_p]
        lib.shmring_unlink.restype = ctypes.c_int
        lib.shmring_close.argtypes = [P]
        for f in ("shmring_capacity", "shmring_producer_pid",
                  "shmring_consumer_pid", "shmring_pending"):
            getattr(lib, f).argtypes = [P]
            getattr(lib, f).restype = ctypes.c_int64
        lib.shmring_writev.argtypes = [P, ctypes.c_int32, vpp, i64p,
                                       ctypes.c_int32, ctypes.c_int]
        lib.shmring_writev.restype = ctypes.c_int
        lib.shmring_read_frag.argtypes = [
            P, ctypes.c_int32, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, P, ctypes.c_int64, ctypes.c_int,
        ]
        lib.shmring_read_frag.restype = ctypes.c_int64
        lib.shmring_read_into.argtypes = [P, i32p, P, ctypes.c_int64,
                                          ctypes.c_int]
        lib.shmring_read_into.restype = ctypes.c_int64
    if hasattr(lib, "shmring_stat"):
        lib.shmring_stat.argtypes = [P, ctypes.c_int32]
        lib.shmring_stat.restype = ctypes.c_int64
    if hasattr(lib, "nativeev_create"):
        lib.nativeev_create.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.nativeev_create.restype = P
        lib.nativeev_attach.argtypes = [ctypes.c_char_p]
        lib.nativeev_attach.restype = P
        lib.nativeev_unlink.argtypes = [ctypes.c_char_p]
        lib.nativeev_unlink.restype = ctypes.c_int
        lib.nativeev_close.argtypes = [P]
        lib.nativeev_install.argtypes = [P]
        lib.nativeev_nslots.argtypes = [P]
        lib.nativeev_nslots.restype = ctypes.c_int64
        lib.nativeev_count.argtypes = [P]
        lib.nativeev_count.restype = ctypes.c_int64
        lib.nativeev_read.argtypes = [P, ctypes.c_int64, P,
                                      ctypes.c_int64, i64p]
        lib.nativeev_read.restype = ctypes.c_int64
    if hasattr(lib, "planexec_create"):
        lib.planexec_create.argtypes = [u8p, ctypes.c_int64]
        lib.planexec_create.restype = P
        lib.planexec_destroy.argtypes = [P]
        lib.planexec_bind.argtypes = [P, P, ctypes.c_int64, i64p,
                                      vpp, vpp, ctypes.c_int64]
        lib.planexec_bind.restype = ctypes.c_int
        lib.planexec_set_ftword.argtypes = [P, i64p]
        lib.planexec_fire_begin.argtypes = [P, vpp, i64p,
                                            ctypes.c_int64,
                                            ctypes.c_int64,
                                            ctypes.c_int64]
        lib.planexec_fire_begin.restype = ctypes.c_int
        lib.planexec_fire_step.argtypes = [P, ctypes.c_int64]
        lib.planexec_fire_step.restype = ctypes.c_int
        lib.planexec_pool_ptr.argtypes = [P]
        lib.planexec_pool_ptr.restype = P
        lib.planexec_ts_ptr.argtypes = [P]
        lib.planexec_ts_ptr.restype = ctypes.POINTER(ctypes.c_double)
        for f in ("planexec_pool_total", "planexec_pool_count",
                  "planexec_round_count", "planexec_input_count",
                  "planexec_err_peer", "planexec_err_round",
                  "planexec_stash_count"):
            getattr(lib, f).argtypes = [P]
            getattr(lib, f).restype = ctypes.c_int64
        lib.planexec_stash_info.argtypes = [P, ctypes.c_int64, i64p,
                                            i64p, i64p]
        lib.planexec_stash_info.restype = ctypes.c_int64
        lib.planexec_stash_data.argtypes = [P, ctypes.c_int64]
        lib.planexec_stash_data.restype = P
        lib.planexec_stash_clear.argtypes = [P]


def wire_symbols_available() -> bool:
    """True when the loaded .so carries the nativewire datapath ABI
    (wire_sendv/shmring_*). False — never an exception — when the
    library is stale, unbuildable, or the build toolchain is absent:
    callers treat that as 'capability not present' and stay on the
    portable staged path."""
    try:
        lib = load_library()
    except Exception:
        return False
    return hasattr(lib, "wire_sendv") and hasattr(lib, "shmring_create")


def telemetry_symbols_available() -> bool:
    """True when the loaded .so carries the native telemetry ABI
    (shmring_stat / wire_stats / nativeev_*). Same never-raises
    discipline as :func:`wire_symbols_available`: a stale .so built
    before the telemetry block means 'capability absent', and the
    observability layers simply stay dark for the native plane."""
    try:
        lib = load_library()
    except Exception:
        return False
    return (hasattr(lib, "shmring_stat") and hasattr(lib, "wire_stats")
            and hasattr(lib, "nativeev_create"))


def planexec_symbols_available() -> bool:
    """True when the loaded .so carries the native plan-executor ABI
    (planexec_*). Same never-raises discipline as
    :func:`wire_symbols_available`: a stale .so means 'capability
    absent' and compiled plans keep firing through the interpreted
    PlannedXchg replay."""
    try:
        lib = load_library()
    except Exception:
        return False
    return (hasattr(lib, "planexec_create")
            and hasattr(lib, "wire_sendv")
            and hasattr(lib, "shmring_create"))


def _u8(data: bytes):
    return ctypes.cast(
        ctypes.create_string_buffer(data, len(data)),
        ctypes.POINTER(ctypes.c_uint8),
    )


def _sg_arrays(parts):
    """(void* array, int64 array, keepalive list) for a scatter-gather
    list of bytes/bytearray/memoryview/ndarray parts — pointers into
    the callers' existing buffers, NO staging copies (the whole point
    of the native wire). The keepalive list must stay referenced until
    the C call returns."""
    import numpy as _np

    n = len(parts)
    ptrs = (ctypes.c_void_p * n)()
    lens = (ctypes.c_int64 * n)()
    keep = []
    for i, p in enumerate(parts):
        if isinstance(p, bytes):
            # c_char_p aliases the bytes object's internal buffer
            ptrs[i] = ctypes.cast(ctypes.c_char_p(p),
                                  ctypes.c_void_p)
            lens[i] = len(p)
            keep.append(p)
        else:
            a = _np.frombuffer(p, dtype=_np.uint8)  # zero-copy view
            ptrs[i] = ctypes.c_void_p(a.ctypes.data)
            lens[i] = a.nbytes
            keep.append(a)
    return ptrs, lens, keep


def _wbuf_ptr(buf):
    """(void* base, nbytes, keepalive) for a WRITABLE reassembly
    buffer (bytearray / writable memoryview / ndarray)."""
    import numpy as _np

    a = _np.frombuffer(buf, dtype=_np.uint8)
    if not a.flags.writeable:
        raise MPIError(ErrorCode.ERR_OTHER,
                       "recv_into target buffer is read-only")
    return ctypes.c_void_p(a.ctypes.data), a.nbytes, a


class DssBuffer:
    """Typed pack/unpack buffer (opal/dss analogue)."""

    TYPES = {1: "int64", 2: "double", 3: "string", 4: "bytes"}

    def __init__(self, raw: Optional[bytes] = None) -> None:
        self._lib = load_library()
        if raw is None:
            self._h = self._lib.dss_new()
        else:
            self._h = self._lib.dss_from_bytes(_u8(raw), len(raw))

    def close(self) -> None:
        if self._h:
            self._lib.dss_free(self._h)
            self._h = None

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass

    # -- pack --------------------------------------------------------------
    def pack_int64(self, vals: Union[int, List[int]]) -> "DssBuffer":
        vals = [vals] if isinstance(vals, int) else list(vals)
        arr = (ctypes.c_int64 * len(vals))(*vals)
        self._lib.dss_pack_int64(self._h, arr, len(vals))
        return self

    def pack_double(self, vals: Union[float, List[float]]) -> "DssBuffer":
        vals = [vals] if isinstance(vals, float) else list(vals)
        arr = (ctypes.c_double * len(vals))(*vals)
        self._lib.dss_pack_double(self._h, arr, len(vals))
        return self

    def pack_string(self, s: str) -> "DssBuffer":
        self._lib.dss_pack_string(self._h, s.encode())
        return self

    def pack_bytes(self, b: bytes) -> "DssBuffer":
        self._lib.dss_pack_bytes(self._h, _u8(b), len(b))
        return self

    # -- unpack ------------------------------------------------------------
    def peek(self) -> Optional[Tuple[str, int]]:
        t = ctypes.c_int32()
        c = ctypes.c_int32()
        if self._lib.dss_peek(self._h, ctypes.byref(t),
                              ctypes.byref(c)) != 0:
            return None
        return self.TYPES.get(t.value, "?"), c.value

    def _check(self, n: int, what: str) -> int:
        if n == -2:
            raise MPIError(
                ErrorCode.ERR_TYPE,
                f"dss unpack type mismatch: next item is "
                f"{self.peek()}, wanted {what}",
            )
        if n < 0:
            raise MPIError(ErrorCode.ERR_TRUNCATE,
                           f"dss buffer exhausted unpacking {what}")
        return n

    def unpack_int64(self, max_count: int = 1_048_576) -> List[int]:
        arr = (ctypes.c_int64 * max_count)()
        n = self._check(
            self._lib.dss_unpack_int64(self._h, arr, max_count), "int64"
        )
        return list(arr[:n])

    def unpack_double(self, max_count: int = 1_048_576) -> List[float]:
        arr = (ctypes.c_double * max_count)()
        n = self._check(
            self._lib.dss_unpack_double(self._h, arr, max_count), "double"
        )
        return list(arr[:n])

    def unpack_string(self, max_len: int = 1 << 20) -> str:
        buf = ctypes.create_string_buffer(max_len)
        self._check(
            self._lib.dss_unpack_string(self._h, buf, max_len), "string"
        )
        return buf.value.decode()

    def unpack_bytes(self, max_len: int = 1 << 26) -> bytes:
        arr = (ctypes.c_uint8 * max_len)()
        n = self._check(
            self._lib.dss_unpack_bytes(self._h, arr, max_len), "bytes"
        )
        return bytes(arr[:n])

    # -- raw ---------------------------------------------------------------
    def tobytes(self) -> bytes:
        n = self._lib.dss_size(self._h)
        p = self._lib.dss_data(self._h)
        return ctypes.string_at(p, n)  # one memcpy, not a Python loop

    def rewind(self) -> None:
        self._lib.dss_rewind(self._h)


#: env var carrying the per-job control-plane secret (minted by tpurun,
#: inherited by every worker it launches) — see SECRET_ENV consumers in
#: tools/tpurun.py and tools/tpu_server.py
SECRET_ENV = "OMPITPU_JOB_SECRET"


class OobEndpoint:
    """Tagged TCP messaging endpoint with tree routing (oob/rml/routed
    analogue).

    Authentication (``opal/mca/sec`` analogue): when ``secret`` is
    given — or ``OMPITPU_JOB_SECRET`` is set, which tpurun exports to
    every worker — inbound connections must answer a fresh-nonce
    SipHash challenge before any of their frames are accepted, and
    outbound connects answer the peer's challenge. ``secret=b""``
    explicitly disables auth regardless of the environment."""

    def __init__(self, node_id: int, port: int = 0,
                 bind_addr: str = "127.0.0.1",
                 secret: Optional[bytes] = None) -> None:
        import os as _os

        self._lib = load_library()
        if secret is None:
            env = _os.environ.get(SECRET_ENV, "")
            secret = env.encode() if env else b""
        # the secret rides the CREATE call: installed before the
        # listener accepts its first connection, so there is no window
        # in which an unauthenticated connection can be admitted
        self._h = self._lib.oob_create_auth(
            node_id, port, bind_addr.encode(),
            _u8(secret) if secret else None, len(secret),
        )
        if not self._h:
            raise MPIError(ErrorCode.ERR_OTHER,
                           f"oob_create failed ({bind_addr}:{port})")
        self.node_id = node_id

    def auth_rejected(self) -> int:
        """Inbound connections refused by the auth challenge."""
        return self._lib.oob_auth_rejected(self._handle())

    def _handle(self):
        """The live native handle; a closed endpoint raises a clean
        MPIError instead of handing NULL to the C layer (which
        segfaults — observed via use-after-close in spawn teardown)."""
        h = self._h
        if not h:
            raise MPIError(ErrorCode.ERR_OTHER,
                           "oob endpoint is closed")
        return h

    @property
    def port(self) -> int:
        return self._lib.oob_port(self._handle())

    def connect(self, peer_id: int, host: str, port: int) -> None:
        if self._lib.oob_connect(self._handle(), peer_id, host.encode(),
                                 port) != 0:
            raise MPIError(
                ErrorCode.ERR_OTHER,
                f"oob connect to node {peer_id} at {host}:{port} failed",
            )

    def add_route(self, dst: int, via: int) -> None:
        self._lib.oob_add_route(self._handle(), dst, via)

    def set_default_route(self, via: int) -> None:
        self._lib.oob_add_route(self._handle(), -1, via)

    def send(self, dst: int, tag: int, payload: bytes) -> None:
        if self._lib.oob_send(self._handle(), dst, tag, _u8(payload),
                              len(payload)) != 0:
            raise MPIError(
                ErrorCode.ERR_OTHER,
                f"oob send to {dst} failed (no connection or route)",
            )

    def recv(self, tag: int = -1,
             timeout_ms: int = 10_000) -> Tuple[int, int, bytes]:
        """Returns (src, tag, payload); raises on timeout.

        The buffer is sized from the queued frame's actual length
        (oob_next_len) instead of a worst-case allocation. A concurrent
        consumer of the same tag can race the size query; the -2 retry
        loop below re-sizes and tries again. One deadline bounds the
        whole call — retries never extend it past timeout_ms.
        """
        import time as _time

        deadline = _time.monotonic() + timeout_ms / 1000
        while True:
            left = max(1, int((deadline - _time.monotonic()) * 1000))
            n = self._lib.oob_next_len(self._handle(), tag, left)
            if n < 0:
                raise MPIError(ErrorCode.ERR_PENDING,
                               f"oob recv timeout (tag {tag})")
            src = ctypes.c_int32()
            tg = ctypes.c_int32(tag)
            arr = (ctypes.c_uint8 * max(n, 1))()
            left = max(1, int((deadline - _time.monotonic()) * 1000))
            got = self._lib.oob_recv(self._handle(), ctypes.byref(src),
                                     ctypes.byref(tg), arr, n, left)
            if got == -2:
                continue  # raced with another consumer; re-size
            if got == -1:
                raise MPIError(ErrorCode.ERR_PENDING,
                               f"oob recv timeout (tag {tag})")
            return src.value, tg.value, ctypes.string_at(arr, got)

    # -- nativewire datapath (optional capability) ------------------------

    def sendv(self, dst: int, tag: int, parts) -> None:
        """Vectored send: one frame whose payload is the concatenation
        of `parts`, written with writev straight from the parts'
        buffers — no b"".join, no ctypes staging copy. Byte-identical
        on the wire to ``send(dst, tag, b"".join(parts))``."""
        ptrs, lens, keep = _sg_arrays(parts)
        rc = self._lib.wire_sendv(self._handle(), dst, tag, ptrs, lens,
                                  len(ptrs))
        del keep
        if rc != 0:
            raise MPIError(
                ErrorCode.ERR_OTHER,
                f"wire sendv to {dst} failed (no connection or route)",
            )

    def recv_frag(self, src: int, tag: int, xfer: int, nchunks: int,
                  chunk: int, buf, timeout_ms: int) -> int:
        """Pop the next SGC2 fragment of (src, tag, xfer) straight
        into writable `buf`. Returns the fragment index >= 0, or the
        C status: -1 timeout, -2 malformed (consumed), -4 the next
        matching frame belongs to the portable path (left queued)."""
        base, nbytes, keep = _wbuf_ptr(buf)
        rc = self._lib.wire_recv_frag(self._handle(), src, tag, xfer,
                                      nchunks, chunk, base, nbytes,
                                      timeout_ms)
        del keep
        return int(rc)

    def ttl_dropped(self) -> int:
        """Frames dropped by the routing-cycle ttl guard."""
        return self._lib.oob_ttl_dropped(self._handle())

    #: wire_stats index names, in C-side order (native/btl_tcp.cc)
    WIRE_STATS = ("tx_frames", "tx_bytes", "rx_frames", "rx_bytes",
                  "rx_stalls", "rx_stall_ns")

    def wire_stats(self) -> dict:
        """The endpoint's native-wire telemetry block as a dict; all
        zeros when the loaded .so predates the telemetry ABI."""
        if not hasattr(self._lib, "wire_stats"):
            return {k: 0 for k in self.WIRE_STATS}
        h = self._handle()
        return {k: int(self._lib.wire_stats(h, i))
                for i, k in enumerate(self.WIRE_STATS)}

    def pending(self) -> int:
        return self._lib.oob_pending(self._handle())

    def close(self) -> None:
        if self._h:
            self._lib.oob_destroy(self._h)
            self._h = None

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass


class ShmRing:
    """SPSC shared-memory byte ring (btl/sm FIFO analogue).

    Mechanical wrapper: status ints pass through unchanged; mapping
    -3 (peer process gone) onto the typed fault-tolerance error is the
    btl component's job, not the binding's. Ring protocol status codes
    (native/btl_shm.cc): writev 0/-1 timeout/-2 never-fits/-3 dead;
    read_frag idx/-1/-2 consumed-bad/-3 dead/-4 stale-dropped/-5
    other-tag-left; read_into len/-1/-2 too-small/-3 dead."""

    def __init__(self, handle, name: str) -> None:
        self._lib = load_library()
        self._h = handle
        self.name = name

    @classmethod
    def create(cls, name: str, capacity: int,
               producer_pid: int) -> Optional["ShmRing"]:
        """O_CREAT|O_EXCL producer-side create; None when the name
        already exists (another sender won the race) or shm failed."""
        lib = load_library()
        h = lib.shmring_create(name.encode(), capacity, producer_pid)
        return cls(h, name) if h else None

    @classmethod
    def attach(cls, name: str,
               consumer_pid: int = 0) -> Optional["ShmRing"]:
        """Consumer-side attach; None while the ring does not exist
        yet (callers retry — the producer creates lazily)."""
        lib = load_library()
        h = lib.shmring_attach(name.encode(), consumer_pid)
        return cls(h, name) if h else None

    @staticmethod
    def unlink(name: str) -> None:
        try:
            load_library().shmring_unlink(name.encode())
        except Exception:
            pass  # best-effort cleanup

    def _handle(self):
        h = self._h
        if not h:
            raise MPIError(ErrorCode.ERR_OTHER, "shm ring is closed")
        return h

    @property
    def capacity(self) -> int:
        return self._lib.shmring_capacity(self._handle())

    def pending(self) -> int:
        return self._lib.shmring_pending(self._handle())

    def producer_pid(self) -> int:
        return self._lib.shmring_producer_pid(self._handle())

    def consumer_pid(self) -> int:
        return self._lib.shmring_consumer_pid(self._handle())

    def writev(self, tag: int, parts, timeout_ms: int) -> int:
        ptrs, lens, keep = _sg_arrays(parts)
        rc = self._lib.shmring_writev(self._handle(), tag, ptrs, lens,
                                      len(ptrs), timeout_ms)
        del keep
        return int(rc)

    def read_frag(self, tag: int, xfer: int, nchunks: int, chunk: int,
                  buf, timeout_ms: int) -> int:
        base, nbytes, keep = _wbuf_ptr(buf)
        rc = self._lib.shmring_read_frag(self._handle(), tag, xfer,
                                         nchunks, chunk, base, nbytes,
                                         timeout_ms)
        del keep
        return int(rc)

    def read_into(self, buf, timeout_ms: int):
        """Generic pop of the head record: (status_or_len, tag)."""
        base, nbytes, keep = _wbuf_ptr(buf)
        tag = ctypes.c_int32()
        rc = self._lib.shmring_read_into(self._handle(),
                                         ctypes.byref(tag), base,
                                         nbytes, timeout_ms)
        del keep
        return int(rc), tag.value

    #: shmring_stat index names, in C-side order (native/btl_shm.cc)
    STATS = ("w_frames", "w_bytes", "w_stalls", "w_stall_ns", "hwm",
             "r_frames", "r_bytes", "r_stalls", "r_stall_ns")

    def stats(self) -> dict:
        """The ring header's telemetry block as a dict; all zeros when
        the loaded .so predates the telemetry ABI (pre-v2 rings can't
        exist then either — the magic changed with the layout)."""
        if not hasattr(self._lib, "shmring_stat"):
            return {k: 0 for k in self.STATS}
        h = self._handle()
        return {k: int(self._lib.shmring_stat(h, i))
                for i, k in enumerate(self.STATS)}

    def close(self) -> None:
        if self._h:
            self._lib.shmring_close(self._h)
            self._h = None

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass


class NativeEventRing:
    """mmap'd fixed-record native event ring ("ompitpu-nativeev-v1").

    One per process, created by the nativewire component when the
    ``wire_native_events`` cvar is on; the C transports append one
    32-byte record per SGC2 fragment once :meth:`install` makes this
    ring the process sink. Drop-oldest wrap: :meth:`read` returns the
    newest ``nslots`` records at most, with the first live sequence so
    consumers can report the gap."""

    #: one record: t_ns u64, xfer u64, tag i32, bytes u32,
    #: idx_dir u32 (bit 31 = receive side), wait_ns u32
    RECORD = struct.Struct("<QQiIII")

    def __init__(self, handle, name: str) -> None:
        self._lib = load_library()
        self._h = handle
        self.name = name

    @classmethod
    def create(cls, name: str,
               nslots: int) -> Optional["NativeEventRing"]:
        lib = load_library()
        if not hasattr(lib, "nativeev_create"):
            return None
        h = lib.nativeev_create(name.encode(), nslots)
        return cls(h, name) if h else None

    @classmethod
    def attach(cls, name: str) -> Optional["NativeEventRing"]:
        lib = load_library()
        if not hasattr(lib, "nativeev_attach"):
            return None
        h = lib.nativeev_attach(name.encode())
        return cls(h, name) if h else None

    @staticmethod
    def unlink(name: str) -> None:
        try:
            load_library().nativeev_unlink(name.encode())
        except Exception:
            pass  # best-effort cleanup

    def _handle(self):
        h = self._h
        if not h:
            raise MPIError(ErrorCode.ERR_OTHER, "event ring is closed")
        return h

    def install(self) -> None:
        """Make this ring the process-global emit sink."""
        self._lib.nativeev_install(self._handle())

    def uninstall(self) -> None:
        self._lib.nativeev_install(None)

    @property
    def nslots(self) -> int:
        return int(self._lib.nativeev_nslots(self._handle()))

    def count(self) -> int:
        """Records ever appended (monotonic across wraps)."""
        return int(self._lib.nativeev_count(self._handle()))

    def read(self, start: int = 0,
             max_records: int = 1 << 16) -> Tuple[int, list]:
        """(first_seq, records) with records decoded to dicts
        ``{t_ns, xfer, tag, bytes, idx, recv, wait_ns}``; first_seq is
        the sequence of records[0] (> start when the ring lapped)."""
        n = min(max_records, self.nslots)
        buf = ctypes.create_string_buffer(n * self.RECORD.size)
        first = ctypes.c_int64(0)
        got = int(self._lib.nativeev_read(
            self._handle(), start,
            ctypes.cast(buf, ctypes.c_void_p), n, ctypes.byref(first)))
        recs = []
        for i in range(got):
            t_ns, xfer, tag, nbytes, idx_dir, wait_ns = \
                self.RECORD.unpack_from(buf, i * self.RECORD.size)
            recs.append({
                "t_ns": t_ns, "xfer": xfer, "tag": tag,
                "bytes": nbytes, "idx": idx_dir & 0x7FFFFFFF,
                "recv": bool(idx_dir >> 31), "wait_ns": wait_ns,
            })
        return int(first.value), recs

    def close(self) -> None:
        if self._h:
            self._lib.nativeev_close(self._h)
            self._h = None

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass


class PlanExec:
    """Native executor for ONE frozen wire plan (coll/plan analogue of
    the reference's posted-descriptor progress loop).

    coll/native_exec.py compiles a WirePlan into a flat descriptor
    blob (rounds, peers, precomposed header bytes, scatter-gather
    payload maps, pool placements), creates a PlanExec once, binds the
    live endpoint/ring handles once, and then every steady-state fire
    is ``fire_begin`` + a ``fire_step`` loop: all rounds walk C-side,
    Python re-enters only between ~100 ms slices (to run the ULFM
    failure detector) and at completion or typed error.

    Return codes (native/planexec.cc): 0 done, 1 slice expired
    (call ``fire_step`` again), 2 fault-word stop (run check_wait,
    then resume), -1 bad call, -2 peer dead (``err_peer`` names the
    pidx), -3 plan timeout, -4 inbound header diverged from the
    frozen expectation, -5 reassembled payload failed CRC."""

    RC_DONE = 0
    RC_AGAIN = 1
    RC_FTSTOP = 2
    RC_BADARG = -1
    RC_PEERDEAD = -2
    RC_TIMEOUT = -3
    RC_DIVERGED = -4
    RC_TRUNCATED = -5

    def __init__(self, blob: bytes) -> None:
        lib = load_library()
        if not hasattr(lib, "planexec_create"):
            raise MPIError(ErrorCode.ERR_OTHER,
                           "planexec symbols not available")
        self._lib = lib
        self._h = lib.planexec_create(_u8(blob), len(blob))
        if not self._h:
            raise MPIError(ErrorCode.ERR_OTHER,
                           "plan descriptor blob rejected")
        self._ftword = None  # keepalive for the fault-word buffer

    def _handle(self):
        h = self._h
        if not h:
            raise MPIError(ErrorCode.ERR_OTHER, "plan executor closed")
        return h

    def bind(self, ep_handle, my_nid: int, peer_nids,
             tx_ring_handles, rx_ring_handles) -> None:
        """Attach the live endpoint + per-peer ring handles (entries
        may be None → that peer uses the vectored-socket leg)."""
        n = len(peer_nids)
        nids = (ctypes.c_int64 * n)(*[int(v) for v in peer_nids])
        tx = (ctypes.c_void_p * n)(*[h or None
                                     for h in tx_ring_handles])
        rx = (ctypes.c_void_p * n)(*[h or None
                                     for h in rx_ring_handles])
        rc = self._lib.planexec_bind(self._handle(), ep_handle,
                                     my_nid, nids, tx, rx, n)
        if rc != 0:
            raise MPIError(ErrorCode.ERR_OTHER,
                           "plan executor bind rejected")

    def set_ftword(self, word_buf) -> None:
        """Point the executor at a 1-element int64 fault word (a
        ctypes int64 array owned by the caller; nonzero aborts waits
        with RC_FTSTOP within the polling interval)."""
        self._ftword = word_buf
        self._lib.planexec_set_ftword(
            self._handle(),
            ctypes.cast(word_buf, ctypes.POINTER(ctypes.c_int64)))

    def fire_begin(self, input_arrays, xfer_base: int,
                   timeout_ms: int) -> int:
        """Arm a fire with the round-0 input regions (contiguous
        ndarrays, pointers live until the fire completes)."""
        n = len(input_arrays)
        ptrs = (ctypes.c_void_p * n)()
        lens = (ctypes.c_int64 * n)()
        for i, a in enumerate(input_arrays):
            ptrs[i] = ctypes.c_void_p(a.ctypes.data)
            lens[i] = int(a.nbytes)
        self._fire_keep = input_arrays
        return int(self._lib.planexec_fire_begin(
            self._handle(), ptrs, lens, n, xfer_base, timeout_ms))

    def fire_step(self, slice_ms: int) -> int:
        return int(self._lib.planexec_fire_step(self._handle(),
                                                slice_ms))

    @property
    def pool_total(self) -> int:
        return int(self._lib.planexec_pool_total(self._handle()))

    @property
    def pool_count(self) -> int:
        return int(self._lib.planexec_pool_count(self._handle()))

    @property
    def round_count(self) -> int:
        return int(self._lib.planexec_round_count(self._handle()))

    @property
    def input_count(self) -> int:
        return int(self._lib.planexec_input_count(self._handle()))

    def pool_view(self):
        """Zero-copy uint8 ndarray over the reassembly slab (valid
        until close; reused across fires — consumers copy out)."""
        import numpy as _np

        total = self.pool_total
        if total == 0:
            return _np.empty(0, dtype=_np.uint8)
        ptr = self._lib.planexec_pool_ptr(self._handle())
        buf = (ctypes.c_uint8 * total).from_address(ptr)
        return _np.frombuffer(buf, dtype=_np.uint8)

    def round_ts(self):
        """Per-round CLOCK_MONOTONIC end stamps from the last fire —
        the same clock as time.perf_counter, so the obs ledger record
        consumes them unchanged."""
        n = self.round_count
        p = self._lib.planexec_ts_ptr(self._handle())
        return [float(p[i]) for i in range(n)]

    def err_peer(self) -> int:
        return int(self._lib.planexec_err_peer(self._handle()))

    def err_round(self) -> int:
        return int(self._lib.planexec_err_round(self._handle()))

    def drain_stash(self):
        """Pop any foreign frames the executor met on the coll
        channel: list of (kind, peer_pidx, tag, bytes) with kind 0 =
        endpoint-queue frame, 1 = shm-ring record. The caller
        re-injects them into the btl stashes so cross-channel traffic
        survives a native fire untouched."""
        h = self._handle()
        out = []
        n = int(self._lib.planexec_stash_count(h))
        kind = ctypes.c_int64()
        peer = ctypes.c_int64()
        tag = ctypes.c_int64()
        for i in range(n):
            ln = int(self._lib.planexec_stash_info(
                h, i, ctypes.byref(kind), ctypes.byref(peer),
                ctypes.byref(tag)))
            if ln < 0:
                continue
            ptr = self._lib.planexec_stash_data(h, i)
            data = ctypes.string_at(ptr, ln) if ln else b""
            out.append((int(kind.value), int(peer.value),
                        int(tag.value), data))
        self._lib.planexec_stash_clear(h)
        return out

    def close(self) -> None:
        if self._h:
            self._lib.planexec_destroy(self._h)
            self._h = None

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass
