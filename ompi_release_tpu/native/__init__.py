"""ctypes bindings for the native control-plane library.

Builds ``native/libompitpu_native.so`` on demand (g++ is in the image;
pybind11 is not, so the C ABI + ctypes is the binding layer).
"""

from .bindings import (  # noqa: F401
    USER_TAG_BASE, DssBuffer, OobEndpoint, ShmRing, load_library,
    wire_symbols_available,
)
