"""ctypes bindings for the native control-plane library.

Builds ``native/libompitpu_native.so`` on demand (g++ is in the image;
pybind11 is not, so the C ABI + ctypes is the binding layer).
"""

from .bindings import (  # noqa: F401
    USER_TAG_BASE, DssBuffer, NativeEventRing, OobEndpoint, ShmRing,
    load_library, telemetry_symbols_available, wire_symbols_available,
)
