"""Collective correctness tests on the 8-device CPU mesh.

Mirrors the reference's clusterless strategy (SURVEY §4): every
algorithm runs multi-"device" with parity checked against numpy.
BASELINE.json configs #2-#5 in miniature.
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

import ompi_release_tpu as mpi
from ompi_release_tpu import ops
from ompi_release_tpu.mca import var as mca_var


@pytest.fixture(scope="module")
def world():
    yield mpi.init()


@pytest.fixture()
def tuned(world):
    """A communicator whose c_coll table is served by the tuned
    component: the coll table is frozen at communicator creation
    (coll_base_comm_select analogue), so the selection var must be set
    BEFORE the dup — setting it afterwards would silently test xla."""
    mca_var.set_value("coll", "tuned")
    try:
        c = world.dup(name="tuned_dup")
    finally:
        mca_var.VARS.unset("coll")
    assert c._coll_providers["allreduce"] == ["tuned"]
    yield c
    c.free()


def _per_rank(world, n, dtype=np.float32, seed=0):
    return _per_rank_n(world.size, n, dtype, seed)


def _per_rank_n(size, n, dtype=np.float32, seed=0):
    rng = np.random.RandomState(seed)
    if np.issubdtype(np.dtype(dtype), np.floating):
        return rng.randn(size, n).astype(dtype)
    return rng.randint(0, 100, size=(size, n)).astype(dtype)


ALGS = ["basic_linear", "nonoverlapping", "recursive_doubling", "ring",
        "segmented_ring"]


@pytest.mark.parametrize("alg", ALGS)
def test_allreduce_algorithms_parity(tuned, alg):
    """Every named algorithm must agree with numpy (configs #2)."""
    x = _per_rank(tuned, 1000)
    expect = x.sum(axis=0)
    mca_var.set_value("coll_tuned_allreduce_algorithm", alg)
    try:
        out = tuned.allreduce(x, ops.SUM)
    finally:
        mca_var.VARS.unset("coll_tuned_allreduce_algorithm")
    assert out.shape == x.shape
    # prove the named algorithm actually compiled (not a fallback)
    assert any(
        k[:3] == ("tuned", "allreduce", alg)
        for k in getattr(tuned, "_coll_programs", {})
    )
    for r in range(tuned.size):
        # atol covers reduction-order float noise on near-zero sums
        np.testing.assert_allclose(np.asarray(out[r]), expect, rtol=2e-5,
                                   atol=1e-4)


def test_allreduce_xla_default(world):
    x = _per_rank(world, 257)  # non-divisible size
    out = world.allreduce(x, ops.SUM)
    np.testing.assert_allclose(
        np.asarray(out[0]), x.sum(axis=0), rtol=2e-5
    )


@pytest.mark.parametrize("opname,npfn", [
    ("max", np.max), ("min", np.min), ("prod", np.prod),
])
def test_allreduce_other_ops(world, opname, npfn):
    x = _per_rank(world, 64, seed=3)
    out = world.allreduce(x, ops.PREDEFINED_OPS[opname])
    np.testing.assert_allclose(
        np.asarray(out[0]), npfn(x, axis=0), rtol=1e-5
    )


def test_allreduce_int_bitwise(world):
    x = _per_rank(world, 50, dtype=np.int32, seed=5)
    out = world.allreduce(x, ops.BXOR)
    expect = np.bitwise_xor.reduce(x, axis=0)
    np.testing.assert_array_equal(np.asarray(out[0]), expect)


def test_allreduce_maxloc(world):
    vals = _per_rank(world, 16, seed=7)
    idxs = np.tile(np.arange(world.size)[:, None], (1, 16)).astype(np.int32)
    mv, mi = world.allreduce((vals, idxs), ops.MAXLOC)
    np.testing.assert_allclose(np.asarray(mv[0]), vals.max(axis=0), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(mi[0]), vals.argmax(axis=0))


def test_bcast(world):
    x = _per_rank(world, 100, seed=11)
    out = world.bcast(x, root=3)
    for r in range(world.size):
        np.testing.assert_array_equal(np.asarray(out[r]), x[3])


def test_bcast_binomial(tuned):
    x = _per_rank(tuned, 100, seed=12)
    out = tuned.bcast(x, root=5)
    assert ("tuned", "bcast", "binomial", 5) in tuned._coll_programs
    for r in range(tuned.size):
        np.testing.assert_array_equal(np.asarray(out[r]), x[5])


@pytest.mark.parametrize("alg", ["binomial", "binary_tree", "chain",
                                 "pipeline", "masked_psum"])
def test_bcast_algorithms_parity(tuned, alg):
    """Every named bcast algorithm (coll_tuned_bcast.c menu incl. the
    segmented pipeline chain) delivers root's buffer bitwise."""
    x = _per_rank(tuned, 700, seed=61)  # pipeline: several segments
    mca_var.set_value("coll_tuned_bcast_algorithm", alg)
    if alg == "pipeline":
        mca_var.set_value("coll_tuned_bcast_segment_size", 512)
    try:
        out = tuned.bcast(x, root=5)
    finally:
        mca_var.VARS.unset("coll_tuned_bcast_algorithm")
        if alg == "pipeline":
            mca_var.VARS.unset("coll_tuned_bcast_segment_size")
    assert any(k[:3] == ("tuned", "bcast", alg)
               for k in tuned._coll_programs)
    for r in range(tuned.size):
        np.testing.assert_array_equal(np.asarray(out[r]), x[5])


def test_bcast_decision_rule(tuned):
    """bcast_intra_dec_fixed: <2 kB binomial; <362 kB binary tree
    (split_bintree substitute); large -> pipeline with regression-
    picked segments."""
    from ompi_release_tpu.coll.components import _TunedModule

    m = _TunedModule(tuned)
    small = np.zeros((8, 100), np.float32)
    assert m._pick_bcast(small) == ("binomial", 0)
    mid = np.zeros((8, 50_000), np.float32)
    assert m._pick_bcast(mid) == ("binary_tree", 1 << 10)
    big = np.zeros((8, 3_000_000), np.float32)  # 12 MB: n=8 << a*msg+b
    alg, seg = m._pick_bcast(big)
    assert alg == "pipeline" and seg == 128 << 10


def test_reduce(world):
    x = _per_rank(world, 100, seed=13)
    out = world.reduce(x, ops.SUM, root=2)
    np.testing.assert_allclose(np.asarray(out[2]), x.sum(axis=0), rtol=2e-5)


@pytest.mark.parametrize("alg", ["binomial", "in_order_binary",
                                 "linear"])
def test_reduce_algorithms_parity(tuned, alg):
    """Every named rooted-reduce algorithm agrees with numpy."""
    x = _per_rank(tuned, 64, seed=63)
    mca_var.set_value("coll_tuned_reduce_algorithm", alg)
    try:
        out = tuned.reduce(x, ops.SUM, root=3)
    finally:
        mca_var.VARS.unset("coll_tuned_reduce_algorithm")
    assert any(k[:3] == ("tuned", "reduce", alg)
               for k in tuned._coll_programs)
    np.testing.assert_allclose(np.asarray(out[3]), x.sum(axis=0),
                               rtol=2e-5, atol=1e-4)


def test_reduce_noncommutative_in_order(tuned):
    """A noncommutative op is served by in_order_binary (strict rank
    order, no root rotation): op(a, b) = a + 2b distinguishes operand
    ORDER; expected value computed by numpy with the same balanced
    contiguous-range grouping."""
    n = tuned.size
    f = lambda a, b: a + 2 * b
    noncommut = ops.user_op("affine", f, commute=False)
    # > 2 kB so the decision picks in_order_binary (small
    # noncommutative goes to the strict linear fold)
    x = _per_rank(tuned, 1024, seed=64)
    out = tuned.reduce(x, noncommut, root=2)
    assert any(k[:3] == ("tuned", "reduce", "in_order_binary")
               for k in tuned._coll_programs)

    # same grouping as the kernel: pairwise merges at stride k
    blocks = [x[i] for i in range(n)]
    k = 1
    while k < n:
        for i in range(0, n, 2 * k):
            if i + k < n:
                blocks[i] = f(blocks[i], blocks[i + k])
        k *= 2
    np.testing.assert_allclose(np.asarray(out[2]), blocks[0],
                               rtol=1e-6)


def test_allgather(world):
    x = _per_rank(world, 10, seed=17)
    out = world.allgather(x)
    expect = x.reshape(-1)
    assert out.shape == (world.size, world.size * 10)
    for r in range(world.size):
        np.testing.assert_array_equal(np.asarray(out[r]), expect)


def test_allgather_ring(tuned):
    x = _per_rank(tuned, 10, seed=18)
    mca_var.set_value("coll_tuned_allgather_algorithm", "ring")
    try:
        out = tuned.allgather(x)
    finally:
        mca_var.VARS.unset("coll_tuned_allgather_algorithm")
    assert ("tuned", "allgather", "ring") in tuned._coll_programs
    for r in range(tuned.size):
        np.testing.assert_array_equal(np.asarray(out[r]), x.reshape(-1))


@pytest.mark.parametrize("alg", ["ring", "bruck", "recursive_doubling",
                                 "lax"])
def test_allgather_algorithms_parity(tuned, alg):
    """Every named allgather algorithm (coll_tuned_allgather.c menu)
    agrees bitwise with the input blocks."""
    x = _per_rank(tuned, 13, seed=41)
    mca_var.set_value("coll_tuned_allgather_algorithm", alg)
    try:
        out = tuned.allgather(x)
    finally:
        mca_var.VARS.unset("coll_tuned_allgather_algorithm")
    assert ("tuned", "allgather", alg) in tuned._coll_programs
    for r in range(tuned.size):
        np.testing.assert_array_equal(np.asarray(out[r]), x.reshape(-1))


def test_allgather_bruck_non_power_of_two(world):
    """Bruck handles ANY n (its point over recursive doubling): run it
    on a 5-rank subcommunicator; forced recursive doubling there is a
    loud error, mirroring the reference's pow2-only implementation."""
    from ompi_release_tpu.utils.errors import MPIError

    mca_var.set_value("coll", "tuned")
    try:
        sub = world.create(world.group.incl([0, 1, 2, 3, 4]),
                           name="tuned5")
    finally:
        mca_var.VARS.unset("coll")
    try:
        x = _per_rank_n(5, 7, seed=42)
        mca_var.set_value("coll_tuned_allgather_algorithm", "bruck")
        try:
            out = sub.allgather(x)
        finally:
            mca_var.VARS.unset("coll_tuned_allgather_algorithm")
        assert ("tuned", "allgather", "bruck") in sub._coll_programs
        for r in range(5):
            np.testing.assert_array_equal(np.asarray(out[r]),
                                          x.reshape(-1))
        mca_var.set_value("coll_tuned_allgather_algorithm",
                          "recursive_doubling")
        try:
            with pytest.raises(MPIError, match="power-of-two"):
                sub.allgather(x)
        finally:
            mca_var.VARS.unset("coll_tuned_allgather_algorithm")
    finally:
        sub.free()


def test_allgather_bad_algorithm_rejected(tuned):
    """A typo'd forced algorithm is rejected at CONFIG time by the
    enum variable (listing the choices), before any collective runs;
    the in-function menu check stays as defense-in-depth."""
    with pytest.raises(ValueError, match="ringg.*not in enum"):
        mca_var.set_value("coll_tuned_allgather_algorithm", "ringg")


def test_allgather_decision_rule(tuned):
    """coll_tuned_decision_fixed.c:537-567: small total -> recursive
    doubling at power-of-two n; large -> ring."""
    from ompi_release_tpu.coll.components import _TunedModule

    m = _TunedModule(tuned)
    small = np.zeros((8, 100), np.float32)    # 3.2 kB total < 50 kB
    assert m._pick_allgather(small) == "recursive_doubling"
    big = np.zeros((8, 30_000), np.float32)   # 960 kB total
    assert m._pick_allgather(big) == "ring"


def test_gather_scatter(world):
    x = _per_rank(world, 10, seed=19)
    g = world.gather(x, root=1)
    np.testing.assert_array_equal(np.asarray(g[1]), x.reshape(-1))
    assert np.all(np.asarray(g[0]) == 0)  # non-root undefined -> zeros

    # scatter: root's buffer holds size chunks
    big = _per_rank(world, world.size * 5, seed=20)
    s = world.scatter(big, root=1)
    for r in range(world.size):
        np.testing.assert_array_equal(
            np.asarray(s[r]), big[1][r * 5:(r + 1) * 5]
        )


@pytest.mark.parametrize("alg", ["binomial", "linear"])
def test_tuned_gather_scatter_algorithms(tuned, alg):
    """tuned gather/scatter (coll_tuned_{gather,scatter}.c): binomial
    tree and linear, parity vs the xla path, roots exercised off 0.
    (Closes the 'tuned has no gather/scatter' selection banner.)"""
    n = tuned.size
    x = _per_rank(tuned, 6, seed=51)
    mca_var.set_value("coll_tuned_gather_algorithm", alg)
    try:
        g = tuned.gather(x, root=3)
    finally:
        mca_var.VARS.unset("coll_tuned_gather_algorithm")
    assert ("tuned", "gather", alg, 3) in tuned._coll_programs
    np.testing.assert_array_equal(np.asarray(g[3]), x.reshape(-1))
    assert np.all(np.asarray(g[0]) == 0)  # non-root undefined -> zeros

    big = _per_rank(tuned, n * 5, seed=52)
    mca_var.set_value("coll_tuned_scatter_algorithm", alg)
    try:
        s = tuned.scatter(big, root=2)
    finally:
        mca_var.VARS.unset("coll_tuned_scatter_algorithm")
    assert ("tuned", "scatter", alg, 2) in tuned._coll_programs
    for r in range(n):
        np.testing.assert_array_equal(
            np.asarray(s[r]), big[2][r * 5:(r + 1) * 5])


def test_tuned_gather_scatter_non_power_of_two(world):
    """Binomial gather/scatter handle non-power-of-two comms (the
    child-exists clamp): 5 ranks, root 4."""
    mca_var.set_value("coll", "tuned")
    try:
        sub = world.create(world.group.incl([0, 1, 2, 3, 4]),
                           name="tuned5gs")
    finally:
        mca_var.VARS.unset("coll")
    try:
        x = _per_rank_n(5, 4, seed=53)
        mca_var.set_value("coll_tuned_gather_algorithm", "binomial")
        mca_var.set_value("coll_tuned_scatter_algorithm", "binomial")
        try:
            g = sub.gather(x, root=4)
            big = _per_rank_n(5, 5 * 3, seed=54)
            s = sub.scatter(big, root=4)
        finally:
            mca_var.VARS.unset("coll_tuned_gather_algorithm")
            mca_var.VARS.unset("coll_tuned_scatter_algorithm")
        np.testing.assert_array_equal(np.asarray(g[4]), x.reshape(-1))
        for r in range(5):
            np.testing.assert_array_equal(
                np.asarray(s[r]), big[4][r * 3:(r + 1) * 3])
    finally:
        sub.free()


def test_reduce_scatter_block(world):
    """ZeRO-style gradient shard (config #4)."""
    n = world.size
    x = _per_rank(world, n * 25, seed=23)
    out = world.reduce_scatter_block(x, ops.SUM)
    assert out.shape == (n, 25)
    full = x.sum(axis=0)
    for r in range(n):
        np.testing.assert_allclose(
            np.asarray(out[r]), full[r * 25:(r + 1) * 25], rtol=2e-5
        )


def test_reduce_scatter_ring_parity(tuned):
    n = tuned.size
    x = _per_rank(tuned, n * 25, seed=24)
    out = tuned.reduce_scatter_block(x, ops.SUM)
    assert ("tuned", "reduce_scatter_block", ops.SUM) in tuned._coll_programs
    full = x.sum(axis=0)
    for r in range(n):
        np.testing.assert_allclose(
            np.asarray(out[r]), full[r * 25:(r + 1) * 25], rtol=2e-5,
            atol=1e-4,
        )


def test_alltoall(world):
    """int32 block shuffle (config #5)."""
    n = world.size
    x = _per_rank(world, n * 4, dtype=np.int32, seed=29)
    out = world.alltoall(x)
    blocks = x.reshape(n, n, 4)
    expect = blocks.transpose(1, 0, 2)  # out[i][j] = in[j][i]
    np.testing.assert_array_equal(
        np.asarray(out).reshape(n, n, 4), expect
    )


def test_alltoall_pairwise(tuned):
    n = tuned.size
    x = _per_rank(tuned, n * 4, dtype=np.int32, seed=31)
    mca_var.set_value("coll_tuned_alltoall_algorithm", "pairwise")
    try:
        out = tuned.alltoall(x)
    finally:
        mca_var.VARS.unset("coll_tuned_alltoall_algorithm")
    assert ("tuned", "alltoall", "pairwise") in tuned._coll_programs
    expect = x.reshape(n, n, 4).transpose(1, 0, 2).reshape(n, -1)
    np.testing.assert_array_equal(np.asarray(out), expect)


@pytest.mark.parametrize("alg", ["pairwise", "bruck", "basic_linear",
                                 "lax"])
def test_alltoall_algorithms_parity(tuned, alg):
    """Every named alltoall algorithm (coll_tuned_alltoall.c menu,
    incl. bruck's log-phase store-and-forward) produces the block
    transpose bitwise."""
    n = tuned.size
    x = _per_rank(tuned, n * 5, dtype=np.int32, seed=33)
    mca_var.set_value("coll_tuned_alltoall_algorithm", alg)
    try:
        out = tuned.alltoall(x)
    finally:
        mca_var.VARS.unset("coll_tuned_alltoall_algorithm")
    assert ("tuned", "alltoall", alg) in tuned._coll_programs
    expect = x.reshape(n, n, 5).transpose(1, 0, 2).reshape(n, -1)
    np.testing.assert_array_equal(np.asarray(out), expect)


def test_alltoall_decision_rule(tuned):
    """coll_tuned_decision_fixed.c:124-133: tiny blocks at n > 12 ->
    bruck; blocks < 3000 B -> basic_linear; else pairwise."""
    from types import SimpleNamespace

    from ompi_release_tpu.coll.components import _TunedModule

    m = _TunedModule(tuned)  # n = 8
    tiny = np.zeros((8, 8 * 4), np.int8)      # 4 B blocks, n <= 12
    assert m._pick_alltoall(tiny) == "basic_linear"
    mid = np.zeros((8, 8 * 500), np.float32)  # 2 kB blocks
    assert m._pick_alltoall(mid) == "basic_linear"
    big = np.zeros((8, 8 * 1000), np.float32)  # 4 kB blocks
    assert m._pick_alltoall(big) == "pairwise"
    m16 = _TunedModule(SimpleNamespace(size=16))
    tiny16 = np.zeros((16, 16 * 4), np.int8)  # 4 B blocks, n > 12
    assert m16._pick_alltoall(tiny16) == "bruck"


def test_alltoall_lax_forced(tuned):
    n = tuned.size
    x = _per_rank(tuned, n * 4, dtype=np.int32, seed=32)
    mca_var.set_value("coll_tuned_alltoall_algorithm", "lax")
    try:
        out = tuned.alltoall(x)
    finally:
        mca_var.VARS.unset("coll_tuned_alltoall_algorithm")
    assert ("tuned", "alltoall", "lax") in tuned._coll_programs
    expect = x.reshape(n, n, 4).transpose(1, 0, 2).reshape(n, -1)
    np.testing.assert_array_equal(np.asarray(out), expect)


def test_scan_exscan(world):
    x = _per_rank(world, 20, seed=37)
    out = world.scan(x, ops.SUM)
    expect = np.cumsum(x, axis=0)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=2e-5)

    ex = world.exscan(x, ops.SUM)
    np.testing.assert_allclose(np.asarray(ex[0]), np.zeros(20), atol=0)
    np.testing.assert_allclose(
        np.asarray(ex[1:]), expect[:-1], rtol=2e-5
    )


def test_scan_exscan_pair_ops(world):
    """MPI_Scan/Exscan with MINLOC/MAXLOC (pair ops): running
    argmax/argmin with MPI's lowest-index tie-break; the rank-0 exscan
    slice is zeros (undefined in MPI)."""
    vals = np.asarray([3., 1., 7., 2., 9., 0., 7., 4.],
                      np.float32)[:world.size].reshape(-1, 1)
    idxs = np.arange(world.size, dtype=np.int32).reshape(-1, 1)
    sv, si = world.scan((vals, idxs), ops.MAXLOC)
    best, bi, want_v, want_i = -np.inf, 0, [], []
    for k, v in enumerate(vals.ravel()):
        if v > best:  # strict: ties keep the LOWER index
            best, bi = v, k
        want_v.append(best)
        want_i.append(bi)
    np.testing.assert_array_equal(np.asarray(sv).ravel(), want_v)
    np.testing.assert_array_equal(np.asarray(si).ravel(), want_i)

    ev, ei = world.exscan((vals, idxs), ops.MAXLOC)
    assert float(np.asarray(ev)[0, 0]) == 0.0
    np.testing.assert_array_equal(np.asarray(ev).ravel()[1:],
                                  want_v[:-1])
    np.testing.assert_array_equal(np.asarray(ei).ravel()[1:],
                                  want_i[:-1])

    mv, mi = world.scan((vals, idxs), ops.MINLOC)
    np.testing.assert_array_equal(
        np.asarray(mv).ravel(),
        np.minimum.accumulate(vals.ravel()))


def test_reduce_and_rsb_pair_ops(world):
    """Rooted MPI_Reduce with MAXLOC (the canonical pair-op call) and
    reduce_scatter_block with MINLOC."""
    n = world.size
    vals = np.asarray([3., 1., 7., 2., 9., 0., 7., 4.],
                      np.float32)[:n].reshape(n, 1)
    idxs = np.arange(n, dtype=np.int32).reshape(n, 1)
    rv, ri = world.reduce((vals, idxs), ops.MAXLOC, root=2)
    rv, ri = np.asarray(rv), np.asarray(ri)
    assert float(rv[2, 0]) == 9.0 and int(ri[2, 0]) == 4
    assert (rv[[0, 1, 3]] == 0).all()  # zeros off-root

    # rsb: every rank contributes n values; rank r keeps element r of
    # the elementwise MINLOC across ranks
    vs = np.stack([np.roll(np.arange(n, dtype=np.float32), r)
                   for r in range(n)])
    ix = np.tile(np.arange(n, dtype=np.int32).reshape(n, 1), (1, n))
    cv, ci = world.reduce_scatter_block((vs, ix), ops.MINLOC)
    cv, ci = np.asarray(cv), np.asarray(ci)
    for r in range(n):
        col = vs[:, r]
        k = int(np.argmin(col))  # lowest index wins ties via MPI rule
        assert float(cv[r, 0]) == float(col[k])
        assert int(ci[r, 0]) == k


def test_64bit_narrowing_refused(world):
    """MPI_DOUBLE is not MPI_FLOAT: with jax_enable_x64 off a float64
    buffer would silently lose precision inside jnp.asarray — the
    driver edge must refuse loudly, naming the remedy."""
    from ompi_release_tpu.utils.errors import MPIError

    x = np.arange(world.size * 4, dtype=np.float64).reshape(world.size, 4)
    with pytest.raises(MPIError, match="narrowed"):
        world.allreduce(x)
    with pytest.raises(MPIError, match="narrowed"):
        world.reduce_scatter_block(
            np.ones((world.size, world.size), np.int64))


def test_general_reduce_scatter_pair_op(world):
    """General MPI_Reduce_scatter with MINLOC: uneven segments of the
    elementwise (value, contributing-rank) minimum."""
    n = world.size
    vals = np.stack([np.roll(np.arange(10, dtype=np.float32), r)
                     for r in range(n)])
    idxs = np.zeros((n, 10), np.int32) \
        + np.arange(n, dtype=np.int32)[:, None]
    rc = [1, 2, 1, 2, 1, 1, 1, 1][:n]
    rc[-1] += 10 - sum(rc)
    out = world.reduce_scatter((vals, idxs), rc, ops.MINLOC)
    offs = np.concatenate([[0], np.cumsum(rc)])
    for i in range(n):
        seg = slice(offs[i], offs[i] + rc[i])
        np.testing.assert_array_equal(np.asarray(out[i][0]),
                                      vals[:, seg].min(0))
        np.testing.assert_array_equal(np.asarray(out[i][1]),
                                      vals[:, seg].argmin(0))


def test_scan_tuned(tuned):
    x = _per_rank(tuned, 20, seed=38)
    out = tuned.scan(x, ops.SUM)
    assert ("tuned", "scan", ops.SUM) in tuned._coll_programs
    np.testing.assert_allclose(
        np.asarray(out), np.cumsum(x, axis=0), rtol=2e-5
    )


def test_barrier(world):
    world.barrier()  # must simply not hang or raise


def test_collectives_on_subcomm(world):
    sub = world.create(world.group.incl([1, 3, 5]), name="odds3")
    x = _per_rank(sub, 40, seed=41)
    out = sub.allreduce(x, ops.SUM)
    np.testing.assert_allclose(
        np.asarray(out[0]), x.sum(axis=0), rtol=2e-5
    )
    sub.free()


def test_self_comm_collectives(world):
    from ompi_release_tpu.runtime.runtime import Runtime

    cs = Runtime.current().self_comm
    x = np.ones((1, 5), np.float32)
    np.testing.assert_array_equal(np.asarray(cs.allreduce(x)), x)
    np.testing.assert_array_equal(np.asarray(cs.bcast(x, 0)), x)
    assert cs._coll_providers["allreduce"] == ["self", "xla", "tuned", "basic"][0:1] or \
        cs._coll_providers["allreduce"][0] == "self"


def test_decision_rules(world):
    """Size-based algorithm pick mirrors coll_tuned_decision_fixed.c."""
    from ompi_release_tpu.coll.components import _TunedModule

    m = _TunedModule(world)
    small = np.zeros((8, 100), np.float32)   # 400 B < 10 kB
    assert m._pick_allreduce(small, ops.SUM) == "recursive_doubling"
    mid = np.zeros((8, 300_000), np.float32)  # 1.2 MB, n*1MiB=8MiB >= it
    assert m._pick_allreduce(mid, ops.SUM) == "ring"
    huge = np.zeros((8, 3_000_000), np.float32)  # 12 MB > 8 MiB
    assert m._pick_allreduce(huge, ops.SUM) == "segmented_ring"
    noncommut = ops.user_op("left", lambda a, b: a, commute=False)
    assert m._pick_allreduce(mid, noncommut) == "nonoverlapping"


def test_dynamic_rules_file(world, tmp_path):
    """Operator rule file (coll_tuned_dynamic_file.c analogue): last
    matching (comm_size, msg_bytes) line wins; precedence is forcing >
    rules > fixed constants; bad files fail at load with line info."""
    from ompi_release_tpu.coll import dynamic_rules
    from ompi_release_tpu.coll.components import _TunedModule
    from ompi_release_tpu.utils.errors import MPIError

    m = _TunedModule(world)
    mid = np.zeros((8, 300_000), np.float32)  # fixed rules say ring
    rf = tmp_path / "rules"
    rf.write_text(
        "# operator tuning run of 2026-07\n"
        "allreduce 0 0 recursive_doubling\n"
        "allreduce 0 1048576 nonoverlapping\n"
        "allreduce 16 0 ring\n"          # comm too small: never matches
        "alltoall 0 0 lax\n"
    )
    mca_var.set_value("coll_tuned_dynamic_rules_filename", str(rf))
    try:
        # not consulted until use_dynamic_rules is on (reference gate)
        assert m._pick_allreduce(mid, ops.SUM) == "ring"
        mca_var.set_value("coll_tuned_use_dynamic_rules", True)
        # 1.2 MB >= 1 MiB: LAST matching line (nonoverlapping) wins
        assert m._pick_allreduce(mid, ops.SUM) == "nonoverlapping"
        small = np.zeros((8, 100), np.float32)
        assert m._pick_allreduce(small, ops.SUM) == "recursive_doubling"
        # operator forcing still outranks the rule file
        mca_var.set_value("coll_tuned_allreduce_algorithm", "ring")
        try:
            assert m._pick_allreduce(mid, ops.SUM) == "ring"
        finally:
            mca_var.VARS.unset("coll_tuned_allreduce_algorithm")
        # a rewritten file is re-read (mtime cache key)
        rf.write_text("allreduce 0 0 basic_linear\n")
        os.utime(rf, (1, 1))  # force a distinct mtime
        assert m._pick_allreduce(mid, ops.SUM) == "basic_linear"
        # 'auto' in a rule falls through to the fixed constants
        rf.write_text("allreduce 0 0 auto\n")
        os.utime(rf, (2, 2))
        assert m._pick_allreduce(mid, ops.SUM) == "ring"
        # load-time validation names the file and line
        rf.write_text("allreduce 0 0 warp_drive\n")
        os.utime(rf, (3, 3))
        with pytest.raises(MPIError, match=r"rules:1.*warp_drive"):
            m._pick_allreduce(mid, ops.SUM)
        rf.write_text("gatherv 0 0 ring\n")
        os.utime(rf, (4, 4))
        with pytest.raises(MPIError, match="unknown collective"):
            m._pick_allreduce(mid, ops.SUM)
        rf.write_text("allreduce 0 ring\n")
        os.utime(rf, (5, 5))
        with pytest.raises(MPIError, match="expected"):
            m._pick_allreduce(mid, ops.SUM)
        # a parsed file that VANISHES mid-run keeps serving its last
        # good copy (scratch cleanup must not crash the hot path);
        # a mid-run REWRITE with a syntax error raises but preserves
        # that copy too (parse-before-clear)
        rf.write_text("allreduce 0 0 basic_linear\n")
        os.utime(rf, (6, 6))
        assert m._pick_allreduce(mid, ops.SUM) == "basic_linear"
        rf.write_text("allreduce broken\n")
        os.utime(rf, (7, 7))
        with pytest.raises(MPIError, match="expected"):
            m._pick_allreduce(mid, ops.SUM)
        rf.unlink()
        assert m._pick_allreduce(mid, ops.SUM) == "basic_linear"
        # ...but a file that never parsed is a loud failure
        dynamic_rules._cache.clear()
        with pytest.raises(MPIError, match="unreadable"):
            m._pick_allreduce(mid, ops.SUM)
    finally:
        mca_var.VARS.unset("coll_tuned_use_dynamic_rules")
        mca_var.VARS.unset("coll_tuned_dynamic_rules_filename")
        dynamic_rules._cache.clear()


def test_dynamic_rules_cover_rooted_collectives(world, tmp_path):
    """reduce/gather/scatter consult the rule file too (every tuned
    decision function is rule-capable, like the reference's tables);
    a noncommutative op refuses a rule that would break operand
    order."""
    from ompi_release_tpu.coll import dynamic_rules
    from ompi_release_tpu.coll.components import _TunedModule

    m = _TunedModule(world)
    rf = tmp_path / "rules"
    rf.write_text(
        "reduce 0 0 linear\n"
        "gather 0 0 binomial\n"
        "scatter 0 0 binomial\n"
    )
    mca_var.set_value("coll_tuned_use_dynamic_rules", True)
    mca_var.set_value("coll_tuned_dynamic_rules_filename", str(rf))
    try:
        x = np.zeros((8, 5000), np.float32)
        assert m._pick_reduce(x, ops.SUM) == "linear"
        assert m._pick_gather(x) == "binomial"
        assert m._pick_scatter(x) == "binomial"
        rf.write_text("reduce 0 0 binomial\n")
        os.utime(rf, (11, 11))
        noncommut = ops.user_op("left", lambda a, b: a, commute=False)
        # the rule says binomial, but binomial rotates operand order:
        # the noncommutative op is upgraded to in_order_binary
        assert m._pick_reduce(x, noncommut) == "in_order_binary"
    finally:
        mca_var.VARS.unset("coll_tuned_use_dynamic_rules")
        mca_var.VARS.unset("coll_tuned_dynamic_rules_filename")
        dynamic_rules._cache.clear()


def test_dynamic_rules_drive_real_collective(tuned, tmp_path):
    """A rule-selected algorithm actually runs: the compiled-program
    cache key records the algorithm the rule file picked, and the
    result keeps parity."""
    rf = tmp_path / "rules"
    rf.write_text("allgather 0 0 lax\n")
    mca_var.set_value("coll_tuned_use_dynamic_rules", True)
    mca_var.set_value("coll_tuned_dynamic_rules_filename", str(rf))
    try:
        x = _per_rank(tuned, 6, seed=23)
        out = tuned.allgather(x)
        assert ("tuned", "allgather", "lax") in tuned._coll_programs
        for r in range(tuned.size):
            np.testing.assert_array_equal(np.asarray(out[r]),
                                          x.reshape(-1))
    finally:
        mca_var.VARS.unset("coll_tuned_use_dynamic_rules")
        mca_var.VARS.unset("coll_tuned_dynamic_rules_filename")


def test_same_algorithm_bitwise_reproducible(tuned):
    """Fixed per-algorithm reduction order means the same algorithm is
    bitwise-reproducible run to run. (CROSS-algorithm order pinning —
    each algorithm vs its own numpy-order reference — lives in
    tests/test_bitwise_parity.py; this test's old name claimed a
    ring-vs-linear comparison it never made.)"""
    x = _per_rank(tuned, 4096, seed=43)
    mca_var.set_value("coll_tuned_allreduce_algorithm", "ring")
    try:
        a = np.asarray(tuned.allreduce(x, ops.SUM))
        b = np.asarray(tuned.allreduce(jnp.asarray(x), ops.SUM))
    finally:
        mca_var.VARS.unset("coll_tuned_allreduce_algorithm")
    assert any(
        k[:3] == ("tuned", "allreduce", "ring")
        for k in tuned._coll_programs
    )
    np.testing.assert_array_equal(a, b)  # bitwise


class TestHierarchicalMl:
    """coll/ml two-level algorithms (forced hierarchy: 2 nodes x 4)."""

    @pytest.fixture()
    def ml(self, world):
        mca_var.set_value("coll_ml_local_size", 4)
        mca_var.set_value("coll", "ml,basic")  # basic backfills the rest
        try:
            c = world.dup(name="ml_dup")
        finally:
            mca_var.VARS.unset("coll")
        yield c
        mca_var.VARS.unset("coll_ml_local_size")
        c.free()

    def test_ml_selected_for_allreduce(self, ml):
        assert ml._coll_providers["allreduce"][0] == "ml"

    def test_two_level_allreduce_parity(self, ml):
        x = _per_rank(ml, 1000, seed=51)
        out = ml.allreduce(x, ops.SUM)
        assert any(k[0] == "ml" for k in ml._coll_programs)
        for r in range(ml.size):
            np.testing.assert_allclose(
                np.asarray(out[r]), x.sum(axis=0), rtol=2e-5, atol=1e-4
            )

    def test_two_level_allreduce_nondivisible(self, ml):
        x = _per_rank(ml, 37, seed=52)  # 37 % 4 != 0: padding path
        out = ml.allreduce(x, ops.MAX)
        np.testing.assert_array_equal(
            np.asarray(out[0]), x.max(axis=0)
        )

    def test_two_level_bcast(self, ml):
        x = _per_rank(ml, 64, seed=53)
        out = ml.bcast(x, root=5)
        for r in range(ml.size):
            np.testing.assert_array_equal(np.asarray(out[r]), x[5])

    def test_two_level_reduce(self, ml):
        x = _per_rank(ml, 48, seed=55)
        out = np.asarray(ml.reduce(x, ops.SUM, root=3))
        np.testing.assert_allclose(out[3], x.sum(axis=0), rtol=2e-5,
                                   atol=1e-4)
        mask = np.ones(ml.size, bool)
        mask[3] = False
        assert (out[mask] == 0).all()
        assert any(k[:2] == ("ml", "reduce") for k in ml._coll_programs)

    def test_two_level_allgather(self, ml):
        x = _per_rank(ml, 24, seed=56)
        out = np.asarray(ml.allgather(x))
        for r in range(ml.size):
            np.testing.assert_array_equal(out[r], x.reshape(-1))
        assert any(k[:2] == ("ml", "allgather")
                   for k in ml._coll_programs)

    def test_two_level_reduce_scatter_block(self, ml):
        n = ml.size
        x = _per_rank(ml, n * 6, seed=57)
        out = np.asarray(ml.reduce_scatter_block(x, ops.SUM))
        tot = x.sum(axis=0)
        for r in range(n):
            np.testing.assert_allclose(out[r], tot[r * 6:(r + 1) * 6],
                                       rtol=2e-5, atol=1e-4)
        assert any(k[:2] == ("ml", "reduce_scatter_block")
                   for k in ml._coll_programs)

    def test_two_level_alltoall(self, ml):
        n = ml.size
        x = np.stack([
            np.asarray([i * 100 + j for j in range(n)], np.int32)
            for i in range(n)
        ])
        out = np.asarray(ml.alltoall(x))
        for i in range(n):
            np.testing.assert_array_equal(
                out[i], np.asarray([s * 100 + i for s in range(n)],
                                   np.int32))
        assert any(k[:2] == ("ml", "alltoall")
                   for k in ml._coll_programs)

    def test_xla_scan_defers_to_tuned_past_gather_limit(self, ml):
        # not an ml test per se, but keeps the decision-rule checks
        # together: a scan whose per-rank payload exceeds the gather
        # limit must compile tuned's recursive doubling, not xla's
        # all_gather+associative_scan
        import ompi_release_tpu as mpi

        world = mpi.init()
        big = np.ones((world.size, 300_000), np.float32)  # 1.2 MB/rank
        out = np.asarray(world.scan(big))
        np.testing.assert_allclose(out[3], 4 * big[0], rtol=1e-6)
        assert any(k[:2] == ("tuned", "scan")
                   for k in world._coll_programs), \
            [k for k in world._coll_programs if "scan" in str(k)]

    def test_ml_declines_noncommutative(self, ml):
        left = ops.user_op("left", lambda a, b: a, commute=False)
        x = _per_rank(ml, 16, seed=54)
        out = ml.allreduce(x, left)  # falls through to basic
        np.testing.assert_allclose(np.asarray(out[0]), x[0], rtol=1e-6)

    def test_ml_declines_without_hierarchy(self, world):
        # no forced local size, all endpoints share one process: ml
        # must not claim the comm
        mca_var.set_value("coll", "ml,basic")
        try:
            c = world.dup(name="no_ml")
        finally:
            mca_var.VARS.unset("coll")
        assert c._coll_providers["allreduce"] == ["basic"]
        c.free()

    def test_ml_barrier(self, ml):
        ml.barrier()
