"""Pipelined segmented collectives + small-message fusion (ISSUE 2).

Pins the two tentpole invariants on the 8-device CPU mesh:

- **Bitwise parity**: the pipelined ring allreduce / binomial bcast /
  binomial reduce produce bit-identical results to their monolithic
  kernels (the pipeline segments WITHIN ring-chunk rows and along the
  position-independent tree schedules — see ``coll/pipeline.py``).
- **Fusion semantics**: small collectives coalesce into one device
  collective per (op, dtype) with explicit flush / max-delay / capacity
  triggers, counted by the ``coll_fusion_*`` pvars.

Plus the tune→rules→runtime loop: a rules file with a ``segsize``
column round-trips through ``dynamic_rules`` and changes the segment
count reported by the ``coll_pipeline_segments`` pvar, including for a
``tpu_tune``-emitted file.
"""

import time

import numpy as np
import pytest

import ompi_release_tpu as mpi
from ompi_release_tpu import ops
from ompi_release_tpu.coll import dynamic_rules, pipeline
from ompi_release_tpu.coll.fusion import FusionBuffer, plan_buckets
from ompi_release_tpu.mca import pvar as pvar_mod
from ompi_release_tpu.mca import var as mca_var


@pytest.fixture(scope="module")
def world():
    yield mpi.init()


@pytest.fixture(scope="module")
def tuned(world):
    """Comm served by the tuned component (the coll table freezes at
    creation — select BEFORE the dup)."""
    mca_var.set_value("coll", "tuned")
    try:
        c = world.dup(name="pipe_tuned")
    finally:
        mca_var.VARS.unset("coll")
    assert c._coll_providers["allreduce"] == ["tuned"]
    yield c
    c.free()


@pytest.fixture
def cvars():
    """Set cvars for one test; restore defaults after."""
    touched = []

    def set_(name, value):
        mca_var.set_value(name, value)
        touched.append(name)

    yield set_
    for name in touched:
        mca_var.VARS.unset(name)


def _pvar(name):
    pv = pvar_mod.PVARS.lookup(name)
    assert pv is not None, f"pvar {name} not registered"
    return pv


def _per_rank(size, n, dtype=np.float32, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randn(size, n).astype(dtype)


# ---------------------------------------------------------------------------
# bitwise parity: pipelined vs monolithic
# ---------------------------------------------------------------------------

class TestPipelineBitwiseParity:
    def test_allreduce_ring_pipelined_bitwise(self, tuned, cvars):
        # 48000 f32 = 187.5 KiB/rank; segsize 64 KiB -> 3 segments
        x = _per_rank(tuned.size, 48_000, seed=1)
        cvars("coll_tuned_allreduce_algorithm", "ring")
        cvars("coll_pipeline_segsize", 0)  # monolithic
        mono = np.asarray(tuned.allreduce(x, ops.SUM))
        mca_var.set_value("coll_pipeline_segsize", 64 * 1024)
        seg_sum0 = _pvar("coll_pipeline_segments").read()["sum"]
        pipe = np.asarray(tuned.allreduce(x, ops.SUM))
        seg = _pvar("coll_pipeline_segments").read()
        np.testing.assert_array_equal(mono, pipe)  # BITWISE
        # the pipelined program is its own plan-cache entry, keyed by
        # the segment count
        assert ("tuned", "allreduce", "ring", ops.SUM, "pipelined", 3) \
            in tuned._coll_programs
        assert seg["sum"] - seg_sum0 == 3

    def test_bcast_binomial_pipelined_bitwise(self, tuned, cvars):
        x = _per_rank(tuned.size, 40_000, seed=2)
        cvars("coll_tuned_bcast_algorithm", "binomial")
        cvars("coll_pipeline_segsize", 0)
        mono = np.asarray(tuned.bcast(x, root=3))
        mca_var.set_value("coll_pipeline_segsize", 32 * 1024)
        pipe = np.asarray(tuned.bcast(x, root=3))
        np.testing.assert_array_equal(mono, pipe)
        for r in range(tuned.size):
            np.testing.assert_array_equal(pipe[r], x[3])
        assert any(k[:3] == ("tuned", "bcast", "binomial")
                   and k[-2] == "pipelined"
                   for k in tuned._coll_programs)

    def test_reduce_binomial_pipelined_bitwise(self, tuned, cvars):
        x = _per_rank(tuned.size, 40_000, seed=3)
        cvars("coll_tuned_reduce_algorithm", "binomial")
        cvars("coll_pipeline_segsize", 0)
        mono = np.asarray(tuned.reduce(x, ops.SUM, root=2))
        mca_var.set_value("coll_pipeline_segsize", 32 * 1024)
        pipe = np.asarray(tuned.reduce(x, ops.SUM, root=2))
        np.testing.assert_array_equal(mono, pipe)

    def test_pipelined_no_per_call_retrace(self, tuned, cvars):
        x = _per_rank(tuned.size, 50_000, seed=4)
        cvars("coll_tuned_allreduce_algorithm", "ring")
        cvars("coll_pipeline_segsize", 50_000)  # 4 segments
        compiled = _pvar("coll_programs_compiled")
        hits = _pvar("coll_plan_cache_hits")
        tuned.allreduce(x, ops.SUM)
        c0, h0 = compiled.read(), hits.read()["sum"]
        tuned.allreduce(x, ops.SUM)
        tuned.allreduce(x, ops.SUM)
        # re-invocations hit the plan cache: no new program, two hits
        assert compiled.read() == c0
        assert hits.read()["sum"] - h0 == 2

    def test_small_message_stays_monolithic(self, tuned, cvars):
        cvars("coll_tuned_allreduce_algorithm", "ring")
        cvars("coll_pipeline_segsize", 1 << 20)
        x = _per_rank(tuned.size, 1000, seed=5)  # 4 KB << segsize
        seg0 = _pvar("coll_pipeline_segments").read()["count"]
        tuned.allreduce(x, ops.SUM)
        assert _pvar("coll_pipeline_segments").read()["count"] == seg0

    def test_max_segments_cap(self):
        mca_var.set_value("coll_pipeline_segsize", 1024)
        mca_var.set_value("coll_pipeline_max_segments", 8)
        try:
            assert pipeline.segment_count("allreduce", 8, 1 << 20) == 8
        finally:
            mca_var.VARS.unset("coll_pipeline_segsize")
            mca_var.VARS.unset("coll_pipeline_max_segments")


# ---------------------------------------------------------------------------
# segsize rules: file -> dynamic_rules -> pipeline -> pvar
# ---------------------------------------------------------------------------

class TestSegsizeRules:
    def test_segsize_column_roundtrip(self, tuned, cvars, tmp_path):
        p = tmp_path / "rules.conf"
        p.write_text(
            "allreduce 0 0 ring 32768\n"
            "bcast 0 0 binomial auto\n"   # auto -> defer to cvar
            "alltoall 0 0 pairwise\n"     # 4-column back-compat
        )
        rules = dynamic_rules.load_rules(str(p))
        assert rules["allreduce"] == [(0, 0, "ring", 32768)]
        assert rules["bcast"] == [(0, 0, "binomial", None)]
        assert rules["alltoall"] == [(0, 0, "pairwise", None)]

        cvars("coll_tuned_use_dynamic_rules", True)
        cvars("coll_tuned_dynamic_rules_filename", str(p))
        assert dynamic_rules.lookup("allreduce", tuned.size, 131072) \
            == "ring"
        assert dynamic_rules.lookup_segsize(
            "allreduce", tuned.size, 131072) == 32768
        assert dynamic_rules.lookup_segsize(
            "bcast", tuned.size, 131072) is None

        # the rule's segsize drives the runtime segment count
        x = np.ones((tuned.size, 32768), np.float32)  # 128 KiB/rank
        seg0 = _pvar("coll_pipeline_segments").read()["sum"]
        out = np.asarray(tuned.allreduce(x, ops.SUM))
        assert _pvar("coll_pipeline_segments").read()["sum"] - seg0 == 4
        np.testing.assert_array_equal(out[0], np.full(32768, tuned.size,
                                                      np.float32))

    def test_segsize_size_suffix_and_errors(self, tmp_path):
        p = tmp_path / "r.conf"
        p.write_text("allreduce 0 0 ring 256K\n")
        assert dynamic_rules.load_rules(str(p))["allreduce"][0][3] \
            == 256 * 1024
        p.write_text("allreduce 0 0 ring nonsense\n")
        with pytest.raises(Exception, match="segsize"):
            dynamic_rules.load_rules(str(p))
        p.write_text("allreduce 0 0 ring 1 2\n")
        with pytest.raises(Exception, match="expected"):
            dynamic_rules.load_rules(str(p))


# ---------------------------------------------------------------------------
# tpu_tune: compile-time field + segsize sweep + emitted-file loop
# ---------------------------------------------------------------------------

class TestTuneSegsize:
    def test_measure_reports_compile_and_segsize(self, world):
        from ompi_release_tpu.tools import tpu_tune

        res = tpu_tune.measure(world, ["allreduce"], [262144], repeats=1,
                               segsizes=[65536], algs=["ring"])
        row = res["allreduce"][0]
        assert row["winner"] == "ring"
        # plan cache primed first: compile time is its own field
        assert row["compile"]["ring"] >= 0.0
        assert "segsize" in row and row["segsize"] in (0, 65536)
        assert set(row["segsize_times"]) == {0, 65536}
        text = tpu_tune.emit(world, res)
        assert "compile:" in text
        assert "segsize sweep" in text
        # the emitted file (5-column rule line) parses cleanly
        assert any(len(ln.split()) == 5 for ln in text.splitlines()
                   if ln and not ln.startswith("#"))

    def test_emitted_segsize_changes_pipeline_segments(
            self, world, tuned, cvars, tmp_path):
        """The acceptance loop: a tpu_tune-emitted rules file with a
        segsize column loads and changes coll_pipeline_segments. The
        sweep's timing winner is environment-dependent, so the row's
        measured segsize is pinned to 64 KiB before emit — the loop
        under test is emit -> load -> segment_count -> pvar, not which
        segsize happens to win on a CPU mesh."""
        from ompi_release_tpu.tools import tpu_tune

        res = tpu_tune.measure(world, ["allreduce"], [262144], repeats=1,
                               segsizes=[65536], algs=["ring"])
        res["allreduce"][0]["segsize"] = 65536
        text = tpu_tune.emit(world, res)
        p = tmp_path / "tuned_rules.conf"
        p.write_text(text)
        dynamic_rules.load_rules(str(p))  # loads without error

        cvars("coll_tuned_use_dynamic_rules", True)
        cvars("coll_tuned_dynamic_rules_filename", str(p))
        assert dynamic_rules.lookup_segsize(
            "allreduce", tuned.size, 262144) == 65536
        x = np.ones((tuned.size, 65536), np.float32)  # 256 KiB/rank
        seg0 = _pvar("coll_pipeline_segments").read()["sum"]
        tuned.allreduce(x, ops.SUM)
        assert _pvar("coll_pipeline_segments").read()["sum"] - seg0 == 4


# ---------------------------------------------------------------------------
# fusion buffer
# ---------------------------------------------------------------------------

class TestFusion:
    def test_flush_semantics_and_parity(self, world):
        fb = FusionBuffer(world, max_delay_us=10_000_000)
        xs = [_per_rank(world.size, 64, seed=10 + i) for i in range(6)]
        f0 = _pvar("coll_fusion_flushes").read()
        b0 = _pvar("coll_fusion_batched").read()
        handles = [fb.allreduce(x) for x in xs]
        assert fb.pending() == 6
        assert not any(h.done for h in handles)
        fb.flush()
        assert fb.pending() == 0
        assert all(h.done for h in handles)
        # 6 tensors, ONE device collective
        assert _pvar("coll_fusion_flushes").read() - f0 == 1
        assert _pvar("coll_fusion_batched").read() - b0 == 6
        for x, h in zip(xs, handles):
            np.testing.assert_allclose(
                np.asarray(h.result())[0], x.sum(axis=0),
                rtol=2e-5, atol=1e-5)

    def test_result_forces_flush(self, world):
        fb = FusionBuffer(world, max_delay_us=10_000_000)
        x = _per_rank(world.size, 32, seed=20)
        h = fb.allreduce(x)
        assert not h.done
        out = np.asarray(h.result())  # correctness never waits on policy
        assert h.done and fb.pending() == 0
        np.testing.assert_allclose(out[0], x.sum(axis=0), rtol=2e-5,
                                   atol=1e-5)

    def test_threshold_dispatches_immediately(self, world):
        fb = FusionBuffer(world, threshold=1024, max_delay_us=10_000_000)
        big = _per_rank(world.size, 512, seed=21)  # 2 KiB/rank >= 1 KiB
        h = fb.allreduce(big)
        assert h.done and fb.pending() == 0
        np.testing.assert_allclose(np.asarray(h.result())[0],
                                   big.sum(axis=0), rtol=2e-5, atol=1e-5)

    def test_max_delay_flushes_older_pendings(self, world):
        fb = FusionBuffer(world, max_delay_us=1000)  # 1 ms bound
        h1 = fb.allreduce(_per_rank(world.size, 16, seed=22))
        time.sleep(0.01)
        h2 = fb.allreduce(_per_rank(world.size, 16, seed=23))
        # the aged pending flushed BEFORE the new tensor queued
        assert h1.done and not h2.done
        fb.flush()
        assert h2.done

    def test_capacity_triggers_flush(self, world):
        fb = FusionBuffer(world, capacity=2048, max_delay_us=10_000_000)
        hs = [fb.allreduce(_per_rank(world.size, 256, seed=24 + i))
              for i in range(3)]  # 3 x 1 KiB > 2 KiB capacity
        assert all(h.done for h in hs)
        assert fb.pending() == 0

    def test_dtype_groups_stay_separate(self, world):
        fb = FusionBuffer(world, max_delay_us=10_000_000)
        f0 = _pvar("coll_fusion_flushes").read()
        hf = fb.allreduce(_per_rank(world.size, 16, seed=30))
        hi = fb.allreduce(
            np.ones((world.size, 16), np.int32), ops.SUM)
        fb.flush()
        # one fused collective per (op, dtype) group
        assert _pvar("coll_fusion_flushes").read() - f0 == 2
        np.testing.assert_array_equal(
            np.asarray(hi.result())[0],
            np.full(16, world.size, np.int32))
        assert hf.done

    def test_pvar_counts_after_burst(self, world):
        fb = FusionBuffer(world, max_delay_us=10_000_000)
        b0 = _pvar("coll_fusion_batched").read()
        f0 = _pvar("coll_fusion_flushes").read()
        s0 = _pvar("coll_fusion_bytes_saved").read()
        n_t, elems = 16, 64
        hs = [fb.allreduce(np.full((world.size, elems), i, np.float32))
              for i in range(n_t)]
        fb.flush()
        per_tensor = elems * 4
        assert _pvar("coll_fusion_batched").read() - b0 == n_t
        assert _pvar("coll_fusion_flushes").read() - f0 == 1
        # every tensor beyond the flush's first rode for free
        assert _pvar("coll_fusion_bytes_saved").read() - s0 \
            == (n_t - 1) * per_tensor
        for i, h in enumerate(hs):
            np.testing.assert_array_equal(
                np.asarray(h.result())[0],
                np.full(elems, float(i) * world.size, np.float32))

    def test_communicator_exposure(self, world):
        fb = world.fusion_buffer()
        assert fb is world.fusion_buffer()  # one per comm
        h = world.fused_allreduce(_per_rank(world.size, 16, seed=40))
        world.fusion_buffer().flush()
        assert h.done

    def test_pair_op_dispatches_immediately(self, world):
        vals = _per_rank(world.size, 8, seed=41)
        idxs = np.tile(np.arange(world.size)[:, None], (1, 8)).astype(
            np.int32)
        fb = FusionBuffer(world, max_delay_us=10_000_000)
        h = fb.allreduce((vals, idxs), ops.MAXLOC)
        assert h.done
        mv, mi = h.result()
        np.testing.assert_array_equal(np.asarray(mi[0]),
                                      vals.argmax(axis=0))


class TestPlanBuckets:
    """The shared fusion planner (also used by parallel/dp.py)."""

    def test_greedy_same_dtype_packing(self):
        items = [("a", 100, "f32"), ("b", 100, "f32"),
                 ("c", 100, "i32"), ("d", 100, "f32")]
        assert plan_buckets(items, 1000) == [["a", "b"], ["c"], ["d"]]

    def test_capacity_split(self):
        items = [("a", 600, "f32"), ("b", 600, "f32"), ("c", 600, "f32")]
        assert plan_buckets(items, 1000) == [["a"], ["b"], ["c"]]
        assert plan_buckets(items, 1200) == [["a", "b"], ["c"]]

    def test_oversized_item_gets_own_bucket(self):
        assert plan_buckets([("big", 5000, "f32")], 1000) == [["big"]]

    def test_dp_gradient_bucketing_still_correct(self, world):
        """dp.allreduce_gradients through the shared planner."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, PartitionSpec as P

        from ompi_release_tpu.parallel import dp

        n = world.size
        mesh = Mesh(np.array(jax.devices()[:n]), ("dp",))
        grads = {
            "w": np.full((n, 8, 8), 2.0, np.float32),
            "b": np.full((n, 8), 4.0, np.float32),
            "i": np.ones((n, 4), np.int32),
        }

        def body(g):
            return dp.allreduce_gradients(
                jax.tree.map(lambda a: a[0], g), "dp",
                mean=False, bucket_bytes=1 << 20)

        out = jax.jit(jax.shard_map(
            lambda g: jax.tree.map(lambda a: a[None], body(g)),
            mesh=mesh, in_specs=P("dp"), out_specs=P("dp")))(
            jax.tree.map(jnp.asarray, grads))
        np.testing.assert_allclose(np.asarray(out["w"][0]),
                                   np.full((8, 8), 2.0 * n), rtol=0)
        np.testing.assert_allclose(np.asarray(out["b"][0]),
                                   np.full(8, 4.0 * n), rtol=0)
        np.testing.assert_array_equal(np.asarray(out["i"][0]),
                                      np.full(4, n, np.int32))
