"""Static hot-path observability discipline for the new coll engines,
the wire transport, the cross-process tracing layer, and the
continuous sampler itself.

``coll/pipeline.py``, ``coll/fusion.py``, ``runtime/wire.py``,
``coll/hier.py``, ``osc/wire_win.py``, ``p2p/pml.py``,
``btl/components.py``, and ``obs/sampler.py`` sit on hot paths (the
wire router is EVERY cross-process byte; the sampler's disabled state
must cost literally nothing); PR 1's contract is that observability
costs ONE attribute check (``_obs.enabled`` / ``_watchdog.enabled``)
when off.
This test enforces it statically, without importing jax: every emit
site (journal ``record``, skew ``begin/body/end``, stall-watchdog
``arm``/``disarm``, per-call pvar registry lookups) must be gated on
an ``enabled`` flag, and every pvar bump (``.add``/``.observe``) must
target a MODULE-LEVEL pre-registered pvar (the zero-cost-counter
class the driver already uses) or itself be gated.

Gating shapes recognized:

- ``if _obs.enabled: <emit>``   (including ``and``-compounds)
- ``if not _obs.enabled: return`` followed by the emit (early-return)
- ``if tok is not None: _watchdog.disarm(tok)`` — disarm of a token
  that only exists under an enabled gate
"""

import ast
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKED = ("ompi_release_tpu/coll/pipeline.py",
           "ompi_release_tpu/coll/fusion.py",
           "ompi_release_tpu/runtime/wire.py",
           "ompi_release_tpu/coll/hier.py",
           "ompi_release_tpu/coll/hier_schedules.py",
           "ompi_release_tpu/osc/wire_win.py",
           "ompi_release_tpu/p2p/pml.py",
           "ompi_release_tpu/btl/components.py",
           "ompi_release_tpu/obs/sampler.py",
           "ompi_release_tpu/runtime/progress.py",
           "ompi_release_tpu/coll/nbc.py",
           "ompi_release_tpu/ft/ulfm.py",
           "ompi_release_tpu/parallel/elastic.py",
           "ompi_release_tpu/obs/sentinel.py",
           "ompi_release_tpu/parallel/tree.py",
           "ompi_release_tpu/coll/plan.py",
           "ompi_release_tpu/coll/topo_schedules.py",
           "ompi_release_tpu/tuning/db.py",
           "ompi_release_tpu/tuning/retune.py",
           "ompi_release_tpu/service/qos.py",
           "ompi_release_tpu/service/tenant.py",
           "ompi_release_tpu/obs/ledger.py",
           "ompi_release_tpu/obs/nativeev.py",
           "ompi_release_tpu/btl/nativewire.py",
           "ompi_release_tpu/osc/plan.py",
           "ompi_release_tpu/oshmem/shmem.py",
           "ompi_release_tpu/coll/native_exec.py")

#: attribute calls that ARE emit sites when ungated
EMIT_ATTRS = {"record", "begin", "body", "end", "arm"}
#: per-call pvar registry lookups (allocate/lock per call — never on
#: an ungated hot path; module scope is where registration belongs)
REGISTRY_ATTRS = {"counter", "aggregate", "histogram", "timer",
                  "highwatermark"}
#: bumps allowed ungated ONLY on module-level pvars
BUMP_ATTRS = {"add", "observe"}
#: receiver-name tokens that mark an emit-capable object
OBS_BASES = ("obs", "skew", "journal", "JOURNAL", "watchdog")


def _mentions_enabled(node) -> bool:
    return any(
        (isinstance(n, ast.Attribute) and n.attr == "enabled")
        or (isinstance(n, ast.Name) and n.id == "enabled")
        for n in ast.walk(node)
    )


def _terminates(stmts) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


def _module_pvars(tree) -> set:
    """Names bound at module level to pvar registrations."""
    out = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            f = stmt.value.func
            attr = f.attr if isinstance(f, ast.Attribute) else \
                (f.id if isinstance(f, ast.Name) else None)
            if attr in REGISTRY_ATTRS:
                out.update(t.id for t in stmt.targets
                           if isinstance(t, ast.Name))
    return out


def _is_registry_call(value) -> bool:
    return (isinstance(value, ast.Call)
            and isinstance(value.func, ast.Attribute)
            and value.func.attr in REGISTRY_ATTRS)


def _assign_targets(node):
    if isinstance(node, ast.Assign):
        return node.targets, node.value
    if isinstance(node, ast.AnnAssign) and node.value is not None:
        return [node.target], node.value
    return [], None


def _import_names(node) -> set:
    """Names bound by an import statement. An imported pvar is a
    module-level registration living in ANOTHER module — bumping it is
    the allowed zero-cost-counter pattern, not per-call allocation."""
    if isinstance(node, (ast.Import, ast.ImportFrom)):
        return {(a.asname or a.name).split(".")[0] for a in node.names}
    return set()


def _module_containers(tree) -> set:
    """Module-level names visibly bound to something OTHER than a pvar
    registration (``_services = weakref.WeakSet()``, imports): their
    ``.add`` calls are container ops or cross-module pvar references,
    exempt from the bump check."""
    out = set()
    for stmt in tree.body:
        targets, value = _assign_targets(stmt)
        if value is not None and not _is_registry_call(value):
            out.update(t.id for t in targets if isinstance(t, ast.Name))
        out |= _import_names(stmt)
    return out


def _bound_containers(func_node) -> set:
    """Names visibly bound inside the function to anything that is NOT
    a pvar-registry call — locals, loop vars, with-targets,
    comprehension vars. Their ``.add``/``.observe`` are container ops.
    Names with no such binding — including bare parameters — stay
    checkable, so a pvar handle smuggled in as an argument and bumped
    ungated is still flagged (the one-attr-check-off contract)."""
    out = set()

    def names(t):
        return [x.id for x in ast.walk(t) if isinstance(x, ast.Name)]

    for n in ast.walk(func_node):
        out |= _import_names(n)
        targets, value = _assign_targets(n)
        if value is not None and not _is_registry_call(value):
            for t in targets:
                out.update(names(t))
        elif isinstance(n, (ast.For, ast.AsyncFor)):
            out.update(names(n.target))
        elif isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                if item.optional_vars is not None:
                    out.update(names(item.optional_vars))
        elif isinstance(n, ast.comprehension):
            out.update(names(n.target))
    return out


def _check_calls(node, gated, pvars, violations, path, exempt=()):
    """Check every Call in an expression subtree (no statements here)."""
    for n in ast.walk(node):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        if not isinstance(f, ast.Attribute):
            continue
        where = f"{path}:{n.lineno}"
        if f.attr in EMIT_ATTRS and not gated:
            # record/begin/body/end/arm on obs-ish receivers; skip
            # unrelated receivers (e.g. dict methods named the same)
            base = f.value
            base_name = (base.id if isinstance(base, ast.Name) else
                         base.attr if isinstance(base, ast.Attribute)
                         else "")
            if any(t in base_name for t in OBS_BASES):
                violations.append(
                    f"{where}: ungated emit {base_name}.{f.attr}()")
        if f.attr in REGISTRY_ATTRS and not gated:
            base = f.value
            if isinstance(base, ast.Name) and base.id in ("pvar",
                                                          "_pvar"):
                violations.append(
                    f"{where}: per-call pvar registry lookup "
                    f"{base.id}.{f.attr}() on the hot path")
        if f.attr in BUMP_ATTRS and not gated:
            base = f.value
            if isinstance(base, ast.Name) and base.id not in pvars \
                    and base.id not in exempt:
                violations.append(
                    f"{where}: {base.id}.{f.attr}() bumps a "
                    f"non-module-level pvar ungated")


def _scan_stmts(stmts, gated, pvars, violations, path, exempt=()):
    for stmt in stmts:
        if isinstance(stmt, ast.If) and _mentions_enabled(stmt.test):
            neg = (isinstance(stmt.test, ast.UnaryOp)
                   and isinstance(stmt.test.op, ast.Not))
            _check_calls(stmt.test, gated, pvars, violations, path,
                         exempt)
            if neg:
                _scan_stmts(stmt.body, gated, pvars, violations, path,
                            exempt)
                _scan_stmts(stmt.orelse, True, pvars, violations, path,
                            exempt)
                if _terminates(stmt.body):
                    gated = True  # `if not enabled: return` early-out
            else:
                _scan_stmts(stmt.body, True, pvars, violations, path,
                            exempt)
                _scan_stmts(stmt.orelse, gated, pvars, violations, path,
                            exempt)
            continue
        # other statements: recurse into child statement lists with the
        # same gating, check the non-statement (expression) children
        for field, value in ast.iter_fields(stmt):
            if isinstance(value, list) and value \
                    and isinstance(value[0], ast.stmt):
                _scan_stmts(value, gated, pvars, violations, path,
                            exempt)
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.excepthandler):
                        _scan_stmts(v.body, gated, pvars, violations,
                                    path, exempt)
                    elif isinstance(v, ast.AST):
                        _check_calls(v, gated, pvars, violations, path,
                                     exempt)
            elif isinstance(value, ast.AST):
                _check_calls(value, gated, pvars, violations, path,
                             exempt)


def _scan_file(rel):
    path = os.path.join(REPO, rel)
    tree = ast.parse(open(path).read(), filename=rel)
    pvars = _module_pvars(tree)
    assert pvars, f"{rel}: expected module-level pvar registrations"
    mod_containers = _module_containers(tree)
    violations = []
    # scan only function bodies (module scope runs once at import)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _scan_stmts(node.body, False, pvars, violations, rel,
                        mod_containers | _bound_containers(node))
    return violations


def test_hot_path_emit_sites_are_gated():
    checked_any_gate = 0
    for rel in CHECKED:
        violations = _scan_file(rel)
        assert not violations, "\n".join(violations)
        # non-vacuous: each file must actually contain a gated emit
        src = open(os.path.join(REPO, rel)).read()
        assert "_obs.enabled" in src and "_obs.record" in src, (
            f"{rel}: expected at least one _obs.enabled-gated "
            f"_obs.record emit site")
        checked_any_gate += 1
    assert checked_any_gate == len(CHECKED)


def test_watchdog_arm_sites_are_gated_and_present():
    """The stall-watchdog arm sites (the new tracing layer's wait
    registry) must exist in the files that block on peers, and every
    one must sit under a ``_watchdog.enabled`` gate — enforced by the
    same scan (``arm`` is an EMIT_ATTR on a watchdog-ish base)."""
    armed = 0
    for rel in ("ompi_release_tpu/runtime/wire.py",
                "ompi_release_tpu/coll/hier.py",
                "ompi_release_tpu/osc/wire_win.py",
                "ompi_release_tpu/p2p/pml.py"):
        src = open(os.path.join(REPO, rel)).read()
        assert "_watchdog.enabled" in src and "_watchdog.arm" in src, (
            f"{rel}: expected gated stall-watchdog arm sites")
        armed += src.count("_watchdog.arm(")
    assert armed >= 6, f"expected >= 6 arm sites, found {armed}"


def test_gating_checker_catches_violations():
    """The checker itself must reject an ungated emit (guards against
    the static test rotting into a rubber stamp)."""
    bad = (
        "import time\n"
        "from .. import obs as _obs\n"
        "from ..mca import pvar\n"
        "_ok = pvar.counter('x')\n"
        "def hot(journal):\n"
        "    _ok.add()\n"                      # fine: module-level pvar
        "    journal.record('op', 'l', 0, 0)\n"  # VIOLATION: ungated
        "    local = pvar.counter('y')\n"        # VIOLATION: per-call
        "    local.add()\n"                      # VIOLATION: non-module
        "def hot2(ctr):\n"
        "    ctr.add()\n"  # VIOLATION: pvar smuggled in as an argument
        "def hot3():\n"
        "    seen = set()\n"
        "    seen.add(1)\n"     # fine: visibly a local container
        "    for q in ():\n"
        "        q.add(2)\n"    # fine: loop var
    )
    tree = ast.parse(bad)
    pvars = _module_pvars(tree)
    violations = []
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            _scan_stmts(node.body, False, pvars, violations, "bad.py",
                        _module_containers(tree)
                        | _bound_containers(node))
    assert len(violations) == 4, violations

    good = (
        "from .. import obs as _obs\n"
        "from ..mca import pvar\n"
        "_ok = pvar.counter('x')\n"
        "def hot(journal):\n"
        "    _ok.add()\n"
        "    if _obs.enabled:\n"
        "        journal.record('op', 'l', 0, 0)\n"
        "def hot2(journal):\n"
        "    if not _obs.enabled:\n"
        "        return 1\n"
        "    journal.record('op', 'l', 0, 0)\n"
    )
    tree = ast.parse(good)
    violations = []
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            _scan_stmts(node.body, False, _module_pvars(tree),
                        violations, "good.py",
                        _module_containers(tree)
                        | _bound_containers(node))
    assert not violations, violations

    # an ungated watchdog arm is a violation; a gated one is not
    wd = (
        "from ..obs import watchdog as _watchdog\n"
        "from ..mca import pvar\n"
        "_ok = pvar.counter('x')\n"
        "def bad_wait():\n"
        "    tok = _watchdog.arm('op')\n"          # VIOLATION: ungated
        "def good_wait():\n"
        "    tok = None\n"
        "    if _watchdog.enabled:\n"
        "        tok = _watchdog.arm('op')\n"
        "    if tok is not None:\n"
        "        _watchdog.disarm(tok)\n"
    )
    tree = ast.parse(wd)
    violations = []
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            _scan_stmts(node.body, False, _module_pvars(tree),
                        violations, "wd.py",
                        _module_containers(tree)
                        | _bound_containers(node))
    assert len(violations) == 1 and "arm" in violations[0], violations
