"""Multi-tenant service plane tests (PR 15, ROADMAP item 2).

Covers the resident daemon stack end to end:

- ``service/tenant.py``: admission control (rank/lane capacity, typed
  denials), leases + heartbeat sweep, scoped eviction (cid-band
  revoke + sentinel clear + pubsub name pruning via listeners).
- ``service/qos.py``: class-spec parsing, weight-proportional lane
  partitioning, and the weighted-fair :class:`WireArbiter` (solo fast
  path, no banked idle credit, bulk-parks-for-latency convergence).
- ``service/daemon.py``: the TAG_TENANT/TAG_TENANTS RPC plane over a
  live in-process daemon, including lease-expiry eviction by the
  serve loop and stale-name hygiene.
- ``ft/ulfm.py`` band revocation against REAL registered
  communicators, plus ``comm.set_qos_class`` inheritance.
- ``runtime/wire.py`` QoS lane-class selection through the
  generation-cached ``WireTuning`` snapshot (zero-config = legacy).
- ``runtime/pubsub.py`` owner identity + TTL (satellite 1) over a
  real NameServer.
- ``comm/dpm.py`` concurrent multi-tenant accept/connect (satellite
  2): two parked connectors from different tenants are both served,
  never bounced off or serialized behind one rendezvous slot.
- ``tools/tpu_top.py`` ``--tenants`` rendering + CLI.
- THE acceptance episode: two REAL tpurun jobs attached to one
  in-process ``tpu_serviced`` — a bulk tenant whose rank is SIGKILLed
  mid-allreduce is evicted with only ITS band revoked while the
  latency tenant's collectives and the daemon finish clean, with
  ``tpu_top --tenants`` showing both episodes.
"""

import os
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

import ompi_release_tpu as mpi
from ompi_release_tpu.ft import ulfm
from ompi_release_tpu.mca import pvar, var as mca_var
from ompi_release_tpu.service import qos as qos_mod
from ompi_release_tpu.service.daemon import ServiceClient, ServiceDaemon
from ompi_release_tpu.service.tenant import TenantRegistry
from ompi_release_tpu.utils.errors import ErrorCode, MPIError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _pv(name):
    p = pvar.PVARS.lookup(name)
    return float(p.read()) if p is not None else 0.0


# ---------------------------------------------------------------------------
# tenant registry: admission control, leases, scoped eviction
# ---------------------------------------------------------------------------


class TestTenantRegistry:
    def test_admit_grants_band_and_lease(self):
        reg = TenantRegistry(capacity_ranks=16, capacity_lanes=8,
                             lease_s=5.0)
        t = reg.admit("a", 4, qos="latency", lanes=2, owner=77)
        assert t.band == ulfm.tenant_band(t.tid)
        assert t.qos == "latency" and t.owner == 77
        assert t.token and t.expires_at > time.monotonic()
        assert reg.used_ranks() == 4 and reg.used_lanes() == 2
        doc = reg.doc()
        assert doc["tenants"][0]["name"] == "a"
        assert "token" not in doc["tenants"][0]  # secret never listed
        assert doc["capacity"]["used_ranks"] == 4
        reg.release(t.tid, t.token)

    def test_typed_denials(self):
        reg = TenantRegistry(capacity_ranks=8, capacity_lanes=2)
        base = _pv("service_admissions_denied")
        with pytest.raises(MPIError) as ei:
            reg.admit("", 4)
        assert ei.value.code == ErrorCode.ERR_ARG
        with pytest.raises(MPIError) as ei:
            reg.admit("x", 0)
        assert ei.value.code == ErrorCode.ERR_ARG
        t = reg.admit("x", 4)
        with pytest.raises(MPIError) as ei:
            reg.admit("x", 2)  # duplicate live name
        assert ei.value.code == ErrorCode.ERR_NAME
        with pytest.raises(MPIError) as ei:
            reg.admit("y", 8)  # 4 + 8 > 8 ranks
        assert ei.value.code == ErrorCode.ERR_NO_MEM
        with pytest.raises(MPIError) as ei:
            reg.admit("z", 1, lanes=2)  # 1 + 2 > 2 lanes
        assert ei.value.code == ErrorCode.ERR_NO_MEM
        assert _pv("service_admissions_denied") == base + 5
        reg.release(t.tid, t.token)

    def test_tenant_id_space_exhaustion(self):
        reg = TenantRegistry(capacity_ranks=1 << 20,
                             capacity_lanes=1 << 20, max_tenants=2)
        a = reg.admit("a", 1)
        b = reg.admit("b", 1)
        with pytest.raises(MPIError) as ei:
            reg.admit("c", 1)
        assert ei.value.code == ErrorCode.ERR_NO_MEM
        # release frees the tid for re-admission (slot reuse)
        reg.release(a.tid, a.token)
        c = reg.admit("c", 1)
        assert c.tid == a.tid
        reg.release(b.tid, b.token)
        reg.release(c.tid, c.token)

    def test_lease_renew_auth_and_stats(self):
        reg = TenantRegistry(lease_s=5.0)
        t = reg.admit("a", 1)
        with pytest.raises(MPIError) as ei:
            reg.renew(t.tid, "wrong-token")
        assert ei.value.code == ErrorCode.ERR_ARG
        with pytest.raises(MPIError) as ei:
            reg.renew(99, t.token)
        assert ei.value.code == ErrorCode.ERR_NAME
        before = t.expires_at
        time.sleep(0.01)
        reg.renew(t.tid, t.token, stats={"coll_s": 12.5})
        assert t.expires_at > before
        assert reg.doc()["tenants"][0]["stats"]["coll_s"] == 12.5
        with pytest.raises(MPIError):
            reg.release(t.tid, "wrong-token")
        reg.release(t.tid, t.token)

    def test_sweep_evicts_expired_leases_only(self):
        reg = TenantRegistry(lease_s=10.0)
        a = reg.admit("a", 1)
        b = reg.admit("b", 1, lease_s=1000.0)
        gone = reg.sweep(now=time.monotonic() + 20.0)
        assert [t.tid for t in gone] == [a.tid]
        assert a.state == "evicted"
        assert "lease expired" in a.evict_reason
        assert [t.tid for t in reg.live()] == [b.tid]
        # the eviction is idempotent and listed for forensics
        assert reg.evict(a.tid, "again") is None
        assert reg.doc()["evicted"][0]["tid"] == a.tid
        reg.release(b.tid, b.token)

    def test_evict_listener_runs_and_raising_listener_is_contained(self):
        reg = TenantRegistry()
        seen = []
        reg.add_evict_listener(
            lambda t, r: (_ for _ in ()).throw(RuntimeError("boom")))
        reg.add_evict_listener(lambda t, r: seen.append((t.tid, r)))
        t = reg.admit("a", 1)
        reg.fail(t.tid, t.token, reason="rank 3 died")
        assert seen == [(t.tid, "rank 3 died")]

    def test_note_owner_lost_evicts_only_that_owner(self):
        reg = TenantRegistry()
        a = reg.admit("a", 1, owner=10)
        b = reg.admit("b", 1, owner=20)
        gone = reg.note_owner_lost(10)
        assert [t.tid for t in gone] == [a.tid]
        assert gone[0].evict_reason == "owner lifeline lost"
        assert [t.tid for t in reg.live()] == [b.tid]
        reg.release(b.tid, b.token)

    def test_eviction_revokes_band_and_readmission_heals(self):
        reg = TenantRegistry()
        t = reg.admit("a", 1)
        tid = t.tid
        cid = ulfm.tenant_cid(tid, 3)
        ulfm.state().clear_band(*t.band)  # pristine starting point
        reg.fail(t.tid, t.token)
        assert ulfm.state().is_revoked(cid)
        # re-admission into the freed slot clears the poison (the
        # explicit-cid rebuild discipline, band-wide)
        t2 = reg.admit("fresh", 1)
        assert t2.tid == tid
        assert not ulfm.state().is_revoked(cid)
        reg.release(t2.tid, t2.token)
        ulfm.state().clear_band(*t2.band)


# ---------------------------------------------------------------------------
# QoS: class parsing, lane partitioning, weighted-fair arbiter
# ---------------------------------------------------------------------------


class TestQosClasses:
    def test_parse_classes(self):
        assert qos_mod.parse_classes("latency:8,bulk:2,best_effort:1") \
            == {"latency": 8.0, "bulk": 2.0, "best_effort": 1.0}
        assert qos_mod.parse_classes("solo") == {"solo": 1.0}
        assert qos_mod.parse_classes("") == {}
        for bad in (":3", "a:x", "a:-1", "a:0"):
            with pytest.raises(MPIError) as ei:
                qos_mod.parse_classes(bad)
            assert ei.value.code == ErrorCode.ERR_ARG

    def test_fair_share(self):
        classes = qos_mod.parse_classes("latency:8,bulk:2")
        assert qos_mod.fair_share("latency", classes) == 0.8
        assert qos_mod.fair_share("bulk", classes) == 0.2
        assert qos_mod.fair_share("unknown", classes) == 1.0
        assert qos_mod.fair_share("x", {}) == 1.0

    def test_lane_ranges_weight_proportional_disjoint(self):
        classes = {"latency": 3.0, "bulk": 1.0}
        ranges = qos_mod.lane_ranges(classes, 8)
        assert ranges == {"latency": (0, 6), "bulk": (6, 2)}
        # every lane covered exactly once, in spec order
        covered = []
        for start, count in ranges.values():
            covered.extend(range(start, start + count))
        assert covered == list(range(8))

    def test_lane_ranges_one_lane_minimum(self):
        ranges = qos_mod.lane_ranges({"a": 100.0, "b": 1.0}, 4)
        assert ranges["b"][1] >= 1
        assert sum(c for _, c in ranges.values()) == 4

    def test_lane_ranges_more_classes_than_lanes(self):
        ranges = qos_mod.lane_ranges(
            {"a": 1.0, "b": 1.0, "c": 1.0}, 2)
        assert ranges == {"a": (0, 1), "b": (1, 1), "c": (0, 1)}


class TestWireArbiter:
    def test_solo_class_never_waits(self):
        arb = qos_mod.WireArbiter({"a": 1.0})
        base = _pv("wire_qos_gate_waits")
        arb.enter("a")
        t0 = time.perf_counter()
        for _ in range(50):
            arb.gate("a")
        arb.leave("a")
        assert time.perf_counter() - t0 < 0.5
        assert arb.spend("a") == pytest.approx(50.0)
        assert _pv("wire_qos_gate_waits") == base

    def test_idle_class_banks_no_credit(self):
        arb = qos_mod.WireArbiter({"a": 1.0, "b": 1.0})
        arb.enter("a")
        for _ in range(30):
            arb.gate("a")
        # b enters from idle: its clock catches up to the active
        # minimum instead of spending 30 banked frames instantly
        arb.enter("b")
        assert arb.spend("b") == pytest.approx(30.0)
        arb.leave("a")
        arb.leave("b")

    def test_bulk_parks_for_latency_at_weight_ratio(self):
        """Under contention the bulk class's frame count tracks the
        latency class's at the weight ratio (within one quantum), and
        the parked time is witnessed by the wire_qos_gate pvars."""
        arb = qos_mod.WireArbiter({"latency": 4.0, "bulk": 1.0},
                                  quantum=4.0)
        waits0 = _pv("wire_qos_gate_waits")
        lat_done = threading.Event()
        bulk_frames = [0]

        def bulk():
            arb.enter("bulk")
            for _ in range(400):
                if lat_done.is_set():
                    break
                arb.gate("bulk")
                bulk_frames[0] += 1
            arb.leave("bulk")

        th = threading.Thread(target=bulk)
        arb.enter("latency")
        th.start()
        for _ in range(80):
            arb.gate("latency")
            time.sleep(0.0005)  # a paced latency sender
        # snapshot while latency is still active: bulk's normalized
        # spend may lead latency's by at most quantum/weight (+ one
        # in-flight gate)
        lat_vt = arb.spend("latency")        # 80 / 4 = 20
        bulk_vt = arb.spend("bulk")
        assert bulk_vt <= lat_vt + 4.0 + 1.0
        # bulk's FRAME count == its vt (weight 1): weight-ratio
        # service, ~20 frames against latency's 80
        lat_done.set()
        arb.leave("latency")
        th.join(timeout=10)
        assert not th.is_alive()
        assert bulk_frames[0] >= 1  # never starved either
        assert _pv("wire_qos_gate_waits") > waits0
        assert _pv("wire_qos_gate_wait_seconds") > 0.0

    def test_arbiter_shared_per_spec(self):
        qos_mod._reset_for_tests()
        a1 = qos_mod.arbiter_for("latency:8,bulk:2")
        a2 = qos_mod.arbiter_for("latency:8,bulk:2")
        a3 = qos_mod.arbiter_for("latency:4,bulk:2")
        assert a1 is a2 and a1 is not a3
        qos_mod._reset_for_tests()


# ---------------------------------------------------------------------------
# wire integration: lane classes through the WireTuning snapshot
# ---------------------------------------------------------------------------


class _StubRouter:
    """Just enough router for the _lane_of rule: the real WireTuning
    snapshot + the real class/lane selection methods."""

    def __init__(self, t):
        from ompi_release_tpu.runtime.wire import WireRouter

        self._t = t
        self._class_of = WireRouter._class_of

    def tuning(self):
        return self._t


class _StubComm:
    def __init__(self, cls=None):
        if cls is not None:
            self._qos_class = cls


class TestWireLaneClasses:
    @pytest.fixture()
    def qos_vars(self):
        from ompi_release_tpu.runtime.wire import WireTuning

        mca_var.set_value("wire_p2p_lanes", 8)
        mca_var.set_value("wire_qos_classes", "latency:3,bulk:1")
        try:
            yield WireTuning()
        finally:
            mca_var.VARS.unset("wire_qos_classes")
            mca_var.VARS.unset("wire_qos_class")
            mca_var.VARS.unset("wire_p2p_lanes")
            qos_mod._reset_for_tests()

    def test_zero_config_is_legacy(self):
        from ompi_release_tpu.runtime.wire import WireRouter, WireTuning

        t = WireTuning()
        assert t.qos_ranges is None and t.arbiter is None
        r = _StubRouter(t)
        for tag in (0, 5, 123):
            assert WireRouter._lane_of(r, tag, _StubComm("bulk")) \
                == tag % t.lanes

    def test_comm_class_selects_lane_subrange(self, qos_vars):
        from ompi_release_tpu.runtime.wire import WireRouter

        t = qos_vars
        assert t.qos_ranges == {"latency": (0, 6), "bulk": (6, 2)}
        assert t.arbiter is not None
        r = _StubRouter(t)
        for tag in range(16):
            lane = WireRouter._lane_of(r, tag, _StubComm("bulk"))
            assert lane in (6, 7)
            lane = WireRouter._lane_of(r, tag, _StubComm("latency"))
            assert 0 <= lane < 6
        # unknown class (and no process default): legacy full range
        assert WireRouter._lane_of(r, 13, _StubComm("mystery")) \
            == 13 % 8

    def test_process_default_class_cvar(self):
        from ompi_release_tpu.runtime.wire import WireRouter, WireTuning

        mca_var.set_value("wire_p2p_lanes", 8)
        mca_var.set_value("wire_qos_classes", "latency:3,bulk:1")
        mca_var.set_value("wire_qos_class", "bulk")
        try:
            r = _StubRouter(WireTuning())
            # unstamped comm rides the process-wide class...
            assert WireRouter._lane_of(r, 1, _StubComm()) in (6, 7)
            # ...a stamped comm overrides it
            assert WireRouter._lane_of(r, 1, _StubComm("latency")) < 6
        finally:
            mca_var.VARS.unset("wire_qos_classes")
            mca_var.VARS.unset("wire_qos_class")
            mca_var.VARS.unset("wire_p2p_lanes")
            qos_mod._reset_for_tests()


# ---------------------------------------------------------------------------
# cid-band revocation against real communicators + QoS stamping
# ---------------------------------------------------------------------------


class TestBandsOnRealComms:
    def test_band_revoke_hits_only_that_tenants_comms(self):
        from ompi_release_tpu.comm.communicator import Communicator

        world = mpi.init()
        st = ulfm.state()
        a = Communicator(world.runtime, world.group, name="tenant-a",
                         cid=ulfm.tenant_cid(5, 0))
        b = Communicator(world.runtime, world.group, name="tenant-b",
                         cid=ulfm.tenant_cid(6, 0))
        try:
            st.revoke_band(*ulfm.tenant_band(5))
            with pytest.raises(MPIError) as ei:
                a.allreduce(np.ones((8, 2), np.float32))
            assert ei.value.code == ErrorCode.ERR_REVOKED
            # the neighbor tenant's comm still works
            out = np.asarray(b.allreduce(np.ones((8, 2), np.float32)))
            np.testing.assert_array_equal(out, np.full((8, 2), 8.0))
            # ...and so does the daemon's own (non-tenant) world
            np.testing.assert_array_equal(
                np.asarray(world.allreduce(np.ones((8, 1), np.int32))),
                np.full((8, 1), 8))
        finally:
            st.clear_band(*ulfm.tenant_band(5))
            st.clear_band(*ulfm.tenant_band(6))
            a._revoked = False
            a.free()
            b.free()

    def test_band_clear_on_sentinel(self):
        from ompi_release_tpu.obs import sentinel

        mca_var.set_value("obs_sentinel", 1)
        sentinel.refresh(True)
        try:
            cid = ulfm.tenant_cid(7, 1)
            neighbor = ulfm.tenant_cid(8, 1)
            sentinel.record_sig(cid, "allreduce", "add")
            sentinel.record_sig(neighbor, "allreduce", "add")
            assert sentinel.chain_of(cid) != 0
            sentinel.clear_band(*ulfm.tenant_band(7))
            assert sentinel.chain_of(cid) == 0
            assert sentinel.chain_of(neighbor) != 0  # out of band
            sentinel.clear_band(*ulfm.tenant_band(8))
        finally:
            mca_var.VARS.unset("obs_sentinel")
            sentinel.refresh()

    def test_qos_class_stamp_inherited_by_children(self):
        world = mpi.init()
        c = world.dup("qos-parent")
        assert c.qos_class is None
        c.set_qos_class("bulk")
        child = c.dup("qos-child")
        assert child.qos_class == "bulk"
        child.set_qos_class(None)
        assert child.qos_class is None and c.qos_class == "bulk"
        child.free()
        c.free()

    def test_sampler_points_carry_tenant_dimension(self):
        from ompi_release_tpu.obs.sampler import SeriesRing

        ring = SeriesRing(16)
        ring.append(0.0, ulfm.tenant_cid(3, 0), "coll_ops", 5,
                    tenant=3)
        ring.append(0.0, 1, "coll_ops", 7, tenant=-1)
        pts = ring.snapshot()
        assert pts[0]["tenant"] == 3
        assert "tenant" not in pts[1]  # non-tenant cids stay compact


# ---------------------------------------------------------------------------
# pubsub owner identity + TTL (satellite 1) over a real server
# ---------------------------------------------------------------------------


class TestPubsubHygiene:
    def test_ttl_expiry_prunes_server_side(self):
        from ompi_release_tpu.tools.tpu_server import (NameClient,
                                                       NameServer)

        srv = NameServer()
        c = NameClient("127.0.0.1", srv.port)
        try:
            c.publish("ttl-svc", "tpu-port:7", ttl_s=0.4)
            assert c.lookup("ttl-svc", timeout_ms=2000) == "tpu-port:7"
            time.sleep(1.0)  # serve loop prunes every iteration
            with pytest.raises(MPIError):
                c.lookup("ttl-svc", timeout_ms=200)
            # the name is re-publishable after expiry (not a dup)
            c.publish("ttl-svc", "tpu-port:8")
            assert c.lookup("ttl-svc", timeout_ms=2000) == "tpu-port:8"
        finally:
            c.close()
            srv.shutdown()

    def test_evict_owner_drops_only_that_owners_names(self):
        from ompi_release_tpu.tools.tpu_server import (NameClient,
                                                       NameServer)

        srv = NameServer()
        ca = NameClient("127.0.0.1", srv.port)
        cb = NameClient("127.0.0.1", srv.port)
        try:
            ca.publish("a-one", "pa1")
            ca.publish("a-two", "pa2")
            cb.publish("b-one", "pb1")
            gone = srv._table.evict_owner(ca.client_id)
            assert sorted(gone) == ["a-one", "a-two"]
            with pytest.raises(MPIError):
                cb.lookup("a-one", timeout_ms=200)
            assert cb.lookup("b-one", timeout_ms=2000) == "pb1"
            # legacy publish (no TTL) still works and never expires
            assert srv._table.names["b-one"].expire_at is None
            assert srv._table.names["b-one"].owner == cb.client_id
        finally:
            ca.close()
            cb.close()
            srv.shutdown()


# ---------------------------------------------------------------------------
# dpm: concurrent multi-tenant accept/connect (satellite 2)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def world():
    return mpi.init()


class TestDpmConcurrency:
    def test_two_parked_connectors_both_served(self, world):
        """THE satellite-2 regression: two connectors from different
        tenants park on one port BEFORE any acceptor exists. The old
        single-slot rendezvous bounced the second with 'port already
        has a connector'; the queue serves both, FIFO."""
        from ompi_release_tpu.comm import (close_port, comm_accept,
                                           comm_connect, open_port)

        srv = world.create(world.group.incl([0, 1]), name="mt-srv")
        c1 = world.create(world.group.incl([2, 3]), name="mt-c1")
        c2 = world.create(world.group.incl([4, 5]), name="mt-c2")
        port = open_port()
        results = {}
        errors = {}

        def connect(name, comm):
            try:
                results[name] = comm_connect(comm, port, timeout_s=20)
            except BaseException as e:  # pragma: no cover
                errors[name] = e

        t1 = threading.Thread(target=connect, args=("c1", c1))
        t1.start()
        time.sleep(0.3)  # c1 parks first (FIFO order pinned below)
        t2 = threading.Thread(target=connect, args=("c2", c2))
        t2.start()
        time.sleep(0.3)  # both parked, no acceptor yet
        ic1 = comm_accept(srv, port, timeout_s=20)
        ic2 = comm_accept(srv, port, timeout_s=20)
        t1.join(timeout=20)
        t2.join(timeout=20)
        assert not errors, errors
        assert ic1.remote_group.world_ranks == (2, 3)   # FIFO: c1 first
        assert ic2.remote_group.world_ranks == (4, 5)
        assert results["c1"].remote_group.world_ranks == (0, 1)
        assert results["c2"].remote_group.world_ranks == (0, 1)
        assert results["c1"].mirror is ic1
        assert results["c2"].mirror is ic2
        close_port(port)

    def test_one_partys_timeout_leaves_others_parked(self, world):
        """A parked connector timing out withdraws only itself: a
        second tenant parked on the same port is still served by the
        next accept (the old code poisoned the whole rendezvous)."""
        from ompi_release_tpu.comm import (close_port, comm_accept,
                                           comm_connect, open_port)

        c1 = world.create(world.group.incl([2, 3]), name="to-c1")
        c2 = world.create(world.group.incl([4, 5]), name="to-c2")
        srv = world.create(world.group.incl([0, 1]), name="to-srv")
        port = open_port()
        with pytest.raises(MPIError) as ei:
            comm_connect(c1, port, timeout_s=0.3)  # nobody accepts
        assert ei.value.code == ErrorCode.ERR_PORT
        results = {}

        def connect():
            results["ic"] = comm_connect(c2, port, timeout_s=20)

        t = threading.Thread(target=connect)
        t.start()
        time.sleep(0.2)
        ic = comm_accept(srv, port, timeout_s=20)
        t.join(timeout=20)
        assert ic.remote_group.world_ranks == (4, 5)
        assert results["ic"].remote_group.world_ranks == (0, 1)
        close_port(port)

    def test_close_port_wakes_parked_parties_promptly(self, world):
        from ompi_release_tpu.comm import (close_port, comm_connect,
                                           open_port)

        c1 = world.create(world.group.incl([2, 3]), name="cp-c1")
        port = open_port()
        caught = {}

        def connect():
            t0 = time.monotonic()
            try:
                comm_connect(c1, port, timeout_s=30)
            except MPIError as e:
                caught["err"] = e
                caught["dt"] = time.monotonic() - t0

        t = threading.Thread(target=connect)
        t.start()
        time.sleep(0.3)
        close_port(port)
        t.join(timeout=10)
        assert caught["err"].code == ErrorCode.ERR_PORT
        assert "closed" in str(caught["err"])
        assert caught["dt"] < 5.0  # woke on close, not on deadline


# ---------------------------------------------------------------------------
# daemon RPC plane (in-process tpu-serviced)
# ---------------------------------------------------------------------------


class TestServiceDaemon:
    @pytest.fixture()
    def daemon(self):
        srv = ServiceDaemon(capacity_ranks=16, capacity_lanes=8,
                            lease_s=30.0)
        client = ServiceClient("127.0.0.1", srv.port)
        admitted = []
        yield srv, client, admitted
        for tid, token in admitted:
            try:
                client.release(tid, token)
            except Exception:
                pass
        for t in srv.registry.live():
            srv.registry.evict(t.tid, "test teardown")
            ulfm.state().clear_band(*t.band)
        client.close()
        srv.shutdown()

    def test_admit_renew_release_roundtrip(self, daemon):
        srv, client, admitted = daemon
        g = client.admit("trainer-a", ranks=8, qos="latency", lanes=2)
        assert g["band"] == list(ulfm.tenant_band(g["tid"]))
        assert g["qos"] == "latency"
        r = client.renew(g["tid"], g["token"],
                         stats={"coll_s": 120.0, "mb_s": 85.0})
        assert r["expires_in_s"] > 0
        view = client.tenants()
        assert view["tenants"][0]["stats"]["mb_s"] == 85.0
        assert view["capacity"]["used_ranks"] == 8
        out = client.release(g["tid"], g["token"])
        assert out["state"] == "evicted"
        assert client.tenants()["tenants"] == []
        ulfm.state().clear_band(*ulfm.tenant_band(g["tid"]))

    def test_typed_denials_cross_the_wire(self, daemon):
        srv, client, admitted = daemon
        g = client.admit("a", ranks=8)
        admitted.append((g["tid"], g["token"]))
        with pytest.raises(MPIError) as ei:
            client.admit("a", ranks=1)
        assert ei.value.code == ErrorCode.ERR_NAME
        with pytest.raises(MPIError) as ei:
            client.admit("b", ranks=16)  # 8 + 16 > 16
        assert ei.value.code == ErrorCode.ERR_NO_MEM
        with pytest.raises(MPIError) as ei:
            client.renew(g["tid"], "stolen-token")
        assert ei.value.code == ErrorCode.ERR_ARG

    def test_eviction_drops_tenant_pubsub_names(self, daemon):
        srv, client, admitted = daemon
        g = client.admit("crashy", ranks=1)
        client.publish("crashy-port", "tpu-port:9")
        assert client.lookup("crashy-port", timeout_ms=2000) \
            == "tpu-port:9"
        client.fail(g["tid"], g["token"], reason="rank died")
        with pytest.raises(MPIError):
            client.lookup("crashy-port", timeout_ms=200)
        view = client.tenants()
        assert view["evicted"][-1]["evict_reason"] == "rank died"
        ulfm.state().clear_band(*ulfm.tenant_band(g["tid"]))

    def test_lease_expiry_evicted_by_serve_loop(self, daemon):
        """No heartbeat -> the serve loop's sweep evicts within ~a
        lease: silent job death is detected by the very loop serving
        live tenants (no reaper thread to lose)."""
        srv, client, admitted = daemon
        g = client.admit("silent", ranks=1, lease_s=0.5)
        deadline = time.monotonic() + 10.0
        while srv.registry.get(g["tid"]) is not None:
            assert time.monotonic() < deadline, "sweep never evicted"
            time.sleep(0.1)
        view = client.tenants()
        assert "lease expired" in view["evicted"][-1]["evict_reason"]
        ulfm.state().clear_band(*ulfm.tenant_band(g["tid"]))

    def test_malformed_rpc_is_contained(self, daemon):
        srv, client, admitted = daemon
        with pytest.raises(MPIError):
            client._tenant_rpc({"op": "explode"})
        # the daemon survives to serve the next request
        assert client.tenants()["capacity"]["ranks"] == 16


# ---------------------------------------------------------------------------
# tpu_top --tenants rendering
# ---------------------------------------------------------------------------


class TestTenantView:
    DOC = {
        "capacity": {"ranks": 64, "lanes": 16, "used_ranks": 10,
                     "used_lanes": 3},
        "tenants": [
            {"tid": 0, "name": "trainer-a", "qos": "latency",
             "ranks": 8, "lanes": 2, "state": "live",
             "beat_age_s": 0.8,
             "stats": {"coll_s": 120.0, "mb_s": 85.5,
                       "lane_share": 0.8, "hol_wait_s": 0.0012}},
            {"tid": 1, "name": "inference-b", "qos": "bulk",
             "ranks": 2, "lanes": 1, "state": "live",
             "beat_age_s": 2.0, "stats": {}},
        ],
        "evicted": [
            {"tid": 2, "name": "crashy", "qos": "best_effort",
             "ranks": 4, "lanes": 1, "state": "evicted",
             "evict_reason": "rank 3 died", "beat_age_s": 31.0,
             "stats": {}},
        ],
    }

    def test_render_tenants(self):
        from ompi_release_tpu.tools.tpu_top import render_tenants

        out = render_tenants(self.DOC)
        assert "10/64 ranks" in out and "3/16 lanes" in out
        assert "trainer-a" in out and "latency" in out
        assert "120.0" in out and "85.50" in out
        assert "80.0" in out          # lane_share as percent
        assert "1.20" in out          # hol_wait_s as ms
        assert "evicted (rank 3 died)" in out
        # stat-less tenants render placeholders, not crashes
        assert "inference-b" in out

    def test_render_empty(self):
        from ompi_release_tpu.tools.tpu_top import render_tenants

        out = render_tenants({"capacity": {}, "tenants": [],
                              "evicted": []})
        assert "(no live tenants)" in out

    def test_cli_one_frame_against_live_daemon(self, capsys):
        from ompi_release_tpu.tools import tpu_top

        srv = ServiceDaemon()
        try:
            t = srv.registry.admit("cli-t", 2, qos="latency")
            rc = tpu_top.main(["--tenants", f"127.0.0.1:{srv.port}",
                               "--iterations", "1", "-d", "0.1"])
            out = capsys.readouterr().out
            assert rc == 0
            assert "cli-t" in out and "latency" in out
            srv.registry.release(t.tid, t.token)
        finally:
            for t in srv.registry.live():
                srv.registry.evict(t.tid, "teardown")
            srv.shutdown()
            for tid in range(2):
                ulfm.state().clear_band(*ulfm.tenant_band(tid))

    def test_cli_bad_target(self):
        from ompi_release_tpu.tools import tpu_top

        assert tpu_top.main(["--tenants", "nonsense",
                             "--iterations", "1"]) == 2


# ---------------------------------------------------------------------------
# THE acceptance episode: two real tpurun jobs, one daemon
# ---------------------------------------------------------------------------

APP_PRELUDE = textwrap.dedent("""
    import os, sys, time
    sys.path.insert(0, %r)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import ompi_release_tpu as mpi
    from ompi_release_tpu.comm.communicator import Communicator
    from ompi_release_tpu.ft import ulfm as _ulfm
    from ompi_release_tpu.runtime.runtime import Runtime
    from ompi_release_tpu.service.daemon import ServiceClient

    world = mpi.init()
    rt = Runtime.current()
    me = rt.bootstrap["process_index"]
    _h, _p = os.environ["OMPITPU_SERVICED"].rsplit(":", 1)

    def attach(name, qos):
        # controller admits; the grant's tid reaches every process
        # via the job's own world comm (sum of tid+1 from rank 0)
        cl = tid = None
        contrib = np.zeros((2, 1), np.int32)
        if me == 0:
            cl = ServiceClient(_h, int(_p))
            g = cl.admit(name, ranks=world.size, qos=qos)
            tid = g["tid"]
            contrib[0, 0] = tid + 1
            token = g["token"]
        else:
            token = None
        tid = int(np.asarray(world.allreduce(contrib))[0, 0]) - 1
        tcomm = Communicator(rt, world.group, name=f"t{tid}",
                             cid=_ulfm.tenant_cid(tid, 0))
        tcomm.set_qos_class(qos)
        return cl, tid, (token if me == 0 else None), tcomm
""" % REPO)


def _write_app(tmp_path, name, body):
    app = tmp_path / name
    app.write_text(APP_PRELUDE + textwrap.dedent(body))
    return app


class TestServiceJobs:
    def test_two_jobs_one_daemon_kill_isolation(self, tmp_path, capfd):
        """THE acceptance criterion, isolation leg: two independently
        launched tpurun jobs attach to ONE resident daemon as tenants
        of one fabric. A bulk-tenant rank is SIGKILLed mid-allreduce:
        its survivors get the typed ULFM error on exactly their
        tenant-band cid and report the failure; the latency tenant's
        collectives, lease renewals, and graceful release — and the
        daemon itself — finish clean; ``tpu_top --tenants`` shows
        both episodes."""
        from ompi_release_tpu.tools.tpu_top import render_tenants
        from ompi_release_tpu.tools.tpurun import Job

        bulk_app = _write_app(tmp_path, "bulk_app.py", """
            cl, tid, token, tcomm = attach("bulk-job", "bulk")
            # fence + drain: the attach-phase WORLD frames must all
            # land before the kill, so the death lands mid-allreduce
            # on the TENANT-band cid (the episode under test)
            world.barrier()
            time.sleep(0.5)
            x = np.stack([np.full(256, me * 2 + i + 1.0, np.float32)
                          for i in range(2)])
            err = None
            for step in range(40):
                if me == 2 and step == 10:
                    import signal
                    os.kill(os.getpid(), signal.SIGKILL)
                try:
                    tcomm.allreduce(x)
                    time.sleep(0.02)
                except mpi.MPIError as e:
                    err = e
                    break
            assert err is not None, "kill never surfaced"
            assert err.code in (mpi.ErrorCode.ERR_PROC_FAILED,
                                mpi.ErrorCode.ERR_REVOKED), err
            assert _ulfm.tenant_of_cid(tcomm.cid) == tid
            if me == 0:
                cl.fail(tid, token,
                        reason="rank 2 died mid-allreduce")
                cl.close()
            print(f"BULK_TYPED_OK rank{me}", flush=True)
            mpi.finalize()
        """)
        lat_app = _write_app(tmp_path, "lat_app.py", """
            cl, tid, token, tcomm = attach("lat-job", "latency")
            x = np.stack([np.arange(64, dtype=np.float32) * (me + i + 1)
                          for i in range(2)])
            want = None
            t0 = time.monotonic()
            for step in range(40):
                out = np.asarray(tcomm.allreduce(x))
                if want is None:
                    want = out.copy()
                assert np.array_equal(out, want)
                if me == 0 and step % 10 == 0:
                    cl.renew(tid, token, stats={
                        "coll_s": (step + 1)
                        / max(time.monotonic() - t0, 1e-9)})
            assert _ulfm.tenant_of_cid(tcomm.cid) == tid
            if me == 0:
                cl.release(tid, token)
                cl.close()
            print(f"LAT_CLEAN_OK rank{me}", flush=True)
            mpi.finalize()
        """)
        srv = ServiceDaemon(capacity_ranks=32, capacity_lanes=16,
                            lease_s=60.0)
        os.environ["OMPITPU_SERVICED"] = f"127.0.0.1:{srv.port}"
        qos_mca = [("wire_qos_classes", "latency:8,bulk:2")]
        results = {}
        try:
            def run(name, app, n, **kw):
                job = Job(n, [sys.executable, str(app)], list(qos_mca),
                          heartbeat_s=0.3, miss_limit=3, **kw)
                results[name] = (job.run(timeout_s=300), job)

            tb = threading.Thread(target=run, args=(
                "bulk", bulk_app, 3), kwargs={"on_failure": "continue"})
            tl = threading.Thread(target=run, args=("lat", lat_app, 2))
            tb.start()
            tl.start()
            tb.join(timeout=320)
            tl.join(timeout=320)
            assert not tb.is_alive() and not tl.is_alive()
            out = capfd.readouterr()
            text = out.out + out.err
            rc_bulk, job_bulk = results["bulk"]
            rc_lat, _job_lat = results["lat"]
            assert rc_bulk == 0, text      # survivors clean, death forgiven
            assert rc_lat == 0, text       # the latency tenant never noticed
            assert text.count("BULK_TYPED_OK") == 2, text  # both survivors
            assert "BULK_TYPED_OK rank2" not in text
            assert text.count("LAT_CLEAN_OK") == 2, text
            assert job_bulk._ft_failed_ranks, "no promoted corpse"

            # the daemon outlived the episode and shows both stories
            client = ServiceClient("127.0.0.1", srv.port)
            try:
                view = client.tenants()
            finally:
                client.close()
            assert view["tenants"] == []  # both tenants gone
            by_name = {t["name"]: t for t in view["evicted"]}
            assert by_name["bulk-job"]["evict_reason"] \
                == "rank 2 died mid-allreduce"
            assert by_name["lat-job"]["evict_reason"] == "released"
            assert by_name["lat-job"]["stats"].get("coll_s", 0) > 0
            frame = render_tenants(view)
            assert "rank 2 died mid-allreduce" in frame
            assert "released" in frame

            # daemon-side scoping: the failed tenant's band is
            # revoked in the daemon process, the clean tenant's too
            # (release also retires its band) — but ONLY tenant bands,
            # never the daemon's own cid space
            st = ulfm.state()
            bulk_tid = by_name["bulk-job"]["tid"]
            assert st.is_revoked(ulfm.tenant_cid(bulk_tid, 0))
            assert not st.is_revoked(0)
        finally:
            os.environ.pop("OMPITPU_SERVICED", None)
            for t in srv.registry.live():
                srv.registry.evict(t.tid, "teardown")
            srv.shutdown()
            for tid in range(4):
                ulfm.state().clear_band(*ulfm.tenant_band(tid))
