"""The continuous fleet metrics plane.

Four layers under test:

- unit: sampler delta snapshots (scalar + histogram deltas, zero-delta
  suppression, per-communicator scoping from journal spans, ring
  bounds), histogram percentile math, OpenMetrics-with-timestamps
  exposition, and the series dump/merge clock correction;
- in-process fleet: a live HnpCoordinator TAG_SERIES responder
  aggregating three WorkerAgents' pushes, queried through tpu_top's
  FleetClient and rendered as per-rank rows;
- gate: tpu_bench_gate's noise-bound fit catching an injected 2x
  latency regression (and a halved bandwidth) in synthetic BENCH
  history while passing the repo's REAL history;
- job: a 3-process tpurun run with the sampler armed — per-rank
  series dumps at finalize, clock-corrected merge, tpu_top rows, the
  HNP-side aggregation, and the skew report's sampled-rate annotation
  (the acceptance criteria).
"""

import json
import os
import sys
import time

import numpy as np
import pytest

from ompi_release_tpu.mca import pvar as pvar_mod
from ompi_release_tpu.mca import var as mca_var
from ompi_release_tpu.obs import doctor as doctor_mod
from ompi_release_tpu.obs import export as export_mod
from ompi_release_tpu.obs import sampler as sampler_mod
from ompi_release_tpu.tools.tpurun import Job

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def obs_sampling():
    """obs enabled + sampler state reset; fully restored afterwards."""
    import ompi_release_tpu.obs as obs

    obs.enable()
    sampler_mod._reset_for_tests()
    try:
        yield obs
    finally:
        sampler_mod._reset_for_tests()
        obs.disable()


# ---------------------------------------------------------------------------
# unit: sampler deltas
# ---------------------------------------------------------------------------

class TestSamplerDeltas:
    def test_counter_delta_not_cumulative_value(self, obs_sampling):
        c = pvar_mod.counter("mp_test_ctr", "t")
        c.add(100)
        s = sampler_mod.SAMPLER
        s.sample_once()  # baseline: first sight records the current read
        c.add(7)
        s.sample_once()
        pts = [p for p in sampler_mod.snapshot()
               if p["name"] == "mp_test_ctr"]
        # second tick's point is the DELTA, not the cumulative 107
        assert pts[-1]["v"] == 7.0, pts

    def test_zero_delta_suppressed(self, obs_sampling):
        c = pvar_mod.counter("mp_quiet_ctr", "t")
        c.add(1)
        s = sampler_mod.SAMPLER
        s.sample_once()
        n_before = len([p for p in sampler_mod.snapshot()
                        if p["name"] == "mp_quiet_ctr"])
        s.sample_once()  # nothing bumped: no new point for this series
        n_after = len([p for p in sampler_mod.snapshot()
                       if p["name"] == "mp_quiet_ctr"])
        assert n_after == n_before == 1

    def test_histogram_delta_buckets(self, obs_sampling):
        h = pvar_mod.histogram("mp_test_hist", "t")
        h.observe(3.0)
        s = sampler_mod.SAMPLER
        s.sample_once()
        h.observe(3.5)   # same (2,4] bucket
        h.observe(100.0)
        s.sample_once()
        pts = [p for p in sampler_mod.snapshot()
               if p["name"] == "mp_test_hist"]
        d = pts[-1]["v"]
        assert d["count"] == 2.0
        assert d["buckets"][4.0] == 1.0    # only the NEW observation
        assert d["buckets"][128.0] == 1.0

    def test_per_communicator_scoping(self, obs_sampling):
        obs = obs_sampling
        s = sampler_mod.SAMPLER
        s.sample_once()
        t = time.perf_counter()
        obs.journal.record("allreduce", "coll", t, 1e-3, nbytes=4096,
                           comm_id=3)
        obs.journal.record("allreduce", "coll", t, 2e-3, nbytes=4096,
                           comm_id=3)
        obs.journal.record("bcast", "coll", t, 1e-3, nbytes=128,
                           comm_id=9)
        obs.journal.record("wire_send", "wire", t, 1e-3, nbytes=999,
                           comm_id=3)  # non-coll layer: not a series
        s.sample_once()
        by_cid = {}
        for p in sampler_mod.snapshot():
            if p["name"] in ("coll_ops", "coll_bytes", "coll_seconds"):
                by_cid.setdefault(p["cid"], {})[p["name"]] = p["v"]
        assert by_cid[3]["coll_ops"] == 2.0
        assert by_cid[3]["coll_bytes"] == 8192.0
        assert by_cid[9]["coll_ops"] == 1.0
        assert by_cid[3]["coll_seconds"] == pytest.approx(3e-3)

    def test_ring_bound_and_counters(self, obs_sampling):
        ring = sampler_mod.SeriesRing(size=4)
        for i in range(10):
            ring.append(float(i), -1, "x", float(i))
        snap = ring.snapshot()
        assert len(snap) == 4
        assert [p["v"] for p in snap] == [6.0, 7.0, 8.0, 9.0]
        assert ring.total_recorded == 10
        pts, cursor = ring.drain_since(8)
        assert [p["v"] for p in pts] == [8.0, 9.0] and cursor == 10

    def test_disabled_sampler_records_nothing(self):
        import ompi_release_tpu.obs as obs

        sampler_mod._reset_for_tests()
        assert not obs.enabled
        assert sampler_mod.SAMPLER.sample_once() == 0
        assert sampler_mod.snapshot() == []
        # and maybe_start without the interval cvar set arms nothing
        obs.enable()
        try:
            assert not sampler_mod.maybe_start()
            assert not sampler_mod.SAMPLER.running()
        finally:
            obs.disable()

    def test_idle_ticks_are_fully_quiet(self, obs_sampling):
        """The self-observation feedback loop stays closed: after the
        baseline tick, a process where NOTHING happened records zero
        points (the sampler's own pvars and the journal bookkeeping
        its tick span moves are excluded from the scan), so an idle
        fleet pushes nothing."""
        s = sampler_mod.SAMPLER
        s.sample_once()  # baseline (first sight of every pvar)
        s.sample_once()  # may see deltas from the baseline tick itself
        assert s.sample_once() == 0

    def test_overhead_pvar_accounts_ticks(self, obs_sampling):
        ov0 = float(pvar_mod.PVARS.lookup(
            "obs_sample_overhead_seconds").read())
        sampler_mod.SAMPLER.sample_once()
        assert float(pvar_mod.PVARS.lookup(
            "obs_sample_overhead_seconds").read()) > ov0


# ---------------------------------------------------------------------------
# unit: percentile math
# ---------------------------------------------------------------------------

class TestPercentile:
    def test_empty(self):
        assert sampler_mod.percentile({}, 0.5) is None
        assert sampler_mod.percentile({4.0: 0}, 0.5) is None

    def test_single_bucket_midpoint(self):
        # all mass in (4, 8]: the geometric-midpoint estimate is 6
        assert sampler_mod.percentile({8.0: 5}, 0.5) == 6.0
        assert sampler_mod.percentile({8.0: 5}, 0.99) == 6.0

    def test_quantile_picks_the_right_bucket(self):
        # 90 obs in (0.5, 1], 10 in (512, 1024]
        b = {1.0: 90, 1024.0: 10}
        assert sampler_mod.percentile(b, 0.5) == 0.75
        assert sampler_mod.percentile(b, 0.99) == 768.0

    def test_zero_bucket_and_string_keys(self):
        assert sampler_mod.percentile({"0.0": 3}, 0.5) == 0.0
        assert sampler_mod.percentile({"8.0": 1, "0.0": 0}, 0.5) == 6.0


# ---------------------------------------------------------------------------
# unit: OpenMetrics-with-timestamps + series dump/merge clock math
# ---------------------------------------------------------------------------

def _pt(i, t, cid, name, v):
    return {"i": i, "t": t, "cid": cid, "name": name, "v": v}


class TestSeriesExport:
    def test_openmetrics_has_timestamps_and_eof(self):
        pts = [_pt(0, 10.5, -1, "coll_invocations", 3.0),
               _pt(1, 10.5, 2, "coll_ops", 5.0)]
        om = export_mod.openmetrics_series(pts, pidx=1,
                                           clock_offset_s=2.0)
        assert om.endswith("# EOF\n")
        assert ('ompitpu_coll_invocations_delta{pidx="1",cid="-1"} '
                "3 12.500000") in om
        assert 'cid="2"' in om

    def test_openmetrics_histogram_expansion(self):
        pts = [_pt(0, 1.0, -1, "coll_allreduce_latency",
                   {"count": 4.0, "sum": 2.0, "min": 0.1, "max": 1.0,
                    "buckets": {1.0: 4}})]
        om = export_mod.openmetrics_series(pts)
        assert "_delta_count" in om and "_delta_sum" in om
        assert "_delta_p50" in om and "_delta_p99" in om

    def test_openmetrics_families_contiguous_and_typed_once(self):
        # interleaved input points; the exposition must regroup them
        # (spec: one TYPE line per family, family samples contiguous)
        pts = [_pt(0, 1.0, -1, "aa", 1.0), _pt(1, 1.0, -1, "bb", 2.0),
               _pt(2, 2.0, -1, "aa", 3.0)]
        lines = export_mod.openmetrics_series(pts).splitlines()
        types = [ln for ln in lines if ln.startswith("# TYPE")]
        assert len(types) == len(set(types)) == 2
        ia = lines.index("# TYPE ompitpu_aa_delta gauge")
        assert lines[ia + 1].startswith("ompitpu_aa_delta{")
        assert lines[ia + 2].startswith("ompitpu_aa_delta{")

    def test_openmetrics_per_point_pidx_for_merged_fleet(self):
        pts = [dict(_pt(0, 1.0, -1, "x", 1.0), pidx=2)]
        om = export_mod.openmetrics_series(pts)
        assert 'pidx="2"' in om

    def test_dump_load_merge_clock_correction(self, tmp_path):
        d0 = {"meta": {"pidx": 0, "clock_offset_s": 0.0},
              "points": [_pt(0, 100.0, -1, "x", 1.0)]}
        d1 = {"meta": {"pidx": 1, "clock_offset_s": 5.0},
              "points": [_pt(0, 96.0, -1, "x", 2.0)]}
        for d in (d0, d1):
            export_mod.dump_series_jsonl(
                str(tmp_path / f"series-p{d['meta']['pidx']}.jsonl"), d)
        docs = doctor_mod.load_series_dir(str(tmp_path))
        assert [int(d["meta"]["pidx"]) for d in docs] == [0, 1]
        merged = doctor_mod.merge_series(docs)
        # p1's 96.0 + offset 5.0 = 101.0 sorts AFTER p0's 100.0
        assert [p["pidx"] for p in merged] == [0, 1]
        assert merged[1]["ts"] == pytest.approx(101.0)

    def test_series_rates_skips_single_tick_procs(self):
        merged = [{"ts": 5.0, "t": 5.0, "pidx": 0, "cid": 0,
                   "name": "coll_ops", "v": 10.0}]
        # one tick = no measurable window: no rate, not a 10000/s lie
        assert doctor_mod.series_rates(merged) == {}

    def test_series_rates_fold(self):
        merged = []
        for k in range(5):
            t = 10.0 + k
            merged.append({"ts": t, "t": t, "pidx": 0, "cid": 0,
                           "name": "coll_ops", "v": 8.0})
            merged.append({"ts": t, "t": t, "pidx": 0, "cid": 0,
                           "name": "coll_bytes", "v": 4e6})
        rates = doctor_mod.series_rates(merged)
        assert rates[0]["coll_ops_per_s"] == pytest.approx(10.0)
        assert rates[0]["coll_mb_per_s"] == pytest.approx(5.0)

    def test_skew_report_annotated_with_rates(self):
        def jdump(pidx, spans):
            return {"meta": {"pidx": pidx, "rank_offset": pidx * 2,
                             "local_size": 2, "clock_offset_s": 0.0},
                    "spans": spans}

        def span(op, t):
            return {"seq": 0, "op": op, "layer": "coll", "t": t,
                    "dt": 0.1, "bytes": 0, "peer": -1, "comm": 0}

        dumps = [
            jdump(0, [span("allreduce", 1.0)]),
            jdump(1, [span("allreduce", 1.4)]),
        ]
        series = [{"meta": {"pidx": 0, "clock_offset_s": 0.0},
                   "points": [_pt(0, 1.0, 0, "coll_ops", 3.0),
                              _pt(1, 2.0, 0, "coll_ops", 3.0)]}]
        text, data = doctor_mod.skew_report(dumps, series=series)
        assert "sampled rates" in text
        assert "coll/s" in text
        assert "0" in data["sampled_rates"]


# ---------------------------------------------------------------------------
# in-process fleet: HNP TAG_SERIES aggregation + FleetClient + rows
# ---------------------------------------------------------------------------

class TestFleetAggregation:
    def test_hnp_aggregates_and_fleet_client_queries(self):
        from ompi_release_tpu.obs.doctor import fleet_to_series_docs
        from ompi_release_tpu.runtime.coordinator import (
            HnpCoordinator, WorkerAgent)
        from ompi_release_tpu.tools.tpu_top import (FleetClient,
                                                    render_fleet)

        hnp = HnpCoordinator(4)
        agents, fc = [], None
        try:
            hnp.start_series_responder()
            for nid in (1, 2, 3):
                ag = WorkerAgent(nid, "127.0.0.1", hnp.port)
                agents.append(ag)
                ag.push_series(
                    [_pt(0, 1.0 + nid, 0, "coll_ops", float(nid))],
                    offset_s=0.25 * nid)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if len(hnp.fleet_series()["procs"]) == 3:
                    break
                time.sleep(0.02)
            fleet = hnp.fleet_series()
            assert set(fleet["procs"]) == {"0", "1", "2"}
            assert fleet["procs"]["1"]["clock_offset_s"] == 0.5
            assert fleet["procs"]["2"]["points"][0]["v"] == 3.0
            # the dashboard's live query path
            fc = FleetClient("127.0.0.1", hnp.port)
            queried = fc.query()
            assert set(queried["procs"]) == {"0", "1", "2"}
            table = render_fleet(fleet_to_series_docs(queried))
            rows = [ln for ln in table.splitlines()[1:] if ln.strip()]
            assert len(rows) == 3, table
        finally:
            if fc is not None:
                fc.close()
            for ag in agents:
                ag.ep.close()
            hnp.shutdown()

    def test_responder_survives_malformed_push(self):
        from ompi_release_tpu.runtime.coordinator import (
            HnpCoordinator, TAG_SERIES, WorkerAgent)

        hnp = HnpCoordinator(2)
        ag = None
        try:
            hnp.start_series_responder()
            ag = WorkerAgent(1, "127.0.0.1", hnp.port)
            # garbled push: non-numeric pidx must cost only this frame
            ag.ep.send(0, TAG_SERIES, json.dumps(
                {"pidx": "x", "points": [], "clock_offset_s": "y"}
            ).encode())
            ag.push_series([_pt(0, 1.0, -1, "x", 1.0)])
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if hnp.fleet_series()["procs"]:
                    break
                time.sleep(0.02)
            assert "0" in hnp.fleet_series()["procs"], (
                "responder died on the malformed push")
        finally:
            if ag is not None:
                ag.ep.close()
            hnp.shutdown()

    def test_push_store_is_bounded(self):
        from ompi_release_tpu.runtime import coordinator as coord

        hnp = coord.HnpCoordinator(2)
        try:
            hnp.start_series_responder()
            big = [_pt(i, float(i), -1, "x", 1.0)
                   for i in range(coord.SERIES_KEEP + 100)]
            hnp._ingest_series(1, {"pidx": 0, "points": big})
            ent = hnp.fleet_series()["procs"]["0"]
            assert len(ent["points"]) == coord.SERIES_KEEP
            assert ent["points"][-1]["i"] == coord.SERIES_KEEP + 99
        finally:
            hnp.shutdown()


# ---------------------------------------------------------------------------
# tpu_top: row math + reconnect behaviour
# ---------------------------------------------------------------------------

class TestTpuTop:
    def test_summarize_points_rates_and_percentiles(self):
        from ompi_release_tpu.tools.tpu_top import summarize_points

        pts = []
        for k in range(6):  # one tick per second, 5 s window
            t = 100.0 + k
            pts.append(_pt(3 * k, t, 0, "coll_ops", 10.0))
            pts.append(_pt(3 * k + 1, t, 0, "coll_bytes", 2e6))
            pts.append(_pt(3 * k + 2, t, -1, "coll_allreduce_latency",
                           {"count": 10.0, "sum": 0.1,
                            "buckets": {0.015625: 10.0}}))
        s = summarize_points(pts, window_s=100.0)
        assert s["ops_s"] == pytest.approx(12.0)   # 60 ops over 5 s
        assert s["mb_s"] == pytest.approx(2.4)
        assert s["p50_ms"] == pytest.approx(11.71875)  # bucket midpoint
        assert s["cids"] == [0]

    def test_summarize_single_tick_has_no_rate(self):
        from ompi_release_tpu.tools.tpu_top import summarize_points

        pts = [_pt(0, 5.0, 0, "coll_ops", 10.0),
               _pt(1, 5.0, 0, "coll_bytes", 1e6)]
        s = summarize_points(pts)
        assert s["ops_s"] is None and s["mb_s"] is None

    def test_summarize_flags_stalls(self):
        from ompi_release_tpu.tools.tpu_top import (render_fleet,
                                                    summarize_points)

        pts = [_pt(0, 1.0, -1, "obs_stalls_detected", 2.0),
               _pt(1, 2.0, 0, "coll_ops", 1.0)]
        s = summarize_points(pts)
        assert s["stalls"] == 2
        table = render_fleet([{"meta": {"pidx": 4}, "points": pts}])
        assert "STALL×2" in table and " 4 " in table

    def test_render_fleet_marks_stale_procs(self):
        from ompi_release_tpu.tools.tpu_top import render_fleet

        docs = [{"meta": {"pidx": 0, "push_age_s": 120.0},
                 "points": [_pt(0, 1.0, 0, "coll_ops", 1.0)]}]
        table = render_fleet(docs, stale_after_s=6.0)
        assert "STALE" in table

    def test_metrics_loop_survives_dead_server(self, capsys):
        from ompi_release_tpu.tools.tpu_server import NameServer
        from ompi_release_tpu.tools.tpu_top import _metrics_loop

        srv = NameServer()
        port = srv.port
        srv.shutdown()  # nothing listens here anymore
        rc = _metrics_loop(f"127.0.0.1:{port}", delay=0.05,
                           iterations=1)
        out = capsys.readouterr().out
        assert "STALE" in out
        assert rc == 1  # never saw data — but no exception, no exit 2

    def test_metrics_loop_renders_live_server(self, capsys):
        from ompi_release_tpu.tools.tpu_server import NameServer
        from ompi_release_tpu.tools.tpu_top import _metrics_loop

        srv = NameServer()
        try:
            rc = _metrics_loop(f"127.0.0.1:{srv.port}", delay=0.05,
                               iterations=2)
        finally:
            srv.shutdown()
        out = capsys.readouterr().out
        assert rc == 0 and "ompitpu_" in out

    def test_compiled_fire_ratio_column(self):
        """comp% folds from the coll_compiled_cache_hits AGGREGATE
        deltas: sum = frozen-plan replays, count = fires through the
        plan layer."""
        from ompi_release_tpu.tools.tpu_top import (render_fleet,
                                                    summarize_points)

        pts = [_pt(0, 1.0, -1, "coll_compiled_cache_hits",
                   {"sum": 9.0, "count": 10.0}),
               _pt(1, 2.0, 0, "coll_ops", 10.0),
               _pt(2, 2.0, -1, "ledger_records", 9.0)]
        s = summarize_points(pts)
        assert s["compiled_frac"] == pytest.approx(0.9)
        assert s["ledger_records"] == 9
        assert s["dark"] is False
        table = render_fleet([{"meta": {"pidx": 0}, "points": pts}])
        assert "comp%" in table and " 90.0" in table
        assert "DARK" not in table
        # no plan traffic in the window: the column renders '-'
        s2 = summarize_points([_pt(0, 1.0, 0, "coll_ops", 1.0)])
        assert s2["compiled_frac"] is None

    def test_dark_rank_flagged(self):
        """A rank replaying frozen plans whose window shows NEITHER
        journal-derived coll_ops points NOR flight-recorder records is
        DARK: obs is on (the sampler only runs under obs) but the
        compiled hot path left no trace — the exact de-optimization
        regression the flight recorder exists to prevent."""
        from ompi_release_tpu.tools.tpu_top import (render_fleet,
                                                    summarize_points)

        pts = [_pt(0, 1.0, -1, "coll_compiled_cache_hits",
                   {"sum": 5.0, "count": 5.0}),
               _pt(1, 2.0, -1, "obs_sample_overhead_pad", 1.0)]
        s = summarize_points(pts)
        assert s["dark"] is True
        table = render_fleet([{"meta": {"pidx": 2}, "points": pts}])
        assert "DARK" in table
        # one ledger record in the window clears the flag
        lit = pts + [_pt(2, 2.0, -1, "ledger_records", 5.0)]
        assert summarize_points(lit)["dark"] is False

    def test_native_vs_staged_byte_split(self):
        """nwMB/s and nat% fold from the wire_native_bytes deltas
        against btl_dcn_staged_bytes (the whole staged-path volume,
        native included): staged_mb_s is the remainder that rode the
        portable copy path."""
        from ompi_release_tpu.tools.tpu_top import (render_fleet,
                                                    summarize_points)

        pts = [_pt(0, 1.0, -1, "wire_native_bytes", 3e6),
               _pt(1, 2.0, -1, "wire_native_bytes", 3e6),
               _pt(2, 2.0, -1, "btl_dcn_staged_bytes", 8e6),
               _pt(3, 2.0, -1, "wire_native_frames", 4.0),
               _pt(4, 2.0, -1, "wire_native_ring_stalls", 0.0),
               _pt(5, 2.0, -1, "wire_native_ring_hwm_frac", 0.25)]
        s = summarize_points(pts)
        assert s["native_mb_s"] == pytest.approx(6.0)  # 6e6 B / 1 s
        assert s["staged_mb_s"] == pytest.approx(2.0)
        assert s["native_frac"] == pytest.approx(0.75)
        assert s["dark_native"] is False
        table = render_fleet([{"meta": {"pidx": 0}, "points": pts}])
        assert "nwMB/s" in table and "nat%" in table
        assert "DARK-NATIVE" not in table
        # no wire traffic at all: the split renders as absent
        s2 = summarize_points([_pt(0, 1.0, 0, "coll_ops", 1.0)])
        assert s2["native_frac"] is None
        assert s2["dark_native"] is False

    def test_dark_native_rank_flagged(self):
        """Native frames moved but NONE of the three C-counter series
        (stalls / stall seconds / hwm) produced a window point: the
        stale-.so signature — fragments ride a library without the
        telemetry block. DARK-NATIVE, like DARK, is a heuristic flag
        on the fleet row."""
        from ompi_release_tpu.tools.tpu_top import (render_fleet,
                                                    summarize_points)

        pts = [_pt(0, 1.0, -1, "wire_native_frames", 2.0),
               _pt(1, 2.0, -1, "wire_native_bytes", 4e6)]
        s = summarize_points(pts)
        assert s["dark_native"] is True
        table = render_fleet([{"meta": {"pidx": 1}, "points": pts}])
        assert "DARK-NATIVE" in table
        # any one native telemetry point in the window clears it
        lit = pts + [_pt(2, 2.0, -1, "wire_native_ring_stalls", 1.0)]
        assert summarize_points(lit)["dark_native"] is False

    def test_server_series_rpc(self, obs_sampling):
        from ompi_release_tpu.tools.tpu_server import (NameClient,
                                                       NameServer)

        sampler_mod.SAMPLER.sample_once()
        srv = NameServer()
        client = None
        try:
            client = NameClient("127.0.0.1", srv.port)
            doc = client.series()
            assert "meta" in doc and isinstance(doc["points"], list)
            assert doc["points"], "series RPC returned an empty ring"
        finally:
            if client is not None:
                client.close()
            srv.shutdown()


# ---------------------------------------------------------------------------
# the bench gate
# ---------------------------------------------------------------------------

def _round_file(path, lines):
    tail = "\n".join(json.dumps(ln) for ln in lines) + "\n"
    path.write_text(json.dumps({"n": 1, "rc": 0, "tail": tail}))
    return str(path)


def _bw(v):
    return {"metric": "allreduce_256MiB", "value": v, "unit": "GB/s",
            "vs_baseline": 1.0, "tier_label": "tpu"}


def _lat(v):
    return {"metric": "ring_4hop_latency", "value": v, "unit": "us/hop",
            "vs_baseline": None, "tier_label": "tpu"}


class TestBenchGate:
    def _history(self, tmp_path, n=4):
        vals = [680.0, 686.0, 678.0, 683.0]
        lats = [0.0085, 0.0088, 0.0082, 0.0086]
        return [_round_file(tmp_path / f"BENCH_r{k:02d}.json",
                            [_bw(vals[k]), _lat(lats[k])])
                for k in range(n)]

    def test_catches_2x_latency_regression(self, tmp_path):
        from ompi_release_tpu.tools import tpu_bench_gate as gate

        hist = self._history(tmp_path)
        cand = _round_file(tmp_path / "cand.json",
                           [_bw(681.0), _lat(0.017)])  # 2x latency
        rc = gate.main(hist + ["--candidate", cand])
        assert rc == 1
        verdict = gate.evaluate(
            [gate.parse_round_file(p) for p in hist],
            gate.parse_round_file(cand))
        regs = {r["metric"] for r in verdict["regressions"]}
        assert regs == {"ring_4hop_latency"}

    def test_catches_halved_bandwidth(self, tmp_path):
        from ompi_release_tpu.tools import tpu_bench_gate as gate

        hist = self._history(tmp_path)
        cand = _round_file(tmp_path / "cand.json",
                           [_bw(340.0), _lat(0.0085)])
        verdict = gate.evaluate(
            [gate.parse_round_file(p) for p in hist],
            gate.parse_round_file(cand))
        assert [r["metric"] for r in verdict["regressions"]] \
            == ["allreduce_256MiB"]

    def test_passes_within_noise(self, tmp_path):
        from ompi_release_tpu.tools import tpu_bench_gate as gate

        hist = self._history(tmp_path)
        cand = _round_file(tmp_path / "cand.json",
                           [_bw(655.0), _lat(0.0095)])  # ~4%/10% off
        rc = gate.main(hist + ["--candidate", cand])
        assert rc == 0

    def test_skips_unclean_and_tier_mismatched_lines(self, tmp_path):
        from ompi_release_tpu.tools import tpu_bench_gate as gate

        hist = [gate.parse_round_file(p)
                for p in self._history(tmp_path)]
        cand = [
            dict(_bw(100.0), unstable=True),          # flagged: skip
            dict(_bw(100.0), partial_rounds=2),       # salvage: skip
            {"metric": "allreduce_256MiB", "value": None, "unit":
             "GB/s", "vs_baseline": None},            # null: skip
            # cpu-tier line must NOT be judged against tpu history
            dict(_bw(3.0), tier_label="loopback-cpu"),
        ]
        verdict = gate.evaluate(hist, cand)
        assert verdict["regressions"] == []
        assert verdict["checked"] == 0

    def test_min_rounds_required(self, tmp_path):
        from ompi_release_tpu.tools import tpu_bench_gate as gate

        hist = [gate.parse_round_file(p)
                for p in self._history(tmp_path, n=2)]
        verdict = gate.evaluate(hist, [_bw(10.0)])
        assert verdict["checked"] == 0 and not verdict["regressions"]

    def test_zero_on_the_real_history(self):
        """The acceptance criterion's second half: the repo's actual
        BENCH_r*.json trajectory must pass its own gate."""
        from ompi_release_tpu.tools import tpu_bench_gate as gate

        files = sorted(
            p for p in os.listdir(REPO)
            if p.startswith("BENCH_r") and p.endswith(".json"))
        if len(files) < 2:
            pytest.skip("no bench history in this checkout")
        rc = gate.main([os.path.join(REPO, p) for p in files])
        assert rc == 0

    def test_legacy_backend_label_maps_to_cpu_tier(self):
        from ompi_release_tpu.tools.tpu_bench_gate import line_tier

        assert line_tier({"backend": "cpu"}) == "loopback-cpu"
        assert line_tier({}) == "tpu"
        assert line_tier({"tier_label": "loopback-cpu"}) \
            == "loopback-cpu"

    def test_sim_metrics_are_lower_better_in_their_own_tier(self,
                                                            tmp_path):
        """The fleet_scaling suite's sim_* lines: the sim_ prefix is
        registered lower-better (more schedule rounds / more bytes
        per rank / longer simulated makespan = regression), and the
        "sim" tier label keeps the deterministic simulator numbers
        out of the wall-clock tiers' noise fits."""
        from ompi_release_tpu.tools import tpu_bench_gate as gate

        def sim(metric, v, unit):
            return {"metric": metric, "value": v, "unit": unit,
                    "vs_baseline": None, "tier_label": "sim"}

        assert gate._direction("rounds", "sim_rd_rounds_p256") == -1
        assert gate._direction("bytes",
                               "sim_rab_bytes_per_rank_p256") == -1
        assert gate._direction("sim_ms",
                               "sim_allreduce_makespan_p256") == -1
        hist = [_round_file(
            tmp_path / f"BENCH_r{k:02d}.json",
            [sim("sim_rd_rounds_p256", 8, "rounds"),
             sim("sim_rab_bytes_per_rank_p256", 4080, "bytes")])
            for k in range(4)]
        # a schedule regression (log-round schedule degrading toward
        # linear: 8 -> 16 rounds) trips the gate...
        cand = _round_file(
            tmp_path / "cand.json",
            [sim("sim_rd_rounds_p256", 16, "rounds"),
             sim("sim_rab_bytes_per_rank_p256", 4080, "bytes")])
        rc = gate.main(hist + ["--candidate", str(cand)])
        assert rc == 1
        verdict = gate.evaluate(
            [gate.parse_round_file(p) for p in hist],
            gate.parse_round_file(cand))
        assert [r["metric"] for r in verdict["regressions"]] \
            == ["sim_rd_rounds_p256"]
        assert verdict["regressions"][0]["tier"] == "sim"
        # ...the identical deterministic replay does not...
        ok = _round_file(
            tmp_path / "ok.json",
            [sim("sim_rd_rounds_p256", 8, "rounds"),
             sim("sim_rab_bytes_per_rank_p256", 4080, "bytes")])
        assert gate.main(hist + ["--candidate", str(ok)]) == 0
        # ...and a same-named line in ANOTHER tier is never judged
        # against the sim history
        other = gate.evaluate(
            [gate.parse_round_file(p) for p in hist],
            [{"metric": "sim_rd_rounds_p256", "value": 99,
              "unit": "rounds", "vs_baseline": None,
              "tier_label": "loopback-cpu"}])
        assert other["checked"] == 0 and not other["regressions"]

    def test_steady_state_metric_directions(self, tmp_path):
        """The steady_state suite's lines: steady_* (per-op wall /
        Python-orchestration seconds) are registered lower-better,
        compiled_* (interpreted-vs-compiled orchestration speedups)
        higher-better — a slower orchestration OR a shrunk speedup is
        a regression, never an improvement."""
        from ompi_release_tpu.tools import tpu_bench_gate as gate

        assert gate._direction(
            "s", "steady_orch_allreduce_256KiB_compiled") == -1
        assert gate._direction(
            None, "steady_orch_allreduce_256KiB_interpreted") == -1
        assert gate._direction(
            "x_orchestration",
            "compiled_allreduce_256KiB_orch_speedup") == 1
        assert gate._direction(
            None, "compiled_spanning_allreduce_orch_speedup") == 1

        def ln(metric, v, unit):
            return {"metric": metric, "value": v, "unit": unit,
                    "vs_baseline": None, "tier_label": "loopback-cpu"}

        hist = [_round_file(
            tmp_path / f"BENCH_r{k:02d}.json",
            [ln("steady_orch_allreduce_256KiB_compiled",
                6.6e-5 + k * 1e-6, "s"),
             ln("compiled_allreduce_256KiB_orch_speedup",
                2.4 + 0.02 * k, "x_orchestration")])
            for k in range(4)]
        # orchestration doubling or the speedup collapsing trips it
        bad = _round_file(
            tmp_path / "cand.json",
            [ln("steady_orch_allreduce_256KiB_compiled", 2.0e-4, "s"),
             ln("compiled_allreduce_256KiB_orch_speedup", 1.0,
                "x_orchestration")])
        from ompi_release_tpu.tools import tpu_bench_gate as gate2

        verdict = gate2.evaluate(
            [gate2.parse_round_file(p) for p in hist],
            gate2.parse_round_file(bad))
        regressed = {r["metric"] for r in verdict["regressions"]}
        assert regressed == {
            "steady_orch_allreduce_256KiB_compiled",
            "compiled_allreduce_256KiB_orch_speedup"}
        # ...an in-band round passes
        ok = _round_file(
            tmp_path / "ok.json",
            [ln("steady_orch_allreduce_256KiB_compiled", 6.7e-5, "s"),
             ln("compiled_allreduce_256KiB_orch_speedup", 2.42,
                "x_orchestration")])
        assert gate2.main(hist + ["--candidate", str(ok)]) == 0

    def test_native_rounds_metric_directions(self, tmp_path):
        """The native_rounds suite's lines (frozen plans lowered into
        the C plan executor): steady_native_orch_* seconds are
        lower-better, compiled_native_* speedups (native over the
        interpreted PlannedXchg replay — the executor's acceptance
        factor) higher-better, and a drift in either direction trips
        the gate against the fitted history."""
        from ompi_release_tpu.tools import tpu_bench_gate as gate

        assert gate._direction(
            "s", "steady_native_orch_allreduce_256KiB") == -1
        assert gate._direction(
            None, "steady_native_orch_bcast_4KiB") == -1
        assert gate._direction(
            "x_orchestration",
            "compiled_native_allreduce_256KiB_orch_speedup") == 1
        assert gate._direction(
            None, "compiled_native_allgather_64KiB_orch_speedup") == 1

        def ln(metric, v, unit):
            return {"metric": metric, "value": v, "unit": unit,
                    "vs_baseline": None, "tier_label": "loopback-cpu"}

        hist = [_round_file(
            tmp_path / f"BENCH_r{k:02d}.json",
            [ln("steady_native_orch_allreduce_256KiB",
                3.1e-5 + k * 1e-6, "s"),
             ln("compiled_native_allreduce_256KiB_orch_speedup",
                2.6 + 0.02 * k, "x_orchestration")])
            for k in range(4)]
        bad = _round_file(
            tmp_path / "cand.json",
            [ln("steady_native_orch_allreduce_256KiB", 1.5e-4, "s"),
             ln("compiled_native_allreduce_256KiB_orch_speedup", 0.9,
                "x_orchestration")])
        verdict = gate.evaluate(
            [gate.parse_round_file(p) for p in hist],
            gate.parse_round_file(bad))
        regressed = {r["metric"] for r in verdict["regressions"]}
        assert regressed == {
            "steady_native_orch_allreduce_256KiB",
            "compiled_native_allreduce_256KiB_orch_speedup"}
        ok = _round_file(
            tmp_path / "ok.json",
            [ln("steady_native_orch_allreduce_256KiB", 3.2e-5, "s"),
             ln("compiled_native_allreduce_256KiB_orch_speedup",
                2.63, "x_orchestration")])
        assert gate.main(hist + ["--candidate", str(ok)]) == 0

    def test_rma_steady_metric_directions(self, tmp_path):
        """The rma_steady suite's lines (frozen RMA access plans,
        osc/plan): steady_rma_* / steady_shmem_* seconds are
        lower-better, the compiled_* orchestration and bulk-path
        speedups higher-better — slower epochs or a collapsed speedup
        regress, never improve."""
        from ompi_release_tpu.tools import tpu_bench_gate as gate

        assert gate._direction(
            "s", "steady_rma_fence_4KiB_planned") == -1
        assert gate._direction(
            None, "steady_rma_fence_4KiB_interpreted") == -1
        assert gate._direction(
            "x_orchestration",
            "compiled_rma_fence_4KiB_orch_speedup") == 1
        assert gate._direction(
            "s", "steady_shmem_put_4KiB_bulk") == -1
        assert gate._direction(
            "x_wall", "compiled_shmem_put_4KiB_bulk_speedup") == 1

        def ln(metric, v, unit):
            return {"metric": metric, "value": v, "unit": unit,
                    "vs_baseline": None, "tier_label": "loopback-cpu"}

        hist = [_round_file(
            tmp_path / f"BENCH_r{k:02d}.json",
            [ln("steady_rma_fence_4KiB_planned",
                7.0e-5 + k * 1e-6, "s"),
             ln("compiled_shmem_put_4KiB_bulk_speedup",
                1.8 + 0.02 * k, "x_wall")])
            for k in range(4)]
        # a doubled planned close or a collapsed bulk win trips it
        bad = _round_file(
            tmp_path / "cand.json",
            [ln("steady_rma_fence_4KiB_planned", 2.0e-4, "s"),
             ln("compiled_shmem_put_4KiB_bulk_speedup", 0.9,
                "x_wall")])
        verdict = gate.evaluate(
            [gate.parse_round_file(p) for p in hist],
            gate.parse_round_file(bad))
        regressed = {r["metric"] for r in verdict["regressions"]}
        assert regressed == {
            "steady_rma_fence_4KiB_planned",
            "compiled_shmem_put_4KiB_bulk_speedup"}
        # ...an in-band round passes
        ok = _round_file(
            tmp_path / "ok.json",
            [ln("steady_rma_fence_4KiB_planned", 7.1e-5, "s"),
             ln("compiled_shmem_put_4KiB_bulk_speedup", 1.83,
                "x_wall")])
        assert gate.main(hist + ["--candidate", str(ok)]) == 0

    def test_flight_recorder_metric_directions(self, tmp_path):
        """The flight-recorder lines: steady_obs_* (obs-ON compiled
        orchestration seconds and the obs-ON/obs-OFF overhead ratio —
        the "tracing never de-optimizes the hot path" budget) and
        ledger_* (bytes per fire record) are all lower-better, so the
        gate trips when enabling obs gets more expensive or the
        fixed-size record grows."""
        from ompi_release_tpu.tools import tpu_bench_gate as gate

        assert gate._direction(
            "s", "steady_obs_orch_spanning_allreduce_256KiB_compiled"
        ) == -1
        assert gate._direction(
            "ratio", "steady_obs_overhead_spanning_allreduce_256KiB"
        ) == -1
        assert gate._direction(
            "bytes", "ledger_record_bytes_spanning_allreduce_256KiB"
        ) == -1

        def ln(metric, v, unit):
            return {"metric": metric, "value": v, "unit": unit,
                    "vs_baseline": None, "tier_label": "loopback-cpu"}

        hist = [_round_file(
            tmp_path / f"BENCH_r{k:02d}.json",
            [ln("steady_obs_overhead_spanning_allreduce_256KiB",
                1.05 + 0.01 * k, "ratio"),
             ln("ledger_record_bytes_spanning_allreduce_256KiB",
                55, "bytes")]) for k in range(4)]
        # the obs-ON leg blowing past its 1.15x budget (tracing
        # de-optimized the hot path again) or a fattened record trips
        bad = _round_file(
            tmp_path / "cand.json",
            [ln("steady_obs_overhead_spanning_allreduce_256KiB",
                4.0, "ratio"),
             ln("ledger_record_bytes_spanning_allreduce_256KiB",
                2048, "bytes")])
        verdict = gate.evaluate(
            [gate.parse_round_file(p) for p in hist],
            gate.parse_round_file(bad))
        regressed = {r["metric"] for r in verdict["regressions"]}
        assert regressed == {
            "steady_obs_overhead_spanning_allreduce_256KiB",
            "ledger_record_bytes_spanning_allreduce_256KiB"}
        ok = _round_file(
            tmp_path / "ok.json",
            [ln("steady_obs_overhead_spanning_allreduce_256KiB",
                1.06, "ratio"),
             ln("ledger_record_bytes_spanning_allreduce_256KiB",
                55, "bytes")])
        assert gate.main(hist + ["--candidate", str(ok)]) == 0

    def test_native_wire_metric_directions(self, tmp_path):
        """The native_wire suite's lines: wire_native_p2p_* bandwidths
        (GB/s) are higher-better, while the wire_native_copies_per_mib
        witness (byte-path materializations per MiB shipped — 0.0 is
        the zero-copy acceptance target) is lower-better: a collapsed
        bandwidth OR arrays sneaking back onto the copy path must both
        trip the gate."""
        from ompi_release_tpu.tools import tpu_bench_gate as gate

        assert gate._direction("GB/s", "wire_native_p2p_256MiB") == 1
        assert gate._direction("GB/s", "wire_native_p2p_shm_256MiB") == 1
        assert gate._direction(
            "copies/MiB", "wire_native_copies_per_mib") == -1
        # ...and the prefix rule covers a unit-less round file too
        assert gate._direction(None, "wire_native_copies_per_mib") == -1

        def ln(metric, v, unit):
            return {"metric": metric, "value": v, "unit": unit,
                    "vs_baseline": None, "tier_label": "loopback-cpu"}

        hist = [_round_file(
            tmp_path / f"BENCH_r{k:02d}.json",
            [ln("wire_native_p2p_256MiB", 2.0 + 0.05 * k, "GB/s"),
             ln("wire_native_copies_per_mib", 0.0, "copies/MiB")])
            for k in range(4)]
        # bandwidth collapsing or copies reappearing trips the gate
        bad = _round_file(
            tmp_path / "cand.json",
            [ln("wire_native_p2p_256MiB", 0.4, "GB/s"),
             ln("wire_native_copies_per_mib", 3.0, "copies/MiB")])
        verdict = gate.evaluate(
            [gate.parse_round_file(p) for p in hist],
            gate.parse_round_file(bad))
        regressed = {r["metric"] for r in verdict["regressions"]}
        assert regressed == {"wire_native_p2p_256MiB",
                             "wire_native_copies_per_mib"}
        ok = _round_file(
            tmp_path / "ok.json",
            [ln("wire_native_p2p_256MiB", 2.1, "GB/s"),
             ln("wire_native_copies_per_mib", 0.0, "copies/MiB")])
        assert gate.main(hist + ["--candidate", str(ok)]) == 0

    def test_native_obs_metric_directions(self, tmp_path):
        """The native_obs suite's lines: the C counter-block series
        (stall count / cumulative stall seconds / ring occupancy HWM)
        are LOWER-better — growth is backpressure, not throughput —
        and native_obs_overhead_ratio (event-ring-on p2p wall over the
        counters-only baseline, acceptance budget 1.05) is lower-better
        via its metric prefix: its unit is 'ratio', NOT an 'x_*' unit,
        which would flip it higher-better in the unit table."""
        from ompi_release_tpu.tools import tpu_bench_gate as gate

        assert gate._direction(
            "stalls", "wire_native_stall_count") == -1
        assert gate._direction(
            "s", "wire_native_stall_seconds") == -1
        assert gate._direction(
            "frac", "wire_native_ring_hwm_frac") == -1
        assert gate._direction(
            "ratio", "native_obs_overhead_ratio") == -1
        assert gate._direction("s", "native_obs_counters_wall_s") == -1
        # the x_* unit family stays higher-better (speedups): the
        # overhead ratio must never be filed under it
        assert gate._direction("x_vs_staged", "anything") == 1

        def ln(metric, v, unit):
            return {"metric": metric, "value": v, "unit": unit,
                    "vs_baseline": None, "tier_label": "loopback-cpu"}

        hist = [_round_file(
            tmp_path / f"BENCH_r{k:02d}.json",
            [ln("native_obs_overhead_ratio", 1.01 + 0.002 * k,
                "ratio"),
             ln("wire_native_stall_seconds", 0.02, "s")])
            for k in range(4)]
        # observability cost ballooning or stalls growing both trip
        bad = _round_file(
            tmp_path / "cand.json",
            [ln("native_obs_overhead_ratio", 1.8, "ratio"),
             ln("wire_native_stall_seconds", 4.0, "s")])
        verdict = gate.evaluate(
            [gate.parse_round_file(p) for p in hist],
            gate.parse_round_file(bad))
        regressed = {r["metric"] for r in verdict["regressions"]}
        assert regressed == {"native_obs_overhead_ratio",
                             "wire_native_stall_seconds"}
        ok = _round_file(
            tmp_path / "ok.json",
            [ln("native_obs_overhead_ratio", 1.012, "ratio"),
             ln("wire_native_stall_seconds", 0.019, "s")])
        assert gate.main(hist + ["--candidate", str(ok)]) == 0

    def test_topo_metric_directions(self, tmp_path):
        """The fleet_scaling suite's topo_* lines (topology-aware
        schedule speedups over the flat ring: inter-host byte ratio,
        virtual-makespan ratio) are registered higher-better in the
        sim tier — a shrunk ratio means the torus/multiring advantage
        regressed, and it must trip the gate."""
        from ompi_release_tpu.tools import tpu_bench_gate as gate

        assert gate._direction(
            "x_inter_bytes", "topo_torus_inter_bytes_x_p1024") == 1
        assert gate._direction(
            "x_makespan", "topo_torus_makespan_x_p256") == 1
        assert gate._direction(
            None, "topo_multiring_makespan_x_p256") == 1
        # ...while the sim_torus_* observables stay lower-better
        assert gate._direction(
            "bytes", "sim_torus_inter_bytes_per_rank_p1024") == -1
        assert gate._direction("rounds", "sim_torus_rounds_p256") == -1

        def ln(metric, v, unit):
            return {"metric": metric, "value": v, "unit": unit,
                    "vs_baseline": None, "tier_label": "sim"}

        hist = [_round_file(
            tmp_path / f"BENCH_r{k:02d}.json",
            [ln("topo_torus_inter_bytes_x_p1024", 8.0, "x_inter_bytes")])
            for k in range(4)]
        bad = _round_file(
            tmp_path / "cand.json",
            [ln("topo_torus_inter_bytes_x_p1024", 1.0,
                "x_inter_bytes")])
        assert gate.main(hist + ["--candidate", str(bad)]) == 1
        ok = _round_file(
            tmp_path / "ok.json",
            [ln("topo_torus_inter_bytes_x_p1024", 8.0,
                "x_inter_bytes")])
        assert gate.main(hist + ["--candidate", str(ok)]) == 0

    def test_tenant_metric_directions(self, tmp_path):
        """The multi_tenant suite's tenant_* lines (service plane):
        latency-tenant p99s and the tenant_latency_isolation
        degradation ratio are registered lower-better in the sim tier
        — a GROWN isolation ratio means the weighted-fair wire lets a
        bulk tenant degrade a latency tenant further, and it must
        trip the gate at the sim tier's tight floor."""
        from ompi_release_tpu.tools import tpu_bench_gate as gate

        assert gate._direction(
            "p99_ratio", "tenant_latency_isolation_p256") == -1
        assert gate._direction(
            "sim_ms", "tenant_lat_contended_p99_p256") == -1
        assert gate._direction(
            None, "tenant_fifo_hol_ratio_p256") == -1

        def ln(metric, v, unit):
            return {"metric": metric, "value": v, "unit": unit,
                    "vs_baseline": None, "tier_label": "sim"}

        hist = [_round_file(
            tmp_path / f"BENCH_r{k:02d}.json",
            [ln("tenant_latency_isolation_p256", 1.22, "p99_ratio"),
             ln("tenant_lat_contended_p99_p256", 0.81, "sim_ms")])
            for k in range(4)]
        # fairness eroding (1.22 -> 1.9, still under the FIFO blowup)
        # IS a regression at the 2% sim floor...
        bad = _round_file(
            tmp_path / "cand.json",
            [ln("tenant_latency_isolation_p256", 1.9, "p99_ratio"),
             ln("tenant_lat_contended_p99_p256", 0.81, "sim_ms")])
        verdict = gate.evaluate(
            [gate.parse_round_file(p) for p in hist],
            gate.parse_round_file(bad))
        assert [r["metric"] for r in verdict["regressions"]] \
            == ["tenant_latency_isolation_p256"]
        assert verdict["regressions"][0]["tier"] == "sim"
        # ...the deterministic replay passes
        ok = _round_file(
            tmp_path / "ok.json",
            [ln("tenant_latency_isolation_p256", 1.22, "p99_ratio"),
             ln("tenant_lat_contended_p99_p256", 0.81, "sim_ms")])
        assert gate.main(hist + ["--candidate", str(ok)]) == 0

    def test_multi_tenant_bench_lines_are_gateable(self):
        """The bench suite itself (small P for speed): emits the
        solo/contended/FIFO p99 legs per QoS class + the isolation
        ratio, sim-tiered, with the in-band fairness bound holding."""
        import bench

        lines = bench._multi_tenant_micro_suite(sizes=(64,))
        by_metric = {l["metric"]: l for l in lines}
        iso = by_metric["tenant_latency_isolation_p64"]
        assert iso["tier_label"] == "sim"
        assert 1.0 <= iso["value"] <= iso["bound"] * 1.10
        assert by_metric["tenant_fifo_hol_ratio_p64"]["value"] \
            > 2.0 * iso["value"]
        solo = by_metric["tenant_lat_solo_p99_p64"]
        cont = by_metric["tenant_lat_contended_p99_p64"]
        assert solo["qos"] == "latency" and cont["value"] \
            >= solo["value"]
        assert by_metric["tenant_bulk_contended_p99_p64"]["qos"] \
            == "bulk"
        from ompi_release_tpu.tools import tpu_bench_gate as gate

        for l in lines:
            assert gate._direction(l["unit"], l["metric"]) == -1

    def test_sim_tier_band_is_tight_not_wall_clock_wobble(self,
                                                          tmp_path):
        """Sim lines are deterministic replays: the ±25% wall-clock
        noise floor must NOT apply, or a 8 -> 10 round schedule
        regression (+25%) would pass silently. The sim tier's floor
        is 2%."""
        from ompi_release_tpu.tools import tpu_bench_gate as gate

        def sim(v, tier="sim"):
            return {"metric": "sim_rd_rounds_p256", "value": v,
                    "unit": "rounds", "vs_baseline": None,
                    "tier_label": tier}

        hist = [[sim(8)] for _ in range(4)]      # bit-identical
        verdict = gate.evaluate(hist, [sim(10)])  # +25%: a real
        assert len(verdict["regressions"]) == 1   # regression, trips
        assert gate.evaluate(hist, [sim(8)])["regressions"] == []
        # the wall-clock tiers keep the wobble floor: +25% on a quiet
        # tpu-tier history stays inside the band
        thist = [[{"metric": "steps_used", "value": 8.0, "unit":
                   "steps", "vs_baseline": None, "tier_label": "tpu"}]
                 for _ in range(4)]
        tcand = [{"metric": "steps_used", "value": 9.9, "unit":
                  "steps", "vs_baseline": None, "tier_label": "tpu"}]
        assert gate.evaluate(thist, tcand)["regressions"] == []


# ---------------------------------------------------------------------------
# the real thing: 3-process job with the sampler armed
# ---------------------------------------------------------------------------

_SERIES_APP = r'''
import os, sys, time
sys.path.insert(0, %(repo)r)
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import ompi_release_tpu as mpi
from ompi_release_tpu.runtime.runtime import Runtime
from ompi_release_tpu import obs
from ompi_release_tpu.obs import sampler as sampler_mod

world = mpi.init()          # 3 procs x 2 devices
rt = Runtime.current()
me = rt.bootstrap["process_index"]
assert obs.enabled and sampler_mod.SAMPLER.running(), (
    obs.enabled, sampler_mod.SAMPLER.running())

x = np.stack([np.arange(128, dtype=np.float32) * (me + i + 1)
              for i in range(2)])
for _ in range(6):
    world.allreduce(x)
    time.sleep(0.12)        # span several sampler ticks
world.barrier()
print(f"SERIES-APP-OK {me}")
mpi.finalize()              # final tick + push + series dump happen here
'''


def test_3proc_job_fleet_series(tmp_path, capfd):
    """Acceptance: a 3-proc loopback job with obs_sample_interval set
    produces per-rank series dumps that merge clock-corrected, renders
    per-rank tpu_top rows, aggregates at the HNP, and annotates the
    doctor report with sampled rates."""
    dump_dir = tmp_path / "dumps"
    app = tmp_path / "series_app.py"
    app.write_text(_SERIES_APP % {"repo": REPO})
    job = Job(3, [sys.executable, str(app)],
              [("obs_enable", "1"),
               ("obs_sample_interval", "0.1"),
               ("obs_dump_dir", str(dump_dir))],
              heartbeat_s=0.5, miss_limit=10)
    rc = job.run(timeout_s=180)
    out = capfd.readouterr()
    assert rc == 0, out.out + out.err
    for me in (0, 1, 2):
        assert f"SERIES-APP-OK {me}" in out.out

    # -- per-rank series dumps, merged with clock correction ----------
    docs = doctor_mod.load_series_dir(str(dump_dir))
    assert len(docs) == 3, sorted(os.listdir(dump_dir))
    for d in docs:
        assert d["points"], f"rank {d['meta']['pidx']} series is empty"
        assert d["meta"]["clock_offset_s"] is not None, d["meta"]
    merged = doctor_mod.merge_series(docs)
    assert {p["pidx"] for p in merged} == {0, 1, 2}
    assert all("ts" in p for p in merged)
    # every rank saw collective activity in its per-cid series
    for pidx in (0, 1, 2):
        ops = sum(p["v"] for p in merged
                  if p["pidx"] == pidx and p["name"] == "coll_ops")
        assert ops >= 6, f"rank {pidx} coll_ops={ops}"

    # -- tpu_top renders per-rank rows from the dumps -----------------
    from ompi_release_tpu.tools.tpu_top import fleet_from_dir

    table = fleet_from_dir(str(dump_dir))
    rows = [ln for ln in table.splitlines()[1:] if ln.strip()]
    assert len(rows) == 3, table
    assert any("allgather" not in r and r.split()[2] != "0.0"
               for r in rows), f"no nonzero coll/s column:\n{table}"

    # -- HNP aggregated the pushed per-rank series --------------------
    fleet = job.hnp.fleet_series()
    assert set(fleet["procs"]) == {"0", "1", "2"}, fleet["procs"].keys()
    for pidx, ent in fleet["procs"].items():
        assert ent["points"], f"HNP holds no points for proc {pidx}"

    # -- report annotation consumes the merged series -----------------
    jdumps = doctor_mod.load_dir(str(dump_dir))
    text, data = doctor_mod.skew_report(jdumps, series=docs)
    assert "sampled rates" in text
    assert set(data["sampled_rates"]) == {"0", "1", "2"}

    # -- OpenMetrics exposition of the merged fleet -------------------
    for d in docs:
        om = export_mod.openmetrics_series(
            d["points"], pidx=int(d["meta"]["pidx"]),
            clock_offset_s=float(d["meta"]["clock_offset_s"]))
        assert om.endswith("# EOF\n")
        assert f'pidx="{int(d["meta"]["pidx"])}"' in om
